"""MoE + expert-parallelism tests: gating invariants, EP sharding
exactness, composition with tp, and the training path (capability
extension — the reference has no EP/MoE, SURVEY §2.2)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlbb_tpu.comm.mesh import MeshSpec, build_mesh
from dlbb_tpu.models.configs import ModelConfig, validate_expert_parallelism
from dlbb_tpu.models.transformer import (
    forward,
    init_params,
    num_parameters,
    shard_params,
    top_k_gates,
)
from dlbb_tpu.train.loop import run_train

MOE = ModelConfig(hidden_size=32, num_layers=2, num_heads=4,
                  ffn_intermediate=64, attention="full", dtype="float32",
                  num_experts=4, moe_top_k=2)


def _x(batch=8, seq=16, hidden=32, seed=1):
    return jax.random.normal(jax.random.key(seed), (batch, seq, hidden),
                             dtype=jnp.float32)


def test_top_k_gates_invariants():
    logits = jax.random.normal(jax.random.key(0), (4, 8, 6))
    gates = top_k_gates(logits, 2)
    # exactly k nonzeros per token, summing to 1
    nonzeros = (np.asarray(gates) > 0).sum(-1)
    np.testing.assert_array_equal(nonzeros, np.full((4, 8), 2))
    np.testing.assert_allclose(np.asarray(gates.sum(-1)),
                               np.ones((4, 8)), rtol=1e-6)
    # top-1 selects the argmax expert
    g1 = top_k_gates(logits, 1)
    np.testing.assert_array_equal(
        np.asarray(g1.argmax(-1)), np.asarray(logits.argmax(-1))
    )


def test_moe_param_count():
    params = init_params(MOE, jax.random.key(0))
    total = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    assert total == num_parameters(MOE)


def test_moe_ep_matches_single_device(devices):
    """Expert-parallel sharding must not change the forward numerics."""
    params = init_params(MOE, jax.random.key(0))
    x = _x()
    y_ref = jax.jit(lambda p, x: forward(p, x, MOE))(params, x)

    mesh = build_mesh(MeshSpec.grid((1, 4, 2), ("dp", "ep", "tp")))
    params_s = shard_params(params, mesh)
    y = jax.jit(lambda p, x: forward(p, x, MOE, mesh=mesh))(params_s, x)
    np.testing.assert_allclose(np.asarray(y_ref), np.asarray(y),
                               rtol=1e-5, atol=1e-5)


def _moe_train_cfg(name="train_moe", **model_over):
    model = {
        "hidden_size": 32, "num_layers": 2, "num_heads": 4,
        "ffn_intermediate": 64, "attention": "full", "dtype": "float32",
        "num_experts": 4, "moe_top_k": 2,
    }
    model.update(model_over)
    return {
        "experiment": {"name": name},
        "model": model,
        "parallelism": {"world_size": 2, "data_parallel": 2,
                        "expert_parallel": 2},
        "input": {"batch_size": 8, "sequence_length": 16, "seed": 42},
        "execution": {"warmup_iterations": 1, "benchmark_iterations": 6},
        "training": {"learning_rate": 1e-2},
    }


def test_moe_train_loss_decreases(devices):
    result = run_train(_moe_train_cfg(), zero_stage=1, verbose=False)
    assert result["mesh"]["ep"] == 2
    losses = result["losses"]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0] * 0.9, losses


def test_capacity_matches_dense_when_ample():
    """With capacity >= S (cf = E/k), no token is dropped and capacity
    dispatch must equal dense dispatch exactly."""
    ample = MOE.with_(moe_dispatch="capacity",
                      moe_capacity_factor=MOE.num_experts / MOE.moe_top_k)
    params = init_params(MOE, jax.random.key(0))
    x = _x()
    y_dense = jax.jit(lambda p, x: forward(p, x, MOE))(params, x)
    y_cap = jax.jit(lambda p, x: forward(p, x, ample))(params, x)
    np.testing.assert_allclose(np.asarray(y_dense), np.asarray(y_cap),
                               rtol=1e-5, atol=1e-5)


def test_capacity_ep_matches_single_device(devices):
    """Capacity dispatch stays exact under ep x tp sharding."""
    cfg = MOE.with_(moe_dispatch="capacity", moe_capacity_factor=2.0)
    params = init_params(cfg, jax.random.key(0))
    x = _x()
    y_ref = jax.jit(lambda p, x: forward(p, x, cfg))(params, x)

    mesh = build_mesh(MeshSpec.grid((1, 4, 2), ("dp", "ep", "tp")))
    params_s = shard_params(params, mesh)
    y = jax.jit(lambda p, x: forward(p, x, cfg, mesh=mesh))(params_s, x)
    np.testing.assert_allclose(np.asarray(y_ref), np.asarray(y),
                               rtol=1e-5, atol=1e-5)


def test_capacity_drops_tokens_when_tight():
    """A tight capacity factor drops tokens (output differs from dense)
    but stays finite — the documented GShard trade-off."""
    tight = MOE.with_(moe_dispatch="capacity", moe_capacity_factor=0.25)
    params = init_params(MOE, jax.random.key(0))
    x = _x()
    y_dense = jax.jit(lambda p, x: forward(p, x, MOE))(params, x)
    y_cap = jax.jit(lambda p, x: forward(p, x, tight))(params, x)
    assert np.all(np.isfinite(np.asarray(y_cap)))
    assert not np.allclose(np.asarray(y_dense), np.asarray(y_cap))


def test_moe_capacity_formula():
    from dlbb_tpu.models.transformer import moe_capacity

    # cf * S * k / E = 1.25 * 16 * 2 / 4 = 10
    assert moe_capacity(MOE.with_(moe_dispatch="capacity"), 16) == 10
    # floor at 1
    tiny = MOE.with_(moe_dispatch="capacity", moe_capacity_factor=0.01)
    assert moe_capacity(tiny, 16) == 1
    # cap at seq_len — an expert can't receive more tokens than the group
    huge = MOE.with_(moe_dispatch="capacity", moe_capacity_factor=100.0)
    assert moe_capacity(huge, 16) == 16


def test_capacity_train_loss_decreases(devices):
    cfg = _moe_train_cfg(name="train_moe_cap", moe_dispatch="capacity",
                         moe_capacity_factor=1.5)
    result = run_train(cfg, zero_stage=1, verbose=False)
    losses = result["losses"]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0] * 0.9, losses


def test_aux_loss_balance_bounds():
    """moe_aux_loss is 1.0 at perfect balance and larger when routing
    collapses onto one expert."""
    import jax.numpy as jnp

    from dlbb_tpu.models.transformer import moe_aux_loss

    E, k = 4, 1
    # perfectly uniform router: every expert equally likely and used
    probs = jnp.full((2, 8, E), 1.0 / E)
    gates = jnp.zeros((2, 8, E)).at[..., :].set(
        jnp.eye(E)[jnp.arange(16).reshape(2, 8) % E]
    )
    np.testing.assert_allclose(float(moe_aux_loss(probs, gates, k)), 1.0,
                               rtol=1e-6)
    # collapsed: all mass and all routing on expert 0
    probs_c = jnp.zeros((2, 8, E)).at[..., 0].set(1.0)
    gates_c = probs_c
    np.testing.assert_allclose(float(moe_aux_loss(probs_c, gates_c, k)),
                               float(E), rtol=1e-6)


def test_forward_with_aux(devices):
    params = init_params(MOE, jax.random.key(0))
    y, aux = jax.jit(
        lambda p, x: forward(p, x, MOE, with_aux=True)
    )(params, _x())
    assert y.shape == (8, 16, 32)
    aux_val = float(aux)
    assert np.isfinite(aux_val) and aux_val >= 1.0 - 1e-5


def test_aux_loss_training(devices):
    """Training with the aux loss converges and reports it; the weight
    requires a MoE model."""
    cfg = _moe_train_cfg(name="train_moe_aux")
    cfg["training"]["moe_aux_loss_weight"] = 0.01
    result = run_train(cfg, zero_stage=1, verbose=False)
    losses = result["losses"]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]

    dense_cfg = _moe_train_cfg(name="bad", num_experts=0)
    dense_cfg["parallelism"].pop("expert_parallel")
    dense_cfg["training"]["moe_aux_loss_weight"] = 0.01
    with pytest.raises(ValueError, match="requires a MoE model"):
        run_train(dense_cfg, verbose=False)


def test_moe_dispatch_validation():
    with pytest.raises(ValueError, match="moe_dispatch"):
        ModelConfig(hidden_size=32, num_layers=2, num_heads=4,
                    ffn_intermediate=64, num_experts=2,
                    moe_dispatch="alltoall")
    with pytest.raises(ValueError, match="capacity_factor"):
        ModelConfig(hidden_size=32, num_layers=2, num_heads=4,
                    ffn_intermediate=64, num_experts=2,
                    moe_capacity_factor=0.0)


def test_validate_expert_parallelism():
    dense = MOE.with_(num_experts=0)
    with pytest.raises(ValueError, match="requires a MoE model"):
        validate_expert_parallelism(dense, 2)
    with pytest.raises(ValueError, match="not divisible"):
        validate_expert_parallelism(MOE, 3)
    validate_expert_parallelism(MOE, 2)  # ok
    validate_expert_parallelism(dense, 1)  # ep=1 always ok


def test_moe_config_validation():
    with pytest.raises(ValueError, match="moe_top_k"):
        ModelConfig(hidden_size=32, num_layers=2, num_heads=4,
                    ffn_intermediate=64, num_experts=2, moe_top_k=3)
    with pytest.raises(ValueError, match="num_experts"):
        ModelConfig(hidden_size=32, num_layers=2, num_heads=4,
                    ffn_intermediate=64, num_experts=-1)
