"""TP transformer correctness on the simulated mesh.

Key property the reference cannot test (it has no single-rank reference
implementation): TP-sharded execution must produce the same numbers as
single-device execution — the sharding layout only changes *where* compute
happens, XLA's inserted all-reduces replacing the reference's hand-written
``comm.Allreduce`` (``models.py:95``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlbb_tpu.models import (
    MODEL_CONFIGS,
    ModelConfig,
    forward,
    init_params,
    num_parameters,
    shard_params,
)
from dlbb_tpu.models.sharding import batch_spec
from jax.sharding import NamedSharding

TINY = ModelConfig(hidden_size=64, num_layers=3, num_heads=4,
                   ffn_intermediate=128, attention="full", dtype="float32")


def _batch(cfg, b=2, s=16, dtype=jnp.float32, seed=0):
    return jax.random.normal(
        jax.random.key(seed), (b, s, cfg.hidden_size), dtype=dtype
    )


def test_forward_shapes_and_dtype():
    params = init_params(TINY, jax.random.key(1))
    x = _batch(TINY)
    y = forward(params, x, TINY)
    assert y.shape == x.shape
    assert y.dtype == x.dtype
    assert np.isfinite(np.asarray(y)).all()


def test_gqa_forward_and_param_accounting():
    """Grouped-query attention: smaller QKV projection, same output shape;
    num_parameters matches the actual pytree; MQA (kv=1) included."""
    for kv in (2, 1):
        cfg = TINY.with_(num_kv_heads=kv)
        assert cfg.qkv_width == cfg.hidden_size + 2 * kv * cfg.head_dim
        params = init_params(cfg, jax.random.key(1))
        qkv_kernel = params["layers"]["qkv"]["kernel"]
        assert qkv_kernel.shape == (
            cfg.num_layers, cfg.hidden_size, cfg.qkv_width
        )
        counted = sum(int(x.size) for x in jax.tree.leaves(params))
        assert counted == num_parameters(cfg)
        y = forward(params, _batch(cfg), cfg)
        assert y.shape == (2, 16, cfg.hidden_size)
        assert np.isfinite(np.asarray(y)).all()


def test_grouped_dense_attention_matches_repeat():
    """dense_attention with kv_heads-width K/V == MHA over repeated K/V —
    the no-materialised-repeat GQA path is numerically identical."""
    from dlbb_tpu.models.attention import dense_attention

    q = jax.random.normal(jax.random.key(0), (2, 8, 16, 4))
    k = jax.random.normal(jax.random.key(1), (2, 2, 16, 4))
    v = jax.random.normal(jax.random.key(2), (2, 2, 16, 4))
    for causal in (True, False):
        got = np.asarray(dense_attention(q, k, v, causal=causal))
        want = np.asarray(dense_attention(
            q, jnp.repeat(k, 4, axis=1), jnp.repeat(v, 4, axis=1),
            causal=causal,
        ))
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_gqa_full_group_matches_mha():
    """num_kv_heads == num_heads is exactly MHA — same params, same output."""
    cfg_mha = TINY
    cfg_gqa = TINY.with_(num_kv_heads=TINY.num_heads)
    params = init_params(cfg_mha, jax.random.key(1))
    x = _batch(cfg_mha)
    np.testing.assert_array_equal(
        np.asarray(forward(params, x, cfg_mha)),
        np.asarray(forward(params, x, cfg_gqa)),
    )


def test_non_causal_attention():
    """causal=False: bidirectional dense attention — output differs from
    causal, matches a manual fp32 softmax reference, and the dense/ulysses
    kernels agree (ulysses covered in test_context_parallel)."""
    from dlbb_tpu.models.attention import dense_attention

    cfg = TINY.with_(causal=False)
    params = init_params(cfg, jax.random.key(1))
    x = _batch(cfg)
    y_bi = np.asarray(forward(params, x, cfg))
    y_causal = np.asarray(forward(params, x, TINY))
    assert np.isfinite(y_bi).all()
    assert not np.allclose(y_bi, y_causal)

    from conftest import dense_attention_ref

    q = jax.random.normal(jax.random.key(3), (2, 4, 8, 16))
    k = jax.random.normal(jax.random.key(4), (2, 4, 8, 16))
    v = jax.random.normal(jax.random.key(5), (2, 4, 8, 16))
    got = np.asarray(dense_attention(q, k, v, causal=False))
    want = dense_attention_ref(q, k, v, causal=False)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_ring_non_causal_accepted():
    """Bidirectional ring attention is a supported combination (oracle
    parity in tests/test_context_parallel.py); 'dense' is the explicit
    always-einsum mode and unknown modes still reject."""
    cfg = TINY.with_(attention="ring", causal=False)
    assert not cfg.causal
    assert TINY.with_(attention="dense").attention == "dense"
    with pytest.raises(ValueError, match="unknown attention"):
        TINY.with_(attention="sparse")


@pytest.mark.parametrize("attention", ["full", "simplified", "flash"])
def test_tp_matches_single_device(mesh2x4, attention):
    """Sharded == unsharded, across attention modes (flash exercises the
    shard_map-over-tp kernel dispatch)."""
    cfg = TINY.with_(attention=attention)
    params = init_params(cfg, jax.random.key(1))
    x = _batch(cfg)
    y_single = forward(params, x, cfg)

    sharded = shard_params(params, mesh2x4)
    xs = jax.device_put(x, NamedSharding(mesh2x4, batch_spec()))
    y_tp = jax.jit(lambda p, a: forward(p, a, cfg, mesh=mesh2x4))(sharded, xs)
    np.testing.assert_allclose(
        np.asarray(y_single), np.asarray(y_tp), rtol=2e-3, atol=2e-3
    )


def test_causal_masking():
    """Full attention must be causal: truncating the suffix of the sequence
    cannot change the prefix outputs."""
    cfg = TINY
    params = init_params(cfg, jax.random.key(1))
    x = _batch(cfg, b=1, s=16)
    full = np.asarray(forward(params, x, cfg))
    trunc = np.asarray(forward(params, x[:, :8], cfg))
    np.testing.assert_allclose(full[:, :8], trunc, rtol=2e-4, atol=2e-4)


def test_simplified_attention_is_query_slice():
    """Simplified mode takes the first third of QKV (reference
    ``models.py:162-167``), so outputs differ from full attention."""
    params = init_params(TINY, jax.random.key(1))
    x = _batch(TINY)
    y_full = np.asarray(forward(params, x, TINY))
    y_simpl = np.asarray(
        forward(params, x, TINY.with_(attention="simplified"))
    )
    assert not np.allclose(y_full, y_simpl)


def test_num_parameters_matches_pytree():
    params = init_params(TINY, jax.random.key(0))
    actual = sum(p.size for p in jax.tree.leaves(params))
    assert num_parameters(TINY) == actual


def test_reference_model_sizes():
    """1B/7B/13B configs (reference ``models.py:252-271``) have the expected
    parameter scale."""
    sizes = {k: num_parameters(v) for k, v in MODEL_CONFIGS.items()}
    assert 1.0e9 < sizes["1B"] < 1.5e9
    assert 6.0e9 < sizes["7B"] < 8.5e9
    assert 11.5e9 < sizes["13B"] < 14.5e9


def test_invalid_config_rejected():
    with pytest.raises(ValueError):
        ModelConfig(hidden_size=100, num_layers=1, num_heads=3,
                    ffn_intermediate=64)
    with pytest.raises(ValueError):
        ModelConfig(hidden_size=64, num_layers=1, num_heads=4,
                    ffn_intermediate=64, attention="flash??")


def test_remat_matches_no_remat(devices):
    """Activation rematerialisation must not change forward or gradient
    numerics — it only changes what is stored vs recomputed."""
    from dlbb_tpu.train.loop import mse_loss

    remat_cfg = TINY.with_(remat=True)
    params = init_params(TINY, jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (4, 8, TINY.hidden_size))
    t = jax.random.normal(jax.random.key(2), (4, 8, TINY.hidden_size))

    y_plain = jax.jit(lambda p, x: forward(p, x, TINY))(params, x)
    y_remat = jax.jit(lambda p, x: forward(p, x, remat_cfg))(params, x)
    np.testing.assert_allclose(np.asarray(y_plain), np.asarray(y_remat),
                               rtol=1e-6, atol=1e-6)

    g_plain = jax.jit(
        lambda p, x, t: jax.grad(mse_loss)(p, x, t, TINY)
    )(params, x, t)
    g_remat = jax.jit(
        lambda p, x, t: jax.grad(mse_loss)(p, x, t, remat_cfg)
    )(params, x, t)
    for a, b in zip(jax.tree.leaves(g_plain), jax.tree.leaves(g_remat)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)

    # the "dots" policy (save matmul outputs, recompute elementwise only)
    # is a scheduling choice, never a numerics choice
    dots_cfg = TINY.with_(remat=True, remat_policy="dots")
    g_dots = jax.jit(
        lambda p, x, t: jax.grad(mse_loss)(p, x, t, dots_cfg)
    )(params, x, t)
    for a, b in zip(jax.tree.leaves(g_plain), jax.tree.leaves(g_dots)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    with pytest.raises(ValueError, match="remat_policy"):
        TINY.with_(remat_policy="selective??")


def test_forward_flops_accounting():
    """Analytic FLOPs: spot-check the dense formula and the mode
    relationships (simplified < full; capacity < dense MoE)."""
    from dlbb_tpu.models.transformer import forward_flops

    h, f, L = TINY.hidden_size, TINY.ffn_intermediate, TINY.num_layers
    b, s = 4, 8
    expected = L * (
        2 * b * s * h * 3 * h          # qkv
        + 4 * b * s * s * h            # QK^T + AV
        + 2 * b * s * h * h            # out proj
        + 2 * b * s * h * f * 2        # ffn
    )
    assert forward_flops(TINY, b, s) == expected
    assert (forward_flops(TINY.with_(attention="simplified"), b, s)
            < forward_flops(TINY, b, s))
    moe = TINY.with_(num_experts=4, moe_top_k=2)
    cap = moe.with_(moe_dispatch="capacity", moe_capacity_factor=1.0)
    assert forward_flops(cap, b, s) < forward_flops(moe, b, s)


def test_tp_forward_compiles_megatron_allreduce_pattern(devices):
    """The reference hand-writes two all-reduces per decoder layer
    (attention-out + FFN-out row-parallel matmuls, ``models.py:95``);
    here they are DECLARED via weight PartitionSpecs and must appear in
    the compiled program — all-reduce ops inside the scanned layer body
    under TP, and none at all without TP."""
    import re

    from dlbb_tpu.models.transformer import init_params_sharded
    from dlbb_tpu.parallel.plan import build_parallelism_mesh

    cfg = TINY.with_(attention="simplified", dtype="float32")

    def compiled_hlo(tp):
        mesh = build_parallelism_mesh(1, 1, 1, tp, 1)
        params = init_params_sharded(cfg, jax.random.key(0), mesh)
        x = jnp.zeros((2, 8, cfg.hidden_size))
        return jax.jit(
            lambda p, b: forward(p, b, cfg)
        ).lower(params, x).compile().as_text()

    hlo_tp = compiled_hlo(4)
    hlo_single = compiled_hlo(1)
    assert "while" in hlo_tp  # layers execute under lax.scan
    # the all-reduces must live INSIDE the scanned layer body (the while
    # loop's called computations), not hoisted to top level — extract the
    # non-entry computations and look there
    body_text = hlo_tp.split("ENTRY")[0]
    assert len(re.findall(r"\ball-reduce", body_text)) >= 2, \
        "TP forward compiled without the Megatron all-reduces in the " \
        "scanned layer body"
    assert "all-reduce" not in hlo_single, \
        "single-device forward must need no collectives"
