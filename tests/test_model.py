"""TP transformer correctness on the simulated mesh.

Key property the reference cannot test (it has no single-rank reference
implementation): TP-sharded execution must produce the same numbers as
single-device execution — the sharding layout only changes *where* compute
happens, XLA's inserted all-reduces replacing the reference's hand-written
``comm.Allreduce`` (``models.py:95``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlbb_tpu.models import (
    MODEL_CONFIGS,
    ModelConfig,
    forward,
    init_params,
    num_parameters,
    shard_params,
)
from dlbb_tpu.models.sharding import batch_spec
from jax.sharding import NamedSharding

TINY = ModelConfig(hidden_size=64, num_layers=3, num_heads=4,
                   ffn_intermediate=128, attention="full", dtype="float32")


def _batch(cfg, b=2, s=16, dtype=jnp.float32, seed=0):
    return jax.random.normal(
        jax.random.key(seed), (b, s, cfg.hidden_size), dtype=dtype
    )


def test_forward_shapes_and_dtype():
    params = init_params(TINY, jax.random.key(1))
    x = _batch(TINY)
    y = forward(params, x, TINY)
    assert y.shape == x.shape
    assert y.dtype == x.dtype
    assert np.isfinite(np.asarray(y)).all()


@pytest.mark.parametrize("attention", ["full", "simplified", "flash"])
def test_tp_matches_single_device(mesh2x4, attention):
    """Sharded == unsharded, across attention modes (flash exercises the
    shard_map-over-tp kernel dispatch)."""
    cfg = TINY.with_(attention=attention)
    params = init_params(cfg, jax.random.key(1))
    x = _batch(cfg)
    y_single = forward(params, x, cfg)

    sharded = shard_params(params, mesh2x4)
    xs = jax.device_put(x, NamedSharding(mesh2x4, batch_spec()))
    y_tp = jax.jit(lambda p, a: forward(p, a, cfg, mesh=mesh2x4))(sharded, xs)
    np.testing.assert_allclose(
        np.asarray(y_single), np.asarray(y_tp), rtol=2e-3, atol=2e-3
    )


def test_causal_masking():
    """Full attention must be causal: truncating the suffix of the sequence
    cannot change the prefix outputs."""
    cfg = TINY
    params = init_params(cfg, jax.random.key(1))
    x = _batch(cfg, b=1, s=16)
    full = np.asarray(forward(params, x, cfg))
    trunc = np.asarray(forward(params, x[:, :8], cfg))
    np.testing.assert_allclose(full[:, :8], trunc, rtol=2e-4, atol=2e-4)


def test_simplified_attention_is_query_slice():
    """Simplified mode takes the first third of QKV (reference
    ``models.py:162-167``), so outputs differ from full attention."""
    params = init_params(TINY, jax.random.key(1))
    x = _batch(TINY)
    y_full = np.asarray(forward(params, x, TINY))
    y_simpl = np.asarray(
        forward(params, x, TINY.with_(attention="simplified"))
    )
    assert not np.allclose(y_full, y_simpl)


def test_num_parameters_matches_pytree():
    params = init_params(TINY, jax.random.key(0))
    actual = sum(p.size for p in jax.tree.leaves(params))
    assert num_parameters(TINY) == actual


def test_reference_model_sizes():
    """1B/7B/13B configs (reference ``models.py:252-271``) have the expected
    parameter scale."""
    sizes = {k: num_parameters(v) for k, v in MODEL_CONFIGS.items()}
    assert 1.0e9 < sizes["1B"] < 1.5e9
    assert 6.0e9 < sizes["7B"] < 8.5e9
    assert 11.5e9 < sizes["13B"] < 14.5e9


def test_invalid_config_rejected():
    with pytest.raises(ValueError):
        ModelConfig(hidden_size=100, num_layers=1, num_heads=3,
                    ffn_intermediate=64)
    with pytest.raises(ValueError):
        ModelConfig(hidden_size=64, num_layers=1, num_heads=4,
                    ffn_intermediate=64, attention="flash??")


def test_remat_matches_no_remat(devices):
    """Activation rematerialisation must not change forward or gradient
    numerics — it only changes what is stored vs recomputed."""
    from dlbb_tpu.train.loop import mse_loss

    remat_cfg = TINY.with_(remat=True)
    params = init_params(TINY, jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (4, 8, TINY.hidden_size))
    t = jax.random.normal(jax.random.key(2), (4, 8, TINY.hidden_size))

    y_plain = jax.jit(lambda p, x: forward(p, x, TINY))(params, x)
    y_remat = jax.jit(lambda p, x: forward(p, x, remat_cfg))(params, x)
    np.testing.assert_allclose(np.asarray(y_plain), np.asarray(y_remat),
                               rtol=1e-6, atol=1e-6)

    g_plain = jax.jit(
        lambda p, x, t: jax.grad(mse_loss)(p, x, t, TINY)
    )(params, x, t)
    g_remat = jax.jit(
        lambda p, x, t: jax.grad(mse_loss)(p, x, t, remat_cfg)
    )(params, x, t)
    for a, b in zip(jax.tree.leaves(g_plain), jax.tree.leaves(g_remat)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_forward_flops_accounting():
    """Analytic FLOPs: spot-check the dense formula and the mode
    relationships (simplified < full; capacity < dense MoE)."""
    from dlbb_tpu.models.transformer import forward_flops

    h, f, L = TINY.hidden_size, TINY.ffn_intermediate, TINY.num_layers
    b, s = 4, 8
    expected = L * (
        2 * b * s * h * 3 * h          # qkv
        + 4 * b * s * s * h            # QK^T + AV
        + 2 * b * s * h * h            # out proj
        + 2 * b * s * h * f * 2        # ffn
    )
    assert forward_flops(TINY, b, s) == expected
    assert (forward_flops(TINY.with_(attention="simplified"), b, s)
            < forward_flops(TINY, b, s))
    moe = TINY.with_(num_experts=4, moe_top_k=2)
    cap = moe.with_(moe_dispatch="capacity", moe_capacity_factor=1.0)
    assert forward_flops(cap, b, s) < forward_flops(moe, b, s)
