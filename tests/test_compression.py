"""Compressed collectives (docs/compression.md): quantise/dequantise
round-trip bounds, psum_compressed == psum within wire tolerance across
(dp) and (dp, tp) meshes, the error-feedback residual's checkpoint
round-trip, the comm-lint compression byte ceiling (clean pass + seeded
dequant-before-collective violation), and the analytic wire model pinned
against the audited HLO totals."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dlbb_tpu.analysis.expectations import (
    SCALE_CHUNK_ELEMS,
    TargetExpectation,
    compressed_op_expectation,
    op_wire_bytes,
    scale_bytes,
    wire_bytes,
)
from dlbb_tpu.analysis.hlo_audit import (
    AuditTarget,
    _compressed_op_target,
    audit_target,
)
from dlbb_tpu.comm.compression import (
    dequantize_chunked,
    psum_compressed,
    quantization_error,
    quantize_chunked,
    reduce_scatter_compressed,
)
from dlbb_tpu.comm.mesh import build_parallelism_mesh
from dlbb_tpu.comm.ops import get_op, make_payload
from dlbb_tpu.compat import shard_map
from dlbb_tpu.models.configs import ModelConfig
from dlbb_tpu.models.transformer import init_params
from dlbb_tpu.train.loop import make_train_step, run_train

AXES = ("ranks",)
N = 4096

TINY = ModelConfig(hidden_size=32, num_layers=2, num_heads=4,
                   ffn_intermediate=64, attention="full", dtype="float32")


def _train_config(**training_over):
    training = {"learning_rate": 1e-2}
    training.update(training_over)
    return {
        "experiment": {"name": "train_compression"},
        "model": {
            "hidden_size": 32, "num_layers": 2, "num_heads": 4,
            "ffn_intermediate": 64, "attention": "full", "dtype": "float32",
        },
        "parallelism": {"world_size": 1, "data_parallel": 4},
        "input": {"batch_size": 8, "sequence_length": 16, "seed": 42},
        "execution": {"warmup_iterations": 1, "benchmark_iterations": 5},
        "training": training,
    }


# ---------------------------------------------------------------------------
# quantise / dequantise kernels
# ---------------------------------------------------------------------------


def test_int8_roundtrip_error_bound():
    """Chunked symmetric int8: per-element error <= half a quantisation
    step of the chunk's own scale (amax/127), never the global amax."""
    rng = np.random.default_rng(0)
    x = rng.standard_normal(N).astype(np.float32)
    x[:SCALE_CHUNK_ELEMS] *= 100.0  # a hot chunk must not hurt the others
    q, scales = quantize_chunked(jnp.asarray(x), "int8")
    assert q.dtype == jnp.int8
    got = np.asarray(dequantize_chunked(q, scales, N, jnp.float32))
    chunk_amax = np.abs(x.reshape(-1, SCALE_CHUNK_ELEMS)).max(axis=1)
    bound = np.repeat(chunk_amax / 126.0, SCALE_CHUNK_ELEMS) + 1e-7
    assert (np.abs(got - x) <= bound).all()


def test_fp8_roundtrip_error_bound():
    """fp8(e4m3) keeps ~2 decimal digits: relative error per element
    bounded by 2^-3 of the value (plus a scale-floor term)."""
    rng = np.random.default_rng(1)
    x = rng.standard_normal(N).astype(np.float32)
    q, scales = quantize_chunked(jnp.asarray(x), "fp8")
    assert q.dtype == jnp.float8_e4m3fn
    got = np.asarray(dequantize_chunked(q, scales, N, jnp.float32))
    chunk_amax = np.abs(x.reshape(-1, SCALE_CHUNK_ELEMS)).max(axis=1)
    floor = np.repeat(chunk_amax / 448.0, SCALE_CHUNK_ELEMS)
    assert (np.abs(got - x) <= np.abs(x) / 8.0 + floor + 1e-7).all()


def test_quantization_error_is_exact_complement():
    """x == D(Q(x)) + quantization_error(x) — the error-feedback identity
    the residual contract relies on."""
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal(1000), jnp.float32)  # pad path too
    for comp in ("int8", "fp8"):
        q, s = quantize_chunked(x, comp)
        recon = dequantize_chunked(q, s, 1000, jnp.float32)
        err = quantization_error(x, comp)
        np.testing.assert_allclose(
            np.asarray(recon + err), np.asarray(x), rtol=1e-6, atol=1e-7
        )


def test_unknown_compression_rejected():
    with pytest.raises(ValueError, match="unknown compression"):
        quantize_chunked(jnp.zeros(8), "int4")


# ---------------------------------------------------------------------------
# compressed reductions == their uncompressed primitives
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("comp,tol", [("int8", 0.04), ("fp8", 0.15)])
def test_psum_compressed_matches_psum_ring(mesh8, comp, tol):
    """psum_compressed == lax.psum within the wire dtype's tolerance on
    the flat 8-rank ring, and every rank holds the identical result."""
    op = get_op("allreduce")
    x = make_payload(op, mesh8, AXES, 1000, dtype=jnp.float32)
    host = np.asarray(x, np.float64)

    fn = jax.jit(shard_map(
        lambda xl: psum_compressed(xl[0], "ranks", compression=comp)[None],
        mesh=mesh8, in_specs=P("ranks"), out_specs=P("ranks"),
    ))
    out = np.asarray(fn(x))
    expected = host.sum(axis=0)
    scale = np.abs(expected).max()
    assert np.abs(out - expected).max() <= tol * scale
    assert np.abs(out - out[0]).max() == 0.0  # replicated result


def test_psum_compressed_dp_axis_of_dp_tp_mesh(mesh2x4):
    """Reduction over ONE axis ('dp') of a (dp, tp) mesh: each tp column
    reduces independently — the exact composition the train path uses."""
    rng = np.random.default_rng(3)
    host = rng.standard_normal((8, 256)).astype(np.float32)
    x = jax.device_put(host, NamedSharding(mesh2x4, P(("dp", "tp"))))

    fn = jax.jit(shard_map(
        lambda xl: psum_compressed(xl[0], "dp", compression="int8")[None],
        mesh=mesh2x4, in_specs=P(("dp", "tp")), out_specs=P(("dp", "tp")),
    ))
    out = np.asarray(fn(x))
    grid = host.reshape(2, 4, 256).astype(np.float64)
    expected = grid.sum(axis=0)  # per tp column
    for dp_i in range(2):
        for tp_j in range(4):
            diff = np.abs(out[dp_i * 4 + tp_j] - expected[tp_j]).max()
            assert diff <= 0.04 * np.abs(expected[tp_j]).max()


def test_allreduce_q_matches_allreduce(mesh8):
    op_q, op = get_op("allreduce_q"), get_op("allreduce")
    x = make_payload(op, mesh8, AXES, N, dtype=jnp.float32)
    baseline = np.asarray(op.build(mesh8, AXES)(x), np.float64)
    for comp, tol in (("int8", 0.04), ("fp8", 0.15)):
        out = np.asarray(op_q.build(mesh8, AXES, compression=comp)(x))
        scale = np.abs(baseline).max()
        assert np.abs(out - baseline).max() <= tol * scale, comp


def test_allreduce_q_bf16_accumulation(mesh8):
    """The bf16-accumulation variant stays within a (looser) tolerance —
    the bandwidth-vs-accuracy leg the sweep engine prices."""
    op_q, op = get_op("allreduce_q"), get_op("allreduce")
    x = make_payload(op, mesh8, AXES, N, dtype=jnp.float32)
    baseline = np.asarray(op.build(mesh8, AXES)(x), np.float64)
    out = np.asarray(op_q.build(
        mesh8, AXES, compression="int8", accum_dtype=jnp.bfloat16)(x))
    assert np.abs(out - baseline).max() <= 0.08 * np.abs(baseline).max()


def test_reducescatter_q_matches_reducescatter(mesh8):
    op_q, op = get_op("reducescatter_q"), get_op("reducescatter")
    x = make_payload(op, mesh8, AXES, 512, dtype=jnp.float32)
    baseline = np.asarray(op.build(mesh8, AXES)(x), np.float64)
    out = np.asarray(op_q.build(mesh8, AXES, compression="int8")(x))
    assert out.shape == baseline.shape
    scale = np.abs(baseline).max()
    assert np.abs(out - baseline).max() <= 0.04 * scale


def test_reduce_scatter_compressed_row_gate(mesh8):
    with pytest.raises(ValueError, match="leading dim"):
        jax.jit(shard_map(
            lambda xl: reduce_scatter_compressed(xl[0], "ranks")[None],
            mesh=mesh8, in_specs=P("ranks"), out_specs=P("ranks"),
        ))(make_payload(get_op("allreduce"), mesh8, AXES, 64))


def test_compressed_ops_single_axis_only(mesh2x2x2):
    for name in ("allreduce_q", "reducescatter_q"):
        with pytest.raises(ValueError, match="single mesh axis"):
            get_op(name).build(mesh2x2x2, ("x", "y", "z"))


# ---------------------------------------------------------------------------
# analytic wire model (stats bytes_on_wire) pinned against the audited HLO
# ---------------------------------------------------------------------------


def test_wire_model_matches_audited_totals(devices):
    """op_wire_bytes IS the audit's per-instruction sum for the
    compressed ops (chunk sizes chosen padding-free), scale side channel
    included — the stats column and the lint ceiling can never drift
    apart."""
    for name in ("allreduce_q", "reducescatter_q"):
        target = _compressed_op_target(name, "int8", num_elements=N)
        findings, meta = audit_target(target)
        assert findings == [], [f.render() for f in findings]
        analytic = op_wire_bytes(name, N, 8, 2, compression="int8")
        assert meta["total_wire_bytes"] == analytic, name


def test_wire_model_counts_chunk_padding(devices):
    """A payload whose ring chunk is NOT a SCALE_CHUNK multiple travels
    zero-padded; the analytic model charges the padding, so a correct
    ring still audits clean (ceiling = max(ratio x baseline, 1.1 x its
    own analytic wire)) and the stats column reports the real bytes."""
    n = 3000  # ring chunks of 375 -> padded to 512 on the wire
    target = _compressed_op_target("allreduce_q", "int8", num_elements=n)
    findings, meta = audit_target(target)
    assert findings == [], [f.render() for f in findings]
    analytic = op_wire_bytes("allreduce_q", n, 8, 2, compression="int8")
    assert meta["total_wire_bytes"] == analytic
    # the padded model is what the audit saw — an unpadded one would
    # undercount by the 512/375 ratio and reject this very module
    unpadded_ring = 7 * (375 * 1 + scale_bytes(375))
    assert analytic > 2 * unpadded_ring


def test_wire_model_uncompressed_consistency():
    """The per-op formulas agree with the per-instruction ring model for
    the single-collective encodings."""
    n, p, b = 1024, 8, 2
    assert op_wire_bytes("allreduce", n, p, b) == \
        wire_bytes("all-reduce", n * b, p)
    assert op_wire_bytes("allgather", n, p, b) == \
        wire_bytes("all-gather", p * n * b, p)
    assert op_wire_bytes("reducescatter", n, p, b) == \
        wire_bytes("reduce-scatter", n * b, p)
    assert op_wire_bytes("sendrecv", n, p, b) == n * b
    # compressed vs baseline: the 0.55x acceptance ratio holds
    # analytically at chunk-aligned, compression-meaningful sizes
    big = 16384  # ring chunks of 2048 elements, SCALE_CHUNK-aligned
    ratio = op_wire_bytes("allreduce_q", big, p, b) / \
        op_wire_bytes("allreduce", big, p, b)
    assert ratio <= 0.55, ratio
    # ...and at tiny payloads the padding + scale overhead honestly
    # EXCEEDS the baseline (compression does not pay below a ring chunk
    # of SCALE_CHUNK_ELEMS) — the model must report that, not hide it
    tiny_ratio = op_wire_bytes("allreduce_q", 256, p, b) / \
        op_wire_bytes("allreduce", 256, p, b)
    assert tiny_ratio > 1.0, tiny_ratio
    assert op_wire_bytes("ag_matmul", n, p, b) is None  # schedule-dependent


def test_stats_rows_carry_bytes_on_wire(tmp_path):
    """stats1d rows (and through them the comparison) carry the analytic
    wire volume; compressed rows show the saving while bandwidth_gbps
    stays normalised by LOGICAL payload bytes."""
    from dlbb_tpu.stats.stats1d import process_file

    rows = {}
    for op_name, extra in (("allreduce", {}),
                           ("allreduce_q", {"compression": "int8"})):
        art = {
            "implementation": "x", "operation": op_name, "num_ranks": 8,
            "num_elements": N, "dtype": "bfloat16",
            "data_size_name": "8KB", "timings": [[0.001] * 4],
            **extra,
        }
        f = tmp_path / f"{op_name}.json"
        f.write_text(json.dumps(art))
        rows[op_name] = process_file(f)
    assert rows["allreduce"]["bytes_on_wire"] == \
        op_wire_bytes("allreduce", N, 8, 2)
    assert rows["allreduce_q"]["bytes_on_wire"] == \
        op_wire_bytes("allreduce_q", N, 8, 2, compression="int8")
    # identical logical-bandwidth normalisation on both rows
    assert rows["allreduce"]["bandwidth_gbps"] == \
        rows["allreduce_q"]["bandwidth_gbps"]
    assert rows["allreduce_q"]["bytes_on_wire"] < \
        0.55 * rows["allreduce"]["bytes_on_wire"]


# ---------------------------------------------------------------------------
# comm-lint: clean passes + seeded violations
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("comp", ["int8", "fp8"])
@pytest.mark.parametrize("op_name", ["allreduce_q", "reducescatter_q"])
def test_compressed_targets_audit_clean(devices, op_name, comp):
    """The compression proof: pure quantised ring, total wire (scales
    included) under 0.55x the bf16 baseline — for BOTH wire dtypes (fp8
    rides the wire bitcast to int8, so backend float-normalisation can
    never silently double it)."""
    findings, meta = audit_target(_compressed_op_target(op_name, comp))
    assert findings == [], [f.render() for f in findings]
    assert meta["num_collectives"] >= 7  # >= P-1 permute hops


def test_dequant_before_collective_flagged(mesh8):
    """Seeded violation: quantise, dequantise locally, then psum in bf16
    — exactly the 'XLA undid the compression' failure mode.  The audit
    must flag the uncompressed all-reduce AND the blown byte ceiling."""
    from dlbb_tpu.comm.compression import (
        dequantize_chunked as deq,
        quantize_chunked as quant,
    )

    def build():
        def body(x):
            q, s = quant(x[0], "int8")
            back = deq(q, s, N, jnp.bfloat16)  # dequantised BEFORE the wire
            return jax.lax.psum(back, "ranks")[None]

        fn = jax.jit(shard_map(body, mesh=mesh8, in_specs=P("ranks"),
                               out_specs=P("ranks")))
        x = make_payload(get_op("allreduce_q"), mesh8, AXES, N,
                         dtype=jnp.bfloat16)
        return fn, (x,)

    target = AuditTarget(
        name="fixture/dequant_before_collective", build=build,
        expectation=compressed_op_expectation("allreduce_q", 8, N),
    )
    findings, _ = audit_target(target)
    rules = {f.rule for f in findings}
    assert "unexpected-collective" in rules, rules
    assert "wire-volume-ceiling" in rules, rules


def test_wire_volume_ceiling_fires_alone_on_fat_ring(mesh8):
    """A ring whose KINDS are right but whose wire is uncompressed bf16:
    only the total-volume rule can catch it — pinned here in isolation
    (no per-instruction ceiling set)."""
    n = 512

    def build():
        def body(x):
            part = x[0]
            perm = [(i, (i + 1) % 8) for i in range(8)]
            for _ in range(7):  # bf16 chunks on the wire: 2x the claim
                part = jax.lax.ppermute(part, "ranks", perm) + x[0]
            return part[None]

        fn = jax.jit(shard_map(body, mesh=mesh8, in_specs=P("ranks"),
                               out_specs=P("ranks")))
        x = make_payload(get_op("allreduce"), mesh8, AXES, n,
                         dtype=jnp.bfloat16)
        return fn, (x,)

    ceiling = int(0.55 * wire_bytes("reduce-scatter", n * 2, 8))
    target = AuditTarget(
        name="fixture/bf16_wire_ring", build=build,
        expectation=TargetExpectation(
            allowed={"collective-permute"},
            required_any={"collective-permute"},
            min_required=7,
            max_total_wire_bytes=ceiling,
        ),
    )
    findings, meta = audit_target(target)
    assert [f.rule for f in findings] == ["wire-volume-ceiling"]
    assert meta["total_wire_bytes"] > ceiling


# ---------------------------------------------------------------------------
# train-loop integration: error feedback, checkpointing, validation
# ---------------------------------------------------------------------------


def _compressed_setup(tmp_dir=None, compression="int8", zero_stage=0):
    mesh = build_parallelism_mesh(data_parallel=4)
    params = init_params(TINY, jax.random.key(0))
    jit_step, state = make_train_step(
        TINY, mesh, optax.adam(1e-2), params, zero_stage=zero_stage,
        grad_compression=compression,
    )
    x = jax.random.normal(jax.random.key(1), (8, 16, 32))
    y = jax.random.normal(jax.random.key(2), (8, 16, 32))
    return jit_step, state, x, y


def test_residual_state_shape_and_sharding(devices):
    """The error-feedback residual is an optimizer-state leaf: [dp, total
    params], dp-sharded (one row per rank, never replicated)."""
    _, state, _, _ = _compressed_setup()
    inner, comp = state.opt_state
    total = sum(p.size for p in jax.tree.leaves(state.params))
    assert comp.residual.shape == (4, total)
    spec = comp.residual.sharding.spec
    assert tuple(spec) and spec[0] == "dp"


def test_residual_checkpoint_roundtrip(devices, tmp_path):
    """Error-feedback residual survives save/restore bit-exactly, with
    its dp sharding — the optimizer-state-leaf contract."""
    from dlbb_tpu.train.checkpoint import CheckpointConfig, Checkpointer

    jit_step, state, x, y = _compressed_setup()
    for _ in range(3):
        state, _ = jit_step(state, x, y)
    res = np.asarray(jax.device_get(state.opt_state[1].residual))
    assert np.abs(res).max() > 0.0  # quantisation error accumulated

    with Checkpointer(CheckpointConfig(str(tmp_path / "ck"))) as ckpt:
        assert ckpt.maybe_save(state, force=True)
        restored = ckpt.restore(state)

    assert int(restored.step) == 3
    r_res = restored.opt_state[1].residual
    np.testing.assert_array_equal(np.asarray(jax.device_get(r_res)), res)
    assert r_res.sharding == state.opt_state[1].residual.sharding
    # the restored state steps on without retracing surprises
    restored, loss = jit_step(restored, x, y)
    assert np.isfinite(float(loss))


def test_compressed_zero2_trains(devices):
    r = run_train(_train_config(grad_compression="int8"), zero_stage=2,
                  verbose=False)
    assert r["zero_stage"] == 2 and r["grad_compression"] == "int8"
    assert all(np.isfinite(r["losses"]))
    assert r["losses"][-1] < r["losses"][0]


def test_residual_moments_dtype_cast(devices):
    """residual follows the moments-storage dtype (memory-reduced Adam)."""
    mesh = build_parallelism_mesh(data_parallel=4)
    params = init_params(TINY, jax.random.key(0))
    _, state = make_train_step(
        TINY, mesh, optax.adam(1e-2), params, zero_stage=0,
        grad_compression="int8", residual_dtype="bfloat16",
    )
    assert state.opt_state[1].residual.dtype == jnp.bfloat16


def test_grad_compression_validation(devices):
    mesh_tp = build_parallelism_mesh(data_parallel=2, tensor_parallel=2)
    mesh_dp = build_parallelism_mesh(data_parallel=4)
    params = init_params(TINY, jax.random.key(0))
    opt = optax.adam(1e-2)
    with pytest.raises(ValueError, match="unknown grad_compression"):
        make_train_step(TINY, mesh_dp, opt, params, grad_compression="int4")
    with pytest.raises(ValueError, match="pure data-parallel"):
        make_train_step(TINY, mesh_tp, opt, params, grad_compression="int8")
    with pytest.raises(ValueError, match="data_parallel=1"):
        # dp=1 has no reduction: the residual would feed back an error
        # that was never incurred on the wire
        make_train_step(TINY, build_parallelism_mesh(data_parallel=1),
                        opt, params, grad_compression="int8")
    with pytest.raises(ValueError, match="ZeRO stages 0"):
        make_train_step(TINY, mesh_dp, opt, params, zero_stage=1,
                        grad_compression="int8")
    with pytest.raises(ValueError, match="gradient_accumulation"):
        make_train_step(TINY, mesh_dp, opt, params, grad_accum=2,
                        grad_compression="int8")
    with pytest.raises(ValueError, match="grad_compression"):
        run_train(_train_config(grad_compression="lossy"), verbose=False)
    with pytest.raises(ValueError, match="compression_accum_dtype"):
        run_train(_train_config(grad_compression="int8",
                                compression_accum_dtype="float16"),
                  verbose=False)


# ---------------------------------------------------------------------------
# compression_smoke marker stage (scripts/run_static_analysis.sh)
# ---------------------------------------------------------------------------


@pytest.mark.compression_smoke
def test_compressed_train_tracks_uncompressed(devices):
    """Loss curve of the int8 error-feedback run tracks the uncompressed
    run step for step — the train-side acceptance gate (BENCH_compress
    measures the same divergence over a longer horizon)."""
    r_base = run_train(_train_config(), verbose=False)
    r_int8 = run_train(_train_config(grad_compression="int8"),
                       verbose=False)
    r_fp8 = run_train(_train_config(grad_compression="fp8"), verbose=False)
    for r in (r_int8, r_fp8):
        assert all(np.isfinite(r["losses"]))
    div8 = max(abs(a - b) / max(abs(a), 1e-9)
               for a, b in zip(r_base["losses"], r_int8["losses"]))
    assert div8 <= 0.02, (div8, r_base["losses"], r_int8["losses"])
    divf = max(abs(a - b) / max(abs(a), 1e-9)
               for a, b in zip(r_base["losses"], r_fp8["losses"]))
    assert divf <= 0.05, divf
    assert r_int8["losses"][-1] < r_int8["losses"][0]


@pytest.mark.compression_smoke
def test_compression_mini_sweep_and_topology(tmp_path, devices):
    """allreduce_q variant mini-sweep through the real engine: artifacts
    carry the compression field, and the sweep manifest + journal carry
    the topology record (platform, rank count, degraded flag — the
    ROADMAP item 5 standing chore, first slice)."""
    from dlbb_tpu.bench.runner import Sweep1D, run_sweep
    from dlbb_tpu.resilience.journal import read_journal

    for variant, expect_comp in (("compress_int8", "int8"),
                                 ("compress_fp8", "fp8"),
                                 ("compress_int8_bf16acc", "int8")):
        out = tmp_path / variant
        sweep = Sweep1D(
            implementation="comp_smoke", variant=variant,
            operations=("allreduce_q",), data_sizes=(("1KB", 256),),
            rank_counts=(8,), warmup_iterations=1,
            measurement_iterations=3, output_dir=str(out),
            compile_cache="off", pipeline=False,
        )
        files = run_sweep(sweep, verbose=False)
        assert len(files) == 1
        art = json.loads(files[0].read_text())
        assert art["compression"] == expect_comp
        assert art["variant"] == variant

        manifest = json.loads((out / "sweep_manifest.json").read_text())
        topo = manifest["topology"]
        assert topo["platform"] == "cpu"
        assert topo["num_devices"] >= 8
        assert topo["simulated"] is True
        # the test harness REQUESTED the simulation: not a degraded fallback
        assert topo["degraded"] is False

        events, torn = read_journal(out)
        assert torn == 0
        topo_events = [e for e in events if e["event"] == "topology"]
        assert topo_events and topo_events[0]["platform"] == "cpu"


def test_topology_record_degraded_classification(monkeypatch):
    """An explicit degraded reason (the bench.py probe fallback) flips
    the record to degraded; a test-requested simulation stays clean."""
    from dlbb_tpu.utils import simulate

    rec = simulate.topology_record()
    assert rec["degraded"] is False  # conftest forced the simulation
    assert rec["simulation_forced"] is True
    monkeypatch.setattr(simulate, "_DEGRADED_REASON",
                        "accelerator backend unreachable (probe timeout)")
    rec = simulate.topology_record()
    assert rec["degraded"] is True
    assert "unreachable" in rec["degraded_reason"]
