"""Static memory auditor (buffer liveness / peak HBM) tests.

Three layers, mirroring docs/memory_audit.md:

- ``hlo_parse`` buffer-size edge cases the liveness pass depends on:
  tuple-shaped outputs, bitcast (zero-cost alias), zero-sized buffers,
  while-carried tuples, and the ``input_output_alias`` donation table —
  pinned on synthetic HLO plus one real ``lax.scan`` lowering.
- the liveness analysis itself: peak/live-set computation, donation
  accounting, nested-computation composition (while / conditional /
  fusion), and every memory rule on seeded-violation fixtures.
- the gate integration: real serving/train targets prove their donated
  buffers aliased and the analytic cache formula pinned to the compiled
  carry; the baseline diff fails on the memory axis alone; the
  ``analyze memory --output`` observability surface (manifest +
  ``analysis_peak_live_bytes`` gauges) round-trips.

The ``memory_smoke`` marker subset is also invoked standalone by
``scripts/run_static_analysis.sh``.
"""

import json

import pytest

from dlbb_tpu.analysis.costmodel import get_tier
from dlbb_tpu.analysis.expectations import TargetExpectation
from dlbb_tpu.analysis.findings import EXIT_FINDINGS
from dlbb_tpu.analysis.hlo_parse import (
    BufferAlias,
    parse_alias_table,
    parse_module,
)
from dlbb_tpu.analysis.memory_audit import (
    REPLICATED_FLOOR_BYTES,
    analyze_memory,
    memory_metrics,
    write_memory_artifacts,
)

# ---------------------------------------------------------------------------
# hlo_parse edge cases (the buffer-size substrate)
# ---------------------------------------------------------------------------


def test_parse_tuple_shaped_output_bytes():
    """A tuple result's bytes sum its elements; get-tuple-element keeps
    per-element types."""
    hlo = """
ENTRY %main (p: f32[8]) -> (f32[8], s32[]) {
  %p = f32[8]{0} parameter(0)
  %i = s32[] constant(3)
  ROOT %t = (f32[8]{0}, s32[]) tuple(f32[8]{0} %p, s32[] %i)
}
"""
    mod = parse_module(hlo)
    t = mod.entry_computation().by_name()["t"]
    assert t.arrays == [("f32", (8,)), ("s32", ())]
    assert t.result_bytes == 8 * 4 + 4


def test_parse_zero_sized_buffer():
    hlo = "%z = f32[0,128]{1,0} parameter(0)"
    mod = parse_module(hlo)
    (comp, instr), = mod.all_instructions()
    assert instr.shape == (0, 128)
    assert instr.result_bytes == 0


def test_parse_parameter_number():
    hlo = """
ENTRY %main (a: f32[4], b: f32[8]) -> f32[8] {
  %a = f32[4]{0} parameter(0)
  ROOT %b = f32[8]{0} parameter(1)
}
"""
    by_name = parse_module(hlo).entry_computation().by_name()
    assert by_name["a"].parameter_number == 0
    assert by_name["b"].parameter_number == 1


def test_parse_alias_table_entries():
    header = ("HloModule jit_step, is_scheduled=true, "
              "input_output_alias={ {0}: (0, {}, may-alias), "
              "{1,0}: (2, {1}, must-alias) }, "
              "entry_computation_layout={(f32[4]{0})->f32[4]{0}}")
    table = parse_alias_table(header)
    assert table == [
        BufferAlias(output_index=(0,), parameter_number=0),
        BufferAlias(output_index=(1, 0), parameter_number=2,
                    parameter_index=(1,)),
    ]
    assert parse_alias_table("HloModule plain, is_scheduled=true") == []


def test_parse_module_carries_alias_table():
    hlo = ("HloModule m, input_output_alias={ {}: (0, {}, may-alias) }\n"
           "ENTRY %main (p: f32[4]) -> f32[4] {\n"
           "  %p = f32[4]{0} parameter(0)\n"
           "  ROOT %n = f32[4]{0} negate(f32[4]{0} %p)\n"
           "}\n")
    mod = parse_module(hlo)
    assert mod.input_output_alias == [
        BufferAlias(output_index=(), parameter_number=0)
    ]


# ---------------------------------------------------------------------------
# liveness analysis units
# ---------------------------------------------------------------------------

CHAIN_HLO = """
HloModule chain, is_scheduled=true
ENTRY %main (p: f32[100]) -> f32[100] {
  %p = f32[100]{0} parameter(0)
  %a = f32[100]{0} negate(f32[100]{0} %p)
  %b = f32[100]{0} exponential(f32[100]{0} %a)
  ROOT %c = f32[100]{0} add(f32[100]{0} %a, f32[100]{0} %b)
}
"""


def test_liveness_chain_peak():
    """At the root instant: param (live whole run) + a (still consumed
    by c) + b + the output buffer = 4 x 400 B."""
    findings, meta = analyze_memory(CHAIN_HLO, TargetExpectation(), "t")
    assert findings == []
    assert meta["peak_live_bytes"] == 1600
    assert meta["peak_instruction"] == "c"
    assert {x["name"] for x in meta["live_at_peak"]} == {"p", "a", "b", "c"}
    assert meta["parameter_bytes"] == 400
    assert meta["output_bytes"] == 400


def test_liveness_dead_buffer_freed():
    """A buffer whose last consumer has executed stops counting: b dies
    before d runs, so the peak instant holds a+b (+p), not a+b+c+d."""
    hlo = """
HloModule t, is_scheduled=true
ENTRY %main (p: f32[100]) -> f32[100] {
  %p = f32[100]{0} parameter(0)
  %a = f32[100]{0} negate(f32[100]{0} %p)
  %b = f32[100]{0} exponential(f32[100]{0} %a)
  %c = f32[100]{0} add(f32[100]{0} %b, f32[100]{0} %b)
  ROOT %d = f32[100]{0} negate(f32[100]{0} %c)
}
"""
    _, meta = analyze_memory(hlo, TargetExpectation(), "t")
    # 400 (p) + the widest instant: a+b at b / b+c at c / c+d at d = 800
    assert meta["peak_live_bytes"] == 1200


def test_liveness_bitcast_is_zero_cost_alias():
    """bitcast charges nothing and keeps its SOURCE alive through the
    bitcast's consumers."""
    hlo = """
HloModule t, is_scheduled=true
ENTRY %main (p: f32[100]) -> f32[100] {
  %p = f32[100]{0} parameter(0)
  %a = f32[100]{0} negate(f32[100]{0} %p)
  %v = f32[4,25]{1,0} bitcast(f32[100]{0} %a)
  %w = f32[4,25]{1,0} negate(f32[4,25]{1,0} %v)
  ROOT %c = f32[100]{0} bitcast(f32[4,25]{1,0} %w)
}
"""
    _, meta = analyze_memory(hlo, TargetExpectation(), "t")
    # p + a (kept alive through v) + w; the two bitcasts add nothing
    assert meta["peak_live_bytes"] == 1200
    names = {x["name"] for x in meta["live_at_peak"]}
    assert "v" not in names and "c" not in names


def test_liveness_while_carried_tuple():
    """While bodies charge their internal peak (params excluded — they
    alias the carry) at the call instant; the body root is the new carry
    double-buffering against the old one."""
    hlo = """
HloModule t, is_scheduled=true

%body (bp: (f32[256], s32[])) -> (f32[256], s32[]) {
  %bp = (f32[256]{0}, s32[]) parameter(0)
  %x = f32[256]{0} get-tuple-element((f32[256]{0}, s32[]) %bp), index=0
  %i = s32[] get-tuple-element((f32[256]{0}, s32[]) %bp), index=1
  %t = f32[2,256]{1,0} broadcast(f32[256]{0} %x), dimensions={1}
  %y = f32[256]{0} slice(f32[2,256]{1,0} %t), slice={[0:1], [0:256]}
  %one = s32[] constant(1)
  %i2 = s32[] add(s32[] %i, s32[] %one)
  ROOT %out = (f32[256]{0}, s32[]) tuple(f32[256]{0} %y, s32[] %i2)
}

%cond (cp: (f32[256], s32[])) -> pred[] {
  %cp = (f32[256]{0}, s32[]) parameter(0)
  %ci = s32[] get-tuple-element((f32[256]{0}, s32[]) %cp), index=1
  %lim = s32[] constant(4)
  ROOT %lt = pred[] compare(s32[] %ci, s32[] %lim), direction=LT
}

ENTRY %main (p: f32[256]) -> f32[256] {
  %p = f32[256]{0} parameter(0)
  %zero = s32[] constant(0)
  %tup = (f32[256]{0}, s32[]) tuple(f32[256]{0} %p, s32[] %zero)
  %w = (f32[256]{0}, s32[]) while((f32[256]{0}, s32[]) %tup), condition=%cond, body=%body, backend_config={"known_trip_count":{"n":"4"}}
  ROOT %res = f32[256]{0} get-tuple-element((f32[256]{0}, s32[]) %w), index=0
}
"""
    _, meta = analyze_memory(hlo, TargetExpectation(), "t")
    # at the while instant: p (1024, the carry, live as operand AND as
    # the loop result consumed by res) + body extra: t (2048) + y (the
    # new carry, 1024) + scalars — and NO phantom copy of the carry for
    # the while's own result (it reuses the carry buffers in place)
    assert 4096 <= meta["peak_live_bytes"] <= 4200
    assert meta["peak_instruction"] == "w"
    # the body's big transient is visible in the cross-computation table
    top = meta["top_transients"][0]
    assert top["name"] == "t" and top["computation"] == "body"
    assert top["execution_count"] == 4
    assert meta["max_transient_bytes"] == 2048


def test_liveness_conditional_takes_max_branch():
    hlo = """
HloModule t, is_scheduled=true

%small (sp: f32[16]) -> f32[16] {
  %sp = f32[16]{0} parameter(0)
  %sm = f32[16]{0} negate(f32[16]{0} %sp)
  ROOT %sr = f32[16]{0} add(f32[16]{0} %sm, f32[16]{0} %sm)
}

%big (bp: f32[16]) -> f32[16] {
  %bp = f32[16]{0} parameter(0)
  %fat = f32[64,16]{1,0} broadcast(f32[16]{0} %bp), dimensions={1}
  %red = f32[16]{0} slice(f32[64,16]{1,0} %fat), slice={[0:1], [0:16]}
  ROOT %br = f32[16]{0} negate(f32[16]{0} %red)
}

ENTRY %main (p: f32[16], q: pred[]) -> f32[16] {
  %p = f32[16]{0} parameter(0)
  %q = pred[] parameter(1)
  ROOT %c = f32[16]{0} conditional(pred[] %q, f32[16]{0} %p, f32[16]{0} %p), true_computation=%big, false_computation=%small
}
"""
    _, meta = analyze_memory(hlo, TargetExpectation(), "t")
    # p (64) + q (1) + worst-branch internal peak: fat (4096) + red (64)
    # both live at red's instant — never the small branch's 192 B
    assert meta["peak_live_bytes"] == 65 + 4096 + 64
    assert meta["max_transient_bytes"] == 4096


def test_liveness_fusion_charges_root_only():
    """Fused intermediates never materialise: the fusion instruction's
    own result is the only charge."""
    hlo = """
HloModule t, is_scheduled=true

%fused (fp: f32[32]) -> f32[32] {
  %fp = f32[32]{0} parameter(0)
  %fa = f32[32]{0} negate(f32[32]{0} %fp)
  %fb = f32[32]{0} exponential(f32[32]{0} %fa)
  ROOT %fc = f32[32]{0} add(f32[32]{0} %fb, f32[32]{0} %fa)
}

ENTRY %main (p: f32[32]) -> f32[32] {
  %p = f32[32]{0} parameter(0)
  ROOT %f = f32[32]{0} fusion(f32[32]{0} %p), kind=kLoop, calls=%fused
}
"""
    _, meta = analyze_memory(hlo, TargetExpectation(), "t")
    assert meta["peak_live_bytes"] == 128 + 128  # p + the fusion result
    assert meta["max_transient_bytes"] == 0
    assert all(t["computation"] != "fused" for t in meta["top_transients"])


DONATED_HLO = """
HloModule t, is_scheduled=true, input_output_alias={ {0}: (0, {}, may-alias) }
ENTRY %main (state: f32[512], x: f32[512]) -> (f32[512], f32[]) {
  %state = f32[512]{0} parameter(0)
  %x = f32[512]{0} parameter(1)
  %new = f32[512]{0} add(f32[512]{0} %state, f32[512]{0} %x)
  %loss = f32[] constant(0)
  ROOT %out = (f32[512]{0}, f32[]) tuple(f32[512]{0} %new, f32[] %loss)
}
"""


def test_donation_single_counts_the_carry():
    """The donated param stays resident to program end; the output
    element reusing its region is charged zero — 2048 (state) + 2048 (x)
    + the scalar, never 3 x 2048."""
    findings, meta = analyze_memory(
        DONATED_HLO, TargetExpectation(expect_donation=True), "t",
        lowered_text="{jax.buffer_donor = true}")
    assert findings == []
    assert meta["peak_live_bytes"] == 2048 + 2048 + 4
    assert meta["donated_param_bytes"] == 2048
    donated = {p["name"]: p for p in meta["donated_params"]}
    assert donated["state"]["aliased"] is True
    assert donated["x"]["aliased"] is False


def test_unaliased_donation_fires():
    """Donor markers in the lowered module but no compiled alias table =
    XLA silently dropped the donation."""
    undonated = DONATED_HLO.replace(
        ", input_output_alias={ {0}: (0, {}, may-alias) }", "")
    findings, meta = analyze_memory(
        undonated, TargetExpectation(expect_donation=True), "t",
        lowered_text="{jax.buffer_donor = true}")
    assert [f.rule for f in findings] == ["unaliased-donation"]
    assert findings[0].severity == "error"
    # and the carry is now double-resident
    assert meta["peak_live_bytes"] == 2048 + 2048 + 2048 + 4


def test_peak_memory_ceiling_fires():
    findings, _ = analyze_memory(
        CHAIN_HLO, TargetExpectation(max_peak_bytes=1000), "t")
    assert [f.rule for f in findings] == ["peak-memory-ceiling"]
    d = findings[0].details
    assert d["peak_live_bytes"] == 1600 and d["max_peak_bytes"] == 1000


def _replicated_hlo(elems: int = 131072) -> str:
    return f"""
HloModule t, is_scheduled=true
ENTRY %main (p: f32[{elems}]) -> f32[{elems}] {{
  %p = f32[{elems}]{{0}} parameter(0)
  %fat = f32[8,{elems}]{{1,0}} broadcast(f32[{elems}]{{0}} %p), dimensions={{1}}
  %s = f32[1,{elems}]{{1,0}} slice(f32[8,{elems}]{{1,0}} %fat), slice={{[0:1], [0:{elems}]}}
  ROOT %r = f32[{elems}]{{0}} reshape(f32[1,{elems}]{{1,0}} %s)
}}
"""


def test_transient_replicated_buffer_fires():
    findings, meta = analyze_memory(
        _replicated_hlo(), TargetExpectation(), "t", num_devices=8)
    assert [f.rule for f in findings] == ["transient-replicated-buffer"]
    assert findings[0].details["name"] == "fat"
    assert findings[0].details["num_devices"] == 8


def test_transient_replicated_buffer_exemptions():
    # single device: replication is meaningless
    f1, _ = analyze_memory(_replicated_hlo(), TargetExpectation(), "t",
                           num_devices=1)
    # under the floor: KB-scale broadcasts are everywhere and harmless
    small = _replicated_hlo(elems=1024)
    f2, _ = analyze_memory(small, TargetExpectation(), "t", num_devices=8)
    assert f1 == [] and f2 == []
    assert 1024 * 4 * 8 < REPLICATED_FLOOR_BYTES
    # a collective producing P x its operand is doing its job (the wire
    # auditor prices it) — an all-gather result is exempt
    gathered = _replicated_hlo().replace(
        "broadcast(f32[131072]{0} %p), dimensions={1}",
        "all-gather(f32[131072]{0} %p), replica_groups={{0,1,2,3,4,5,6,7}}"
        ", dimensions={0}")
    f3, _ = analyze_memory(gathered, TargetExpectation(), "t",
                           num_devices=8)
    assert [f.rule for f in f3] == []


def test_serving_cache_drift_fires():
    findings, meta = analyze_memory(
        DONATED_HLO,
        TargetExpectation(donated_bytes_expected=4096,
                          donated_bytes_tolerance=0.10),
        "t", lowered_text="{jax.buffer_donor = true}")
    assert [f.rule for f in findings] == ["serving-cache-drift"]
    assert findings[0].details["donated_param_bytes"] == 2048
    # within tolerance: clean
    ok, _ = analyze_memory(
        DONATED_HLO,
        TargetExpectation(donated_bytes_expected=2000,
                          donated_bytes_tolerance=0.10),
        "t", lowered_text="{jax.buffer_donor = true}")
    assert ok == []


def test_hbm_headroom_and_infeasible_warning():
    tier = get_tier("cpu-sim")
    _, meta = analyze_memory(CHAIN_HLO, TargetExpectation(), "t",
                             tier=tier)
    assert meta["hbm_bytes"] == int(tier.hbm_bytes)
    assert meta["hbm_headroom_bytes"] == int(tier.hbm_bytes) - 1600
    assert meta["feasible"] is True
    from dataclasses import replace

    tiny_tier = replace(tier, hbm_bytes=1024.0)
    findings, meta2 = analyze_memory(CHAIN_HLO, TargetExpectation(), "t",
                                     tier=tiny_tier)
    assert meta2["feasible"] is False
    assert [f.rule for f in findings] == ["hbm-infeasible"]
    assert findings[0].severity == "warning"


# ---------------------------------------------------------------------------
# real lowerings (the lax.scan pin + the serving/train donation proof)
# ---------------------------------------------------------------------------


def test_real_lax_scan_lowering(devices):
    """The liveness pass on a real donated lax.scan program: alias table
    parsed, donated carry aliased, scan while-body analysed without
    double-charging the carry."""
    import jax
    import jax.numpy as jnp

    def step(state, xs):
        def body(c, x):
            return c + jnp.dot(x, x.T).sum(), c
        return jax.lax.scan(body, state, xs)

    jitted = jax.jit(step, donate_argnums=(0,))
    state = jnp.zeros((), jnp.float32)
    xs = jnp.ones((8, 16, 16), jnp.float32)
    lowered = jitted.lower(state, xs)
    module = parse_module(lowered.compile().as_text())
    assert any(a.parameter_number == 0
               for a in module.input_output_alias)
    findings, meta = analyze_memory(
        module, TargetExpectation(expect_donation=True), "scan",
        lowered_text=lowered.as_text())
    assert findings == []
    # xs (8*16*16*4 = 8192) dominates; the while machinery must stay a
    # small constant over it, far under a per-trip duplication (8x)
    assert 8192 < meta["peak_live_bytes"] < 3 * 8192
    assert any(p["aliased"] for p in meta["donated_params"])


@pytest.mark.memory_smoke
def test_decode_step_cache_crosscheck(devices):
    """The acceptance pin: the decode-step target audits clean, its
    donated cache carry is aliased in the liveness report, and the
    analytic kv_cache_bytes_per_device agrees with the compiled donated
    bytes within the documented tolerance."""
    from dlbb_tpu.analysis.hlo_audit import (
        _decode_step_target,
        _serve_cache_bytes_per_device,
        audit_target,
    )

    target = _decode_step_target()
    findings, meta = audit_target(target, passes=("memory",),
                                  tier=get_tier("cpu-sim"))
    assert findings == [], [f.render() for f in findings]
    mem = meta["memory"]
    analytic = _serve_cache_bytes_per_device(2, 4)
    assert mem["analytic_donated_bytes"] == analytic
    donated = mem["donated_param_bytes"]
    tol = target.expectation.donated_bytes_tolerance
    assert abs(donated - analytic) <= tol * analytic
    assert donated >= mem["peak_live_bytes"] * 0.1  # cache is material
    aliased = [p for p in mem["donated_params"] if p["aliased"]]
    assert aliased, "decode carry must be aliased (donated)"
    assert mem["feasible"] is True


@pytest.mark.memory_smoke
def test_train_step_donation_proof(devices):
    """A donating train step shows its state aliased; the SAME program
    jitted without donation trips unaliased-donation AND the peak
    ceiling — the seeded violation the CI stage pins (exit 1)."""
    import jax
    import optax

    from dlbb_tpu import analysis
    from dlbb_tpu.analysis.hlo_audit import (
        AuditTarget,
        _train_step_target,
        audit_target,
    )

    target = _train_step_target(zero_stage=0)
    findings, meta = audit_target(target, passes=("memory",))
    assert findings == [], [f.render() for f in findings]
    mem = meta["memory"]
    assert mem["donated_param_bytes"] > 0
    assert any(p["aliased"] for p in mem["donated_params"])

    # seeded violation: strip the donation (wrap the donating jit in an
    # outer donation-free jit) — state doubles, both memory rules fire
    def undonated_build():
        jit_step, args = target.build()
        return jax.jit(lambda *a: jit_step(*a)), args

    bad = AuditTarget(
        name=target.name, build=undonated_build,
        expectation=target.expectation, min_devices=target.min_devices,
    )
    bad_findings, bad_meta = audit_target(bad, passes=("memory",))
    rules = {f.rule for f in bad_findings}
    assert "unaliased-donation" in rules
    assert "peak-memory-ceiling" in rules
    # the undonated lowering keeps input and output state resident:
    # materially (> 25 %) more peak memory than the donating program
    assert (bad_meta["memory"]["peak_live_bytes"]
            > mem["peak_live_bytes"] * 1.25)
    del optax, analysis


class _FixtureProgram:
    """A pre-lowered stand-in driving ``audit_target`` from fixed HLO
    text: seeded-violation modules stay deterministic (a real lowering
    of a replicated spike is at XLA's mercy — the simplifier can
    algebraically remove a broadcast+reduce pair)."""

    def __init__(self, compiled_text: str, lowered_text: str = ""):
        self._compiled = compiled_text
        self._lowered = lowered_text

    def lower(self, *args):
        return _FixtureProgram(self._compiled, self._lowered)

    def compile(self):
        return self

    def as_text(self):
        # audit_target reads lowered.as_text() for the donor markers and
        # compiled.as_text() for the module; returning the compiled text
        # from both is fine for marker-free fixtures
        return self._compiled


@pytest.mark.memory_smoke
def test_seeded_replicated_fixture_exits_one(monkeypatch, devices):
    """`analyze memory` over a seeded fat-replicated-intermediate
    fixture must exit 1 (findings) through the real CLI driver."""
    from dlbb_tpu import analysis
    from dlbb_tpu.analysis.hlo_audit import AuditTarget

    seeded = AuditTarget(
        name="fixture/replicated_spike",
        build=lambda: (_FixtureProgram(_replicated_hlo()), ()),
        expectation=TargetExpectation(),
        min_devices=8,
    )
    monkeypatch.setattr(
        "dlbb_tpu.analysis.hlo_audit.default_targets", lambda: [seeded])
    assert analysis.run_analysis(which="memory",
                                 verbose=False) == EXIT_FINDINGS


# ---------------------------------------------------------------------------
# gate integration: baseline diff + observability surface
# ---------------------------------------------------------------------------


def test_diff_fails_on_memory_axis_alone(tmp_path):
    """A donation regression moves ONLY peak_live_bytes — the committed
    baseline must fail CI on the memory axis with the schedule axes
    untouched."""
    from dlbb_tpu.analysis.schedule_audit import (
        diff_baselines,
        snapshot_baselines,
    )

    base = {
        "cost_model_version": "cm1", "tier": "cpu-sim",
        "critical_path_us": 10.0, "comm_on_critical_path_us": 5.0,
        "comm_total_us": 6.0, "compute_total_us": 2.0,
        "overlap_efficiency": 0.5, "total_wire_bytes": 4096,
        "num_collectives": 4, "collective_kinds": {"all-reduce": 4},
        "peak_live_bytes": 100_000, "max_transient_bytes": 10_000,
    }
    snapshot_baselines({"t": base}, tmp_path)
    ok = diff_baselines({"t": dict(base)}, tmp_path)
    assert [f for f in ok if f.severity == "error"] == []

    regressed = dict(base, peak_live_bytes=150_000)
    findings = diff_baselines({"t": regressed}, tmp_path)
    errors = [f.rule for f in findings if f.severity == "error"]
    assert errors == ["peak-memory-regression"]

    fat_transient = dict(base, max_transient_bytes=20_000)
    findings = diff_baselines({"t": fat_transient}, tmp_path)
    errors = [f.rule for f in findings if f.severity == "error"]
    assert errors == ["transient-buffer-regression"]

    improved = dict(base, peak_live_bytes=50_000)
    findings = diff_baselines({"t": improved}, tmp_path)
    assert [f.rule for f in findings] == ["baseline-improved"]


def test_committed_baselines_carry_memory_axis():
    """Every committed per-target snapshot records the memory keys the
    diff gate needs."""
    from dlbb_tpu.analysis.schedule_audit import (
        DEFAULT_BASELINE_DIR,
        load_baselines,
    )

    baselines = load_baselines(DEFAULT_BASELINE_DIR)
    assert len(baselines) >= 30
    for name, base in baselines.items():
        assert base.get("peak_live_bytes", 0) > 0, name
        assert "max_transient_bytes" in base, name


def test_attribution_peak_bytes_column():
    """`obs attribute`'s per-phase static memory prediction: populated
    from a serving report's geometry, honest-blank otherwise."""
    from dlbb_tpu.obs.attribution import _serving_peak_bytes

    report = {
        "model": {"hidden_size": 256, "num_layers": 4, "num_heads": 8,
                  "kv_heads": 8, "dtype": "bfloat16"},
        "mesh": {"dp": 2, "tp": 4},
        "serving": {"max_batch": 8, "max_seq": 128,
                    "prefill_buckets": [16, 32, 64]},
    }
    peaks = _serving_peak_bytes(report)
    cache_dev = (2 * 4 * 8 * 128 * 8 * 32 * 2) // 8
    assert peaks["decode"] > cache_dev  # cache + sharded weights + act
    assert peaks["prefill"] > cache_dev
    # a sweep report (no serving geometry) stays honest-blank
    assert _serving_peak_bytes({}) == {}
    assert _serving_peak_bytes({"model": {"hidden_size": 256}}) == {}


def test_memory_metrics_and_artifacts(tmp_path):
    """`analyze memory --output DIR`: gauges + manifest merge without
    clobbering a co-located sweep export."""
    memory = {
        "comm/ops.py::allreduce": {"peak_live_bytes": 2048,
                                   "hbm_headroom_bytes": 4096,
                                   "max_transient_bytes": 0},
        "serve/engine.py::decode_step[dp,tp]": {
            "peak_live_bytes": 121_793, "max_transient_bytes": 12_288},
    }
    tier = get_tier("cpu-sim")
    registry = memory_metrics(memory, tier)
    text = registry.to_prometheus()
    assert ('dlbb_analysis_peak_live_bytes{target="comm/ops.py::'
            'allreduce",tier="cpu-sim"} 2048') in text
    assert "dlbb_analysis_memory_targets" in text

    # pre-existing sweep export must survive the fold
    (tmp_path / "metrics.prom").write_text(
        "# TYPE dlbb_sweep_wall_seconds gauge\n"
        "dlbb_sweep_wall_seconds 1.5\n")
    (tmp_path / "sweep_manifest.json").write_text(
        json.dumps({"schema": "dlbb_sweep_manifest_v1", "kind": "1d"}))
    write_memory_artifacts(memory, tmp_path, tier)
    prom = (tmp_path / "metrics.prom").read_text()
    assert "dlbb_sweep_wall_seconds 1.5" in prom
    assert "dlbb_analysis_peak_live_bytes" in prom
    manifest = json.loads((tmp_path / "sweep_manifest.json").read_text())
    assert manifest["kind"] == "1d"  # merged, not clobbered
    audit = manifest["memory_audit"]
    assert audit["tier"] == "cpu-sim"
    assert audit["peak_live_bytes"][
        "serve/engine.py::decode_step[dp,tp]"] == 121_793
    report = json.loads((tmp_path / "memory_audit.json").read_text())
    assert report["schema"] == "dlbb_memory_audit_v1"
