"""Multi-host code paths: mocked branch pins + one REAL 2-process run.

A TPU pod isn't available in CI (same constraint as the reference, which
tests multi-node by running N ranks on localhost under mpirun — SURVEY §4),
but the multi-process branches must not be untestable-by-accident: most
tests monkeypatch ``jax.process_count`` / ``jax.process_index`` /
``multihost_utils.process_allgather`` / ``jax.distributed.initialize`` to
drive the exact code the pod launcher would, and
``test_real_two_process_sweep`` runs the same premise as the reference's
localhost mpirun — two genuine ``jax.distributed`` processes over a TCP
coordinator (CPU backend, gloo) driving a real ``Sweep1D`` through the
gather and collective-resume branches (worker: ``multihost_worker.py``).
"""

import jax
import numpy as np
import pytest


def _fake_allgather_factory(n_hosts: int, skew: float = 1.0):
    """Emulate ``process_allgather``: every host contributes ``local``; host
    i's copy is scaled by ``skew**i`` so cross-host spread is non-zero."""

    def fake(local):
        arr = np.asarray(local)
        return np.stack([arr * (skew ** i) for i in range(n_hosts)])

    return fake


def test_gather_timings_multiprocess(monkeypatch):
    """_gather_timings' multi-process branch: one timing row per host,
    shaped like the reference's [rank][iteration] gather
    (collectives/1d/openmpi.py:270)."""
    from jax.experimental import multihost_utils

    from dlbb_tpu.bench import runner

    monkeypatch.setattr(jax, "process_count", lambda: 4)
    monkeypatch.setattr(
        multihost_utils, "process_allgather",
        _fake_allgather_factory(4, skew=1.5),
    )
    local = [0.001, 0.002, 0.003]
    rows = runner._gather_timings(local)
    assert np.asarray(rows).shape == (4, 3)
    np.testing.assert_allclose(rows[0], local)
    np.testing.assert_allclose(rows[2], np.asarray(local) * 1.5 ** 2)


def test_gather_timings_single_process():
    from dlbb_tpu.bench import runner

    assert runner._gather_timings([0.5]) == [[0.5]]


def test_e2e_cross_host_cv(monkeypatch, devices):
    """run_e2e's cross-host spread fields (run_mpi.py:199-212 analogue):
    with 2 emulated hosts at 20% skew, per_host_means_s has one entry per
    host and the CV is positive."""
    from jax.experimental import multihost_utils

    from dlbb_tpu.bench.e2e import run_e2e

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(
        multihost_utils, "process_allgather",
        _fake_allgather_factory(2, skew=1.2),
    )
    config = {
        "experiment": {"name": "mocked_multihost"},
        "model": {"hidden_size": 32, "num_layers": 2, "num_heads": 4,
                  "ffn_intermediate": 64, "attention": "simplified",
                  "dtype": "float32"},
        "parallelism": {"world_size": 2, "data_parallel": 2},
        "input": {"batch_size": 4, "sequence_length": 8, "seed": 42},
        "execution": {"warmup_iterations": 1, "benchmark_iterations": 2},
    }
    result = run_e2e(config, verbose=False)
    assert len(result["per_host_means_s"]) == 2
    assert result["per_host_means_s"][1] == pytest.approx(
        result["per_host_means_s"][0] * 1.2
    )
    assert result["cross_host_cv"] > 0
    assert result["cross_host_variance"] > 0


class _InitRecorder:
    def __init__(self):
        self.calls = []

    def __call__(self, **kw):
        self.calls.append(kw)


def test_initialize_distributed_explicit(monkeypatch):
    """Explicit coordinator args go straight to jax.distributed.initialize
    (the launch_tpu_pod.sh handshake)."""
    from dlbb_tpu.comm import mesh as mesh_mod

    rec = _InitRecorder()
    monkeypatch.setattr(jax.distributed, "initialize", rec)
    monkeypatch.setattr(jax, "process_index", lambda: 3)
    monkeypatch.setattr(jax, "process_count", lambda: 4)
    ctx = mesh_mod.initialize_distributed(
        coordinator_address="10.0.0.1:1234", num_processes=4, process_id=3
    )
    assert rec.calls == [{
        "coordinator_address": "10.0.0.1:1234",
        "num_processes": 4,
        "process_id": 3,
    }]
    assert ctx.process_id == 3
    assert ctx.num_processes == 4
    assert not ctx.is_coordinator


def test_initialize_distributed_auto(monkeypatch):
    """auto=True: argument-free initialize (TPU metadata discovery)."""
    from dlbb_tpu.comm import mesh as mesh_mod

    rec = _InitRecorder()
    monkeypatch.setattr(jax.distributed, "initialize", rec)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    monkeypatch.setattr(jax, "process_count", lambda: 16)
    ctx = mesh_mod.initialize_distributed(auto=True)
    assert rec.calls == [{}]
    assert ctx.is_coordinator
    assert ctx.num_processes == 16


def test_initialize_distributed_default_noop(monkeypatch):
    """No args: single-host no-op — the coordinator handshake must never
    run for library users on one host / the simulated mesh."""
    from dlbb_tpu.comm import mesh as mesh_mod

    rec = _InitRecorder()
    monkeypatch.setattr(jax.distributed, "initialize", rec)
    ctx = mesh_mod.initialize_distributed()
    assert rec.calls == []
    assert ctx.num_processes == 1
    assert ctx.is_coordinator


def test_real_two_process_sweep(tmp_path):
    """NON-MOCK: two real OS processes under ``jax.distributed`` (local TCP
    coordinator, CPU backend, gloo collectives) drive a tiny ``Sweep1D``
    end-to-end, exercising the ``_gather_timings`` allgather branch (the
    artifact carries one timing row per host) and the ``_resume_exists``
    collective decision with both agreeing AND disagreeing hosts — the
    branches every other test in this file can only mock.  Runs in fresh
    subprocesses because this pytest process already owns a
    single-process backend."""
    import os
    import socket
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parents[1]
    worker = repo / "tests" / "multihost_worker.py"
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    env = dict(os.environ)
    env["PYTHONPATH"] = f"{repo}:{env.get('PYTHONPATH', '')}"
    # the worker sets its own XLA_FLAGS/JAX_PLATFORMS before importing jax
    env.pop("XLA_FLAGS", None)

    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(pid), str(port),
             str(tmp_path)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker exited {p.returncode}:\n{out}"
    assert "WORKER-OK proc=0" in outs[0]
    assert "WORKER-OK proc=1" in outs[1]
