"""Replica-fleet supervisor tests (``dlbb_tpu/serve/fleet.py``).

The supervisor's routing, fencing, hedging and degradation logic is
pure host-side state over feeds/controls, so most of this file unit-
tests a :class:`FleetSupervisor` constructed directly (``__init__``
spawns no threads and builds no engines — the meshes are only counted
until ``serve()`` runs).  The ``fleet_smoke``-marked tail runs the real
2-replica fleet on the simulated 8-rank mesh: a replica kill mid-trace
must fail its residents over and still reproduce the single-engine
oracle's completed tokens exactly, and the artifact family
(``fleet_*.json`` + manifest + journal + metrics.prom) must carry the
fleet columns the reports aggregate.  ``scripts/run_static_analysis.sh``
invokes the marked subset standalone.
"""

import ast
import json
from pathlib import Path
from types import SimpleNamespace

import pytest

from dlbb_tpu.comm.mesh import fault_domain_record, partition_devices
from dlbb_tpu.models.configs import ModelConfig
from dlbb_tpu.resilience import inject
from dlbb_tpu.serve.engine import ServingConfig
from dlbb_tpu.serve.fleet import (DEGRADE_LEVELS, FleetConfig,
                                  FleetSupervisor, ReplicaControl,
                                  ReplicaKilled, RequestFeed, _StartGate,
                                  run_fleet, validate_fleet)
from dlbb_tpu.serve.traffic import Request, generate_trace

MODEL = ModelConfig.from_dict(dict(
    hidden_size=64, num_layers=2, num_heads=4, num_kv_heads=4,
    ffn_intermediate=128, dtype="float32", attention="full"))
SERVING = ServingConfig.from_dict(dict(
    max_batch=8, block_size=8, max_seq=64, queue_capacity=64,
    hbm_budget_gb=None))

SMOKE_CONFIG = {
    "experiment": {"name": "fleet_smoke"},
    "model": dict(hidden_size=64, num_layers=2, num_heads=4,
                  num_kv_heads=4, ffn_intermediate=128, dtype="float32",
                  attention="full"),
    # per-replica plan: 2 replicas x (dp=2 x tp=2) on the 8 sim devices
    "parallelism": {"data_parallel": 2, "world_size": 2},
    "serving": dict(max_batch=8, block_size=8, max_seq=64,
                    queue_capacity=64, hbm_budget_gb=None),
    "fleet": {"replicas": 2},
}


class _Journal:
    """Captures journal lines like SweepJournal.event would."""

    def __init__(self):
        self.events = []

    def event(self, event, config=None, **extra):
        self.events.append({"event": event, "config": config, **extra})

    def of(self, kind):
        return [e for e in self.events if e["event"] == kind]


def _sup(replicas=2, journal=None, serving=SERVING, **fleet_kw):
    return FleetSupervisor(
        MODEL, serving, FleetConfig(replicas=replicas, **fleet_kw),
        meshes=[object()] * replicas, journal=journal)


def _req(rid, prompt=8, out=4, deadline=None, prefix_seed=None,
         prefix_len=None):
    return Request(rid=rid, arrival_s=0.0, prompt_len=prompt,
                   output_len=out, seed=100 + rid, deadline_s=deadline,
                   prefix_seed=prefix_seed, prefix_len=prefix_len)


# ---------------------------------------------------------------- feed


def test_request_feed_semantics():
    feed = RequestFeed()
    assert bool(feed)            # open-but-empty: more work may come
    assert len(feed) == 0
    assert feed[0].arrival_s > 1e11 and feed[0].rid == -1  # horizon
    a, b, c = _req(0), _req(1), _req(2)
    feed.push(a)
    feed.push(b)
    feed.push_front(c)           # failover re-admission jumps the line
    assert [r.rid for r in feed] == [2, 0, 1]
    assert feed[0].rid == 2
    with pytest.raises(IndexError):
        feed[1]                  # feeds only expose the head
    assert feed.discard(0) and not feed.discard(99)
    assert feed.popleft().rid == 2
    feed.close()
    with pytest.raises(RuntimeError):
        feed.push(_req(3))
    with pytest.raises(RuntimeError):
        feed.push_front(_req(3))
    assert feed.popleft().rid == 1
    assert not feed              # drained AND closed -> loop exits
    with pytest.raises(IndexError):
        feed[0]


def test_replica_control_heartbeat_and_kill():
    ctl = ReplicaControl(0, _StartGate(0.05))
    assert ctl.beat_ema is None
    ctl.beat()
    ctl.beat()
    assert ctl.started and ctl.beats == 2 and ctl.beat_ema is not None
    ctl.check()                  # no kill flag, no active plan: no-op
    ctl.cancel(7, "hedge-lost")
    assert ctl.take_cancels() == [(7, "hedge-lost")]
    assert ctl.take_cancels() == []
    ctl.request_kill("replica-hung")
    ctl.request_kill("second-reason-ignored")
    assert ctl.kill_reason == "replica-hung"
    with pytest.raises(ReplicaKilled, match="replica-hung"):
        ctl.check()              # fenced replica can never dispatch again


# -------------------------------------------------------------- config


def test_fleet_config_roundtrip_and_unknown_key():
    cfg = FleetConfig.from_dict({"replicas": 3, "tick_s": 0.01})
    assert cfg.replicas == 3 and cfg.tick_s == 0.01
    assert FleetConfig.from_dict(cfg.to_dict()).to_dict() == cfg.to_dict()
    with pytest.raises(ValueError, match="max_replicas"):
        FleetConfig.from_dict({"max_replicas": 3})


@pytest.mark.parametrize("bad", [
    {"replicas": 0},
    {"heartbeat_factor": 0.5},
    {"heartbeat_min_s": 0.0},
    {"stall_timeout_s": -1.0},
    {"degrade_high_water": 0.0},
    {"tick_s": 0.0},
    {"hedge_min_completions": 0},
])
def test_fleet_config_validate_rejects(bad):
    with pytest.raises(ValueError):
        FleetConfig.from_dict(bad).validate()


def test_validate_fleet_admission_ladder():
    cfg = {"parallelism": {"data_parallel": 2, "world_size": 2}}
    assert validate_fleet(cfg, MODEL, SERVING, FleetConfig(2), 8) == (2, 2)
    # rung 1: fleet knobs
    with pytest.raises(ValueError, match="replicas"):
        validate_fleet(cfg, MODEL, SERVING, FleetConfig(0), 8)
    # non-(dp, tp) axes rejected before any partitioning
    with pytest.raises(ValueError, match="pipeline_parallel"):
        validate_fleet({"parallelism": {"pipeline_parallel": 2}},
                       MODEL, SERVING, FleetConfig(2), 8)
    # rung 2: lopsided fleet
    with pytest.raises(ValueError, match="equal failure domains"):
        validate_fleet(cfg, MODEL, SERVING, FleetConfig(3), 8)
    # rung 3: per-replica plan outgrows its domain
    with pytest.raises(ValueError, match="failure"):
        validate_fleet({"parallelism": {"data_parallel": 2,
                                        "world_size": 4}},
                       MODEL, SERVING, FleetConfig(2), 8)
    # rung 4: per-replica serving envelope (each replica carries its
    # OWN full KV planes, so the HBM budget is checked per domain)
    tight = ServingConfig.from_dict(dict(
        max_batch=8, block_size=8, max_seq=64, queue_capacity=64,
        hbm_budget_gb=1e-9))
    with pytest.raises(ValueError):
        validate_fleet(cfg, MODEL, tight, FleetConfig(2), 8)


def test_partition_devices_and_fault_domains():
    devs = [SimpleNamespace(id=i) for i in range(8)]
    groups = partition_devices(devs, 2)
    assert [[d.id for d in g] for g in groups] == [[0, 1, 2, 3],
                                                   [4, 5, 6, 7]]
    with pytest.raises(ValueError, match="partition"):
        partition_devices(devs, 3)
    with pytest.raises(ValueError):
        partition_devices(devs, 0)
    rec = fault_domain_record(groups)
    assert rec == {"0": [0, 1, 2, 3], "1": [4, 5, 6, 7]}
    assert json.loads(json.dumps(rec)) == rec  # manifest-serialisable


# ------------------------------------------------------------- routing


@pytest.mark.fleet_smoke
def test_routing_deterministic_least_loaded():
    reqs = [_req(i) for i in range(6)]

    def route_all():
        sup = _sup()
        for r in reqs:
            sup._route(r)
        return dict(sup._assign), list(sup._routed_count), sup

    a1, c1, sup = route_all()
    a2, c2, _ = route_all()
    assert a1 == a2 and c1 == c2  # same trace -> same routing table
    # equal-size requests alternate: least-loaded, ties to the lower id
    assert [a1[i] for i in range(6)] == [0, 1, 0, 1, 0, 1]
    assert c1 == [3, 3]
    assert [r.rid for r in sup.feeds[0]] == [0, 2, 4]
    assert sup._blocks[0] == sum(
        sup._blocks_for(r) for r in reqs if a1[r.rid] == 0)


@pytest.mark.fleet_smoke
def test_prefix_affinity_colocates_groups():
    sup = _sup()
    # two shared-prefix populations, interleaved arrivals
    reqs = [_req(i, prefix_seed=7 if i % 2 == 0 else 9, prefix_len=4)
            for i in range(8)]
    for r in reqs:
        sup._route(r)
    homes = {seed: {sup._assign[r.rid] for r in reqs
                    if r.prefix_seed == seed} for seed in (7, 9)}
    assert all(len(h) == 1 for h in homes.values())  # group -> ONE home
    # first member of each group misses (homes the prefix), rest hit
    assert sup._affinity_misses == 2
    assert sup._affinity_hits == 6
    # a plain trace never touches the affinity counters
    plain = _sup()
    for i in range(8):
        plain._route(_req(i))
    assert plain._affinity_hits == 0 and plain._affinity_misses == 0
    # fencing the home purges its affinity: the group re-homes on the
    # survivor instead of chasing a dead replica
    home = next(iter(homes[7]))
    sup._fence(home, "replica-killed")
    sup._route(_req(100, prefix_seed=7, prefix_len=4))
    assert sup._assign[100] != home
    assert sup._affinity[(7, 4)] == sup._assign[100]


def test_route_fails_closed_with_no_replicas():
    sup = _sup(replicas=1, journal=(j := _Journal()))
    sup._fence(0, "replica-crashed")
    sup._route(_req(0))
    assert sup._terminal[0] == "failed[no-replica]"
    assert j.of("request-failed")[0]["reason"] == "no-replica"


# ------------------------------------------------------------ failover


@pytest.mark.fleet_smoke
def test_failover_preserves_request_and_deadline():
    j = _Journal()
    sup = _sup(journal=j)
    reqs = [_req(i, deadline=2.5 + i) for i in range(4)]
    for r in reqs:
        sup._route(r)
    dead = [r for r in reqs if sup._assign[r.rid] == 0]
    survivors_before = [r.rid for r in sup.feeds[1]]
    sup._fence(0, "replica-killed", chain={"error": "ReplicaKilled: x"})

    assert sup._fenced[0] and sup._fence_reason[0] == "replica-killed"
    assert sup.feeds[0].closed
    assert sup.controls[0].kill_reason == "replica-killed"
    # residents moved to the survivor's feed HEAD, ahead of its own
    # queue (they already served their wait on the dead replica) — the
    # SAME Request objects, so arrival_s/deadline_s accounting is
    # untouched by the move
    moved = list(sup.feeds[1])[:len(dead)]
    assert {r.rid for r in moved} == {r.rid for r in dead}
    assert all(any(m is r for r in dead) for m in moved)
    assert [r.rid for r in sup.feeds[1]][len(dead):] == survivors_before
    assert all(sup._assign[r.rid] == 1 for r in dead)
    assert sup._failover_rids == {r.rid for r in dead}
    assert int(sup._failover_counter["replica-killed"]) == len(dead)
    # block estimates migrated, none leaked on the fenced side
    assert sup._blocks[0] == 0
    assert sup._blocks[1] == sum(sup._blocks_for(r) for r in reqs)
    # journal: fence + one failover line per moved request, with the
    # fence reason AND the original error chain on every line
    assert j.of("replica-fenced")[0]["reason"] == "replica-killed"
    fo = j.of("request-failover")
    assert {e["config"] for e in fo} == {f"request-{r.rid}" for r in dead}
    assert all(e["from_replica"] == 0 and e["to_replica"] == 1
               and e["reason"] == "replica-killed"
               and "error" in e for e in fo)
    # fencing is idempotent: a second fence must not re-route
    sup._fence(0, "replica-killed")
    assert len(sup._failover_log) == len(dead)


def test_failover_torn_rolls_back_and_retries():
    j = _Journal()
    sup = _sup(journal=j)
    reqs = [_req(i) for i in range(4)]
    for r in reqs:
        sup._route(r)
    with inject.plan_scope("serve-failover-torn:1"):
        sup._fence(0, "replica-killed")
    torn = j.of("failover-torn")
    assert len(torn) == 1 and torn[0]["attempt"] == 1
    # the retry committed exactly once: no double-routed request, no
    # leaked block estimate from the rolled-back attempt
    rids = [r.rid for r in sup.feeds[1]]
    assert sorted(rids) == [0, 1, 2, 3] and len(set(rids)) == 4
    assert sup._blocks[1] == sum(sup._blocks_for(r) for r in reqs)
    assert len(sup._failover_log) == 2
    assert len({e["rid"] for e in sup._failover_log}) == 2


def test_failover_orphans_fail_closed():
    # nowhere to fail over to: residents fail terminally, never hang
    j = _Journal()
    sup = _sup(replicas=1, journal=j)
    sup._route(_req(0, deadline=1.0))
    sup._fence(0, "replica-hung")
    assert sup._terminal[0] == "failed[replica-lost]"
    assert j.of("request-failed")[0]["reason"] == "replica-lost"
    assert len(sup._failover_log) == 0


# -------------------------------------------------------------- hedging


def test_hedge_resolution_first_completion_wins():
    sup = _sup()
    sup._route(_req(0, out=4))
    assert sup._assign[0] == 0
    sup._hedged[0] = 1
    # hedge copy (replica 1) completes first -> hedge WON, primary
    # copy cancelled
    sup._handle_event(1, 0, "request-completed",
                      {"latency_s": 0.2, "tokens": [5, 6, 7, 8]})
    assert sup._terminal[0] == "completed"
    assert sup._completed_by[0] == 1
    assert sup._tokens[0] == [5, 6, 7, 8]
    assert int(sup._hedge_counter["won"]) == 1
    assert sup.controls[0].take_cancels() == [(0, "hedge-lost")]
    # the loser's cancel arriving later must NOT overwrite the win
    sup._handle_event(0, 0, "request-canceled", {"reason": "hedge-lost"})
    assert sup._terminal[0] == "completed"
    # primary-wins mirror: loser is the hedge replica
    sup2 = _sup()
    sup2._route(_req(1))
    sup2._hedged[1] = 1
    sup2._handle_event(0, 1, "request-completed",
                       {"latency_s": 0.1, "tokens": [1]})
    assert int(sup2._hedge_counter["lost"]) == 1
    assert sup2.controls[1].take_cancels() == [(1, "hedge-lost")]


# ------------------------------------------------------------- ladder


@pytest.mark.fleet_smoke
def test_degrade_ladder_monotonic_and_journaled():
    j = _Journal()
    sup = _sup(journal=j)
    assert sup._level == 0 and DEGRADE_LEVELS[0] == "full"
    sup.degrade_to(2, "test overload")
    assert sup._level == 2
    # every level ENTERED is applied, journaled and counted — a jump
    # from 0 to 2 walks through 1
    assert [e["name"] for e in j.of("degrade-transition")] == [
        "no-speculation", "short-horizon"]
    assert [rec["level"] for rec in sup._degrade_log] == [1, 2]
    assert all(not c.spec_enabled for c in sup.controls)
    assert all(c.horizon_cap == 1 for c in sup.controls)
    assert int(sup._degrade_counter["no-speculation"]) == 1
    assert int(sup._degrade_counter["short-horizon"]) == 1
    # monotonic: the fleet never silently recovers a service class
    sup.degrade_to(1, "ignored")
    sup.degrade_to(2, "ignored")
    assert sup._level == 2 and len(sup._degrade_log) == 2
    with pytest.raises(ValueError, match="out of range"):
        sup.degrade_to(len(DEGRADE_LEVELS), "past the ladder")
    # level 3 sheds best-effort arrivals at the door, keeps SLO traffic
    sup.degrade_to(3, "capacity lost")
    sup._route(_req(50))                       # no deadline -> shed
    sup._route(_req(51, deadline=2.0))         # SLO class -> served
    assert sup._terminal[50] == "rejected[degraded-shed]"
    assert sup._shed == 1
    assert 51 in sup._assign and 51 not in sup._terminal
    shed = [e for e in j.of("request-rejected")
            if e["reason"] == "degraded-shed"]
    assert shed and shed[0]["config"] == "request-50"


# ------------------------------------------------- zero-injection pin


@pytest.mark.fleet_smoke
def test_fleet_is_host_side_only():
    """The PR-11 zero-injection pin, extended one level up: fleet.py
    must never build a device program AT ALL (no jax import, no
    jit/shard_map/pallas), so every ``inject.fire`` site it adds —
    replica kill/hang in ``ReplicaControl.check``, failover-torn in
    ``_fence`` — is host-side by construction and the jitted
    prefill/decode programs stay byte-identical with or without a
    fleet.  ``tests/test_serve_resilience.py`` pins the engine's device
    functions themselves."""
    import dlbb_tpu.serve.fleet as fleet_mod

    src = Path(fleet_mod.__file__).read_text()
    tree = ast.parse(src)
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            assert not any(a.name == "jax" or a.name.startswith("jax.")
                           for a in node.names), \
                "fleet.py must stay host-side (imports jax)"
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            assert not (mod == "jax" or mod.startswith("jax.")), \
                f"fleet.py must stay host-side (from {mod} import ...)"
        name = (node.id if isinstance(node, ast.Name)
                else node.attr if isinstance(node, ast.Attribute) else None)
        assert name not in ("jit", "pjit", "shard_map", "pallas_call"), \
            f"device-program builder {name!r} found in fleet.py"


# ------------------------------------------------- engine integration


@pytest.fixture(scope="module")
def kill_run(tmp_path_factory, devices):
    """ONE oracle + ONE killed fleet run shared by the engine-backed
    smokes below (each fleet run compiles two replicas — sharing keeps
    the tier-1 budget honest)."""
    from dlbb_tpu.serve.bench import run_serving

    trace = generate_trace("poisson", 16, seed=5, rate=60.0,
                           prompt_range=(4, 12), output_range=(4, 8))
    out = tmp_path_factory.mktemp("fleet")
    single = {k: v for k, v in SMOKE_CONFIG.items() if k != "fleet"}
    oracle = run_serving(single, trace, verbose=False,
                         devices=devices[:4], journal=False,
                         capture_tokens=True)
    rep = run_fleet(SMOKE_CONFIG, trace, output_dir=str(out),
                    verbose=False, journal=True,
                    fault_plan="serve-replica-kill:@8",
                    capture_tokens=True)
    return oracle, rep, out


@pytest.mark.fleet_smoke
def test_fleet_smoke_kill_failover_token_identity(kill_run):
    """The headline contract: kill a replica mid-trace; every request
    still completes, failed-over requests re-prefill on the survivor,
    and the completed tokens are byte-identical to an unfaulted
    single-engine run (greedy decode depends only on (params seed,
    request), and every replica initialises from the same seed)."""
    oracle, rep, _ = kill_run

    fenced = [r for r in rep["replicas"]
              if r["fence_reason"] == "replica-killed"]
    assert len(fenced) == 1, rep["replicas"]
    outcomes = rep["requests"]["outcomes"]
    assert all(v == "completed" for v in outcomes.values()), outcomes
    assert rep["failovers"]["total"] >= 1
    assert all(r["reason"] == "replica-killed"
               for r in rep["failovers"]["requests"])
    assert rep["failover_ttft_penalty_s"] is not None
    assert rep["completed_tokens"] == oracle["completed_tokens"]
    # the survivor drained clean: nothing the failovers attached leaked
    ok = [r for r in rep["replicas"] if r["status"] == "ok"]
    assert ok and ok[0]["report"]["cache"]["blocks_reserved"] == 0


@pytest.mark.fleet_smoke
def test_fleet_smoke_artifact_family(kill_run):
    """The fleet run writes the full serving artifact family with the
    fleet markers the reports key on: fleet_<name>.json (schema
    dlbb_fleet_report_v1), a manifest with kind=fleet + fault_domains,
    the shared journal with per-replica tracks + the failover record,
    and metrics.prom with the failover/hedge/degrade counter
    families."""
    _, rep, out = kill_run
    assert rep["schema"] == "dlbb_fleet_report_v1"
    assert set(rep["fleet"]["fault_domains"]) == {"0", "1"}
    assert all(len(v) == 4 for v in rep["fleet"]["fault_domains"].values())
    assert rep["topology"]["fault_domains"] == rep["fleet"]["fault_domains"]

    art = json.loads((out / "fleet_fleet_smoke.json").read_text())
    assert art["schema"] == "dlbb_fleet_report_v1"
    manifest = json.loads((out / "serving_manifest.json").read_text())
    assert manifest["kind"] == "fleet"
    assert manifest["fault_domains"] == rep["fleet"]["fault_domains"]
    assert manifest["failovers"] == rep["failovers"]["total"] >= 1
    assert manifest["degrade_level"] == rep["degrade"]["level"]

    prom = (out / "metrics.prom").read_text()
    for family in ("serve_failovers_total", "serve_hedges_total",
                   "serve_degrade_transitions_total",
                   "serve_replica_resident_requests",
                   "serve_fleet_live_replicas"):
        assert family in prom, f"{family} missing from metrics.prom"
    assert 'serve_failovers_total{reason="replica-killed"}' in prom

    lines = [json.loads(ln) for ln in
             (out / "sweep_journal.jsonl").read_text().splitlines()]
    ups = [e for e in lines if e.get("event") == "replica-up"]
    assert {e["replica"] for e in ups} == {0, 1}
    fenced = [e for e in lines if e.get("event") == "replica-fenced"]
    assert fenced and fenced[0]["reason"] == "replica-killed"
    fo = [e for e in lines if e.get("event") == "request-failover"]
    assert len(fo) == rep["failovers"]["total"]
