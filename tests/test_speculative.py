"""Speculative decoding tests (``docs/serving.md``, "Speculative
decoding"): draft-and-verify multi-token decode on the serving engine.

The load-bearing contract is TOKEN IDENTITY: greedy speculative decode
(n-gram or draft-model drafter, per-step or fused, adaptive or fixed γ)
must produce completed-token sequences IDENTICAL to the per-step greedy
token-feedback engine on the same trace — speculation buys forwards,
never different results.  Sampled decode weakens the gate to
DISTRIBUTION identity, which the residual-sampling helpers pin
empirically here.  On top of that: the scheduler edges speculation
makes reachable (mid-verify completion, cold-drafter fallback,
rejection rollback leaving the ledger clean, dispatch failure during a
verify unit), the drafter's pure-function determinism, the validation
ladder, and the report/metrics/journal surfaces.
"""

import jax
import numpy as np
import pytest

from dlbb_tpu.comm.mesh import build_parallelism_mesh
from dlbb_tpu.models.configs import ModelConfig
from dlbb_tpu.resilience import inject
from dlbb_tpu.serve.engine import (
    ServingConfig,
    ServingEngine,
    _ngram_propose,
    residual_distribution,
    speculative_sample,
)
from dlbb_tpu.serve.traffic import Request, TrafficTrace, generate_trace

TINY = dict(hidden_size=64, num_layers=2, num_heads=4,
            ffn_intermediate=128, dtype="float32", attention="full")
MODEL = ModelConfig(**TINY)
SERVE = dict(max_batch=8, block_size=8, max_seq=96, hbm_budget_gb=None)


def _trace(reqs):
    return TrafficTrace(kind="poisson", seed=0, params={},
                        requests=tuple(reqs))


def _spec_trace(n=10, seed=7, out=(40, 56)):
    """The repeating-structure mini-trace: motif prompts (period 4)
    warm the n-gram drafter from the first decode, and the outputs are
    long enough for greedy-feedback cycles to form mid-sequence."""
    return generate_trace("poisson", n, seed=seed, rate=500.0,
                          prompt_range=(8, 16), output_range=out,
                          prompt_period=4)


@pytest.fixture(scope="module")
def oracle_engine(mesh2x4):
    """Per-step greedy token feedback, no drafting — the identity
    oracle every speculative configuration is gated against."""
    return ServingEngine(
        MODEL, ServingConfig(**SERVE, speculation="greedy"), mesh2x4,
        verbose=False, capture_tokens=True)


def _engine(mesh, **extra):
    return ServingEngine(MODEL, ServingConfig(**SERVE, **extra), mesh,
                         verbose=False, capture_tokens=True)


# ---------------------------------------------------------------------------
# config surface
# ---------------------------------------------------------------------------


def test_spec_config_validation_ladder():
    with pytest.raises(ValueError, match="speculation"):
        ServingConfig(**SERVE, speculation="turbo").validate(MODEL)
    # a drafter with no draft budget is a silent no-op trap
    with pytest.raises(ValueError, match="spec_gamma"):
        ServingConfig(**SERVE, speculation="ngram").validate(MODEL)
    # γ without a drafter: no verify step would ever run
    with pytest.raises(ValueError, match="drafting"):
        ServingConfig(**SERVE, spec_gamma=4).validate(MODEL)
    with pytest.raises(ValueError, match="drafting"):
        ServingConfig(**SERVE, speculation="greedy",
                      spec_gamma=4).validate(MODEL)
    with pytest.raises(ValueError, match="exceed"):
        ServingConfig(**SERVE, speculation="ngram",
                      spec_gamma=96).validate(MODEL)
    with pytest.raises(ValueError, match="spec_adaptive"):
        ServingConfig(**SERVE, spec_adaptive=True).validate(MODEL)
    # token-feedback modes and float-plane compaction are exclusive
    with pytest.raises(ValueError, match="compact"):
        ServingConfig(**SERVE, speculation="ngram", spec_gamma=4,
                      decode_horizon=16,
                      compact_threshold=0.5).validate(MODEL)
    with pytest.raises(ValueError, match="spec_draft_layers"):
        ServingConfig(**SERVE, speculation="draft-model", spec_gamma=4,
                      spec_draft_layers=0).validate(MODEL)


def test_ngram_propose_pure_and_cyclic():
    """The drafter is a pure, deterministic function of the history;
    a trailing match at distance d extends CYCLICALLY (the history is
    locally d-periodic), and a cold history proposes nothing."""
    hist = [1, 2, 5, 6, 7, 5, 6, 7]
    got = _ngram_propose(hist, gamma=5)
    # trailing 3-gram [5,6,7] matched 3 back -> period-3 extension
    assert got == [5, 6, 7, 5, 6]
    assert _ngram_propose(list(hist), gamma=5) == got  # deterministic
    # cold: the last token never occurred before
    assert _ngram_propose([1, 2, 3], gamma=4) is None
    # exact continuation when the match is far enough back
    assert _ngram_propose([9, 4, 4, 8, 9, 4], gamma=2) == [4, 8]


# ---------------------------------------------------------------------------
# token identity: every speculative configuration == the greedy oracle
# ---------------------------------------------------------------------------


@pytest.mark.spec_smoke
def test_ngram_fused_matches_oracle(oracle_engine, mesh2x4):
    """The CI gate: n-gram drafting on the fused-scan fast path serves
    the seeded mini-trace token-identical to the per-step greedy
    engine, with real verify traffic and nonzero acceptance."""
    trace = _spec_trace()
    base = oracle_engine.run_trace(trace)
    spec = _engine(mesh2x4, speculation="ngram", spec_gamma=4,
                   decode_horizon=16).run_trace(trace)
    assert base["requests"]["completed"] == len(trace)
    assert spec["requests"]["completed"] == len(trace)
    assert spec["completed_tokens"] == base["completed_tokens"]
    s = spec["speculation"]
    assert s["mode"] == "ngram" and s["gamma"] == 4
    assert s["verify_units"] > 0
    assert s["proposed_tokens"] >= s["accepted_tokens"] > 0
    assert 0.0 < s["acceptance_rate"] <= 1.0
    # accepted draft tokens shrank the dispatch count below one-per-token
    assert spec["decode_units"] < spec["decode_steps"]
    # rollback left the ledger clean
    assert spec["cache"]["blocks_reserved"] == 0


@pytest.mark.spec_smoke
def test_draft_model_matches_oracle(oracle_engine, mesh2x4):
    """Model drafting: a 1-layer draft transformer on the SAME mesh
    with its own KV plane stays token-identical to the oracle (the
    verify step re-derives every committed token from the target)."""
    trace = _spec_trace(n=6, out=(24, 32))
    base = oracle_engine.run_trace(trace)
    spec = _engine(mesh2x4, speculation="draft-model", spec_gamma=4,
                   spec_draft_layers=1).run_trace(trace)
    assert spec["completed_tokens"] == base["completed_tokens"]
    assert spec["speculation"]["verify_units"] > 0
    assert spec["cache"]["blocks_reserved"] == 0


def test_greedy_fused_and_ngram_per_step_match_oracle(oracle_engine,
                                                      mesh2x4):
    """The two remaining grid corners: greedy token feedback through
    the fused scan (no drafting), and n-gram drafting on the per-step
    engine, each token-identical to the per-step greedy oracle."""
    trace = _spec_trace(n=8)
    base = oracle_engine.run_trace(trace)
    fused = _engine(mesh2x4, speculation="greedy",
                    decode_horizon=16).run_trace(trace)
    assert fused["completed_tokens"] == base["completed_tokens"]
    assert fused["fast_path"]["fused_scans"] > 0
    perstep = _engine(mesh2x4, speculation="ngram",
                      spec_gamma=8).run_trace(trace)
    assert perstep["completed_tokens"] == base["completed_tokens"]
    assert perstep["speculation"]["verify_units"] > 0


@pytest.mark.parametrize("variant", ["tp2_gqa", "bf16"])
def test_identity_across_model_variants(variant, mesh2x4):
    """Token identity is a property of the acceptance rule, not the
    sharding or dtype: a (tp)-only GQA mesh (grouped cache reads,
    kv-head shard) and a bf16 (dp, tp) model each stay identical to
    THEIR per-step greedy oracle — same weights, same mesh — under
    n-gram drafting on the fused scan.  bf16 needs no tolerance: the
    verify step commits via argmax over the same table, and the oracle
    runs the same quantised feedback."""
    if variant == "tp2_gqa":
        cfg = ModelConfig(**{**TINY, "num_kv_heads": 2})
        mesh = build_parallelism_mesh(tensor_parallel=2,
                                      devices=jax.devices()[:2])
    else:
        cfg = ModelConfig(**{**TINY, "dtype": "bfloat16"})
        mesh = mesh2x4
    trace = _spec_trace(n=6, out=(24, 32))
    base = ServingEngine(
        cfg, ServingConfig(**SERVE, speculation="greedy"), mesh,
        verbose=False, capture_tokens=True).run_trace(trace)
    spec = ServingEngine(
        cfg, ServingConfig(**SERVE, speculation="ngram", spec_gamma=4,
                           decode_horizon=16), mesh,
        verbose=False, capture_tokens=True).run_trace(trace)
    assert spec["completed_tokens"] == base["completed_tokens"]
    assert spec["speculation"]["verify_units"] > 0
    # rejection rollback left the ledger in the never-drafted state
    for key in ("total_blocks", "blocks_reserved", "blocks_in_use"):
        assert spec["cache"][key] == base["cache"][key]


def test_adaptive_gamma_matches_oracle(oracle_engine, mesh2x4):
    """Per-request adaptive γ (the EMA ladder backoff) changes which
    verify widths run, never which tokens commit."""
    trace = _spec_trace(n=8)
    base = oracle_engine.run_trace(trace)
    spec = _engine(mesh2x4, speculation="ngram", spec_gamma=8,
                   spec_adaptive=True,
                   decode_horizon=16).run_trace(trace)
    assert spec["completed_tokens"] == base["completed_tokens"]
    assert spec["speculation"]["adaptive"] is True
    assert spec["speculation"]["verify_units"] > 0


# ---------------------------------------------------------------------------
# scheduler edges speculation makes reachable
# ---------------------------------------------------------------------------


def test_mid_verify_completion_clamps_commits(oracle_engine, mesh2x4):
    """A request whose remaining budget is smaller than γ completes
    mid-verify: commits clamp to remaining, the slot frees, and no
    token past output_len ever lands."""
    engine = _engine(mesh2x4, speculation="ngram", spec_gamma=8)
    trace = _trace([
        # period-4 prompt: drafter warm from the first decode, so the
        # very first verify unit overshoots rid 0's 3-token budget
        Request(rid=0, arrival_s=0.0, prompt_len=8, output_len=3,
                seed=11, prompt_period=4),
        Request(rid=1, arrival_s=0.0, prompt_len=8, output_len=24,
                seed=12, prompt_period=4),
    ])
    report = engine.run_trace(trace)
    base = oracle_engine.run_trace(trace)
    assert report["completed_tokens"] == base["completed_tokens"]
    assert len(report["completed_tokens"]["0"]) == 3
    assert len(report["completed_tokens"]["1"]) == 24
    assert report["requests"]["completed"] == 2
    assert report["cache"]["blocks_reserved"] == 0


def test_cold_drafter_falls_back_to_plain_decode(oracle_engine,
                                                 mesh2x4):
    """Random prompts (no period) leave the n-gram drafter cold at
    admission: those slots dispatch plain decode units (counted as
    fallbacks) until history warms, and identity still holds."""
    engine = _engine(mesh2x4, speculation="ngram", spec_gamma=4)
    trace = generate_trace("poisson", 6, seed=13, rate=500.0,
                           prompt_range=(4, 8), output_range=(30, 40))
    report = engine.run_trace(trace)
    base = oracle_engine.run_trace(trace)
    assert report["completed_tokens"] == base["completed_tokens"]
    assert report["speculation"]["fallback_units"] > 0


def test_decode_fail_during_verify_retries_cleanly(oracle_engine,
                                                   mesh2x4):
    """serve-decode-fail firing at the verify dispatch site: the host
    rollback (ledger snapshot + slot lengths) replays the unit and the
    completed tokens stay identical to an un-faulted oracle run."""
    engine = _engine(mesh2x4, speculation="ngram", spec_gamma=4,
                     decode_horizon=16)
    trace = _spec_trace(n=6, out=(24, 32))
    with inject.plan_scope("serve-decode-fail:1"):
        report = engine.run_trace(trace)
    base = oracle_engine.run_trace(trace)
    assert report["resilience"]["retries"] >= 1
    assert report["requests"]["completed"] == len(trace)
    assert report["completed_tokens"] == base["completed_tokens"]
    assert report["speculation"]["verify_units"] > 0
    assert report["cache"]["blocks_reserved"] == 0


# ---------------------------------------------------------------------------
# sampled decode: distribution identity
# ---------------------------------------------------------------------------


def test_residual_distribution_degenerates_to_p():
    p = np.array([0.5, 0.3, 0.2])
    # q dominates p everywhere -> rejection has zero probability and
    # the residual is defined as p itself
    assert np.allclose(residual_distribution(p, np.ones(3)), p)
    r = residual_distribution(p, np.array([0.1, 0.6, 0.3]))
    assert np.isclose(r.sum(), 1.0)
    assert r[1] == 0.0 and r[2] == 0.0 and r[0] == 1.0


def test_speculative_sample_distribution_identity():
    """The Leviathan accept/residual composite law equals the target
    distribution exactly — sampled speculative decode is
    DISTRIBUTION-identical to the sequential sampler (the documented
    weakening of the greedy token-identity gate)."""
    rng = np.random.default_rng(0)
    p = np.array([0.45, 0.35, 0.15, 0.05])
    q = np.array([0.10, 0.60, 0.20, 0.10])
    n = 20000
    counts = np.zeros(4)
    for _ in range(n):
        draft = rng.choice(4, p=q)
        tok, _accepted = speculative_sample(p, q, draft, rng)
        counts[tok] += 1
    emp = counts / n
    # 4 sigma of a binomial at n=20k is ~1.4e-2 on the largest cell
    assert np.abs(emp - p).max() < 0.015


# ---------------------------------------------------------------------------
# observability: journal events, metrics export, report writers
# ---------------------------------------------------------------------------


@pytest.mark.spec_smoke
def test_spec_verify_journal_events_and_metrics(mesh2x4, tmp_path):
    """Every verify unit journals one ``spec-verify`` event per slot
    (gamma/accepted/committed), the journal replays un-torn, and the
    prometheus export carries the speculation counters."""
    from dlbb_tpu.obs import spans
    from dlbb_tpu.obs.export import serving_metrics
    from dlbb_tpu.resilience.journal import SweepJournal, read_journal

    engine = _engine(mesh2x4, speculation="ngram", spec_gamma=4,
                     decode_horizon=16)
    journal = SweepJournal(tmp_path, meta={"mode": "serve"},
                           sink=spans.journal_sink)
    engine.journal = journal
    try:
        report = engine.run_trace(_spec_trace(n=6, out=(24, 32)))
    finally:
        engine.journal = None
        journal.close()
    events, torn = read_journal(tmp_path)
    assert torn == 0
    verifies = [e for e in events if e["event"] == "spec-verify"]
    assert len(verifies) > 0
    for e in verifies:
        assert 1 <= e["gamma"] <= 4
        assert 0 <= e["accepted"] <= e["gamma"]
        assert 1 <= e["committed"] <= e["gamma"] + 1
    registry = serving_metrics(report, engine.registry)
    prom = registry.to_prometheus()
    assert "serve_spec_proposed_total" in prom
    assert "serve_spec_accepted_total" in prom
    assert "serve_spec_acceptance_ema" in prom
    s = report["speculation"]
    assert registry.get("serve_spec_proposed_total",
                        drafter="ngram") == s["proposed_tokens"]
    assert registry.get("serve_spec_accepted_total",
                        drafter="ngram") == s["accepted_tokens"]


def test_serving_report_spec_columns(tmp_path):
    from dlbb_tpu.stats.serving_report import write_serving_report
    from dlbb_tpu.utils.config import save_json

    fake = {
        "schema": "dlbb_serving_report_v1",
        "trace": {"kind": "poisson", "num_requests": 4},
        "requests": {"arrived": 4, "completed": 4, "rejected": 0,
                     "shed_rate": 0.0, "rejected_detail": []},
        "mesh": {"dp": 2, "tp": 4},
        "serving": {"max_batch": 8, "block_size": 8, "max_seq": 96},
        "speculation": {"mode": "ngram", "gamma": 4, "adaptive": False,
                        "verify_units": 10, "fallback_units": 2,
                        "proposed_tokens": 40, "accepted_tokens": 25,
                        "acceptance_rate": 0.625,
                        "mean_accepted_len": 3.5,
                        "draft_overhead_s": 0.01},
        "goodput_tokens_per_s": 100.0,
        "ttft": {"median": 0.01, "p99": 0.02, "p999": 0.03},
        "per_token_latency": {"median": 0.001, "p99": 0.002,
                              "p999": 0.003},
        "cache": {"peak_blocks_in_use": 12},
        "timeseries": {"queue_depth": [0, 1]},
        "decode_steps": 42,
        "wall_seconds": 1.5,
    }
    results = tmp_path / "results"
    save_json(fake, results / "serving_specrun.json")
    rows = write_serving_report(results, tmp_path / "stats")
    assert len(rows) == 1
    row = rows[0]
    assert row["speculation"] == "ngram"
    assert row["spec_gamma"] == 4
    assert row["acceptance_rate"] == 0.625
    assert row["mean_accepted_len"] == 3.5
    md = (tmp_path / "stats" / "SERVING.md").read_text()
    assert "ngram" in md


def test_speculative_report_writer(tmp_path):
    from dlbb_tpu.stats.serving_report import write_speculative_report
    from dlbb_tpu.utils.config import save_json

    bench = {
        "schema": "dlbb_bench_spec_v1",
        "baseline": "off_fused16",
        "settings": {
            "off_fused16": {
                "speculation": "off", "decode_horizon": 16,
                "output_tokens_per_s": {"median": 100.0, "min": 95.0,
                                        "max": 105.0},
                "ttft_p50_ms": 10.0, "per_token_p50_ms": 2.0,
            },
            "ngram_g4_fused16": {
                "speculation": "ngram", "spec_gamma": 4,
                "decode_horizon": 16,
                "output_tokens_per_s": {"median": 150.0, "min": 140.0,
                                        "max": 160.0},
                "ttft_p50_ms": 8.0, "per_token_p50_ms": 1.2,
                "acceptance_rate": 0.7, "mean_accepted_len": 3.8,
                "draft_overhead_s": 0.01, "token_identical": True,
            },
        },
    }
    path = tmp_path / "BENCH_spec.json"
    save_json(bench, path)
    rows = write_speculative_report(path, tmp_path / "stats")
    assert len(rows) == 2
    by_name = {r["setting"]: r for r in rows}
    assert by_name["ngram_g4_fused16"]["speedup_vs_baseline"] == 1.5
    assert by_name["ngram_g4_fused16"]["token_identical"] is True
    md = (tmp_path / "stats" / "SPECULATIVE.md").read_text()
    assert "1.50x" in md and "ngram_g4_fused16" in md and "yes" in md
    # missing artifact: no rows, nothing clobbered
    assert write_speculative_report(tmp_path / "nope.json",
                                    tmp_path / "stats2") == []


# ---------------------------------------------------------------------------
# sampled decode (temperature > 0): in-engine residual sampling
# ---------------------------------------------------------------------------


def test_sampled_validation_ladder():
    """temperature > 0 routes decode through the verify unit's residual
    sampler — every configuration where the knob would silently emit
    greedy tokens is rejected up front."""
    with pytest.raises(ValueError, match="requires a drafting"):
        ServingConfig(**SERVE, temperature=0.8).validate(MODEL)
    with pytest.raises(ValueError, match="decode_horizon=1"):
        ServingConfig(**SERVE, speculation="ngram", spec_gamma=4,
                      temperature=0.8,
                      decode_horizon=16).validate(MODEL)
    with pytest.raises(ValueError, match="prefill_chunk"):
        ServingConfig(**SERVE, speculation="ngram", spec_gamma=4,
                      temperature=0.8, prefill_chunk=16).validate(MODEL)
    with pytest.raises(ValueError, match="requires temperature"):
        ServingConfig(**SERVE, sample_seed=3).validate(MODEL)
    with pytest.raises(ValueError, match=">= 0"):
        ServingConfig(**SERVE, temperature=-0.1).validate(MODEL)


@pytest.mark.spec_smoke
def test_sampled_run_replayable_and_seed_sensitive(mesh2x4):
    """The sampled path runs in-engine through the scheduler: the same
    (trace seed, sample_seed) pair replays token-identically, a
    different sample_seed diverges, and the report records the sampled
    law (temperature, seed, sampled=True)."""
    trace = _spec_trace(n=6, out=(24, 32))
    kw = dict(speculation="ngram", spec_gamma=4, temperature=0.8)
    a = _engine(mesh2x4, **kw, sample_seed=3).run_trace(trace)
    b = _engine(mesh2x4, **kw, sample_seed=3).run_trace(trace)
    c = _engine(mesh2x4, **kw, sample_seed=4).run_trace(trace)
    assert a["requests"]["completed"] == len(trace)
    assert a["completed_tokens"] == b["completed_tokens"]
    assert a["completed_tokens"] != c["completed_tokens"]
    s = a["speculation"]
    assert s["sampled"] is True
    assert s["temperature"] == 0.8 and s["sample_seed"] == 3
    assert s["verify_units"] > 0
    assert a["cache"]["blocks_reserved"] == 0
