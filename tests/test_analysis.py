"""comm-lint tests.

Seeded-violation fixtures: each deliberately broken computation / source
snippet must produce exactly the expected finding, and its fixed twin must
pass clean.  Plus the standing guarantees: every ``comm/ops.py`` registry
collective audits clean, and the repo itself lints clean (the tier-1 gate
behind ``scripts/run_static_analysis.sh``).
"""

import json
import textwrap
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

from dlbb_tpu.analysis.expectations import TargetExpectation
from dlbb_tpu.analysis.findings import AnalysisReport
from dlbb_tpu.analysis.hlo_audit import (
    AuditTarget,
    audit_target,
    registry_op_targets,
    run_hlo_audit,
)
from dlbb_tpu.analysis.source_lint import lint_source, run_source_lint

REPO_ROOT = Path(__file__).resolve().parents[1]


# ---------------------------------------------------------------------------
# HLO auditor: seeded violations
# ---------------------------------------------------------------------------


def _missharded_matmul_target(mesh8):
    """A benchmark claiming 'row-parallel matmul, all-reduce only' whose
    output sharding forces GSPMD to insert a hidden all-gather."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def build():
        xs = jax.device_put(
            jnp.ones((64, 16), jnp.float32),
            NamedSharding(mesh8, P("ranks", None)),
        )
        w = jax.device_put(
            jnp.ones((16, 32), jnp.float32),
            NamedSharding(mesh8, P(None, None)),
        )
        fn = jax.jit(
            lambda a, b: a @ b,
            out_shardings=NamedSharding(mesh8, P(None, None)),
        )
        return fn, (xs, w)

    return AuditTarget(
        name="fixture/missharded_matmul",
        build=build,
        expectation=TargetExpectation(
            allowed={"all-reduce"}, required_any=None,
        ),
        min_devices=8,
    )


def test_missharded_matmul_yields_unexpected_allgather(mesh8):
    findings, meta = audit_target(_missharded_matmul_target(mesh8))
    assert len(findings) == 1, [f.to_dict() for f in findings]
    f = findings[0]
    assert f.rule == "unexpected-collective"
    assert f.severity == "error"
    assert f.details["kind"] == "all-gather"
    # acceptance contract: op kind, shape, byte volume, replica groups,
    # and the plan-derived expected volume all present and serializable
    assert f.details["shape"] == [64, 32]
    assert f.details["result_bytes"] == 64 * 32 * 4
    assert f.details["replica_groups"]
    assert f.details["analytic_wire_bytes"] > 0
    assert f.details["expected_allowed_kinds"] == ["all-reduce"]
    json.dumps(f.to_dict())  # must be JSON-serializable as-is


def test_well_sharded_matmul_is_clean(mesh8):
    """The same matmul with the output left row-sharded needs no
    communication at all."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def build():
        xs = jax.device_put(
            jnp.ones((64, 16), jnp.float32),
            NamedSharding(mesh8, P("ranks", None)),
        )
        w = jax.device_put(
            jnp.ones((16, 32), jnp.float32),
            NamedSharding(mesh8, P(None, None)),
        )
        fn = jax.jit(
            lambda a, b: a @ b,
            out_shardings=NamedSharding(mesh8, P("ranks", None)),
        )
        return fn, (xs, w)

    findings, _ = audit_target(AuditTarget(
        name="fixture/row_parallel_matmul",
        build=build,
        expectation=TargetExpectation(allowed=set(), required_any=None),
        min_devices=8,
    ))
    assert findings == []


def _donation_target(mesh8, donate: bool):
    from jax.sharding import NamedSharding, PartitionSpec as P

    def build():
        kwargs = {"donate_argnums": (0,)} if donate else {}
        fn = jax.jit(lambda s, x: (s + x, jnp.sum(x)), **kwargs)
        sharding = NamedSharding(mesh8, P("ranks", None))
        s = jax.device_put(jnp.zeros((8, 16), jnp.float32), sharding)
        x = jax.device_put(jnp.ones((8, 16), jnp.float32), sharding)
        return fn, (s, x)

    return AuditTarget(
        name=f"fixture/step_donate_{donate}",
        build=build,
        expectation=TargetExpectation(
            allowed={"all-reduce"}, required_any=None,
            expect_donation=True,
        ),
        min_devices=8,
    )


def test_undonated_step_yields_missing_donation(mesh8):
    findings, _ = audit_target(_donation_target(mesh8, donate=False))
    assert [f.rule for f in findings] == ["missing-donation"]


def test_donated_step_is_clean(mesh8):
    findings, _ = audit_target(_donation_target(mesh8, donate=True))
    assert findings == []


def test_registry_ops_audit_clean(devices):
    """Every comm/ops.py registry collective lowers to exactly the HLO
    collective its expectation table claims — the clean-pass guarantee the
    sweeps rely on."""
    report = run_hlo_audit(targets=registry_op_targets())
    assert report.findings == [], [f.render() for f in report.findings]
    assert len(report.targets_audited) >= 10
    assert report.skipped_targets == []


def test_barrier_audits_clean(devices):
    """The timing barrier must lower to a scalar-sized all-reduce and
    nothing else (it synchronises; it must not move data)."""
    from dlbb_tpu.analysis.hlo_audit import _barrier_target

    findings, meta = audit_target(_barrier_target())
    assert findings == [], [f.render() for f in findings]
    assert meta["num_collectives"] >= 1


def test_parse_async_start_payload_is_kind_aware():
    """Async ``-start`` tuples hold (operand, result, ...); the payload is
    the result — the smallest element for reduce-scatter (it shrinks by the
    group size), the largest for all-gather (it grows)."""
    from dlbb_tpu.analysis.hlo_parse import parse_collectives

    rs = ("  %rs = (f32[64]{0}, f32[8]{0}) reduce-scatter-start("
          "f32[64]{0} %p), channel_id=1, "
          "replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}")
    (instr,) = parse_collectives(rs)
    assert instr.kind == "reduce-scatter"
    assert instr.result_bytes == 32 and instr.shape == (8,)

    ag = ("  %ag = (f32[8]{0}, f32[64]{0}) all-gather-start("
          "f32[8]{0} %p), channel_id=1, "
          "replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}")
    (instr,) = parse_collectives(ag)
    assert instr.kind == "all-gather"
    assert instr.result_bytes == 256 and instr.shape == (64,)


def test_audit_skips_targets_needing_more_devices(devices):
    report = run_hlo_audit(targets=[AuditTarget(
        name="fixture/needs_1024_devices",
        build=lambda: (_ for _ in ()).throw(AssertionError("not built")),
        expectation=TargetExpectation(),
        min_devices=1024,
    )])
    assert report.targets_audited == []
    assert len(report.skipped_targets) == 1


# ---------------------------------------------------------------------------
# source lint: seeded violations
# ---------------------------------------------------------------------------


HOST_SYNC_TIMER_FIXTURE = textwrap.dedent("""
    import jax
    from dlbb_tpu.utils.metrics import Timer

    def bench(fn, x):
        with Timer() as t:
            y = fn(x)
            jax.block_until_ready(y)
            z = fn(y)
        return t.elapsed, z
""")


def test_lint_host_sync_in_timer_block():
    findings, _ = lint_source(HOST_SYNC_TIMER_FIXTURE, "fixture.py")
    assert [f.rule for f in findings] == ["host-sync-in-timed-region"]
    assert findings[0].location == "fixture.py:8"


def test_lint_final_bracketing_sync_allowed():
    src = HOST_SYNC_TIMER_FIXTURE.replace("        z = fn(y)\n", "")
    src = src.replace("return t.elapsed, z", "return t.elapsed, y")
    findings, _ = lint_source(src, "fixture.py")
    assert findings == []


PERF_COUNTER_FIXTURE = textwrap.dedent("""
    import time
    import numpy as np

    def bench(fn, x):
        t0 = time.perf_counter()
        y = fn(x)
        host = np.asarray(y)
        y = fn(y)
        elapsed = time.perf_counter() - t0
        return elapsed, host
""")


def test_lint_host_sync_in_perf_counter_region():
    findings, _ = lint_source(PERF_COUNTER_FIXTURE, "fixture.py")
    assert [f.rule for f in findings] == ["host-sync-in-timed-region"]
    assert "np.asarray" in findings[0].message


def test_lint_suppression_comment():
    src = HOST_SYNC_TIMER_FIXTURE.replace(
        "jax.block_until_ready(y)",
        "jax.block_until_ready(y)  "
        "# comm-lint: disable=host-sync-in-timed-region",
    )
    findings, suppressed = lint_source(src, "fixture.py")
    assert findings == []
    assert suppressed == 1


def test_lint_file_level_suppression():
    src = ("# comm-lint: disable-file=host-sync-in-timed-region\n"
           + HOST_SYNC_TIMER_FIXTURE)
    findings, suppressed = lint_source(src, "fixture.py")
    assert findings == []
    assert suppressed == 1


DONATION_FIXTURE = textwrap.dedent("""
    import jax

    def make_step(optimizer):
        def train_step(state, batch):
            return state, batch

        return jax.jit(train_step)
""")


def test_lint_missing_donation_on_train_step_jit():
    findings, _ = lint_source(DONATION_FIXTURE, "fixture.py")
    assert [f.rule for f in findings] == ["missing-donation"]
    fixed = DONATION_FIXTURE.replace(
        "jax.jit(train_step)", "jax.jit(train_step, donate_argnums=(0,))"
    )
    assert lint_source(fixed, "fixture.py")[0] == []


JIT_IN_LOOP_FIXTURE = textwrap.dedent("""
    import jax

    def sweep(xs, scales):
        outs = []
        for s in scales:
            f = jax.jit(lambda x: x * s)
            outs.append(f(xs))
        return outs
""")


def test_lint_jit_in_loop_scalar_capture():
    findings, _ = lint_source(JIT_IN_LOOP_FIXTURE, "fixture.py")
    assert [f.rule for f in findings] == ["jit-in-loop"]
    hoisted = textwrap.dedent("""
        import jax

        def sweep(xs, scales):
            f = jax.jit(lambda x, s: x * s)
            outs = []
            for s in scales:
                outs.append(f(xs, s))
            return outs
    """)
    assert lint_source(hoisted, "fixture.py")[0] == []


def test_lint_jit_in_loop_def():
    """An in-loop ``def`` closing over the loop variable is the same fresh
    trace + compile hazard as an inline lambda."""
    src = textwrap.dedent("""
        import jax

        def sweep(xs, scales):
            outs = []
            for s in scales:
                def f(x):
                    return x * s
                outs.append(jax.jit(f)(xs))
            return outs
    """)
    findings, _ = lint_source(src, "fixture.py")
    assert [f.rule for f in findings] == ["jit-in-loop"]
    assert findings[0].severity == "warning"
    hoisted = textwrap.dedent("""
        import jax

        def sweep(xs, scales):
            def f(x, s):
                return x * s
            g = jax.jit(f)
            return [g(xs, s) for s in scales]
    """)
    assert lint_source(hoisted, "fixture.py")[0] == []


HOST_TRANSFER_LOOP_FIXTURE = textwrap.dedent("""
    import jax
    import numpy as np

    def collect(model, xs):
        outs = []
        for x in xs:
            y = model(x)
            y.block_until_ready()
            outs.append(np.asarray(jax.device_get(y)))
        return outs
""")


def test_lint_host_transfer_in_loop():
    """The host-side twin of jit-in-loop: a per-iteration device->host
    transfer/sync serialises dispatch into every trip."""
    findings, _ = lint_source(HOST_TRANSFER_LOOP_FIXTURE, "fixture.py")
    assert [f.rule for f in findings] == ["host-transfer-in-loop"] * 3
    assert all(f.severity == "warning" for f in findings)
    batched = textwrap.dedent("""
        import numpy as np

        def collect(model, xs):
            outs = [model(x) for x in xs]
            return np.asarray(outs)
    """)
    assert lint_source(batched, "fixture.py")[0] == []


def test_lint_host_transfer_in_loop_exemptions():
    """Timed regions (the timed-region rules own them), constant-literal
    probe ladders, loop-exit paths, jnp.asarray (device-side), and the
    measurement API homes are all exempt."""
    src = textwrap.dedent("""
        import time
        import jax
        import jax.numpy as jnp

        def measure(jitted, xs, guard):
            for mode in ("head", "whole"):
                jax.device_get(mode)
            for x in xs:
                if guard.requested:
                    state = jax.device_get(x)
                    break
                t0 = time.perf_counter()
                out = jitted(x)
                jax.block_until_ready(out)
                dt = time.perf_counter() - t0
                dev = jnp.asarray(x)
            return dt
    """)
    assert lint_source(src, "fixture.py")[0] == []
    # Timer-block bodies defer to host-sync-in-timed-region's bracketing
    # convention (the final statement is the sanctioned closing sync)
    timer = textwrap.dedent("""
        from dlbb_tpu.utils.metrics import Timer

        def measure(jitted, xs):
            times = []
            for x in xs:
                with Timer() as t:
                    out = jitted(x)
                    jax.block_until_ready(out)
                times.append(t.elapsed)
            return times
    """)
    assert lint_source(timer, "fixture.py")[0] == []
    # the measurement/capture API homes drive the device in loops by
    # design — exempt exactly like the profiler rule's API homes
    assert lint_source(HOST_TRANSFER_LOOP_FIXTURE,
                       "dlbb_tpu/utils/timing.py")[0] == []
    assert lint_source(HOST_TRANSFER_LOOP_FIXTURE,
                       "dlbb_tpu/obs/capture.py")[0] == []


def test_lint_host_transfer_in_loop_suppression():
    sup = HOST_TRANSFER_LOOP_FIXTURE.replace(
        "y.block_until_ready()",
        "y.block_until_ready()"
        "  # comm-lint: disable=host-transfer-in-loop",
    ).replace(
        "outs.append(np.asarray(jax.device_get(y)))",
        "# comm-lint: disable=host-transfer-in-loop\n"
        "        outs.append(np.asarray(jax.device_get(y)))",
    )
    findings, hits = lint_source(sup, "fixture.py")
    assert findings == [] and hits >= 3


def test_lint_host_sync_in_finally_block():
    """perf_counter regions inside a ``finally:`` block are linted too."""
    src = textwrap.dedent("""
        import time
        import numpy as np

        def bench(fn, x):
            try:
                y = None
            finally:
                t0 = time.perf_counter()
                y = fn(x)
                host = np.asarray(y)
                y = fn(y)
                elapsed = time.perf_counter() - t0
            return elapsed, host
    """)
    findings, _ = lint_source(src, "fixture.py")
    assert [f.rule for f in findings] == ["host-sync-in-timed-region"]


WALLCLOCK_TIMER_FIXTURE = textwrap.dedent("""
    import time
    from dlbb_tpu.utils.metrics import Timer

    def bench(fn, x):
        with Timer() as t:
            y = fn(x)
            started = time.time()
        return t.elapsed, y, started
""")


def test_lint_wallclock_in_timer_block():
    """time.time() inside a Timer block is non-monotonic measurement
    corruption — and unlike host syncs it gets NO bracketing exemption
    (here it IS the final statement and still fires)."""
    findings, _ = lint_source(WALLCLOCK_TIMER_FIXTURE, "fixture.py")
    assert [f.rule for f in findings] == ["wallclock-in-timed-region"]
    assert "time.time()" in findings[0].message
    fixed = WALLCLOCK_TIMER_FIXTURE.replace(
        "started = time.time()", "started = time.perf_counter()"
    )
    assert lint_source(fixed, "fixture.py")[0] == []


def test_lint_wallclock_in_perf_counter_region():
    src = textwrap.dedent("""
        import time
        from datetime import datetime

        def bench(fn, x):
            t0 = time.perf_counter()
            y = fn(x)
            stamp = datetime.now()
            elapsed = time.perf_counter() - t0
            return elapsed, y, stamp
    """)
    findings, _ = lint_source(src, "fixture.py")
    assert [f.rule for f in findings] == ["wallclock-in-timed-region"]
    assert "datetime.now()" in findings[0].message
    # a timestamp OUTSIDE the region is the sanctioned pattern (what
    # runner.py does for the manifest)
    moved = textwrap.dedent("""
        import time
        from datetime import datetime

        def bench(fn, x):
            t0 = time.perf_counter()
            y = fn(x)
            elapsed = time.perf_counter() - t0
            stamp = datetime.now()
            return elapsed, y, stamp
    """)
    assert lint_source(moved, "fixture.py")[0] == []


def test_lint_wallclock_suppression():
    src = WALLCLOCK_TIMER_FIXTURE.replace(
        "started = time.time()",
        "started = time.time()  "
        "# comm-lint: disable=wallclock-in-timed-region",
    )
    findings, suppressed = lint_source(src, "fixture.py")
    assert findings == []
    assert suppressed == 1


PROFILER_TIMER_FIXTURE = textwrap.dedent("""
    import jax
    from dlbb_tpu.utils.metrics import Timer

    def bench(fn, x):
        with Timer(sync=x) as t:
            with jax.profiler.trace("/tmp/trace"):
                y = fn(x)
        return t.elapsed, y
""")


def test_lint_profiler_in_timer_block():
    """A profiler session inside a Timer block contaminates the number
    being published — capture belongs on a dedicated profile rep
    outside the region (docs/observability.md); no bracketing
    exemption."""
    findings, _ = lint_source(PROFILER_TIMER_FIXTURE, "fixture.py")
    assert [f.rule for f in findings] == ["profiler-in-timed-region"]
    assert "jax.profiler.trace" in findings[0].message


def test_lint_profiler_in_perf_counter_region():
    src = textwrap.dedent("""
        import time
        from dlbb_tpu.utils.profiling import annotate

        def bench(fn, x):
            t0 = time.perf_counter()
            with annotate("measure"):
                y = fn(x)
            elapsed = time.perf_counter() - t0
            return elapsed, y
    """)
    findings, _ = lint_source(src, "fixture.py")
    assert [f.rule for f in findings] == ["profiler-in-timed-region"]
    # the sanctioned pattern — the annotation WRAPS the timed region
    # (what train/loop.py and utils/timing.py do) — is clean
    moved = textwrap.dedent("""
        import time
        from dlbb_tpu.utils.profiling import annotate

        def bench(fn, x):
            with annotate("measure"):
                t0 = time.perf_counter()
                y = fn(x)
                elapsed = time.perf_counter() - t0
            return elapsed, y
    """)
    assert lint_source(moved, "fixture.py")[0] == []


def test_lint_profiler_rule_exempts_api_homes():
    """utils/profiling.py and obs/capture.py ARE the capture API — the
    timed-region profiler rule must not fire on their own internals
    (obs/capture.py times its capture's wall cost by design)."""
    findings, _ = lint_source(
        PROFILER_TIMER_FIXTURE, "dlbb_tpu/obs/capture.py"
    )
    assert findings == []


def test_lint_profiler_suppression():
    src = PROFILER_TIMER_FIXTURE.replace(
        'with jax.profiler.trace("/tmp/trace"):',
        'with jax.profiler.trace("/tmp/trace"):  '
        "# comm-lint: disable=profiler-in-timed-region",
    )
    findings, suppressed = lint_source(src, "fixture.py")
    assert findings == []
    assert suppressed == 1


SET_ITER_FIXTURE = textwrap.dedent("""
    NAMES_A = ("b", "a")
    NAMES_B = ("c",)

    def publish():
        for name in {*NAMES_A, *NAMES_B}:
            print(name)
""")


def test_lint_unsorted_set_iteration():
    findings, _ = lint_source(SET_ITER_FIXTURE, "fixture.py")
    assert [f.rule for f in findings] == ["unsorted-set-iteration"]
    fixed = SET_ITER_FIXTURE.replace(
        "{*NAMES_A, *NAMES_B}", "sorted({*NAMES_A, *NAMES_B})"
    )
    assert lint_source(fixed, "fixture.py")[0] == []


ATOMIC_WRITE_FIXTURE = textwrap.dedent("""
    import json

    def publish(result, path):
        with open(path, "w") as f:
            json.dump(result, f, indent=2)
        path.with_suffix(".summary.json").write_text(
            json.dumps(result) + "\\n"
        )
""")


def test_lint_non_atomic_artifact_write():
    """Both torn-write shapes are flagged (in-place json.dump and
    truncate-then-write write_text); the save_json migration and the
    suppression comment both silence it; the helper file itself is
    exempt (its json.dump-to-tmp IS the atomic mechanism)."""
    findings, _ = lint_source(ATOMIC_WRITE_FIXTURE, "fixture.py")
    assert [f.rule for f in findings] == ["non-atomic-artifact-write"] * 2
    fixed = textwrap.dedent("""
        from dlbb_tpu.utils.config import save_json

        def publish(result, path):
            save_json(result, path)
    """)
    assert lint_source(fixed, "fixture.py")[0] == []
    suppressed = ATOMIC_WRITE_FIXTURE.replace(
        "json.dump(result, f, indent=2)",
        "json.dump(result, f, indent=2)"
        "  # comm-lint: disable=non-atomic-artifact-write",
    ).replace(
        "path.with_suffix(\".summary.json\").write_text(",
        "# comm-lint: disable=non-atomic-artifact-write\n"
        "        path.with_suffix(\".summary.json\").write_text(",
    )
    findings, hits = lint_source(suppressed, "fixture.py")
    assert findings == [] and hits == 2
    # the atomic helper's own tmp-file json.dump is sanctioned
    assert lint_source(ATOMIC_WRITE_FIXTURE,
                       "dlbb_tpu/utils/config.py")[0] == []


# ---------------------------------------------------------------------------
# standing guarantees + report plumbing
# ---------------------------------------------------------------------------


def test_repo_lints_clean():
    """The repo's own sources must pass the lint rules (fast, pure AST —
    the tier-1 gate run by scripts/run_static_analysis.sh)."""
    report = run_source_lint(root=REPO_ROOT)
    assert report.errors == [], [f.render() for f in report.errors]
    assert report.files_linted > 40


def test_report_json_roundtrip(tmp_path, mesh8):
    findings, _ = audit_target(_missharded_matmul_target(mesh8))
    report = AnalysisReport(findings=findings,
                            targets_audited=["fixture/missharded_matmul"])
    out = tmp_path / "report.json"
    report.write_json(out)
    data = json.loads(out.read_text())
    assert data["summary"]["errors"] == 1
    f = data["findings"][0]
    assert f["rule"] == "unexpected-collective"
    for key in ("kind", "shape", "result_bytes", "replica_groups",
                "analytic_wire_bytes", "expected_allowed_kinds"):
        assert key in f["details"], key


def test_cli_analyze_lint_exits_zero():
    from dlbb_tpu.analysis import run_analysis

    assert run_analysis(which="lint", root=str(REPO_ROOT),
                        verbose=False) == 0


# ---------------------------------------------------------------------------
# fail-closed: vacuous runs must not read as clean gates
# ---------------------------------------------------------------------------


def test_lint_wrong_root_is_an_error(tmp_path):
    """A typo'd --root (no dlbb_tpu/ or scripts/ underneath) must fail, not
    print '0 findings over 0 files' and exit 0."""
    report = run_source_lint(root=str(tmp_path))
    assert [f.rule for f in report.errors] == ["no-files-linted"]
    assert report.files_linted == 0


def test_hlo_all_targets_skipped_is_an_error(monkeypatch):
    """When every audit target is skipped for lack of devices, the CLI exit
    code must be nonzero — CI wired to it must not vacuously pass."""
    from dlbb_tpu import analysis

    starved = AuditTarget(
        name="fixture/needs_1024_devices",
        build=lambda: (_ for _ in ()).throw(AssertionError("not built")),
        expectation=TargetExpectation(),
        min_devices=1024,
    )
    monkeypatch.setattr(
        "dlbb_tpu.analysis.hlo_audit.default_targets", lambda: [starved])
    assert analysis.run_analysis(which="hlo", verbose=False) == 1


def test_audit_crash_is_contained(devices):
    """One target whose build raises must become an audit-crash finding,
    not abort the audit of the remaining targets."""
    boom = AuditTarget(
        name="fixture/raises_on_build",
        build=lambda: (_ for _ in ()).throw(RuntimeError("boom")),
        expectation=TargetExpectation(),
        min_devices=1,
    )
    report = run_hlo_audit(targets=[boom, *registry_op_targets()])
    crash = [f for f in report.findings if f.rule == "audit-crash"]
    assert len(crash) == 1 and "boom" in crash[0].message
    assert len(report.targets_audited) >= 10  # the rest still audited
