"""Serving resilience tests (PR 11): the chaos harness extended into
the continuous-batching engine.

The serving fault matrix — transient prefill/decode dispatch failures
retried after rolling the host ledger/slot state back to the
pre-dispatch snapshot, torn bookkeeping replayed, exhausted retries
failing only the affected requests with journaled exception chains, a
hung dispatch abandoned by the EMA-scaled watchdog while the engine
continues on a fresh carry, per-request SLO deadlines shedding blown
queue heads, and SIGTERM drain + ``cli serve --resume`` reproducing an
uninterrupted run's artifact set.  Plus the static zero-instruction pin
on the decode hot path: the jitted device programs never reference the
injection registry, so an inactive (or active) plan adds zero
instructions to the fused-scan body.
"""

import ast
import json
import time
from dataclasses import replace
from pathlib import Path

import pytest

from dlbb_tpu.models.configs import ModelConfig
from dlbb_tpu.obs import spans
from dlbb_tpu.resilience import inject
from dlbb_tpu.resilience.journal import SweepJournal, read_journal
from dlbb_tpu.serve.engine import ServingConfig, ServingEngine
from dlbb_tpu.serve.traffic import Request, TrafficTrace, generate_trace

REPO = Path(__file__).resolve().parents[1]

TINY = dict(hidden_size=64, num_layers=2, num_heads=4,
            ffn_intermediate=128, dtype="float32", attention="full")

SMOKE_MODEL = ModelConfig(**TINY)
# fast backoff so retry tests don't sleep their wall budget away
SMOKE_SERVING = ServingConfig(max_batch=8, block_size=8, max_seq=64,
                              queue_capacity=64, hbm_budget_gb=None,
                              retry_backoff_s=0.01)


def _trace(n=10, seed=5, rate=200.0, **kw):
    kw.setdefault("prompt_range", (4, 12))
    kw.setdefault("output_range", (3, 6))
    return generate_trace("poisson", n, seed=seed, rate=rate, **kw)


@pytest.fixture(scope="module")
def chaos_engine(mesh2x4):
    """One compiled engine shared by the fault-matrix tests (fresh
    cache per run_trace; registry counters accumulate, so tests assert
    per-run report fields, not absolute counter values)."""
    return ServingEngine(SMOKE_MODEL, SMOKE_SERVING, mesh2x4,
                         verbose=False)


# ---------------------------------------------------------------------------
# injection registry + the static hot-path pin
# ---------------------------------------------------------------------------


def test_serve_sites_registered_and_parse():
    for site in ("serve-prefill-fail", "serve-decode-fail",
                 "serve-decode-hang", "serve-cache-torn",
                 "serve-trace-corrupt", "serve-preempt"):
        assert site in inject.SITES
    plan = inject.FaultPlan.parse(
        "serve-decode-fail:2,serve-decode-hang:@1,hang_seconds=5")
    assert plan.fire("serve-decode-fail")
    assert plan.fire("serve-decode-hang")
    assert plan.param("hang_seconds") == 5.0


def test_decode_hot_path_static_zero_injection_pin():
    """The PR-5 zero-overhead contract extended to serving: every
    jitted device program in serve/engine.py — the fused-scan body
    above all — must never reference the injection registry.  Fault
    sites live strictly on the HOST side of the dispatch boundary, so
    the lowered decode program is byte-identical with or without a
    plan (the serve_fastpath per-step ≡ fused equivalence tests run
    unmodified against this same code)."""
    src = (REPO / "dlbb_tpu" / "serve" / "engine.py").read_text()
    tree = ast.parse(src)
    device_fns = {
        "_decode_step_math", "_serve_block", "_cached_attention",
        "_chunk_attention", "_write_prompt_blocks", "_inject_token",
        "build_decode_fused", "build_decode_step", "build_prefill",
        "build_prefill_chunk", "build_compact_gather",
        "build_compact_scatter",
    }
    seen = set()
    for node in tree.body:
        if isinstance(node, ast.FunctionDef) and node.name in device_fns:
            seen.add(node.name)
            for sub in ast.walk(node):
                # any reference to the inject module (inject.fire,
                # inject.param, a bare import) inside a device program
                # breaks the pin; name-substring matches (_inject_token
                # itself) do not
                if isinstance(sub, ast.Name) and sub.id == "inject":
                    raise AssertionError(
                        f"injection reference inside device program "
                        f"{node.name}")
                if (isinstance(sub, ast.Attribute)
                        and sub.attr in ("fire", "param")
                        and isinstance(sub.value, ast.Name)
                        and sub.value.id == "inject"):
                    raise AssertionError(
                        f"inject.{sub.attr} inside device program "
                        f"{node.name}")
    assert seen == device_fns, f"missing device fns: {device_fns - seen}"
    # the KV-cache module (the other half of the device path) too
    assert "inject" not in (
        REPO / "dlbb_tpu" / "serve" / "kvcache.py").read_text()


# ---------------------------------------------------------------------------
# traffic: deadlines + corrupt-trace load
# ---------------------------------------------------------------------------


def test_request_deadline_field_roundtrip(tmp_path):
    t = _trace(deadline_s=0.5)
    assert all(r.deadline_s == 0.5 for r in t)
    assert t.params["deadline_s"] == 0.5
    path = tmp_path / "t.json"
    t.save(path)
    assert TrafficTrace.load(path) == t
    # deadline-free traces serialise exactly as the original v1 schema
    plain = _trace()
    payload = plain.to_dict()
    assert all("deadline_s" not in r for r in payload["requests"])
    with pytest.raises(ValueError, match="deadline_s"):
        _trace(deadline_s=0.0)


def test_trace_corrupt_load_fails_closed(tmp_path):
    path = tmp_path / "t.json"
    _trace().save(path)
    with inject.plan_scope("serve-trace-corrupt:@1"):
        with pytest.raises(ValueError,
                           match="corrupt or truncated") as ei:
            TrafficTrace.load(path)
        assert ei.value.__cause__ is not None  # the chained JSON error
        # the site is exhausted: the very next load succeeds — the file
        # itself was never touched
        assert len(TrafficTrace.load(path)) == 10


# ---------------------------------------------------------------------------
# the fault matrix through the engine (serve_chaos_smoke)
# ---------------------------------------------------------------------------


@pytest.mark.serve_chaos_smoke
def test_transient_dispatch_failures_retry_and_recover(chaos_engine,
                                                       tmp_path):
    """serve-prefill-fail + serve-decode-fail fire once each BEFORE the
    jit consumes the carry; the engine restores the pre-dispatch
    snapshot, backs off, re-issues — every request still completes and
    the retries are journaled + counted."""
    engine = chaos_engine
    journal = SweepJournal(tmp_path, meta={"mode": "serve"},
                           sink=spans.journal_sink)
    engine.journal = journal
    try:
        with inject.plan_scope("serve-prefill-fail:1,serve-decode-fail:1"):
            report = engine.run_trace(_trace())
    finally:
        engine.journal = None
        journal.close()
    assert report["requests"]["completed"] == 10
    assert report["requests"]["failed"] == 0
    assert report["resilience"]["retries"] >= 2
    assert all(v == "completed"
               for v in report["requests"]["outcomes"].values())
    events, _ = read_journal(tmp_path)
    phases = {e.get("phase") for e in events
              if e["event"] == "dispatch-retry"}
    assert {"prefill", "decode"} <= phases
    # the reason-labelled retry counters landed in the registry
    assert engine.registry.get("serve_request_retries",
                               phase="prefill") >= 1
    assert engine.registry.get("serve_request_retries",
                               phase="decode") >= 1


@pytest.mark.serve_chaos_smoke
def test_cache_torn_bookkeeping_rolls_back_and_replays(chaos_engine):
    """serve-cache-torn raises mid-way through the per-slot accounting
    loop, leaving tokens_done advanced for some slots but not the
    ledger: the rollback restores the pre-dispatch snapshot and the
    replay recomputes the whole unit's accounting from the device
    result already in hand."""
    engine = chaos_engine
    with inject.plan_scope("serve-cache-torn:1"):
        report = engine.run_trace(_trace())
    assert report["requests"]["completed"] == 10
    assert report["resilience"]["retries"] >= 1
    # ledger fully consistent after rollback: nothing dangling
    assert report["cache"]["blocks_reserved"] == 0
    assert report["cache"]["blocks_in_use"] == 0
    assert engine.registry.get("serve_request_retries",
                               phase="bookkeeping") >= 1


@pytest.mark.serve_chaos_smoke
def test_permanent_decode_failure_fails_only_affected_requests(
        chaos_engine, tmp_path):
    """Retries exhausted -> the resident requests fail CLOSED (journaled
    request-failed with the full exception chain), the run itself
    drains, and the engine stays serviceable for the next trace."""
    engine = chaos_engine
    original = engine.serving
    engine.serving = replace(original, max_dispatch_retries=0)
    journal = SweepJournal(tmp_path, meta={"mode": "serve"},
                           sink=spans.journal_sink)
    engine.journal = journal
    try:
        with inject.plan_scope("serve-decode-fail:*"):
            report = engine.run_trace(_trace())
    finally:
        engine.serving = original
        engine.journal = None
        journal.close()
    req = report["requests"]
    assert req["failed"] == 10 and req["completed"] == 0
    assert len(req["outcomes"]) == 10  # every request has a terminal state
    assert all(v == "failed[dispatch-failed]"
               for v in req["outcomes"].values())
    detail = report["resilience"]["failed"]
    assert detail and detail[0]["traceback"]
    assert "TransientFault" in detail[0]["error"]
    events, _ = read_journal(tmp_path)
    failed = [e for e in events if e["event"] == "request-failed"]
    assert len(failed) == 10
    assert all(e["reason"] == "dispatch-failed" for e in failed)
    # blocks freed, and the engine serves the next trace cleanly
    assert report["cache"]["blocks_reserved"] == 0
    clean = engine.run_trace(_trace(seed=6))
    assert clean["requests"]["completed"] == 10


@pytest.mark.serve_chaos_smoke
def test_hung_dispatch_abandoned_by_watchdog(chaos_engine, tmp_path):
    """serve-decode-hang sleeps 10s on the dispatch; the watchdog
    (EMA-scaled, 0.3s floor) abandons it on its daemon thread, fails
    the resident requests as hung-dispatch, and the engine continues
    on a fresh carry — later requests complete."""
    engine = chaos_engine
    original = engine.serving
    engine.serving = replace(original, dispatch_deadline_factor=50.0,
                             dispatch_deadline_min_s=0.3)
    journal = SweepJournal(tmp_path, meta={"mode": "serve"},
                           sink=spans.journal_sink)
    engine.journal = journal
    t0 = time.perf_counter()
    try:
        with inject.plan_scope(
                "serve-decode-hang:@1,hang_seconds=10"):
            report = engine.run_trace(_trace())
    finally:
        engine.serving = original
        engine.journal = None
        journal.close()
    wall = time.perf_counter() - t0
    assert wall < 8.0, f"engine blocked behind the hang ({wall:.1f}s)"
    assert report["resilience"]["hung_dispatches"] == 1
    outcomes = report["requests"]["outcomes"]
    hung = [r for r, o in outcomes.items()
            if o == "failed[hung-dispatch]"]
    assert len(hung) >= 1
    assert report["requests"]["completed"] == 10 - len(hung)
    events, _ = read_journal(tmp_path)
    assert any(e["event"] == "request-failed"
               and e["reason"] == "hung-dispatch" for e in events)
    assert engine.registry.get("serve_hung_dispatches") >= 1


@pytest.mark.serve_chaos_smoke
def test_carry_reset_mid_chunked_prefill_restarts_prefill(mesh2x4):
    """A catastrophic decode failure during the chunked-prefill
    interleave replaces the carry with a fresh cache — taking the
    admitting request's already-written chunks with it.  The prefill
    must RESTART on the fresh carry (chunk writes are deterministic, so
    the replay is exact), not keep chunking into an empty cache and
    report a silently-corrupted request as completed.  Pinned at token
    level: the victim is only the resident request; the admitting
    request's completed tokens equal an unfaulted run's."""
    engine = ServingEngine(
        SMOKE_MODEL,
        replace(SMOKE_SERVING, prefill_chunk=8,
                dispatch_deadline_factor=50.0,
                dispatch_deadline_min_s=0.3),
        mesh2x4, verbose=False, capture_tokens=True)
    # A (1 chunk) is resident when B's 3-chunk prefill interleaves —
    # the FIRST decode-site evaluation of the run is that interleaved
    # dispatch, so @1 aims the hang exactly at it
    trace = TrafficTrace(
        kind="poisson", seed=0, params={},
        requests=(
            Request(rid=0, arrival_s=0.0, prompt_len=4, output_len=4,
                    seed=11),
            Request(rid=1, arrival_s=0.0, prompt_len=20, output_len=4,
                    seed=12),
        ),
    )
    baseline = engine.run_trace(trace)
    assert baseline["requests"]["completed"] == 2
    with inject.plan_scope("serve-decode-hang:@1,hang_seconds=10"):
        report = engine.run_trace(trace)
    outcomes = report["requests"]["outcomes"]
    assert outcomes["0"] == "failed[hung-dispatch]"
    assert outcomes["1"] == "completed"
    assert report["resilience"]["hung_dispatches"] == 1
    assert report["resilience"]["retries"] >= 1  # the prefill restart
    assert engine.registry.get("serve_request_retries",
                               phase="prefill") >= 1
    # the corruption pin: B's tokens survive the mid-prefill reset
    assert (report["completed_tokens"]["1"]
            == baseline["completed_tokens"]["1"])


@pytest.mark.serve_chaos_smoke
def test_deadline_sheds_queue_heads_and_counts_late_completions(
        chaos_engine, tmp_path):
    """A t=0 burst with a 20ms SLO: the first grant wave is admitted
    within microseconds (wait << SLO, so it serves — and completes
    LATE, since 8 serial prefills alone exceed 20ms — counted, not
    rejected), while the queue heads left behind are re-examined only
    after those prefills and are shed with reason=deadline, DISTINCT
    from queue-full (shed_rate stays 0).  Arrivals pinned at 0 and the
    SLO at 20ms keep both outcomes deterministic on any host speed:
    the first admission check happens before any dispatch (µs), and
    every later boundary sits behind ≥8 prefill dispatches (≫20ms)."""
    engine = chaos_engine
    burst = TrafficTrace(
        kind="poisson", seed=0, params={"deadline_s": 0.02},
        requests=tuple(
            Request(rid=i, arrival_s=0.0, prompt_len=8, output_len=4,
                    seed=100 + i, deadline_s=0.02)
            for i in range(12)
        ),
    )
    journal = SweepJournal(tmp_path, meta={"mode": "serve"},
                           sink=spans.journal_sink)
    engine.journal = journal
    try:
        report = engine.run_trace(burst)
    finally:
        engine.journal = None
        journal.close()
    req = report["requests"]
    assert req["deadline_shed"] >= 1
    assert req["completed_past_deadline"] >= 1
    assert req["shed_rate"] == 0.0  # no queue-full rejection happened
    assert req["completed"] + req["deadline_shed"] == 12
    shed = [d for d in req["rejected_detail"]
            if d["reason"] == "deadline"]
    assert len(shed) == req["deadline_shed"]
    assert all(d["queue_wait_s"] > d["deadline_s"] for d in shed)
    assert all(req["outcomes"][str(d["rid"])] == "rejected[deadline]"
               for d in shed)
    events, _ = read_journal(tmp_path)
    assert any(e["event"] == "request-rejected"
               and e.get("reason") == "deadline" for e in events)
    assert any(e["event"] == "request-completed"
               and e.get("past_deadline") for e in events)


@pytest.mark.serve_chaos_smoke
def test_preempt_drains_and_journals(chaos_engine, tmp_path):
    """serve-preempt SIGTERMs the process at a scheduler boundary; the
    engine's own PreemptionGuard turns it into a graceful drain:
    admission stops, the in-flight window settles, resident requests
    are journaled request-preempted, and the report carries the
    remaining-rid cursor for --resume."""
    engine = chaos_engine
    journal = SweepJournal(tmp_path, meta={"mode": "serve"},
                           sink=spans.journal_sink)
    engine.journal = journal
    try:
        with inject.plan_scope("serve-preempt:@3"):
            report = engine.run_trace(_trace())
    finally:
        engine.journal = None
        journal.close()
    assert report["preempted"] is True
    assert report["remaining_rids"]
    assert report["raw_samples"] is not None  # checkpoint merge input
    preempted = [r for r, o in report["requests"]["outcomes"].items()
                 if o == "preempted"]
    done = report["requests"]["completed"]
    assert done + len(report["remaining_rids"]) == 10
    assert report["cache"]["blocks_reserved"] == 0  # drained clean
    events, _ = read_journal(tmp_path)
    assert any(e["event"] == "preempted" for e in events)
    assert len([e for e in events
                if e["event"] == "request-preempted"]) == len(preempted)


@pytest.mark.serve_chaos_smoke
def test_kill_mid_trace_resume_equals_uninterrupted(tmp_path, devices):
    """The serving resume invariant end to end (serve/bench.py):
    SIGTERM mid-trace writes the checkpoint INSTEAD of the result
    artifact; `--resume` replays the remaining trace and merges both
    sessions into an artifact set with the same names, report schema,
    and per-request outcomes (for non-preempted requests) as an
    uninterrupted run."""
    from dlbb_tpu.serve.bench import (
        RESUME_CHECKPOINT,
        resume_serving,
        run_serving,
    )

    config = {
        "experiment": {"name": "x"},
        "model": dict(TINY),
        "parallelism": {"data_parallel": 2, "world_size": 4},
        "serving": {"max_batch": 8, "block_size": 8, "max_seq": 64,
                    "queue_capacity": 64, "hbm_budget_gb": None},
    }
    trace = _trace()
    ref = tmp_path / "ref"
    out = tmp_path / "preempted"
    run_serving(config, trace, str(ref), verbose=False)
    rep = run_serving(config, trace, str(out), verbose=False,
                      fault_plan="serve-preempt:@3")
    assert rep["preempted"]
    assert (out / RESUME_CHECKPOINT).exists()
    assert not (out / "serving_x.json").exists()
    preempted_rids = {r for r, o in rep["requests"]["outcomes"].items()
                      if o == "preempted"}
    merged = resume_serving(str(out), verbose=False)
    assert not (out / RESUME_CHECKPOINT).exists()
    assert merged["requests"]["sessions"] == 2
    # artifact-set equality: names, schema keys, per-request outcomes
    assert (sorted(p.name for p in ref.iterdir())
            == sorted(p.name for p in out.iterdir()))
    a = json.loads((ref / "serving_x.json").read_text())
    b = json.loads((out / "serving_x.json").read_text())
    assert sorted(a) == sorted(b)
    oa, ob = a["requests"]["outcomes"], b["requests"]["outcomes"]
    assert set(oa) == set(ob)
    for rid in oa:
        if rid not in preempted_rids:
            assert oa[rid] == ob[rid], rid
    # the merged summaries were re-summarized over both sessions' raw
    # samples; a preempted request replayed in session 2 may contribute
    # a second TTFT sample (it was prefilled twice — honest accounting)
    assert b["ttft"]["count"] >= a["ttft"]["count"]
    assert "raw_samples" not in b
    # the append-only journal holds BOTH sessions
    events, torn = read_journal(out)
    assert torn == 0
    assert [e for e in events if e["event"] == "sweep-start"
            and e.get("resume")]
    assert any(e["event"] == "request-preempted" for e in events)


@pytest.mark.serve_chaos_smoke
def test_journal_to_trace_pairs_failed_and_preempted(tmp_path):
    """obs/spans.journal_to_trace reconstructs failed/retried/preempted
    request lifecycles into per-request X spans — a crashed serving run
    stays debuggable from the fsync'd journal alone."""
    with SweepJournal(tmp_path, meta={"mode": "serve"}) as j:
        j.event("request-arrived", config="request-1", prompt=4)
        j.event("dispatch-retry", phase="decode", attempt=1)
        j.event("request-failed", config="request-1",
                reason="hung-dispatch", error="DeadlineExceeded: ...")
        j.event("request-arrived", config="request-2", prompt=8)
        j.event("request-preempted", config="request-2", tokens_done=3)
        j.event("preempted", remaining=1)
    path, n, torn = spans.journal_to_trace(tmp_path,
                                           tmp_path / "trace.json")
    assert torn == 0
    payload = spans.load_trace(path)
    xs = {e["name"]: e for e in payload["traceEvents"]
          if e["ph"] == "X"}
    assert xs["request-1"]["cat"] == "config-failed"
    assert xs["request-1"]["args"]["reason"] == "hung-dispatch"
    assert xs["request-2"]["cat"] == "config-preempted"
    # instants for every journal line (the retry included) still there
    names = [e["name"] for e in payload["traceEvents"]
             if e["ph"] == "i"]
    assert "dispatch-retry" in names


# ---------------------------------------------------------------------------
# config validation, metrics folding, report columns
# ---------------------------------------------------------------------------


def test_resilience_config_validation_ladder():
    cfg = ModelConfig(**TINY)
    good = ServingConfig(max_batch=4, block_size=8, max_seq=32,
                         hbm_budget_gb=None,
                         dispatch_deadline_factor=8.0)
    good.validate(cfg)
    for bad in (
        dict(max_dispatch_retries=-1),
        dict(retry_backoff_s=-0.1),
        dict(dispatch_deadline_factor=0.0),
        dict(dispatch_deadline_min_s=0.0),
    ):
        with pytest.raises(ValueError, match=next(iter(bad))):
            ServingConfig(max_batch=4, block_size=8, max_seq=32,
                          hbm_budget_gb=None, **bad).validate(cfg)
    # knobs round-trip the config dict
    rt = ServingConfig.from_dict(good.to_dict())
    assert rt.dispatch_deadline_factor == 8.0
    assert rt.max_dispatch_retries == good.max_dispatch_retries


def test_serving_metrics_folds_resilience_and_deadlines():
    from dlbb_tpu.obs.export import serving_metrics

    report = {
        "goodput_tokens_per_s": 100.0,
        "requests": {"shed_rate": 0.1, "deadline_shed": 3,
                     "completed_past_deadline": 2, "failed": 1,
                     "preempted": 0},
        "resilience": {"retries": 4, "hung_dispatches": 1},
    }
    reg = serving_metrics(report)
    assert reg.get("serve_deadline_shed") == 3
    assert reg.get("serve_completed_past_deadline") == 2
    assert reg.get("serve_failed_requests") == 1
    assert reg.get("serve_request_retries", phase="decode") == 4
    assert reg.get("serve_hung_dispatches") == 1
    text = reg.to_prometheus()
    assert "dlbb_serve_deadline_shed" in text
    assert "dlbb_serve_request_retries_total" in text
    assert "dlbb_serve_hung_dispatches_total" in text
    # a live registry whose retries were ALL bookkeeping-phase (the
    # cache-torn scenario) is already seeded — folding the report on
    # top must NOT re-add the total under phase=decode
    from dlbb_tpu.obs.export import MetricsRegistry

    live = MetricsRegistry()
    live.labeled_counter("serve_request_retries", "phase")["bookkeeping"] \
        += 4
    reg2 = serving_metrics(report, registry=live)
    assert reg2.get("serve_request_retries", phase="decode") == 0
    assert reg2.get("serve_request_retries", phase="bookkeeping") == 4


def test_serving_report_gains_resilience_columns(tmp_path):
    from dlbb_tpu.stats.serving_report import write_serving_report
    from dlbb_tpu.utils.config import save_json

    fake = {
        "schema": "dlbb_serving_report_v1",
        "trace": {"kind": "poisson", "num_requests": 10},
        "requests": {"completed": 7, "rejected": 2, "failed": 1,
                     "deadline_shed": 2, "completed_past_deadline": 3},
        "resilience": {"retries": 5},
        "mesh": {"dp": 2, "tp": 4},
        "serving": {"max_batch": 8, "block_size": 16, "max_seq": 256},
        "goodput_tokens_per_s": 10.0,
        "ttft": {"median": 0.01, "p99": 0.02, "p999": 0.03},
        "per_token_latency": {"median": 0.001, "p99": 0.002,
                              "p999": 0.003},
        "cache": {"peak_blocks_in_use": 4},
        "timeseries": {"queue_depth": [0, 1]},
        "decode_steps": 9,
        "wall_seconds": 1.0,
    }
    save_json(fake, tmp_path / "results" / "serving_r1.json")
    rows = write_serving_report(tmp_path / "results", tmp_path / "stats")
    assert rows[0]["failed"] == 1
    assert rows[0]["deadline_shed"] == 2
    assert rows[0]["past_deadline"] == 3
    assert rows[0]["retries"] == 5
    md = (tmp_path / "stats" / "SERVING.md").read_text()
    assert "| late |" in md.replace("  ", " ")
    csv_head = (tmp_path / "stats" / "serving.csv").read_text()
    assert "failed" in csv_head and "past_deadline" in csv_head
