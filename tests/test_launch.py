"""Launcher-layer contract tests.

The reference's launch layer is mpirun/deepspeed shell scripts exporting the
CCL_* tuning env before spawning ranks (``collectives/3d/launch_dsccl.sh:34-74``).
The TPU analogue carries process-start ``XLA_FLAGS`` (collective-combiner
thresholds — the ``CCL_FUSION_BYTES_THRESHOLD`` analogue) which cannot be
applied after backend init, so the only place they can be honoured is the
launcher.  These tests pin that contract without a pod via the launcher's
dry-run mode, and pin the runner-side gate that refuses to run a flag
variant whose flags are absent (mislabelled results are worse than errors).
"""

import os
import subprocess
from pathlib import Path

import pytest

from dlbb_tpu.bench.runner import Sweep1D, _check_variant_flags, run_sweep
from dlbb_tpu.comm.variants import VARIANTS, get_variant

LAUNCHER = Path(__file__).resolve().parents[1] / "dlbb_tpu" / "launch" / "launch_tpu_pod.sh"


def _dryrun(*args: str, env_extra: dict | None = None) -> str:
    env = dict(os.environ)
    env["DLBB_LAUNCH_DRYRUN"] = "1"
    env.update(env_extra or {})
    out = subprocess.run(
        ["bash", str(LAUNCHER), *args],
        capture_output=True, text=True, env=env, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    return out.stdout


def test_launcher_injects_combiner_threshold_flags():
    stdout = _dryrun("bench1d", "--variant", "combine4mb", "--ranks", "8")
    assert "--xla_tpu_all_reduce_combine_threshold_bytes=4194304" in stdout
    assert "exec python -m dlbb_tpu.cli bench1d --variant combine4mb" in stdout


def test_launcher_injects_flags_for_equals_form():
    stdout = _dryrun("bench1d", "--variant=combine128mb")
    assert "--xla_tpu_all_reduce_combine_threshold_bytes=134217728" in stdout


def test_launcher_plain_variant_adds_no_flags():
    stdout = _dryrun("bench1d", "--variant", "ring")
    xla_line = next(l for l in stdout.splitlines() if l.startswith("XLA_FLAGS="))
    assert "combine_threshold" not in xla_line


def test_launcher_manual_override_still_respected():
    stdout = _dryrun(
        "bench1d",
        env_extra={"VARIANT_XLA_FLAGS": "--xla_tpu_all_reduce_combine_threshold_bytes=1048576"},
    )
    assert "--xla_tpu_all_reduce_combine_threshold_bytes=1048576" in stdout


def test_every_flag_variant_is_launcher_resolvable():
    """Each flag-carrying variant resolves through the same path the
    launcher uses — no variant can silently carry unlaunchable metadata."""
    for name, v in VARIANTS.items():
        if v.xla_flags:
            stdout = _dryrun("bench1d", "--variant", name)
            for flag in v.xla_flags:
                assert flag in stdout, (name, flag)


def test_runner_refuses_flag_variant_without_flags(monkeypatch):
    monkeypatch.delenv("XLA_FLAGS", raising=False)
    with pytest.raises(RuntimeError, match="combine4mb"):
        _check_variant_flags(get_variant("combine4mb"))
    # run_sweep goes through the same gate before touching any device
    with pytest.raises(RuntimeError, match="requires XLA_FLAGS"):
        run_sweep(Sweep1D(variant="combine4mb"), verbose=False)


def test_runner_accepts_flag_variant_with_flags_present(monkeypatch):
    flags = " ".join(get_variant("combine4mb").xla_flags)
    monkeypatch.setenv("XLA_FLAGS", flags)
    _check_variant_flags(get_variant("combine4mb"))  # no raise
