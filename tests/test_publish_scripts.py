"""Publisher-script contract tests (corpus provenance guards).

The committed ``results/``+``stats/`` corpus is only as trustworthy as the
scripts that claim to produce it; these tests pin the failure-handling
contracts of ``scripts/publish_tpu_e2e.py``'s parent loop without a chip:
boundary artifacts are written only for expected-infeasible configs whose
stderr matches a memory/compile signature, other failures still fail the
run, and success unlinks a stale boundary artifact.
"""

import importlib.util
import json
import sys
import types
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]


def _load(monkeypatch, tmp_path, run_results):
    """Import publish_tpu_e2e with subprocess.run faked.

    ``run_results``: {(size, attention, seq): (returncode, stderr)} —
    configs absent from the dict succeed.
    """
    spec = importlib.util.spec_from_file_location(
        "publish_tpu_e2e", REPO / "scripts" / "publish_tpu_e2e.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    calls = []

    def fake_run(cmd, capture_output=True, text=True):
        only = cmd[cmd.index("--only") + 1]
        size, attention, seq = only.split(",")
        key = (size, attention, int(seq))
        calls.append(key)
        rc, stderr = run_results.get(key, (0, ""))
        return types.SimpleNamespace(
            returncode=rc, stdout=f"ran {only}\n", stderr=stderr
        )

    import subprocess

    monkeypatch.setattr(subprocess, "run", fake_run)
    monkeypatch.setattr(
        sys, "argv", ["publish_tpu_e2e.py", "--output", str(tmp_path)]
    )
    return mod, calls


def test_boundary_artifact_only_for_memory_signature(monkeypatch, tmp_path):
    mod, _ = _load(
        monkeypatch, tmp_path,
        {("1B", "dense", 8192): (1, "jax: RESOURCE_EXHAUSTED while x\n")},
    )
    assert mod.main() == 0
    art = tmp_path / "xla_tpu_1b_dense_s8192_world1_infeasible.json"
    data = json.loads(art.read_text())
    assert data["status"] == "infeasible"
    assert "RESOURCE_EXHAUSTED" in data["observed_error"]
    # the deterministic reason comes from the script, not the stderr
    assert "score tensor" in data["reason"]


def test_unexpected_error_at_boundary_config_still_fails(monkeypatch,
                                                         tmp_path):
    mod, _ = _load(
        monkeypatch, tmp_path,
        {("1B", "dense", 8192): (1, "ImportError: no module named foo\n")},
    )
    assert mod.main() == 1  # NOT silently recorded as infeasible
    assert not list(tmp_path.glob("*_infeasible.json"))


def test_failure_outside_expected_set_fails(monkeypatch, tmp_path):
    mod, _ = _load(
        monkeypatch, tmp_path,
        {("7B", "full", 512): (1, "RESOURCE_EXHAUSTED\n")},
    )
    assert mod.main() == 1
    assert not list(tmp_path.glob("*_infeasible.json"))


def test_success_unlinks_stale_boundary_artifact(monkeypatch, tmp_path):
    stale = tmp_path / "xla_tpu_1b_dense_s8192_world1_infeasible.json"
    stale.write_text("{}")
    mod, calls = _load(monkeypatch, tmp_path, {})
    assert mod.main() == 0
    assert not stale.exists()
    assert ("1B", "dense", 8192) in calls


def test_boundary_unlinks_stale_measured_artifact(monkeypatch, tmp_path):
    """A config that regressed to infeasible must not leave its stale
    measured JSON shadowing the fresh boundary artifact (the mirror of the
    success-path stale-boundary unlink)."""
    stale = tmp_path / "xla_tpu_1b_dense_s8192_world1.json"
    stale.write_text("{}")
    mod, _ = _load(
        monkeypatch, tmp_path,
        {("1B", "dense", 8192): (1, "jax: RESOURCE_EXHAUSTED while x\n")},
    )
    assert mod.main() == 0
    assert not stale.exists()
    assert (tmp_path
            / "xla_tpu_1b_dense_s8192_world1_infeasible.json").exists()


def test_boundary_reason_computed_from_config(monkeypatch, tmp_path):
    """The deterministic boundary reason reflects the config's own shape
    parameters (head count from the model table, the actual seq), not a
    hardcoded dense-1B-8192 string."""
    mod, _ = _load(monkeypatch, tmp_path, {})
    reason = mod._boundary_reason("1B", "dense", 8192)
    # 1B: 16 heads; 8 * 16 * 8192^2 * 4 B = 32 GiB
    assert "N=16" in reason and "S=8192" in reason and "32 GiB" in reason
    reason7b = mod._boundary_reason("7B", "dense", 4096)
    # 7B: 32 heads; 8 * 32 * 4096^2 * 4 B = 16 GiB
    assert "N=32" in reason7b and "S=4096" in reason7b
    assert "16 GiB fp32" in reason7b


def _load_train(monkeypatch, tmp_path, run_results):
    """Import publish_tpu_train with subprocess.run faked.

    ``run_results``: {suffix: (returncode, stderr)}; absent configs
    succeed."""
    spec = importlib.util.spec_from_file_location(
        "publish_tpu_train", REPO / "scripts" / "publish_tpu_train.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    calls = []

    def fake_run(cmd, capture_output=True, text=True):
        suffix = cmd[cmd.index("--only") + 1]
        calls.append(suffix)
        rc, stderr = run_results.get(suffix, (0, ""))
        return types.SimpleNamespace(
            returncode=rc, stdout=f"ran {suffix}\n", stderr=stderr
        )

    import subprocess

    monkeypatch.setattr(subprocess, "run", fake_run)
    monkeypatch.setattr(
        sys, "argv", ["publish_tpu_train.py", "--output", str(tmp_path)]
    )
    return mod, calls


def test_train_boundary_only_for_remat_off(monkeypatch, tmp_path):
    """sgd_remat_off's memory failure is the no-remat ladder point; the
    boundary artifact records a reason computed from the 1B geometry."""
    mod, calls = _load_train(
        monkeypatch, tmp_path,
        {"sgd_remat_off": (1, "XLA ... RESOURCE_EXHAUSTED hbm\n")},
    )
    assert mod.main() == 0
    art = tmp_path / "train_ddp_1B_train_chip_sgd_remat_off_infeasible.json"
    data = json.loads(art.read_text())
    assert data["status"] == "infeasible"
    assert "remat" in data["reason"]
    # every other config ran
    assert set(calls) == {s for s, _, _, _ in mod.CONFIGS}


def test_train_shape_ladder_boundary(monkeypatch, tmp_path):
    """The big shape-ladder rungs may OOM; their boundary reason is
    computed from the rung's own (batch, seq), and the small rungs are
    never allowed to fail silently."""
    mod, calls = _load_train(
        monkeypatch, tmp_path,
        {"adam_bf16m_dots_b32_s1024": (1, "RESOURCE_EXHAUSTED hbm\n")},
    )
    assert mod.main() == 0
    art = tmp_path / ("train_ddp_1B_train_chip_adam_bf16m_dots_b32_s1024"
                      "_infeasible.json")
    data = json.loads(art.read_text())
    assert data["status"] == "infeasible"
    assert "B=32" in data["reason"] and "S=1024" in data["reason"]
    # every Adam shape rung is measured-infeasible on the 16 GiB chip
    # (b16/s512 needs 16.35G of 15.75G), so all of them are boundary;
    # the smallest STATELESS-SGD rungs are the ones that must never
    # fail silently — an OOM there would be a regression
    assert "adam_bf16m_dots_b16_s512" in mod.EXPECTED_FAIL_OK
    assert "sgd_dots_b16_s512" not in mod.EXPECTED_FAIL_OK
    assert "sgd_dots_b8_s1024" not in mod.EXPECTED_FAIL_OK
    assert mod._ladder_shape("adam_bf16m_dots_b16_s512") == (16, 512)
    assert mod._ladder_shape("sgd_dots_b8_s1024") == (8, 1024)


def test_train_adam_fp32m_failure_is_real(monkeypatch, tmp_path):
    """adam_fp32m is measured since the timing-loop donation fix; an OOM
    there is a regression, never silently recorded as infeasible."""
    mod, _ = _load_train(
        monkeypatch, tmp_path,
        {"adam_fp32m": (1, "RESOURCE_EXHAUSTED\n")},
    )
    assert mod.main() == 1
    assert not list(tmp_path.glob("*adam_fp32m*_infeasible.json"))


def test_train_missing_mode_runs_only_absent_configs(monkeypatch,
                                                     tmp_path):
    """--missing resumes a matrix interrupted by a tunnel outage: configs
    with a measured OR boundary artifact are excluded; only absent ones
    re-run."""
    mod, calls = _load_train(monkeypatch, tmp_path, {})
    measured = [s for s, _, _, _ in mod.CONFIGS]
    pending = {"sgd_dots_b16_s512", "adam_bf16m_dots_b8_s1024"}
    for s in measured:
        if s in pending:
            continue
        # half land as measured artifacts, half as boundaries — both
        # must count as "present"
        name = mod._artifact_name(s)
        suffix = "_infeasible" if s in mod.EXPECTED_FAIL_OK else ""
        (tmp_path / f"{name}{suffix}.json").write_text("{}")
    monkeypatch.setattr(sys, "argv", [
        "publish_tpu_train.py", "--output", str(tmp_path), "--missing",
    ])
    assert mod.main() == 0
    assert set(calls) == pending


def test_train_unknown_only_suffix_rejected(monkeypatch, tmp_path):
    mod, _ = _load_train(monkeypatch, tmp_path, {})
    monkeypatch.setattr(
        sys, "argv",
        ["publish_tpu_train.py", "--output", str(tmp_path),
         "--only", "adam_bf16"],
    )
    import pytest

    with pytest.raises(SystemExit, match="unknown config"):
        mod.main()


def _load_baselines():
    spec = importlib.util.spec_from_file_location(
        "publish_baselines", REPO / "scripts" / "publish_baselines.py"
    )
    # the module force-selects the simulated backend at import; that is
    # already this test session's backend, so importing is safe
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_parallelism_stage_families_consistent():
    """Every family member has a runnable config, every config belongs to
    a family, and each config's mesh product fits the 8-device stage."""
    mod = _load_baselines()

    members = {m for ms in mod.PARALLELISM_FAMILIES.values() for m in ms}
    configs = set(mod._PARALLELISM_CONFIGS)
    assert members == configs
    for name, (_, par, _) in mod._PARALLELISM_CONFIGS.items():
        product = 1
        for v in par.values():
            if isinstance(v, int) and v > 0:
                product *= v
        # num_microbatches is a schedule knob, not a mesh axis
        if "num_microbatches" in par:
            product //= par["num_microbatches"]
        assert product <= 8, (name, par)


def test_cp_scaling_skip_ladder(monkeypatch, tmp_path):
    """The cp_scaling stage's skip ladder in priority order: a
    known-infeasible cell writes its boundary WITHOUT executing (the
    rendezvous crash is a fatal CHECK — re-running it would kill a
    --fresh publisher), the footprint cap wins over the time budget
    (Ulysses at S=32768 must say 96 GiB, not 'time'), and only
    footprint-fitting cells outside the long-S allowance get time
    skips.  Measured cells call run_train exactly once each."""
    mod = _load_baselines()
    monkeypatch.setattr(mod, "RESULTS", tmp_path / "results")
    monkeypatch.setattr(mod, "STATS", tmp_path / "stats")

    ran = []

    def fake_run_train(config, zero_stage=0, output_dir=None, **kw):
        name = config["experiment"]["name"]
        ran.append(name)
        out = Path(output_dir) / f"train_ddp_{name}.json"
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps({
            "experiment": {"name": name},
            "mesh": {"dp": 1, "sp": 2, "pp": 1, "ep": 1, "tp": 1},
            "step_time": {"mean": 1.0},
            "tokens_per_second": 100.0,
        }))
        return {"tokens_per_second": 100.0}

    import dlbb_tpu.train.loop as loop_mod

    monkeypatch.setattr(loop_mod, "run_train", fake_run_train)
    mod.stage_cp_scaling()

    out = tmp_path / "results" / "parallelism" / "cp_scaling"
    art = {p.stem.removeprefix("train_ddp_"): json.loads(p.read_text())
           for p in out.glob("train_ddp_cp_*.json")}
    # full grid accounted for: every (S, sp, impl) cell has an artifact
    assert len(art) == 18
    # measured cells executed exactly once each, none of the capped ones
    assert sorted(ran) == sorted(
        n for n, a in art.items() if "status" not in a)
    # the rendezvous cell never executed and carries the infeasible class
    assert art["cp_s32768_sp8_ring"]["status"] == "infeasible"
    assert "cp_s32768_sp8_ring" not in ran
    # Ulysses at S=32768: footprint attribution at EVERY sp (never time)
    for sp in (2, 4, 8):
        a = art[f"cp_s32768_sp{sp}_ulysses"]
        assert a["status"] == "skipped_estimated_footprint", (sp, a)
    # ring at S=32768 outside the sp allowance: time attribution
    for sp in (2, 4):
        a = art[f"cp_s32768_sp{sp}_ring"]
        assert a["status"] == "skipped_estimated_time", (sp, a)
    # the report renders over the mixed cells without error
    assert (tmp_path / "stats" / "parallelism" / "CP_SCALING.md").exists()


def test_reports_regeneration_is_byte_stable(tmp_path):
    """``reports`` over the committed corpus must be a byte-level no-op.

    The derived tables (VARIANTS.md, VARIANTS3D.md, PARALLELISM.md,
    NORTHSTAR.md and their CSVs) are committed artifacts; the native-core
    stats path claims byte-stable regeneration — this pins it.  The whole
    ``stats/`` tree is copied aside, regenerated in place, and every file
    compared back byte-for-byte (inputs trivially identical, derived
    outputs must round-trip)."""
    import filecmp
    import shutil

    from dlbb_tpu.cli import main as cli_main

    stats_copy = tmp_path / "stats"
    par_copy = tmp_path / "results" / "parallelism"
    shutil.copytree(REPO / "stats", stats_copy)
    shutil.copytree(REPO / "results" / "parallelism", par_copy)

    rc = cli_main([
        "reports",
        "--stats", str(stats_copy),
        "--results", str(tmp_path / "results"),
    ])
    assert rc == 0

    mismatches = []
    for f in sorted(stats_copy.rglob("*")):
        if not f.is_file():
            continue
        committed = REPO / "stats" / f.relative_to(stats_copy)
        if not committed.is_file():
            mismatches.append(f"{f.relative_to(stats_copy)}: new file")
        elif not filecmp.cmp(f, committed, shallow=False):
            mismatches.append(f"{f.relative_to(stats_copy)}: differs")
    assert not mismatches, mismatches


def _load_baselines():
    """Import publish_baselines (guarded main; import is side-effect
    free on the simulated mesh)."""
    spec = importlib.util.spec_from_file_location(
        "publish_baselines", REPO / "scripts" / "publish_baselines.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_tuning_grid_dedups_full_grid_variants():
    """ADVICE r5: the reduced tuning grid must not re-run VARIANTS_3D
    members at the full-grid stage's rank counts (same output dirs,
    different max_global_bytes -> --fresh artifacts for shared cells
    would be order-dependent).  Rank counts the full-grid stage does NOT
    cover (ring @ 16) are kept, and member order is the deterministic
    input order."""
    mod = _load_baselines()
    members = mod._tuning_grid_members(mod.EXECUTABLE_VARIANTS, (4, 8))
    names = [n for n, _ in members]
    # no full-grid variant re-measured at full-grid rank counts
    assert not set(names) & set(mod.VARIANTS_3D), names
    # "default" excluded, order deterministic (input order)
    assert "default" not in names
    expected = [n for n in mod.EXECUTABLE_VARIANTS
                if n != "default" and n not in mod.VARIANTS_3D]
    assert names == expected
    # every surviving member sweeps the full requested rank tuple
    assert all(ranks == (4, 8) for _, ranks in members)
    # the 16-rank rung keeps ring: stage_variants3d only covers (4, 8)
    members16 = mod._tuning_grid_members(mod.VARIANTS_16, (16,))
    assert ("ring", (16,)) in members16


def test_cp_time_skip_reason_wording():
    """ADVICE r5: the skipped_estimated_time reason must say the measured
    S axis ends at 16384 and S=32768 is boundary-documented only — not
    point readers at an sp allowance that produced no measurement."""
    mod = _load_baselines()
    reason = mod._cp_time_skip_reason(32768, (8,))
    assert "boundary-documented only" in reason
    assert "measured S axis ends at 16384" in reason
    assert "to carry the S axis" not in reason


def test_cp_scaling_report_wording(tmp_path):
    """The CP_SCALING.md prose must match: no claim that an sp degree
    'carries the S axis' at S=32768 (that cell is the rendezvous-timeout
    infeasible cell; all Ulysses S=32768 cells are footprint-capped)."""
    from dlbb_tpu.stats.parallelism_report import write_cp_scaling_report

    write_cp_scaling_report(tmp_path / "empty", tmp_path / "out")
    md = (tmp_path / "out" / "CP_SCALING.md").read_text()
    assert "boundary-documented only" in md
    assert "carries the S axis" not in md
    assert "ends at S=16384" in md
