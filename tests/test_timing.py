"""Timing-honesty unit tests (SURVEY §7 "timing semantics under async
dispatch"; VERDICT r1 weak #4/#5).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlbb_tpu.utils.timing import (
    per_iter_plausible,
    resolve_timing_mode,
    single_iteration_estimate,
    time_collective,
    time_fn_chained,
)


def test_per_iter_plausible_decision():
    # sync backend: block time ~= forced time
    assert per_iter_plausible(0.050, 0.055)
    # enqueue-only block: 0.5 ms "measured" vs 100 ms true completion
    assert not per_iter_plausible(0.0005, 0.100)
    # below the floor: dispatch noise ~ probe — trust per-iter
    assert per_iter_plausible(0.0001, 0.005)
    # boundary: exactly ratio * forced passes
    assert per_iter_plausible(0.2 * 0.100, 0.100)


def test_single_iteration_estimate_cpu(devices):
    """On a sync backend the forced-completion estimate matches a directly
    measured iteration to within noise."""
    x = jnp.ones((512, 512))
    f = jax.jit(lambda a: a @ a)
    est = single_iteration_estimate(f, x, trials=3)
    assert est >= 0.0
    import time

    jax.block_until_ready(f(x))
    t0 = time.perf_counter()
    jax.block_until_ready(f(x))
    direct = time.perf_counter() - t0
    # same order of magnitude (generous: single-core box under load)
    assert est < direct * 10 + 0.01


def test_time_collective_cpu_sanity_passes(devices):
    """per_iter mode on the sync CPU backend must not trip the plausibility
    floor; the forced-completion figure is recorded."""
    x = jnp.ones((256, 256))
    f = jax.jit(lambda a: a @ a)
    with np.errstate(all="ignore"):
        timings, meta = time_collective(f, x, warmup=2, iterations=5)
    assert meta["timing_mode"] == "per_iter"
    assert "per_iter_sanity_failed" not in meta
    assert meta["forced_completion_s"] >= 0.0
    assert len(timings) == 5


def test_chained_meta_has_percentile_caveat(devices):
    """Chunked samples are chunk means — the result metadata must say so
    (VERDICT r1 weak #4: percentiles over chunk means, not tails)."""
    x = jnp.ones((64, 64))
    f = jax.jit(lambda a: a @ a)
    samples, meta, carry = time_fn_chained(f, x, warmup=1, iterations=10,
                                           chunk_size=5)
    assert "chunk means" in meta["percentile_caveat"]
    # x was donated; the returned carry is live and has the input's shape
    assert carry.shape == (64, 64)
    assert meta["timing_mode"] == "chained"
    assert len(samples) == 2


def test_chained_max_seconds_clamps_chunks(devices):
    """The wall-time budget applies in chained mode too (review finding):
    chunk count shrinks and the clamp is recorded."""
    x = jnp.ones((512, 512))
    f = jax.jit(lambda a: a @ a)
    samples, meta, _ = time_fn_chained(
        f, x, warmup=1, iterations=10_000, chunk_size=10,
        max_seconds=0.02,
    )
    assert meta["time_budget_clamped"] is True
    assert meta["chunks"] == len(samples)
    assert meta["measurement_iterations"] == meta["chunks"] * 10
    assert meta["chunks"] < 1000


def test_resolve_timing_mode_env(monkeypatch):
    monkeypatch.setenv("DLBB_TIMING_MODE", "chained")
    assert resolve_timing_mode("auto") == "chained"
    monkeypatch.delenv("DLBB_TIMING_MODE")
    assert resolve_timing_mode("per_iter") == "per_iter"
