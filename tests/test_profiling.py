"""Tracing/profiling subsystem tests (XLA-profiler analogue of the
reference's CCL_LOG_LEVEL / I_MPI_DEBUG env tracing, SURVEY §5.1)."""

import os

import jax
import jax.numpy as jnp

from dlbb_tpu.utils.profiling import (
    annotate,
    default_trace_dir,
    maybe_trace,
    step_annotation,
)


def _xplane_files(root):
    return [
        os.path.join(dirpath, f)
        for dirpath, _, files in os.walk(root)
        for f in files
        if f.endswith(".xplane.pb")
    ]


def test_maybe_trace_writes_xplane(devices, tmp_path):
    trace_dir = str(tmp_path / "trace")
    with maybe_trace(trace_dir) as resolved:
        assert resolved == trace_dir
        with annotate("measure"):
            for i in range(2):
                with step_annotation("step", i):
                    y = jax.jit(lambda x: x @ x)(jnp.ones((64, 64)))
                    jax.block_until_ready(y)
    assert _xplane_files(trace_dir), "no xplane trace emitted"


def test_maybe_trace_noop_without_dir(devices, tmp_path, monkeypatch):
    monkeypatch.delenv("DLBB_TRACE_DIR", raising=False)
    assert default_trace_dir() is None
    with maybe_trace(None) as resolved:
        assert resolved is None
    assert list(tmp_path.iterdir()) == []


def test_maybe_trace_env_default(devices, tmp_path, monkeypatch):
    trace_dir = str(tmp_path / "envtrace")
    monkeypatch.setenv("DLBB_TRACE_DIR", trace_dir)
    with maybe_trace(None) as resolved:
        assert resolved == trace_dir
        jax.block_until_ready(jnp.ones((8, 8)) * 2)
    assert _xplane_files(trace_dir)


def test_cli_train_with_trace(devices, tmp_path):
    """--trace on the CLI wraps the whole run and emits a trace."""
    import yaml

    from dlbb_tpu.cli import main

    cfg = {
        "experiment": {"name": "trace_smoke"},
        "model": {
            "hidden_size": 32, "num_layers": 1, "num_heads": 2,
            "ffn_intermediate": 64, "attention": "full", "dtype": "float32",
        },
        "parallelism": {"world_size": 2, "data_parallel": 2},
        "input": {"batch_size": 4, "sequence_length": 8, "seed": 42},
        "execution": {"warmup_iterations": 1, "benchmark_iterations": 2},
        "training": {"learning_rate": 1e-2},
    }
    cfg_path = tmp_path / "cfg.yaml"
    cfg_path.write_text(yaml.safe_dump(cfg))
    trace_dir = str(tmp_path / "clitrace")
    rc = main([
        "train", "--config", str(cfg_path), "--trace", trace_dir,
        "--output", str(tmp_path / "out"),
    ])
    assert rc == 0
    assert _xplane_files(trace_dir)
