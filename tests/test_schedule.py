"""Pipelined sweep engine tests (``dlbb_tpu.bench.schedule``).

Tier-1 guarantees for the compile-ahead scheduler: dedup keys never
collide across variants, one poisoned work unit skips its configs while
the pipeline drains, serial (``--no-pipeline``) and pipelined runs produce
identical result-JSON schemas, and the payload cache never hands out a
donated (deleted) array.
"""

import json
import threading

import pytest

from dlbb_tpu.bench import Sweep1D, run_sweep
from dlbb_tpu.bench.schedule import (
    CompileAheadScheduler,
    PayloadCache,
    WorkUnit,
    configure_compilation_cache,
    work_unit_key,
)
from dlbb_tpu.comm.mesh import MeshSpec, get_mesh
from dlbb_tpu.comm.ops import OPERATIONS, CollectiveOp, get_op, payload_aval


def _key(variant="default", op="allreduce", n=256, mode="per_iter",
         options=None, mesh=None):
    mesh = mesh if mesh is not None else get_mesh(MeshSpec.ring(4))
    axes = ("ranks",)
    aval = payload_aval(get_op(op), mesh, axes, n)
    return work_unit_key(get_op(op), variant, mesh, axes, 0, aval, mode,
                         100, options)


def test_work_unit_key_identity_and_variant_collision(devices):
    """Equal build parameters intern to one key; the same payload shape
    under a DIFFERENT variant (hierarchical vs joint reduction compiles a
    different program) must never share a cache entry."""
    assert _key() == _key()
    assert _key(variant="default") != _key(variant="hier2x2x2")
    assert _key(op="allreduce") != _key(op="broadcast")
    assert _key(n=256) != _key(n=512)
    assert _key(mode="per_iter") != _key(mode="chained")
    assert _key(options=None) != _key(options={"xla_foo": "1"})


def test_work_unit_key_mesh_identity(devices):
    """Same shape on a different device subset is a different program."""
    m4 = get_mesh(MeshSpec.ring(4))
    m4b = get_mesh(MeshSpec.ring(4), devices=list(reversed(devices))[:4])
    assert _key(mesh=m4) != _key(mesh=m4b)
    # and the mesh cache returns the SAME object for the same request
    assert get_mesh(MeshSpec.ring(4)) is m4


def _tiny(tmp_path, **kw):
    defaults = dict(
        implementation="xla_test",
        operations=("allreduce", "broadcast"),
        data_sizes=(("1KB", 256),),
        rank_counts=(4,),
        dtype="float32",
        warmup_iterations=1,
        measurement_iterations=3,
        output_dir=str(tmp_path / "results"),
        compile_cache=str(tmp_path / "xla_cache"),
        # exercise the compile-ahead thread regardless of the host-auto
        # default (schedule.default_pipeline is core-count dependent)
        pipeline=True,
    )
    defaults.update(kw)
    return Sweep1D(**defaults)


def test_serial_and_pipelined_results_equivalent(tmp_path, devices):
    """--no-pipeline and the pipelined engine must emit the same artifact
    set with the same schema and identical non-timing fields."""
    fp = run_sweep(_tiny(tmp_path, output_dir=str(tmp_path / "pipe")),
                   verbose=False)
    fs = run_sweep(_tiny(tmp_path, output_dir=str(tmp_path / "serial"),
                         pipeline=False), verbose=False)
    assert [p.name for p in fp] == [p.name for p in fs]
    for pp, ps in zip(fp, fs):
        dp, ds = json.loads(pp.read_text()), json.loads(ps.read_text())
        assert sorted(dp) == sorted(ds)
        for k in ("implementation", "operation", "num_ranks",
                  "num_elements", "dtype", "timing_mode", "mesh_shape"):
            assert dp[k] == ds[k], k
        for d in (dp, ds):
            assert d["compile_seconds"] >= 0.0
            assert isinstance(d["compile_cache_hit"], bool)
    manifests = [
        json.loads((tmp_path / d / "sweep_manifest.json").read_text())
        for d in ("pipe", "serial")
    ]
    assert manifests[0]["pipeline"] is True
    assert manifests[1]["pipeline"] is False
    assert all(m["configs"]["measured"] == 2 for m in manifests)


def test_compile_failure_contained_pipeline_drains(tmp_path, devices,
                                                   monkeypatch):
    """A work unit whose build raises skips its configs but the pipeline
    drains: later configs still measure and the manifest records the
    failure."""
    def boom_build(mesh, axes, root=0):
        raise RuntimeError("poisoned work unit")

    monkeypatch.setitem(
        OPERATIONS, "boom",
        CollectiveOp("boom", "per_rank", "per_rank", boom_build),
    )
    files = run_sweep(
        _tiny(tmp_path, operations=("boom", "allreduce", "broadcast")),
        verbose=False,
    )
    names = sorted(p.name for p in files)
    assert names == [
        "xla_test_allreduce_ranks4_1KB_fp32.json",
        "xla_test_broadcast_ranks4_1KB_fp32.json",
    ]
    man = json.loads(
        (tmp_path / "results" / "sweep_manifest.json").read_text()
    )
    assert man["configs"]["failed"] == 1
    assert man["configs"]["measured"] == 2
    assert man["work_units"]["compile_failed"] == 1


def test_planning_failure_contained(tmp_path, devices):
    """A config that cannot even be PLANNED (unknown op) is skipped like a
    measurement failure: the rest of the sweep proceeds and the cache
    scoping still unwinds.  The memory cap is set because its estimator
    also resolves the op name — containment must cover that path too (a
    publisher stage always sets max_global_bytes)."""
    files = run_sweep(
        _tiny(tmp_path, operations=("nosuchop", "allreduce"),
              max_global_bytes=1 << 30),
        verbose=False,
    )
    assert [p.name for p in files] == [
        "xla_test_allreduce_ranks4_1KB_fp32.json"
    ]
    man = json.loads(
        (tmp_path / "results" / "sweep_manifest.json").read_text()
    )
    assert man["configs"]["failed"] == 1
    assert man["configs"]["measured"] == 1


def test_chained_mode_through_engine(tmp_path, devices):
    """timing_mode=chained AOT-compiles the donating timing loop; results
    keep chained-mode metadata and the donated payload is never reused."""
    files = run_sweep(
        _tiny(tmp_path, operations=("allreduce", "reduce"),
              timing_mode="chained"),
        verbose=False,
    )
    assert len(files) == 2
    for f in files:
        d = json.loads(f.read_text())
        assert d["timing_mode"] == "chained"
        assert "chunk_size" in d
        assert "compile_seconds" in d and "compile_cache_hit" in d


def test_warm_persistent_cache_hits(tmp_path, devices):
    """A second sweep over the same grid (fresh jit objects, same
    programs) deserialises from the persistent cache: every artifact
    reports a compile-cache hit."""
    kw = dict(compile_cache=str(tmp_path / "shared_cache"))
    run_sweep(_tiny(tmp_path, output_dir=str(tmp_path / "cold"), **kw),
              verbose=False)
    warm = run_sweep(_tiny(tmp_path, output_dir=str(tmp_path / "warm"), **kw),
                     verbose=False)
    assert warm
    for f in warm:
        assert json.loads(f.read_text())["compile_cache_hit"] is True
    man = json.loads((tmp_path / "warm" / "sweep_manifest.json").read_text())
    assert man["compile_cache"]["persistent_hits"] == 2
    assert man["compile_cache"]["persistent_misses"] == 0


def test_default_pipeline_env_overrides(monkeypatch):
    from dlbb_tpu.bench.schedule import default_pipeline

    monkeypatch.setenv("DLBB_SWEEP_PIPELINE", "1")
    assert default_pipeline() is True
    monkeypatch.setenv("DLBB_SWEEP_PIPELINE", "off")
    assert default_pipeline() is False
    monkeypatch.delenv("DLBB_SWEEP_PIPELINE")
    monkeypatch.setenv("DLBB_COMPILE_OVERLAP", "1")
    assert default_pipeline() is True
    monkeypatch.delenv("DLBB_COMPILE_OVERLAP")
    # unforced: purely a core-count policy
    import os

    assert default_pipeline() is ((os.cpu_count() or 1) >= 4)


def test_cache_scope_restores_prior_config(tmp_path):
    """A cache dir the CALLER configured before the sweep survives the
    sweep's cache scoping — deactivation restores it instead of
    clobbering it to disabled."""
    import jax

    from dlbb_tpu.bench import schedule

    prior = str(tmp_path / "user_cache")
    jax.config.update("jax_compilation_cache_dir", prior)
    try:
        schedule.configure_compilation_cache(str(tmp_path / "sweep_cache"))
        assert jax.config.jax_compilation_cache_dir == str(
            tmp_path / "sweep_cache")
        schedule.deactivate_compilation_cache()
        assert jax.config.jax_compilation_cache_dir == prior
    finally:
        jax.config.update("jax_compilation_cache_dir", None)
        schedule.deactivate_compilation_cache()


def test_configure_compilation_cache_off(monkeypatch, tmp_path):
    for value in ("off", "0", "none", ""):
        monkeypatch.setenv("DLBB_XLA_CACHE", value)
        assert configure_compilation_cache("auto") is None
    monkeypatch.delenv("DLBB_XLA_CACHE")
    d = tmp_path / "explicit"
    assert configure_compilation_cache(str(d)) == str(d)
    assert d.is_dir()
    assert configure_compilation_cache(None) is None


def test_scheduler_dedup_and_drain():
    """Each unit compiles exactly once however many configs consume it,
    and a failing build never wedges the worker."""
    compiles = []

    def make_build(name, fail=False):
        def build():
            compiles.append(name)
            if fail:
                raise ValueError(f"{name} failed")
            return (lambda x: x), (lambda x: x)
        return build

    units = [
        WorkUnit(key=("a",), build=make_build("a")),
        WorkUnit(key=("b",), build=make_build("b", fail=True)),
        WorkUnit(key=("c",), build=make_build("c")),
    ]
    sched = CompileAheadScheduler(units, prefetch=1, pipeline=True)
    sched.start()
    # consume unit a twice (two configs sharing it), then b, then c
    for u in (units[0], units[0], units[1], units[2]):
        sched.get(u)
    sched.close()
    assert compiles == ["a", "b", "c"]  # once each, in order
    assert units[0].error is None and units[0].consumers == 2
    assert isinstance(units[1].error, ValueError)
    assert units[2].error is None


def test_scheduler_serial_mode_compiles_inline():
    built = threading.Event()
    unit = WorkUnit(
        key=("x",),
        build=lambda: (built.set() or ((lambda x: x), (lambda x: x))),
    )
    sched = CompileAheadScheduler([unit], pipeline=False)
    sched.start()  # no thread in serial mode
    assert not built.is_set()
    got = sched.get(unit)
    assert built.is_set() and got.error is None
    sched.close()


def test_payload_cache_lru_and_invalidate():
    class FakeArr:
        def __init__(self, nbytes):
            self.nbytes = nbytes

    cache = PayloadCache(max_bytes=100)
    a = cache.get(("a",), lambda: FakeArr(40))
    assert cache.get(("a",), lambda: FakeArr(999)) is a  # hit, no rebuild
    cache.get(("b",), lambda: FakeArr(40))
    cache.get(("c",), lambda: FakeArr(40))  # evicts LRU ("a")
    assert cache.evictions == 1
    assert cache.get(("a",), lambda: FakeArr(40)) is not a  # rebuilt
    # oversized payloads pass through uncached
    big = cache.get(("big",), lambda: FakeArr(1000))
    assert cache.get(("big",), lambda: FakeArr(1000)) is not big
    # donated entries are dropped so a deleted array is never handed out
    cache.invalidate(("a",))
    fresh = cache.get(("a",), lambda: FakeArr(40))
    assert isinstance(fresh, FakeArr)
    stats = cache.stats()
    assert stats["budget_bytes"] == 100
    assert stats["hits"] >= 1 and stats["misses"] >= 4
