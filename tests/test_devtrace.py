"""Device-trace analysis tests (``dlbb_tpu/obs/devtrace.py``).

Unit surface: op-kind bucket classification, warmup-window exclusion,
the fail-closed contract (missing/truncated/empty captures are explicit
findings, never silent empty reports), the static-vs-measured overlap
gate (a seeded serialized-ring fixture on a demonstrably-concurrent
runtime exits 1 with ``runtime-serialized-collective``; a single-stream
runtime downgrades to a warning), the corpus op-sample extraction, and
a β-identified fit on a synthetic device-op corpus recovering known
coefficients.

The ``devtrace_smoke`` marker test drives the whole pipeline through a
real captured mini-sweep on the simulated mesh: captured stats stay
equivalent to an uncaptured run, ``obs devtrace`` is green, and the
report lists measured overlap efficiency beside the committed static
value for the overlap-proof target.
"""

import gzip
import json
from pathlib import Path

import pytest

from dlbb_tpu.analysis.findings import EXIT_CLEAN, EXIT_FINDINGS
from dlbb_tpu.obs.devtrace import (
    CaptureError,
    analyze_capture,
    analyze_run,
    audit_target_name,
    bucket_of,
    parse_capture,
    run_devtrace,
)

REPO = Path(__file__).resolve().parent.parent
GOLDEN = REPO / "tests" / "data" / "golden_capture"
BASELINES = REPO / "stats" / "analysis" / "baselines"


def _dev(name, ts, dur, tid=1, pid=7):
    return {"ph": "X", "pid": pid, "tid": tid, "ts": float(ts),
            "dur": float(dur), "name": name,
            "args": {"hlo_module": "jit_f", "hlo_op": name}}


def _annot(name, ts, dur, tid=99, pid=7):
    short = name.rsplit(":", 1)[-1]
    return {"ph": "X", "pid": pid, "tid": tid, "ts": float(ts),
            "dur": float(dur), "name": short,
            "args": {"long_name": name}}


def _write_capture(directory: Path, events) -> Path:
    d = directory / "plugins" / "profile" / "run"
    d.mkdir(parents=True, exist_ok=True)
    path = d / "perfetto_trace.json.gz"
    with gzip.open(path, "wt") as f:
        json.dump({"displayTimeUnit": "ms", "traceEvents": events}, f)
    return path


def _result_json(tmp_path: Path, trace_dir: Path, *,
                 op="ag_matmul", variant="overlap_ring",
                 name="xla_tpu_fixture.json") -> Path:
    data = {
        "implementation": "xla_tpu",
        "operation": op,
        "variant": variant,
        "num_ranks": 8,
        "num_elements": 4096,
        "dtype": "float32",
        "timings": [[0.001, 0.001]],
        "timing_mode": "per_iter",
        "system_info": {"backend": "cpu", "platform": "linux",
                        "cpu_count": 2, "num_devices": 8},
        "device_trace": {
            "schema": "dlbb_device_capture_v1",
            "label": name.rsplit(".", 1)[0],
            "trace_dir": str(trace_dir),
            "profile_reps": 1,
            "excluded_from_stats": True,
        },
    }
    path = tmp_path / name
    path.write_text(json.dumps(data))
    return path


# ---------------------------------------------------------------------------
# bucket classification
# ---------------------------------------------------------------------------


def test_bucket_classification():
    assert bucket_of("all-reduce.2") == "collective"
    assert bucket_of("all-gather-start.1") == "collective"
    assert bucket_of("reduce-scatter.7") == "collective"
    assert bucket_of("all-to-all") == "collective"
    assert bucket_of("collective-permute.21") == "permute"
    assert bucket_of("collective-permute-done.3") == "permute"
    assert bucket_of("dot.39") == "dot"
    assert bucket_of("convolution.1") == "dot"
    assert bucket_of("broadcast_multiply_fusion") == "fusion"
    assert bucket_of("convert_bitcast_fusion.5.clone") == "fusion"
    assert bucket_of("convert.12") == "other"
    assert bucket_of("partition-id.7") == "other"


def test_audit_target_name_matches_committed_baselines():
    """The (op, variant) -> audit-target mapping must produce names the
    committed schedule baselines actually use — the static join breaks
    silently otherwise."""
    from dlbb_tpu.analysis.schedule_audit import baseline_path

    for op, variant in (("allreduce", "default"),
                        ("allgather", "default"),
                        ("ag_matmul", "overlap_ring"),
                        ("ag_matmul", "overlap_bidir"),
                        ("matmul_rs", "overlap_ring"),
                        ("allreduce_q", "compress_int8"),
                        ("reducescatter_q", "compress_fp8")):
        target = audit_target_name(op, variant)
        assert baseline_path(BASELINES, target).exists(), (op, variant,
                                                          target)


# ---------------------------------------------------------------------------
# parsing: golden capture, warmup exclusion, fail-closed
# ---------------------------------------------------------------------------


def test_parse_golden_capture():
    """The committed golden capture (a real sim-mesh allreduce capture,
    host noise stripped) parses into 8 devices x one all-reduce each,
    keyed by the HLO instruction name."""
    from dlbb_tpu.obs.capture import perfetto_trace_files

    trace = perfetto_trace_files(GOLDEN / "trace")
    assert trace, "golden capture fixture missing"
    timeline = parse_capture(trace[0])
    assert len(timeline["devices"]) == 8
    analysis = analyze_capture(timeline)
    by_name = {r["name"]: r for r in analysis["per_op"]}
    assert by_name["all-reduce.2"]["count"] == 8
    assert by_name["all-reduce.2"]["bucket"] == "collective"
    assert analysis["comm_events"] == 8
    assert analysis["buckets_us"]["collective"] > 0
    # the join key is the HLO instruction name — exactly what the
    # hlo_audit inventory records per instruction
    assert all("." in n or "fusion" in n or n.isidentifier()
               for n in by_name)


def test_warmup_exclusion(tmp_path):
    """Device events inside a ``warmup`` annotation window are dropped;
    with ``measure``/``profile_rep`` windows present, only in-window
    events are kept."""
    events = [
        _annot("warmup", 0, 100),
        _annot("measure", 200, 100),
        _dev("all-reduce.1", 10, 50, tid=1),    # inside warmup: dropped
        _dev("all-reduce.1", 220, 50, tid=1),   # inside measure: kept
        _dev("all-reduce.1", 400, 50, tid=1),   # outside both: dropped
    ]
    path = _write_capture(tmp_path, events)
    timeline = parse_capture(path)
    assert timeline["device_events"] == 1
    assert timeline["excluded_warmup"] == 2
    analysis = analyze_capture(timeline)
    assert analysis["comm_events"] == 1
    assert analysis["comm_total_us"] == 50.0


def test_profile_rep_window_selects(tmp_path):
    events = [
        _annot("profile_rep:cfg", 100, 200),
        _dev("all-gather.1", 150, 20),
        _dev("all-gather.1", 500, 20),  # outside the rep window
    ]
    timeline = parse_capture(_write_capture(tmp_path, events))
    assert timeline["device_events"] == 1


def test_container_thunks_not_double_counted(tmp_path):
    """``call`` wraps a computation whose fusions appear as their own
    events — counting both would double-charge the fusion bucket."""
    events = [
        _dev("call.3", 0, 100),
        _dev("convert_fusion.1", 1, 98),
        _dev("all-reduce.1", 200, 10),
    ]
    analysis = analyze_capture(parse_capture(_write_capture(tmp_path,
                                                            events)))
    assert analysis["buckets_us"]["fusion"] == 98.0
    assert all(r["name"] != "call.3" for r in analysis["per_op"])


def test_async_pair_counts_one_collective_done_never_serialized(tmp_path):
    """An async collective lowers to a ``-start``/``-done`` pair: the
    wait time charges the collective bucket, but the pair is ONE
    logical instruction (α's analytic convention) and the often
    zero-length ``-done`` must not classify as a serialized hop."""
    from dlbb_tpu.obs.devtrace import device_comm_samples

    events = [
        _dev("all-gather-start.1", 0, 100),
        _dev("all-gather-done.1", 100, 0),
        _dev("dot.1", 10, 50),
    ]
    timeline = parse_capture(_write_capture(tmp_path, events))
    analysis = analyze_capture(timeline)
    assert analysis["comm_total_us"] == 100.0  # both halves' time
    assert analysis["comm_events"] == 1  # one logical hop
    assert analysis["comm_serialized_events"] == 0
    assert analysis["comm_straddled_events"] == 1
    comm = device_comm_samples(timeline)
    assert comm["comm_instructions"] == 1


def test_capture_resolves_from_foreign_cwd(tmp_path):
    """Relative ``trace_dir`` records from a run launched in another
    cwd resolve through the run directory's capture subdir."""
    label = "xla_tpu_fixture"
    _write_capture(tmp_path / "captures" / label,
                   [_dev("all-gather.1", 0, 10)])
    _result_json(tmp_path,
                 Path("who/knows/where") / "captures" / label)
    report, findings = analyze_run(tmp_path, BASELINES)
    assert not any(f.rule in ("capture-missing", "no-captures")
                   for f in findings)
    assert report["captures"][0]["device_events"] == 1


def test_missing_capture_fail_closed(tmp_path):
    with pytest.raises(CaptureError):
        parse_capture(tmp_path / "nope.json.gz")


def test_truncated_capture_fail_closed(tmp_path):
    path = tmp_path / "perfetto_trace.json.gz"
    good = gzip.compress(json.dumps(
        {"traceEvents": [_dev("all-reduce.1", 0, 1)]}).encode())
    path.write_bytes(good[: len(good) // 2])  # torn mid-write
    with pytest.raises(CaptureError, match="truncated|unparseable"):
        parse_capture(path)


def test_empty_capture_fail_closed(tmp_path):
    path = tmp_path / "perfetto_trace.json.gz"
    with gzip.open(path, "wt") as f:
        json.dump({"traceEvents": [
            {"ph": "M", "pid": 1, "tid": 0, "name": "process_name",
             "args": {"name": "/host:CPU"}},
        ]}, f)
    with pytest.raises(CaptureError, match="no device events"):
        parse_capture(path)


def test_run_with_no_captures_is_error(tmp_path):
    (tmp_path / "unrelated.json").write_text("{}")
    report, findings = analyze_run(tmp_path, BASELINES)
    assert [f.rule for f in findings] == ["no-captures"]
    assert findings[0].severity == "error"
    assert report["captures"] == []


def test_recorded_capture_missing_on_disk_is_error(tmp_path):
    _result_json(tmp_path, tmp_path / "deleted_dir")
    _report, findings = analyze_run(tmp_path, BASELINES)
    rules = {f.rule for f in findings}
    assert "capture-missing" in rules
    # no parseable capture at all -> the run-level fail-closed finding
    assert "no-captures" in rules


def test_run_time_contained_failure_surfaces_as_warning(tmp_path):
    path = _result_json(tmp_path, tmp_path / "dev")
    data = json.loads(path.read_text())
    data["device_trace"]["error"] = "RuntimeError: profiler held"
    data["device_trace"]["error_kind"] = "RuntimeError"
    path.write_text(json.dumps(data))
    _report, findings = analyze_run(tmp_path, BASELINES)
    by_rule = {f.rule: f for f in findings}
    assert by_rule["capture-failed"].severity == "warning"


# ---------------------------------------------------------------------------
# the static-vs-measured overlap gate
# ---------------------------------------------------------------------------


def _ring_events(*, concurrent: bool):
    """Eight serialized ring-hop permutes on device lane 1 (no
    straddling compute there), plus compute on lane 2 — overlapping
    (proving the runtime CAN run thunks concurrently) or sequential
    (single-stream)."""
    events = [_dev(f"collective-permute.{i}", i * 100, 90, tid=1)
              for i in range(8)]
    if concurrent:
        events += [_dev("dot_fusion.1", 0, 60, tid=2),
                   _dev("dot_fusion.2", 30, 60, tid=2)]
    else:
        events += [_dev("dot_fusion.1", 0, 30, tid=2),
                   _dev("dot_fusion.2", 40, 30, tid=2)]
    return events


def test_serialized_ring_on_concurrent_runtime_exits_one(tmp_path):
    """THE acceptance fixture: the committed static baseline proves the
    ring hidden (overlap_efficiency 0.87), the measured timeline shows
    every hop serialized, and the capture demonstrates the runtime can
    overlap — a ``runtime-serialized-collective`` ERROR, exit 1."""
    from dlbb_tpu.obs import run_obs

    cap_dir = tmp_path / "cap"
    _write_capture(cap_dir, _ring_events(concurrent=True))
    _result_json(tmp_path, cap_dir)
    report, findings = analyze_run(tmp_path, BASELINES)
    f = next(f for f in findings
             if f.rule == "runtime-serialized-collective")
    assert f.severity == "error"
    assert f.details["static_overlap_efficiency"] > 0
    assert f.details["serialized_events"] == 8
    assert f.details["runtime_concurrent"] is True
    # measured sits beside static in the report row
    row = next(c for c in report["captures"] if "error" not in c)
    assert row["static"]["overlap_efficiency"] > 0
    assert row["measured_overlap_efficiency"] == 0.0
    rc = run_obs("devtrace", journal=str(tmp_path),
                 output=str(tmp_path / "out"),
                 baselines=str(BASELINES), verbose=False)
    assert rc == EXIT_FINDINGS


def test_serialized_ring_on_single_stream_runtime_warns(tmp_path):
    """The cpu-sim reality: no thunk concurrency anywhere in the
    capture means hop hiding is unobservable, not disproved — the gate
    downgrades to a warning and CI stays green."""
    from dlbb_tpu.obs import run_obs

    cap_dir = tmp_path / "cap"
    _write_capture(cap_dir, _ring_events(concurrent=False))
    _result_json(tmp_path, cap_dir)
    _report, findings = analyze_run(tmp_path, BASELINES)
    f = next(f for f in findings
             if f.rule == "runtime-serialized-collective")
    assert f.severity == "warning"
    rc = run_obs("devtrace", journal=str(tmp_path),
                 output=str(tmp_path / "out"),
                 baselines=str(BASELINES), verbose=False)
    assert rc == EXIT_CLEAN


def test_hidden_ring_passes_gate(tmp_path):
    """Hops with straddling compute occupancy on their own device do
    NOT trip the gate, and measured overlap efficiency is positive."""
    events = []
    for i in range(4):
        events.append(_dev(f"collective-permute.{i}", i * 100, 80,
                           tid=1))
        events.append(_dev(f"dot_fusion.{i}", i * 100 + 10, 60, tid=1))
    cap_dir = tmp_path / "cap"
    _write_capture(cap_dir, events)
    _result_json(tmp_path, cap_dir)
    report, findings = analyze_run(tmp_path, BASELINES)
    assert not [f for f in findings
                if f.rule == "runtime-serialized-collective"]
    row = next(c for c in report["captures"] if "error" not in c)
    assert row["measured_overlap_efficiency"] > 0.5
    assert row["runtime_concurrent"] is True


def test_qring_exempt_from_gate(tmp_path):
    """Quantised-ring ops are deliberately sequential — exempt exactly
    as in the static auditor."""
    cap_dir = tmp_path / "cap"
    _write_capture(cap_dir, _ring_events(concurrent=True))
    _result_json(tmp_path, cap_dir, op="allreduce_q",
                 variant="compress_int8")
    _report, findings = analyze_run(tmp_path, BASELINES)
    assert not [f for f in findings
                if f.rule == "runtime-serialized-collective"]


# ---------------------------------------------------------------------------
# corpus op-sample extraction + β-identified fit
# ---------------------------------------------------------------------------


def test_golden_capture_op_sample_extraction(tmp_path):
    """devtrace on the committed golden capture emits a corpus fit row
    (device-timed: dispatches 0, flops 0, analytic wire joined from the
    artifact), and ``build_corpus`` ingests the written report as the
    ``devtrace`` source."""
    from dlbb_tpu.obs.corpus import build_corpus

    report, findings = run_devtrace(GOLDEN, out_dir=tmp_path,
                                    baselines_dir=BASELINES,
                                    verbose=False)
    assert not [f for f in findings if f.severity == "error"]
    assert len(report["op_samples"]) == 1
    s = report["op_samples"][0]
    assert s["op"] == "allreduce"
    assert s["source"] == "devtrace"
    assert s["dispatches"] == 0.0
    assert s["flops"] == 0
    # analytic ring wire of a 256-elem f32 allreduce on 8 ranks
    assert s["wire_bytes"] == 896
    assert s["collectives"] == 1.0
    assert s["measured_median_us"] > 0
    corpus = build_corpus([tmp_path / "golden_capture.json"])
    assert len(corpus["samples"]) == 1
    assert corpus["samples"][0]["source"] == "devtrace"
    assert corpus["samples"][0]["tier"] == "cpu-sim"


def test_fit_identifies_beta_from_device_samples():
    """A synthetic device-op corpus generated from known coefficients
    (α = 300 µs, β = 500 B/µs) is recovered by ``fit_tier`` with β
    FITTED (confidence interval recorded, no ``pinned`` marker) — the
    identification program-scale samples alone cannot do."""
    from dlbb_tpu.obs.fit import fit_tier

    alpha, beta = 300.0, 500.0
    samples = []
    for i, wire in enumerate((1e3, 4e3, 1.6e4, 6.4e4, 2.56e5, 1.024e6,
                              4.096e6, 1.6384e7, 6.5536e7)):
        for colls in (1.0, 7.0):
            samples.append({
                "file": f"synth{i}", "source": "devtrace",
                "op": "allreduce", "variant": "default",
                "kind": "all-reduce", "ranks": 8, "dtype": "float32",
                "num_elements": int(wire // 4),
                "wire_bytes": int(wire), "flops": 0,
                "collectives": colls, "dispatches": 0.0,
                "measured_median_us": alpha * colls + wire / beta,
                "measured_p90_us": alpha * colls + wire / beta,
                "measured_p99_us": None, "iterations": 1,
                "tier": "cpu-sim", "host": "synth",
            })
    fit = fit_tier(samples, "cpu-sim")
    c = fit["coefficients"]
    assert c["beta_bytes_per_us"]["value"] == pytest.approx(beta,
                                                            rel=0.05)
    assert "pinned" not in c["beta_bytes_per_us"]
    assert "ci95" in c["beta_bytes_per_us"]
    assert c["alpha_us"]["value"] == pytest.approx(alpha, rel=0.05)
    assert fit["device_samples"] == len(samples)


def test_fit_host_filter_exempts_device_samples():
    """``host_filter`` isolates the host-runtime dispatch term; device
    rows carry none and must survive the filter (they are what
    identifies β)."""
    from dlbb_tpu.obs.fit import fit_tier

    device = []
    for i, wire in enumerate((1e3, 1e4, 1e5, 1e6, 4e6, 1.6e7)):
        device.append({
            "file": f"d{i}", "source": "devtrace", "op": "allgather",
            "variant": "default", "kind": "all-gather", "ranks": 8,
            "dtype": "float32", "num_elements": int(wire // 4),
            "wire_bytes": int(wire), "flops": 0, "collectives": 1.0,
            "dispatches": 0.0,
            "measured_median_us": 100.0 + wire / 200.0,
            "measured_p90_us": 100.0 + wire / 200.0,
            "measured_p99_us": None, "iterations": 1,
            "tier": "cpu-sim", "host": "laptop",
        })
    host = []
    for i in range(12):
        wire = 1e4 * (i + 1)
        host.append({
            "file": f"h{i}", "op": f"prog{i}", "variant": "calibration",
            "kind": "program", "ranks": 8, "dtype": None,
            "num_elements": 0, "wire_bytes": int(wire), "flops": 0,
            "collectives": 2.0 + (i % 3), "dispatches": 1.0,
            "measured_median_us": 98.5 + 100.0 * (2.0 + (i % 3))
            + wire / 200.0,
            "measured_p90_us": 0.0, "measured_p99_us": None,
            "iterations": 1, "tier": "cpu-sim", "host": "calibration",
        })
    fit = fit_tier(device + host, "cpu-sim", min_samples=12,
                   host_filter="calibration")
    # the device rows were NOT filtered out: β is fitted, not pinned
    assert fit["device_samples"] == len(device)
    assert "pinned" not in fit["coefficients"]["beta_bytes_per_us"]
    assert fit["coefficients"]["beta_bytes_per_us"]["value"] == \
        pytest.approx(200.0, rel=0.1)


# ---------------------------------------------------------------------------
# serving rows + degraded journal instants
# ---------------------------------------------------------------------------


def test_serving_capture_phase_rows(tmp_path):
    """Serving capture metas (report ``observability.device_captures``)
    parse into per-phase rows."""
    cap = tmp_path / "cap_decode"
    _write_capture(cap, [_dev("all-reduce.1", 0, 10),
                         _dev("loop_fusion.1", 20, 40)])
    report = {
        "schema": "dlbb_serving_report_v1",
        "observability": {"device_captures": [{
            "schema": "dlbb_device_capture_v1",
            "label": "serve_decode_fused_k2",
            "trace_dir": str(cap), "profile_reps": 1,
            "excluded_from_stats": True, "phase": "decode",
        }]},
    }
    (tmp_path / "serving_test.json").write_text(json.dumps(report))
    out, findings = analyze_run(tmp_path, BASELINES)
    assert not [f for f in findings if f.severity == "error"]
    row = out["captures"][0]
    assert row["kind"] == "serving"
    assert row["phase"] == "decode"
    assert row["buckets_us"]["fusion"] == 40.0


def test_journal_degraded_event_renders_labelled_instant(tmp_path):
    """PR-11 ``degraded`` journal events render as labelled,
    process-scoped instants in the reconstructed timeline — and the
    config pairing around them still works."""
    from dlbb_tpu.obs.spans import journal_to_trace

    journal = tmp_path / "sweep_journal.jsonl"
    records = [
        {"ts": 1.0, "event": "sweep-start"},
        {"ts": 1.5, "event": "degraded",
         "reason": "tpu probe failed: tunnel down"},
        {"ts": 2.0, "event": "started", "config": "cfg_a.json"},
        {"ts": 3.0, "event": "completed", "config": "cfg_a.json"},
    ]
    journal.write_text("".join(json.dumps(r) + "\n" for r in records))
    out, _n, torn = journal_to_trace(tmp_path, tmp_path / "trace.json")
    assert torn == 0
    events = json.loads(out.read_text())["traceEvents"]
    degraded = [e for e in events if e.get("cat") == "degraded"]
    assert len(degraded) == 1
    assert degraded[0]["name"] == \
        "degraded[tpu probe failed: tunnel down]"
    assert degraded[0]["ph"] == "i"
    assert degraded[0]["s"] == "p"
    # the started -> completed pairing still yields the config X span
    spans = [e for e in events if e.get("ph") == "X"]
    assert any(e["name"] == "cfg_a.json" for e in spans)


# ---------------------------------------------------------------------------
# devtrace_smoke: the real captured pipeline on the simulated mesh
# ---------------------------------------------------------------------------

_VOLATILE = {
    "timings", "timestamp", "compile_seconds", "compile_cache_hit",
    "forced_completion_s", "forced_completion_probe_skipped",
    "system_info", "device_trace",
    # load-dependent branches in utils/timing.py record different
    # metadata KEYS run to run (the >=50ms probe-skip threshold, the
    # implausible-timing chained fallback, the time-budget clamp) —
    # volatile for the same reason the timings themselves are
    "per_iter_sanity_failed", "per_iter_median_s",
    "measurement_iterations", "warmup_iterations",
    "time_budget_s", "time_budget_clamped",
}


@pytest.mark.devtrace_smoke
def test_captured_sweep_devtrace_green_and_stats_equivalent(tmp_path,
                                                            devices):
    """The CI gate: a device-captured overlap-variant mini-sweep stays
    stats-equivalent to an uncaptured run, ``obs devtrace`` on it is
    green (exit 0 — the cpu-sim single-stream downgrade), the report
    lists measured overlap efficiency beside the committed static value
    for the overlap-proof target, and the op-level fit samples are
    mined."""
    from dlbb_tpu.bench import Sweep3D, run_sweep
    from dlbb_tpu.obs import run_obs

    def sweep(out, **kw):
        return Sweep3D(
            operations=("ag_matmul",), variant="overlap_ring",
            batch_sizes=(4,), seq_lengths=(32,), hidden_dims=(64,),
            rank_counts=(8,), warmup_iterations=1,
            measurement_iterations=4, output_dir=str(tmp_path / out),
            pipeline=False, compile_cache="off", **kw,
        )

    fc = run_sweep(sweep("captured",
                         device_trace_dir=str(tmp_path / "dev")),
                   verbose=False)
    fu = run_sweep(sweep("uncaptured"), verbose=False)
    assert [p.name for p in fc] == [p.name for p in fu]
    for pc, pu in zip(fc, fu):
        dc, du = json.loads(pc.read_text()), json.loads(pu.read_text())
        assert "device_trace" in dc and "device_trace" not in du
        assert sorted(set(dc) - _VOLATILE) == sorted(set(du) - _VOLATILE)
        for k in sorted(set(dc) & set(du) - _VOLATILE):
            assert dc[k] == du[k], k
        assert dc["device_trace"]["excluded_from_stats"] is True
        # the parseable artifact the devtrace parser keys on, with the
        # xplane kept alongside and the capture cost accounted
        assert Path(dc["device_trace"]["perfetto_trace"]).exists()
        assert dc["device_trace"]["trace_bytes"] > 0
        assert dc["device_trace"]["wall_seconds"] > 0
    from dlbb_tpu.obs.capture import xplane_files

    assert xplane_files(tmp_path / "dev")

    rc = run_obs("devtrace", journal=str(tmp_path / "captured"),
                 output=str(tmp_path / "report"),
                 baselines=str(BASELINES), verbose=False)
    assert rc == EXIT_CLEAN
    report = json.loads((tmp_path / "report" / "captured.json")
                        .read_text())
    row = next(c for c in report["captures"] if "error" not in c)
    # measured overlap listed beside the committed static value for the
    # overlap-proof target (the acceptance criterion)
    assert row["static"]["target"] == "comm/ops.py::ag_matmul[ring]"
    assert row["static"]["overlap_efficiency"] > 0
    assert row["measured_overlap_efficiency"] is not None
    assert report["op_samples"], "op-level fit samples were mined"
    # the MD report renders both columns
    md = (tmp_path / "report" / "captured.md").read_text()
    assert "measured overlap" in md and "static overlap" in md
