"""Pipeline-parallelism tests: exactness of the GPipe engine vs the plain
layer scan, composition with dp/tp, and the training path (capability
extension — the reference has no PP, SURVEY §2.2)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlbb_tpu.comm.mesh import MeshSpec, build_mesh
from dlbb_tpu.compat import PARTIAL_AUTO_SHARD_MAP
from dlbb_tpu.models.configs import ModelConfig
from dlbb_tpu.models.transformer import forward, init_params, shard_params
from dlbb_tpu.parallel.pipeline import validate_pipeline
from dlbb_tpu.train.loop import run_train

# pp composed with another >1 mesh axis needs partial-auto shard_map
# (pp manual, dp/tp/ep auto), which this jaxlib's SPMD partitioner cannot
# lower (see dlbb_tpu/compat.py) — pure-pp meshes are unaffected.
needs_partial_auto = pytest.mark.skipif(
    not PARTIAL_AUTO_SHARD_MAP,
    reason="partial-auto shard_map (pp + other >1 axes) unsupported on "
           "this jaxlib (dlbb_tpu.compat.PARTIAL_AUTO_SHARD_MAP)",
)

TINY = ModelConfig(hidden_size=32, num_layers=4, num_heads=4,
                   ffn_intermediate=64, attention="full", dtype="float32")


def _x(batch=8, seq=16, hidden=32, seed=1):
    return jax.random.normal(jax.random.key(seed), (batch, seq, hidden),
                             dtype=jnp.float32)


def test_pipeline_matches_single_device(devices):
    """pp=4 pipeline output must equal the unsharded layer scan exactly."""
    params = init_params(TINY, jax.random.key(0))
    x = _x()
    y_ref = jax.jit(lambda p, x: forward(p, x, TINY))(params, x)

    mesh = build_mesh(MeshSpec.grid((4,), ("pp",)))
    params_pp = shard_params(params, mesh)
    y_pp = jax.jit(
        lambda p, x: forward(p, x, TINY, mesh=mesh)
    )(params_pp, x)
    np.testing.assert_allclose(np.asarray(y_ref), np.asarray(y_pp),
                               rtol=1e-5, atol=1e-5)


@needs_partial_auto
def test_pipeline_with_dp_tp(devices):
    """pp composes with dp and tp on a (dp=2, pp=2, tp=2) mesh."""
    params = init_params(TINY, jax.random.key(0))
    x = _x()
    y_ref = jax.jit(lambda p, x: forward(p, x, TINY))(params, x)

    mesh = build_mesh(MeshSpec.grid((2, 2, 2), ("dp", "pp", "tp")))
    params_s = shard_params(params, mesh)
    y = jax.jit(lambda p, x: forward(p, x, TINY, mesh=mesh))(params_s, x)
    np.testing.assert_allclose(np.asarray(y_ref), np.asarray(y),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_microbatch_count(devices):
    """More microbatches than stages (bubble amortisation) stays exact."""
    params = init_params(TINY, jax.random.key(0))
    x = _x()
    y_ref = jax.jit(lambda p, x: forward(p, x, TINY))(params, x)

    mesh = build_mesh(MeshSpec.grid((2,), ("pp",)))
    params_pp = shard_params(params, mesh)
    y = jax.jit(
        lambda p, x: forward(p, x, TINY, mesh=mesh, num_microbatches=8)
    )(params_pp, x)
    np.testing.assert_allclose(np.asarray(y_ref), np.asarray(y),
                               rtol=1e-5, atol=1e-5)


def _train_config(pp=1):
    cfg = {
        "experiment": {"name": "train_pp"},
        "model": {
            "hidden_size": 32, "num_layers": 4, "num_heads": 4,
            "ffn_intermediate": 64, "attention": "full", "dtype": "float32",
        },
        "parallelism": {"world_size": 2, "data_parallel": 2},
        "input": {"batch_size": 8, "sequence_length": 16, "seed": 42},
        "execution": {"warmup_iterations": 1, "benchmark_iterations": 4},
        "training": {"learning_rate": 1e-2},
    }
    if pp > 1:
        cfg["parallelism"]["pipeline_parallel"] = pp
    return cfg


@needs_partial_auto
def test_pipeline_train_matches_plain(devices):
    """The pipelined train step must follow the same optimisation
    trajectory as the unpipelined one (same global math)."""
    r_plain = run_train(_train_config(pp=1), verbose=False)
    r_pp = run_train(_train_config(pp=2), verbose=False)
    assert r_pp["mesh"]["pp"] == 2
    np.testing.assert_allclose(
        r_plain["losses"], r_pp["losses"], rtol=1e-4, atol=1e-5
    )


@needs_partial_auto
def test_pipeline_train_zero3(devices):
    """pp composes with ZeRO-3/FSDP: same trajectory as plain DDP."""
    r_plain = run_train(_train_config(pp=1), verbose=False)
    cfg = _train_config(pp=2)
    r = run_train(cfg, zero_stage=3, verbose=False)
    assert r["mode"] == "zero3" and r["mesh"]["pp"] == 2
    np.testing.assert_allclose(
        r_plain["losses"], r["losses"], rtol=1e-4, atol=1e-5
    )


@needs_partial_auto
def test_moe_pipeline_forward(devices):
    """MoE FFN inside the pipelined layer scan stays exact (pp x ep)."""
    moe = TINY.with_(num_experts=4, moe_top_k=2)
    params = init_params(moe, jax.random.key(0))
    x = _x()
    y_ref = jax.jit(lambda p, x: forward(p, x, moe))(params, x)

    mesh = build_mesh(MeshSpec.grid((2, 2, 2), ("dp", "pp", "ep")))
    params_s = shard_params(params, mesh)
    y = jax.jit(lambda p, x: forward(p, x, moe, mesh=mesh))(params_s, x)
    np.testing.assert_allclose(np.asarray(y_ref), np.asarray(y),
                               rtol=1e-5, atol=1e-5)


@needs_partial_auto
def test_moe_pipeline_with_aux(devices):
    """with_aux under pp: the pipelined aux (per-stage masked accumulation
    + psum, averaged over layers x microbatches) equals the mean of the
    per-microbatch unpipelined auxes — and equals the unpipelined
    full-batch aux when every microbatch routes identically (the fixed
    test batch at m=1)."""
    moe = TINY.with_(num_experts=4, moe_top_k=2)
    params = init_params(moe, jax.random.key(0))
    x = _x()
    mesh = build_mesh(MeshSpec.grid((2, 2), ("pp", "ep")))
    params_s = shard_params(params, mesh)

    # m == batch-size 8 microbatches of 1 row: oracle = mean over rows
    y_pp, aux_pp = jax.jit(
        lambda p, a: forward(p, a, moe, mesh=mesh, num_microbatches=8,
                             with_aux=True)
    )(params_s, x)
    per_row = [
        float(forward(params, x[i:i + 1], moe, with_aux=True)[1])
        for i in range(8)
    ]
    np.testing.assert_allclose(float(aux_pp), np.mean(per_row),
                               rtol=1e-5, atol=1e-6)
    y_ref, _ = forward(params, x, moe, with_aux=True)
    np.testing.assert_allclose(np.asarray(y_ref), np.asarray(y_pp),
                               rtol=1e-5, atol=1e-5)


@needs_partial_auto
def test_moe_pipeline_train_with_aux_weight(devices):
    """MoE + pipeline + load-balancing loss trains end-to-end (the
    combination previously raised)."""
    cfg = _train_config(pp=2)
    cfg["model"].update(num_experts=4, moe_top_k=2)
    cfg["training"]["moe_aux_loss_weight"] = 0.01
    r = run_train(cfg, verbose=False)
    assert r["mesh"]["pp"] == 2
    assert all(np.isfinite(r["losses"]))
    assert r["losses"][-1] < r["losses"][0]


def test_1f1b_schedule_invariants():
    """The wavefront schedule: one-pair producer->consumer lag for both
    hops, every microbatch forwarded and backwarded exactly once per
    stage, and in-flight microbatches bounded by 2P-1 (the O(pp)
    activation live-range, independent of m)."""
    from dlbb_tpu.parallel.pipeline import schedule_1f1b

    for P, m in ((2, 4), (4, 8), (4, 4), (2, 2), (4, 2)):
        pairs, fwd, bwd = schedule_1f1b(P, m)
        assert pairs == m + 2 * (P - 1)
        for i in range(P):
            f_u = {int(fwd[u, i]): u for u in range(pairs)
                   if 0 <= fwd[u, i] < m}
            b_u = {int(bwd[u, i]): u for u in range(pairs)
                   if 0 <= bwd[u, i] < m}
            assert sorted(f_u) == sorted(b_u) == list(range(m))
            for q in range(m):
                # forward at or before backward (the last stage runs both
                # in one pair: the body's F part precedes its B part)
                assert f_u[q] <= b_u[q]
                if i > 0:  # activation produced one pair earlier upstream
                    f_up = {int(fwd[u, i - 1]): u for u in range(pairs)
                            if 0 <= fwd[u, i - 1] < m}
                    assert f_u[q] == f_up[q] + 1
                if i < P - 1:  # cotangent produced one pair earlier below
                    b_dn = {int(bwd[u, i + 1]): u for u in range(pairs)
                            if 0 <= bwd[u, i + 1] < m}
                    assert b_u[q] == b_dn[q] + 1
            inflight = max(
                sum(1 for q in range(m) if f_u[q] <= u < b_u[q])
                for u in range(pairs)
            )
            assert inflight <= 2 * P - 1


def test_1f1b_grads_match_unpipelined(devices):
    """pipeline_1f1b_grads == jax.value_and_grad of the unpipelined loss
    (same math; recompute-based backward; fp accumulation order differs)."""
    from dlbb_tpu.parallel.pipeline import pipeline_1f1b_grads
    from dlbb_tpu.train.loop import mse_loss

    params = init_params(TINY, jax.random.key(0))
    x, t = _x(seed=1), _x(seed=2)
    loss_ref, grads_ref = jax.value_and_grad(mse_loss)(params, x, t, TINY)

    mesh = build_mesh(MeshSpec.grid((4,), ("pp",)))
    ps = shard_params(params, mesh)
    loss_pp, grads_pp = jax.jit(
        lambda p, a, b: pipeline_1f1b_grads(p, a, b, TINY, mesh,
                                            num_microbatches=8)
    )(ps, x, t)
    np.testing.assert_allclose(float(loss_ref), float(loss_pp), rtol=1e-6)
    for (ka, ga), (kb, gb) in zip(
        jax.tree_util.tree_leaves_with_path(grads_ref),
        jax.tree_util.tree_leaves_with_path(grads_pp),
    ):
        np.testing.assert_allclose(
            np.asarray(ga), np.asarray(gb), rtol=1e-4, atol=1e-6,
            err_msg=str(ka),
        )


@needs_partial_auto
def test_1f1b_train_matches_gpipe(devices):
    """training.pipeline_schedule='1f1b' follows the same optimisation
    trajectory as GPipe autodiff and the unpipelined step."""
    r_plain = run_train(_train_config(pp=1), verbose=False)
    cfg = _train_config(pp=2)
    cfg["training"]["pipeline_schedule"] = "1f1b"
    r_1f1b = run_train(cfg, verbose=False)
    assert r_1f1b["pipeline_schedule"] == "1f1b"
    np.testing.assert_allclose(
        r_plain["losses"], r_1f1b["losses"], rtol=1e-4, atol=1e-5
    )


@needs_partial_auto
def test_1f1b_moe_aux_matches_gpipe(devices):
    """MoE + aux loss under 1F1B == the GPipe with_aux path (same
    per-microbatch aux averaging)."""
    base = _train_config(pp=2)
    base["model"].update(num_experts=4, moe_top_k=2)
    base["training"]["moe_aux_loss_weight"] = 0.01
    r_gpipe = run_train(base, verbose=False)
    cfg = _train_config(pp=2)
    cfg["model"].update(num_experts=4, moe_top_k=2)
    cfg["training"]["moe_aux_loss_weight"] = 0.01
    cfg["training"]["pipeline_schedule"] = "1f1b"
    r_1f1b = run_train(cfg, verbose=False)
    np.testing.assert_allclose(
        r_gpipe["losses"], r_1f1b["losses"], rtol=1e-4, atol=1e-5
    )


def test_1f1b_without_pp_rejected(devices):
    cfg = _train_config(pp=1)
    cfg["training"]["pipeline_schedule"] = "1f1b"
    with pytest.raises(ValueError, match="pipeline_parallel"):
        run_train(cfg, verbose=False)


def test_microbatches_without_pp_rejected(devices):
    """num_microbatches without pipeline_parallel must error, not be
    silently ignored."""
    cfg = _train_config(pp=1)
    cfg["parallelism"]["num_microbatches"] = 4
    with pytest.raises(ValueError, match="pipeline_parallel"):
        run_train(cfg, verbose=False)


def test_validate_pipeline_errors():
    with pytest.raises(ValueError, match="not divisible by"):
        validate_pipeline(TINY, 3, 8, None)  # 4 layers % 3 stages
    with pytest.raises(ValueError, match="num_microbatches"):
        validate_pipeline(TINY, 2, 8, 3)  # batch 8 % 3 microbatches
    ring = TINY.with_(attention="ring")
    with pytest.raises(ValueError, match="pipeline"):
        validate_pipeline(ring, 2, 8, None)
    assert validate_pipeline(TINY, 2, 8, None) == 2
    assert validate_pipeline(TINY, 2, 8, 4) == 4
