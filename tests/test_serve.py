"""Serving subsystem tests: traffic traces, the paged KV-cache ledger,
the build-time serving validation (HBM budget gate), prefill/decode
equivalence against the full-sequence forward pass, and the
continuous-batching engine end to end (``serve_smoke``)."""

import json
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dlbb_tpu.comm.mesh import build_parallelism_mesh
from dlbb_tpu.models.configs import (
    ModelConfig,
    kv_cache_bytes,
    validate_serving,
)
from dlbb_tpu.models.transformer import forward, init_params_sharded
from dlbb_tpu.serve.engine import (
    ServingConfig,
    ServingEngine,
    _inject_token,
    build_decode_step,
    build_prefill,
)
from dlbb_tpu.serve.kvcache import (
    BlockLedger,
    CacheOverflow,
    create_kv_cache,
)
from dlbb_tpu.serve.traffic import TrafficTrace, generate_trace

TINY = dict(hidden_size=64, num_layers=2, num_heads=4,
            ffn_intermediate=128, dtype="float32", attention="full")


# ---------------------------------------------------------------------------
# traffic
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kind", ["poisson", "bursty", "diurnal"])
def test_trace_deterministic_and_replayable(kind, tmp_path):
    a = generate_trace(kind, 40, seed=11, rate=20.0)
    b = generate_trace(kind, 40, seed=11, rate=20.0)
    assert a == b
    c = generate_trace(kind, 40, seed=12, rate=20.0)
    assert a != c
    # arrivals sorted, lengths within bounds, seeds present
    arr = [r.arrival_s for r in a]
    assert arr == sorted(arr) and arr[0] > 0
    assert all(8 <= r.prompt_len <= 96 for r in a)
    assert all(4 <= r.output_len <= 48 for r in a)
    # JSON round trip through the atomic writer
    path = tmp_path / "trace.json"
    a.save(path)
    loaded = TrafficTrace.load(path)
    assert loaded == a


def test_trace_rejects_bad_args(tmp_path):
    with pytest.raises(ValueError, match="unknown trace kind"):
        generate_trace("constant", 10)
    with pytest.raises(ValueError, match="num_requests"):
        generate_trace("poisson", 0)
    with pytest.raises(ValueError, match="rate"):
        generate_trace("poisson", 10, rate=0.0)
    with pytest.raises(ValueError, match="1 <= lo <= hi"):
        generate_trace("poisson", 10, prompt_range=(0, 96))
    (tmp_path / "bad.json").write_text(json.dumps({"schema": "nope"}))
    with pytest.raises(ValueError, match="not a serving trace"):
        TrafficTrace.load(tmp_path / "bad.json")


def test_bursty_is_burstier_than_poisson():
    """The MMPP trace's inter-arrival coefficient of variation must
    exceed the Poisson trace's (CV 1) — the property the generator
    exists to provide."""
    def cv(trace):
        gaps = np.diff([0.0] + [r.arrival_s for r in trace])
        return gaps.std() / gaps.mean()

    poisson = generate_trace("poisson", 400, seed=3, rate=50.0)
    bursty = generate_trace("bursty", 400, seed=3, rate=50.0,
                            burst_factor=10.0, dwell_s=0.5)
    assert cv(bursty) > cv(poisson)


# ---------------------------------------------------------------------------
# ledger + config validation
# ---------------------------------------------------------------------------


def test_block_ledger_accounting():
    led = BlockLedger(total_blocks=8, block_size=4)
    assert led.blocks_for(1) == 1 and led.blocks_for(4) == 1
    assert led.blocks_for(5) == 2
    assert led.reserve(0, 9) == 3          # ceil(9/4); 12-token capacity
    assert led.blocks_reserved == 3 and led.blocks_free == 5
    led.append(0, 5)                       # prompt: 2 blocks in use
    assert led.blocks_in_use == 2
    led.append(0, 4)                       # 9 tokens -> 3rd block
    assert led.blocks_in_use == 3 and led.peak_in_use == 3
    led.append(0, 3)                       # 12 tokens: exactly full
    with pytest.raises(CacheOverflow, match="outgrew"):
        led.append(0)                      # 13th token > reservation
    assert led.free(0) == 3
    assert led.blocks_reserved == 0
    with pytest.raises(CacheOverflow):
        led.free(0)
    # all-or-nothing reservation against the budget
    led.reserve(1, 32)                     # all 8 blocks
    assert not led.can_reserve(1)
    with pytest.raises(CacheOverflow, match="cannot reserve"):
        led.reserve(2, 1)


def test_validate_serving_envelope():
    cfg = ModelConfig(**TINY)
    validate_serving(cfg, max_batch=4, max_seq=32, block_size=8,
                     dp=2, tp=4)
    with pytest.raises(ValueError, match="attention"):
        validate_serving(cfg.with_(attention="simplified"), 4, 32, 8)
    with pytest.raises(ValueError, match="multiple"):
        validate_serving(cfg, max_batch=4, max_seq=30, block_size=8)
    with pytest.raises(ValueError, match="divisible by dp"):
        validate_serving(cfg, max_batch=3, max_seq=32, block_size=8, dp=2)
    with pytest.raises(ValueError, match="kv_heads"):
        validate_serving(cfg.with_(num_kv_heads=2), 4, 32, 8, tp=4)
    with pytest.raises(ValueError, match="dense FFN"):
        validate_serving(cfg.with_(num_experts=4), 4, 32, 8)


def test_hbm_budget_gate_rejects_oversized_cache():
    """The satellite fix: an infeasible ``max_batch x max_seq`` KV-cache
    is a clear build-time error, never an OOM mid-trace."""
    cfg = ModelConfig(**TINY)
    total = kv_cache_bytes(cfg, max_batch=64, max_seq=4096)
    assert total == 2 * 2 * 64 * 4096 * 4 * 16 * 4  # K+V,L,B,S,kvh,d,f32
    # generous budget passes
    validate_serving(cfg, 64, 4096, 128, hbm_budget_bytes=total)
    with pytest.raises(ValueError, match="HBM budget"):
        validate_serving(cfg, 64, 4096, 128,
                         hbm_budget_bytes=total // 4)
    # sharding divides the per-device footprint: dp=2 x tp=4 fits in 1/8
    validate_serving(cfg, 64, 4096, 128, dp=2, tp=4,
                     hbm_budget_bytes=total // 8)
    # ServingConfig.validate wires the GiB knob through
    sv = ServingConfig(max_batch=64, max_seq=4096, block_size=128,
                       hbm_budget_gb=total / 4 / 2**30)
    with pytest.raises(ValueError, match="hbm_budget_gb"):
        sv.validate(cfg)


def test_serving_config_buckets_and_dict():
    sv = ServingConfig(max_batch=4, block_size=8, max_seq=64)
    assert sv.prefill_buckets == (8, 16, 32, 64)
    assert sv.num_blocks == 8
    assert sv.bucket_for(1) == 8 and sv.bucket_for(9) == 16
    assert sv.bucket_for(64) == 64
    with pytest.raises(ValueError, match="largest prefill bucket"):
        sv.bucket_for(65)
    round_trip = ServingConfig.from_dict(sv.to_dict())
    assert round_trip.prefill_buckets == sv.prefill_buckets
    assert round_trip.max_seq == sv.max_seq
    # explicit buckets normalise to ascending unique order (bucket_for's
    # first-match walk and the buckets[-1]-is-largest consumers rely on it)
    shuffled = ServingConfig(max_batch=4, block_size=8, max_seq=64,
                             prefill_buckets=(64, 16, 16, 32))
    assert shuffled.prefill_buckets == (16, 32, 64)
    assert shuffled.bucket_for(8) == 16
    with pytest.raises(ValueError, match="bucket"):
        ServingConfig(max_batch=4, block_size=8, max_seq=64,
                      prefill_buckets=(12,)).validate(ModelConfig(**TINY))


def test_resolved_trace_always_fits_the_envelope():
    """resolve_trace's auto length bounds must satisfy the engine's
    pre-run validation for ANY feasible envelope — including tiny
    max_seq where prompt+output once overflowed (max_out is now the
    exact remainder of max_prompt)."""
    from dlbb_tpu.serve.bench import resolve_trace

    for max_seq, block in ((8, 8), (16, 8), (24, 8), (256, 16)):
        sv = ServingConfig(max_batch=4, block_size=block,
                           max_seq=max_seq, hbm_budget_gb=None)
        trace = resolve_trace("poisson", num_requests=50, seed=5,
                              serving=sv)
        for r in trace:
            assert r.total_tokens <= sv.max_seq, (max_seq, r)
            assert r.prompt_len <= sv.prefill_buckets[-1]
            assert r.output_len >= 1


def test_default_parallelism_prefers_tp_over_single_device():
    from dlbb_tpu.serve.bench import default_parallelism

    assert default_parallelism(8, 4, 8) == (2, 4)
    assert default_parallelism(8, 8, 8) == (2, 4)
    assert default_parallelism(1, 4, 8) == (1, 1)
    # kv_heads indivisible by 4/2: tp collapses, dp takes the devices
    assert default_parallelism(8, 3, 8) == (8, 1)
    # an awkward max_batch costs dp width, never the whole tp axis
    assert default_parallelism(8, 4, 3) == (1, 4)
    assert default_parallelism(8, 4, 6) == (2, 4)


def test_plan_expected_kinds_decode():
    from dlbb_tpu.analysis.expectations import plan_expected_kinds

    # dp is pure batch parallelism at inference: no collectives at all
    assert plan_expected_kinds(dp=8, decode=True) == set()
    # tp keeps its tiny per-token set; nothing gradient-shaped sneaks in
    assert plan_expected_kinds(dp=2, tp=4, decode=True) == {
        "all-reduce", "collective-permute"}
    with pytest.raises(ValueError, match="dp, tp"):
        plan_expected_kinds(sp=2, decode=True)


# ---------------------------------------------------------------------------
# prefill/decode equivalence vs the full-sequence forward pass
# ---------------------------------------------------------------------------

# fp32 pin: the cached path computes the same logits over the same
# positions, but XLA fuses/partitions the [S, S] prefill and the
# per-step [1, S] decode contractions differently per mesh layout —
# observed divergence <= ~7e-7 on unit-scale layernormed outputs.
F32_TOL = 1e-5


def _equivalence_case(cfg, mesh, dp, tol):
    """Prefill P tokens, decode the rest feeding the TRUE next inputs,
    and compare every produced position against the one-shot forward."""
    params = init_params_sharded(cfg, jax.random.key(0), mesh)
    seq, prompt, slot = 24, 11, 2
    rng = np.random.default_rng(0)
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    x_full = jnp.asarray(
        rng.standard_normal((1, seq, cfg.hidden_size), dtype=np.float32),
        dtype=dtype,
    )
    y_full = jax.jit(lambda p, a: forward(p, a, cfg, mesh=mesh))(
        params, x_full)

    sv = ServingConfig(max_batch=4, block_size=8, max_seq=32,
                       hbm_budget_gb=None)
    sv.validate(cfg, dp=dp, tp=mesh.shape["tp"])
    cache = create_kv_cache(cfg, sv.max_batch, sv.num_blocks,
                            sv.block_size, mesh=mesh)
    prefill = build_prefill(cfg, mesh)
    decode = build_decode_step(cfg, mesh)

    bucket = sv.bucket_for(prompt)
    xp = np.zeros((1, bucket, cfg.hidden_size), np.float32)
    xp[:, :prompt] = np.asarray(x_full[:, :prompt], np.float32)
    cache, y_last = prefill(cache, params, jnp.asarray(xp, dtype),
                            np.int32(slot), np.int32(prompt))
    errs = [float(jnp.abs(y_last - y_full[0, prompt - 1]).max())]

    x = jax.device_put(
        jnp.zeros((sv.max_batch, 1, cfg.hidden_size), dtype),
        NamedSharding(mesh, P("dp" if dp > 1 else None, None, None)),
    )
    active = np.zeros(sv.max_batch, bool)
    active[slot] = True
    active = jnp.asarray(active)
    carry = (cache, x)
    for i in range(prompt, seq):
        carry = _inject_token(carry, np.int32(slot), x_full[0, i])
        carry, y = decode(carry, params, active)
        errs.append(float(jnp.abs(y[slot, 0] - y_full[0, i]).max()))
    assert max(errs) <= tol, f"max divergence {max(errs)} > {tol}"
    # the decoded slot advanced exactly seq - prompt tokens
    assert int(carry[0].lengths[slot]) == seq
    assert int(carry[0].lengths[0]) == 0  # untouched slots stay empty


def test_prefill_decode_matches_forward_dp_tp(mesh2x4):
    """(dp, tp) mesh, full MHA, fp32: exact to rounding noise."""
    _equivalence_case(ModelConfig(**TINY), mesh2x4, dp=2, tol=F32_TOL)


def test_prefill_decode_matches_forward_tp_only_gqa():
    """(tp)-only mesh with GQA (kv_heads=2 < num_heads=4): head-dim
    sharding alone, grouped cache reads at kv_heads width with a 2-way
    kv-head shard."""
    cfg = ModelConfig(**{**TINY, "num_kv_heads": 2})
    mesh = build_parallelism_mesh(tensor_parallel=2,
                                  devices=jax.devices()[:2])
    _equivalence_case(cfg, mesh, dp=1, tol=F32_TOL)


# bf16 tolerance pin: the cached path reorders nothing algebraically,
# but bf16 rounding differs between the [S, S] prefill matmuls and the
# per-step [1, S] decode contractions; 0.05 absolute on unit-scale
# layernormed outputs holds with ~6x headroom (observed max ~8e-3).
BF16_TOL = 0.05


def test_prefill_decode_matches_forward_bf16(mesh2x4):
    cfg = ModelConfig(**{**TINY, "dtype": "bfloat16"})
    _equivalence_case(cfg, mesh2x4, dp=2, tol=BF16_TOL)


# ---------------------------------------------------------------------------
# the engine end to end
# ---------------------------------------------------------------------------

SMOKE_MODEL = ModelConfig(**TINY)
SMOKE_SERVING = ServingConfig(max_batch=8, block_size=8, max_seq=64,
                              queue_capacity=64, hbm_budget_gb=None)


def _smoke_trace(n=30, seed=7):
    return generate_trace("poisson", n, seed=seed, rate=200.0,
                          prompt_range=(4, 16), output_range=(2, 8))


@pytest.fixture(scope="module")
def smoke_engine(mesh2x4):
    """One compiled engine shared by the module's trace-running tests
    (fresh cache per run_trace; the request counters accumulate, so only
    the FIRST trace-running test may assert absolute counts)."""
    return ServingEngine(SMOKE_MODEL, SMOKE_SERVING, mesh2x4,
                         verbose=False)


@pytest.mark.serve_smoke
def test_engine_serves_poisson_trace_clean(smoke_engine, tmp_path):
    """The serve_smoke gate: a seeded 30-request Poisson mini-trace on
    the simulated mesh completes with ZERO rejected-by-bug requests, a
    valid span-trace file, journaled request lifecycle, live registry
    counters + metrics.prom export, and finite metrics (queue capacity
    >= trace size, so any rejection here is an engine bug, not load)."""
    from dlbb_tpu.obs import spans
    from dlbb_tpu.obs.export import serving_metrics
    from dlbb_tpu.resilience.journal import SweepJournal, read_journal

    engine = smoke_engine
    trace = _smoke_trace()
    span_path = tmp_path / "serve_trace.json"
    journal = SweepJournal(tmp_path, meta={"mode": "serve"},
                           sink=spans.journal_sink)
    engine.journal = journal
    try:
        with spans.tracing(span_path):
            report = engine.run_trace(trace)
    finally:
        engine.journal = None
        journal.close()

    req = report["requests"]
    assert req["arrived"] == 30 and req["completed"] == 30
    assert req["rejected"] == 0 and req["rejected_rids"] == []
    assert report["goodput_tokens_per_s"] > 0
    assert math.isfinite(report["goodput_tokens_per_s"])
    for block in ("ttft", "per_token_latency", "prefill_time",
                  "decode_step_time", "e2e_latency"):
        for q in ("median", "p95", "p99", "p999"):
            assert math.isfinite(report[block][q]), (block, q)
    assert report["ttft"]["count"] == 30
    assert report["completed_output_tokens"] == sum(
        r.output_len for r in trace)
    # queue-depth/occupancy timeseries present and consistent
    series = report["timeseries"]
    n = len(series["t_s"])
    assert n > 0 and all(len(v) == n for v in series.values())
    assert series["t_s"] == sorted(series["t_s"])
    assert max(series["blocks_in_use"]) <= SMOKE_SERVING.total_blocks
    # every block freed at the end
    assert report["cache"]["blocks_reserved"] == 0
    # span trace: schema-valid trace-event JSON with the serving phases
    payload = spans.load_trace(span_path)
    assert spans.validate_trace_events(payload["traceEvents"]) == []
    names = {e["name"] for e in payload["traceEvents"]}
    assert {"serve-prefill", "serve-decode"} <= names
    # journal: full request lifecycle, fsync'd
    events, torn = read_journal(tmp_path)
    assert torn == 0
    kinds = {e["event"] for e in events}
    assert {"request-arrived", "request-admitted", "request-prefill",
            "request-completed"} <= kinds
    completed = [e for e in events if e["event"] == "request-completed"]
    assert len(completed) == 30
    # journal -> Perfetto timeline: each request's arrived->completed
    # pair becomes one end-to-end X span (cli obs trace on a serving dir)
    timeline, n_events, torn2 = spans.journal_to_trace(
        tmp_path, tmp_path / "timeline.json")
    assert torn2 == 0
    rebuilt = spans.load_trace(timeline)
    req_spans = [e for e in rebuilt["traceEvents"] if e["ph"] == "X"]
    assert len(req_spans) == 30
    assert all(e["cat"] == "config-completed" for e in req_spans)
    # the MetricsRegistry satellite: counters live in the registry and
    # export to the Prometheus textfile
    reg = engine.registry
    done_total = int(reg.get("serve_requests", outcome="completed"))
    assert done_total >= 30  # cumulative across the shared engine's runs
    prom_path = serving_metrics(report, registry=reg).write_textfile(
        tmp_path / "metrics.prom")
    text = prom_path.read_text()
    assert (f'dlbb_serve_requests_total{{outcome="completed"}} '
            f"{done_total}") in text
    assert "dlbb_serve_goodput_tokens_per_second" in text
    assert 'dlbb_serve_ttft_seconds{quantile="p999"}' in text
    assert 'dlbb_serve_cache_blocks{stat="peak_blocks_in_use"}' in text


def test_engine_bounded_queue_rejects_under_overload(smoke_engine):
    """Admission control: a queue bound of 1 under a burst MUST shed
    load — rejections counted, journaled as queue-full, and the rest of
    the trace still completes.  Only queue_capacity changes (host-side
    scheduling state), so the shared engine's compiles are reused."""
    from dataclasses import replace

    engine = smoke_engine
    trace = generate_trace("poisson", 12, seed=3, rate=5000.0,
                           prompt_range=(4, 16), output_range=(4, 8))
    original = engine.serving
    engine.serving = replace(original, queue_capacity=1)
    try:
        report = engine.run_trace(trace)
    finally:
        engine.serving = original
    req = report["requests"]
    assert req["rejected"] > 0
    assert req["completed"] == 12 - req["rejected"]
    assert len(req["rejected_rids"]) == req["rejected"]
    assert max(report["timeseries"]["queue_depth"]) <= 1


def test_engine_rejects_infeasible_trace_upfront(smoke_engine):
    """A request that cannot fit the serving envelope fails BEFORE the
    run (and before any compile) with a clear error, not mid-trace."""
    engine = smoke_engine
    bad = generate_trace("poisson", 4, seed=1, rate=10.0,
                         prompt_range=(40, 60), output_range=(30, 40))
    with pytest.raises(ValueError, match="max_seq"):
        engine.run_trace(bad)
    with pytest.raises(ValueError, match="empty trace"):
        engine.run_trace(TrafficTrace(kind="poisson", seed=0, params={}))


@pytest.mark.serve_smoke
def test_serving_bench_writes_artifact_set(tmp_path):
    """serve/bench.py end to end: result JSON + replayable trace +
    manifest + metrics.prom + journal, all parseable."""
    from dlbb_tpu.serve.bench import run_serving

    config = {
        "experiment": {"name": "smoke"},
        "model": dict(TINY),
        "parallelism": {"data_parallel": 2, "world_size": 4},
        "serving": {"max_batch": 8, "block_size": 8, "max_seq": 32,
                    "prefill_buckets": [16], "hbm_budget_gb": None},
    }
    trace = generate_trace("poisson", 4, seed=7, rate=200.0,
                           prompt_range=(4, 16), output_range=(2, 6))
    report = run_serving(config, trace, str(tmp_path), verbose=False)
    assert report["requests"]["completed"] == 4
    result = json.loads((tmp_path / "serving_smoke.json").read_text())
    assert result["schema"] == "dlbb_serving_report_v1"
    assert result["mesh"] == {"dp": 2, "sp": 1, "pp": 1, "ep": 1, "tp": 4}
    manifest = json.loads(
        (tmp_path / "serving_manifest.json").read_text())
    assert manifest["schema"] == "dlbb_serving_manifest_v1"
    assert manifest["requests"]["completed"] == 4
    assert "topology" in manifest
    replay = TrafficTrace.load(tmp_path / "trace_smoke.json")
    assert len(replay) == 4
    assert "dlbb_serve_requests_total" in (
        tmp_path / "metrics.prom").read_text()
    assert (tmp_path / "sweep_journal.jsonl").exists()


def test_serving_report_writer(tmp_path):
    from dlbb_tpu.stats.serving_report import write_serving_report
    from dlbb_tpu.utils.config import save_json

    fake = {
        "schema": "dlbb_serving_report_v1",
        "trace": {"kind": "poisson", "num_requests": 10},
        "requests": {"completed": 9, "rejected": 1},
        "mesh": {"dp": 2, "tp": 4, "sp": 1, "pp": 1, "ep": 1},
        "serving": {"max_batch": 8, "block_size": 16, "max_seq": 256},
        "goodput_tokens_per_s": 123.4,
        "throughput_tokens_per_s": 150.0,
        "ttft": {"median": 0.01, "p99": 0.02, "p999": 0.03},
        "per_token_latency": {"median": 0.001, "p99": 0.002,
                              "p999": 0.003},
        "cache": {"peak_blocks_in_use": 12},
        "timeseries": {"queue_depth": [0, 3, 1]},
        "decode_steps": 42,
        "wall_seconds": 1.5,
    }
    results = tmp_path / "results"
    save_json(fake, results / "serving_run1.json")
    rows = write_serving_report(results, tmp_path / "stats")
    assert len(rows) == 1
    row = rows[0]
    assert row["name"] == "run1" and row["mesh"] == "dp2xtp4"
    assert row["ttft_p999_ms"] == 30.0 and row["peak_queue_depth"] == 3
    md = (tmp_path / "stats" / "SERVING.md").read_text()
    assert "run1" in md and "poisson" in md
    csv_text = (tmp_path / "stats" / "serving.csv").read_text()
    assert csv_text.startswith("name,trace,")
    # an empty dir produces no report (and clobbers nothing)
    assert write_serving_report(tmp_path / "nothing",
                                tmp_path / "stats2") == []
    assert not (tmp_path / "stats2").exists()
