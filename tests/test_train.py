"""Training-loop tests: DDP + ZeRO-{1,2,3} on the simulated (dp, tp) mesh
(reference's training capability: ``test/ccl.py:59-117`` ZeRO train step)."""

import jax
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding

from dlbb_tpu.comm.mesh import MeshSpec, build_mesh
from dlbb_tpu.data.synthetic import SyntheticEmbeddingDataset
from dlbb_tpu.models.configs import ModelConfig
from dlbb_tpu.models.sharding import batch_spec
from dlbb_tpu.models.transformer import init_params
from dlbb_tpu.train.loop import (
    make_train_step,
    opt_state_specs,
    resolve_zero_stage,
    run_train,
)

TINY = ModelConfig(hidden_size=32, num_layers=2, num_heads=4,
                   ffn_intermediate=64, attention="full", dtype="float32")


def _config(zero=False):
    return {
        "experiment": {"name": "train_smoke"},
        "model": {
            "hidden_size": 32, "num_layers": 2, "num_heads": 4,
            "ffn_intermediate": 64, "attention": "full", "dtype": "float32",
        },
        "parallelism": {"world_size": 2, "data_parallel": 4},
        "input": {"batch_size": 8, "sequence_length": 16, "seed": 42},
        "execution": {"warmup_iterations": 1, "benchmark_iterations": 6},
        "training": {"learning_rate": 1e-2},
    }


@pytest.mark.parametrize("zero1", [False, True])
def test_loss_decreases(devices, zero1):
    """The full train step optimises: MSE loss must drop over steps
    (reference asserts the ZeRO step merely completes; we assert progress)."""
    result = run_train(_config(), zero1=zero1, verbose=False)
    losses = result["losses"]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0] * 0.9, losses
    assert result["final_step"] == 7  # warmup 1 + 6 measured


def test_train_utilisation_metrics(devices):
    """run_train reports tokens/s + achieved TFLOP/s with the 3x-forward +
    optimizer-update FLOPs accounting, so ZeRO-stage overheads compare as
    utilisation (parity depth with reference run_mpi.py:217-225)."""
    from dlbb_tpu.models.transformer import forward_flops
    from dlbb_tpu.train.loop import OPTIMIZER_FLOPS_PER_PARAM

    result = run_train(_config(), verbose=False)
    tokens = 8 * 16
    mean = result["step_time"]["mean"]
    np.testing.assert_allclose(
        result["tokens_per_second"], tokens / mean, rtol=1e-6
    )
    fwd = forward_flops(TINY, 8, 16)
    assert result["forward_flops"] == fwd
    assert result["model_flops_per_step"] == (
        3 * fwd + OPTIMIZER_FLOPS_PER_PARAM["adam"] * result["num_params"]
    )
    np.testing.assert_allclose(
        result["achieved_tflops_per_second"],
        result["model_flops_per_step"] / mean / 1e12, rtol=1e-6,
    )
    assert result["num_params"] > 0


def test_zero1_shards_optimizer_state(devices):
    """ZeRO-1: Adam mu/nu must actually be sharded over dp, DDP must not."""
    mesh = build_mesh(MeshSpec.grid((4, 2), ("dp", "tp")))
    params = init_params(TINY, jax.random.key(0))
    opt = optax.adam(1e-3)

    _, state_ddp = make_train_step(TINY, mesh, opt, params, zero1=False)
    _, state_z1 = make_train_step(TINY, mesh, opt, params, zero1=True)

    def dp_sharded_leaves(opt_state):
        count = 0
        for leaf in jax.tree.leaves(opt_state):
            sharding = leaf.sharding
            if isinstance(sharding, NamedSharding) and any(
                "dp" in (ax if isinstance(ax, tuple) else (ax,))
                for ax in sharding.spec if ax is not None
            ):
                count += 1
        return count

    assert dp_sharded_leaves(state_ddp.opt_state) == 0
    assert dp_sharded_leaves(state_z1.opt_state) > 0


def test_zero1_matches_ddp_numerics(devices):
    """Sharding the optimizer state must not change the optimisation
    trajectory — same losses either way."""
    r_ddp = run_train(_config(), zero1=False, verbose=False)
    r_z1 = run_train(_config(), zero1=True, verbose=False)
    np.testing.assert_allclose(
        r_ddp["losses"], r_z1["losses"], rtol=1e-4, atol=1e-5
    )


def _dp_sharded_leaves(tree):
    count = 0
    for leaf in jax.tree.leaves(tree):
        sharding = leaf.sharding
        if isinstance(sharding, NamedSharding) and any(
            "dp" in (ax if isinstance(ax, tuple) else (ax,))
            for ax in sharding.spec if ax is not None
        ):
            count += 1
    return count


@pytest.mark.parametrize("stage", [2, 3])
def test_zero23_matches_ddp_numerics(devices, stage):
    """Sharding grads (stage 2) or params (stage 3) must not change the
    optimisation trajectory."""
    r_ddp = run_train(_config(), zero_stage=0, verbose=False)
    r_z = run_train(_config(), zero_stage=stage, verbose=False)
    assert r_z["mode"] == f"zero{stage}"
    np.testing.assert_allclose(
        r_ddp["losses"], r_z["losses"], rtol=1e-4, atol=1e-5
    )


def test_zero3_shards_params(devices):
    """ZeRO-3/FSDP: the parameters themselves must live dp-sharded;
    stages <=2 keep them dp-replicated."""
    mesh = build_mesh(MeshSpec.grid((4, 2), ("dp", "tp")))
    params = init_params(TINY, jax.random.key(0))
    opt = optax.adam(1e-3)

    _, state_z2 = make_train_step(TINY, mesh, opt, params, zero_stage=2)
    _, state_z3 = make_train_step(TINY, mesh, opt, params, zero_stage=3)

    assert _dp_sharded_leaves(state_z2.params) == 0
    assert _dp_sharded_leaves(state_z3.params) > 0
    # opt state is dp-sharded in both
    assert _dp_sharded_leaves(state_z2.opt_state) > 0
    assert _dp_sharded_leaves(state_z3.opt_state) > 0


def test_zero_stage_config_key(devices):
    """training.zero_stage in the YAML config selects the stage."""
    cfg = _config()
    cfg["training"]["zero_stage"] = 2
    result = run_train(cfg, verbose=False)
    assert result["mode"] == "zero2"
    assert result["zero_stage"] == 2


def test_resolve_zero_stage():
    assert resolve_zero_stage() == 0
    assert resolve_zero_stage(zero1=True) == 1
    assert resolve_zero_stage(zero1=True, zero_stage=3) == 3
    with pytest.raises(ValueError):
        resolve_zero_stage(zero_stage=4)


def test_opt_state_specs_scalar_replicated(devices):
    params = init_params(TINY, jax.random.key(0))
    opt_state = optax.adam(1e-3).init(params)
    specs = opt_state_specs(params, opt_state, zero1=True, dp_size=4)
    # the adam count scalar must stay replicated
    leaves = jax.tree.leaves(specs, is_leaf=lambda x: x is not None)
    from jax.sharding import PartitionSpec as P

    counts = [s for s, l in zip(
        jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P)),
        jax.tree.leaves(opt_state),
    ) if getattr(l, "ndim", None) == 0]
    assert all(s == P() for s in counts)


def test_parallelism_report(tmp_path):
    """The parallelism-family comparison joins train artifacts per family,
    ranks by per-token throughput (fair when members run unequal batches,
    e.g. the grad-accum reshard pair), and lists missing members with null
    times instead of dropping them."""
    import json

    from dlbb_tpu.stats.parallelism_report import write_parallelism_report

    def art(name, mean_s, tokens_per_s):
        (tmp_path / f"train_ddp_{name}.json").write_text(json.dumps({
            "experiment": {"name": name},
            "mesh": {"dp": 2, "sp": 1, "pp": 2, "ep": 1, "tp": 2},
            "step_time": {"mean": mean_s},
            "tokens_per_second": tokens_per_s,
        }))

    art("pp2_gpipe", 0.10, 1000.0)
    art("pp2_1f1b", 0.08, 1250.0)
    art("ga2_divisible_b16", 0.10, 2000.0)
    art("ga2_reshard_b20", 0.15, 1600.0)  # bigger batch, worse per token
    families = {
        "pipeline_schedule": ["pp2_gpipe", "pp2_1f1b"],
        "grad_accum_reshard": ["ga2_divisible_b16", "ga2_reshard_b20"],
        "context_parallel": ["sp2_ring", "sp2_ulysses"],  # missing
    }
    rows = write_parallelism_report(tmp_path, tmp_path / "out", families)
    by = {r["member"]: r for r in rows}
    assert by["pp2_1f1b"]["winner"] is True
    assert by["pp2_gpipe"]["winner"] is False
    assert by["pp2_gpipe"]["slowdown_vs_winner"] == 1.25
    assert by["ga2_divisible_b16"]["winner"] is True
    assert by["ga2_reshard_b20"]["slowdown_vs_winner"] == 1.25
    assert by["sp2_ring"]["step_time_mean_s"] is None  # listed, not dropped
    assert (tmp_path / "out" / "PARALLELISM.md").exists()
    assert (tmp_path / "out" / "parallelism_comparison.csv").exists()


def test_cp_scaling_report(tmp_path):
    """The long-context CP scaling report joins ring/Ulysses artifacts per
    (S, sp) cell, computes the ring/Ulysses ratio where both measured, and
    renders footprint-capped boundary artifacts as visible skip cells
    (the capped Ulysses column at long S is itself the finding)."""
    import json

    from dlbb_tpu.stats.parallelism_report import write_cp_scaling_report

    def art(name, tokens_per_s):
        (tmp_path / f"train_ddp_{name}.json").write_text(json.dumps({
            "experiment": {"name": name},
            "mesh": {"dp": 1, "sp": 2, "pp": 1, "ep": 1, "tp": 1},
            "step_time": {"mean": 1.0},
            "tokens_per_second": tokens_per_s,
        }))

    def boundary(name, est_gib):
        (tmp_path / f"train_ddp_{name}.json").write_text(json.dumps({
            "experiment": {"name": name},
            "status": "skipped_estimated_footprint",
            "estimated_bytes": est_gib * 2**30,
        }))

    def time_boundary(name):
        (tmp_path / f"train_ddp_{name}.json").write_text(json.dumps({
            "experiment": {"name": name},
            "status": "skipped_estimated_time",
        }))

    def infeasible(name):
        (tmp_path / f"train_ddp_{name}.json").write_text(json.dumps({
            "experiment": {"name": name},
            "status": "infeasible",
        }))

    art("cp_s8192_sp2_ring", 1000.0)
    art("cp_s8192_sp2_ulysses", 1250.0)
    art("cp_s32768_sp4_ring", 400.0)
    boundary("cp_s32768_sp4_ulysses", 103)
    time_boundary("cp_s32768_sp2_ring")
    boundary("cp_s32768_sp2_ulysses", 103)
    infeasible("cp_s32768_sp8_ring")
    boundary("cp_s32768_sp8_ulysses", 96)
    rows = write_cp_scaling_report(tmp_path, tmp_path / "out")
    by = {(r["seq_len"], r["sp"]): r for r in rows}
    assert by[(8192, 2)]["winner"] == "ulysses"
    assert by[(8192, 2)]["ring_over_ulysses"] == 0.8
    capped = by[(32768, 4)]
    assert capped["winner"] == "ring (ulysses capped)"
    assert capped["ring_over_ulysses"] is None
    assert "103 GiB" in capped["ulysses_tokens_per_second"]
    both_skip = by[(32768, 2)]
    assert both_skip["winner"] is None
    assert "estimated_time" in both_skip["ring_tokens_per_second"]
    hard = by[(32768, 8)]
    assert hard["winner"] is None
    assert "infeasible" in hard["ring_tokens_per_second"]
    assert (tmp_path / "out" / "CP_SCALING.md").exists()
    assert (tmp_path / "out" / "cp_scaling.csv").exists()


def test_zero3_compiles_param_allgather_pattern(devices):
    """ZeRO-3/FSDP is DECLARED (dp-sharded params); the compiled step must
    contain all-gather collectives (params gathered on use) that plain DDP
    (replicated params, dp=grad-psum only) does not need."""
    import re

    import jax.numpy as jnp

    from dlbb_tpu.parallel.plan import build_parallelism_mesh
    from dlbb_tpu.train.loop import make_train_step

    cfg = TINY.with_(attention="simplified")
    mesh = build_parallelism_mesh(8, 1, 1, 1, 1)
    x = jnp.zeros((8, 8, cfg.hidden_size))

    def hlo_for(stage):
        params = init_params(cfg, jax.random.key(0))
        jit_step, state = make_train_step(
            cfg, mesh, optax.sgd(1e-3), params, zero_stage=stage
        )
        return jit_step.lower(state, x, x).compile().as_text()

    hlo3 = hlo_for(3)
    hlo0 = hlo_for(0)
    assert len(re.findall(r"\ball-gather", hlo3)) >= 1, \
        "ZeRO-3 step compiled without param all-gathers"
    # DDP still all-reduces gradients over dp, but has no param gathers
    assert len(re.findall(r"\ball-reduce", hlo0)) >= 1
    assert len(re.findall(r"\ball-gather", hlo3)) > \
        len(re.findall(r"\ball-gather", hlo0))
