"""Sequence/context parallelism correctness: ring attention and Ulysses must
reproduce dense causal attention exactly (up to fp accumulation order), both
standalone and inside the model forward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dlbb_tpu.comm.mesh import MeshSpec, build_mesh
from dlbb_tpu.models.configs import ModelConfig
from dlbb_tpu.models.transformer import forward, init_params
from dlbb_tpu.parallel import ring_attention, ulysses_attention

B, N, S, D = 2, 8, 64, 16


@pytest.fixture(scope="module")
def sp_mesh():
    return build_mesh(MeshSpec.grid((2, 4), ("dp", "sp")))


def _qkv(seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(seed), 3)
    return tuple(
        jax.random.normal(k, (B, N, S, D), dtype=dtype) for k in ks
    )


from conftest import dense_attention_ref


def _dense_causal_ref(q, k, v):
    return dense_attention_ref(q, k, v, causal=True)


@pytest.mark.parametrize("attn", [ring_attention, ulysses_attention])
def test_matches_dense_causal(sp_mesh, attn, devices):
    q, k, v = _qkv()
    expected = _dense_causal_ref(*(np.asarray(t, np.float64) for t in (q, k, v)))
    sharding = NamedSharding(sp_mesh, P("dp", None, "sp", None))
    qs, ks, vs = (jax.device_put(t, sharding) for t in (q, k, v))
    out = np.asarray(attn(qs, ks, vs, sp_mesh))
    np.testing.assert_allclose(out, expected, rtol=2e-4, atol=2e-5)


def test_ulysses_non_causal_matches_dense(sp_mesh, devices):
    """Bidirectional Ulysses == dense non-causal attention (the causal=False
    path added for the long-context configs)."""
    q, k, v = _qkv()
    expected = dense_attention_ref(q, k, v, causal=False)
    sharding = NamedSharding(sp_mesh, P("dp", None, "sp", None))
    qs, ks, vs = (jax.device_put(t, sharding) for t in (q, k, v))
    out = np.asarray(ulysses_attention(qs, ks, vs, sp_mesh, causal=False))
    np.testing.assert_allclose(out, expected, rtol=2e-4, atol=2e-5)


def test_ring_non_causal_matches_dense(sp_mesh, devices):
    """Bidirectional ring attention (mask omitted; same position-agnostic
    ring schedule) == dense non-causal attention."""
    q, k, v = _qkv()
    expected = dense_attention_ref(q, k, v, causal=False)
    sharding = NamedSharding(sp_mesh, P("dp", None, "sp", None))
    qs, ks, vs = (jax.device_put(t, sharding) for t in (q, k, v))
    out = np.asarray(ring_attention(qs, ks, vs, sp_mesh, causal=False))
    np.testing.assert_allclose(out, expected, rtol=2e-4, atol=2e-5)


def _gqa_qkv(kvh, seed=3):
    ks = jax.random.split(jax.random.key(seed), 3)
    q = jax.random.normal(ks[0], (B, N, S, D))
    k = jax.random.normal(ks[1], (B, kvh, S, D))
    v = jax.random.normal(ks[2], (B, kvh, S, D))
    return q, k, v


@pytest.mark.parametrize("attn,kvh,causal", [
    (ring_attention, 4, True),
    (ring_attention, 2, True),   # kvh=2 < sp=4: ring keeps grouped anyway
    (ring_attention, 4, False),
    (ulysses_attention, 4, True),   # kvh == sp — minimum grouped Ulysses
    (ulysses_attention, 4, False),
])
def test_gqa_grouped_matches_repeated_oracle(sp_mesh, attn, kvh, causal,
                                             devices):
    """Grouped K/V through ring/Ulysses == the repeated-K/V fp64 oracle;
    K/V ride the ring / all-to-all at kv_heads width."""
    q, k, v = _gqa_qkv(kvh)
    expected = dense_attention_ref(
        q, np.repeat(np.asarray(k), N // kvh, 1),
        np.repeat(np.asarray(v), N // kvh, 1), causal=causal,
    )
    sharding = NamedSharding(sp_mesh, P("dp", None, "sp", None))
    qs, ks, vs = (jax.device_put(t, sharding) for t in (q, k, v))
    out = np.asarray(attn(qs, ks, vs, sp_mesh, causal=causal))
    np.testing.assert_allclose(out, expected, rtol=2e-4, atol=2e-5)


def test_ulysses_kv_head_divisibility(sp_mesh, devices):
    q, _, _ = _gqa_qkv(2)
    k = v = jnp.zeros((B, 2, S, D))  # kv_heads=2 < sp=4
    with pytest.raises(ValueError, match="kv_heads"):
        ulysses_attention(q, k, v, sp_mesh)


def test_ring_attention_jits_inside_jit(sp_mesh, devices):
    q, k, v = _qkv()
    sharding = NamedSharding(sp_mesh, P("dp", None, "sp", None))
    qs, ks, vs = (jax.device_put(t, sharding) for t in (q, k, v))
    f = jax.jit(lambda a, b, c: ring_attention(a, b, c, sp_mesh))
    out = np.asarray(f(qs, ks, vs))
    expected = _dense_causal_ref(*(np.asarray(t, np.float64) for t in (q, k, v)))
    np.testing.assert_allclose(out, expected, rtol=2e-4, atol=2e-5)


def test_ulysses_head_divisibility(sp_mesh, devices):
    q = k = v = jnp.zeros((B, 6, S, D))  # 6 heads not divisible by sp=4
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(q, k, v, sp_mesh)


@pytest.mark.parametrize("mode", ["ring", "ulysses"])
def test_model_forward_context_parallel(sp_mesh, devices, mode):
    """The full model with attention='ring'/'ulysses' on a (dp, sp) mesh
    must match the single-device full-attention model."""
    cfg = ModelConfig(hidden_size=64, num_layers=2, num_heads=4,
                      ffn_intermediate=128, attention="full", dtype="float32")
    params = init_params(cfg, jax.random.key(1))
    x = jax.random.normal(jax.random.key(2), (2, 32, 64), dtype=jnp.float32)
    y_ref = forward(params, x, cfg)

    cfg_sp = cfg.with_(attention=mode)
    xs = jax.device_put(x, NamedSharding(sp_mesh, P("dp", "sp", None)))
    y_sp = jax.jit(
        lambda p, a: forward(p, a, cfg_sp, mesh=sp_mesh)
    )(params, xs)
    np.testing.assert_allclose(
        np.asarray(y_ref), np.asarray(y_sp), rtol=2e-3, atol=2e-3
    )


@pytest.mark.parametrize("mode", ["ring", "ulysses"])
def test_model_forward_gqa_context_parallel(sp_mesh, devices, mode):
    """Model-level GQA (num_kv_heads=2) through ring/Ulysses on the
    (dp, sp) mesh == the single-device full-attention GQA model.
    sp=4 does not divide kv_heads=2, so Ulysses exercises its documented
    broadcast fallback while ring stays grouped."""
    cfg = ModelConfig(hidden_size=64, num_layers=2, num_heads=4,
                      num_kv_heads=2, ffn_intermediate=128,
                      attention="full", dtype="float32")
    params = init_params(cfg, jax.random.key(3))
    x = jax.random.normal(jax.random.key(4), (2, 32, 64), dtype=jnp.float32)
    y_ref = forward(params, x, cfg)
    cfg_sp = cfg.with_(attention=mode)
    xs = jax.device_put(x, NamedSharding(sp_mesh, P("dp", "sp", None)))
    y_sp = jax.jit(
        lambda p, a: forward(p, a, cfg_sp, mesh=sp_mesh)
    )(params, xs)
    np.testing.assert_allclose(
        np.asarray(y_ref), np.asarray(y_sp), rtol=2e-3, atol=2e-3
    )


def test_model_forward_ring_non_causal(sp_mesh, devices):
    """causal=False end-to-end through the model's ring path (the config
    restriction that rejected this combination is gone)."""
    cfg = ModelConfig(hidden_size=64, num_layers=2, num_heads=4,
                      causal=False, ffn_intermediate=128,
                      attention="full", dtype="float32")
    params = init_params(cfg, jax.random.key(5))
    x = jax.random.normal(jax.random.key(6), (2, 32, 64), dtype=jnp.float32)
    y_ref = forward(params, x, cfg)
    cfg_sp = cfg.with_(attention="ring")
    xs = jax.device_put(x, NamedSharding(sp_mesh, P("dp", "sp", None)))
    y_sp = jax.jit(
        lambda p, a: forward(p, a, cfg_sp, mesh=sp_mesh)
    )(params, xs)
    np.testing.assert_allclose(
        np.asarray(y_ref), np.asarray(y_sp), rtol=2e-3, atol=2e-3
    )


def test_model_forward_sp_requires_mesh(devices):
    cfg = ModelConfig(hidden_size=64, num_layers=1, num_heads=4,
                      ffn_intermediate=128, attention="ring", dtype="float32")
    params = init_params(cfg, jax.random.key(1))
    x = jnp.zeros((1, 16, 64))
    with pytest.raises(ValueError, match="needs a mesh"):
        forward(params, x, cfg)
