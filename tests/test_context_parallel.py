"""Sequence/context parallelism correctness: ring attention and Ulysses must
reproduce dense causal attention exactly (up to fp accumulation order), both
standalone and inside the model forward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dlbb_tpu.comm.mesh import MeshSpec, build_mesh
from dlbb_tpu.models.configs import ModelConfig
from dlbb_tpu.models.transformer import forward, init_params
from dlbb_tpu.parallel import ring_attention, ulysses_attention

B, N, S, D = 2, 8, 64, 16


@pytest.fixture(scope="module")
def sp_mesh():
    return build_mesh(MeshSpec.grid((2, 4), ("dp", "sp")))


def _qkv(seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(seed), 3)
    return tuple(
        jax.random.normal(k, (B, N, S, D), dtype=dtype) for k in ks
    )


from conftest import dense_attention_ref


def _dense_causal_ref(q, k, v):
    return dense_attention_ref(q, k, v, causal=True)


@pytest.mark.parametrize("attn", [ring_attention, ulysses_attention])
def test_matches_dense_causal(sp_mesh, attn, devices):
    q, k, v = _qkv()
    expected = _dense_causal_ref(*(np.asarray(t, np.float64) for t in (q, k, v)))
    sharding = NamedSharding(sp_mesh, P("dp", None, "sp", None))
    qs, ks, vs = (jax.device_put(t, sharding) for t in (q, k, v))
    out = np.asarray(attn(qs, ks, vs, sp_mesh))
    np.testing.assert_allclose(out, expected, rtol=2e-4, atol=2e-5)


def test_ulysses_non_causal_matches_dense(sp_mesh, devices):
    """Bidirectional Ulysses == dense non-causal attention (the causal=False
    path added for the long-context configs)."""
    q, k, v = _qkv()
    expected = dense_attention_ref(q, k, v, causal=False)
    sharding = NamedSharding(sp_mesh, P("dp", None, "sp", None))
    qs, ks, vs = (jax.device_put(t, sharding) for t in (q, k, v))
    out = np.asarray(ulysses_attention(qs, ks, vs, sp_mesh, causal=False))
    np.testing.assert_allclose(out, expected, rtol=2e-4, atol=2e-5)


def test_ring_attention_jits_inside_jit(sp_mesh, devices):
    q, k, v = _qkv()
    sharding = NamedSharding(sp_mesh, P("dp", None, "sp", None))
    qs, ks, vs = (jax.device_put(t, sharding) for t in (q, k, v))
    f = jax.jit(lambda a, b, c: ring_attention(a, b, c, sp_mesh))
    out = np.asarray(f(qs, ks, vs))
    expected = _dense_causal_ref(*(np.asarray(t, np.float64) for t in (q, k, v)))
    np.testing.assert_allclose(out, expected, rtol=2e-4, atol=2e-5)


def test_ulysses_head_divisibility(sp_mesh, devices):
    q = k = v = jnp.zeros((B, 6, S, D))  # 6 heads not divisible by sp=4
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(q, k, v, sp_mesh)


@pytest.mark.parametrize("mode", ["ring", "ulysses"])
def test_model_forward_context_parallel(sp_mesh, devices, mode):
    """The full model with attention='ring'/'ulysses' on a (dp, sp) mesh
    must match the single-device full-attention model."""
    cfg = ModelConfig(hidden_size=64, num_layers=2, num_heads=4,
                      ffn_intermediate=128, attention="full", dtype="float32")
    params = init_params(cfg, jax.random.key(1))
    x = jax.random.normal(jax.random.key(2), (2, 32, 64), dtype=jnp.float32)
    y_ref = forward(params, x, cfg)

    cfg_sp = cfg.with_(attention=mode)
    xs = jax.device_put(x, NamedSharding(sp_mesh, P("dp", "sp", None)))
    y_sp = jax.jit(
        lambda p, a: forward(p, a, cfg_sp, mesh=sp_mesh)
    )(params, xs)
    np.testing.assert_allclose(
        np.asarray(y_ref), np.asarray(y_sp), rtol=2e-3, atol=2e-3
    )


def test_model_forward_sp_requires_mesh(devices):
    cfg = ModelConfig(hidden_size=64, num_layers=1, num_heads=4,
                      ffn_intermediate=128, attention="ring", dtype="float32")
    params = init_params(cfg, jax.random.key(1))
    x = jnp.zeros((1, 16, 64))
    with pytest.raises(ValueError, match="needs a mesh"):
        forward(params, x, cfg)
