"""Resilience subsystem tests (``dlbb_tpu/resilience/``, PR 5).

The fault matrix: every injection site fires deterministically under a
seeded plan and an inactive plan is a provable no-op; the hardened sweep
driver retries transients (recomputing from scratch), quarantines
permanent failures with their exception chain, abandons hung units at
the watchdog deadline while the pipeline drains, survives torn writes
(resume re-validates instead of trusting existence), and turns SIGTERM
into a journaled stop a ``--resume`` run completes exactly; checkpoint
integrity refuses corrupt steps and falls back to the newest intact one.
"""

import ast
import json
import os
import signal
import time
from pathlib import Path

import numpy as np
import pytest

from dlbb_tpu.bench import Sweep1D, run_sweep
from dlbb_tpu.resilience import inject
from dlbb_tpu.resilience.errors import (
    CorruptStats,
    DeadlineExceeded,
    TransientFault,
    exception_chain,
    is_transient,
)
from dlbb_tpu.resilience.journal import (
    SweepJournal,
    read_journal,
    started_not_completed,
)
from dlbb_tpu.resilience.preempt import PreemptionGuard
from dlbb_tpu.resilience.validate import (
    validate_result_json,
    validate_timings,
)
from dlbb_tpu.utils.config import atomic_write_text, save_json

REPO = Path(__file__).resolve().parents[1]


def _tiny(tmp_path, out="results", **kw):
    defaults = dict(
        implementation="rt",
        operations=("allreduce", "broadcast"),
        data_sizes=(("1KB", 256),),
        rank_counts=(4,),
        dtype="float32",
        warmup_iterations=1,
        measurement_iterations=3,
        output_dir=str(tmp_path / out),
        compile_cache="off",
        pipeline=True,
    )
    defaults.update(kw)
    return Sweep1D(**defaults)


def _manifest(tmp_path, out="results"):
    return json.loads(
        (tmp_path / out / "sweep_manifest.json").read_text()
    )


# ---------------------------------------------------------------------------
# fault plan parsing / determinism
# ---------------------------------------------------------------------------


def test_fault_plan_triggers_deterministic():
    plan = inject.FaultPlan.parse("exec-transient:2,stats-nan:@3")
    fires = [plan.fire("exec-transient") for _ in range(4)]
    assert fires == [True, True, False, False]
    fires = [plan.fire("stats-nan") for _ in range(4)]
    assert fires == [False, False, True, False]
    assert plan.fired == [("exec-transient", 1), ("exec-transient", 2),
                          ("stats-nan", 3)]
    # an unlisted site never fires and burns no bookkeeping
    assert plan.fire("torn-write") is False
    assert "torn-write" not in plan.hits


def test_fault_plan_probabilistic_seeded():
    """The p-trigger is a seeded coin: two identically-seeded plans agree
    hit for hit (crc32-based site seed, stable across processes)."""
    a = inject.FaultPlan.parse("exec-transient:p0.5,seed=7")
    b = inject.FaultPlan.parse("exec-transient:p0.5,seed=7")
    seq_a = [a.fire("exec-transient") for _ in range(32)]
    seq_b = [b.fire("exec-transient") for _ in range(32)]
    assert seq_a == seq_b
    assert True in seq_a and False in seq_a  # a real coin, not a constant
    c = inject.FaultPlan.parse("exec-transient:p0.5,seed=8")
    assert [c.fire("exec-transient") for _ in range(32)] != seq_a


def test_fault_plan_rejects_unknown():
    with pytest.raises(ValueError, match="unknown fault site"):
        inject.FaultPlan.parse("no-such-site:1")
    with pytest.raises(ValueError, match="unknown fault-plan parameter"):
        inject.FaultPlan.parse("nope=3")


def test_inactive_plan_is_noop():
    assert inject.active() is None
    assert inject.fire("exec-transient") is False
    with inject.plan_scope("exec-transient:1") as plan:
        assert inject.fire("exec-transient") is True
        assert plan.fired == [("exec-transient", 1)]
    assert inject.active() is None and inject.fire("exec-transient") is False


def test_timed_regions_carry_zero_injection_instructions():
    """The zero-overhead contract, statically: ``utils/timing.py`` — the
    only module that brackets device work with clocks — must never
    reference the resilience package, so an inactive (or even active)
    plan adds zero instructions to any timed region."""
    src = (REPO / "dlbb_tpu" / "utils" / "timing.py").read_text()
    assert "resilience" not in src and "inject" not in src
    # and the runner's injection sites live outside time_collective: the
    # only statements between the gate acquisition and the measurement
    # call are the try that wraps it
    tree = ast.parse((REPO / "dlbb_tpu" / "bench" / "runner.py").read_text())
    for node in ast.walk(tree):
        if isinstance(node, ast.With):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    name = getattr(sub.func, "attr", "")
                    assert name != "fire", (
                        "inject.fire inside a with-block of runner.py — "
                        "possible timed-region injection"
                    )


def test_is_transient_taxonomy():
    assert is_transient(TransientFault("x"))
    assert is_transient(CorruptStats("x"))
    assert not is_transient(RuntimeError("x"))
    assert not is_transient(DeadlineExceeded("u", 1.0))
    chain = exception_chain(ValueError("inner"))
    assert chain["chain"][0]["type"] == "ValueError" and chain["traceback"]


# ---------------------------------------------------------------------------
# atomic writes + validation
# ---------------------------------------------------------------------------


def test_atomic_write_text_replaces_and_cleans_tmp(tmp_path):
    p = tmp_path / "a" / "x.json"
    atomic_write_text("one", p)
    assert p.read_text() == "one"
    atomic_write_text("two", p)
    assert p.read_text() == "two"
    assert list(p.parent.glob("*.tmp")) == []


def test_save_json_torn_write_injection(tmp_path):
    """The torn-write site models the legacy non-atomic writer: a
    truncated JSON lands at the FINAL path and the writer 'crashes' —
    exactly what resume re-validation must refuse."""
    p = tmp_path / "r.json"
    with inject.plan_scope("torn-write:@1"):
        with pytest.raises(inject.TornWrite):
            save_json({"operation": "x", "timings": [[1.0]]}, p)
    assert p.exists()
    ok, why = validate_result_json(p)
    assert not ok and "unparseable" in why
    # the next save (site exhausted) repairs it atomically
    with inject.plan_scope("torn-write:@1") as plan:
        plan.fire("torn-write")  # burn the single trigger
        save_json({"implementation": "i", "operation": "x", "num_ranks": 2,
                   "num_elements": 4, "timings": [[1.0, 2.0]]}, p)
    assert validate_result_json(p)[0]


def test_validate_result_json_rejects_corruption(tmp_path):
    good = {"implementation": "i", "operation": "allreduce", "num_ranks": 2,
            "num_elements": 4, "timings": [[1e-3, 2e-3]]}
    p = tmp_path / "g.json"
    save_json(good, p)
    assert validate_result_json(p) == (True, "ok")
    assert validate_result_json(tmp_path / "missing.json")[1] == "missing"
    (tmp_path / "torn.json").write_text(json.dumps(good)[:25])
    assert "unparseable" in validate_result_json(tmp_path / "torn.json")[1]
    bad = dict(good, timings=[[1e-3, float("nan")]])
    (tmp_path / "nan.json").write_text(
        json.dumps(bad).replace("NaN", "NaN"))
    assert "non-finite" in validate_result_json(tmp_path / "nan.json")[1]
    missing = {k: v for k, v in good.items() if k != "timings"}
    save_json(missing, tmp_path / "m.json")
    assert "missing fields" in validate_result_json(tmp_path / "m.json")[1]
    save_json(dict(good, timings=[]), tmp_path / "e.json")
    assert "empty" in validate_result_json(tmp_path / "e.json")[1]
    assert not validate_timings([[1.0, float("inf")]])[0]
    assert validate_timings([[1.0, 2.0]])[0]


def test_journal_appends_and_tolerates_torn_tail(tmp_path):
    with SweepJournal(tmp_path, meta={"kind": "1d"}) as j:
        j.event("planned", config="a.json")
        j.event("started", config="a.json")
        j.event("completed", config="a.json")
        j.event("started", config="b.json")
    # simulate a crash mid-append: torn trailing line
    with open(tmp_path / "sweep_journal.jsonl", "a") as f:
        f.write('{"ts": 1, "event": "comp')
    events, torn = read_journal(tmp_path)
    assert torn == 1
    assert [e["event"] for e in events] == [
        "sweep-start", "planned", "started", "completed", "started"]
    assert started_not_completed(events) == {"b.json"}
    # append-only across sessions: a resumed run adds its own marker
    with SweepJournal(tmp_path, meta={"resume": True}) as j:
        j.event("resume-valid", config="a.json")
    events, _ = read_journal(tmp_path)
    assert [e["event"] for e in events].count("sweep-start") == 2


# ---------------------------------------------------------------------------
# hardened sweep driver (the fault matrix through the real engine)
# ---------------------------------------------------------------------------


@pytest.mark.chaos_smoke
def test_sweep_transient_retried_and_flagged(tmp_path, devices):
    files = run_sweep(_tiny(tmp_path, fault_plan="exec-transient:1",
                            max_retries=2), verbose=False)
    assert len(files) == 2
    retries = sorted(json.loads(f.read_text())["retries"] for f in files)
    assert retries == [0, 1]
    man = _manifest(tmp_path)
    assert man["resilience"]["retries_total"] == 1
    assert man["configs"]["failed"] == 0
    for f in files:
        assert validate_result_json(f)[0]
    events, _ = read_journal(tmp_path / "results")
    assert any(e["event"] == "retry" for e in events)


@pytest.mark.chaos_smoke
def test_sweep_nan_stats_never_written(tmp_path, devices):
    """Injected NaN/Inf in the timing vector is caught BEFORE the write
    and the config re-measures from scratch — no corrupt artifact ever
    exists on disk, even transiently under the atomic writer."""
    files = run_sweep(_tiny(tmp_path, fault_plan="stats-nan:1",
                            max_retries=2), verbose=False)
    assert len(files) == 2
    for f in files:
        ok, why = validate_result_json(f)
        assert ok, why
    assert sum(json.loads(f.read_text())["retries"] for f in files) == 1


def test_sweep_transient_exhausted_is_quarantined(tmp_path, devices):
    """A transient that keeps firing past max_retries fails CLOSED: the
    config lands in the manifest with its exception chain and the journal
    records failed — never a silent skip."""
    files = run_sweep(_tiny(tmp_path, fault_plan="exec-transient:*",
                            max_retries=1), verbose=False)
    assert files == []
    man = _manifest(tmp_path)
    assert man["configs"]["failed"] == 2
    q = man["resilience"]["quarantined"]
    assert len(q) == 2
    for rec in q:
        assert rec["retries"] == 1
        assert "TransientFault" in rec["error"]
        assert rec["traceback"]
    events, _ = read_journal(tmp_path / "results")
    assert sum(1 for e in events if e["event"] == "failed") == 2


@pytest.mark.chaos_smoke
def test_sweep_torn_write_resume_revalidates(tmp_path, devices):
    run_sweep(_tiny(tmp_path, fault_plan="torn-write:@1", max_retries=0),
              verbose=False)
    out = tmp_path / "results"
    torn = [p for p in out.glob("rt_*.json")
            if not validate_result_json(p)[0]]
    assert len(torn) == 1
    files = run_sweep(_tiny(tmp_path, resume=True), verbose=False)
    assert len(files) == 2
    for f in files:
        assert validate_result_json(f)[0]
    events, _ = read_journal(out)
    invalid = [e for e in events if e["event"] == "resume-invalid"]
    assert len(invalid) == 1 and invalid[0]["config"] == torn[0].name
    man = _manifest(tmp_path)
    assert man["configs"]["resume_invalid"] == 1
    assert man["configs"]["resumed"] == 1


def test_sweep_resume_trusts_only_valid_artifacts(tmp_path, devices):
    """The PR-5 headline fix: resume no longer trusts existence.  A valid
    artifact is skipped untouched; a truncated one re-measures."""
    first = run_sweep(_tiny(tmp_path), verbose=False)
    assert len(first) == 2
    victim, kept = sorted(first)
    victim.write_text(victim.read_text()[:30])  # torn
    kept_mtime = kept.stat().st_mtime_ns
    resumed = run_sweep(_tiny(tmp_path, resume=True), verbose=False)
    assert sorted(resumed) == sorted(first)
    assert kept.stat().st_mtime_ns == kept_mtime, "valid artifact re-ran"
    assert validate_result_json(victim)[0], "torn artifact not re-measured"


def test_sweep_compile_failure_quarantined_with_chain(tmp_path, devices):
    files = run_sweep(_tiny(tmp_path, fault_plan="compile-fail:@1",
                            max_retries=0), verbose=False)
    assert len(files) == 1
    man = _manifest(tmp_path)
    assert man["configs"]["failed"] == 1
    [q] = man["resilience"]["quarantined"]
    assert q["phase"] == "compile" and "InjectedFault" in q["error"]


@pytest.mark.chaos_smoke
def test_sweep_hung_unit_watchdog_quarantine_and_drain(tmp_path, devices):
    """A hung measurement is abandoned at the deadline and quarantined;
    the rest of the grid still measures and the sweep returns long before
    the hang would — the pipeline drain is never blocked.

    The injected hang is 120s against a 60s wall budget: on a loaded
    host the mini-sweep's own compile+measure time can exceed the old
    25s-vs-30s margin (the tier-1 flake fixed in PR 11), but it cannot
    approach 60s without the 120s sleep — so the assertion now
    separates "blocked behind the hang" from "slow host" cleanly.  The
    abandoned sleeper is a daemon thread; it never outlives the test
    process."""
    t0 = time.perf_counter()
    files = run_sweep(
        _tiny(tmp_path, fault_plan="exec-hang:@1,hang_seconds=120",
              unit_deadline_seconds=0.75, max_retries=0),
        verbose=False,
    )
    wall = time.perf_counter() - t0
    assert len(files) == 1
    assert wall < 60.0, f"sweep blocked behind the hang ({wall:.1f}s)"
    man = _manifest(tmp_path)
    assert man["resilience"]["watchdog"]["abandoned_measurements"] == 1
    assert man["resilience"]["watchdog"]["gate_degraded"] is True
    [q] = man["resilience"]["quarantined"]
    assert "DeadlineExceeded" in q["error"]
    assert validate_result_json(files[0])[0]


def test_scheduler_abandoned_unit_never_recompiled_inline():
    """A build that already blew its compile deadline must not be re-run
    inline for a config that shares the unit — a deterministically
    hanging build would hang the consumer thread, where no watchdog
    applies.  Every later consumer quarantines fast instead."""
    import threading

    from dlbb_tpu.bench.schedule import CompileAheadScheduler, WorkUnit

    release = threading.Event()

    def hang_build():
        release.wait(20)
        return (lambda x: x), (lambda x: x)

    unit = WorkUnit(key=("hang",), build=hang_build, label="hang")
    sched = CompileAheadScheduler([unit], pipeline=True)
    sched.start()
    try:
        with pytest.raises(DeadlineExceeded):
            sched.get(unit, deadline=0.3)
        assert sched.wedged
        t0 = time.perf_counter()
        with pytest.raises(DeadlineExceeded, match="previously abandoned"):
            sched.get(unit, deadline=0.3)
        # the second consumer did NOT sit in the hanging build inline
        assert time.perf_counter() - t0 < 5.0
    finally:
        release.set()
        sched.close()


def test_watchdog_zombie_write_suppressed(tmp_path, devices):
    """An abandoned measurement thread that wakes up AFTER its config was
    quarantined must not write its artifact — resume and the stats
    pipeline would trust a file the manifest says failed."""
    run_sweep(
        _tiny(tmp_path, fault_plan="exec-hang:@1,hang_seconds=2",
              unit_deadline_seconds=0.5, max_retries=0),
        verbose=False,
    )
    man = _manifest(tmp_path)
    [q] = man["resilience"]["quarantined"]
    quarantined_file = tmp_path / "results" / q["config"]
    # wait past the zombie's wake-up + measurement; its write must have
    # been suppressed by the cancellation token
    time.sleep(3.5)
    assert not quarantined_file.exists(), (
        "zombie thread resurrected a quarantined config on disk"
    )


def test_sweep_hung_compile_wedge_inline_fallback(tmp_path, devices):
    """A wedged background compile is abandoned at the deadline; later
    units compile inline on the consumer thread (the worker is stuck) so
    the rest of the grid still measures."""
    files = run_sweep(
        _tiny(tmp_path, fault_plan="compile-hang:@1,hang_seconds=6",
              unit_deadline_seconds=0.75, max_retries=0),
        verbose=False,
    )
    assert len(files) == 1
    man = _manifest(tmp_path)
    wd = man["resilience"]["watchdog"]
    assert wd["abandoned_compiles"] == 1 and wd["scheduler_wedged"]
    assert validate_result_json(files[0])[0]


@pytest.mark.chaos_smoke
def test_sweep_preemption_journaled_resume_equivalent(tmp_path, devices):
    """SIGTERM between configs -> graceful journaled stop; a --resume run
    completes the grid with the same artifact set (names, schema keys,
    finite stats) as an uninterrupted run."""
    ref = run_sweep(_tiny(tmp_path, out="ref"), verbose=False)
    files = run_sweep(_tiny(tmp_path, fault_plan="preempt:@2"),
                      verbose=False)
    assert len(files) == 1
    # the handler was restored: SIGTERM disposition is back to default
    assert signal.getsignal(signal.SIGTERM) in (
        signal.SIG_DFL, signal.default_int_handler, signal.SIG_IGN,
    ) or callable(signal.getsignal(signal.SIGTERM))
    man = _manifest(tmp_path)
    assert man["resilience"]["preempted"] is True
    events, _ = read_journal(tmp_path / "results")
    assert any(e["event"] == "preempted" for e in events)
    resumed = run_sweep(_tiny(tmp_path, resume=True), verbose=False)
    assert sorted(p.name for p in resumed) == sorted(p.name for p in ref)
    for got in resumed:
        want = json.loads((tmp_path / "ref" / got.name).read_text())
        have = json.loads(got.read_text())
        assert sorted(have) == sorted(want), got.name
        assert validate_result_json(got)[0]


def test_sweep_without_plan_has_no_resilience_cost(tmp_path, devices):
    """No active plan: artifacts carry retries=0, the manifest's
    resilience block shows a clean run, and no injection bookkeeping
    exists (fire() was a pure no-op throughout)."""
    assert inject.active() is None
    files = run_sweep(_tiny(tmp_path), verbose=False)
    assert all(json.loads(f.read_text())["retries"] == 0 for f in files)
    man = _manifest(tmp_path)
    r = man["resilience"]
    assert r["fault_plan"] is None
    assert r["retries_total"] == 0 and r["quarantined"] == []
    assert r["watchdog"]["abandoned_measurements"] == 0
    assert r["preempted"] is False


# ---------------------------------------------------------------------------
# checkpoint integrity
# ---------------------------------------------------------------------------


def _state(step):
    import jax.numpy as jnp

    from dlbb_tpu.train.loop import TrainState

    return TrainState({"w": jnp.full((8, 8), float(step))},
                      {"m": jnp.zeros((8,))},
                      jnp.asarray(step, jnp.int32))


@pytest.mark.chaos_smoke
def test_checkpoint_corruption_falls_back_to_intact_step(tmp_path, devices):
    from dlbb_tpu.resilience.errors import CheckpointCorruption
    from dlbb_tpu.train.checkpoint import CheckpointConfig, Checkpointer

    with Checkpointer(CheckpointConfig(str(tmp_path / "ck"),
                                       max_to_keep=5)) as ckpt:
        for s in (1, 2, 3):
            assert ckpt.maybe_save(_state(s), force=True)
        assert ckpt.verify_step(3) == (True, "ok")
        ckpt._corrupt_step(3)
        ok, why = ckpt.verify_step(3)
        assert not ok and ("mismatch" in why or "missing" in why)
        assert ckpt.latest_intact_step() == 2
        restored = ckpt.restore_or(_state(0))
        assert int(restored.step) == 2
        assert float(restored.params["w"][0, 0]) == 2.0
        with pytest.raises(CheckpointCorruption):
            ckpt.restore(_state(0), step=3)


def test_checkpoint_corrupt_injection_site(tmp_path, devices):
    from dlbb_tpu.train.checkpoint import CheckpointConfig, Checkpointer

    with inject.plan_scope("ckpt-corrupt:@2"):
        with Checkpointer(CheckpointConfig(str(tmp_path / "ck"),
                                           max_to_keep=5)) as ckpt:
            ckpt.maybe_save(_state(1), force=True)
            ckpt.maybe_save(_state(2), force=True)  # fires -> corrupts
            restored = ckpt.restore_or(_state(0))
            assert int(restored.step) == 1


def test_checkpoint_all_corrupt_returns_initial(tmp_path, devices, capsys):
    from dlbb_tpu.train.checkpoint import CheckpointConfig, Checkpointer

    with Checkpointer(CheckpointConfig(str(tmp_path / "ck"),
                                       max_to_keep=5)) as ckpt:
        ckpt.maybe_save(_state(1), force=True)
        ckpt._corrupt_step(1)
        initial = _state(0)
        restored = ckpt.restore_or(initial)
        assert int(restored.step) == 0
    out = capsys.readouterr().out
    assert "integrity FAILED" in out and "no intact checkpoint" in out


def test_checkpoint_legacy_without_manifest_still_restores(tmp_path,
                                                           devices):
    """A checkpoint saved before the integrity subsystem (no manifest)
    keeps restoring — accepted as 'unverified', not rejected."""
    from dlbb_tpu.train.checkpoint import (
        INTEGRITY_DIRNAME,
        CheckpointConfig,
        Checkpointer,
    )

    d = tmp_path / "ck"
    with Checkpointer(CheckpointConfig(str(d), max_to_keep=5)) as ckpt:
        ckpt.maybe_save(_state(1), force=True)
        m = d / INTEGRITY_DIRNAME / "1.json"
        assert m.exists()
        m.unlink()  # pre-PR5 checkpoint: no manifest
        ok, why = ckpt.verify_step(1)
        assert ok and "unverified" in why
        assert int(ckpt.restore_or(_state(0)).step) == 1


# ---------------------------------------------------------------------------
# preemption guard + train loop
# ---------------------------------------------------------------------------


def test_preemption_guard_flag_and_restore():
    prev = signal.getsignal(signal.SIGTERM)
    with PreemptionGuard() as guard:
        assert guard.installed
        assert not guard.requested
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.time() + 5.0
        while not guard.requested and time.time() < deadline:
            time.sleep(0.01)
        assert guard.requested
        assert guard.signal_received == signal.SIGTERM
    assert signal.getsignal(signal.SIGTERM) == prev


def test_train_preemption_forces_final_save(tmp_path, devices):
    """SIGTERM mid-train breaks the loop and forces the final checkpoint
    save — the restore after preemption starts from the last finished
    step (the Varuna/CheckFreq graceful-preemption contract)."""
    from dlbb_tpu.train.checkpoint import latest_step
    from dlbb_tpu.train.loop import run_train

    config = {
        "experiment": {"name": "preempt_train"},
        "model": {"hidden_size": 32, "num_layers": 2, "num_heads": 4,
                  "ffn_intermediate": 64, "attention": "full",
                  "dtype": "float32"},
        "parallelism": {"world_size": 2, "data_parallel": 4},
        "input": {"batch_size": 8, "sequence_length": 16, "seed": 42},
        "execution": {"warmup_iterations": 1, "benchmark_iterations": 6},
        "training": {"learning_rate": 1e-2,
                     "checkpoint": {"directory": str(tmp_path / "ck")}},
    }
    with inject.plan_scope("preempt:@3"):
        result = run_train(config, verbose=False)
    assert result["preempted_at_step"] is not None
    saved = latest_step(str(tmp_path / "ck"))
    assert saved is not None
    assert saved == result["final_step"]
    # and the saved step passes integrity
    from dlbb_tpu.train.checkpoint import CheckpointConfig, Checkpointer

    with Checkpointer(CheckpointConfig(str(tmp_path / "ck"))) as ckpt:
        ok, why = ckpt.verify_step(saved)
        assert ok, why


# ---------------------------------------------------------------------------
# chaos gate (subprocess class is slow -> tier-1 skips it, CI smoke runs
# the in-process classes through the same entry point as the CLI)
# ---------------------------------------------------------------------------


@pytest.mark.chaos_smoke
def test_chaos_gate_fast_classes(tmp_path, devices):
    from dlbb_tpu.resilience.chaos import run_chaos

    for name in ("transient", "torn"):
        assert run_chaos(plan=name, output=str(tmp_path / name),
                         verbose=False) == 0


def test_chaos_gate_rejects_unknown_class(tmp_path):
    from dlbb_tpu.resilience.chaos import run_chaos

    assert run_chaos(plan="nope", output=str(tmp_path)) == 2


@pytest.mark.slow
def test_chaos_gate_kill_class(tmp_path, devices):
    """The SIGKILL-mid-write class (real subprocesses): atomic writes
    leave no destination artifact, and resume re-measures to a grid
    equivalent to an uninterrupted run — the acceptance invariant."""
    from dlbb_tpu.resilience.chaos import run_chaos

    assert run_chaos(plan="kill", output=str(tmp_path / "kill"),
                     verbose=False) == 0
