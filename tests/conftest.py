"""Test fixtures: CPU-simulated 8-device mesh (default) or the real TPU
chip (``DLBB_TPU_TESTS=1``).

The reference tests "multi-node without a cluster" by running N ranks on one
box under mpirun/torchrun (SURVEY §4).  The JAX analogue is
``--xla_force_host_platform_device_count=8``: eight fake CPU devices in one
process.  Env must be set before jax initialises a backend, hence module
top-level, before any dlbb_tpu import.

``DLBB_TPU_TESTS=1 pytest tests/ -m tpu`` instead runs the ``tpu``-marked
subset on the real chip — the compiled-mosaic regression net for the pallas
kernels (everything else runs them in interpret mode), its log committed
under ``results/tpu_tests/``.  Selection is enforced here: in TPU mode the
simulated-mesh tests are skipped (one physical device), and in default mode
the ``tpu`` tests are.
"""

import os

RUN_TPU_TESTS = os.environ.get("DLBB_TPU_TESTS") == "1"

if not RUN_TPU_TESTS:
    from dlbb_tpu.utils.simulate import force_cpu_simulation

    force_cpu_simulation(8)

import jax  # noqa: E402
import pytest  # noqa: E402

from dlbb_tpu.comm import MeshSpec, build_mesh  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "tpu: needs a real TPU chip (compiled pallas path); run with "
        "DLBB_TPU_TESTS=1 pytest -m tpu",
    )
    config.addinivalue_line(
        "markers",
        "pipeline_smoke: compile-ahead sweep-engine smoke (tier-1; also "
        "invoked standalone by scripts/run_static_analysis.sh)",
    )
    config.addinivalue_line(
        "markers",
        "overlap_smoke: ring-decomposed collective-matmul smoke (tier-1; "
        "also invoked standalone by scripts/run_static_analysis.sh)",
    )
    config.addinivalue_line(
        "markers",
        "chaos_smoke: resilience fault-matrix smoke (tier-1; also invoked "
        "standalone by scripts/run_static_analysis.sh)",
    )
    config.addinivalue_line(
        "markers",
        "compression_smoke: quantised-collective smoke — allreduce_q "
        "variant mini-sweep + one compressed train step (tier-1; also "
        "invoked standalone by scripts/run_static_analysis.sh)",
    )
    config.addinivalue_line(
        "markers",
        "schedule_smoke: α–β schedule-audit smoke — dependency-graph "
        "fixtures + overlap/diff gates (tier-1; also invoked standalone "
        "by scripts/run_static_analysis.sh)",
    )
    config.addinivalue_line(
        "markers",
        "obs_smoke: observability smoke — traced+captured sweep stats "
        "equivalence and the calibration calibrate/diff round trip "
        "(tier-1; also invoked standalone by "
        "scripts/run_static_analysis.sh)",
    )
    config.addinivalue_line(
        "markers",
        "fit_smoke: cost-model fit smoke — cm2 regression on a mini "
        "corpus recovers seeded coefficients, the fitted DB round-trips "
        "through calibrate/diff, degenerate corpora fail closed "
        "(tier-1; also invoked standalone by "
        "scripts/run_static_analysis.sh)",
    )
    config.addinivalue_line(
        "markers",
        "serve_smoke: serving-engine smoke — a seeded 30-request Poisson "
        "mini-trace through the continuous-batching engine with span "
        "trace + journal + metrics export (tier-1; also invoked "
        "standalone by scripts/run_static_analysis.sh)",
    )
    config.addinivalue_line(
        "markers",
        "serve_fastpath_smoke: decode fast-path smoke — per-step and "
        "fused-K engines must produce identical completed-token "
        "sequences on a seeded mini-trace, with schema-valid artifacts "
        "(tier-1; also invoked standalone by "
        "scripts/run_static_analysis.sh)",
    )
    config.addinivalue_line(
        "markers",
        "prefix_smoke: shared-prefix / quantized-KV smoke — prefix-"
        "cached and int8-KV engines must produce identical completed-"
        "token sequences to the no-sharing fp engine on a seeded "
        "shared-prefix mini-trace, with refcount/trie/CoW accounting "
        "consistent at drain (tier-1; also invoked standalone by "
        "scripts/run_static_analysis.sh)",
    )
    config.addinivalue_line(
        "markers",
        "serve_chaos_smoke: serving resilience smoke — seeded "
        "mini-traces per serving fault class (dispatch retry+rollback, "
        "hung-dispatch watchdog, torn bookkeeping, per-request "
        "deadlines, SIGTERM drain + resume equivalence) (tier-1; also "
        "invoked standalone by scripts/run_static_analysis.sh)",
    )
    config.addinivalue_line(
        "markers",
        "memory_smoke: static memory-audit smoke — real serving/train "
        "targets prove donated buffers aliased and the analytic cache "
        "bytes pinned to the compiled carry; seeded violations "
        "(dropped donation, replicated spike) must exit 1 (tier-1; "
        "also invoked standalone by scripts/run_static_analysis.sh)",
    )
    config.addinivalue_line(
        "markers",
        "devtrace_smoke: device-trace analysis smoke — captured "
        "overlap-variant mini-sweep stays stats-equivalent to an "
        "uncaptured run and `obs devtrace` reports measured overlap "
        "beside the static proof (tier-1; also invoked standalone by "
        "scripts/run_static_analysis.sh)",
    )
    config.addinivalue_line(
        "markers",
        "spec_smoke: speculative-decoding smoke — n-gram and "
        "draft-model draft-and-verify engines must stay token-identical "
        "to the per-step greedy oracle on a seeded repeating-structure "
        "mini-trace, with spec-verify journal events and acceptance "
        "counters exported (tier-1; also invoked standalone by "
        "scripts/run_static_analysis.sh)",
    )
    config.addinivalue_line(
        "markers",
        "numerics_smoke: static numerics-audit smoke — seeded "
        "low-precision/upcast/roundtrip HLO fixtures trip every rule, "
        "real targets stay clean, and the fp64 shadow cross-check "
        "confirms the analytic error bound empirically (tier-1; also "
        "invoked standalone by scripts/run_static_analysis.sh)",
    )
    config.addinivalue_line(
        "markers",
        "autotune_smoke: plan-search smoke — the cm2-driven autotuner "
        "enumerates, prunes (every drop journaled with a reason), ranks "
        "deterministically, measures the top-k + mesh champions through "
        "the real serving engine, and the pinned calibration-grid "
        "agreement stays >= 0.70 (tier-1; also invoked standalone by "
        "scripts/run_static_analysis.sh)",
    )
    config.addinivalue_line(
        "markers",
        "fleet_smoke: replica-fleet smoke — a 2-replica fleet on the "
        "simulated mesh routes deterministically with prefix affinity, "
        "survives a replica kill with failover re-prefill and "
        "reference-identical tokens, walks the degradation ladder "
        "monotonically, and the zero-injection pin holds over "
        "serve/fleet.py (tier-1; also invoked standalone by "
        "scripts/run_static_analysis.sh)",
    )
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 `-m 'not slow'` run (subprocess "
        "chaos classes, multi-minute sweeps)",
    )


def pytest_collection_modifyitems(config, items):
    if RUN_TPU_TESTS:
        skip = pytest.mark.skip(
            reason="simulated-mesh test (DLBB_TPU_TESTS=1 runs -m tpu only)"
        )
        for item in items:
            if "tpu" not in item.keywords:
                item.add_marker(skip)
    else:
        skip = pytest.mark.skip(
            reason="needs the real TPU chip (set DLBB_TPU_TESTS=1)"
        )
        for item in items:
            if "tpu" in item.keywords:
                item.add_marker(skip)


def dense_attention_ref(q, k, v, causal=True):
    """fp64 numpy oracle for dense (optionally causal) attention — the one
    numerical reference shared by the model/context-parallel tests."""
    import numpy as np

    q, k, v = (np.asarray(t, np.float64) for t in (q, k, v))
    logits = np.einsum("bnqd,bnkd->bnqk", q, k) / np.sqrt(q.shape[-1])
    if causal:
        s = q.shape[2]
        mask = np.tril(np.ones((s, s), dtype=bool))
        logits = np.where(mask, logits, -np.inf)
    logits = logits - logits.max(-1, keepdims=True)
    p = np.exp(logits)
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bnqk,bnkd->bnqd", p, v)


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 simulated devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session")
def mesh8(devices):
    """Flat 8-rank ring mesh."""
    return build_mesh(MeshSpec.ring(8))


@pytest.fixture(scope="session")
def mesh4(devices):
    return build_mesh(MeshSpec.ring(4))


@pytest.fixture(scope="session")
def mesh2x4(devices):
    """Multi-axis mesh for hierarchical collectives / dp x tp models."""
    return build_mesh(MeshSpec.grid((2, 4), ("dp", "tp")))


@pytest.fixture(scope="session")
def mesh2x2x2(devices):
    return build_mesh(MeshSpec.grid((2, 2, 2), ("x", "y", "z")))
