"""Ring-decomposed collective matmul correctness on the simulated mesh.

The overlap claim rests on two invariants this file pins:

1. **Numerics**: the decomposed schedules (ring, bidir) must be
   value-equivalent to the GSPMD fused path — forward AND backward
   (the custom VJP replaces autodiff) — on every supported mesh shape.
2. **Schedule shape**: the compiled program must actually contain the
   collective-permute chain with no fused collective left (the HLO-audit
   contract, ``analysis/expectations.overlap_op_expectation``; the full
   audit gate runs in test_analysis via the default target registry).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dlbb_tpu.comm.mesh import build_parallelism_mesh
from dlbb_tpu.models.configs import ModelConfig, validate_tp_overlap
from dlbb_tpu.models.sharding import batch_spec
from dlbb_tpu.models.transformer import forward, init_params, shard_params
from dlbb_tpu.parallel.collective_matmul import (
    activation_spec,
    allgather_matmul,
    matmul_reducescatter,
)

TINY = ModelConfig(hidden_size=64, num_layers=2, num_heads=4,
                   ffn_intermediate=128, attention="full", dtype="float32")


def _operands(mesh, b=4, s=16, h=16, f=16, dtype=jnp.float32):
    x = jax.random.normal(jax.random.key(0), (b, s, h), dtype)
    w_col = jax.random.normal(jax.random.key(1), (h, f), dtype)
    w_row = jax.random.normal(jax.random.key(2), (f, h), dtype)
    xs = jax.device_put(x, NamedSharding(mesh, activation_spec(mesh)))
    w_cols = jax.device_put(w_col, NamedSharding(mesh, P(None, "tp")))
    w_rows = jax.device_put(w_row, NamedSharding(mesh, P("tp", None)))
    return (x, w_col, w_row), (xs, w_cols, w_rows)


MESHES = {
    "dp2xtp4": dict(data_parallel=2, tensor_parallel=4),
    "tp8": dict(data_parallel=1, tensor_parallel=8),
    "dp2xsp2xtp2": dict(data_parallel=2, sequence_parallel=2,
                        tensor_parallel=2),
}


@pytest.mark.parametrize("mesh_name", sorted(MESHES))
@pytest.mark.parametrize("schedule", ["ring", "bidir"])
def test_primitives_match_unsharded(devices, mesh_name, schedule):
    """allgather_matmul / matmul_reducescatter == plain matmul chain on
    (dp,tp), flat tp, and (dp,sp,tp) meshes, forward and grad (the custom
    VJP vs autodiff of the unsharded reference)."""
    mesh = build_parallelism_mesh(**MESHES[mesh_name])
    (x, w1, w2), (xs, w1s, w2s) = _operands(mesh)

    y = jax.jit(
        lambda a, b: allgather_matmul(a, b, mesh, schedule=schedule)
    )(xs, w1s)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x @ w1),
                               rtol=1e-5, atol=1e-5)
    z = jax.jit(
        lambda a, b, c: matmul_reducescatter(
            allgather_matmul(a, b, mesh, schedule=schedule), c, mesh,
            schedule=schedule)
    )(xs, w1s, w2s)
    np.testing.assert_allclose(np.asarray(z), np.asarray((x @ w1) @ w2),
                               rtol=1e-4, atol=1e-4)

    def loss_overlap(a, b, c):
        return jnp.sum(matmul_reducescatter(
            allgather_matmul(a, b, mesh, schedule=schedule), c, mesh,
            schedule=schedule) ** 2)

    def loss_ref(a, b, c):
        return jnp.sum(((a @ b) @ c) ** 2)

    got = jax.jit(jax.grad(loss_overlap, argnums=(0, 1, 2)))(xs, w1s, w2s)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(x, w1, w2)
    for g, r in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=1e-4, atol=1e-4)


def test_uneven_shard_counts_rejected(devices):
    """Sequence or weight dims that do not divide the ring must fail at
    trace time with a clear message, never silently mis-shard."""
    mesh = build_parallelism_mesh(data_parallel=2, tensor_parallel=4)
    with pytest.raises(ValueError, match="not divisible by the"):
        allgather_matmul(jnp.ones((2, 10, 8)), jnp.ones((8, 12)), mesh)
    with pytest.raises(ValueError, match="weight dim .* not divisible"):
        allgather_matmul(jnp.ones((2, 16, 8)), jnp.ones((8, 10)), mesh)
    with pytest.raises(ValueError, match="weight dim .* not divisible"):
        matmul_reducescatter(jnp.ones((2, 16, 8)), jnp.ones((10, 8)), mesh)
    with pytest.raises(ValueError, match="unknown tp_overlap schedule"):
        allgather_matmul(jnp.ones((2, 16, 8)), jnp.ones((8, 16)), mesh,
                         schedule="zigzag")
    from dlbb_tpu.comm.mesh import MeshSpec, build_mesh

    no_tp = build_mesh(MeshSpec.ring(8))  # "ranks" axis only
    with pytest.raises(ValueError, match="no 'tp' axis"):
        allgather_matmul(jnp.ones((2, 16, 8)), jnp.ones((8, 16)), no_tp)


@pytest.mark.overlap_smoke
@pytest.mark.parametrize("schedule", ["ring", "bidir"])
def test_forward_overlap_matches_gspmd(mesh2x4, schedule):
    """Model-level gate (also run standalone by
    scripts/run_static_analysis.sh): tp_overlap=ring|bidir forward ==
    the off (GSPMD fused) path on the dp2 x tp4 mesh."""
    params = init_params(TINY, jax.random.key(1))
    x = jax.random.normal(jax.random.key(0), (4, 16, 64), jnp.float32)
    sharded = shard_params(params, mesh2x4)
    xs = jax.device_put(x, NamedSharding(mesh2x4, batch_spec(mesh2x4)))
    out_sh = NamedSharding(mesh2x4, batch_spec(mesh2x4))
    y_off = jax.jit(lambda p, a: forward(p, a, TINY, mesh=mesh2x4),
                    out_shardings=out_sh)(sharded, xs)
    cfg = TINY.with_(tp_overlap=schedule)
    y = jax.jit(lambda p, a: forward(p, a, cfg, mesh=mesh2x4),
                out_shardings=out_sh)(sharded, xs)
    np.testing.assert_allclose(np.asarray(y_off), np.asarray(y),
                               rtol=2e-4, atol=2e-4)


def test_forward_overlap_bf16_tolerance(mesh2x4):
    """The acceptance dtype: bf16 overlapped forward matches the fused
    path within bf16 tolerances (ring adds sequentially where the fused
    all-reduce adds in XLA's order — both bf16-rounded)."""
    cfg16 = TINY.with_(dtype="bfloat16")
    params = init_params(cfg16, jax.random.key(1))
    x = jax.random.normal(jax.random.key(0), (4, 16, 64), jnp.bfloat16)
    sharded = shard_params(params, mesh2x4)
    xs = jax.device_put(x, NamedSharding(mesh2x4, batch_spec(mesh2x4)))
    out_sh = NamedSharding(mesh2x4, batch_spec(mesh2x4))
    y_off = jax.jit(lambda p, a: forward(p, a, cfg16, mesh=mesh2x4),
                    out_shardings=out_sh)(sharded, xs)
    for schedule in ("ring", "bidir"):
        cfg = cfg16.with_(tp_overlap=schedule)
        y = jax.jit(lambda p, a: forward(p, a, cfg, mesh=mesh2x4),
                    out_shardings=out_sh)(sharded, xs)
        np.testing.assert_allclose(
            np.asarray(y_off, np.float32), np.asarray(y, np.float32),
            rtol=2e-2, atol=2e-2,
        )


def test_forward_overlap_with_sp_mesh(devices):
    """tp_overlap composes with a sequence-parallel axis: on the
    (dp, sp, tp) mesh the residual stream is sequence-sharded over
    (sp, tp) and ring attention sees exactly the layout the off path
    gives it."""
    cfg_off = TINY.with_(attention="ring")
    mesh = build_parallelism_mesh(data_parallel=2, sequence_parallel=2,
                                  tensor_parallel=2)
    params = init_params(cfg_off, jax.random.key(1))
    x = jax.random.normal(jax.random.key(0), (4, 16, 64), jnp.float32)
    sharded = shard_params(params, mesh)
    xs = jax.device_put(x, NamedSharding(mesh, batch_spec(mesh)))
    out_sh = NamedSharding(mesh, batch_spec(mesh))
    y_off = jax.jit(lambda p, a: forward(p, a, cfg_off, mesh=mesh),
                    out_shardings=out_sh)(sharded, xs)
    for schedule in ("ring", "bidir"):
        cfg = cfg_off.with_(tp_overlap=schedule)
        y = jax.jit(lambda p, a: forward(p, a, cfg, mesh=mesh),
                    out_shardings=out_sh)(sharded, xs)
        np.testing.assert_allclose(np.asarray(y_off), np.asarray(y),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("schedule", ["ring", "bidir"])
def test_train_grads_match_fused(mesh2x4, schedule):
    """Custom-VJP gradients through the full model == autodiff of the
    fused GSPMD path (the train-step backward is this composition)."""
    from dlbb_tpu.train.loop import mse_loss

    params = init_params(TINY, jax.random.key(1))
    sharded = shard_params(params, mesh2x4)
    sh = NamedSharding(mesh2x4, batch_spec(mesh2x4))
    x = jax.device_put(
        jax.random.normal(jax.random.key(0), (4, 16, 64), jnp.float32), sh)
    t = jax.device_put(
        jax.random.normal(jax.random.key(2), (4, 16, 64), jnp.float32), sh)
    cfg = TINY.with_(tp_overlap=schedule)
    g_off = jax.jit(
        lambda p, a, b: jax.grad(mse_loss)(p, a, b, TINY, mesh=mesh2x4)
    )(sharded, x, t)
    g = jax.jit(
        lambda p, a, b: jax.grad(mse_loss)(p, a, b, cfg, mesh=mesh2x4)
    )(sharded, x, t)
    for a, b in zip(jax.tree.leaves(g_off), jax.tree.leaves(g)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)


def test_overlap_hlo_has_permute_chain_no_allreduce(mesh2x4):
    """The decomposition in the compiled program: the scanned layer body
    must contain the ppermute chain (4 ring matmuls x (tp-1) hops) and
    ZERO all-reduce; the only all-gather is the single final reshard to
    the caller's batch layout.  (The standing registry-wide gate is the
    comm-lint HLO audit — this pins the model-level shape directly.)"""
    import re

    cfg = TINY.with_(tp_overlap="ring", attention="simplified")
    params = init_params(cfg, jax.random.key(1))
    sharded = shard_params(params, mesh2x4)
    xs = jax.device_put(
        jnp.ones((4, 16, 64), jnp.float32),
        NamedSharding(mesh2x4, batch_spec(mesh2x4)))
    out_sh = NamedSharding(mesh2x4, batch_spec(mesh2x4))
    hlo = jax.jit(
        lambda p, a: forward(p, a, cfg, mesh=mesh2x4),
        out_shardings=out_sh,
    ).lower(sharded, xs).compile().as_text()
    body = hlo.split("ENTRY")[0]
    tp = mesh2x4.shape["tp"]
    assert len(re.findall(r"collective-permute\(", body)) >= 4 * (tp - 1), \
        "overlapped forward lost its ppermute chain"
    assert not re.findall(r"\ball-reduce\(", body), \
        "an all-reduce survived in the overlapped layer body — the " \
        "decomposition collapsed back to the fused lowering"
    assert len(re.findall(r"\ball-gather\(", hlo)) <= 1, \
        "more than the single final activation reshard all-gather"


def test_micro_ops_decomposed_match_fused(mesh8):
    """The registry micro-ops: overlap_ring / overlap_bidir variants
    compute exactly what the fused default computes (same deterministic
    weight, same payload)."""
    from dlbb_tpu.comm.ops import (
        build_ag_matmul,
        build_matmul_rs,
        get_op,
        make_payload,
    )

    for opname, builder in (("ag_matmul", build_ag_matmul),
                            ("matmul_rs", build_matmul_rs)):
        op = get_op(opname)
        x = make_payload(op, mesh8, ("ranks",), 2 * 16 * 64,
                         dtype=jnp.float32, shape=(2, 16, 64))
        ref = np.asarray(builder(mesh8, ("ranks",), schedule="fused")(x))
        for schedule in ("ring", "bidir"):
            got = np.asarray(
                builder(mesh8, ("ranks",), schedule=schedule)(x))
            np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5,
                                       err_msg=f"{opname}/{schedule}")


def test_micro_ops_flat_payload_rejected(mesh8):
    """The matmul micro-ops are 3D-only: a flat 1D payload must fail with
    a pointer at bench3d, not produce nonsense."""
    from dlbb_tpu.comm.ops import build_ag_matmul, build_matmul_rs

    with pytest.raises(ValueError, match="3D sweep"):
        build_ag_matmul(mesh8, ("ranks",), schedule="ring")(
            jnp.ones((8, 256), jnp.float32))
    with pytest.raises(ValueError, match="3D sweep"):
        build_matmul_rs(mesh8, ("ranks",), schedule="fused")(
            jnp.ones((8, 256), jnp.float32))
    # and a typo'd schedule must be rejected at build time, never silently
    # measured as the ring schedule under a wrong variant label
    with pytest.raises(ValueError, match="unknown collective-matmul"):
        build_ag_matmul(mesh8, ("ranks",), schedule="bi-dir")
    with pytest.raises(ValueError, match="unknown collective-matmul"):
        build_matmul_rs(mesh8, ("ranks",), schedule="zigzag")


def test_micro_ops_donation_safe_under_chained_timing(mesh8):
    """Chained timing donates its carry; the chain glue must map each
    op's output back to a valid next input so the donated buffers never
    resurface (the sweep engine's chained path runs these ops inside one
    jitted fori_loop)."""
    from dlbb_tpu.comm.ops import (
        build_ag_matmul,
        build_matmul_rs,
        get_op,
        make_payload,
    )
    from dlbb_tpu.utils.timing import time_fn_chained

    for opname, builder, schedule in (
            ("ag_matmul", build_ag_matmul, "ring"),
            ("matmul_rs", build_matmul_rs, "bidir")):
        op = get_op(opname)
        fn = builder(mesh8, ("ranks",), schedule=schedule)
        x = make_payload(op, mesh8, ("ranks",), 2 * 16 * 64,
                         dtype=jnp.float32, shape=(2, 16, 64))
        samples, meta, carry = time_fn_chained(
            fn, x, chain=op.make_chain(8), warmup=1, iterations=10)
        assert len(samples) >= 1
        assert meta["timing_mode"] == "chained"
        # the returned carry is alive and shaped like the next input
        assert carry.shape == (8, 2, 16, 64)
        assert np.isfinite(np.asarray(samples)).all()


def test_validate_tp_overlap_rejections():
    """Plan-level validation: the knob needs tp > 1, no pipeline, a dense
    FFN, and a divisible sequence."""
    cfg = TINY.with_(tp_overlap="ring")
    with pytest.raises(ValueError, match="world_size"):
        validate_tp_overlap(cfg, tp=1)
    with pytest.raises(ValueError, match="pipeline"):
        validate_tp_overlap(cfg, tp=4, pp=2)
    moe = TINY.with_(num_experts=4, tp_overlap="ring")
    with pytest.raises(ValueError, match="dense FFN"):
        validate_tp_overlap(moe, tp=4)
    with pytest.raises(ValueError, match="sequence_length"):
        validate_tp_overlap(cfg, tp=4, seq_len=10)
    with pytest.raises(ValueError, match="unknown tp_overlap"):
        TINY.with_(tp_overlap="diagonal")
    # the off default validates anywhere, tp=1 included
    validate_tp_overlap(TINY, tp=1)
    validate_tp_overlap(cfg, tp=4, seq_len=16)


def test_plan_carries_tp_overlap(devices):
    """ParallelismPlan records the schedule and enforces the validation
    from the YAML surface (sequence divisibility included)."""
    from dlbb_tpu.parallel.plan import ParallelismPlan

    cfg = TINY.with_(tp_overlap="ring")
    config = {"parallelism": {"world_size": 4, "data_parallel": 2},
              "input": {"batch_size": 4, "sequence_length": 16}}
    plan = ParallelismPlan.from_config(config, cfg)
    assert plan.tp_overlap == "ring"
    bad = {"parallelism": {"world_size": 4, "data_parallel": 2},
           "input": {"batch_size": 4, "sequence_length": 18}}
    with pytest.raises(ValueError, match="sequence_length=18"):
        ParallelismPlan.from_config(bad, cfg)
