"""Bench harness + stats pipeline integration tests on the simulated mesh.

The reference's benchmark scripts double as integration tests (SURVEY §4);
here a miniature sweep runs end-to-end — payload → timed collective → JSON —
and the stats pipeline consumes the artifacts, mirroring the
results/ → stats/ flow of the reference.
"""

import json

import numpy as np
import pytest

from dlbb_tpu.bench import Sweep1D, Sweep3D, run_sweep
from dlbb_tpu.compat import supports_compiler_option
from dlbb_tpu.stats import process_1d_results, process_3d_results


def _tiny_1d(tmp_path, **kw):
    defaults = dict(
        implementation="xla_test",
        operations=("allreduce", "broadcast", "sendrecv"),
        data_sizes=(("1KB", 256), ("64KB", 16384)),
        rank_counts=(2, 4, 16),  # 16 must be skipped (only 8 devices)
        dtype="float32",
        warmup_iterations=1,
        measurement_iterations=3,
        output_dir=str(tmp_path / "results"),
    )
    defaults.update(kw)
    return Sweep1D(**defaults)


def test_sweep_1d_writes_reference_schema(tmp_path, devices):
    files = run_sweep(_tiny_1d(tmp_path), verbose=False)
    # 3 ops x 2 sizes x 2 feasible rank counts
    assert len(files) == 12
    data = json.loads(files[0].read_text())
    for key in (
        "implementation", "operation", "num_ranks", "data_size_name",
        "num_elements", "dtype", "warmup_iterations",
        "measurement_iterations", "timings",
    ):
        assert key in data, key
    assert data["num_ranks"] in (2, 4)
    timings = np.asarray(data["timings"])
    assert timings.ndim == 2 and timings.shape[1] == 3
    assert (timings > 0).all()


def test_sweep_1d_resume_skips_existing(tmp_path, devices):
    """resume=True picks an interrupted sweep back up: configs whose artifact
    already exists are not re-measured (their files are untouched), missing
    ones still run, and the returned list covers the full grid either way."""
    sweep = _tiny_1d(tmp_path)
    first = run_sweep(sweep, verbose=False)
    assert len(first) == 12
    # delete two artifacts to simulate an interruption mid-grid
    removed = {first[3], first[7]}
    for p in removed:
        p.unlink()
    mtimes = {p: p.stat().st_mtime_ns for p in first if p not in removed}
    resumed = run_sweep(_tiny_1d(tmp_path, resume=True), verbose=False)
    assert sorted(resumed) == sorted(first)
    for p, t in mtimes.items():
        assert p.stat().st_mtime_ns == t, f"{p.name} was re-measured"
    for p in removed:
        assert p.exists(), f"{p.name} was not re-run"


def test_sweep_1d_rank_gate(tmp_path, devices):
    files = run_sweep(_tiny_1d(tmp_path, rank_counts=(16,)), verbose=False)
    assert files == []  # all configs infeasible on 8 devices


def test_sweep_1d_hierarchical_variant(tmp_path, devices):
    sweep = _tiny_1d(
        tmp_path,
        variant="hier2x2x2",
        operations=("allreduce",),
        rank_counts=(8,),
    )
    files = run_sweep(sweep, verbose=False)
    assert len(files) == 2
    data = json.loads(files[0].read_text())
    assert data["implementation"] == "xla_test_hier2x2x2"
    assert data["mesh_shape"] == [2, 2, 2]


def test_sweep_1d_time_budget_clamps_iterations(tmp_path, devices):
    """max_config_seconds scales iteration counts down and records the
    actual counts — artifacts never overstate the sample size."""
    sweep = _tiny_1d(
        tmp_path, operations=("allreduce",), data_sizes=(("1MB", 262144),),
        rank_counts=(8,), measurement_iterations=10_000,
        max_config_seconds=0.05,
    )
    files = run_sweep(sweep, verbose=False)
    assert len(files) == 1
    data = json.loads(files[0].read_text())
    assert data["time_budget_clamped"] is True
    assert data["measurement_iterations"] < 10_000
    assert data["measurement_iterations"] == len(data["timings"][0])
    assert data["time_budget_s"] == 0.05


def test_sweep_1d_nofuse_variant(tmp_path, devices):
    """The fusion-off variant (combiner HLO passes disabled via
    per-computation compiler options) executes and is labeled.  On jaxlibs
    whose compile path rejects repeated DebugOptions fields the variant is
    unsupported (run_sweep refuses up-front, see test below) and this
    skips."""
    if not supports_compiler_option("xla_disable_hlo_passes",
                                    "all-reduce-combiner"):
        pytest.skip("per-computation xla_disable_hlo_passes unsupported "
                    "on this jaxlib (repeated DebugOptions field)")
    sweep = _tiny_1d(
        tmp_path, variant="nofuse", operations=("allreduce",),
        data_sizes=(("1KB", 256),), rank_counts=(8,),
    )
    files = run_sweep(sweep, verbose=False)
    assert len(files) == 1
    data = json.loads(files[0].read_text())
    assert data["implementation"] == "xla_test_nofuse"


def test_sweep_refuses_unsupported_compiler_options(tmp_path, devices):
    """Where per-computation compiler options cannot be applied, the sweep
    must refuse to run rather than silently mislabel results (same
    convention as unset variant XLA_FLAGS)."""
    if supports_compiler_option("xla_disable_hlo_passes",
                                "all-reduce-combiner"):
        pytest.skip("this jaxlib supports the option; nothing to refuse")
    sweep = _tiny_1d(
        tmp_path, variant="nofuse", operations=("allreduce",),
        data_sizes=(("1KB", 256),), rank_counts=(8,),
    )
    with pytest.raises(RuntimeError, match="compiler"):
        run_sweep(sweep, verbose=False)


def test_estimate_global_bytes_pinned_per_op():
    """The memory-cap estimator derives its input AND output multipliers
    from the op registry's declared buffer kinds (per_rank -> P,
    per_peer -> P^2) — pinned here for every registered op so a registry
    change that alters an estimate is a visible diff, and a new op can
    never silently fall back to a hard-coded name list's default.

    (For the pre-registry hard-coded list the per_rank-output ops —
    sendrecv/broadcast included — all multiply by exactly P; the pins
    freeze that contract.)"""
    from dlbb_tpu.bench.runner import _estimate_global_bytes
    from dlbb_tpu.comm.ops import OPERATIONS

    p, n, itemsize = 4, 256, 4  # ranks, elements, float32
    expected_mults = {  # (in + out) multiplier per op
        "allreduce": p + p,
        "allgather": p + p * p,
        "broadcast": p + p,
        "gather": p + p * p,
        "scatter": p * p + p,
        "reduce": p + p,
        "alltoall": p * p + p * p,
        "sendrecv": p + p,
        "reducescatter": p * p + p,
        "allreduce_hierarchical": p + p,
        # collective-matmul micro-ops: per-rank in AND out (ag_matmul's
        # output is byte-for-byte the input size; matmul_rs's is input/P,
        # conservatively estimated at the per_rank multiplier) PLUS the
        # registry-declared transient — the fused ag_matmul materialises
        # the gathered [B, P*S, H] activation on every device (P^2), the
        # fused matmul_rs a full per-device partial product (P)
        "ag_matmul": p + p + p * p,
        "matmul_rs": p + p + p,
        # compressed micro-ops (docs/compression.md): same declared buffer
        # kinds as their uncompressed counterparts — the quantised copies
        # are byte-wide transients well inside the in+out envelope
        "allreduce_q": p + p,
        "reducescatter_q": p * p + p,
    }
    assert sorted(expected_mults) == sorted(OPERATIONS)  # full coverage
    s = Sweep1D(dtype="float32")
    for op_name, mult in expected_mults.items():
        est = _estimate_global_bytes(
            s, {"operation": op_name, "num_elements": n}, p
        )
        assert est == mult * n * itemsize, op_name
    # the transient term models the FUSED schedule only: under the
    # overlap variants the decomposed ring never materialises it, so the
    # estimate drops back to in+out (a fused-sized cap must not skip
    # ring configs that fit)
    for op_name in ("ag_matmul", "matmul_rs"):
        est = _estimate_global_bytes(
            Sweep1D(dtype="float32", variant="overlap_ring"),
            {"operation": op_name, "num_elements": n}, p,
        )
        assert est == (p + p) * n * itemsize, op_name


@pytest.mark.pipeline_smoke
def test_pipeline_smoke_two_op_mini_sweep(tmp_path, devices):
    """Marker-gated smoke for the compile-ahead engine (also invoked by
    scripts/run_static_analysis.sh): a 2-op pipelined mini-sweep measures,
    records compile accounting in every artifact, and writes the sweep
    manifest."""
    sweep = _tiny_1d(
        tmp_path, operations=("allreduce", "allgather"),
        data_sizes=(("1KB", 256),), rank_counts=(4,),
        compile_cache=str(tmp_path / "xc"), pipeline=True,
    )
    files = run_sweep(sweep, verbose=False)
    assert len(files) == 2
    for f in files:
        data = json.loads(f.read_text())
        assert data["compile_seconds"] >= 0.0
        assert isinstance(data["compile_cache_hit"], bool)
    man = json.loads(
        (tmp_path / "results" / "sweep_manifest.json").read_text()
    )
    assert man["pipeline"] is True
    assert man["work_units"]["unique"] == 2
    assert man["configs"]["measured"] == 2


def test_variant_axis_order_meshes():
    """grid/hier axis-order variants resolve to transposed meshes; ring
    fallback covers other rank counts."""
    from dlbb_tpu.comm.variants import get_variant

    assert get_variant("grid2x4").mesh_spec(8).shape == (2, 4)
    assert get_variant("grid4x2").mesh_spec(8).shape == (4, 2)
    assert get_variant("hier2x4").hierarchical
    import pytest

    with pytest.raises(ValueError):
        get_variant("grid4x2").mesh_spec(4)


def test_stats_1d_pipeline(tmp_path, devices):
    run_sweep(_tiny_1d(tmp_path), verbose=False)
    results = process_1d_results(
        tmp_path / "results", tmp_path / "stats", verbose=False
    )
    assert len(results) == 12
    r = results[0]
    for key in (
        "mean_time_us", "median_time_us", "p95_time_us", "p99_time_us",
        "load_imbalance_percent", "bandwidth_gbps", "per_rank_means_us",
    ):
        assert key in r, key
    assert r["bandwidth_gbps"] > 0
    # consolidated CSV with reference columns
    csv_text = (tmp_path / "stats" / "benchmark_statistics.csv").read_text()
    header = csv_text.splitlines()[0]
    assert header.startswith("mpi_implementation,operation,num_ranks")
    assert "bandwidth_gbps" in header
    # per-file stats JSONs exist
    assert len(list((tmp_path / "stats").glob("*_stats.json"))) == 12


def test_sweep_3d_and_stats(tmp_path, devices):
    sweep = Sweep3D(
        implementation="xla_test",
        operations=("allreduce", "allgather"),
        batch_sizes=(1, 2),
        seq_lengths=(8,),
        hidden_dims=(16,),
        rank_counts=(4,),
        dtype="bfloat16",
        warmup_iterations=1,
        measurement_iterations=2,
        output_dir=str(tmp_path / "results3d"),
    )
    files = run_sweep(sweep, verbose=False)
    assert len(files) == 4
    data = json.loads(files[0].read_text())
    assert data["tensor_shape"] == {"batch": 1, "seq_len": 8, "hidden_dim": 16}
    assert data["tensor_size_mb"] == 1 * 8 * 16 * 2 / 2**20

    results = process_3d_results(
        tmp_path / "results3d", tmp_path / "stats3d", "xla_test", verbose=False
    )
    assert len(results) == 4
    std = tmp_path / "stats3d" / "benchmark_statistics_3d_xla_test_standard.csv"
    tr = tmp_path / "stats3d" / "benchmark_statistics_3d_xla_test_transpose.csv"
    assert std.exists() and tr.exists()
    header = std.read_text().splitlines()[0]
    assert header == (
        "implementation,operation,num_ranks,hidden_dim,seq_len,batch,"
        "tensor_size_mb,num_elements,mean_time_ms,median_time_ms,"
        "min_time_ms,max_time_ms"
    )
    # transpose CSV: metrics as rows, config ids as columns
    lines = tr.read_text().splitlines()
    assert lines[0].startswith("Metric,allgather_r4_h16_s8_b1")
    assert lines[1].startswith("mean_time_ms,")


def test_stats_1d_granularity_marker(tmp_path):
    """Chained-mode artifacts (whose samples are chunk MEANS — percentiles
    are not per-iteration tails) must be distinguishable from per-iteration
    ones in both the per-file stats JSON and the consolidated CSV."""
    base = {
        "implementation": "xla_test", "operation": "allreduce",
        "num_ranks": 4, "data_size_name": "1KB", "num_elements": 256,
        "dtype": "bfloat16", "warmup_iterations": 1,
        "measurement_iterations": 3, "timings": [[1e-4, 1.2e-4, 0.9e-4]],
    }
    chained = dict(
        base, operation="broadcast", timing_granularity="chunked(5)",
        percentile_caveat="percentiles are over 5-iteration chunk means, "
                          "not per-iteration tails",
    )
    d = tmp_path / "r"
    d.mkdir()
    (d / "xla_test_allreduce_ranks4_1KB.json").write_text(json.dumps(base))
    (d / "xla_test_broadcast_ranks4_1KB.json").write_text(json.dumps(chained))
    results = process_1d_results(d, tmp_path / "s", verbose=False)
    by_op = {r["operation"]: r for r in results}
    assert by_op["allreduce"]["timing_granularity"] == "per_iteration"
    assert by_op["broadcast"]["timing_granularity"] == "chunked(5)"
    csv_lines = (
        tmp_path / "s" / "benchmark_statistics.csv"
    ).read_text().splitlines()
    # extension columns: granularity marker + dtype (the corpus carries
    # the north-star curve in both bf16 and fp32) + the analytic wire
    # volume (docs/compression.md)
    assert csv_lines[0].endswith("timing_granularity,dtype,bytes_on_wire")
    assert any("chunked(5)" in line for line in csv_lines[1:])
    assert any("per_iteration" in line for line in csv_lines[1:])
    # the full caveat text lands in the per-file stats JSON
    stats = json.loads(
        (tmp_path / "s" / "xla_test_broadcast_ranks4_1KB_stats.json")
        .read_text()
    )
    assert "chunk means" in stats["percentile_caveat"]


def test_stats_1d_null_system_info(tmp_path):
    """An artifact with an explicit ``"system_info": null`` (as opposed to
    a missing key) must process cleanly with ``backend`` = None — the
    ``.get`` default only covers the missing-key case."""
    artifact = {
        "implementation": "xla_test", "operation": "allreduce",
        "num_ranks": 4, "data_size_name": "1KB", "num_elements": 256,
        "dtype": "bfloat16", "warmup_iterations": 1,
        "measurement_iterations": 3, "timings": [[1e-4, 1.2e-4, 0.9e-4]],
        "system_info": None,
    }
    d = tmp_path / "r"
    d.mkdir()
    (d / "xla_test_allreduce_ranks4_1KB.json").write_text(
        json.dumps(artifact))
    results = process_1d_results(d, tmp_path / "s", verbose=False)
    assert len(results) == 1
    assert results[0]["backend"] is None


def test_stats_3d_granularity_marker(tmp_path):
    """3D: the standard CSV header is the reference contract (unchanged);
    the granularity marker rides the transposed CSV's metadata block."""
    art = {
        "implementation": "xla_test", "operation": "allreduce",
        "num_ranks": 4, "num_elements": 128,
        "tensor_shape": {"batch": 1, "seq_len": 8, "hidden_dim": 16},
        "tensor_size_mb": 0.000244140625,
        "timing_granularity": "chunked(5)",
        "timings": [[1e-3, 1.1e-3]],
    }
    d = tmp_path / "r3"
    d.mkdir()
    (d / "xla_test_allreduce_ranks4_b1_s8_h16.json").write_text(
        json.dumps(art)
    )
    process_3d_results(d, tmp_path / "s3", "xla_test", verbose=False)
    header = (
        tmp_path / "s3" / "benchmark_statistics_3d_xla_test_standard.csv"
    ).read_text().splitlines()[0]
    assert "timing_granularity" not in header  # reference contract intact
    tr = (
        tmp_path / "s3" / "benchmark_statistics_3d_xla_test_transpose.csv"
    ).read_text()
    assert "timing_granularity,chunked(5)" in tr


def _write_1d_artifact(path, impl, op, ranks, size_name, n, mean_s,
                       backend=None):
    path.parent.mkdir(parents=True, exist_ok=True)
    artifact = {
        "mpi_implementation": impl, "operation": op, "num_ranks": ranks,
        "data_size_name": size_name, "num_elements": n, "dtype": "bfloat16",
        "warmup_iterations": 1, "measurement_iterations": 2,
        "timings": [[mean_s, mean_s]] * ranks,
    }
    if backend is not None:
        artifact["system_info"] = {"backend": backend}
    path.write_text(json.dumps(artifact))


def test_compare_1d_verdicts(tmp_path):
    """The comparison join picks the best reference backend per config and
    classifies beat/match/lose by the speedup thresholds."""
    from dlbb_tpu.stats.compare import compare_1d

    ref = tmp_path / "ref"
    # slow backend and fast backend: best must be 'fast' (1 ms)
    _write_1d_artifact(ref / "slow" / "a.json", "slow", "allreduce", 4,
                       "1KB", 256, 5e-3)
    _write_1d_artifact(ref / "fast" / "a.json", "fast", "allreduce", 4,
                       "1KB", 256, 1e-3)
    # config only the reference covers (ranks=16) must not produce a row
    _write_1d_artifact(ref / "fast" / "b.json", "fast", "allreduce", 16,
                       "1KB", 256, 1e-3)
    own = tmp_path / "own"
    _write_1d_artifact(own / "a.json", "xla_tpu", "allreduce", 4,
                       "1KB", 256, 0.5e-3)  # 2x faster -> beat
    _write_1d_artifact(own / "c.json", "xla_tpu", "broadcast", 4,
                       "1KB", 256, 1e-3)    # no ref config -> dropped
    rows = compare_1d(ref, own)
    assert len(rows) == 1
    r = rows[0]
    assert r["ref_best_backend"] == "fast"
    assert r["speedup"] == 2.0
    assert r["verdict"] == "beat"
    assert r["raw_verdict"] == "beat"


def test_fp32_artifacts_dtype_suffixed_and_joined(tmp_path):
    """The fp32 half of the north-star curve: float32 sweeps write
    dtype-suffixed filenames next to the bf16 corpus, and the comparison
    emits one row per (config, dtype) with the dtype column filled."""
    from dlbb_tpu.bench.runner import _result_filename
    from dlbb_tpu.stats.compare import compare_1d

    sweep32 = _tiny_1d(tmp_path, operations=("allreduce",),
                       data_sizes=(("1KB", 256),), rank_counts=(2,),
                       implementation="xla_tpu", dtype="float32")
    cfg = {"operation": "allreduce", "size_label": "1KB",
           "num_elements": 256}
    assert _result_filename(sweep32, "xla_tpu", 2, cfg) \
        == "xla_tpu_allreduce_ranks2_1KB_fp32.json"
    run_sweep(sweep32, verbose=False)
    out = tmp_path / "results" / "xla_tpu_allreduce_ranks2_1KB_fp32.json"
    assert out.exists()
    assert json.loads(out.read_text())["dtype"] == "float32"

    ref = tmp_path / "ref"
    _write_1d_artifact(ref / "fast" / "a.json", "fast", "allreduce", 2,
                       "1KB", 256, 1e-3)
    own = tmp_path / "own"
    _write_1d_artifact(own / "a.json", "xla_tpu", "allreduce", 2,
                       "1KB", 256, 1e-3)
    art32 = json.loads(out.read_text())
    (own / "a_fp32.json").write_text(json.dumps(art32))
    rows = compare_1d(ref, own)
    assert len(rows) == 2
    assert {r["xla_dtype"] for r in rows} == {"bfloat16", "float32"}


def test_compare_1d_simulated_rows_are_not_comparable(tmp_path):
    """Own-side artifacts measured on the simulated mesh (system_info.backend
    == 'cpu') get the structural not_comparable(simulated) verdict — never
    'lose' — while the speedup-only raw_verdict is preserved."""
    from dlbb_tpu.stats.compare import NOT_COMPARABLE, compare_1d

    ref = tmp_path / "ref"
    _write_1d_artifact(ref / "fast" / "a.json", "fast", "allreduce", 4,
                       "1KB", 256, 1e-3)
    own = tmp_path / "own"
    _write_1d_artifact(own / "a.json", "xla_tpu", "allreduce", 4,
                       "1KB", 256, 10e-3, backend="cpu")  # 10x slower
    rows = compare_1d(ref, own)
    assert len(rows) == 1
    assert rows[0]["verdict"] == NOT_COMPARABLE
    assert rows[0]["raw_verdict"] == "lose"
    assert rows[0]["speedup"] == 0.1


def test_compare_report_against_reference_corpus(tmp_path, devices):
    """End-to-end: a real (tiny) sweep's artifacts joined against the
    reference's actual checked-in 1D corpus produce the committed report
    files with a verdict per covered config."""
    import pytest

    from dlbb_tpu.stats.compare import write_comparison

    ref_root = __import__("pathlib").Path("/root/reference")
    if not (ref_root / "collectives" / "1d" / "results").exists():
        pytest.skip("reference corpus not available")
    run_sweep(
        _tiny_1d(tmp_path, operations=("allreduce",),
                 data_sizes=(("1KB", 256),), rank_counts=(2, 4),
                 implementation="xla_tpu"),
        verbose=False,
    )
    out = tmp_path / "cmp"
    summary = write_comparison(
        ref_root, tmp_path / "results", tmp_path / "none3d", out
    )
    assert summary["1d"]["configs"] == 2  # ranks 2 and 4 joined
    # the sweep ran on the CPU-simulated mesh -> structurally
    # not_comparable(simulated), never counted as a loss; the speedup-only
    # raw verdicts are preserved in the sub-breakdown
    assert summary["1d"]["not_comparable_simulated"] == 2
    assert sum(summary["1d"][k] for k in ("beat", "match", "lose")) == 0
    raw = summary["1d"]["not_comparable_raw_verdicts"]
    assert sum(raw.values()) == 2
    assert (out / "COMPARISON.md").exists()
    assert (out / "comparison_1d.csv").exists()
    md = (out / "COMPARISON.md").read_text()
    assert "allreduce" in md and "Caveats" in md


def test_compare_e2e_reads_driver_bench_records(tmp_path):
    """Driver BENCH_r*.json files nest the bench.py line under 'parsed';
    the E2E section must unwrap it (regression: silently-empty section)."""
    from dlbb_tpu.stats.compare import _e2e_rows

    (tmp_path / "bench_baseline_cpu.json").write_text(json.dumps(
        {"tokens_per_second": 100.0}
    ))
    (tmp_path / "BENCH_r01.json").write_text(json.dumps({
        "n": 1, "cmd": "python bench.py", "rc": 0,
        "parsed": {"metric": "e2e", "value": 250.0, "unit": "tokens/s",
                   "vs_baseline": 2.5,
                   "extras": {"7B_full": {"tokens_per_second": 50.0}}},
    }))
    rows = _e2e_rows(tmp_path)
    assert len(rows) == 2
    assert rows[0]["speedup"] == 2.5 and rows[0]["verdict"] == "beat"
    assert rows[1]["xla_tpu_tokens_per_s"] == 50.0


def test_bench_allreduce_multichip_schema(devices):
    """The headline multi-chip branch of bench.py (never taken on the
    single-chip image) runs on the simulated 8-device mesh: schema keys,
    positive bandwidth, and the vs_baseline arithmetic hold."""
    import bench

    out = bench.bench_allreduce_multichip(
        8, num_elements=262_144, warmup=1, iterations=5
    )
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in out, key
    assert out["metric"] == "1d_allreduce_1MB_bus_bandwidth_8ranks"
    assert out["unit"] == "GB/s"
    assert out["value"] > 0
    assert out["max_time_s"] > 0
    np.testing.assert_allclose(
        out["vs_baseline"],
        round(out["value"] / bench.ONECCL_BASELINE_GBPS, 3),
        rtol=1e-9,
    )


def test_bench_latest_chip_probe():
    """The degraded fallback points at the newest committed chip capture
    so a bench-day outage doesn't orphan the round's chip evidence."""
    import bench

    p = bench.latest_chip_probe()
    # this repo carries round 5's capture; newest sorts last by name
    assert p is not None and p.startswith("results/bench_probe_r")
    assert (bench.REPO / p).is_file()


def test_bench_probe_backend_outcomes(monkeypatch):
    """The device-init probe runs out-of-process so a down-but-not-refusing
    tunnel (jax.devices() hanging in-process) cannot hang the driver's
    bench run: timeout and nonzero exit both resolve to None (-> the
    degraded simulated-mesh fallback), success parses the device count."""
    import subprocess
    import types

    import bench

    def fake(result):
        def run(cmd, capture_output=True, text=True, timeout=None):
            if result == "timeout":
                raise subprocess.TimeoutExpired(cmd, timeout)
            if result == "fail":
                return types.SimpleNamespace(
                    returncode=1, stdout="", stderr="backend init error\n"
                )
            if result == "empty":
                return types.SimpleNamespace(
                    returncode=0, stdout="", stderr=""
                )
            return types.SimpleNamespace(
                returncode=0, stdout="warning noise\n8\n", stderr=""
            )
        return run

    monkeypatch.setattr(subprocess, "run", fake("timeout"))
    n, reason = bench.probe_backend(timeout_s=1.0)
    assert n is None and "timed out" in reason
    monkeypatch.setattr(subprocess, "run", fake("fail"))
    n, reason = bench.probe_backend()
    assert n is None and "exited 1" in reason
    monkeypatch.setattr(subprocess, "run", fake("empty"))
    n, reason = bench.probe_backend()
    assert n is None and "no device count" in reason
    monkeypatch.setattr(subprocess, "run", fake("ok"))
    assert bench.probe_backend() == (8, None)


def test_variants_report_picks_winner(tmp_path):
    """The tuning-comparison capstone: per-size join over variant stats
    CSVs, winner + speedup-vs-default computed, fixed-shape variants with
    missing rank rows dropped rather than guessed."""
    import csv

    from dlbb_tpu.stats import write_variants_report

    cols = ["mpi_implementation", "operation", "num_ranks",
            "data_size_name", "mean_time_us"]

    def fake(impl, rows):
        d = tmp_path / impl
        d.mkdir()
        with (d / "benchmark_statistics.csv").open("w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=cols)
            w.writeheader()
            for size, mean in rows:
                w.writerow({"mpi_implementation": impl,
                            "operation": "allreduce", "num_ranks": 8,
                            "data_size_name": size, "mean_time_us": mean})

    fake("xla_tpu", [("1KB", 100.0), ("16MB", 9000.0)])
    fake("xla_tpu_hier2x4", [("1KB", 50.0), ("16MB", 12000.0)])
    fake("xla_tpu_grid2x2x2", [("1KB", 200.0)])  # no 16MB row

    summary = write_variants_report(tmp_path)
    assert summary["winners"]["1KB"]["winner"] == "xla_tpu_hier2x4"
    assert summary["winners"]["1KB"]["speedup_vs_default"] == 2.0
    assert summary["winners"]["16MB"]["winner"] == "xla_tpu"
    assert (tmp_path / "VARIANTS.md").exists()
    with (tmp_path / "variants_comparison.csv").open() as f:
        rows = {r["data_size_name"]: r for r in csv.DictReader(f)}
    assert rows["16MB"]["xla_tpu_grid2x2x2"] == ""  # absent, not guessed
    # markdown renders absent cells blank, never the string "None"
    assert "None" not in (tmp_path / "VARIANTS.md").read_text()


def test_variants_report_fresh_tree(tmp_path):
    from dlbb_tpu.stats import write_variants_report

    summary = write_variants_report(tmp_path / "does_not_exist")
    assert summary == {"sizes": [], "winners": {}}


def test_stats_reads_reference_artifact(tmp_path):
    """The pipeline must ingest the reference's own result JSONs (same
    schema, 'mpi_implementation' key)."""
    ref = {
        "mpi_implementation": "openmpi",
        "operation": "allreduce",
        "num_ranks": 4,
        "data_size_name": "1KB",
        "num_elements": 256,
        "dtype": "<class 'numpy.float16'>",
        "warmup_iterations": 10,
        "measurement_iterations": 3,
        "timings": [[1e-4, 1.2e-4, 0.9e-4]] * 4,
    }
    d = tmp_path / "ref"
    d.mkdir()
    (d / "openmpi_allreduce_ranks4_1KB.json").write_text(json.dumps(ref))
    results = process_1d_results(d, tmp_path / "refstats", verbose=False)
    assert len(results) == 1
    assert results[0]["mpi_implementation"] == "openmpi"
    # fp16 element size resolved from the numpy-repr dtype string
    expected_bw = 256 * 2 * 4 / (1.2e-4) / 2**30
    np.testing.assert_allclose(results[0]["bandwidth_gbps"], expected_bw, rtol=1e-9)


def test_variants3d_report(tmp_path):
    """3D-shape variant comparison: joins variant standard CSVs with the
    default 3D corpus per config, picks the winner, and drops configs only
    one implementation measured."""
    import csv as _csv

    from dlbb_tpu.stats.variants_report import write_variants3d_report

    cols = ["implementation", "operation", "num_ranks", "hidden_dim",
            "seq_len", "batch", "tensor_size_mb", "num_elements",
            "mean_time_ms", "median_time_ms", "min_time_ms", "max_time_ms"]

    def std_csv(path, impl, rows):
        path.parent.mkdir(parents=True, exist_ok=True)
        with path.open("w", newline="") as f:
            w = _csv.DictWriter(f, fieldnames=cols)
            w.writeheader()
            for ranks, b, s, h, mean in rows:
                w.writerow({
                    "implementation": impl, "operation": "allreduce",
                    "num_ranks": ranks, "hidden_dim": h, "seq_len": s,
                    "batch": b, "tensor_size_mb": 1, "num_elements": 1,
                    "mean_time_ms": mean, "median_time_ms": mean,
                    "min_time_ms": mean, "max_time_ms": mean,
                })

    base = tmp_path / "3d" / "base_standard.csv"
    std_csv(base, "xla_tpu", [(8, 1, 2048, 2048, 10.0),
                              (8, 8, 2048, 2048, 80.0)])
    std_csv(tmp_path / "v3d" / "xla_tpu_ring" / "r_standard.csv",
            "xla_tpu_ring", [(8, 1, 2048, 2048, 5.0),
                             (4, 1, 1, 2048, 1.0)])  # ranks-4: ring only
    rows = write_variants3d_report(tmp_path / "v3d", base,
                                   tmp_path / "out")
    assert len(rows) == 1  # the single config both measured
    r = rows[0]
    assert r["winner"] == "xla_tpu_ring"
    assert r["winner_speedup_vs_default"] == 2.0
    assert (tmp_path / "out" / "VARIANTS3D.md").exists()
    assert (tmp_path / "out" / "variants3d_comparison.csv").exists()

    # a scanned dir named xla_tpu would shadow the baseline — rejected
    import pytest

    std_csv(tmp_path / "v3d" / "xla_tpu" / "x_standard.csv",
            "xla_tpu", [(8, 1, 2048, 2048, 3.0)])
    with pytest.raises(ValueError, match="shadow"):
        write_variants3d_report(tmp_path / "v3d", base, tmp_path / "out")


def test_northstar_report(tmp_path):
    """The driver-metric table: one row per size label (payload order),
    one column per (ranks, dtype), median/bandwidth cells, honest blanks
    for unmeasured combinations."""
    import csv as _csv

    from dlbb_tpu.stats.northstar import write_northstar_report

    cols = ["mpi_implementation", "operation", "num_ranks",
            "data_size_name", "num_elements", "median_time_us",
            "bandwidth_gbps", "dtype"]
    stats_csv = tmp_path / "benchmark_statistics.csv"
    with stats_csv.open("w", newline="") as f:
        w = _csv.DictWriter(f, fieldnames=cols)
        w.writeheader()
        for ranks, size, n, dtype, med, bw in (
            (2, "1KB", 256, "bfloat16", 100.0, 0.01),
            (2, "1KB", 256, "float32", 80.0, 0.02),
            (2, "16MB", 4194304, "bfloat16", 9000.0, 1.5),
            # 16MB fp32 unmeasured -> blank cell
        ):
            w.writerow({"mpi_implementation": "xla_tpu",
                        "operation": "allreduce", "num_ranks": ranks,
                        "data_size_name": size, "num_elements": n,
                        "median_time_us": med, "bandwidth_gbps": bw,
                        "dtype": dtype})
    counts = write_northstar_report(stats_csv, tmp_path / "out",
                                    operations=("allreduce",))
    assert counts == {"allreduce": 2}
    with (tmp_path / "out" / "northstar_allreduce.csv").open() as f:
        rows = list(_csv.DictReader(f))
    assert [r["size"] for r in rows] == ["1KB", "16MB"]  # payload order
    assert rows[0]["2r/fp32"].startswith("80us")
    assert rows[1]["2r/fp32"] == ""  # honest blank
    md = (tmp_path / "out" / "NORTHSTAR.md").read_text()
    assert "allreduce" in md and "p50" in md

    # absent stats CSV -> no-op, nothing written
    assert write_northstar_report(tmp_path / "missing.csv",
                                  tmp_path / "out2") == {}
    assert not (tmp_path / "out2").exists()

    # stats CSV without any north-star op rows -> no-op too: a partial
    # regeneration must not clobber the committed report with a shell
    empty_csv = tmp_path / "empty_stats.csv"
    with empty_csv.open("w", newline="") as f:
        w = _csv.DictWriter(f, fieldnames=cols)
        w.writeheader()
        w.writerow({"mpi_implementation": "xla_tpu",
                    "operation": "reducescatter", "num_ranks": 2,
                    "data_size_name": "1KB", "num_elements": 256,
                    "median_time_us": 1.0, "bandwidth_gbps": 0.1,
                    "dtype": "bfloat16"})
    assert write_northstar_report(empty_csv, tmp_path / "out3") == {}
    assert not (tmp_path / "out3").exists()
