"""Bench harness + stats pipeline integration tests on the simulated mesh.

The reference's benchmark scripts double as integration tests (SURVEY §4);
here a miniature sweep runs end-to-end — payload → timed collective → JSON —
and the stats pipeline consumes the artifacts, mirroring the
results/ → stats/ flow of the reference.
"""

import json

import numpy as np

from dlbb_tpu.bench import Sweep1D, Sweep3D, run_sweep
from dlbb_tpu.stats import process_1d_results, process_3d_results


def _tiny_1d(tmp_path, **kw):
    defaults = dict(
        implementation="xla_test",
        operations=("allreduce", "broadcast", "sendrecv"),
        data_sizes=(("1KB", 256), ("64KB", 16384)),
        rank_counts=(2, 4, 16),  # 16 must be skipped (only 8 devices)
        dtype="float32",
        warmup_iterations=1,
        measurement_iterations=3,
        output_dir=str(tmp_path / "results"),
    )
    defaults.update(kw)
    return Sweep1D(**defaults)


def test_sweep_1d_writes_reference_schema(tmp_path, devices):
    files = run_sweep(_tiny_1d(tmp_path), verbose=False)
    # 3 ops x 2 sizes x 2 feasible rank counts
    assert len(files) == 12
    data = json.loads(files[0].read_text())
    for key in (
        "implementation", "operation", "num_ranks", "data_size_name",
        "num_elements", "dtype", "warmup_iterations",
        "measurement_iterations", "timings",
    ):
        assert key in data, key
    assert data["num_ranks"] in (2, 4)
    timings = np.asarray(data["timings"])
    assert timings.ndim == 2 and timings.shape[1] == 3
    assert (timings > 0).all()


def test_sweep_1d_rank_gate(tmp_path, devices):
    files = run_sweep(_tiny_1d(tmp_path, rank_counts=(16,)), verbose=False)
    assert files == []  # all configs infeasible on 8 devices


def test_sweep_1d_hierarchical_variant(tmp_path, devices):
    sweep = _tiny_1d(
        tmp_path,
        variant="hier2x2x2",
        operations=("allreduce",),
        rank_counts=(8,),
    )
    files = run_sweep(sweep, verbose=False)
    assert len(files) == 2
    data = json.loads(files[0].read_text())
    assert data["implementation"] == "xla_test_hier2x2x2"
    assert data["mesh_shape"] == [2, 2, 2]


def test_sweep_1d_time_budget_clamps_iterations(tmp_path, devices):
    """max_config_seconds scales iteration counts down and records the
    actual counts — artifacts never overstate the sample size."""
    sweep = _tiny_1d(
        tmp_path, operations=("allreduce",), data_sizes=(("1MB", 262144),),
        rank_counts=(8,), measurement_iterations=10_000,
        max_config_seconds=0.05,
    )
    files = run_sweep(sweep, verbose=False)
    assert len(files) == 1
    data = json.loads(files[0].read_text())
    assert data["time_budget_clamped"] is True
    assert data["measurement_iterations"] < 10_000
    assert data["measurement_iterations"] == len(data["timings"][0])
    assert data["time_budget_s"] == 0.05


def test_sweep_1d_nofuse_variant(tmp_path, devices):
    """The fusion-off variant (combiner HLO passes disabled via
    per-computation compiler options) executes and is labeled."""
    sweep = _tiny_1d(
        tmp_path, variant="nofuse", operations=("allreduce",),
        data_sizes=(("1KB", 256),), rank_counts=(8,),
    )
    files = run_sweep(sweep, verbose=False)
    assert len(files) == 1
    data = json.loads(files[0].read_text())
    assert data["implementation"] == "xla_test_nofuse"


def test_variant_axis_order_meshes():
    """grid/hier axis-order variants resolve to transposed meshes; ring
    fallback covers other rank counts."""
    from dlbb_tpu.comm.variants import get_variant

    assert get_variant("grid2x4").mesh_spec(8).shape == (2, 4)
    assert get_variant("grid4x2").mesh_spec(8).shape == (4, 2)
    assert get_variant("hier2x4").hierarchical
    import pytest

    with pytest.raises(ValueError):
        get_variant("grid4x2").mesh_spec(4)


def test_stats_1d_pipeline(tmp_path, devices):
    run_sweep(_tiny_1d(tmp_path), verbose=False)
    results = process_1d_results(
        tmp_path / "results", tmp_path / "stats", verbose=False
    )
    assert len(results) == 12
    r = results[0]
    for key in (
        "mean_time_us", "median_time_us", "p95_time_us", "p99_time_us",
        "load_imbalance_percent", "bandwidth_gbps", "per_rank_means_us",
    ):
        assert key in r, key
    assert r["bandwidth_gbps"] > 0
    # consolidated CSV with reference columns
    csv_text = (tmp_path / "stats" / "benchmark_statistics.csv").read_text()
    header = csv_text.splitlines()[0]
    assert header.startswith("mpi_implementation,operation,num_ranks")
    assert "bandwidth_gbps" in header
    # per-file stats JSONs exist
    assert len(list((tmp_path / "stats").glob("*_stats.json"))) == 12


def test_sweep_3d_and_stats(tmp_path, devices):
    sweep = Sweep3D(
        implementation="xla_test",
        operations=("allreduce", "allgather"),
        batch_sizes=(1, 2),
        seq_lengths=(8,),
        hidden_dims=(16,),
        rank_counts=(4,),
        dtype="bfloat16",
        warmup_iterations=1,
        measurement_iterations=2,
        output_dir=str(tmp_path / "results3d"),
    )
    files = run_sweep(sweep, verbose=False)
    assert len(files) == 4
    data = json.loads(files[0].read_text())
    assert data["tensor_shape"] == {"batch": 1, "seq_len": 8, "hidden_dim": 16}
    assert data["tensor_size_mb"] == 1 * 8 * 16 * 2 / 2**20

    results = process_3d_results(
        tmp_path / "results3d", tmp_path / "stats3d", "xla_test", verbose=False
    )
    assert len(results) == 4
    std = tmp_path / "stats3d" / "benchmark_statistics_3d_xla_test_standard.csv"
    tr = tmp_path / "stats3d" / "benchmark_statistics_3d_xla_test_transpose.csv"
    assert std.exists() and tr.exists()
    header = std.read_text().splitlines()[0]
    assert header == (
        "implementation,operation,num_ranks,hidden_dim,seq_len,batch,"
        "tensor_size_mb,num_elements,mean_time_ms,median_time_ms,"
        "min_time_ms,max_time_ms"
    )
    # transpose CSV: metrics as rows, config ids as columns
    lines = tr.read_text().splitlines()
    assert lines[0].startswith("Metric,allgather_r4_h16_s8_b1")
    assert lines[1].startswith("mean_time_ms,")


def test_stats_reads_reference_artifact(tmp_path):
    """The pipeline must ingest the reference's own result JSONs (same
    schema, 'mpi_implementation' key)."""
    ref = {
        "mpi_implementation": "openmpi",
        "operation": "allreduce",
        "num_ranks": 4,
        "data_size_name": "1KB",
        "num_elements": 256,
        "dtype": "<class 'numpy.float16'>",
        "warmup_iterations": 10,
        "measurement_iterations": 3,
        "timings": [[1e-4, 1.2e-4, 0.9e-4]] * 4,
    }
    d = tmp_path / "ref"
    d.mkdir()
    (d / "openmpi_allreduce_ranks4_1KB.json").write_text(json.dumps(ref))
    results = process_1d_results(d, tmp_path / "refstats", verbose=False)
    assert len(results) == 1
    assert results[0]["mpi_implementation"] == "openmpi"
    # fp16 element size resolved from the numpy-repr dtype string
    expected_bw = 256 * 2 * 4 / (1.2e-4) / 2**30
    np.testing.assert_allclose(results[0]["bandwidth_gbps"], expected_bw, rtol=1e-9)
