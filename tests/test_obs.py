"""Observability subsystem tests (docs/observability.md): span-tracer
schema + concurrency, the zero-overhead disabled path (pinned statically
like ``resilience/inject.py``), journal→span sink equivalence, the
metrics registry / Prometheus export, the calibration diff gate's pinned
exit codes, and the ``obs_smoke`` gate — a traced + device-captured
sweep must emit a Perfetto-loadable trace while publishing stats
equivalent to an untraced run (profile reps never enter the series)."""

import json
import threading
import time
from pathlib import Path

import pytest

from dlbb_tpu.analysis.findings import (
    EXIT_CLEAN,
    EXIT_CRASH,
    EXIT_FINDINGS,
)
from dlbb_tpu.obs import calibration as cal
from dlbb_tpu.obs import spans
from dlbb_tpu.obs.export import MetricsRegistry
from dlbb_tpu.obs.spans import (
    SpanTracer,
    journal_to_trace,
    validate_trace_events,
)

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _no_leaked_tracer():
    """A test that crashes mid-scope must not leak a process-global
    tracer into the rest of the suite."""
    yield
    spans.stop()


# ---------------------------------------------------------------------------
# span tracer
# ---------------------------------------------------------------------------


def test_span_trace_schema_valid(tmp_path):
    tracer = SpanTracer(tmp_path / "t.json", meta={"who": "test"})
    with tracer.span("outer", cat="a", key="v"):
        with tracer.span("inner", cat="b"):
            tracer.instant("marker", cat="j", args={"n": 1})
    path = tracer.finish()
    data = json.loads(path.read_text())
    evs = data["traceEvents"]
    assert validate_trace_events(evs) == []
    assert data["otherData"]["schema"] == spans.SPAN_SCHEMA
    assert data["otherData"]["who"] == "test"
    # B/E pairs + instant, all with the required keys and µs timestamps
    assert [e["ph"] for e in evs] == ["B", "B", "i", "E", "E"]
    names = [e["name"] for e in evs]
    assert names == ["outer", "inner", "marker", "inner", "outer"]
    assert all(e["ts"] >= 0 for e in evs)
    assert evs[0]["args"] == {"key": "v"}


def test_span_end_emitted_on_exception(tmp_path):
    tracer = SpanTracer(tmp_path / "t.json")
    with pytest.raises(ValueError):
        with tracer.span("doomed"):
            raise ValueError("boom")
    assert validate_trace_events(tracer.events()) == []


def test_concurrent_thread_nesting(tmp_path):
    """Spans from concurrently-running threads must stay properly nested
    per tid (the invariant Perfetto's flame view needs)."""
    tracer = SpanTracer(tmp_path / "t.json")
    barrier = threading.Barrier(4)

    def work(i):
        barrier.wait()  # all threads alive at once: tids are distinct
        with tracer.span(f"outer{i}", cat="t"):
            time.sleep(0.002)
            with tracer.span(f"inner{i}", cat="t"):
                tracer.instant(f"tick{i}")
                time.sleep(0.002)

    threads = [threading.Thread(target=work, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    evs = tracer.events()
    assert validate_trace_events(evs) == []
    assert len({e["tid"] for e in evs}) == 4
    assert sum(1 for e in evs if e["ph"] == "B") == 8


def test_misnested_trace_detected():
    bad = [
        {"name": "a", "ph": "B", "ts": 0.0, "pid": 1, "tid": 1},
        {"name": "b", "ph": "E", "ts": 1.0, "pid": 1, "tid": 1},
    ]
    assert any("misnested" in p for p in validate_trace_events(bad))
    assert any("unclosed" in p
               for p in validate_trace_events(bad[:1]))


def test_disabled_span_is_shared_singleton():
    """Zero-overhead contract, dynamically: with no tracer active,
    span() hands back ONE shared nullcontext (no allocation per call)
    and instant() is a no-op."""
    assert spans.active() is None
    assert spans.span("a") is spans.span("b", cat="x", arg=1)
    spans.instant("nothing-happens")  # must not raise, must not allocate


def test_timed_regions_carry_zero_obs_instructions():
    """The zero-overhead contract, statically (same pin shape as
    ``resilience/inject.py``): ``utils/timing.py`` — the only module
    that brackets device work with clocks — must never reference the
    obs package, so tracing state can add zero instructions to any
    timed region."""
    import ast

    src = (REPO / "dlbb_tpu" / "utils" / "timing.py").read_text()
    assert "spans" not in src
    for node in ast.walk(ast.parse(src)):
        if isinstance(node, ast.Import):
            mods = [a.name for a in node.names]
        elif isinstance(node, ast.ImportFrom):
            mods = [node.module or ""]
        else:
            continue
        assert not any("obs" in m for m in mods), (
            f"timing.py imports {mods} — the timed-region module must "
            "never reference dlbb_tpu.obs"
        )


def test_tracing_scope_first_starter_wins(tmp_path):
    outer_path = tmp_path / "outer.json"
    inner_path = tmp_path / "inner.json"
    with spans.tracing(outer_path) as outer:
        assert spans.active() is outer
        with spans.tracing(inner_path) as inner:
            assert inner is outer  # pass-through, no second tracer
            spans.span("x").__enter__()  # lands in the outer trace
            spans.active().end("x")
    assert outer_path.exists() and not inner_path.exists()
    assert spans.active() is None
    names = [e["name"]
             for e in json.loads(outer_path.read_text())["traceEvents"]]
    assert "x" in names


def test_tracing_disabled_path_noop():
    with spans.tracing(None) as tracer:
        assert tracer is None
        assert spans.span("x") is spans.span("y")


# ---------------------------------------------------------------------------
# journal -> span sink
# ---------------------------------------------------------------------------


def test_journal_sink_equivalence(tmp_path):
    """Every journal event must appear as exactly one trace instant with
    the same name and payload — the two artifacts tell one story."""
    from dlbb_tpu.resilience.journal import SweepJournal, read_journal

    with spans.tracing(tmp_path / "t.json") as tracer:
        j = SweepJournal(tmp_path, meta={"kind": "test"},
                         sink=spans.journal_sink)
        j.event("planned", config="a.json")
        j.event("started", config="a.json")
        j.event("completed", config="a.json", retries=0)
        j.close()
        instants = [e for e in tracer.events() if e["cat"] == "journal"]
    events, torn = read_journal(tmp_path)
    assert torn == 0
    assert [e["event"] for e in events] == \
        [i["name"] for i in instants]  # sweep-start included, in order
    by_name = {i["name"]: i for i in instants}
    assert by_name["completed"]["args"]["config"] == "a.json"
    assert by_name["completed"]["args"]["retries"] == 0


def test_journal_sink_fires_even_when_file_journal_disabled(tmp_path):
    from dlbb_tpu.resilience.journal import SweepJournal

    with spans.tracing(tmp_path / "t.json") as tracer:
        j = SweepJournal(tmp_path, enabled=False, sink=spans.journal_sink)
        j.event("planned", config="a.json")
        assert not (tmp_path / "sweep_journal.jsonl").exists()
        assert [e["name"] for e in tracer.events()
                if e["cat"] == "journal"] == ["planned"]


def test_journal_sink_exceptions_contained(tmp_path):
    from dlbb_tpu.resilience.journal import SweepJournal, read_journal

    def bad_sink(event, record):
        raise RuntimeError("observer crash")

    j = SweepJournal(tmp_path, sink=bad_sink)
    j.event("planned", config="a.json")  # must not raise
    j.close()
    events, _ = read_journal(tmp_path)
    assert [e["event"] for e in events] == ["sweep-start", "planned"]


def test_journal_to_trace_reconstruction(tmp_path):
    from dlbb_tpu.resilience.journal import SweepJournal

    j = SweepJournal(tmp_path, meta={"kind": "1d"})
    j.event("planned", config="a.json")
    j.event("started", config="a.json")
    j.event("completed", config="a.json")
    j.event("started", config="b.json")
    j.event("failed", config="b.json", error="boom")
    j.close()
    path, n, torn = journal_to_trace(tmp_path, tmp_path / "trace.json")
    data = json.loads(path.read_text())
    evs = data["traceEvents"]
    assert torn == 0 and n == len(evs)
    assert validate_trace_events(evs) == []
    complete = {e["name"]: e for e in evs if e["ph"] == "X"}
    assert set(complete) == {"a.json", "b.json"}
    assert complete["a.json"]["cat"] == "config-completed"
    assert complete["b.json"]["cat"] == "config-failed"
    assert complete["b.json"]["args"]["error"] == "boom"


def test_journal_to_trace_empty_dir_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        journal_to_trace(tmp_path, tmp_path / "trace.json")


# ---------------------------------------------------------------------------
# metrics registry / Prometheus export
# ---------------------------------------------------------------------------


def test_metrics_registry_counters_and_gauges():
    m = MetricsRegistry()
    m.inc("requests", outcome="ok")
    m.inc("requests", 2, outcome="ok")
    m.inc("requests", outcome="err")
    m.set_gauge("depth", 7.5)
    assert m.get("requests", outcome="ok") == 3
    assert m.get("requests", outcome="err") == 1
    assert m.get("never-registered") == 0
    with pytest.raises(ValueError):
        m.inc("requests", -1, outcome="ok")
    with pytest.raises(ValueError):
        m.set_gauge("requests", 1)  # kind clash
    text = m.to_prometheus()
    assert 'dlbb_requests_total{outcome="ok"} 3' in text
    assert "# TYPE dlbb_depth gauge" in text
    assert "dlbb_depth 7.5" in text


def test_labeled_counter_backs_manifest_dict():
    m = MetricsRegistry()
    counts = m.labeled_counter("sweep_configs", "outcome",
                               initial=("measured", "failed"))
    counts["measured"] += 2
    counts["failed"] += 1
    assert dict(counts) == {"measured": 2, "failed": 1}
    # the SAME numbers are in the registry (one source of truth)
    assert m.get("sweep_configs", outcome="measured") == 2
    assert 'dlbb_sweep_configs_total{outcome="measured"} 2' \
        in m.to_prometheus()
    with pytest.raises(ValueError):
        counts["measured"] = 0  # counters never decrease


def test_prometheus_textfile_write(tmp_path):
    m = MetricsRegistry()
    m.inc("x")
    path = m.write_textfile(tmp_path / "metrics.prom")
    assert path.read_text().rstrip().endswith("dlbb_x_total 1")


# ---------------------------------------------------------------------------
# calibration diff gate (seeded fixtures; pinned EXIT_* contract)
# ---------------------------------------------------------------------------


def _fake_report(targets, tier="cpu-sim", version="cm1"):
    rows = []
    for name, (pred, meas) in sorted(targets.items()):
        rows.append({
            "target": name, "tier": tier, "cost_model_version": version,
            "predicted_us": pred, "measured_us": meas,
            "signed_rel_error": (meas - pred) / pred,
            "error_factor": max(meas, pred) / min(meas, pred),
            "reps": 5,
        })
    return {
        "schema": cal.CALIBRATION_SCHEMA, "tier": tier,
        "cost_model_version": version,
        "aggregate": cal.aggregate_errors(rows),
        "targets": rows, "skipped": [], "timestamp": 0.0,
    }


def _diff_rc(tmp_path, report, baseline, name="case"):
    from dlbb_tpu.cli import main

    base_dir = tmp_path / f"{name}_base"
    cal.save_calibration_baseline(baseline, base_dir)
    rep_path = tmp_path / f"{name}_report.json"
    rep_path.write_text(json.dumps(report))
    return main(["obs", "diff", "--report", str(rep_path),
                 "--calibration", str(base_dir)])


def test_obs_diff_clean_exit_zero(tmp_path):
    base = _fake_report({"t::a": (10.0, 100.0), "t::b": (5.0, 40.0)})
    cur = _fake_report({"t::a": (10.0, 120.0), "t::b": (5.0, 35.0)})
    assert _diff_rc(tmp_path, cur, base) == EXIT_CLEAN


def test_obs_diff_regression_exit_one(tmp_path):
    base = _fake_report({"t::a": (10.0, 100.0), "t::b": (5.0, 40.0)})
    # error factors blew up 10x across the board -> aggregate gate trips
    cur = _fake_report({"t::a": (10.0, 1000.0), "t::b": (5.0, 400.0)})
    assert _diff_rc(tmp_path, cur, base) == EXIT_FINDINGS


def test_obs_diff_missing_baseline_exit_one(tmp_path):
    cur = _fake_report({"t::a": (10.0, 100.0)})
    rep_path = tmp_path / "r.json"
    rep_path.write_text(json.dumps(cur))
    from dlbb_tpu.cli import main

    assert main(["obs", "diff", "--report", str(rep_path),
                 "--calibration", str(tmp_path / "nope")]) == EXIT_FINDINGS


def test_obs_diff_cost_model_skew_exit_one(tmp_path):
    base = _fake_report({"t::a": (10.0, 100.0)}, version="cm0")
    cur = _fake_report({"t::a": (10.0, 100.0)})
    assert _diff_rc(tmp_path, cur, base) == EXIT_FINDINGS


def test_obs_diff_crash_exit_two(tmp_path):
    from dlbb_tpu.cli import main

    # unreadable report -> the analyzer crashed, not "findings"
    assert main(["obs", "diff", "--report",
                 str(tmp_path / "missing.json")]) == EXIT_CRASH


def test_obs_diff_subset_joins_soundly(tmp_path):
    """A subset run (the obs_smoke stage) must diff against the JOINED
    target set — committed-only targets cannot fail it, new targets only
    warn."""
    base = _fake_report({
        "t::a": (10.0, 100.0), "t::b": (5.0, 40.0), "t::c": (2.0, 30.0),
    })
    cur = _fake_report({"t::a": (10.0, 110.0), "t::new": (1.0, 500.0)})
    assert _diff_rc(tmp_path, cur, base) == EXIT_CLEAN
    findings = cal.diff_calibration(cur, tmp_path / "case_base")
    assert {f.rule for f in findings} == {"uncalibrated-target"}
    assert all(f.severity == "warning" for f in findings)


def test_aggregate_errors_empty_and_signed():
    agg = cal.aggregate_errors([])
    assert agg["targets_measured"] == 0
    assert agg["geomean_error_factor"] is None
    rows = _fake_report({"t::a": (10.0, 5.0)})["targets"]
    agg = cal.aggregate_errors(rows)
    # UNDER-prediction carries its sign: measured half of predicted
    assert agg["median_signed_rel_error"] == pytest.approx(-0.5)
    assert agg["geomean_error_factor"] == pytest.approx(2.0)


# ---------------------------------------------------------------------------
# summarize satellites (p999 + empty-series contract)
# ---------------------------------------------------------------------------


def test_summarize_p999_and_empty_contract():
    import numpy as np

    from dlbb_tpu.utils.metrics import SUMMARY_KEYS, summarize

    xs = np.random.default_rng(7).lognormal(size=4096).tolist()
    out = summarize(xs)
    assert set(out) == set(SUMMARY_KEYS)
    np.testing.assert_allclose(out["p999"], np.percentile(xs, 99.9),
                               rtol=1e-12)
    empty = summarize([])
    assert set(empty) == set(SUMMARY_KEYS)
    assert empty["count"] == 0
    assert all(np.isnan(v) for k, v in empty.items() if k != "count")
    # downstream stats consumers index these keys on quarantined-empty
    # series — they must exist (no KeyError), never a bare {}
    assert empty["median"] != empty["median"]  # NaN


# ---------------------------------------------------------------------------
# obs_smoke gate: traced sweep equivalence + calibration round trip
# ---------------------------------------------------------------------------

_VOLATILE = {
    # timing fields + everything derived from them or from the run moment
    "timings", "timestamp", "compile_seconds", "compile_cache_hit",
    "forced_completion_s", "forced_completion_probe_skipped",
    "system_info", "device_trace",
}


def _tiny_sweep(tmp_path, out, **kw):
    from dlbb_tpu.bench import Sweep1D

    return Sweep1D(
        operations=("allreduce", "allgather"),
        data_sizes=(("1KB", 256),),
        rank_counts=(4,),
        warmup_iterations=2,
        measurement_iterations=8,
        output_dir=str(tmp_path / out),
        pipeline=False,
        compile_cache="off",
        **kw,
    )


@pytest.mark.obs_smoke
def test_traced_sweep_equivalent_to_untraced(tmp_path, devices):
    """The acceptance gate: span tracing + device capture ON must emit a
    Perfetto-loadable trace AND publish stats equivalent to an untraced
    serial run (same proof style as the PR-3 serial-vs-pipelined gate);
    the dedicated profile reps never enter the stats series."""
    from dlbb_tpu.bench import run_sweep
    from dlbb_tpu.obs.capture import xplane_files

    trace_path = tmp_path / "spans.json"
    dev_dir = tmp_path / "dev"
    ft = run_sweep(_tiny_sweep(tmp_path, "traced",
                               span_trace=str(trace_path),
                               device_trace_dir=str(dev_dir)),
                   verbose=False)
    fu = run_sweep(_tiny_sweep(tmp_path, "untraced"), verbose=False)
    assert [p.name for p in ft] == [p.name for p in fu]
    for pt, pu in zip(ft, fu):
        dt, du = json.loads(pt.read_text()), json.loads(pu.read_text())
        # identical schema modulo the capture metadata...
        assert sorted(set(dt) - {"device_trace"}) == sorted(du)
        # ...identical non-timing content...
        for k in sorted(set(dt) & set(du) - _VOLATILE):
            assert dt[k] == du[k], k
        # ...and the stats series is exactly the configured length on
        # BOTH sides: profile reps never joined it
        for d in (dt, du):
            assert d["measurement_iterations"] == 8
            assert all(len(row) == 8 for row in d["timings"])
        assert dt["device_trace"]["excluded_from_stats"] is True

    # the span trace is valid Perfetto-loadable trace-event JSON with
    # the whole phase taxonomy present
    trace = json.loads(trace_path.read_text())
    evs = trace["traceEvents"]
    assert validate_trace_events(evs) == []
    cats = {e.get("cat") for e in evs}
    assert {"sweep", "compile", "measure", "payload", "io", "capture",
            "journal"} <= cats
    # device capture produced real xplane traces, one dir per config
    assert xplane_files(dev_dir)
    manifest = json.loads(
        (tmp_path / "traced" / "sweep_manifest.json").read_text())
    assert manifest["observability"]["device_captures"] == 2
    assert manifest["observability"]["span_trace"] == str(trace_path)
    untraced_manifest = json.loads(
        (tmp_path / "untraced" / "sweep_manifest.json").read_text())
    assert untraced_manifest["observability"]["span_trace"] is None
    assert untraced_manifest["observability"]["device_captures"] == 0


@pytest.mark.obs_smoke
def test_obs_calibrate_and_diff_roundtrip(tmp_path, devices):
    """``obs calibrate`` on a micro-op subset produces a signed-error
    report + manifest aggregate, and ``obs diff`` round-trips against a
    same-process baseline (clean) and catches a seeded regression.

    The diff against the COMMITTED sim-tier baseline deliberately lives
    in ``scripts/run_static_analysis.sh`` (a fresh ``cli obs diff``
    process), not here: measured medians inside the fully-loaded tier-1
    pytest process run several-x hotter than any fresh-process baseline,
    which is host-load noise, not cost-model drift — exactly what the
    gate must not fire on."""
    from dlbb_tpu.cli import main

    out = tmp_path / "cal"
    rc = main(["obs", "calibrate", "--output", str(out),
               "--targets", "::allgather", "::alltoall", "::barrier",
               "--reps", "15", "--warmup", "5"])
    assert rc == EXIT_CLEAN
    report = json.loads((out / cal.REPORT_NAME).read_text())
    assert report["tier"] == "cpu-sim"
    assert report["cost_model_version"] == "cm1"
    measured = {r["target"] for r in report["targets"]}
    assert measured == {"comm/ops.py::allgather", "comm/ops.py::alltoall",
                        "comm/ops.py::barrier"}
    for r in report["targets"]:
        assert r["predicted_us"] > 0 and r["measured_us"] > 0
        assert r["error_factor"] >= 1.0
        # signed error and factor must agree on direction
        assert (r["signed_rel_error"] >= 0) == (
            r["measured_us"] >= r["predicted_us"])
        # the prediction must match the committed schedule baseline the
        # calibration claims to join against
        committed = json.loads(
            (REPO / "stats" / "analysis" / "baselines" /
             f"comm_ops.py_{r['target'].rsplit(':', 1)[-1]}.json")
            .read_text())
        assert r["predicted_us"] == committed["critical_path_us"]
    agg = report["aggregate"]
    assert agg["targets_measured"] == 3
    assert agg["geomean_error_factor"] >= 1.0
    # the aggregate also landed in the manifest (acceptance criterion)
    manifest = json.loads((out / "sweep_manifest.json").read_text())
    assert manifest["calibration"]["geomean_error_factor"] == \
        agg["geomean_error_factor"]
    assert (out / cal.CSV_NAME).read_text().startswith("target,")

    # self-baseline diff: clean by construction
    base_dir = tmp_path / "base"
    cal.save_calibration_baseline(report, base_dir)
    rc = main(["obs", "diff", "--report", str(out / cal.REPORT_NAME),
               "--calibration", str(base_dir)])
    assert rc == EXIT_CLEAN
    # seeded regression on the REAL measured data: a baseline whose
    # errors were 100x smaller means this run's model got 100x worse
    shrunk = json.loads(json.dumps(report))
    for row in shrunk["targets"]:
        row["measured_us"] = row["predicted_us"] * (
            1 + (row["measured_us"] / row["predicted_us"] - 1) / 100)
        row["error_factor"] = max(row["measured_us"], row["predicted_us"]) \
            / min(row["measured_us"], row["predicted_us"])
    cal.save_calibration_baseline(shrunk, tmp_path / "shrunk")
    rc = main(["obs", "diff", "--report", str(out / cal.REPORT_NAME),
               "--calibration", str(tmp_path / "shrunk")])
    assert rc == EXIT_FINDINGS
