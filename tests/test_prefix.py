"""Shared-prefix KV cache + int8-quantized KV planes
(``docs/serving.md``, "Prefix cache & quantized KV").

The load-bearing contract is EQUIVALENCE: the prefix-cached engine (fp
planes) must produce completed-token sequences IDENTICAL to the
no-sharing engine on the same trace — an attach copies the exact block
values the skipped chunks would have computed, so reuse buys prefill
dispatches, never different results.  Around that: the host-side radix
trie's refcount/copy-on-write/free semantics, the rollback snapshot
covering trie + refcounts (a replayed dispatch never double-frees or
leaks a shared block), the int8 codec's fp32 round-trip stability, the
quantized-layout footprint formula, and the config validation fences
(prefix caching is a dp=1 + chunked-prefill + no-speculation feature)."""

import json

import numpy as np
import pytest

from dlbb_tpu.comm.mesh import build_parallelism_mesh
from dlbb_tpu.models.configs import (
    ModelConfig,
    kv_cache_bytes_per_device,
)
from dlbb_tpu.serve.engine import ServingConfig, ServingEngine
from dlbb_tpu.serve.kvcache import (
    BlockLedger,
    CacheOverflow,
    PrefixTrie,
    dequantize_kv_blocks,
    quantize_kv_blocks,
)
from dlbb_tpu.serve.traffic import generate_trace

TINY = dict(hidden_size=64, num_layers=2, num_heads=4,
            ffn_intermediate=128, dtype="float32", attention="full")
MODEL = ModelConfig(**TINY)
SERVE = dict(max_batch=4, block_size=8, max_seq=96, hbm_budget_gb=None,
             prefill_chunk=16)


def _prefix_trace(num=8, seed=3, groups=2, prefix_len=64):
    return generate_trace("poisson", num, seed=seed, rate=100.0,
                          prompt_range=(65, 80), output_range=(4, 8),
                          prefix_groups=groups, prefix_len=prefix_len)


@pytest.fixture(scope="module")
def mesh_tp4():
    """dp=1 x tp=4 — the prefix/quant serving envelope."""
    return build_parallelism_mesh(tensor_parallel=4)


# ---------------------------------------------------------------------------
# config surface
# ---------------------------------------------------------------------------


def test_prefix_caching_validation_fences():
    # prefix caching rides the chunked-prefill machinery
    with pytest.raises(ValueError, match="prefill_chunk"):
        ServingConfig(**SERVE | {"prefill_chunk": None},
                      prefix_caching=True).validate(MODEL)
    # dp=1 only: a donor copy must be shard-local
    with pytest.raises(ValueError, match="dp=1"):
        ServingConfig(**SERVE, prefix_caching=True).validate(MODEL, dp=2)
    # speculation's token-feedback bookkeeping is out of envelope
    with pytest.raises(ValueError, match="speculation"):
        ServingConfig(**SERVE, prefix_caching=True, speculation="greedy",
                      ).validate(MODEL)
    ServingConfig(**SERVE, prefix_caching=True).validate(MODEL, dp=1)


def test_kv_quantization_validation_fences():
    with pytest.raises(ValueError, match="kv_quantization"):
        ServingConfig(**SERVE, kv_quantization="fp4").validate(MODEL)
    with pytest.raises(ValueError, match="speculation"):
        ServingConfig(**SERVE, kv_quantization="int8",
                      speculation="ngram", spec_gamma=2).validate(MODEL)
    with pytest.raises(ValueError, match="compact_threshold"):
        ServingConfig(**SERVE, kv_quantization="int8",
                      decode_horizon=8,
                      compact_threshold=0.5).validate(MODEL)
    sv = ServingConfig(**SERVE, prefix_caching=True,
                       kv_quantization="int8")
    sv.validate(MODEL, dp=1)
    # both knobs round-trip the config dict (report/manifest identity)
    back = ServingConfig.from_dict(sv.to_dict())
    assert back.prefix_caching and back.kv_quantization == "int8"


def test_quantized_footprint_formula():
    """int8 layout: one byte per element + one fp32 scale per
    (block, kv-head) per plane — strictly between 1/4 and 1/3 of the
    fp32 footprint at block_size=8, and the per-device split divides
    exactly like the fp path."""
    fp = kv_cache_bytes_per_device(MODEL, 8, 64, dp=1, tp=4)
    q = kv_cache_bytes_per_device(MODEL, 8, 64, dp=1, tp=4,
                                  kv_quantization="int8", block_size=8)
    assert fp / 4 < q < fp / 3
    whole = kv_cache_bytes_per_device(MODEL, 8, 64,
                                      kv_quantization="int8",
                                      block_size=8)
    assert whole == 4 * q  # tp divides kv-heads; scales shard with them


# ---------------------------------------------------------------------------
# int8 codec
# ---------------------------------------------------------------------------


def test_int8_roundtrip_is_fp32_stable():
    """quantize -> dequantize(fp32) -> quantize is a fixed point: the
    second pass reproduces the first bit-exactly (|q*s/s - q| well under
    0.5 ulp of the int grid), so requantizing an untouched block in the
    decode step never walks its values."""
    rng = np.random.default_rng(0)
    blocks = rng.standard_normal((2, 4, 3, 8, 4, 16)).astype(np.float32)
    q, s = quantize_kv_blocks(blocks)
    assert str(q.dtype) == "int8" and str(s.dtype) == "float32"
    deq = dequantize_kv_blocks(q, s, np.float32)
    q2, s2 = quantize_kv_blocks(deq)
    np.testing.assert_array_equal(np.asarray(q), np.asarray(q2))
    np.testing.assert_array_equal(np.asarray(s), np.asarray(s2))
    # max quantization error bounded by half a step per (block, head)
    step = np.asarray(s)[..., None, :, None]
    assert np.max(np.abs(np.asarray(deq) - blocks) / step) <= 0.5 + 1e-6


def test_int8_all_zero_block_uses_unit_scale():
    q, s = quantize_kv_blocks(np.zeros((1, 1, 2, 4, 2, 8), np.float32))
    assert np.all(np.asarray(q) == 0) and np.all(np.asarray(s) == 1.0)


# ---------------------------------------------------------------------------
# trie + refcounted ledger semantics (pure host, no device)
# ---------------------------------------------------------------------------


def _chain(*vals):
    return [tuple(range(v * 10, v * 10 + 4)) for v in vals]


def test_trie_match_attach_release_refcounts():
    trie = PrefixTrie()
    assert trie.match(_chain(1, 2)) == (0, None)
    created, newly = trie.extend(0, _chain(1, 2))
    assert created == 2 and newly == 2 and trie.num_nodes == 2
    depth, donor = trie.match(_chain(1, 2, 3))
    assert depth == 2 and donor == 0
    trie.attach(1, _chain(1, 2), 2)
    assert trie.total_refs() == 4 and trie.shared_depth(1) == 2
    # divergent extend: slot 1 adds its own third block (copy-on-write
    # edge) — the shared spine keeps both refs
    created, newly = trie.extend(1, _chain(1, 2, 9))
    assert created == 1 and trie.num_nodes == 3
    # release the donor: spine survives (slot 1 still refs it), only
    # nodes that lose their LAST ref prune
    assert trie.release(0) == 0
    assert trie.num_nodes == 3 and trie.shared_depth(1) == 3
    assert trie.release(1) == 3
    assert trie.num_nodes == 0 and trie.total_refs() == 0
    # idempotent: releasing a slot with no refs is a no-op, never a
    # double-free
    assert trie.release(1) == 0


def test_ledger_shared_blocks_counted_once():
    """Two slots holding the same 2-block prefix reserve it ONCE
    fleet-wide: dedup at register() refunds the private reservation, so
    a third request that would not fit privately still admits."""
    led = BlockLedger(total_blocks=8, block_size=4, prefix_caching=True)
    chain = _chain(1, 2)
    led.reserve(0, total_tokens=12, chain=None, attach_blocks=0)
    led.register(0, chain)
    assert led.blocks_reserved == 3  # 2 shared + 1 private
    assert led.shared_blocks == 2
    depth, donor = led.match_prefix(_chain(1, 2, 5))
    assert (depth, donor) == (2, 0)
    # second request attaches: only its private tail is new budget
    assert led.can_reserve(12, shared_blocks=2)
    led.reserve(1, total_tokens=12, chain=chain, attach_blocks=2)
    led.register(1, chain)
    assert led.blocks_reserved == 4  # 2 shared + 2 private tails
    # free slot 0: the shared spine survives under slot 1's refs
    led.append(0, 8)
    led.append(1, 8)
    assert led.free(0) == 1
    assert led.shared_blocks == 2 and led.blocks_reserved == 3
    assert led.free(1) == 3
    assert led.blocks_reserved == 0 and led.shared_blocks == 0
    assert led.stats()["prefix_refs"] == 0


def test_ledger_register_overflow_fails_closed():
    led = BlockLedger(total_blocks=4, block_size=4, prefix_caching=True)
    led.reserve(0, total_tokens=4)
    with pytest.raises(CacheOverflow):
        led.register(0, _chain(1, 2))  # 2 new shared > 1 reserved


def test_ledger_snapshot_restores_trie_and_refcounts():
    """The pre-dispatch rollback covers the trie: a torn attach (or a
    torn free) replayed from the snapshot neither leaks a node nor
    double-frees a shared block."""
    led = BlockLedger(total_blocks=16, block_size=4, prefix_caching=True)
    chain = _chain(1, 2)
    led.reserve(0, 12), led.register(0, chain)
    snap = led.snapshot()
    # torn mutation: a second slot attaches AND the donor frees
    led.reserve(1, 12, chain=chain, attach_blocks=2)
    led.register(1, chain)
    led.free(0)
    led.restore(snap)
    assert led.blocks_reserved == 3 and led.shared_blocks == 2
    assert led.trie.total_refs() == 2 and led.trie.shared_depth(0) == 2
    # replay applies cleanly on the restored state
    led.reserve(1, 12, chain=chain, attach_blocks=2)
    led.register(1, chain)
    led.free(0), led.free(1)
    assert led.blocks_reserved == 0 and led.trie.num_nodes == 0


# ---------------------------------------------------------------------------
# traffic: seeded shared-prefix groups
# ---------------------------------------------------------------------------


def test_prefix_trace_groups_share_seeds_and_roundtrip(tmp_path):
    trace = _prefix_trace()
    seeds = {r.prefix_seed for r in trace.requests}
    assert len(seeds) == 2 and None not in seeds
    assert all(r.prefix_len == 64 for r in trace.requests)
    assert all(r.prefix_len < r.prompt_len for r in trace.requests)
    path = tmp_path / "t.json"
    trace.save(path)
    replay = type(trace).load(path)
    assert replay.requests == trace.requests


def test_plain_trace_bytes_unchanged(tmp_path):
    """The prefix draws happen strictly AFTER the original rng
    consumption, so traces without prefix_groups are byte-identical to
    the pre-prefix schema (saved replay traces stay valid)."""
    plain = generate_trace("poisson", 4, seed=7, rate=50.0,
                           prompt_range=(4, 16), output_range=(2, 6))
    assert all(r.prefix_len is None and r.prefix_seed is None
               for r in plain.requests)
    plain.save(tmp_path / "p.json")
    payload = json.loads((tmp_path / "p.json").read_text())
    assert all("prefix_len" not in r for r in payload["requests"])


# ---------------------------------------------------------------------------
# engine equivalence + accounting (the prefix_smoke gate)
# ---------------------------------------------------------------------------


@pytest.mark.prefix_smoke
def test_prefix_and_int8_engines_token_identical(mesh_tp4):
    """The gate: on a seeded 2-group shared-prefix trace, the
    prefix-cached fp engine is TOKEN-IDENTICAL to the no-sharing
    engine (attach copies the exact chunk values), the int8 engine
    completes every request (argmax-identical on this model), the trie
    registers real hits, and every shared block drains to zero."""
    trace = _prefix_trace()

    def run(**extra):
        eng = ServingEngine(MODEL, ServingConfig(**SERVE, **extra),
                            mesh_tp4, verbose=False, capture_tokens=True)
        return eng.run_trace(trace), eng

    base, _ = run()
    pfx, eng = run(prefix_caching=True)
    assert pfx["completed_tokens"] == base["completed_tokens"]
    # group members admitted AFTER their group's first registration
    # attach (the exact count depends on admission timing; with
    # max_batch=4 and a fast trace at least the trailing arrivals hit)
    hits = pfx["prefix"]["hits"]
    assert hits >= 2
    assert pfx["prefix"]["tokens_reused"] == hits * 64
    assert pfx["prefix"]["hit_rate"] == pytest.approx(hits / 8)
    assert pfx["cache"]["peak_shared_blocks"] > 0
    assert pfx["cache"]["shared_blocks"] == 0  # drained
    assert pfx["cache"]["prefix_refs"] == 0
    assert pfx["cache"]["blocks_reserved"] == 0
    assert int(eng.registry.get("serve_prefix_hits")) == hits
    assert len(pfx["timeseries"]["shared_blocks"]) == len(
        pfx["timeseries"]["t_s"])

    quant, _ = run(prefix_caching=True, kv_quantization="int8")
    assert quant["requests"]["completed"] == len(trace)
    assert quant["prefix"]["hits"] >= 2
    assert quant["completed_tokens"] == base["completed_tokens"]


@pytest.mark.prefix_smoke
def test_prefix_run_artifacts_and_metrics(tmp_path):
    """serve/bench.py + obs surface end to end: journal carries
    prefix-attach events, journal_to_trace renders them as
    prefix-cache instants, metrics.prom exports the hit counters and
    the quantized HBM record prices the int8 layout."""
    from dlbb_tpu.obs import spans
    from dlbb_tpu.resilience.journal import read_journal
    from dlbb_tpu.serve.bench import run_serving

    config = {
        "experiment": {"name": "pfx"},
        "model": dict(TINY),
        "parallelism": {"data_parallel": 1, "world_size": 4},
        "serving": dict(SERVE, prefix_caching=True,
                        kv_quantization="int8"),
    }
    trace = _prefix_trace(num=6, groups=2)
    report = run_serving(config, trace, str(tmp_path), verbose=False)
    assert report["requests"]["completed"] == 6
    hits = report["prefix"]["hits"]
    assert hits >= 1

    events, torn = read_journal(tmp_path)
    assert torn == 0
    attaches = [e for e in events if e["event"] == "prefix-attach"]
    assert len(attaches) == hits
    assert all(e["tokens"] == 64 and e["blocks"] == 8 for e in attaches)
    timeline, _n, _t = spans.journal_to_trace(tmp_path,
                                              tmp_path / "tl.json")
    rebuilt = spans.load_trace(timeline)
    pre = [e for e in rebuilt["traceEvents"]
           if e.get("cat") == "prefix-cache"]
    assert len(pre) == hits and all(e["ph"] == "i" for e in pre)

    text = (tmp_path / "metrics.prom").read_text()
    assert f"dlbb_serve_prefix_hits_total {hits}" in text
    assert (f"dlbb_serve_prefix_tokens_reused_total {hits * 64}"
            in text)
    assert "dlbb_serve_prefix_hit_rate" in text
    assert 'dlbb_serve_cache_blocks{stat="peak_shared_blocks"}' in text

    result = json.loads((tmp_path / "serving_pfx.json").read_text())
    hbm = result["hbm"]
    fp = kv_cache_bytes_per_device(MODEL, SERVE["max_batch"],
                                   SERVE["max_seq"], dp=1, tp=4)
    assert hbm["kv_cache_bytes_per_device"] < fp / 3


@pytest.mark.prefix_smoke
def test_degraded_attach_after_carry_reset_stays_correct(mesh_tp4):
    """A carry reset between plan and prefill (a permanent decode
    failure mid-trace) invalidates every planned attach: the prefill
    degrades to the full computation (copying a fresh carry's zeroed
    blocks would serve garbage) and the completed requests still match
    the no-sharing engine under the same fault plan."""
    trace = _prefix_trace()

    def run(**extra):
        eng = ServingEngine(
            MODEL, ServingConfig(**SERVE, max_dispatch_retries=0,
                                 **extra),
            mesh_tp4, verbose=False, capture_tokens=True)
        return eng.run_trace(trace, collect_raw=False)

    import dlbb_tpu.resilience.inject as inject
    with inject.plan_scope("serve-decode-fail:@2"):
        base = run()
    with inject.plan_scope("serve-decode-fail:@2"):
        pfx = run(prefix_caching=True)
    done = {k for k, v in base["requests"]["outcomes"].items()
            if v == "completed"}
    for rid in done:
        assert (pfx["completed_tokens"].get(rid)
                == base["completed_tokens"].get(rid)), rid
    assert pfx["cache"]["blocks_reserved"] == 0
    assert pfx["cache"]["shared_blocks"] == 0
