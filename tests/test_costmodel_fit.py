"""cm2 fitted cost model + attribution: corpus ingestion, the α–β–γ
regression (seeded-coefficient recovery, fail-closed degeneracies,
versioned DB), cm1-fallback warning, calibration schema growth
(dispatch columns, per-model baselines, Prometheus export), the
merged sweep+serving journal trace, and the attribution partition
contract (phases sum to the wall)."""

from __future__ import annotations

import json
import math

import pytest

from dlbb_tpu.analysis.costmodel import (
    CM2_VERSION,
    COST_MODEL_VERSION,
    CostTier,
    FitMissingError,
    dispatch_cost_us,
    fit_db_path,
    get_tier,
    load_fitted_tier,
    resolve_tier,
)
from dlbb_tpu.obs import corpus as corpus_mod
from dlbb_tpu.obs import fit as fit_mod
from dlbb_tpu.obs.attribution import (
    ATTRIBUTION_SCHEMA,
    PHASES,
    partition_journal,
    partition_trace,
    predict_iteration_us,
    run_attribution,
    validate_attribution,
)
from dlbb_tpu.obs.fit import FitError, fit_tier, run_fit, save_fit

# ---------------------------------------------------------------------------
# synthetic corpora
# ---------------------------------------------------------------------------

TRUE = {"gamma": 220.0, "alpha": 35.0, "beta": 5000.0, "peak": 2000.0}


def _sample(wire, collectives=1.0, dispatches=1.0, flops=0, op="allreduce",
            tier="cpu-sim", noise=1.0):
    measured = (TRUE["gamma"] * dispatches + TRUE["alpha"] * collectives
                + wire / TRUE["beta"] + flops / TRUE["peak"]) * noise
    return {
        "file": f"synth_{op}_{wire}_{collectives}.json", "op": op,
        "variant": "default", "kind": "all-reduce", "ranks": 8,
        "dtype": "bfloat16", "num_elements": wire // 2,
        "wire_bytes": int(wire), "flops": int(flops),
        "collectives": float(collectives), "dispatches": float(dispatches),
        "measured_median_us": measured, "measured_p99_us": measured * 1.2,
        "iterations": 20, "tier": tier, "host": "synthhost/cpu2/dev8",
        "timestamp": 0.0,
    }


def _synthetic_corpus():
    samples = []
    for wire in (1024, 65536, 1048576, 8 * 1048576):
        for coll in (1.0, 7.0):
            samples.append(_sample(wire, collectives=coll))
        samples.append(_sample(wire, collectives=1.0, dispatches=0.1))
        samples.append(_sample(wire, flops=2_000_000, op="ag_matmul"))
        samples.append(_sample(wire, flops=16_000_000, op="ag_matmul"))
    return samples


def test_fit_recovers_seeded_coefficients():
    fit = fit_tier(_synthetic_corpus(), "cpu-sim")
    c = fit["coefficients"]
    assert c["gamma_dispatch_us"]["value"] == pytest.approx(
        TRUE["gamma"], rel=0.05)
    assert c["alpha_us"]["value"] == pytest.approx(TRUE["alpha"], rel=0.1)
    assert c["beta_bytes_per_us"]["value"] == pytest.approx(
        TRUE["beta"], rel=0.05)
    assert c["peak_flops_per_us"]["value"] == pytest.approx(
        TRUE["peak"], rel=0.05)
    assert not fit["alpha_pinned"] and not fit["peak_pinned"]
    assert fit["residuals"]["geomean_error_factor"] < 1.05
    # CI bounds bracket the fitted value where reported
    ci = c["gamma_dispatch_us"].get("ci95")
    assert ci and ci[0] <= c["gamma_dispatch_us"]["value"] <= ci[1]


def test_fit_rejects_outliers():
    samples = _synthetic_corpus()
    samples.append(_sample(1024, noise=80.0))  # one wild host spike
    fit = fit_tier(samples, "cpu-sim")
    assert fit["outliers_rejected"] >= 1
    assert fit["coefficients"]["gamma_dispatch_us"]["value"] == \
        pytest.approx(TRUE["gamma"], rel=0.08)


def test_fit_pins_alpha_and_peak_when_unidentifiable():
    # every sample: one collective, one dispatch, zero flops — α and γ
    # are collinear and peak unconstrained; the fit must PIN, not guess
    samples = [_sample(w) for w in
               (1024, 4096, 65536, 262144, 1048576, 4 * 1048576)] * 4
    fit = fit_tier(samples, "cpu-sim", min_samples=8)
    assert fit["alpha_pinned"] and fit["peak_pinned"]
    cm1 = get_tier("cpu-sim")
    c = fit["coefficients"]
    assert c["alpha_us"] == {"value": cm1.alpha_us, "pinned": "cm1"}
    assert c["peak_flops_per_us"]["pinned"] == "cm1"
    # intercept lands in γ (minus the pinned cm1 α)
    assert c["gamma_dispatch_us"]["value"] == pytest.approx(
        TRUE["gamma"] + TRUE["alpha"] - cm1.alpha_us, rel=0.05)


def test_fit_fails_closed_on_degenerate_corpora():
    with pytest.raises(FitError, match="need >="):
        fit_tier(_synthetic_corpus()[:4], "cpu-sim")
    with pytest.raises(FitError, match="single message size"):
        fit_tier([_sample(1024) for _ in range(20)], "cpu-sim")
    with pytest.raises(FitError, match="no usable corpus samples"):
        fit_tier([], "cpu-sim")
    # all rows quarantined/non-finite: equally refused
    bad = [dict(_sample(1024), measured_median_us=float("nan"))
           for _ in range(20)]
    with pytest.raises(FitError, match="no usable corpus samples"):
        fit_tier(bad, "cpu-sim")
    with pytest.raises(KeyError):
        fit_tier(_synthetic_corpus(), "no-such-tier")


def test_fit_db_versioning_append_only(tmp_path):
    fit = fit_tier(_synthetic_corpus(), "cpu-sim")
    path, v1 = save_fit(fit, tmp_path)
    assert path == fit_db_path("cpu-sim", tmp_path) and v1 == 1
    _, v2 = save_fit(fit, tmp_path)
    assert v2 == 2
    db = json.loads(path.read_text())
    assert [e["fit_version"] for e in db["versions"]] == [1, 2]
    tier = load_fitted_tier("cpu-sim", tmp_path)
    assert tier.version == CM2_VERSION
    assert tier.fit["fit_version"] == 2  # latest wins
    pinned = load_fitted_tier("cpu-sim", tmp_path, fit_version=1)
    assert pinned.fit["fit_version"] == 1
    with pytest.raises(FitMissingError):
        load_fitted_tier("cpu-sim", tmp_path, fit_version=9)
    assert tier.gamma_dispatch_us == pytest.approx(TRUE["gamma"], rel=0.05)
    assert dispatch_cost_us(3, tier) == pytest.approx(
        3 * tier.gamma_dispatch_us)


def test_resolve_tier_cm2_fallback_warns(tmp_path, capsys):
    tier = resolve_tier("cpu-sim", model=CM2_VERSION, fit_dir=tmp_path)
    out = capsys.readouterr().out
    assert "fit-missing" in out and "falling back to cm1" in out
    # the fallback tier IS cm1: version records what actually priced
    assert tier.version == COST_MODEL_VERSION
    assert tier.gamma_dispatch_us == 0.0
    with pytest.raises(KeyError):
        resolve_tier("cpu-sim", model="cm99")


def test_resolve_tier_cm1_is_identity():
    assert resolve_tier("cpu-sim") == get_tier("cpu-sim")


# ---------------------------------------------------------------------------
# corpus ingestion
# ---------------------------------------------------------------------------


def _artifact(op="allreduce", ranks=8, elems=512, dtype="bfloat16",
              variant="default", timings=((0.001, 0.0012, 0.0011),),
              **extra):
    return {
        "operation": op, "num_ranks": ranks, "num_elements": elems,
        "dtype": dtype, "variant": variant,
        "timings": [list(t) for t in timings],
        "timing_mode": extra.pop("timing_mode", "per_iter"),
        "system_info": {"backend": extra.pop("backend", "cpu"),
                        "platform": "testbox", "cpu_count": 2,
                        "num_devices": ranks},
        **extra,
    }


def test_corpus_ingest_and_features(tmp_path):
    (tmp_path / "a.json").write_text(json.dumps(_artifact()))
    (tmp_path / "b.json").write_text(json.dumps(_artifact(
        op="ag_matmul", tensor_shape=[2, 64, 256], elems=2 * 64 * 256)))
    (tmp_path / "chained.json").write_text(json.dumps(_artifact(
        timing_mode="chained", timing_granularity="chunked(10)")))
    (tmp_path / "noop.json").write_text(json.dumps({"hello": 1}))
    (tmp_path / "sweep_manifest.json").write_text(json.dumps(
        {"wall_seconds": 2.0, "compile_seconds_total": 1.0}))
    corpus = corpus_mod.build_corpus([tmp_path])
    by_op = {s["op"]: s for s in corpus["samples"]}
    assert set(by_op) == {"allreduce", "ag_matmul"} and \
        len(corpus["samples"]) == 3
    by_file = {s["file"].rsplit("/", 1)[-1]: s for s in corpus["samples"]}
    ar = by_file["a.json"]
    assert ar["wire_bytes"] == int(2 * 7 / 8 * 512 * 2)
    assert ar["measured_median_us"] == pytest.approx(1100.0)
    assert ar["tier"] == "cpu-sim" and ar["dispatches"] == 1.0
    ag = by_file["b.json"]
    assert ag["flops"] == 2 * 2 * 64 * 256 * 256
    assert ag["wire_bytes"] == 7 * 2 * 64 * 256 * 2
    chained = [s for s in corpus["samples"]
               if s["dispatches"] != 1.0]
    assert chained and chained[0]["dispatches"] == pytest.approx(0.1)
    assert any("no operation/timings" in s["reason"]
               for s in corpus["skipped"])
    assert corpus["manifests"][0]["wall_seconds"] == 2.0
    with pytest.raises(FileNotFoundError):
        corpus_mod.build_corpus([tmp_path / "missing"])


def test_run_fit_end_to_end(tmp_path, capsys):
    results = tmp_path / "results"
    results.mkdir()
    rng_wires = [(512, 1), (8192, 1), (65536, 3), (524288, 7),
                 (1048576, 1), (4194304, 3)]
    i = 0
    for elems, _ in rng_wires:
        for ranks in (4, 8):
            for variant in ("default", "overlap_ring"):
                op = "ag_matmul" if variant == "overlap_ring" else \
                    "allreduce"
                art = _artifact(op=op, ranks=ranks, elems=elems,
                                variant=variant)
                if op == "ag_matmul":
                    art["tensor_shape"] = [1, 32, 64]
                meas = 300.0 + elems / 2000.0
                art["timings"] = [[meas * 1e-6] * 5]
                (results / f"r{i}.json").write_text(json.dumps(art))
                i += 1
    out = run_fit([results], fit_dir=tmp_path / "db", min_samples=8)
    assert "cpu-sim" in out["fits"]
    assert fit_db_path("cpu-sim", tmp_path / "db").exists()
    # an explicitly requested unfittable tier fails closed
    with pytest.raises(FitError):
        run_fit([results], tiers=["tpu-v5lite"], fit_dir=tmp_path / "db2",
                min_samples=8)


# ---------------------------------------------------------------------------
# schedule meta + calibration schema
# ---------------------------------------------------------------------------

_TINY_HLO = """
HloModule tiny, entry_computation_layout={()->f32[4]}

ENTRY %main () -> f32[4] {
  %c = f32[4] constant({1, 2, 3, 4})
  ROOT %ar = f32[4] all-reduce(%c), replica_groups={{0,1,2,3,4,5,6,7}}, to_apply=%add
}

%add (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %s = f32[] add(%a, %b)
}
"""


def test_schedule_meta_carries_dispatch_overhead():
    from dlbb_tpu.analysis.expectations import TargetExpectation
    from dlbb_tpu.analysis.schedule_audit import analyze_schedule

    exp = TargetExpectation(allowed={"all-reduce"})
    fitted = CostTier(name="cpu-sim", alpha_us=10.0,
                      beta_bytes_per_us=1000.0,
                      peak_flops_per_us=1000.0,
                      gamma_dispatch_us=500.0, version=CM2_VERSION)
    _, meta = analyze_schedule(_TINY_HLO, exp, "t", tier=fitted)
    assert meta["cost_model_version"] == CM2_VERSION
    assert meta["dispatch_count"] == 1
    assert meta["dispatch_overhead_us"] == pytest.approx(500.0)
    assert meta["predicted_wall_us"] == pytest.approx(
        meta["critical_path_us"] + 500.0)
    # cm1 pricing: γ = 0, wall == critical path, version recorded cm1
    _, meta1 = analyze_schedule(_TINY_HLO, exp, "t", tier="cpu-sim")
    assert meta1["cost_model_version"] == COST_MODEL_VERSION
    assert meta1["dispatch_overhead_us"] == 0.0
    assert meta1["predicted_wall_us"] == meta1["critical_path_us"]


def _fake_report(model, tier="cpu-sim", n=3, factor=2.0):
    from dlbb_tpu.obs.calibration import aggregate_errors

    rows = []
    for i in range(n):
        pred, meas = 100.0 * (i + 1), 100.0 * (i + 1) * factor
        rows.append({
            "target": f"t{i}", "tier": tier, "cost_model_version": model,
            "predicted_us": pred, "dispatch_count": 1,
            "predicted_dispatch_overhead_us": 50.0 if model == "cm2"
            else 0.0,
            "measured_us": meas,
            "signed_rel_error": (meas - pred) / pred,
            "error_factor": max(meas, pred) / min(meas, pred),
            "reps": 5,
        })
    return {
        "schema": "dlbb_calibration_v1", "tier": tier,
        "cost_model_version": model, "aggregate": aggregate_errors(rows),
        "targets": rows, "skipped": [], "timestamp": 0.0,
        **({"fit": {"fit_version": 3, "db_path": "x", "samples_used": 40,
                    "residuals": {"geomean_error_factor": 1.5,
                                  "rms_log_error": 0.3}}}
           if model == "cm2" else {}),
    }


def test_calibration_csv_columns_and_report_write(tmp_path):
    from dlbb_tpu.obs.calibration import CSV_COLUMNS, write_report

    assert "dispatch_count" in CSV_COLUMNS
    assert "predicted_dispatch_overhead_us" in CSV_COLUMNS
    report = _fake_report(CM2_VERSION)
    write_report(report, tmp_path)
    csv_text = (tmp_path / "calibration_report.csv").read_text()
    header = csv_text.splitlines()[0].split(",")
    assert header == list(CSV_COLUMNS)
    assert ",1,50.0," in csv_text
    manifest = json.loads((tmp_path / "sweep_manifest.json").read_text())
    assert manifest["calibration"]["fit_version"] == 3
    prom = (tmp_path / "metrics.prom").read_text()
    assert 'dlbb_obs_calibration_error_factor{model="cm2",' \
        'tier="cpu-sim"}' in prom
    assert "dlbb_obs_fit_residual_error_factor" in prom
    assert "dlbb_obs_fit_version" in prom


def test_per_model_calibration_baselines(tmp_path):
    from dlbb_tpu.obs.calibration import (
        baseline_name,
        diff_calibration,
        save_calibration_baseline,
    )

    assert baseline_name("cm1") == "calibration_baseline.json"
    assert baseline_name("cm2") == "calibration_baseline_cm2.json"
    rep1 = _fake_report(COST_MODEL_VERSION)
    rep2 = _fake_report(CM2_VERSION)
    p1 = save_calibration_baseline(rep1, tmp_path)
    p2 = save_calibration_baseline(rep2, tmp_path)
    assert p1.name != p2.name
    # each model diffs against ITS committed baseline: both clean
    assert diff_calibration(rep1, tmp_path) == []
    assert diff_calibration(rep2, tmp_path) == []
    # a cm2 report with no cm2 baseline is a missing-baseline error even
    # though the cm1 file exists
    p2.unlink()
    findings = diff_calibration(rep2, tmp_path)
    assert [f.rule for f in findings] == ["missing-calibration-baseline"]
    assert "cm2" in findings[0].message


# ---------------------------------------------------------------------------
# merged journal trace (sweep + serving streams)
# ---------------------------------------------------------------------------


def test_journal_to_trace_merges_sweep_and_serving_streams(tmp_path):
    from dlbb_tpu.obs.spans import journal_to_trace, validate_trace_events

    recs = [
        {"ts": 1.0, "event": "sweep-start", "mode": "sweep"},
        {"ts": 2.0, "event": "started", "config": "cfg_a.json"},
        {"ts": 3.0, "event": "completed", "config": "cfg_a.json"},
        {"ts": 4.0, "event": "sweep-start", "mode": "serve",
         "name": "mini"},
        {"ts": 5.0, "event": "request-arrived", "config": "request-0"},
        {"ts": 6.0, "event": "request-completed", "config": "request-0",
         "output_tokens": 3},
        {"ts": 6.5, "event": "degraded", "reason": "probe"},
    ]
    with open(tmp_path / "sweep_journal.jsonl", "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    path, n, torn = journal_to_trace(tmp_path, tmp_path / "trace.json")
    trace = json.loads(path.read_text())
    events = trace["traceEvents"]
    assert validate_trace_events(events) == []
    pids = {e["pid"] for e in events}
    assert pids == {1, 2}
    names = {(e["pid"], e["args"]["name"]) for e in events
             if e["ph"] == "M"}
    assert names == {(1, "sweep"), (2, "serving")}
    spans = {(e["pid"], e["name"]): e for e in events if e["ph"] == "X"}
    assert (1, "cfg_a.json") in spans and (2, "request-0") in spans
    # the serve-session degraded event lands on the serving track, as a
    # labelled process-scoped instant (the reason IS the name)
    degraded = [e for e in events if e.get("cat") == "degraded"]
    assert degraded and degraded[0]["pid"] == 2
    assert degraded[0]["name"] == "degraded[probe]"
    assert degraded[0]["s"] == "p"
    assert trace["otherData"]["streams"] == {"1": "sweep", "2": "serving"}


# ---------------------------------------------------------------------------
# attribution
# ---------------------------------------------------------------------------


def test_partition_trace_sums_to_wall():
    ev = []

    def b(name, ts, tid=7):
        ev.append({"name": name, "ph": "B", "ts": ts, "pid": 1,
                   "tid": tid})

    def e(name, ts, tid=7):
        ev.append({"name": name, "ph": "E", "ts": ts, "pid": 1,
                   "tid": tid})

    b("plan", 0.0); e("plan", 100.0)                     # noqa: E702
    b("cfg.json", 150.0)                                 # unmapped parent
    b("compile-wait", 160.0); e("compile-wait", 400.0)   # noqa: E702
    b("measure", 420.0); e("measure", 900.0)             # noqa: E702
    b("write", 900.0); e("write", 950.0)                 # noqa: E702
    e("cfg.json", 960.0)
    phases, wall, _ = partition_trace(ev)
    assert wall == pytest.approx(960.0)
    assert sum(phases.values()) == pytest.approx(wall)
    assert phases["plan"] == pytest.approx(100.0)
    assert phases["compile"] == pytest.approx(240.0)
    assert phases["execute"] == pytest.approx(480.0)
    assert phases["write"] == pytest.approx(50.0)
    assert phases["idle"] == pytest.approx(50.0)   # 100->150
    assert phases["host"] == pytest.approx(40.0)   # unmapped cfg glue
    assert set(phases) <= set(PHASES)


def test_partition_journal_sums_to_wall():
    recs = [
        {"ts": 0.0, "event": "sweep-start"},
        {"ts": 0.5, "event": "request-arrived", "config": "request-0"},
        {"ts": 0.6, "event": "request-admitted", "config": "request-0"},
        {"ts": 0.9, "event": "request-prefill", "config": "request-0"},
        {"ts": 1.5, "event": "request-completed", "config": "request-0"},
    ]
    phases, wall = partition_journal(recs)
    assert wall == pytest.approx(1.5e6)
    assert sum(phases.values()) == pytest.approx(wall)
    assert phases["queue-wait"] == pytest.approx(0.1e6)
    assert phases["prefill"] == pytest.approx(0.3e6)
    assert phases["decode"] == pytest.approx(0.6e6)


def _serving_dir(tmp_path):
    recs = [
        {"ts": 10.0, "event": "sweep-start", "mode": "serve",
         "name": "mini"},
        {"ts": 10.1, "event": "request-arrived", "config": "request-0",
         "prompt": 8, "output": 4},
        {"ts": 10.2, "event": "request-admitted", "config": "request-0",
         "queue_depth": 1},
        {"ts": 10.5, "event": "request-prefill", "config": "request-0",
         "slot": 0, "ttft_s": 0.4},
        {"ts": 11.4, "event": "request-completed", "config": "request-0",
         "output_tokens": 4, "latency_s": 1.3},
        {"ts": 11.5, "event": "request-arrived", "config": "request-1"},
        {"ts": 11.6, "event": "request-rejected", "config": "request-1",
         "reason": "queue-full"},
    ]
    with open(tmp_path / "sweep_journal.jsonl", "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    report = {
        "schema": "dlbb_serving_report_v1",
        "model": {"hidden_size": 64, "num_layers": 2, "dtype": "float32"},
        "mesh": {"dp": 2, "tp": 4},
        "serving": {"max_batch": 4, "max_seq": 64,
                    "prefill_buckets": [16, 64], "decode_horizon": 1},
        "requests": {"arrived": 2, "admitted": 1, "completed": 1,
                     "rejected": 1},
        "decode_units": 4, "decode_steps": 4,
        "fast_path": {"prefill_chunks": 0},
    }
    (tmp_path / "serving_mini.json").write_text(json.dumps(report))
    return tmp_path


def test_attribution_serving_from_journal(tmp_path, capsys):
    _serving_dir(tmp_path)
    out = tmp_path / "attr"
    record = run_attribution(tmp_path, out_dir=out, name="mini")
    assert validate_attribution(record) == []
    assert record["kind"] == "serving" and record["source"] == "journal"
    # wall spans sweep-start (10.0) to the last journal event, the
    # request-1 rejection at 11.6
    assert record["wall_us"] == pytest.approx(1.6e6)
    assert sum(record["phases_us"].values()) == pytest.approx(
        record["wall_us"], rel=0.0001)
    md = (out / "mini.md").read_text()
    assert ATTRIBUTION_SCHEMA in md and "queue-wait" in md
    csv_text = (out / "mini.csv").read_text()
    assert csv_text.splitlines()[0].startswith("kind,name,")
    assert "request,request-0" in csv_text
    rows = {e["name"]: e for e in record["entities"]}
    assert rows["request-0"]["queue_wait_us"] == pytest.approx(0.1e6)
    assert rows["request-0"]["decode_us"] == pytest.approx(0.9e6)
    assert rows["request-0"]["tokens"] == 4
    assert rows["request-1"]["outcome"] == "rejected"
    # predictions priced the report's exact dispatch counts
    assert record["predicted_us"]["decode_units"] == 4
    assert record["predicted_us"]["prefill_dispatches"] == 1


def test_attribution_validates_partition_gap():
    rec = {
        "schema": ATTRIBUTION_SCHEMA, "name": "x", "kind": "sweep",
        "cost_model_version": "cm1", "wall_us": 100.0,
        "phases_us": {"execute": 10.0}, "entities": [],
    }
    problems = validate_attribution(rec)
    assert problems and "phases cover" in problems[0]
    rec["phases_us"] = {"execute": 97.0}
    assert validate_attribution(rec) == []
    rec["phases_us"] = {"warpdrive": 100.0}
    assert any("unknown phase" in p for p in validate_attribution(rec))


def test_predict_iteration_decomposition():
    tier = CostTier(name="t", alpha_us=10.0, beta_bytes_per_us=100.0,
                    peak_flops_per_us=50.0, gamma_dispatch_us=200.0,
                    version=CM2_VERSION)
    parts = predict_iteration_us(
        {"dispatches": 1.0, "collectives": 3.0, "wire_bytes": 1000,
         "flops": 500}, tier)
    assert parts["dispatch"] == pytest.approx(200.0)
    assert parts["wire"] == pytest.approx(3 * 10.0 + 1000 / 100.0)
    assert parts["compute"] == pytest.approx(10.0)
    assert parts["total"] == pytest.approx(
        parts["dispatch"] + parts["wire"] + parts["compute"])


# ---------------------------------------------------------------------------
# fit_smoke: the committed corpus -> fit -> cm2 DB round trip (also run
# standalone by scripts/run_static_analysis.sh)
# ---------------------------------------------------------------------------


@pytest.mark.fit_smoke
def test_fit_smoke_committed_corpus(tmp_path):
    import pathlib

    repo = pathlib.Path(__file__).resolve().parents[1]
    corpus_dir = repo / "results" / "fit_corpus"
    if not corpus_dir.exists():
        pytest.skip("no committed fit corpus")
    out = run_fit([corpus_dir], fit_dir=tmp_path, verbose=False)
    fit = out["fits"]["cpu-sim"]
    c = fit["coefficients"]
    assert c["gamma_dispatch_us"]["value"] > 0
    assert math.isfinite(c["beta_bytes_per_us"]["value"])
    assert fit["residuals"]["geomean_error_factor"] < 10.0
    tier = load_fitted_tier("cpu-sim", tmp_path)
    assert tier.version == CM2_VERSION


@pytest.mark.fit_smoke
def test_fit_smoke_committed_db_prices_cm2(tmp_path):
    """The COMMITTED fitted DB resolves and the committed cm2
    calibration baseline exists, joins, and carries the dispatch
    columns — the acceptance surface of `obs calibrate --model cm2` +
    `obs diff` without re-measuring (the CI shell stage runs the live
    measurement)."""
    import pathlib

    from dlbb_tpu.obs.calibration import (
        DEFAULT_CALIBRATION_DIR,
        load_calibration_baseline,
    )

    repo = pathlib.Path(__file__).resolve().parents[1]
    if not fit_db_path("cpu-sim", repo / "stats/analysis/costmodel_fit"
                       ).exists():
        pytest.skip("no committed cm2 DB")
    tier = load_fitted_tier(
        "cpu-sim", repo / "stats/analysis/costmodel_fit")
    assert tier.version == CM2_VERSION and tier.gamma_dispatch_us > 0
    base = load_calibration_baseline(
        repo / DEFAULT_CALIBRATION_DIR, model=CM2_VERSION)
    assert base["cost_model_version"] == CM2_VERSION
    agg = base["aggregate"]
    # the acceptance number: fitted-model geomean error <= 3x on the
    # cpu-sim tier (vs cm1's committed ~289x)
    assert agg["geomean_error_factor"] <= 3.0
    for row in base["targets"]:
        assert row["dispatch_count"] >= 1
        assert row["predicted_dispatch_overhead_us"] > 0
