"""Decode fast-path tests (``docs/serving.md``): fused multi-step
decode / chunked prefill / host-overlap window / slot compaction.

The load-bearing contract is EQUIVALENCE: every fast-path configuration
must produce the identical completed-token sequences (argmax over each
generated output) as the PR-9 per-step engine on the same trace — the
fast path buys dispatches, never different results.  On top of that,
the scheduler edge cases the fast path makes reachable: completion
mid-fused-scan (masked slot stays dead, blocks free at scan exit),
admission arriving during an in-flight window, and a K horizon that
overshoots every remaining output length.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlbb_tpu.comm.mesh import build_parallelism_mesh
from dlbb_tpu.models.configs import ModelConfig
from dlbb_tpu.serve.engine import ServingConfig, ServingEngine
from dlbb_tpu.serve.traffic import Request, TrafficTrace, generate_trace

TINY = dict(hidden_size=64, num_layers=2, num_heads=4,
            ffn_intermediate=128, dtype="float32", attention="full")
MODEL = ModelConfig(**TINY)
SERVE = dict(max_batch=8, block_size=8, max_seq=64, hbm_budget_gb=None)


def _trace(reqs):
    return TrafficTrace(kind="poisson", seed=0, params={},
                        requests=tuple(reqs))


@pytest.fixture(scope="module")
def baseline_engine(mesh2x4):
    """The per-step PR-9 engine — every equivalence test's oracle."""
    return ServingEngine(MODEL, ServingConfig(**SERVE), mesh2x4,
                         verbose=False, capture_tokens=True)


@pytest.fixture(scope="module")
def fast_engine(mesh2x4):
    """The full fast path: fused scans (K<=16), in-flight window 2,
    chunked prefill (8-token chunks)."""
    return ServingEngine(
        MODEL,
        ServingConfig(**SERVE, decode_horizon=16, inflight_window=2,
                      prefill_chunk=8),
        mesh2x4, verbose=False, capture_tokens=True,
    )


# ---------------------------------------------------------------------------
# config surface
# ---------------------------------------------------------------------------


def test_fastpath_config_validation():
    with pytest.raises(ValueError, match="decode_horizon"):
        ServingConfig(**SERVE, decode_horizon=0).validate(MODEL)
    with pytest.raises(ValueError, match="inflight_window"):
        ServingConfig(**SERVE, inflight_window=0).validate(MODEL)
    # a window without fused scans would be a silent no-op (k=1 units
    # never stay in flight)
    with pytest.raises(ValueError, match="inflight_window"):
        ServingConfig(**SERVE, inflight_window=2).validate(MODEL)
    with pytest.raises(ValueError, match="prefill_chunk"):
        ServingConfig(**SERVE, prefill_chunk=12).validate(MODEL)
    # the chunk must divide max_seq: chunk-rounding a near-max_seq
    # prompt must never overrun the slot's block ring
    with pytest.raises(ValueError, match="divide"):
        ServingConfig(max_batch=8, block_size=8, max_seq=40,
                      hbm_budget_gb=None,
                      prefill_chunk=16).validate(MODEL)
    with pytest.raises(ValueError, match="compact_threshold"):
        ServingConfig(**SERVE, compact_threshold=0.9).validate(MODEL)
    # compaction without fused scans would be a silent no-op
    with pytest.raises(ValueError, match="decode_horizon"):
        ServingConfig(**SERVE, compact_threshold=0.5).validate(MODEL)
    # compaction demands an unsharded slot dim
    with pytest.raises(ValueError, match="dp=1"):
        ServingConfig(**SERVE, decode_horizon=16,
                      compact_threshold=0.5).validate(MODEL, dp=2, tp=4)
    # the power-of-two fused bucket ladder
    assert ServingConfig(**SERVE).fused_horizons == ()
    assert ServingConfig(**SERVE,
                         decode_horizon=16).fused_horizons == (2, 4, 8, 16)
    # round trip keeps the fast-path knobs
    sv = ServingConfig(**SERVE, decode_horizon=4, prefill_chunk=8,
                       reject_infeasible=True)
    rt = ServingConfig.from_dict(sv.to_dict())
    assert rt.decode_horizon == 4 and rt.prefill_chunk == 8
    assert rt.reject_infeasible is True


# ---------------------------------------------------------------------------
# the equivalence contract (serve_fastpath_smoke)
# ---------------------------------------------------------------------------


@pytest.mark.serve_fastpath_smoke
def test_fused_engine_matches_per_step_tokens(baseline_engine,
                                              fast_engine):
    """The CI gate: the full fast path (fused scans + window + chunked
    prefill) serves the seeded mini-trace with completed-token
    sequences IDENTICAL to the per-step engine's, token for token."""
    trace = generate_trace("poisson", 24, seed=7, rate=500.0,
                           prompt_range=(4, 20), output_range=(2, 12))
    base = baseline_engine.run_trace(trace)
    fast = fast_engine.run_trace(trace)
    assert base["requests"]["completed"] == 24
    assert fast["requests"]["completed"] == 24
    assert base["completed_tokens"] == fast["completed_tokens"]
    # every request produced exactly its output_len tokens
    for r in trace:
        assert len(fast["completed_tokens"][str(r.rid)]) == r.output_len
    # the fast path actually engaged
    fp = fast["fast_path"]
    assert fp["enabled"] and fp["fused_scans"] > 0
    assert fp["prefill_chunks"] > 0
    assert fast["decode_units"] < fast["decode_steps"]
    # per-step engine: one dispatch per step, nothing fused
    assert base["fast_path"]["fused_scans"] == 0
    assert base["decode_units"] == base["decode_steps"]


@pytest.mark.serve_fastpath_smoke
def test_fastpath_artifact_set_schema_valid(tmp_path):
    """serve/bench.py with fast-path overrides: the artifact set stays
    schema-valid and records the fast-path counters."""
    from dlbb_tpu.serve.bench import run_serving

    config = {
        "experiment": {"name": "fastsmoke"},
        "model": dict(TINY),
        "parallelism": {"data_parallel": 2, "world_size": 4},
        "serving": {**SERVE, "decode_horizon": 8, "inflight_window": 2},
    }
    trace = generate_trace("poisson", 6, seed=9, rate=500.0,
                           prompt_range=(4, 16), output_range=(4, 10))
    report = run_serving(config, trace, str(tmp_path), verbose=False)
    assert report["requests"]["completed"] == 6
    result = json.loads((tmp_path / "serving_fastsmoke.json").read_text())
    assert result["schema"] == "dlbb_serving_report_v1"
    assert result["fast_path"]["decode_horizon"] == 8
    assert result["serving"]["decode_horizon"] == 8
    prom = (tmp_path / "metrics.prom").read_text()
    assert "dlbb_serve_decode_steps_total" in prom
    assert "dlbb_serve_fused_scan_steps_total" in prom
    assert "dlbb_serve_prefill_chunks_total" in prom
    assert "dlbb_serve_decode_batch_occupancy" in prom


# ---------------------------------------------------------------------------
# scheduler edge cases the fast path makes reachable
# ---------------------------------------------------------------------------


def test_completion_mid_fused_scan(baseline_engine, mesh2x4):
    """A slot whose request completes mid-scan is masked inactive for
    the remaining trips: it receives EXACTLY output_len tokens, its
    cache stops advancing, and its blocks free at scan exit."""
    engine = ServingEngine(
        MODEL, ServingConfig(**SERVE, decode_horizon=8), mesh2x4,
        verbose=False, capture_tokens=True,
    )
    # both resident from t=0; nothing pending/queued after admission, so
    # the horizon is max(remaining) and the scan overshoots rid 0
    trace = _trace([
        Request(rid=0, arrival_s=0.0, prompt_len=6, output_len=3,
                seed=11),
        Request(rid=1, arrival_s=0.0, prompt_len=6, output_len=12,
                seed=12),
    ])
    report = engine.run_trace(trace)
    base = baseline_engine.run_trace(trace)
    assert report["completed_tokens"] == base["completed_tokens"]
    assert len(report["completed_tokens"]["0"]) == 3
    assert len(report["completed_tokens"]["1"]) == 12
    # a fused scan ran past rid 0's completion
    assert report["fast_path"]["fused_steps"] >= 8
    # scan exit freed everything
    assert report["cache"]["blocks_reserved"] == 0
    assert report["requests"]["completed"] == 2


def test_admission_during_inflight_window(baseline_engine, mesh2x4):
    """An arrival landing while decode units are in flight is admitted
    at the next scan boundary (the engine drains the window before the
    prefill, keeping TTFT honest) and the tokens stay identical."""
    engine = ServingEngine(
        MODEL, ServingConfig(**SERVE, decode_horizon=4,
                             inflight_window=3),
        mesh2x4, verbose=False, capture_tokens=True,
    )
    trace = _trace([
        Request(rid=0, arrival_s=0.0, prompt_len=8, output_len=24,
                seed=21),
        Request(rid=1, arrival_s=0.0, prompt_len=8, output_len=24,
                seed=22),
        # lands mid-decode: the per-step run takes ~24 steps to drain
        Request(rid=2, arrival_s=0.05, prompt_len=8, output_len=8,
                seed=23),
    ])
    report = engine.run_trace(trace)
    base = baseline_engine.run_trace(trace)
    assert report["requests"]["completed"] == 3
    assert report["completed_tokens"] == base["completed_tokens"]
    assert report["fast_path"]["fused_scans"] > 0


def test_k_horizon_overshoots_every_remaining_length(mesh2x4):
    """decode_horizon far beyond every remaining output: the fused
    bucket clamps to the drain horizon, masked trips never generate
    tokens past output_len, and the ledger never overflows."""
    engine = ServingEngine(
        MODEL, ServingConfig(**SERVE, decode_horizon=64), mesh2x4,
        verbose=False, capture_tokens=True,
    )
    trace = _trace([
        Request(rid=0, arrival_s=0.0, prompt_len=4, output_len=3,
                seed=31),
        Request(rid=1, arrival_s=0.0, prompt_len=4, output_len=5,
                seed=32),
    ])
    report = engine.run_trace(trace)
    assert report["requests"]["completed"] == 2
    assert len(report["completed_tokens"]["0"]) == 3
    assert len(report["completed_tokens"]["1"]) == 5
    # the scan ladder never dispatched more trips than the longest
    # remaining output (prefill already produced token 1 of each)
    assert report["decode_steps"] == 4
    assert report["cache"]["blocks_reserved"] == 0


def test_compaction_engine_equivalence():
    """Slot compaction (dp=1): fused scans on the gather-compacted half
    batch produce the same tokens; compacted_scans counts the variant's
    engagements."""
    mesh = build_parallelism_mesh(tensor_parallel=4,
                                  devices=jax.devices()[:4])
    trace = generate_trace("poisson", 8, seed=13, rate=500.0,
                           prompt_range=(4, 16), output_range=(6, 20))
    base = ServingEngine(MODEL, ServingConfig(**SERVE), mesh,
                         verbose=False, capture_tokens=True)
    comp = ServingEngine(
        MODEL, ServingConfig(**SERVE, decode_horizon=16,
                             compact_threshold=0.5),
        mesh, verbose=False, capture_tokens=True,
    )
    rb = base.run_trace(trace)
    rc = comp.run_trace(trace)
    assert rb["completed_tokens"] == rc["completed_tokens"]
    assert rc["fast_path"]["compacted_scans"] > 0


# ---------------------------------------------------------------------------
# chunked prefill: program-level equivalence
# ---------------------------------------------------------------------------


def test_chunked_prefill_matches_monolithic(mesh2x4):
    """Chunk-by-chunk prefill writes the identical cache and returns
    the identical last-token output as the monolithic bucketed
    prefill (the offset-causal prefix-carry attention is the same
    math)."""
    from dlbb_tpu.models.transformer import init_params_sharded
    from dlbb_tpu.serve.engine import (
        build_prefill,
        build_prefill_chunk,
        create_prefix,
    )
    from dlbb_tpu.serve.kvcache import create_kv_cache

    params = init_params_sharded(MODEL, jax.random.key(0), mesh2x4)
    prompt, slot, chunk = 19, 1, 8
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal(
        (1, 24, MODEL.hidden_size)).astype(np.float32))

    sv = ServingConfig(**SERVE)
    cache_a = create_kv_cache(MODEL, sv.max_batch, sv.num_blocks,
                              sv.block_size, mesh=mesh2x4)
    bucket = sv.bucket_for(prompt)
    xa = jnp.zeros((1, bucket, MODEL.hidden_size),
                   jnp.float32).at[:, :prompt].set(x[:, :prompt])
    cache_a, ya = build_prefill(MODEL, mesh2x4)(
        cache_a, params, xa, np.int32(slot), np.int32(prompt))

    cache_b = create_kv_cache(MODEL, sv.max_batch, sv.num_blocks,
                              sv.block_size, mesh=mesh2x4)
    prefix = create_prefix(MODEL, mesh2x4)
    n_chunks = -(-prompt // chunk)
    xb = jnp.zeros((1, n_chunks * chunk, MODEL.hidden_size),
                   jnp.float32).at[:, :prompt].set(x[:, :prompt])
    for ci in range(n_chunks):
        jit = build_prefill_chunk(MODEL, mesh2x4, chunk, ci * chunk)
        cache_b, prefix, yb = jit(
            cache_b, prefix, params, xb[:, ci * chunk:(ci + 1) * chunk],
            np.int32(slot), np.int32(prompt))

    assert float(jnp.abs(ya - yb).max()) <= 1e-5
    ka = np.asarray(cache_a.k)[:, slot].reshape(
        MODEL.num_layers, -1, MODEL.kv_heads, MODEL.head_dim)[:, :prompt]
    kb = np.asarray(cache_b.k)[:, slot].reshape(
        MODEL.num_layers, -1, MODEL.kv_heads, MODEL.head_dim)[:, :prompt]
    assert float(np.abs(ka - kb).max()) <= 1e-5
    assert int(cache_b.lengths[slot]) == prompt
    assert int(cache_b.lengths[0]) == 0


# ---------------------------------------------------------------------------
# rejection detail + journal reasons (admission-tuning satellite)
# ---------------------------------------------------------------------------


def test_rejection_detail_and_shed_rate(baseline_engine):
    """Queue-full rejections carry the queue head's wait time (how
    backed up admission was when load was shed) and the report exposes
    the shed rate."""
    from dataclasses import replace

    engine = baseline_engine
    trace = generate_trace("poisson", 12, seed=3, rate=5000.0,
                           prompt_range=(4, 16), output_range=(4, 8))
    original = engine.serving
    engine.serving = replace(original, queue_capacity=1)
    try:
        report = engine.run_trace(trace)
    finally:
        engine.serving = original
    req = report["requests"]
    assert req["rejected"] > 0
    detail = req["rejected_detail"]
    assert len(detail) == req["rejected"]
    assert all(d["reason"] == "queue-full" for d in detail)
    assert all(d["queue_wait_s"] >= 0.0 for d in detail)
    assert req["shed_rate"] == pytest.approx(
        req["rejected"] / req["arrived"])
    assert req["rejected_rids"] == [d["rid"] for d in detail]


def test_infeasible_rejected_and_journaled_distinctly(mesh2x4, tmp_path):
    """reject_infeasible: an unservable request is shed at arrival with
    reason="infeasible" — a DISTINCT journal event from queue-full —
    while the feasible rest of the trace completes."""
    from dlbb_tpu.obs import spans
    from dlbb_tpu.resilience.journal import SweepJournal, read_journal

    engine = ServingEngine(
        MODEL, ServingConfig(**SERVE, reject_infeasible=True), mesh2x4,
        verbose=False,
    )
    trace = _trace([
        Request(rid=0, arrival_s=0.0, prompt_len=8, output_len=4,
                seed=1),
        # prompt + output outgrows max_seq: infeasible, not load
        Request(rid=1, arrival_s=0.0, prompt_len=40, output_len=30,
                seed=2),
    ])
    journal = SweepJournal(tmp_path, meta={"mode": "serve"},
                           sink=spans.journal_sink)
    engine.journal = journal
    try:
        report = engine.run_trace(trace)
    finally:
        engine.journal = None
        journal.close()
    req = report["requests"]
    assert req["completed"] == 1 and req["rejected"] == 1
    assert req["rejected_detail"][0]["reason"] == "infeasible"
    assert "max_seq" in req["rejected_detail"][0]["detail"]
    # infeasible is a config mismatch, never LOAD: not in the shed rate
    assert req["shed_rate"] == 0.0
    events, torn = read_journal(tmp_path)
    assert torn == 0
    kinds = {e["event"] for e in events}
    assert "request-infeasible" in kinds
    assert "request-rejected" not in kinds  # no load was shed
    # the reason-labelled counter split the two paths
    assert engine.registry.get("serve_rejections",
                               reason="infeasible") >= 1
    # journal -> timeline: the infeasible rejection still closes the
    # request's arrived->end span
    timeline, _n, torn2 = spans.journal_to_trace(
        tmp_path, tmp_path / "timeline.json")
    assert torn2 == 0
    rebuilt = spans.load_trace(timeline)
    infeasible_spans = [e for e in rebuilt["traceEvents"]
                        if e["ph"] == "X"
                        and e["cat"] == "config-infeasible"]
    assert len(infeasible_spans) == 1
    # the strict default still fails the whole trace up front
    with pytest.raises(ValueError, match="max_seq"):
        ServingEngine(MODEL, ServingConfig(**SERVE), mesh2x4,
                      verbose=False).run_trace(trace)


# ---------------------------------------------------------------------------
# span-trace fidelity (one span per scan) + journal timelines
# ---------------------------------------------------------------------------


def test_fused_scan_emits_one_span_with_steps_attr(mesh2x4, tmp_path):
    """A fused K-step scan is ONE ``serve-decode`` span carrying a
    ``steps`` attribute — not K fake per-step spans — and the journal
    timeline stays correct when several requests complete inside one
    host iteration."""
    from dlbb_tpu.obs import spans
    from dlbb_tpu.resilience.journal import SweepJournal, read_journal

    engine = ServingEngine(
        MODEL, ServingConfig(**SERVE, decode_horizon=8), mesh2x4,
        verbose=False,
    )
    trace = _trace([
        Request(rid=i, arrival_s=0.0, prompt_len=6, output_len=6,
                seed=40 + i)
        for i in range(4)
    ])
    span_path = tmp_path / "trace.json"
    journal = SweepJournal(tmp_path, meta={"mode": "serve"},
                           sink=spans.journal_sink)
    engine.journal = journal
    try:
        with spans.tracing(span_path):
            report = engine.run_trace(trace)
    finally:
        engine.journal = None
        journal.close()
    payload = spans.load_trace(span_path)
    assert spans.validate_trace_events(payload["traceEvents"]) == []
    decode_begins = [e for e in payload["traceEvents"]
                     if e["ph"] == "B" and e["name"] == "serve-decode"]
    # one span per dispatched unit, scans included
    assert len(decode_begins) == report["decode_units"]
    fused = [e for e in decode_begins if e["args"]["steps"] > 1]
    assert len(fused) == report["fast_path"]["fused_scans"]
    assert sum(e["args"]["steps"] for e in decode_begins) == \
        report["decode_steps"]
    # all four requests completed in ONE host iteration (same scan);
    # the journal still pairs every lifecycle span
    events, torn = read_journal(tmp_path)
    assert torn == 0
    completed = [e for e in events if e["event"] == "request-completed"]
    assert len(completed) == 4
    timeline, _n, torn2 = spans.journal_to_trace(
        tmp_path, tmp_path / "timeline.json")
    assert torn2 == 0
    rebuilt = spans.load_trace(timeline)
    req_spans = [e for e in rebuilt["traceEvents"] if e["ph"] == "X"]
    assert len(req_spans) == 4
    assert all(e["cat"] == "config-completed" for e in req_spans)


# ---------------------------------------------------------------------------
# report writers
# ---------------------------------------------------------------------------


def test_serving_report_shed_columns(tmp_path):
    from dlbb_tpu.stats.serving_report import write_serving_report
    from dlbb_tpu.utils.config import save_json

    fake = {
        "schema": "dlbb_serving_report_v1",
        "trace": {"kind": "poisson", "num_requests": 10},
        "requests": {"arrived": 10, "completed": 8, "rejected": 2,
                     "shed_rate": 0.2,
                     "rejected_detail": [
                         {"rid": 4, "reason": "queue-full",
                          "queue_depth": 3, "queue_wait_s": 0.05},
                         {"rid": 7, "reason": "queue-full",
                          "queue_depth": 3, "queue_wait_s": 0.15},
                     ]},
        "mesh": {"dp": 2, "tp": 4},
        "serving": {"max_batch": 8, "block_size": 16, "max_seq": 256},
        "fast_path": {"fused_steps": 64, "prefill_chunks": 5},
        "goodput_tokens_per_s": 100.0,
        "ttft": {"median": 0.01, "p99": 0.02, "p999": 0.03},
        "per_token_latency": {"median": 0.001, "p99": 0.002,
                              "p999": 0.003},
        "cache": {"peak_blocks_in_use": 12},
        "timeseries": {"queue_depth": [0, 3]},
        "decode_steps": 42,
        "wall_seconds": 1.5,
    }
    results = tmp_path / "results"
    save_json(fake, results / "serving_fastrun.json")
    rows = write_serving_report(results, tmp_path / "stats")
    assert len(rows) == 1
    row = rows[0]
    assert row["shed_rate"] == 0.2
    assert row["rej_queue_wait_ms"] == 100.0  # mean of 50 and 150
    assert row["fused_steps"] == 64
    md = (tmp_path / "stats" / "SERVING.md").read_text()
    assert "20%" in md and "100.0" in md


def test_fastpath_report_writer(tmp_path):
    from dlbb_tpu.stats.serving_report import write_fastpath_report
    from dlbb_tpu.utils.config import save_json

    bench = {
        "schema": "dlbb_bench_serve_v1",
        "baseline": "per_step",
        "settings": {
            "per_step": {
                "decode_horizon": 1,
                "output_tokens_per_s": {"median": 100.0, "min": 95.0,
                                        "max": 105.0},
                "per_token_p50_ms": 10.0, "decode_units": 200,
            },
            "fused_k16": {
                "decode_horizon": 16,
                "output_tokens_per_s": {"median": 250.0, "min": 240.0,
                                        "max": 260.0},
                "per_token_p50_ms": 4.0, "decode_units": 20,
            },
        },
    }
    path = tmp_path / "BENCH_serve.json"
    save_json(bench, path)
    rows = write_fastpath_report(path, tmp_path / "stats")
    assert len(rows) == 2
    by_name = {r["setting"]: r for r in rows}
    assert by_name["fused_k16"]["speedup_vs_baseline"] == 2.5
    assert by_name["per_step"]["speedup_vs_baseline"] == 1.0
    md = (tmp_path / "stats" / "FASTPATH.md").read_text()
    assert "2.50x" in md and "fused_k16" in md
    # missing artifact: no rows, nothing clobbered
    assert write_fastpath_report(tmp_path / "nope.json",
                                 tmp_path / "stats2") == []
