"""Real 2-process worker for the non-mock multi-host test.

Launched by ``tests/test_multihost.py::test_real_two_process_sweep`` as
``python tests/multihost_worker.py <process_id> <coordinator_port> <out_dir>``.
Each process initialises ``jax.distributed`` against a local TCP
coordinator (CPU backend, gloo cross-process collectives, 2 simulated
devices per process -> one global 4-device mesh) and drives a tiny real
``Sweep1D`` through the code paths the mocked tests can only fake:

- ``_gather_timings``: process_count == 2 -> the host-side allgather
  branch; the written artifact must carry one timing row per host.
- ``_resume_ok``: the collective resume decision (existence + artifact
  validation); exercised with the hosts *disagreeing* (only process 0
  holds a valid artifact at the probe path) -> must return False on BOTH
  hosts, and with both agreeing -> must return True on both.

NOT imported by pytest collection (no ``test_`` prefix in module-level
names); runs standalone only.
"""

import dataclasses
import json
import os
import sys
from pathlib import Path

# process-start env: must precede the jax import
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # the axon sitecustomize
# force-registers the TPU plugin; only the config update selects CPU
jax.config.update("jax_cpu_collectives_implementation", "gloo")


def main(process_id: int, port: int, out_dir: str) -> None:
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=2,
        process_id=process_id,
    )
    assert jax.process_count() == 2, jax.process_count()
    assert jax.local_device_count() == 2
    assert len(jax.devices()) == 4

    from dlbb_tpu.bench.runner import (
        Sweep1D,
        _resume_ok,
        run_sweep,
    )

    sweep = Sweep1D(
        operations=("allreduce",),
        data_sizes=(("1KB", 256),),
        rank_counts=(4,),
        warmup_iterations=1,
        measurement_iterations=3,
        timing_mode="per_iter",
        output_dir=out_dir,
    )
    written = run_sweep(sweep, verbose=process_id == 0)
    assert len(written) == 1, written
    artifact = json.loads(Path(written[0]).read_text())
    # the multi-host gather branch: one timing row per host
    assert len(artifact["timings"]) == 2, len(artifact["timings"])
    assert len(artifact["timings"][0]) == 3
    assert artifact["num_ranks"] == 4

    # resume pass: shared disk, both hosts hold the artifact -> both skip
    resumed = run_sweep(
        dataclasses.replace(sweep, resume=True), verbose=False
    )
    assert resumed == written, (resumed, written)

    # disagreeing hosts: only process 0 holds a VALID artifact at the
    # probe path (a copy of the real one, so its local check passes) ->
    # the collective decision must be False on BOTH (a per-host decision
    # here is exactly the pod-hang bug the docstring warns about)
    mine = Path(out_dir) / f"probe_proc{process_id}.json"
    if process_id == 0:
        mine.write_text(Path(written[0]).read_text())
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices("probe_written")
    disagree, _ = _resume_ok(mine)
    assert disagree is False, disagree

    # agreeing hosts: the shared VALID artifact exists everywhere -> True
    agree, _ = _resume_ok(Path(written[0]))
    assert agree is True, agree

    # a torn artifact (truncated JSON) must not be trusted even though it
    # EXISTS on both hosts — the validation half of the collective check
    torn = Path(out_dir) / f"torn_shared_proc{process_id}.json"
    torn.write_text(Path(written[0]).read_text()[:40])
    multihost_utils.sync_global_devices("torn_written")
    trusted, why = _resume_ok(torn)
    assert trusted is False, (trusted, why)

    # e2e cross-host CV branch (bench/e2e.py): a tiny forward benchmark
    # over the global 4-device dp mesh.  The fixed-seed data layer is
    # multi-process-correct by construction: every process materialises
    # the identical batch, so the global device_put's same-value check
    # passes — exactly the property this exercises.
    from dlbb_tpu.bench.e2e import run_e2e

    e2e_cfg = {
        "experiment": {"name": "mh2_e2e"},
        "model": {"hidden_size": 64, "num_layers": 1, "num_heads": 2,
                  "ffn_intermediate": 128, "attention": "dense",
                  "dtype": "float32"},
        "parallelism": {"world_size": 1, "data_parallel": 4},
        "input": {"batch_size": 4, "sequence_length": 32, "seed": 42},
        "execution": {"warmup_iterations": 1, "benchmark_iterations": 3},
    }
    r = run_e2e(e2e_cfg, output_dir=out_dir if process_id == 0 else None,
                verbose=False)
    # the host-side allgather of per-host forward means: 2 entries, and
    # the CV is a real cross-host number (>= 0), not the single-process 0
    assert len(r["per_host_means_s"]) == 2, r["per_host_means_s"]
    assert r["cross_host_cv"] >= 0.0

    print(f"WORKER-OK proc={process_id}", flush=True)


if __name__ == "__main__":
    main(int(sys.argv[1]), int(sys.argv[2]), sys.argv[3])
