"""Plan autotuner tests (``docs/autotune.md``, ``cli plan --auto``).

The load-bearing contracts: (1) the full plan space is accounted for —
every enumerated point is either ranked or journaled with a prune
reason from the fixed vocabulary, never silently dropped; (2) ranking
is deterministic with the documented tie-break (predicted cost, then
plan complexity, then lexical key); (3) a missing cm2 fit fails the
whole search CLOSED (ranking with unfitted analytic seeds would
launder cm1 guesses as "model-picked"); (4) the pinned
calibration-grid agreement regression — cm2's top-2 contains the
measured winner for >= 70% of the committed baseline families; and
(5) the measured smoke: predict-prune-measure end-to-end through the
real serving engine with the agreement table, manifest, and metrics
surfaces all consistent.
"""

from __future__ import annotations

import json
from pathlib import Path

import pytest

from dlbb_tpu.analysis.costmodel import CostTier, load_fitted_tier
from dlbb_tpu.models.configs import ModelConfig
from dlbb_tpu.plan.autotune import (
    CAL_FAMILIES,
    DEFAULT_PLAN_INPUT,
    DEFAULT_PLAN_MODEL,
    DEFAULT_PLAN_SERVING,
    PRUNE_FIT,
    PRUNE_HBM,
    PRUNE_REASONS,
    PRUNE_VALIDATION,
    PlanPoint,
    calibration_agreement,
    enumerate_serving_space,
    enumerate_train_space,
    heuristic_point,
    predict_point_us,
    prune_point,
    rank_points,
    run_plan_search,
)
from dlbb_tpu.resilience.journal import read_journal
from dlbb_tpu.stats.parallelism_report import write_autotune_report
from dlbb_tpu.stats.serving_report import publish_capacity_curve

REPO = Path(__file__).resolve().parents[1]
FIT_DIR = REPO / "stats" / "analysis" / "costmodel_fit"
CAL_BASELINE = (REPO / "stats" / "analysis" / "calibration"
                / "calibration_baseline_cm2.json")

MODEL = ModelConfig.from_dict(DEFAULT_PLAN_MODEL)


@pytest.fixture(scope="module")
def tier():
    return load_fitted_tier("cpu-sim", FIT_DIR)


# ---------------------------------------------------------------------------
# enumeration
# ---------------------------------------------------------------------------


def test_serving_space_is_the_full_grid():
    """(dp,tp) factorizations x K x W x chunk x compact — 4*5*2*2*2 for
    an 8-device mesh, every key unique (the journal identifier)."""
    pts = enumerate_serving_space(MODEL, 8, DEFAULT_PLAN_SERVING)
    assert len(pts) == 4 * 5 * 2 * 2 * 2
    keys = [p.key() for p in pts]
    assert len(set(keys)) == len(keys)
    assert all(p.dp * p.tp == 8 for p in pts)


def test_train_space_covers_variant_axis():
    """Every ordered mesh factorization appears, and sp > 1 points
    enumerate BOTH attention variants (the per-op variant axis)."""
    pts = enumerate_train_space(MODEL, 8)
    assert all(p.dp * p.sp * p.pp * p.tp == 8 for p in pts)
    sp2 = {p.attention for p in pts if p.sp > 1}
    assert sp2 == {"ring", "ulysses"}
    assert {p.attention for p in pts if p.sp == 1} == {None}


# ---------------------------------------------------------------------------
# pruning: reasons, never silent
# ---------------------------------------------------------------------------


def test_every_prune_carries_a_vocabulary_reason(tier):
    """Full-grid accounting: each serving point either survives or is
    rejected with (reason, detail), reason from the fixed vocabulary."""
    pts = enumerate_serving_space(MODEL, 8, DEFAULT_PLAN_SERVING)
    kept = pruned = 0
    for p in pts:
        res = prune_point(p, MODEL, tier, 8,
                          serving=DEFAULT_PLAN_SERVING)
        if res is None:
            kept += 1
        else:
            reason, detail = res
            assert reason in PRUNE_REASONS
            assert detail  # the contract's message, not a bare code
            pruned += 1
    assert kept + pruned == len(pts)
    assert kept > 0 and pruned > 0


def test_validation_reject_quotes_the_contract(tier):
    """A plan wider than the mesh and a tp that breaks the engine's own
    ServingConfig.validate both reject with actionable detail."""
    wide = PlanPoint(target="serving", dp=4, tp=4)
    reason, detail = prune_point(wide, MODEL, tier, 8,
                                 serving=DEFAULT_PLAN_SERVING)
    assert reason == PRUNE_VALIDATION
    assert "16" in detail and "8" in detail
    # tp=8 > kv_heads=4: the engine contract's rejection, quoted
    tp8 = PlanPoint(target="serving", dp=1, tp=8)
    reason, detail = prune_point(tp8, MODEL, tier, 8,
                                 serving=DEFAULT_PLAN_SERVING)
    assert reason == PRUNE_VALIDATION and detail


def test_infeasible_hbm_prunes_with_headroom_detail(tier):
    """A tier with a 1-byte HBM capacity rejects every plan with the
    infeasible-hbm reason and the peak-bytes arithmetic in the detail;
    hbm_bytes=0 (unknown) never prunes."""
    tiny = CostTier(name="cpu-sim-tiny", alpha_us=tier.alpha_us,
                    beta_bytes_per_us=tier.beta_bytes_per_us,
                    peak_flops_per_us=tier.peak_flops_per_us,
                    gamma_dispatch_us=tier.gamma_dispatch_us,
                    hbm_bytes=1.0, version=tier.version, fit=tier.fit)
    ok = PlanPoint(target="serving", dp=2, tp=4)
    reason, detail = prune_point(ok, MODEL, tiny, 8,
                                 serving=DEFAULT_PLAN_SERVING)
    assert reason == PRUNE_HBM
    assert "peak" in detail and "headroom" in detail
    unknown = CostTier(name="cpu-sim-nohbm", alpha_us=1,
                       beta_bytes_per_us=1, peak_flops_per_us=1,
                       hbm_bytes=0.0)
    assert prune_point(ok, MODEL, unknown, 8,
                       serving=DEFAULT_PLAN_SERVING) is None


def test_train_prune_divisibility(tier):
    """Train-side validate_* family: a batch that does not divide dp*sp
    rejects with the divisibility message."""
    p = PlanPoint(target="train", dp=8)
    res = prune_point(p, MODEL, tier, 8,
                      input_cfg={**DEFAULT_PLAN_INPUT, "batch_size": 6})
    assert res is not None and res[0] == PRUNE_VALIDATION
    assert "divisible" in res[1]


# ---------------------------------------------------------------------------
# ranking: deterministic tie-break
# ---------------------------------------------------------------------------


def test_tie_break_prefers_simpler_then_lexical():
    """Equal predicted cost: the plan with fewer engaged knobs wins;
    equal complexity falls through to the lexical key."""
    plain = PlanPoint(target="serving", dp=8, tp=1)
    knobby = PlanPoint(target="serving", dp=8, tp=1, decode_horizon=16,
                       inflight_window=2)
    cost = {"cost_us": 100.0}
    ranked = rank_points([(knobby, cost), (plain, cost)])
    assert ranked[0][0] is plain  # complexity 0 beats complexity 2
    a = PlanPoint(target="serving", dp=2, tp=4)
    b = PlanPoint(target="serving", dp=4, tp=2)
    ranked = rank_points([(b, cost), (a, cost)])
    assert [p.key() for p, _ in ranked] == [a.key(), b.key()]


def test_rank_orders_by_predicted_cost():
    a = PlanPoint(target="serving", dp=8, tp=1, decode_horizon=16)
    b = PlanPoint(target="serving", dp=8, tp=1)
    ranked = rank_points([(b, {"cost_us": 50.0}), (a, {"cost_us": 5.0})])
    assert ranked[0][0] is a


def test_fused_horizon_shrinks_predicted_dispatch(tier):
    """The predictor prices the knobs' purpose: K=16,W=2 amortizes the
    fitted gamma term below the K=1 plan on the same mesh."""
    slow = predict_point_us(PlanPoint(target="serving", dp=2, tp=4),
                            MODEL, tier, serving=DEFAULT_PLAN_SERVING)
    fast = predict_point_us(
        PlanPoint(target="serving", dp=2, tp=4, decode_horizon=16,
                  inflight_window=2),
        MODEL, tier, serving=DEFAULT_PLAN_SERVING)
    assert fast["dispatch_us"] < slow["dispatch_us"]
    assert fast["cost_us"] < slow["cost_us"]


# ---------------------------------------------------------------------------
# the pinned agreement regression (satellite gate: >= 0.70)
# ---------------------------------------------------------------------------


@pytest.mark.autotune_smoke
def test_calibration_grid_agreement_regression():
    """cm2's top-2 must contain the measured winner for >= 70% of the
    pinned validation-grid families over the COMMITTED calibration
    baseline — the seeded regression that keeps the ranking model
    honest across fit refreshes."""
    cal = calibration_agreement(CAL_BASELINE)
    assert cal.get("error") is None
    assert cal["total"] == len(CAL_FAMILIES)  # no missing-target rows
    assert all(f["status"] == "ok" for f in cal["families"])
    assert cal["ratio"] >= 0.70


def test_agreement_reports_missing_targets_visibly(tmp_path):
    """A family whose members are absent from the baseline is reported
    with status missing-target and excluded from the denominator —
    visibly, never silently."""
    baseline = tmp_path / "cal.json"
    baseline.write_text(json.dumps({"targets": [
        {"target": "a", "predicted_us": 1.0, "measured_us": 1.0},
        {"target": "b", "predicted_us": 2.0, "measured_us": 0.5},
    ]}))
    cal = calibration_agreement(baseline, families={
        "present": [("a", 1), ("b", 1)],
        "absent": [("a", 1), ("ghost", 1)],
    })
    assert cal["total"] == 1 and cal["ratio"] == 1.0
    statuses = {f["family"]: f["status"] for f in cal["families"]}
    assert statuses == {"present": "ok", "absent": "missing-target"}
    absent = next(f for f in cal["families"] if f["family"] == "absent")
    assert absent["missing"] == ["ghost"]


# ---------------------------------------------------------------------------
# fail-closed: cm2 fit missing
# ---------------------------------------------------------------------------


def test_missing_fit_fails_closed_and_journals_every_point(tmp_path):
    """No fitted cm2 tier -> NO ranking happens at all: every point is
    journaled pruned cm2-fit-missing, the manifest accounts for the
    full grid, and the report carries the error."""
    out = tmp_path / "search"
    res = run_plan_search(
        target="serving", n_devices=8, measure=False, verbose=False,
        output_dir=out, fit_dir=tmp_path / "no_fit_here",
        cal_baseline=CAL_BASELINE,
    )
    assert res["error"].startswith(PRUNE_FIT)
    assert res["ranked"] == [] and res["measured"] == []
    manifest = json.loads((out / "sweep_manifest.json").read_text())
    assert manifest["pruned"][PRUNE_FIT] == manifest["searched"] > 0
    events, bad = read_journal(out)
    assert bad == 0
    pruned = [e for e in events if e.get("event") == "plan-pruned"]
    assert len(pruned) == manifest["searched"]
    assert all(e["reason"] == PRUNE_FIT for e in pruned)


# ---------------------------------------------------------------------------
# static search accounting (no measurement)
# ---------------------------------------------------------------------------


@pytest.mark.autotune_smoke
def test_static_search_accounts_for_every_point(tmp_path):
    """searched == pruned + ranked, the journal carries one event per
    pruned point with a vocabulary reason, the manifest and metrics.prom
    agree with the report, and a re-run ranks identically."""
    out = tmp_path / "auto"
    res = run_plan_search(
        target="serving", n_devices=8, measure=False, verbose=False,
        output_dir=out, fit_dir=FIT_DIR, cal_baseline=CAL_BASELINE,
    )
    n_pruned = sum(res["pruned"].values())
    assert res["searched"] == n_pruned + len(res["ranked"])
    assert set(res["pruned"]) == set(PRUNE_REASONS)
    assert all(r["reason"] in PRUNE_REASONS for r in res["pruned_points"])
    assert len(res["pruned_points"]) == n_pruned

    events, bad = read_journal(out)
    assert bad == 0
    assert len([e for e in events if e.get("event") == "plan-pruned"]) \
        == n_pruned
    assert len([e for e in events if e.get("event") == "plan-ranked"]) \
        == len(res["ranked"])

    manifest = json.loads((out / "sweep_manifest.json").read_text())
    assert manifest["searched"] == res["searched"]
    assert manifest["pruned"] == res["pruned"]

    prom = (out / "metrics.prom").read_text()
    assert ('dlbb_plan_search_points_total{outcome="searched"} '
            f'{res["searched"]}') in prom
    assert 'dlbb_plan_agreement_ratio{scope="calibration-grid"}' in prom

    again = run_plan_search(
        target="serving", n_devices=8, measure=False, verbose=False,
        output_dir=tmp_path / "auto2", fit_dir=FIT_DIR,
        cal_baseline=CAL_BASELINE,
    )
    assert [r["plan"] for r in again["ranked"]] \
        == [r["plan"] for r in res["ranked"]]


@pytest.mark.autotune_smoke
def test_train_static_search_ranks_and_accounts(tmp_path):
    """The train target's grid goes through the same accounting; the
    default-heuristic plan (plain DDP) is a known key."""
    res = run_plan_search(
        target="train", n_devices=8, measure=False, verbose=False,
        output_dir=tmp_path / "train", fit_dir=FIT_DIR,
        cal_baseline=CAL_BASELINE,
    )
    assert res["searched"] == sum(res["pruned"].values()) \
        + len(res["ranked"])
    assert len(res["ranked"]) > 0
    assert heuristic_point("train", 8, MODEL).key() \
        == "train[dp8,tp1,sp1,pp1]"


# ---------------------------------------------------------------------------
# measured smoke: predict-prune-measure end-to-end
# ---------------------------------------------------------------------------


@pytest.mark.autotune_smoke
def test_measured_search_smoke(tmp_path, devices):
    """Top-1 + the default heuristic measured through the real serving
    engine on one shared seeded trace: agreement rows carry both rank
    columns, the manifest's measured count matches, and the bench
    artifact keeps chip rows pending_tunnel."""
    out = tmp_path / "auto"
    bench = tmp_path / "BENCH_autotune.json"
    res = run_plan_search(
        target="serving", n_devices=8, top_k=1, mesh_champions=False,
        num_requests=4, seed=11, rate=500.0,
        trace_params={"prompt_range": (8, 16), "output_range": (16, 24)},
        output_dir=out, fit_dir=FIT_DIR, cal_baseline=CAL_BASELINE,
        devices=devices, verbose=False, bench_out=bench,
    )
    roles = {r["role"] for r in res["measured"]}
    assert roles == {"top-k", "default-heuristic"}
    assert res["winner"] in {r["plan"] for r in res["measured"]}
    assert res["speedup_vs_default"] is not None
    for row in res["agreement"]["rows"]:
        assert row["predicted_rank"] >= 1
        assert row["measured_rank"] >= 1
        assert row["goodput_tokens_per_s"] > 0

    manifest = json.loads((out / "sweep_manifest.json").read_text())
    assert manifest["measured"] == len(res["measured"])
    events, _ = read_journal(out)
    assert len([e for e in events if e.get("event") == "plan-measured"]) \
        == len(res["measured"])
    prom = (out / "metrics.prom").read_text()
    assert 'dlbb_plan_agreement_ratio{scope="measured-topk"}' in prom

    payload = json.loads(bench.read_text())
    assert payload["schema"] == "dlbb_bench_autotune_v1"
    assert payload["chip"]["status"] == "pending_tunnel"
    assert payload["measured"] == res["measured"]


# ---------------------------------------------------------------------------
# report consolidation + capacity publishing
# ---------------------------------------------------------------------------


def _bench_payload():
    return {
        "schema": "dlbb_bench_autotune_v1", "target": "serving",
        "devices": 8, "searched": 10,
        "pruned": {"validation-reject": 4, "infeasible-hbm": 0,
                   "cm2-fit-missing": 0},
        "tier": {"name": "cpu-sim", "fit": {"fit_version": 2}},
        "ranked": [{"plan": "serve[dp8,tp1,K16,W2]"}],
        "default_plan": "serve[dp2,tp4,K1,W1]",
        "speedup_vs_default": 1.4,
        "agreement": {
            "rows": [
                {"plan": "serve[dp4,tp2,K16,W2]", "role": "top-k",
                 "predicted_us": 300.0, "predicted_rank": 1,
                 "measured_rank": 1, "goodput_tokens_per_s": 1600.0,
                 "ttft_p50_s": 0.02},
                {"plan": "serve[dp2,tp4,K1,W1]",
                 "role": "default-heuristic", "predicted_us": 400.0,
                 "predicted_rank": 2, "measured_rank": 2,
                 "goodput_tokens_per_s": 900.0, "ttft_p50_s": 0.03},
            ],
            "measured_winner": "serve[dp4,tp2,K16,W2]",
            "predicted_winner": "serve[dp4,tp2,K16,W2]",
            "top1_match": True, "top2_contains": True,
        },
        "calibration_agreement": {
            "ratio": 1.0, "agree": 1, "total": 1, "baseline": "b.json",
            "families": [{
                "family": "decode_path", "status": "ok",
                "predicted_order": ["a::x", "a::y"],
                "measured_winner": "a::x",
                "top2_contains_winner": True,
            }],
        },
    }


def test_write_autotune_report(tmp_path):
    bench = tmp_path / "BENCH_autotune.json"
    bench.write_text(json.dumps(_bench_payload()))
    rows = write_autotune_report(bench, tmp_path / "stats")
    assert len(rows) == 2
    md = (tmp_path / "stats" / "AUTOTUNE.md").read_text()
    assert "## Search accounting" in md
    assert "## Measured agreement" in md
    assert "## Calibration-grid agreement" in md
    assert "serve[dp4,tp2,K16,W2]" in md
    assert "**1.40x**" in md


def test_autotune_report_never_clobbers_on_empty(tmp_path):
    """No measured rows -> no rewrite: the committed AUTOTUNE.md from
    the last real run survives a dry regeneration."""
    stats = tmp_path / "stats"
    stats.mkdir()
    (stats / "AUTOTUNE.md").write_text("committed")
    payload = _bench_payload()
    payload["agreement"]["rows"] = []
    bench = tmp_path / "BENCH_autotune.json"
    bench.write_text(json.dumps(payload))
    assert write_autotune_report(bench, stats) == []
    assert (stats / "AUTOTUNE.md").read_text() == "committed"
    assert write_autotune_report(tmp_path / "nope.json", stats) == []


def _capacity_report():
    curve = [
        {"users": 4, "demand_tokens_per_s": 160.0,
         "replicas_predicted": 1, "replicas_measured": 1},
        {"users": 64, "demand_tokens_per_s": 2560.0,
         "replicas_predicted": 2, "replicas_measured": None},
    ]
    return {
        "schema": "dlbb_capacity_v1", "devices": 8, "slo_s": 30.0,
        "user_rate_req_per_s": 0.2, "mean_output_tokens": 200.0,
        "trace": {"kind": "poisson", "num_requests": 24, "seed": 42},
        "plans": [
            {"plan": "serve[dp4,tp2,K16,W2]", "slo_attainable": True,
             "predicted_goodput_tokens_per_s": 3000.0,
             "measured_goodput_tokens_per_s": 1600.0,
             "predicted_ttft_s": 0.004, "measured_ttft_p50_s": 0.02,
             "completed": 24, "total": 24, "curve": curve},
        ],
    }


def test_publish_capacity_curve_idempotent(tmp_path):
    """Publishing writes capacity.json + the SERVING.md section; a
    second publish replaces the section instead of stacking two."""
    out = tmp_path / "serving"
    md = publish_capacity_curve(_capacity_report(), out)
    text = md.read_text()
    assert text.count("## Fleet capacity curve") == 1
    assert "serve[dp4,tp2,K16,W2]" in text
    assert "2 / —" in text  # blown-TTFT cell renders as a dash
    assert (out / "capacity.json").exists()
    publish_capacity_curve(_capacity_report(), out)
    assert md.read_text().count("## Fleet capacity curve") == 1
