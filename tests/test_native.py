"""Native C++ stats core: build, bindings, and numpy-equivalence
(the framework's runtime-side native component — SURVEY §2.4 notes the
reference keeps its native layer in external comm libs)."""

import numpy as np
import pytest

from dlbb_tpu.native import (
    load_imbalance_native,
    native_available,
    row_means_native,
    summarize_native,
)

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native stats core unavailable (no g++?)"
)

RNG = np.random.default_rng(42)


def test_summarize_matches_numpy():
    for n in (1, 2, 7, 100, 10_001):
        xs = RNG.lognormal(size=n)
        got = summarize_native(xs)
        assert got is not None
        assert got["count"] == n
        np.testing.assert_allclose(got["mean"], xs.mean(), rtol=1e-12)
        np.testing.assert_allclose(got["std"], xs.std(), rtol=1e-9, atol=1e-15)
        np.testing.assert_allclose(got["min"], xs.min(), rtol=0)
        np.testing.assert_allclose(got["max"], xs.max(), rtol=0)
        np.testing.assert_allclose(got["median"], np.median(xs), rtol=1e-12)
        np.testing.assert_allclose(got["p95"], np.percentile(xs, 95),
                                   rtol=1e-12)
        np.testing.assert_allclose(got["p99"], np.percentile(xs, 99),
                                   rtol=1e-12)
        np.testing.assert_allclose(got["p999"], np.percentile(xs, 99.9),
                                   rtol=1e-12)


def test_summarize_used_by_metrics():
    """utils.metrics.summarize routes through the native core and keeps
    its schema (p999 included — the serving-path tail metric)."""
    from dlbb_tpu.utils.metrics import summarize

    xs = RNG.normal(size=256).tolist()
    out = summarize(xs)
    assert set(out) == {"mean", "std", "min", "max", "median", "p95",
                        "p99", "p999", "count"}
    np.testing.assert_allclose(out["p95"], np.percentile(xs, 95), rtol=1e-12)
    np.testing.assert_allclose(out["p999"], np.percentile(xs, 99.9),
                               rtol=1e-12)


def test_summarize_empty_series_contract():
    """An empty series returns explicit NaN-valued keys with count 0 —
    never a bare {} a downstream stats pass would KeyError on — through
    BOTH dispatch paths (native returns None on empty; the metrics
    layer owns the contract)."""
    from dlbb_tpu.utils.metrics import SUMMARY_KEYS, summarize

    assert summarize_native([]) is None
    out = summarize([])
    assert set(out) == set(SUMMARY_KEYS)
    assert out["count"] == 0
    assert all(np.isnan(v) for k, v in out.items() if k != "count")


def test_load_imbalance_matches_reference_formula():
    means = RNG.uniform(1.0, 2.0, size=16)
    expected = (means.max() - means.mean()) / means.mean() * 100.0
    np.testing.assert_allclose(load_imbalance_native(means), expected,
                               rtol=1e-12)
    assert load_imbalance_native([]) == 0.0


def test_row_means_matches_numpy():
    mat = RNG.normal(size=(8, 100))
    got = row_means_native(mat)
    np.testing.assert_allclose(got, mat.mean(axis=1), rtol=1e-12)


def test_stats1d_pipeline_uses_native():
    from dlbb_tpu.stats.stats1d import calculate_statistics

    timings = RNG.lognormal(mean=-8, size=(4, 50))
    stats = calculate_statistics(timings.tolist())
    flat = timings.ravel()
    np.testing.assert_allclose(stats["mean_time_us"], flat.mean() * 1e6,
                               rtol=1e-9)
    means = timings.mean(axis=1)
    expected_li = (means.max() - means.mean()) / means.mean() * 100.0
    np.testing.assert_allclose(stats["load_imbalance_percent"], expected_li,
                               rtol=1e-9)


def test_native_disabled_falls_back(monkeypatch):
    """DLBB_NATIVE=0 must cleanly disable the native path (fresh loader
    state) while summarize keeps working via numpy."""
    import dlbb_tpu.native as native

    monkeypatch.setenv("DLBB_NATIVE", "0")
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_tried", False)
    assert native.summarize_native([1.0, 2.0]) is None
    from dlbb_tpu.utils.metrics import summarize

    out = summarize([1.0, 2.0, 3.0])
    assert out["mean"] == 2.0
    # restore loader state for later tests
    monkeypatch.setattr(native, "_tried", False)


def test_stats3d_native_matches_numpy(monkeypatch):
    """calculate_statistics_3d goes through the shared summarize dispatch;
    the native kernel (when buildable) and the forced numpy fallback must
    produce identical ms-scale numbers, and the key mapping must be
    field-correct either way."""
    import numpy as np

    from dlbb_tpu import native
    from dlbb_tpu.stats.stats3d import calculate_statistics_3d

    rng = np.random.default_rng(0)
    timings = rng.uniform(1e-4, 5e-3, size=(4, 25)).tolist()
    flat = np.asarray(timings).ravel()
    want = {
        "mean_time_ms": float(flat.mean() * 1e3),
        "median_time_ms": float(np.median(flat) * 1e3),
        "min_time_ms": float(flat.min() * 1e3),
        "max_time_ms": float(flat.max() * 1e3),
    }

    got_default = calculate_statistics_3d(timings)  # native if buildable
    # force the numpy fallback regardless of toolchain
    monkeypatch.setattr(native, "summarize_native", lambda _: None)
    got_numpy = calculate_statistics_3d(timings)
    for k, v in want.items():
        np.testing.assert_allclose(got_default[k], v, rtol=1e-12, atol=0)
        np.testing.assert_allclose(got_numpy[k], v, rtol=1e-12, atol=0)
