"""Collective correctness tests on the simulated 8-device mesh.

Mirrors the reference's 12-case MPI smoke suite ``test/test_open.py``
(sendrecv :35, bcast :65, scatter :86, gather :105, allgather :125,
reduce :142, allreduce :159, buffer Bcast :175, buffer Allreduce :195,
barrier :214, ring isend/irecv :227, MAX/MIN/PROD :248) as asserted pytest
cases instead of mpirun-launched scripts.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from dlbb_tpu.comm import get_op, make_payload
from dlbb_tpu.comm.ops import (
    build_allreduce,
    build_allreduce_hierarchical,
    build_barrier,
)

AXES = ("ranks",)
N = 64


def _np_input(op_name, mesh, dtype=jnp.float32):
    op = get_op(op_name)
    x = make_payload(op, mesh, AXES, N, dtype=dtype)
    return op, x, np.asarray(x).astype(np.float64)


def test_allreduce_sum(mesh8):
    op, x, host = _np_input("allreduce", mesh8)
    fn = op.build(mesh8, AXES)
    out = np.asarray(fn(x))
    expected = host.sum(axis=0)
    for r in range(8):
        np.testing.assert_allclose(out[r], expected, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("red,npfn", [("max", np.max), ("min", np.min), ("prod", np.prod)])
def test_allreduce_max_min_prod(mesh8, red, npfn):
    """MAX/MIN/PROD reduction ops (reference ``test/test_open.py:248``)."""
    op, x, host = _np_input("allreduce", mesh8)
    fn = build_allreduce(mesh8, AXES, reduce_op=red)
    out = np.asarray(fn(x))
    expected = npfn(host, axis=0)
    rtol = 1e-3 if red == "prod" else 1e-5
    for r in range(8):
        np.testing.assert_allclose(out[r], expected, rtol=rtol, atol=1e-5)


def test_allgather(mesh8):
    op, x, host = _np_input("allgather", mesh8)
    fn = op.build(mesh8, AXES)
    out = np.asarray(fn(x))  # [8, 8, N] — every rank holds all 8 buffers
    for r in range(8):
        np.testing.assert_allclose(out[r], host, rtol=1e-5, atol=1e-5)


def test_allgather_3d_payload(mesh8):
    """Shaped (B,S,H) payloads keep their structure through allgather
    (3D sweep path, reference ``collectives/3d/openmpi.py:21-23``)."""
    op = get_op("allgather")
    x = make_payload(op, mesh8, AXES, 0, dtype=jnp.float32, shape=(2, 4, 8))
    out = np.asarray(op.build(mesh8, AXES)(x))
    assert out.shape == (8, 8, 2, 4, 8)
    np.testing.assert_allclose(out[3], np.asarray(x), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("root", [0, 3])
def test_broadcast(mesh8, root):
    op, x, host = _np_input("broadcast", mesh8)
    fn = op.build(mesh8, AXES, root)
    out = np.asarray(fn(x))
    for r in range(8):
        np.testing.assert_allclose(out[r], host[root], rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("root", [0, 5])
def test_gather(mesh8, root):
    op, x, host = _np_input("gather", mesh8)
    fn = op.build(mesh8, AXES, root)
    out = np.asarray(fn(x))  # [8, 8, N]
    np.testing.assert_allclose(out[root], host, rtol=1e-5, atol=1e-5)
    for r in range(8):
        if r != root:
            assert np.all(out[r] == 0.0)


@pytest.mark.parametrize("root", [0, 2])
def test_scatter(mesh8, root):
    op = get_op("scatter")
    x = make_payload(op, mesh8, AXES, N)  # [8, 8, N]
    host = np.asarray(x)
    fn = op.build(mesh8, AXES, root)
    out = np.asarray(fn(x))  # [8, N]
    # rank i must receive row i of the ROOT's sendbuf
    for r in range(8):
        np.testing.assert_allclose(out[r], host[root, r], rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("root", [0, 7])
def test_reduce(mesh8, root):
    op, x, host = _np_input("reduce", mesh8)
    fn = op.build(mesh8, AXES, root)
    out = np.asarray(fn(x))
    np.testing.assert_allclose(out[root], host.sum(axis=0), rtol=1e-4, atol=1e-4)
    for r in range(8):
        if r != root:
            assert np.all(out[r] == 0.0)


def test_alltoall(mesh8):
    op = get_op("alltoall")
    x = make_payload(op, mesh8, AXES, N)  # [8, 8, N]
    host = np.asarray(x)
    fn = op.build(mesh8, AXES)
    out = np.asarray(fn(x))
    # out[i][j] == in[j][i]  (rank i receives chunk i from every rank j)
    for i in range(8):
        for j in range(8):
            np.testing.assert_allclose(out[i, j], host[j, i], rtol=1e-5, atol=1e-5)


def test_sendrecv_ring(mesh8):
    """Ring shift: rank i's buffer lands on rank (i+1) % P
    (reference ``test/test_open.py:227`` ring isend/irecv)."""
    op, x, host = _np_input("sendrecv", mesh8)
    fn = op.build(mesh8, AXES)
    out = np.asarray(fn(x))
    for r in range(8):
        np.testing.assert_allclose(out[(r + 1) % 8], host[r], rtol=1e-5, atol=1e-5)


def test_reducescatter(mesh8):
    op = get_op("reducescatter")
    x = make_payload(op, mesh8, AXES, N, dtype=jnp.float32)  # [8, 8, N]
    host = np.asarray(x).astype(np.float64)
    fn = op.build(mesh8, AXES)
    out = np.asarray(fn(x))  # [8, 1, N]
    # rank i gets sum over senders j of chunk i
    for r in range(8):
        np.testing.assert_allclose(out[r, 0], host[:, r].sum(axis=0), rtol=1e-4, atol=1e-4)


def test_barrier(mesh8):
    fn = build_barrier(mesh8, AXES)
    x = make_payload(get_op("allreduce"), mesh8, AXES, 1)
    out = fn(x)
    out.block_until_ready()  # completion == all devices reached the psum


def test_allreduce_bf16(mesh8):
    """Buffer-typed allreduce parity (reference numpy-buffer Allreduce
    ``test/test_open.py:195``); bf16 is the native TPU payload type."""
    op = get_op("allreduce")
    x = make_payload(op, mesh8, AXES, N, dtype=jnp.bfloat16)
    fn = op.build(mesh8, AXES)
    out = np.asarray(fn(x).astype(jnp.float32))
    expected = np.asarray(x.astype(jnp.float32)).sum(axis=0)
    np.testing.assert_allclose(out[0], expected, rtol=0.05, atol=0.5)


def test_hierarchical_allreduce_matches_flat(mesh2x2x2):
    """Per-axis hierarchical psum == joint psum on a 2x2x2 mesh
    (BASELINE.json config 3)."""
    axes = ("x", "y", "z")
    op = get_op("allreduce")
    x = make_payload(op, mesh2x2x2, axes, N, dtype=jnp.float32)
    flat = op.build(mesh2x2x2, axes)
    hier = build_allreduce_hierarchical(mesh2x2x2, axes)
    np.testing.assert_allclose(
        np.asarray(flat(x)), np.asarray(hier(x)), rtol=1e-4, atol=1e-4
    )


def test_allreduce_on_4rank_mesh(mesh4):
    """Rank-count sweep axis works (reference RANK_COUNTS gate,
    ``collectives/1d/openmpi.py:210-214``)."""
    op, x, host = _np_input("allreduce", mesh4)
    fn = op.build(mesh4, AXES)
    out = np.asarray(fn(x))
    np.testing.assert_allclose(out[0], host.sum(axis=0), rtol=1e-4, atol=1e-4)
