"""Pallas flash attention vs the dense reference path.

Runs in pallas interpret mode on the CPU-simulated mesh (the kernel
auto-selects interpret off TPU); the same code path compiles natively on
a real chip.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlbb_tpu.models.attention import dense_causal
from dlbb_tpu.ops import flash_attention


def _qkv(key, b, n, s, d, dtype):
    ks = jax.random.split(key, 3)
    shape = (b, n, s, d)
    return tuple(jax.random.normal(k, shape, dtype=dtype) for k in ks)


@pytest.mark.parametrize("block_q,block_k", [(64, 64), (64, 128), (128, 64)])
def test_flash_matches_dense_fp32(block_q, block_k):
    q, k, v = _qkv(jax.random.key(0), 2, 2, 256, 64, jnp.float32)
    out = flash_attention(q, k, v, block_q=block_q, block_k=block_k)
    ref = dense_causal(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_matches_dense_bf16():
    q, k, v = _qkv(jax.random.key(1), 1, 4, 256, 64, jnp.bfloat16)
    out = flash_attention(q, k, v, block_q=128, block_k=128)
    ref = dense_causal(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32), np.asarray(ref, dtype=np.float32),
        atol=2e-2, rtol=2e-2,
    )


def test_flash_noncausal_matches_softmax():
    q, k, v = _qkv(jax.random.key(2), 1, 2, 128, 64, jnp.float32)
    out = flash_attention(q, k, v, causal=False, block_q=64, block_k=64)
    d = q.shape[-1]
    logits = jnp.einsum("bnqd,bnkd->bnqk", q, k) / jnp.sqrt(jnp.float32(d))
    ref = jnp.einsum("bnqk,bnkd->bnqd", jax.nn.softmax(logits, -1), v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_grads_match_dense():
    q, k, v = _qkv(jax.random.key(3), 1, 2, 128, 64, jnp.float32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, block_q=64, block_k=64) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_causal(q, k, v) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gf, gd, name in zip(g_flash, g_dense, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gd), atol=1e-4, rtol=1e-4,
            err_msg=f"d{name} mismatch",
        )


@pytest.mark.parametrize("kvh", [1, 2, 4])
def test_flash_gqa_matches_grouped_dense(kvh):
    """Grouped K/V ([B, kv_heads, S, D]) through the kernel == dense
    grouped attention; K/V never materialise at num_heads width."""
    from dlbb_tpu.models.attention import dense_attention

    b, n, s, d = 1, 8, 128, 64
    ks = jax.random.split(jax.random.key(10), 3)
    q = jax.random.normal(ks[0], (b, n, s, d))
    k = jax.random.normal(ks[1], (b, kvh, s, d))
    v = jax.random.normal(ks[2], (b, kvh, s, d))
    out = flash_attention(q, k, v, block_q=64, block_k=64)
    ref = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    # and == the repeated-K/V MHA oracle
    ref_rep = dense_causal(q, jnp.repeat(k, n // kvh, 1),
                           jnp.repeat(v, n // kvh, 1))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_rep),
                               atol=2e-5, rtol=2e-5)


def test_flash_gqa_grads_match_dense():
    """dk/dv of the grouped kernel accumulate over the sharing query heads
    and stay at kv_heads width; all three grads match the dense grouped
    path."""
    from dlbb_tpu.models.attention import dense_attention

    b, n, kvh, s, d = 1, 4, 2, 128, 64
    ks = jax.random.split(jax.random.key(11), 3)
    q = jax.random.normal(ks[0], (b, n, s, d))
    k = jax.random.normal(ks[1], (b, kvh, s, d))
    v = jax.random.normal(ks[2], (b, kvh, s, d))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, block_q=64, block_k=64) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    assert g_flash[1].shape == (b, kvh, s, d)
    for gf, gd, name in zip(g_flash, g_dense, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gd), atol=1e-4, rtol=1e-4,
            err_msg=f"d{name} mismatch",
        )


def test_flash_gqa_noncausal():
    from dlbb_tpu.models.attention import dense_attention

    b, n, kvh, s, d = 1, 4, 2, 128, 64
    ks = jax.random.split(jax.random.key(12), 3)
    q = jax.random.normal(ks[0], (b, n, s, d))
    k = jax.random.normal(ks[1], (b, kvh, s, d))
    v = jax.random.normal(ks[2], (b, kvh, s, d))
    out = flash_attention(q, k, v, causal=False, block_q=64, block_k=64)
    ref = dense_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_model_forward_flash_matches_full():
    from dlbb_tpu.models.configs import ModelConfig
    from dlbb_tpu.models.transformer import forward, init_params

    kw = dict(hidden_size=128, num_layers=2, num_heads=2,
              ffn_intermediate=256, dtype="float32")
    cfg_full = ModelConfig(attention="full", **kw)
    cfg_flash = ModelConfig(attention="flash", **kw)
    params = init_params(cfg_full, jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (2, 128, 128))
    out_full = forward(params, x, cfg_full)
    out_flash = forward(params, x, cfg_flash)
    np.testing.assert_allclose(np.asarray(out_flash), np.asarray(out_full),
                               atol=1e-4, rtol=1e-4)


def test_flash_autofits_indivisible_seq():
    # S=96 doesn't divide the requested 64 block — the kernel falls back to
    # the largest divisor (48) instead of failing
    q, k, v = _qkv(jax.random.key(4), 1, 1, 96, 64, jnp.float32)
    out = flash_attention(q, k, v, block_q=64, block_k=64)
    ref = dense_causal(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_kv_cache_decode():
    # sk > s: the single query row is the LAST position and must attend to
    # the whole cache (diagonal anchored at the end of the key axis)
    b, n, sk, d = 1, 2, 128, 64
    key = jax.random.key(5)
    q_full, k, v = _qkv(key, b, n, sk, d, jnp.float32)
    ref_full = dense_causal(q_full, k, v)
    q_last = q_full[:, :, -1:, :]
    out = flash_attention(q_last, k, v, block_q=64, block_k=64)
    np.testing.assert_allclose(
        np.asarray(out[:, :, 0]), np.asarray(ref_full[:, :, -1]),
        atol=2e-5, rtol=2e-5,
    )


def test_flash_tp_shard_map_matches_unsharded(mesh2x4):
    from jax.sharding import PartitionSpec as P

    from dlbb_tpu.compat import shard_map

    q, k, v = _qkv(jax.random.key(6), 2, 4, 128, 64, jnp.float32)
    spec = P("dp", "tp", None, None)
    out_sharded = shard_map(
        lambda q, k, v: flash_attention(q, k, v),
        mesh=mesh2x4, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)
    ref = flash_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out_sharded), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_grads_fully_masked_rows_zero():
    """sk < s with causal: rows r with r + (sk - s) < 0 attend to nothing —
    forward emits zeros there and the backward must emit zero gradients
    (regression: p = exp(NEG_INF - NEG_INF) = 1 injected garbage)."""
    b, n, s, d = 1, 2, 128, 64
    sk = 64  # rows 0..63 are fully masked (offset = -64)
    ks = jax.random.split(jax.random.key(7), 3)
    q = jax.random.normal(ks[0], (b, n, s, d))
    k = jax.random.normal(ks[1], (b, n, sk, d))
    v = jax.random.normal(ks[2], (b, n, sk, d))

    out = flash_attention(q, k, v, block_q=64, block_k=64)
    np.testing.assert_array_equal(np.asarray(out[:, :, :64]), 0.0)

    def dense_ref(q, k, v):
        dd = q.shape[-1]
        logits = jnp.einsum("bnqd,bnkd->bnqk", q, k) / jnp.sqrt(jnp.float32(dd))
        rows = jnp.arange(s)[:, None]
        cols = jnp.arange(sk)[None, :]
        mask = rows + (sk - s) >= cols
        logits = jnp.where(mask, logits, -jnp.inf)
        p = jax.nn.softmax(logits, -1)
        p = jnp.where(jnp.any(mask, -1, keepdims=True), p, 0.0)
        return jnp.einsum("bnqk,bnkd->bnqd", p, v)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, block_q=64, block_k=64) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_ref(q, k, v) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    # masked q rows get exactly zero gradient
    np.testing.assert_array_equal(np.asarray(g_flash[0][:, :, :64]), 0.0)
    for gf, gd, name in zip(g_flash, g_dense, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gd), atol=1e-4, rtol=1e-4,
            err_msg=f"d{name} mismatch",
        )


def test_flash_dp_only_mesh_no_allgather(devices):
    """On a dp-only mesh, flash attention must go through shard_map so the
    batch stays sharded — the compiled forward contains no all-gather
    (regression: bare pallas_call made GSPMD replicate the batch)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dlbb_tpu.comm.mesh import MeshSpec, build_mesh
    from dlbb_tpu.models.configs import ModelConfig
    from dlbb_tpu.models.transformer import forward, init_params

    mesh = build_mesh(MeshSpec.grid((8,), ("dp",)))
    cfg = ModelConfig(hidden_size=128, num_layers=1, num_heads=2,
                      ffn_intermediate=256, attention="flash", dtype="float32")
    params = init_params(cfg, jax.random.key(0))
    x = jax.device_put(
        jax.random.normal(jax.random.key(1), (8, 128, 128)),
        NamedSharding(mesh, P("dp")),
    )
    lowered = jax.jit(lambda p, x: forward(p, x, cfg, mesh=mesh)).lower(params, x)
    hlo = lowered.compile().as_text()
    assert "all-gather" not in hlo, "dp-sharded flash forward all-gathers"

    # and numerics still match the unsharded run
    out = jax.jit(lambda p, x: forward(p, x, cfg, mesh=mesh))(params, x)
    ref = forward(params, x, cfg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)


def test_flash_rejects_sequence_parallel_mesh(devices):
    from dlbb_tpu.comm.mesh import MeshSpec, build_mesh
    from dlbb_tpu.models.configs import ModelConfig
    from dlbb_tpu.models.transformer import forward, init_params

    mesh = build_mesh(MeshSpec.grid((4, 2), ("sp", "tp")))
    cfg = ModelConfig(hidden_size=64, num_layers=1, num_heads=2,
                      ffn_intermediate=128, attention="flash", dtype="float32")
    params = init_params(cfg, jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (2, 64, 64))
    with pytest.raises(ValueError, match="ring"):
        forward(params, x, cfg, mesh=mesh)
