"""E2E harness tests (reference ``run_mpi.py`` flow: config → model → data →
warmup → timed benchmark → metrics JSON)."""

import json

import pytest

from dlbb_tpu.bench.e2e import run_e2e
from dlbb_tpu.data import SyntheticEmbeddingDataset


def _config(**over):
    cfg = {
        "experiment": {"name": "smoke", "output_dir": None},
        "model": {
            "hidden_size": 64,
            "num_layers": 2,
            "num_heads": 4,
            "ffn_intermediate": 128,
            "attention": "simplified",
        },
        "parallelism": {"world_size": 4, "data_parallel": 2},
        "input": {"batch_size": 4, "sequence_length": 16, "seed": 42},
        "execution": {"warmup_iterations": 1, "benchmark_iterations": 3},
    }
    cfg.update(over)
    return cfg


def test_e2e_runs_and_writes_metrics(tmp_path, devices):
    result = run_e2e(_config(), output_dir=str(tmp_path), verbose=False)
    assert result["mesh"] == {"dp": 2, "sp": 1, "pp": 1, "ep": 1, "tp": 4}
    assert result["forward_time"]["count"] == 3
    assert result["forward_time"]["mean"] > 0
    assert result["compile_time_s"] > 0
    assert result["tokens_per_second"] > 0
    assert result["cross_host_variance"] == 0.0  # single process
    saved = json.loads((tmp_path / "xla_tpu_smoke.json").read_text())
    assert saved["model"]["num_parameters"] == result["model"]["num_parameters"]


def test_e2e_sequence_parallel_ring(tmp_path, devices):
    """E2E harness runs ring-attention context parallelism end-to-end
    (sequence_parallel config knob; capability absent from the reference)."""
    cfg = _config(
        model={
            "hidden_size": 64, "num_layers": 2, "num_heads": 4,
            "ffn_intermediate": 128, "attention": "ring", "dtype": "float32",
        },
        parallelism={"world_size": 1, "data_parallel": 2,
                     "sequence_parallel": 4},
    )
    result = run_e2e(cfg, verbose=False)
    assert result["mesh"] == {"dp": 2, "sp": 4, "pp": 1, "ep": 1, "tp": 1}
    assert result["forward_time"]["mean"] > 0


def test_e2e_ring_requires_sp(devices):
    cfg = _config(
        model={
            "hidden_size": 64, "num_layers": 1, "num_heads": 4,
            "ffn_intermediate": 128, "attention": "ring",
        },
    )
    import pytest as _pytest

    with _pytest.raises(ValueError, match="sequence_parallel"):
        run_e2e(cfg, verbose=False)


def test_e2e_world_size_preflight(devices):
    """Device-count validation, parity with run_mpi.py:73-77."""
    cfg = _config(parallelism={"world_size": 16, "data_parallel": 1})
    with pytest.raises(ValueError, match="16 devices"):
        run_e2e(cfg, verbose=False)


def test_dataset_is_fixed_and_seeded(devices):
    a = SyntheticEmbeddingDataset(2, 8, 16, seed=42)
    b = SyntheticEmbeddingDataset(2, 8, 16, seed=42)
    c = SyntheticEmbeddingDataset(2, 8, 16, seed=7)
    import numpy as np

    assert a.get_batch() is a.get_batch()  # same object every call
    np.testing.assert_array_equal(np.asarray(a.get_batch()), np.asarray(b.get_batch()))
    assert not np.array_equal(np.asarray(a.get_batch()), np.asarray(c.get_batch()))
