"""Real-chip regression net for the compiled (mosaic) pallas paths.

Every other test runs the flash kernel in pallas *interpret* mode on the
CPU-simulated mesh; a mosaic-level bug would previously surface only as a
wrong headline BENCH number.  This ``tpu``-marked subset compiles the
kernels natively on the one real chip and asserts numerics against the
dense oracle, so a broken compiled path is a red test, not a bad artifact.

Run: ``DLBB_TPU_TESTS=1 python -m pytest tests/ -m tpu``
(committed log: ``results/tpu_tests/pytest_tpu_log.txt``).

Tolerances: TPU matmuls run on the MXU at DEFAULT internal precision even
for fp32 inputs (bf16 multiply passes, fp32 accumulate), and the kernel's
blocked accumulation order differs from the dense einsum's — measured
compiled-vs-dense deltas reach ~5e-2 absolute on O(1)..O(10) data (first
chip run of this file).  The bounds below sit just above that noise; a
mosaic miscompile (wrong mask, wrong block index, stale VMEM) produces
O(1) errors and still fails loudly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.tpu


@pytest.fixture(scope="module", autouse=True)
def _require_tpu():
    if jax.default_backend() != "tpu":
        pytest.skip("no TPU backend available")


def _qkv(seed, b, n, s, d, dtype, kvh=None):
    ks = jax.random.split(jax.random.key(seed), 3)
    q = jax.random.normal(ks[0], (b, n, s, d), dtype=dtype)
    k = jax.random.normal(ks[1], (b, kvh or n, s, d), dtype=dtype)
    v = jax.random.normal(ks[2], (b, kvh or n, s, d), dtype=dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_flash_compiled_fwd_matches_dense(causal):
    from dlbb_tpu.models.attention import dense_attention
    from dlbb_tpu.ops import flash_attention

    q, k, v = _qkv(0, 2, 4, 1024, 128, jnp.bfloat16)
    out = flash_attention(q, k, v, causal=causal, interpret=False)
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=3e-2, rtol=3e-2,
    )


def test_flash_compiled_fwd_fp32():
    from dlbb_tpu.models.attention import dense_attention
    from dlbb_tpu.ops import flash_attention

    q, k, v = _qkv(1, 1, 2, 512, 128, jnp.float32)
    out = flash_attention(q, k, v, interpret=False)
    ref = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-2, rtol=2e-2)


def test_flash_compiled_gqa_fwd():
    from dlbb_tpu.models.attention import dense_attention
    from dlbb_tpu.ops import flash_attention

    q, k, v = _qkv(2, 1, 8, 1024, 128, jnp.bfloat16, kvh=2)
    out = flash_attention(q, k, v, interpret=False)
    ref = dense_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=3e-2, rtol=3e-2,
    )


def test_flash_compiled_bwd_matches_dense():
    from dlbb_tpu.models.attention import dense_attention
    from dlbb_tpu.ops import flash_attention

    q, k, v = _qkv(3, 1, 2, 512, 128, jnp.float32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, interpret=False) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v) ** 2)

    g_flash = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.jit(jax.grad(loss_dense, argnums=(0, 1, 2)))(q, k, v)
    for gf, gd, name in zip(g_flash, g_dense, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gd), atol=1e-1, rtol=5e-2,
            err_msg=f"d{name} mismatch",
        )


def test_flash_compiled_gqa_bwd():
    from dlbb_tpu.models.attention import dense_attention
    from dlbb_tpu.ops import flash_attention

    q, k, v = _qkv(4, 1, 4, 512, 128, jnp.float32, kvh=2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, interpret=False) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v) ** 2)

    g_flash = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.jit(jax.grad(loss_dense, argnums=(0, 1, 2)))(q, k, v)
    assert g_flash[1].shape == (1, 2, 512, 128)
    for gf, gd, name in zip(g_flash, g_dense, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gd), atol=1e-1, rtol=5e-2,
            err_msg=f"d{name} mismatch",
        )


def test_full_attention_routes_to_flash_on_tpu():
    """attention='full' at S >= FLASH_ROUTE_MIN_SEQ must produce the same
    numbers as the pinned 'dense' kernel — the routing is a kernel swap,
    not a math change."""
    from dlbb_tpu.models.configs import ModelConfig
    from dlbb_tpu.models.transformer import (
        FLASH_ROUTE_MIN_SEQ,
        forward,
        init_params,
    )

    kw = dict(hidden_size=256, num_layers=2, num_heads=2,
              ffn_intermediate=512, dtype="float32")
    cfg_full = ModelConfig(attention="full", **kw)
    cfg_dense = ModelConfig(attention="dense", **kw)
    params = init_params(cfg_full, jax.random.key(0))
    x = jax.random.normal(
        jax.random.key(1), (1, FLASH_ROUTE_MIN_SEQ, 256), jnp.float32
    )
    # the routing must actually fire: the pallas kernel lowers to a
    # tpu_custom_call, which the dense einsum path never emits (guards
    # against the gate silently regressing to dense-vs-dense)
    hlo_full = jax.jit(
        lambda p, a: forward(p, a, cfg_full)
    ).lower(params, x).compile().as_text()
    hlo_dense = jax.jit(
        lambda p, a: forward(p, a, cfg_dense)
    ).lower(params, x).compile().as_text()
    # match the mosaic call target specifically: unrelated TPU helper
    # custom-calls (e.g. ConcatBitcast at some shapes) appear in both HLOs
    assert "tpu_custom_call" in hlo_full, "full did not route to the kernel"
    assert "tpu_custom_call" not in hlo_dense
    out_full = jax.jit(lambda p, a: forward(p, a, cfg_full))(params, x)
    out_dense = jax.jit(lambda p, a: forward(p, a, cfg_dense))(params, x)
    np.testing.assert_allclose(
        np.asarray(out_full), np.asarray(out_dense), atol=5e-2, rtol=5e-2
    )


def test_e2e_smoke_on_chip():
    """One real e2e benchmark on the chip (flash attention, chained
    device-honest timing) — the compiled end-to-end path."""
    from dlbb_tpu.bench.e2e import run_e2e

    result = run_e2e({
        "experiment": {"name": "tpu_smoke"},
        "model": {"hidden_size": 512, "num_layers": 2, "num_heads": 4,
                  "ffn_intermediate": 1024, "attention": "flash"},
        "parallelism": {"world_size": 1, "data_parallel": 1},
        "input": {"batch_size": 2, "sequence_length": 1024, "seed": 42},
        "execution": {"warmup_iterations": 2, "benchmark_iterations": 5},
    }, verbose=False)
    assert result["tokens_per_second"] > 0
    assert result["forward_time"]["mean"] > 0
    assert np.isfinite(result["achieved_tflops_per_second"])
