"""Budget-floor behavior of per-iteration timing (``time_fn_per_iter``).

Pins the sample-floor contract directly with a synthetic slow function:
normally at least 3 samples are measured, but when even three iterations
cannot fit ``max_seconds`` the floor drops to 1 — one honest recorded
sample instead of a multiple-of-budget overrun.  (The sweep-level budget
test is in test_bench.py; this one exercises the floor boundary, which a
real collective cannot hit deterministically.)
"""

import time

import jax.numpy as jnp

from dlbb_tpu.utils.timing import time_fn_per_iter


def _slow_fn(seconds):
    def fn(x):
        time.sleep(seconds)
        return jnp.asarray(x)

    return fn


def test_floor_three_samples_when_they_fit():
    # iteration ~8 ms, budget 80 ms -> clamped but >= 3 samples
    timings, warmup_run, clamped = time_fn_per_iter(
        _slow_fn(0.008), 1.0, warmup=10, iterations=100,
        max_seconds=0.08,
    )
    assert clamped
    assert 3 <= len(timings) < 100


def test_floor_drops_to_one_when_three_cannot_fit():
    # iteration ~60 ms, budget 100 ms: 3 samples would be ~2x budget
    timings, warmup_run, clamped = time_fn_per_iter(
        _slow_fn(0.06), 1.0, warmup=10, iterations=100,
        max_seconds=0.1,
    )
    assert clamped
    assert len(timings) == 1
    assert timings[0] >= 0.05


def test_no_budget_runs_everything():
    timings, warmup_run, clamped = time_fn_per_iter(
        _slow_fn(0.0), 1.0, warmup=2, iterations=5, max_seconds=None,
    )
    assert not clamped
    assert len(timings) == 5
