"""Schedule-auditor tests (docs/schedule_audit.md).

Three layers, mirroring the comm-lint convention of test_analysis.py:

- dependency-graph parser units — synthetic HLO text pinning operand /
  control-dep edges, async start/done pairing, while-loop trip-count
  propagation (the scanned-ring undercount bugfix), and conditional
  branch extraction;
- seeded-violation fixtures — a deliberately serialized ring (no
  straddling compute), a divergent-branch collective mismatch, and a
  baseline-diff regression must each fail with exactly the expected
  finding, and their fixed twins must pass clean;
- real lowered targets — the PR-4 ring/bidir collective-matmul targets
  must report ``overlap_efficiency > 0`` with every hop straddled, and
  the `analyze` exit-code contract (0 clean / 1 findings / 2 crash) is
  pinned so the CI diff gate composes with the other smoke stages.
"""

import json
import textwrap

import pytest

from dlbb_tpu.analysis.costmodel import (
    COST_MODEL_VERSION,
    collective_cost_us,
    compute_cost_us,
    get_tier,
)
from dlbb_tpu.analysis.expectations import TargetExpectation, wire_bytes
from dlbb_tpu.analysis.findings import EXIT_CLEAN, EXIT_CRASH, EXIT_FINDINGS
from dlbb_tpu.analysis.hlo_parse import parse_collectives, parse_module
from dlbb_tpu.analysis.schedule_audit import (
    analyze_schedule,
    diff_baselines,
    baseline_path,
    snapshot_baselines,
)

GROUPS8 = "replica_groups={{0,1,2,3,4,5,6,7}}"
RING4 = "source_target_pairs={{0,1},{1,2},{2,3},{3,0}}"


# ---------------------------------------------------------------------------
# dependency-graph parser units
# ---------------------------------------------------------------------------


WHILE_MODULE = textwrap.dedent("""
    HloModule scanned, is_scheduled=true

    %body (p.1: (s32[], f32[64])) -> (s32[], f32[64]) {
      %p.1 = (s32[], f32[64]{0}) parameter(0)
      %gte.0 = s32[] get-tuple-element((s32[], f32[64]{0}) %p.1), index=0
      %gte.1 = f32[64]{0} get-tuple-element((s32[], f32[64]{0}) %p.1), index=1
      %ar = f32[64]{0} all-reduce(f32[64]{0} %gte.1), channel_id=1, """
    + GROUPS8 + """, to_apply=%add
      ROOT %tuple = (s32[], f32[64]{0}) tuple(s32[] %gte.0, f32[64]{0} %ar)
    }

    %cond (p.2: (s32[], f32[64])) -> pred[] {
      %p.2 = (s32[], f32[64]{0}) parameter(0)
      ROOT %lt = pred[] constant(true)
    }

    ENTRY %main (arg: f32[64]) -> f32[64] {
      %arg = f32[64]{0} parameter(0)
      %zero = s32[] constant(0)
      %init = (s32[], f32[64]{0}) tuple(s32[] %zero, f32[64]{0} %arg)
      %while = (s32[], f32[64]{0}) while((s32[], f32[64]{0}) %init), \
condition=%cond, body=%body, backend_config={"known_trip_count":{"n":"3"}}
      ROOT %out = f32[64]{0} get-tuple-element((s32[], f32[64]{0}) %while), index=1
    }
""")


def test_while_body_collectives_carry_trip_count():
    """The scanned-ring undercount bugfix: a collective inside a while
    body executes ``known_trip_count`` times per module invocation, and
    the inventory must charge it that many times — the old line-oriented
    parser counted one iteration of wire volume regardless."""
    module = parse_module(WHILE_MODULE)
    assert module.entry == "main"
    assert module.computations["body"].execution_count == 3
    assert module.computations["main"].execution_count == 1

    (ar,) = parse_collectives(module)
    assert ar.kind == "all-reduce"
    assert ar.computation == "body"
    assert ar.execution_count == 3
    assert ar.result_bytes == 64 * 4

    _, meta = analyze_schedule(
        module, TargetExpectation(), "fixture/while", tier="cpu-sim")
    per_iter = wire_bytes("all-reduce", 64 * 4, 8)
    assert meta["total_wire_bytes"] == 3 * per_iter
    assert meta["collective_kinds"] == {"all-reduce": 3}
    # the while's critical path prices trip_count executions of the body
    tier = get_tier("cpu-sim")
    assert meta["critical_path_us"] >= 3 * collective_cost_us(per_iter, tier)


def test_while_body_wire_counted_in_hlo_audit_total(mesh8):
    """End-to-end pin of the undercount fix on a REAL lowered scan: a
    psum inside a 3-step lax.scan lowers to a while body, and the audit's
    total wire must charge all 3 iterations."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dlbb_tpu.analysis.hlo_audit import AuditTarget, audit_target
    from dlbb_tpu.compat import shard_map

    def build():
        def body(x):
            def step(c, _):
                return lax.psum(c, "ranks") * 0.125, None

            y, _ = lax.scan(step, x, None, length=3)
            return y

        fn = jax.jit(shard_map(
            body, mesh=mesh8, in_specs=(P("ranks"),), out_specs=P("ranks"),
        ))
        x = jax.device_put(
            jnp.ones((8, 32), jnp.float32),
            NamedSharding(mesh8, P("ranks")),
        )
        return fn, (x,)

    findings, meta = audit_target(AuditTarget(
        name="fixture/scanned_psum",
        build=build,
        expectation=TargetExpectation(
            allowed={"all-reduce"}, required_any={"all-reduce"},
            min_required=3,  # 3 loop iterations, execution-weighted
        ),
        min_devices=8,
    ), passes=("hlo", "schedule"))
    assert findings == [], [f.render() for f in findings]
    scanned = [c for c in meta["collectives"] if c["execution_count"] == 3]
    assert scanned, meta["collectives"]
    assert meta["num_collectives"] >= 3
    per_iter = wire_bytes("all-reduce", scanned[0]["result_bytes"], 8)
    assert meta["total_wire_bytes"] >= 3 * per_iter


ASYNC_MODULE = textwrap.dedent("""
    ENTRY %main (p: f32[32,32]) -> f32[256,32] {
      %p = f32[32,32]{1,0} parameter(0)
      %w = f32[32,32]{1,0} parameter(1)
      %ags = (f32[32,32]{1,0}, f32[256,32]{1,0}) all-gather-start(\
f32[32,32]{1,0} %p), channel_id=1, """ + GROUPS8 + """, dimensions={0}
      %dot.in = f32[32,32]{1,0} dot(f32[32,32]{1,0} %p, f32[32,32]{1,0} \
%w), lhs_contracting_dims={1}, rhs_contracting_dims={0}
      %agd = f32[256,32]{1,0} all-gather-done((f32[32,32]{1,0}, \
f32[256,32]{1,0}) %ags)
      %dot.out = f32[32,32]{1,0} dot(f32[32,32]{1,0} %dot.in, \
f32[32,32]{1,0} %w), lhs_contracting_dims={1}, rhs_contracting_dims={0}
      ROOT %out = f32[256,32]{1,0} add(f32[256,32]{1,0} %agd, \
f32[256,32]{1,0} %agd)
    }
""")


def test_async_pair_window_and_payload():
    """Async start/done pairing: the inventory counts the pair once with
    the gathered payload on the start; the overlap window is the
    scheduled span strictly between start and done, so only %dot.in (in
    the window, independent) hides wire time — %dot.out comes after the
    done and hides nothing."""
    module = parse_module(ASYNC_MODULE)
    (ag,) = parse_collectives(module)
    assert ag.kind == "all-gather"
    assert ag.result_bytes == 256 * 32 * 4  # the gathered result array

    _, meta = analyze_schedule(
        module, TargetExpectation(), "fixture/async", tier="cpu-sim")
    (c,) = meta["collectives"]
    assert c["async"] is True
    dot_flops = 2 * 32 * 32 * 32
    assert c["straddling_flops"] == dot_flops  # dot.in only
    tier = get_tier("cpu-sim")
    assert c["hidden_us"] == pytest.approx(
        min(c["cost_us"], compute_cost_us(dot_flops, tier)))


def test_control_dependency_serialises_compute():
    """control-predecessors are dependency edges: a dot forced after the
    permute by a control dep is NOT straddling compute."""
    base = textwrap.dedent("""
        ENTRY %main (p: f32[64,64]) -> f32[64,64] {
          %p = f32[64,64]{1,0} parameter(0)
          %w = f32[64,64]{1,0} parameter(1)
          %cp = f32[64,64]{1,0} collective-permute(f32[64,64]{1,0} %p), \
channel_id=1, """ + RING4 + """
          %dot = f32[64,64]{1,0} dot(f32[64,64]{1,0} %p, f32[64,64]{1,0} \
%w), lhs_contracting_dims={1}, rhs_contracting_dims={0}CTRL
          ROOT %out = f32[64,64]{1,0} add(f32[64,64]{1,0} %cp, \
f32[64,64]{1,0} %dot)
        }
    """)
    free = parse_module(base.replace("CTRL", ""))
    _, meta = analyze_schedule(
        free, TargetExpectation(), "fixture/ctrl", tier="cpu-sim")
    assert meta["collectives"][0]["straddling_flops"] > 0

    pinned = parse_module(
        base.replace("CTRL", ", control-predecessors={%cp}"))
    instr = pinned.computations["main"].by_name()["dot"]
    assert instr.control_deps == ("cp",)
    _, meta = analyze_schedule(
        pinned, TargetExpectation(), "fixture/ctrl", tier="cpu-sim")
    assert meta["collectives"][0]["straddling_flops"] == 0


# ---------------------------------------------------------------------------
# seeded violation: deliberately serialized ring
# ---------------------------------------------------------------------------


SERIALIZED_RING = textwrap.dedent("""
    ENTRY %main (p: f32[128,128]) -> f32[128,128] {
      %p = f32[128,128]{1,0} parameter(0)
      %w = f32[128,128]{1,0} parameter(1)
      %cp.1 = f32[128,128]{1,0} collective-permute(f32[128,128]{1,0} %p), \
channel_id=1, """ + RING4 + """
      %dot.1 = f32[128,128]{1,0} dot(f32[128,128]{1,0} %cp.1, \
f32[128,128]{1,0} %w), lhs_contracting_dims={1}, rhs_contracting_dims={0}
      %cp.2 = f32[128,128]{1,0} collective-permute(f32[128,128]{1,0} \
%dot.1), channel_id=2, """ + RING4 + """
      ROOT %dot.2 = f32[128,128]{1,0} dot(f32[128,128]{1,0} %cp.2, \
f32[128,128]{1,0} %w), lhs_contracting_dims={1}, rhs_contracting_dims={0}
    }
""")

OVERLAPPED_RING = textwrap.dedent("""
    ENTRY %main (p: f32[128,128]) -> f32[128,128] {
      %p = f32[128,128]{1,0} parameter(0)
      %w = f32[128,128]{1,0} parameter(1)
      %cp.1 = f32[128,128]{1,0} collective-permute(f32[128,128]{1,0} %p), \
channel_id=1, """ + RING4 + """
      %dot.1 = f32[128,128]{1,0} dot(f32[128,128]{1,0} %p, \
f32[128,128]{1,0} %w), lhs_contracting_dims={1}, rhs_contracting_dims={0}
      %cp.2 = f32[128,128]{1,0} collective-permute(f32[128,128]{1,0} \
%cp.1), channel_id=2, """ + RING4 + """
      %dot.2 = f32[128,128]{1,0} dot(f32[128,128]{1,0} %cp.1, \
f32[128,128]{1,0} %w), lhs_contracting_dims={1}, rhs_contracting_dims={0}
      ROOT %add = f32[128,128]{1,0} add(f32[128,128]{1,0} %dot.1, \
f32[128,128]{1,0} %dot.2)
    }
""")


@pytest.mark.schedule_smoke
def test_serialized_ring_yields_finding():
    """Every hop of the serialized fixture is an ancestor/descendant of
    every dot — zero straddling compute, one finding per hop."""
    exp = TargetExpectation(expect_overlap=True)
    findings, meta = analyze_schedule(
        SERIALIZED_RING, exp, "fixture/serialized_ring", tier="cpu-sim")
    assert [f.rule for f in findings] == ["serialized-collective"] * 2
    assert all(f.severity == "error" for f in findings)
    assert meta["overlap_efficiency"] == 0.0
    assert meta["ring_hops"] == {"total": 2, "straddled": 0}
    # the whole comm time sits on the critical path
    assert meta["comm_on_critical_path_us"] == pytest.approx(
        meta["comm_total_us"])
    json.dumps([f.to_dict() for f in findings])


@pytest.mark.schedule_smoke
def test_overlapped_ring_twin_is_clean():
    """The fixed twin — same hops, dots independent of the chunk in
    flight — passes with every hop straddled and efficiency > 0."""
    exp = TargetExpectation(expect_overlap=True)
    findings, meta = analyze_schedule(
        OVERLAPPED_RING, exp, "fixture/overlapped_ring", tier="cpu-sim")
    assert findings == [], [f.render() for f in findings]
    assert meta["ring_hops"] == {"total": 2, "straddled": 2}
    assert meta["overlap_efficiency"] > 0
    # without the overlap claim the same module yields no findings either
    assert analyze_schedule(
        SERIALIZED_RING, TargetExpectation(), "fixture/no_claim",
        tier="cpu-sim",
    )[0] == []


def test_real_serialized_ring_target(mesh8):
    """A REAL lowered serialized ring: matmul feeding each hop (the
    anti-pattern the decomposition exists to avoid) — the auditor must
    refuse it even though the permute-count contract would pass."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dlbb_tpu.analysis.hlo_audit import AuditTarget, audit_target
    from dlbb_tpu.compat import shard_map

    fwd = [(i, (i + 1) % 8) for i in range(8)]

    def build():
        def body(x, w):
            cur = x
            for _ in range(4):
                cur = lax.ppermute(cur, "ranks", fwd)
                cur = cur @ w  # every dot consumes the chunk in flight
            return cur

        fn = jax.jit(shard_map(
            body, mesh=mesh8,
            in_specs=(P("ranks"), P(None, None)),
            out_specs=P("ranks"),
        ))
        sharding = NamedSharding(mesh8, P("ranks"))
        x = jax.device_put(jnp.ones((8, 64), jnp.float32), sharding)
        w = jax.device_put(
            jnp.ones((64, 64), jnp.float32),
            NamedSharding(mesh8, P(None, None)),
        )
        return fn, (x, w)

    findings, meta = audit_target(AuditTarget(
        name="fixture/serialized_real_ring",
        build=build,
        expectation=TargetExpectation(
            allowed={"collective-permute"},
            required_any={"collective-permute"},
            min_required=4,
            expect_overlap=True,
        ),
        min_devices=8,
    ), passes=("hlo", "schedule"))
    rules = {f.rule for f in findings}
    assert rules == {"serialized-collective"}, [f.render() for f in findings]
    assert meta["schedule"]["overlap_efficiency"] == 0.0


def test_ring_collective_matmul_targets_overlap_clean(devices):
    """The PR-4 acceptance gate: the ring/bidir micro-op targets must
    report overlap_efficiency > 0 with EVERY hop straddled by a matmul,
    and the hops must be the ring_hop-named permutes (the naming hook in
    parallel/collective_matmul.py)."""
    from dlbb_tpu.analysis.hlo_audit import (
        _collective_matmul_target,
        audit_target,
    )

    for op in ("ag_matmul", "matmul_rs"):
        for schedule in ("ring", "bidir"):
            target = _collective_matmul_target(op, schedule)
            findings, meta = audit_target(
                target, passes=("hlo", "schedule"))
            assert findings == [], (op, schedule,
                                    [f.render() for f in findings])
            s = meta["schedule"]
            assert s["overlap_efficiency"] > 0, (op, schedule)
            assert s["ring_hops"]["total"] >= 7, (op, schedule)
            assert (s["ring_hops"]["straddled"]
                    == s["ring_hops"]["total"]), (op, schedule)
            named = [c for c in s["collectives"] if c["is_ring_hop"]]
            assert len(named) == s["ring_hops"]["total"]


def test_fused_target_reports_zero_overlap(devices):
    """The fused schedule is the serialized baseline: efficiency 0 — and
    no finding, because its expectation makes no overlap claim."""
    from dlbb_tpu.analysis.hlo_audit import (
        _collective_matmul_target,
        audit_target,
    )

    findings, meta = audit_target(
        _collective_matmul_target("ag_matmul", "fused"),
        passes=("schedule",))
    assert findings == []
    assert meta["schedule"]["overlap_efficiency"] == 0.0


# ---------------------------------------------------------------------------
# seeded violation: divergent-branch collective mismatch
# ---------------------------------------------------------------------------


def _conditional_module(true_body: str, false_body: str) -> str:
    return textwrap.dedent("""
        %branch_true (bt: f32[64]) -> f32[64] {
          %bt = f32[64]{0} parameter(0)
          TRUE_BODY
        }

        %branch_false (bf: f32[64]) -> f32[64] {
          %bf = f32[64]{0} parameter(0)
          FALSE_BODY
        }

        ENTRY %main (pr: pred[], x: f32[64]) -> f32[64] {
          %pr = pred[] parameter(0)
          %x = f32[64]{0} parameter(1)
          ROOT %cond = f32[64]{0} conditional(pred[] %pr, f32[64]{0} %x, \
f32[64]{0} %x), true_computation=%branch_true, \
false_computation=%branch_false
        }
    """).replace("TRUE_BODY", true_body).replace("FALSE_BODY", false_body)


_AR_TRUE = ("ROOT %ar.t = f32[64]{0} all-reduce(f32[64]{0} %bt), "
            "channel_id=1, " + GROUPS8 + ", to_apply=%add")
_AR_FALSE = ("ROOT %ar.f = f32[64]{0} all-reduce(f32[64]{0} %bf), "
             "channel_id=2, " + GROUPS8 + ", to_apply=%add")


@pytest.mark.schedule_smoke
def test_divergent_branch_collectives_yield_finding():
    """Branches posting different collective sequences (all-reduce vs
    all-gather) are the classic cross-shard deadlock on pods."""
    diverged = _conditional_module(
        _AR_TRUE,
        "ROOT %ag.f = f32[64]{0} all-gather(f32[8]{0} %bf), channel_id=2, "
        + GROUPS8 + ", dimensions={0}",
    )
    findings, _ = analyze_schedule(
        diverged, TargetExpectation(), "fixture/divergent", tier="cpu-sim")
    assert [f.rule for f in findings] == ["divergent-branch-collectives"]
    assert findings[0].severity == "error"
    assert "deadlock" in findings[0].message
    branches = findings[0].details["branches"]
    assert set(branches) == {"branch_true", "branch_false"}


@pytest.mark.schedule_smoke
def test_matching_branch_collectives_are_clean():
    """Same kind + replica groups on both branches: no finding (the
    channel id may differ — it is not part of the posted signature), and
    the inventory charges exactly ONE branch per invocation (only one
    executes — charging both would double the wire totals)."""
    matching = _conditional_module(_AR_TRUE, _AR_FALSE)
    findings, meta = analyze_schedule(
        matching, TargetExpectation(), "fixture/matching", tier="cpu-sim")
    assert findings == [], [f.render() for f in findings]
    assert meta["collective_kinds"] == {"all-reduce": 1}
    assert meta["num_collectives"] == 1
    assert meta["total_wire_bytes"] == wire_bytes("all-reduce", 64 * 4, 8)


def test_divergent_replica_groups_yield_finding():
    """Same kind but different replica groups diverges too — the shards
    would post mismatched groups and hang just the same."""
    diverged = _conditional_module(
        _AR_TRUE,
        "ROOT %ar.f = f32[64]{0} all-reduce(f32[64]{0} %bf), channel_id=2, "
        "replica_groups={{0,1,2,3},{4,5,6,7}}, to_apply=%add",
    )
    findings, _ = analyze_schedule(
        diverged, TargetExpectation(), "fixture/groups", tier="cpu-sim")
    assert [f.rule for f in findings] == ["divergent-branch-collectives"]


# ---------------------------------------------------------------------------
# seeded violation: baseline-diff regression
# ---------------------------------------------------------------------------


def _schedule_meta(**overrides):
    meta = {
        "cost_model_version": COST_MODEL_VERSION,
        "tier": "cpu-sim",
        "critical_path_us": 10.0,
        "comm_on_critical_path_us": 4.0,
        "comm_total_us": 5.0,
        "compute_total_us": 6.0,
        "overlap_efficiency": 0.8,
        "total_wire_bytes": 4096,
        "num_collectives": 7,
        "collective_kinds": {"collective-permute": 7},
    }
    meta.update(overrides)
    return meta


@pytest.mark.schedule_smoke
def test_baseline_snapshot_and_clean_diff(tmp_path):
    metas = {"t/one": _schedule_meta(), "t/two": _schedule_meta()}
    written = snapshot_baselines(metas, tmp_path)
    assert len(written) == 2
    assert baseline_path(tmp_path, "t/one").exists()
    data = json.loads(baseline_path(tmp_path, "t/one").read_text())
    assert data["target"] == "t/one"
    assert data["cost_model_version"] == COST_MODEL_VERSION
    assert diff_baselines(metas, tmp_path) == []
    # a snapshot on a smaller host must NOT prune baselines of targets it
    # merely skipped for lack of devices...
    snapshot_baselines({"t/one": _schedule_meta()}, tmp_path,
                       skipped_targets=("t/two",))
    assert baseline_path(tmp_path, "t/two").exists()
    # ...but a re-snapshot does prune baselines for removed targets
    snapshot_baselines({"t/one": _schedule_meta()}, tmp_path)
    assert not baseline_path(tmp_path, "t/two").exists()


@pytest.mark.schedule_smoke
def test_baseline_diff_regressions(tmp_path):
    """The three gated regressions: >10% critical-path growth, any new
    collective kind, >10% wire growth — each exactly one error finding;
    growth under the gate passes."""
    snapshot_baselines({"t": _schedule_meta()}, tmp_path)

    ok = diff_baselines(
        {"t": _schedule_meta(critical_path_us=10.9)}, tmp_path)
    assert ok == [], [f.render() for f in ok]

    cp = diff_baselines(
        {"t": _schedule_meta(critical_path_us=11.2)}, tmp_path)
    assert [f.rule for f in cp] == ["critical-path-regression"]
    assert cp[0].details["ratio"] == pytest.approx(1.12)

    kinds = diff_baselines({"t": _schedule_meta(
        collective_kinds={"collective-permute": 7, "all-gather": 1},
    )}, tmp_path)
    assert [f.rule for f in kinds] == ["new-collective-kind"]
    assert kinds[0].details["new_kinds"] == ["all-gather"]

    wire = diff_baselines(
        {"t": _schedule_meta(total_wire_bytes=8192)}, tmp_path)
    assert [f.rule for f in wire] == ["wire-volume-regression"]


def test_baseline_diff_bookkeeping(tmp_path):
    """missing-baseline (new target / empty dir) and cost-model skew are
    errors; a stale baseline and a big improvement are warnings only."""
    empty = tmp_path / "empty"
    (finding,) = diff_baselines({"t": _schedule_meta()}, empty)
    assert finding.rule == "missing-baseline"
    assert finding.severity == "error"

    snapshot_baselines({"t": _schedule_meta()}, tmp_path)
    new = diff_baselines(
        {"t": _schedule_meta(), "t/new": _schedule_meta()}, tmp_path)
    assert [f.rule for f in new] == ["missing-baseline"]

    skew = diff_baselines(
        {"t": _schedule_meta(cost_model_version="cm999")}, tmp_path)
    assert [f.rule for f in skew] == ["cost-model-mismatch"]

    stale = diff_baselines({}, tmp_path)
    assert [(f.rule, f.severity) for f in stale] == [
        ("stale-baseline", "warning")]
    # ...but not when the target was merely skipped for lack of devices
    assert diff_baselines({}, tmp_path, skipped_targets=("t",)) == []

    improved = diff_baselines(
        {"t": _schedule_meta(critical_path_us=2.0)}, tmp_path)
    assert [(f.rule, f.severity) for f in improved] == [
        ("baseline-improved", "warning")]


# ---------------------------------------------------------------------------
# exit-code contract (0 clean / 1 findings / 2 crash)
# ---------------------------------------------------------------------------


@pytest.mark.schedule_smoke
def test_analyze_exit_code_contract(tmp_path, monkeypatch):
    """Pinned so the CI diff gate composes with the chaos and compression
    smoke stages: 0 = clean, 1 = findings, 2 = analyzer crash."""
    from pathlib import Path

    from dlbb_tpu import analysis

    repo_root = Path(__file__).resolve().parents[1]
    assert (EXIT_CLEAN, EXIT_FINDINGS, EXIT_CRASH) == (0, 1, 2)
    assert analysis.run_analysis(
        which="lint", root=str(repo_root), verbose=False) == EXIT_CLEAN
    # findings -> 1 (vacuous lint root is itself a finding, fail-closed)
    assert analysis.run_analysis(
        which="lint", root=str(tmp_path), verbose=False) == EXIT_FINDINGS
    # analyzer crash -> 2, never an unhandled traceback with code 1
    monkeypatch.setattr(
        "dlbb_tpu.analysis.run_source_lint",
        lambda **kw: (_ for _ in ()).throw(RuntimeError("boom")))
    assert analysis.run_analysis(
        which="lint", root=str(repo_root), verbose=False) == EXIT_CRASH


def test_cost_model_table_pins():
    """The versioned table: the committed-baseline tier exists in the
    current version, and pricing is monotone in bytes/FLOPs (the property
    the regression gate leans on)."""
    tier = get_tier("cpu-sim")
    assert get_tier(None).name == tier.name  # default tier
    assert collective_cost_us(0, tier) == pytest.approx(tier.alpha_us)
    assert (collective_cost_us(1 << 20, tier)
            > collective_cost_us(1 << 10, tier))
    assert compute_cost_us(2_000_000, tier) > compute_cost_us(1_000, tier)
    with pytest.raises(KeyError):
        get_tier("no-such-tier")
    with pytest.raises(KeyError):
        get_tier("cpu-sim", version="no-such-version")
