"""Static numerics auditor: seeded-violation fixtures for every rule,
fusion-coverage regression on a captured fused-scan HLO, clean real
targets, the baseline/diff gate on the numerics axis, the fp64 shadow
cross-check, and the ``float64-literal-in-jit`` source-lint rule.

Seeded fixtures are hand-written HLO text: the CPU XLA pipeline folds
identity converts and auto-upcasts bf16 reduce combiners to f32 — i.e.
it OPTIMISES AWAY the violations the pass exists to catch — so a lowered
fixture cannot carry them (the same reason the shadow cross-check forces
its low-precision accumulators through scan carries).

The ``numerics_smoke`` marker subset is also invoked standalone by
``scripts/run_static_analysis.sh``.
"""

import gzip
import json
from pathlib import Path

import pytest

from dlbb_tpu.analysis.expectations import (
    TargetExpectation,
    policy_dtype_for,
)
from dlbb_tpu.analysis.hlo_parse import parse_module, resolve_producers
from dlbb_tpu.analysis.numerics_audit import (
    LOW_PRECISION_ACCUM_FLOOR,
    accumulation_error_bounds,
    analyze_numerics,
    numerics_metrics,
    unit_roundoff,
    write_numerics_artifacts,
)
from dlbb_tpu.analysis.numerics_shadow import (
    ShadowCase,
    run_shadow,
    seeded_reduction_hlo,
    write_shadow_report,
)

FIXTURE_DIR = Path(__file__).parent / "data"
FUSED_SCAN_FIXTURE = FIXTURE_DIR / "decode_fused_k4_dp_tp.hlo.txt.gz"


def _rules(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# error-bound model
# ---------------------------------------------------------------------------


def test_unit_roundoff_table():
    assert unit_roundoff("f32") == 2.0 ** -24
    assert unit_roundoff("bf16") == 2.0 ** -8
    assert unit_roundoff("f16") == 2.0 ** -11
    assert unit_roundoff("f64") == 2.0 ** -53
    assert unit_roundoff("s8") is None


def test_accumulation_error_bounds():
    seq, tree = accumulation_error_bounds(4096, "bf16")
    assert seq == 4095 * 2.0 ** -8
    assert tree == 12 * 2.0 ** -8  # ceil(log2 4096) = 12
    assert accumulation_error_bounds(1, "bf16") == (0.0, 0.0)
    # a bf16 accumulator over 4k elements is total loss; f32 is not
    assert seq > 1.0
    assert accumulation_error_bounds(4096, "f32")[0] < 2.5e-4


# ---------------------------------------------------------------------------
# seeded-violation fixtures (hand-written HLO, one per rule)
# ---------------------------------------------------------------------------


def test_seeded_low_precision_accumulation():
    findings, meta = analyze_numerics(
        seeded_reduction_hlo(4096, "bf16"), TargetExpectation(),
        "seed::bf16-reduce")
    assert _rules(findings) == ["low-precision-accumulation"]
    d = findings[0].details
    assert d["elements"] == 4096
    assert d["bound_sequential"] == 4095 * 2.0 ** -8
    assert meta["numerics_low_precision_sites"] == 1
    assert meta["numerics_max_rel_error_bound"] == d["bound_tree"]
    # the same shape accumulated in f32 is clean
    clean, _ = analyze_numerics(
        seeded_reduction_hlo(4096, "f32"), TargetExpectation(),
        "seed::f32-reduce")
    assert clean == []


SEEDED_UPCAST = """\
HloModule seeded_upcast, entry_computation_layout={(f32[4096]{0})->f32[4096]{0}}

%add_f32 (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %add = f32[] add(f32[] %a, f32[] %b)
}

ENTRY %main (x: f32[4096]) -> f32[4096] {
  %x = f32[4096]{0} parameter(0)
  ROOT %ar = f32[4096]{0} all-reduce(f32[4096]{0} %x), channel_id=1, replica_groups={{0,1,2,3,4,5,6,7}}, use_global_device_ids=true, to_apply=%add_f32
}
"""

SEEDED_WHILE_UPCAST = """\
HloModule seeded_while_upcast, entry_computation_layout={(f32[2048]{0})->(s32[], f32[2048]{0})}

%body (p: (s32[], f32[2048])) -> (s32[], f32[2048]) {
  %p = (s32[], f32[2048]{0}) parameter(0)
  %i = s32[] get-tuple-element((s32[], f32[2048]{0}) %p), index=0
  %one = s32[] constant(1)
  %ip = s32[] add(s32[] %i, s32[] %one)
  %v = f32[2048]{0} get-tuple-element((s32[], f32[2048]{0}) %p), index=1
  ROOT %t = (s32[], f32[2048]{0}) tuple(s32[] %ip, f32[2048]{0} %v)
}

%cond (p: (s32[], f32[2048])) -> pred[] {
  %p = (s32[], f32[2048]{0}) parameter(0)
  %i = s32[] get-tuple-element((s32[], f32[2048]{0}) %p), index=0
  %n = s32[] constant(8)
  ROOT %lt = pred[] compare(s32[] %i, s32[] %n), direction=LT
}

ENTRY %main (x: f32[2048]) -> (s32[], f32[2048]) {
  %x = f32[2048]{0} parameter(0)
  %zero = s32[] constant(0)
  %init = (s32[], f32[2048]{0}) tuple(s32[] %zero, f32[2048]{0} %x)
  ROOT %loop = (s32[], f32[2048]{0}) while((s32[], f32[2048]{0}) %init), condition=%cond, body=%body
}
"""


def test_seeded_silent_upcast_collective():
    findings, _ = analyze_numerics(
        SEEDED_UPCAST, TargetExpectation(policy_dtype="bf16"),
        "seed::upcast", num_devices=8)
    assert _rules(findings) == ["silent-upcast"]
    # half the f32 payload is bytes the bf16 plan never priced
    assert findings[0].details["extra_bytes"] == 4096 * 4 // 2
    # without a declared low policy the same module is legal f32 math
    clean, _ = analyze_numerics(
        SEEDED_UPCAST, TargetExpectation(policy_dtype="f32"),
        "seed::upcast-f32", num_devices=8)
    assert clean == []


def test_seeded_silent_upcast_while_carry():
    findings, _ = analyze_numerics(
        SEEDED_WHILE_UPCAST, TargetExpectation(policy_dtype="bf16"),
        "seed::while-upcast", peak_live_bytes=32_768)
    rules = _rules(findings)
    assert "silent-upcast" in rules
    carry = [f for f in findings if "while-carry" in f.message][0]
    assert carry.details["extra_bytes"] == 2048 * 4 // 2
    assert carry.details["peak_live_bytes"] == 32_768


SEEDED_ROUNDTRIP = """\
HloModule seeded_roundtrip, entry_computation_layout={(s8[1024]{0})->s8[1024]{0}}

ENTRY %main (x: s8[1024]) -> s8[1024] {
  %x = s8[1024]{0} parameter(0)
  %dq = f32[1024]{0} convert(s8[1024]{0} %x)
  %scale = f32[] constant(0.5)
  %bscale = f32[1024]{0} broadcast(f32[] %scale), dimensions={}
  %scaled = f32[1024]{0} multiply(f32[1024]{0} %dq, f32[1024]{0} %bscale)
  ROOT %q = s8[1024]{0} convert(f32[1024]{0} %scaled)
}
"""

# a masking select (other side a broadcast constant fill) is
# layout-only: the trace walks through it and the roundtrip still trips
SEEDED_MASKED_ROUNDTRIP = """\
HloModule seeded_masked_roundtrip, entry_computation_layout={(s8[1024]{0}, pred[1024]{0})->s8[1024]{0}}

ENTRY %main (x: s8[1024], m: pred[1024]) -> s8[1024] {
  %x = s8[1024]{0} parameter(0)
  %m = pred[1024]{0} parameter(1)
  %dq = f32[1024]{0} convert(s8[1024]{0} %x)
  %zero = f32[] constant(0)
  %fill = f32[1024]{0} broadcast(f32[] %zero), dimensions={}
  %masked = f32[1024]{0} select(pred[1024]{0} %m, f32[1024]{0} %fill, f32[1024]{0} %dq)
  ROOT %q = s8[1024]{0} convert(f32[1024]{0} %masked)
}
"""

# the int8 decode-append shape: dequantise -> select MERGING a live
# data stream (the fresh token's K/V) -> requantise.  The merge is real
# work, so the trace aborts and no finding is emitted.
SEEDED_MERGE_HOP = """\
HloModule seeded_merge_hop, entry_computation_layout={(s8[1024]{0}, f32[1024]{0}, pred[1024]{0})->s8[1024]{0}}

ENTRY %main (x: s8[1024], fresh: f32[1024], m: pred[1024]) -> s8[1024] {
  %x = s8[1024]{0} parameter(0)
  %fresh = f32[1024]{0} parameter(1)
  %m = pred[1024]{0} parameter(2)
  %dq = f32[1024]{0} convert(s8[1024]{0} %x)
  %merged = f32[1024]{0} select(pred[1024]{0} %m, f32[1024]{0} %fresh, f32[1024]{0} %dq)
  ROOT %q = s8[1024]{0} convert(f32[1024]{0} %merged)
}
"""

# the legitimate ring hop: dequantise -> ACCUMULATE (equal-size add)
# -> requantise.  The add aborts the trace, so no finding.
SEEDED_RING_HOP = """\
HloModule seeded_ring_hop, entry_computation_layout={(s8[1024]{0}, f32[1024]{0})->s8[1024]{0}}

ENTRY %main (x: s8[1024], acc: f32[1024]) -> s8[1024] {
  %x = s8[1024]{0} parameter(0)
  %acc = f32[1024]{0} parameter(1)
  %dq = f32[1024]{0} convert(s8[1024]{0} %x)
  %sum = f32[1024]{0} add(f32[1024]{0} %dq, f32[1024]{0} %acc)
  ROOT %q = s8[1024]{0} convert(f32[1024]{0} %sum)
}
"""


def test_seeded_quantise_roundtrip():
    findings, _ = analyze_numerics(
        SEEDED_ROUNDTRIP, TargetExpectation(), "seed::roundtrip")
    assert _rules(findings) == ["quantise-roundtrip"]
    assert findings[0].details["wire_dtype"] == "s8"


def test_ring_hop_requantise_is_legitimate():
    findings, _ = analyze_numerics(
        SEEDED_RING_HOP, TargetExpectation(), "seed::ring-hop")
    assert findings == []


def test_masking_select_roundtrip_still_trips():
    findings, _ = analyze_numerics(
        SEEDED_MASKED_ROUNDTRIP, TargetExpectation(), "seed::masked")
    assert _rules(findings) == ["quantise-roundtrip"]


def test_merge_select_requantise_is_legitimate():
    """The int8 decode-append idiom: requantising after a select that
    writes a live data stream over the dequantised window is real work,
    not a no-op roundtrip."""
    findings, _ = analyze_numerics(
        SEEDED_MERGE_HOP, TargetExpectation(), "seed::merge-hop")
    assert findings == []


SEEDED_CHURN = """\
HloModule seeded_churn, entry_computation_layout={(bf16[256]{0})->bf16[256]{0}}

%fused_up (p0: bf16[256]) -> f32[256] {
  %p0 = bf16[256]{0} parameter(0)
  ROOT %up = f32[256]{0} convert(bf16[256]{0} %p0)
}

ENTRY %main (x: bf16[256]) -> bf16[256] {
  %x = bf16[256]{0} parameter(0)
  %fus = f32[256]{0} fusion(bf16[256]{0} %x), kind=kLoop, calls=%fused_up
  %idn = bf16[256]{0} convert(bf16[256]{0} %x)
  ROOT %down = bf16[256]{0} convert(f32[256]{0} %fus)
}
"""

# the intentional precision clamp (f32 -> bf16 -> f32, NARROWING middle)
# must never be churn: allreduce_q's fusions clamp exactly like this
SEEDED_CLAMP = """\
HloModule seeded_clamp, entry_computation_layout={(f32[256]{0})->f32[256]{0}}

ENTRY %main (x: f32[256]) -> f32[256] {
  %x = f32[256]{0} parameter(0)
  %down = bf16[256]{0} convert(f32[256]{0} %x)
  ROOT %up = f32[256]{0} convert(bf16[256]{0} %down)
}
"""


def test_seeded_convert_churn_crosses_fusion_boundary():
    """The widening-roundtrip leg only fires if resolve_producers can
    descend into the fusion body where the inner convert lives — the
    satellite-2 fusion-coverage regression, pinned on a seeded module."""
    findings, meta = analyze_numerics(
        SEEDED_CHURN, TargetExpectation(), "seed::churn")
    assert sorted(_rules(findings)) == ["convert-churn", "convert-churn"]
    widening = [f for f in findings if "chain" in f.details
                and len(f.details["chain"]) == 3][0]
    assert widening.details["chain"] == ["bf16", "f32", "bf16"]
    assert "fused_up" in widening.details["intermediate"]
    assert meta["numerics_convert_count"] >= 3


def test_narrowing_clamp_is_not_churn():
    findings, _ = analyze_numerics(
        SEEDED_CLAMP, TargetExpectation(), "seed::clamp")
    assert findings == []


def test_seeded_nondeterministic_reduction():
    # counted in meta always; a finding only under the bitwise claim
    findings, meta = analyze_numerics(
        SEEDED_UPCAST, TargetExpectation(), "seed::nondet")
    assert findings == []
    assert meta["nondeterministic_reductions"] == 1
    findings, _ = analyze_numerics(
        SEEDED_UPCAST,
        TargetExpectation(expect_bitwise_reproducible=True),
        "seed::nondet-claimed")
    assert _rules(findings) == ["nondeterministic-reduction"]
    assert findings[0].details["group_size"] == 8


SEEDED_F64 = """\
HloModule seeded_f64, entry_computation_layout={(f64[512]{0})->f64[512]{0}}

ENTRY %main (x: f64[512]) -> f64[512] {
  %x = f64[512]{0} parameter(0)
  ROOT %y = f64[512]{0} add(f64[512]{0} %x, f64[512]{0} %x)
}
"""

SEEDED_BELOW_POLICY = """\
HloModule seeded_below_policy, entry_computation_layout={(bf16[1024]{0})->bf16[]}

%add_bf16 (a: bf16[], b: bf16[]) -> bf16[] {
  %a = bf16[] parameter(0)
  %b = bf16[] parameter(1)
  ROOT %add = bf16[] add(bf16[] %a, bf16[] %b)
}

ENTRY %main (x: bf16[1024]) -> bf16[] {
  %x = bf16[1024]{0} parameter(0)
  %zero = bf16[] constant(0)
  %win = bf16[64]{0} slice(bf16[1024]{0} %x), slice={[0:64]}
  ROOT %reduce = bf16[] reduce(bf16[64]{0} %win, bf16[] %zero), dimensions={0}, to_apply=%add_bf16
}
"""


def test_seeded_policy_conformance():
    findings, _ = analyze_numerics(
        SEEDED_F64, TargetExpectation(policy_dtype="f32"), "seed::f64")
    assert _rules(findings) == ["policy-conformance"]
    assert "f64" in findings[0].message

    findings, _ = analyze_numerics(
        SEEDED_BELOW_POLICY, TargetExpectation(policy_dtype="f32"),
        "seed::below-policy")
    assert _rules(findings) == ["policy-conformance", "policy-conformance"]
    msgs = " ".join(f.message for f in findings)
    assert "parameter" in msgs and "accumulator" in msgs
    # under a matching bf16 policy the same module is conformant (the
    # short n=64 reduction sits under the accumulation floor too)
    assert 64 < LOW_PRECISION_ACCUM_FLOOR
    clean, _ = analyze_numerics(
        SEEDED_BELOW_POLICY, TargetExpectation(policy_dtype="bf16"),
        "seed::bf16-ok")
    assert clean == []


def test_policy_dtype_for():
    assert policy_dtype_for("float32") == "f32"
    assert policy_dtype_for("bfloat16") == "bf16"
    assert policy_dtype_for("float16") == "f16"
    with pytest.raises(ValueError):
        policy_dtype_for("float8_e4m3")


# ---------------------------------------------------------------------------
# fusion-computation coverage (satellite 2) on the captured fused scan
# ---------------------------------------------------------------------------


def test_fused_scan_fixture_fusion_bodies_are_visited():
    """Regression on a captured decode_fused compile: graph walks must
    see instructions inside fusion bodies (where the dot accumulators
    actually live), and producer resolution must cross the boundary."""
    module = parse_module(
        gzip.open(FUSED_SCAN_FIXTURE, "rt").read())
    entry = module.entry_computation()
    assert entry is not None
    fusion_comps = {
        callee for _c, i in module.all_instructions()
        for role, callee in i.called if role == "calls"
    }
    assert fusion_comps, "captured module must contain fusions"
    visited = {c.name for c, _i in module.all_instructions()}
    assert fusion_comps <= visited, (
        "all_instructions() skipped fusion bodies: "
        f"{sorted(fusion_comps - visited)[:5]}")
    # at least one fusion body does real arithmetic the walk can reach
    fused_arith = [
        (c, i) for c, i in module.all_instructions()
        if c.name in fusion_comps and i.opcode in ("add", "multiply",
                                                   "convert", "dot")
    ]
    assert fused_arith
    # producer resolution crosses a fusion call site: resolving a fusion
    # result must land on the body root, not dead-end at the call
    for comp, instr in module.all_instructions():
        if instr.opcode == "fusion" and comp.name == entry.name:
            producers = resolve_producers(module, comp, instr.name)
            assert any(c.name in fusion_comps for c, _p in producers), (
                f"%{instr.name} did not resolve into its body")
            break
    else:
        pytest.fail("no fusion instruction in the entry computation")


def test_fused_scan_fixture_numerics_meta():
    """The captured serving fast path: f32 policy-clean, with its dot
    reduction sites (inside the scan body) visible to the audit."""
    module = parse_module(gzip.open(FUSED_SCAN_FIXTURE, "rt").read())
    findings, meta = analyze_numerics(
        module, TargetExpectation(policy_dtype="f32"),
        "fixture::decode_fused", num_devices=8)
    assert findings == [], [f.render() for f in findings]
    assert meta["reduction_sites"] > 0
    assert meta["numerics_low_precision_sites"] == 0
    assert 0 < meta["numerics_max_rel_error_bound"] < 1e-5  # f32 bounds


# ---------------------------------------------------------------------------
# real targets stay clean (the smoke subset; the full 39-target surface
# is gated by `cli analyze numerics` in scripts/run_static_analysis.sh)
# ---------------------------------------------------------------------------


@pytest.mark.numerics_smoke
def test_real_targets_audit_clean(devices):
    from dlbb_tpu.analysis.hlo_audit import audit_target, default_targets

    want = {
        "comm/ops.py::allreduce_q[int8]",
        "train/loop.py::train_step[ddp,compressed=int8]",
        "serve/engine.py::decode_fused[k4,dp,tp]",
    }
    targets = [t for t in default_targets() if t.name in want]
    assert len(targets) == len(want)
    for target in targets:
        findings, meta = audit_target(target, passes=("numerics",))
        assert findings == [], [f.render() for f in findings]
        num = meta["numerics"]
        assert num["numerics_low_precision_sites"] == 0
        # every fp dtype present is declared-policy or a wire format
        assert "f64" not in num["fp_dtypes"]


@pytest.mark.numerics_smoke
def test_seeded_fixture_drives_audit_to_findings(devices):
    """End-to-end: a target whose lowering carries a bf16 long reduction
    must exit with findings through the full audit_target path."""
    from dlbb_tpu.analysis.hlo_audit import AuditTarget, audit_target

    class _PreLowered:
        """Stand-in jit object returning fixed HLO text."""

        def __init__(self, text):
            self._text = text

        def lower(self, *args):
            return self

        def compile(self):
            return self

        def as_text(self):
            return self._text

    seeded = AuditTarget(
        name="seeded::bf16-reduction",
        build=lambda: (_PreLowered(seeded_reduction_hlo(2048, "bf16")), ()),
        expectation=TargetExpectation(policy_dtype="bf16"),
        min_devices=1,
    )
    findings, meta = audit_target(seeded, passes=("numerics",))
    assert "low-precision-accumulation" in _rules(findings)
    assert meta["numerics"]["numerics_low_precision_sites"] == 1


# ---------------------------------------------------------------------------
# baseline snapshot / diff gate on the numerics axis
# ---------------------------------------------------------------------------


_BASE = {
    "cost_model_version": "cm1", "tier": "cpu-sim",
    "critical_path_us": 10.0, "comm_on_critical_path_us": 5.0,
    "comm_total_us": 6.0, "compute_total_us": 2.0,
    "overlap_efficiency": 0.5, "total_wire_bytes": 4096,
    "num_collectives": 4, "collective_kinds": {"all-reduce": 4},
    "peak_live_bytes": 100_000, "max_transient_bytes": 10_000,
    "numerics_low_precision_sites": 0, "numerics_convert_count": 40,
    "numerics_max_rel_error_bound": 4.0e-7,
}


def test_diff_fails_on_numerics_axis_alone(tmp_path):
    from dlbb_tpu.analysis.schedule_audit import (
        diff_baselines,
        snapshot_baselines,
    )

    snapshot_baselines({"t": _BASE}, tmp_path)
    ok = diff_baselines({"t": dict(_BASE)}, tmp_path)
    assert [f for f in ok if f.severity == "error"] == []

    # error bound drift beyond the 2x slack (e.g. an f32 -> f16 accum
    # downgrade moves it ~2^13x; shape jitter stays under 2x)
    drifted = dict(_BASE, numerics_max_rel_error_bound=1.0e-6)
    errors = [f.rule for f in diff_baselines({"t": drifted}, tmp_path)
              if f.severity == "error"]
    assert errors == ["numerics-error-regression"]

    churned = dict(_BASE, numerics_convert_count=60)
    errors = [f.rule for f in diff_baselines({"t": churned}, tmp_path)
              if f.severity == "error"]
    assert errors == ["convert-churn-regression"]

    # the zero-baseline axis gates at exactly zero growth — the ratio
    # gate would skip a falsy baseline, so this needs its own rule
    downgraded = dict(_BASE, numerics_low_precision_sites=1)
    errors = [f.rule for f in diff_baselines({"t": downgraded}, tmp_path)
              if f.severity == "error"]
    assert errors == ["new-low-precision-accumulation"]


def test_committed_baselines_carry_numerics_axis():
    from dlbb_tpu.analysis.schedule_audit import (
        DEFAULT_BASELINE_DIR,
        load_baselines,
    )

    baselines = load_baselines(DEFAULT_BASELINE_DIR)
    assert len(baselines) >= 30
    for name, base in baselines.items():
        assert base.get("numerics_low_precision_sites") == 0, name
        assert "numerics_convert_count" in base, name
        assert "numerics_max_rel_error_bound" in base, name


# ---------------------------------------------------------------------------
# fp64 shadow cross-check
# ---------------------------------------------------------------------------


@pytest.mark.numerics_smoke
def test_shadow_confirms_static_bounds(tmp_path, devices):
    cases = (
        ShadowCase("bf16-sequential-2048", "bf16", 2048, "sequential"),
        ShadowCase("bf16-tree-2048", "bf16", 2048, "tree"),
        ShadowCase("f32-control-2048", "f32", 2048, "sequential",
                   expect_flagged=False),
    )
    report = run_shadow(cases, seed=7)
    assert report["refuted"] == 0
    assert report["confirmed"] == len(cases)
    by_name = {r["case"]: r for r in report["cases"]}
    flagged = by_name["bf16-sequential-2048"]
    assert flagged["static_flagged"] is True
    assert 0 < flagged["measured_rel_error"] <= flagged["gating_bound"]
    control = by_name["f32-control-2048"]
    assert control["static_flagged"] is False
    # the control's error is orders of magnitude under the bf16 bound
    assert control["measured_rel_error"] < flagged["gating_bound"] * 1e-3

    path = write_shadow_report(report, tmp_path)
    data = json.loads(path.read_text())
    assert data["schema"] == "dlbb_numerics_shadow_v1"
    assert data["confirmed"] == len(cases)


def test_committed_shadow_report():
    """The committed cross-check artifact: zero refuted, at least one
    statically flagged accumulation site confirmed within its bound."""
    path = Path("stats/analysis/numerics/shadow_report.json")
    data = json.loads(path.read_text())
    assert data["schema"] == "dlbb_numerics_shadow_v1"
    assert data["refuted"] == 0
    confirmed_flagged = [
        r for r in data["cases"]
        if r["confirmed"] and r["static_flagged"]
        and r["measured_rel_error"] <= r["gating_bound"]
    ]
    assert confirmed_flagged, "no flagged site confirmed within bound"


# ---------------------------------------------------------------------------
# observability surface
# ---------------------------------------------------------------------------


def test_numerics_metrics_and_artifacts(tmp_path):
    numerics = {
        "comm/ops.py::allreduce": {
            "numerics_max_rel_error_bound": 0.0,
            "numerics_low_precision_sites": 0,
            "numerics_convert_count": 0},
        "serve/engine.py::decode_fused[k4,dp,tp]": {
            "numerics_max_rel_error_bound": 3.58e-7,
            "numerics_low_precision_sites": 0,
            "numerics_convert_count": 6},
    }
    text = numerics_metrics(numerics).to_prometheus()
    assert ('dlbb_analysis_numerics_convert_count{target="serve/'
            'engine.py::decode_fused[k4,dp,tp]"} 6') in text
    assert "dlbb_analysis_numerics_targets 2" in text

    (tmp_path / "metrics.prom").write_text(
        "# TYPE dlbb_sweep_wall_seconds gauge\n"
        "dlbb_sweep_wall_seconds 1.5\n")
    (tmp_path / "sweep_manifest.json").write_text(
        json.dumps({"schema": "dlbb_sweep_manifest_v1", "kind": "1d"}))
    write_numerics_artifacts(numerics, tmp_path)
    prom = (tmp_path / "metrics.prom").read_text()
    assert "dlbb_sweep_wall_seconds 1.5" in prom
    assert "dlbb_analysis_numerics_max_rel_error_bound" in prom
    manifest = json.loads((tmp_path / "sweep_manifest.json").read_text())
    assert manifest["kind"] == "1d"  # merged, not clobbered
    audit = manifest["numerics_audit"]
    assert audit["targets_audited"] == 2
    report = json.loads((tmp_path / "numerics_audit.json").read_text())
    assert report["schema"] == "dlbb_numerics_audit_v1"


def test_per_pass_finding_count_gauges():
    """Satellite: obs/export.analysis_metrics seeds a gauge sample for
    every pass/severity (zeros included — a silently dropped gate must
    stay visible) and counts real findings per pass."""
    from dlbb_tpu.analysis.findings import AnalysisReport, Finding
    from dlbb_tpu.obs.export import analysis_metrics

    report = AnalysisReport()
    report.suppressed = 3
    report.findings.append(Finding(
        pass_name="numerics", rule="convert-churn", severity="error",
        target="t", message="m"))
    report.findings.append(Finding(
        pass_name="lint", rule="jit-in-loop", severity="warning",
        target="f.py", message="m"))
    text = analysis_metrics(report).to_prometheus()
    assert ('dlbb_analysis_findings{pass="numerics",severity="error"} 1'
            in text)
    assert ('dlbb_analysis_findings{pass="lint",severity="warning"} 1'
            in text)
    # clean passes still export a zero sample
    assert ('dlbb_analysis_findings{pass="memory",severity="error"} 0'
            in text)
    assert "dlbb_analysis_suppressed 3" in text


def test_numerics_no_targets_fails_closed(monkeypatch, tmp_path):
    """The PR-2 vacuous-run contract extends to the numerics pass: an
    empty target surface must exit 1, not read as a clean audit."""
    import dlbb_tpu.analysis.hlo_audit as hlo_audit
    from dlbb_tpu.analysis import run_analysis
    from dlbb_tpu.analysis.findings import EXIT_FINDINGS

    monkeypatch.setattr(hlo_audit, "default_targets", lambda: [])
    json_path = tmp_path / "report.json"
    rc = run_analysis(which="numerics", json_path=str(json_path))
    assert rc == EXIT_FINDINGS
    data = json.loads(json_path.read_text())
    assert [f["rule"] for f in data["findings"]] == ["no-targets-audited"]


# ---------------------------------------------------------------------------
# float64-literal-in-jit source lint
# ---------------------------------------------------------------------------


def _lint(source):
    from dlbb_tpu.analysis.source_lint import lint_source

    findings, suppressed = lint_source(source, "dlbb_tpu/fake.py")
    return [f for f in findings if f.rule == "float64-literal-in-jit"], \
        suppressed


def test_float64_in_jitted_function_flagged():
    findings, _ = _lint(
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return x + np.float64(1.0)\n"
    )
    assert len(findings) == 1
    assert "np.float64" in findings[0].message


def test_float64_astype_and_dtype_kwargs_flagged():
    findings, _ = _lint(
        "import jax, functools\n"
        "import numpy as np\n"
        "import jax.numpy as jnp\n"
        "@functools.partial(jax.jit, donate_argnums=(0,))\n"
        "def step(x):\n"
        "    a = x.astype(np.float64)\n"
        "    b = jnp.zeros((4,), dtype='float64')\n"
        "    c = np.ones((4,))\n"
        "    return a, b, c\n"
    )
    assert len(findings) == 3
    descs = " ".join(f.details["expression"] for f in findings)
    assert ".astype" in descs and "dtype=" in descs and "np.ones" in descs


def test_float64_outside_jit_is_clean_and_suppression_works():
    # host-side float64 statistics are legitimate
    clean, _ = _lint(
        "import numpy as np\n"
        "def summarise(xs):\n"
        "    return np.float64(sum(xs)) / len(xs)\n"
    )
    assert clean == []
    # jit picked up by name: flagged, then suppressed inline
    flagged, _ = _lint(
        "import jax\n"
        "import numpy as np\n"
        "def step(x):\n"
        "    return x.astype(np.float64)\n"
        "step = jax.jit(step)\n"
    )
    assert len(flagged) == 1
    suppressed_findings, hits = _lint(
        "import jax\n"
        "import numpy as np\n"
        "def step(x):\n"
        "    return x.astype(np.float64)  "
        "# comm-lint: disable=float64-literal-in-jit\n"
        "step = jax.jit(step)\n"
    )
    assert suppressed_findings == []
    assert hits == 1


def test_float64_in_timed_region_flagged():
    findings, _ = _lint(
        "import time\n"
        "import numpy as np\n"
        "def measure(fn):\n"
        "    t0 = time.perf_counter()\n"
        "    out = np.asarray([1.5, 2.5])\n"
        "    dt = time.perf_counter() - t0\n"
        "    return out, dt\n"
    )
    assert len(findings) == 1
    assert "float literals" in findings[0].details["expression"]
