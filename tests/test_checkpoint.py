"""Checkpoint / resume tests (orbax-backed; no reference analogue —
SURVEY §5.4 records the reference has none)."""

import jax
import numpy as np
import optax
import pytest

from dlbb_tpu.comm.mesh import MeshSpec, build_mesh
from dlbb_tpu.compat import PARTIAL_AUTO_SHARD_MAP
from dlbb_tpu.models.configs import ModelConfig
from dlbb_tpu.models.transformer import init_params
from dlbb_tpu.train.checkpoint import (
    CheckpointConfig,
    Checkpointer,
    latest_step,
)
from dlbb_tpu.train.loop import make_train_step, run_train

TINY = ModelConfig(hidden_size=32, num_layers=2, num_heads=4,
                   ffn_intermediate=64, attention="full", dtype="float32")


def _setup(zero1=False):
    mesh = build_mesh(MeshSpec.grid((4, 2), ("dp", "tp")))
    params = init_params(TINY, jax.random.key(0))
    jit_step, state = make_train_step(
        TINY, mesh, optax.adam(1e-2), params, zero1=zero1
    )
    x = jax.random.normal(jax.random.key(1), (8, 16, 32))
    y = jax.random.normal(jax.random.key(2), (8, 16, 32))
    return jit_step, state, x, y


@pytest.mark.parametrize("zero1", [False, True])
def test_save_restore_roundtrip(devices, tmp_path, zero1):
    """Restored state is bit-identical (values + shardings) to the saved
    state — including the dp-sharded ZeRO-1 optimizer state."""
    jit_step, state, x, y = _setup(zero1)
    for _ in range(3):
        state, _ = jit_step(state, x, y)

    with Checkpointer(CheckpointConfig(str(tmp_path / "ck"))) as ckpt:
        assert ckpt.maybe_save(state, force=True)
        restored = ckpt.restore(state)

    assert int(restored.step) == 3
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert a.sharding == b.sharding, (a.sharding, b.sharding)


@pytest.mark.skipif(
    not PARTIAL_AUTO_SHARD_MAP,
    reason="pp x ep mesh needs partial-auto shard_map, unsupported on "
           "this jaxlib (dlbb_tpu.compat.PARTIAL_AUTO_SHARD_MAP)",
)
def test_save_restore_pp_ep_mesh(devices, tmp_path):
    """Checkpointing preserves shardings on a pp x ep mesh too (MoE model
    with the layer stack sharded across pipeline stages and experts
    sharded over ep, ZeRO-3)."""
    mesh = build_mesh(MeshSpec.grid((2, 2, 2), ("dp", "pp", "ep")))
    moe = TINY.with_(num_experts=4, moe_top_k=2)
    params = init_params(moe, jax.random.key(0))
    jit_step, state = make_train_step(
        moe, mesh, optax.adam(1e-2), params, zero_stage=3,
    )
    x = jax.random.normal(jax.random.key(1), (8, 16, 32))
    y = jax.random.normal(jax.random.key(2), (8, 16, 32))
    state, _ = jit_step(state, x, y)

    with Checkpointer(CheckpointConfig(str(tmp_path / "ck"))) as ckpt:
        assert ckpt.maybe_save(state, force=True)
        restored = ckpt.restore(state)

    assert int(restored.step) == 1
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert a.sharding == b.sharding, (a.sharding, b.sharding)


def test_resume_continues_trajectory(devices, tmp_path):
    """save at step k, keep training to step n; a fresh state restored from
    the checkpoint and stepped n-k more times lands on the same losses."""
    jit_step, state, x, y = _setup()
    for _ in range(2):
        state, _ = jit_step(state, x, y)

    with Checkpointer(CheckpointConfig(str(tmp_path / "ck"))) as ckpt:
        ckpt.maybe_save(state, force=True)

        ref_losses = []
        for _ in range(3):
            state, loss = jit_step(state, x, y)
            ref_losses.append(float(loss))

        # fresh (wrong) state, resumed from the checkpoint
        _, fresh, _, _ = _setup()
        resumed = ckpt.restore_or(fresh)
    assert int(resumed.step) == 2
    res_losses = []
    for _ in range(3):
        resumed, loss = jit_step(resumed, x, y)
        res_losses.append(float(loss))
    np.testing.assert_allclose(res_losses, ref_losses, rtol=1e-5)


def test_restore_or_passthrough(devices, tmp_path):
    """No checkpoint on disk -> restore_or returns the input unchanged."""
    _, state, _, _ = _setup()
    with Checkpointer(CheckpointConfig(str(tmp_path / "empty"))) as ckpt:
        out = ckpt.restore_or(state)
    assert out is state
    assert latest_step(str(tmp_path / "missing")) is None


def test_retention_policy(devices, tmp_path):
    """max_to_keep prunes old steps; save_interval_steps skips saves."""
    jit_step, state, x, y = _setup()
    cfg = CheckpointConfig(
        str(tmp_path / "ck"), save_interval_steps=2, max_to_keep=2
    )
    with Checkpointer(cfg) as ckpt:
        for _ in range(6):
            state, _ = jit_step(state, x, y)
            ckpt.maybe_save(state)
        ckpt.wait()
        assert ckpt.latest_step() == 6
        steps = sorted(ckpt._mgr.all_steps())
    assert steps == [4, 6], steps  # interval=2 -> 2,4,6; keep last 2


def test_run_train_resume_via_config(devices, tmp_path):
    """Config-driven flow: a second run_train with the same checkpoint dir
    resumes where the first left off."""
    config = {
        "experiment": {"name": "ck_smoke"},
        "model": {
            "hidden_size": 32, "num_layers": 2, "num_heads": 4,
            "ffn_intermediate": 64, "attention": "full", "dtype": "float32",
        },
        "parallelism": {"world_size": 2, "data_parallel": 4},
        "input": {"batch_size": 8, "sequence_length": 16, "seed": 42},
        "execution": {"warmup_iterations": 1, "benchmark_iterations": 3},
        "training": {
            "learning_rate": 1e-2,
            "checkpoint": {"directory": str(tmp_path / "ck")},
        },
    }
    r1 = run_train(config, verbose=False)
    assert r1["resumed_from_step"] is None
    assert r1["final_step"] == 4  # 1 warmup + 3 measured

    r2 = run_train(config, verbose=False)
    assert r2["resumed_from_step"] == 4
    assert r2["final_step"] == 8
    # resumed run continues the optimisation, not restarts it
    assert r2["losses"][0] < r1["losses"][0]


def test_checkpoint_disabled_no_restore(devices, tmp_path):
    """enabled: false must disable the whole subsystem — a stale checkpoint
    in the directory is neither restored nor overwritten."""
    config = {
        "experiment": {"name": "ck_disabled"},
        "model": {
            "hidden_size": 32, "num_layers": 2, "num_heads": 4,
            "ffn_intermediate": 64, "attention": "full", "dtype": "float32",
        },
        "parallelism": {"world_size": 2, "data_parallel": 4},
        "input": {"batch_size": 8, "sequence_length": 16, "seed": 42},
        "execution": {"warmup_iterations": 1, "benchmark_iterations": 2},
        "training": {
            "learning_rate": 1e-2,
            "checkpoint": {"directory": str(tmp_path / "ck")},
        },
    }
    r1 = run_train(config, verbose=False)
    assert r1["final_step"] == 3

    config["training"]["checkpoint"]["enabled"] = False
    r2 = run_train(config, verbose=False)
    assert r2["resumed_from_step"] is None
    assert r2["final_step"] == 3  # fresh run, not resumed
    assert latest_step(str(tmp_path / "ck")) == 3  # stale ckpt untouched
