"""Optimizer/schedule construction + gradient accumulation
(capability extension — the reference trains only with fixed-LR Adam,
``test/ccl.py:74-89``)."""

import numpy as np
import pytest

from dlbb_tpu.train.loop import run_train
from dlbb_tpu.train.optim import build_optimizer, build_schedule


def _config(**training_over):
    training = {"learning_rate": 1e-2}
    training.update(training_over)
    return {
        "experiment": {"name": "train_optim"},
        "model": {
            "hidden_size": 32, "num_layers": 2, "num_heads": 4,
            "ffn_intermediate": 64, "attention": "full", "dtype": "float32",
        },
        "parallelism": {"world_size": 2, "data_parallel": 4},
        "input": {"batch_size": 8, "sequence_length": 16, "seed": 42},
        "execution": {"warmup_iterations": 1, "benchmark_iterations": 6},
        "training": training,
    }


def test_grad_accum_matches_full_batch(devices):
    """Mean-of-micro-step gradients == full-batch gradient for a mean
    loss: identical optimisation trajectory."""
    r_full = run_train(_config(), verbose=False)
    r_accum = run_train(_config(gradient_accumulation=4), verbose=False)
    assert r_accum["gradient_accumulation"] == 4
    np.testing.assert_allclose(
        r_full["losses"], r_accum["losses"], rtol=1e-4, atol=1e-5
    )


def test_grad_accum_indivisible_rejected(devices):
    with pytest.raises(ValueError, match="not divisible"):
        run_train(_config(gradient_accumulation=3), verbose=False)


def test_grad_accum_dp_reshard_warns(devices):
    """A micro-batch smaller than dp is legal (GSPMD reshards, numerics
    exact) but surfaced as a layout-churn warning, not an error."""
    with pytest.warns(UserWarning, match="not divisible by dp"):
        run_train(_config(gradient_accumulation=4), verbose=False)


def test_grad_accum_dp_shardmap_attention_rejected(devices):
    """shard_map attention modes partition the batch over dp and cannot
    reshard a too-small micro-batch: clear ValueError, not a cryptic
    shard_map trace error."""
    cfg = _config(gradient_accumulation=4)
    cfg["model"]["attention"] = "ring"
    cfg["parallelism"] = {"world_size": 1, "data_parallel": 4,
                          "sequence_parallel": 2}
    with pytest.raises(ValueError, match="cannot reshard"):
        run_train(cfg, verbose=False)


def test_pipeline_grad_accum_microbatch_validated(devices):
    """Training validates the pipeline microbatch schedule against the
    accumulation micro-step batch (batch/grad_accum) up front, instead of
    failing at trace time inside the micro-step — while the shared plan
    (also used by forward-only harnesses) keeps validating the full batch."""
    from dlbb_tpu.models.configs import ModelConfig
    from dlbb_tpu.parallel.plan import ParallelismPlan

    cfg = _config(gradient_accumulation=4)
    cfg["parallelism"] = {"world_size": 1, "pipeline_parallel": 2,
                          "num_microbatches": 4}
    # the forward-only plan is untouched by the training-only grad_accum
    # key: 4 microbatches divide the full batch of 8
    model_cfg = ModelConfig.from_dict(cfg["model"])
    plan = ParallelismPlan.from_config(cfg, model_cfg)
    assert plan.num_microbatches == 4
    # but training micro-steps 8/4 = 2 rows, which 4 microbatches cannot
    # divide — rejected before any compile
    with pytest.raises(ValueError, match="not divisible"):
        run_train(cfg, verbose=False)


@pytest.mark.parametrize("training", [
    {"optimizer": "adamw", "weight_decay": 0.01},
    {"optimizer": "sgd", "momentum": 0.9, "learning_rate": 0.05},
    {"optimizer": "adafactor", "learning_rate": 0.05},
    {"schedule": "warmup_cosine", "warmup_steps": 2, "decay_steps": 20},
])
def test_optimizer_variants_train(devices, training):
    result = run_train(_config(**training), verbose=False)
    losses = result["losses"]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses


@pytest.mark.parametrize("stage", [1, 3])
def test_adafactor_zero_stages(devices, stage):
    """Regression: adafactor's v_row/v_col/v subtrees mirror the params'
    treedef with lower-rank factored statistics; opt_state_specs must not
    assign them the params' 2-D PartitionSpecs (crashed device_put)."""
    result = run_train(_config(optimizer="adafactor", learning_rate=0.05),
                       zero_stage=stage, verbose=False)
    losses = result["losses"]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses


def test_bf16_moments_adam_matches_fp32(devices):
    """Memory-reduced Adam (training.moments_dtype=bfloat16 — the option
    that fits the reference's optimizer on the 16 GiB v5e at 1B/b8/s512):
    the optimisation trajectory must track fp32-moments Adam within bf16
    rounding tolerance, and the state must actually be stored in bf16."""
    import jax
    import jax.numpy as jnp

    from dlbb_tpu.train.optim import cast_moments

    r32 = run_train(_config(optimizer="adam"), verbose=False)
    r16 = run_train(_config(optimizer="adam", moments_dtype="bfloat16"),
                    verbose=False)
    assert r16["moments_dtype"] == "bfloat16"
    assert r32["moments_dtype"] is None
    np.testing.assert_allclose(r16["losses"], r32["losses"],
                               rtol=2e-2, atol=1e-3)

    import optax

    opt = cast_moments(optax.adam(1e-3), jnp.bfloat16)
    params = {"w": jnp.ones((4, 4), jnp.float32)}
    state = opt.init(params)
    float_dtypes = {
        x.dtype for x in jax.tree.leaves(state)
        if jnp.issubdtype(x.dtype, jnp.floating)
    }
    assert float_dtypes == {jnp.dtype(jnp.bfloat16)}
    grads = {"w": jnp.full((4, 4), 0.5, jnp.float32)}
    updates, state2 = opt.update(grads, state, params)
    # updates are applied to fp32 params — they must come out fp32
    assert updates["w"].dtype == jnp.float32
    float_dtypes2 = {
        x.dtype for x in jax.tree.leaves(state2)
        if jnp.issubdtype(x.dtype, jnp.floating)
    }
    assert float_dtypes2 == {jnp.dtype(jnp.bfloat16)}


def test_fp16_moments_roundtrip(devices):
    """float16 moments_dtype round-trip (only bf16 was exercised before).

    SGD momentum state lives at gradient scale — comfortably inside
    fp16's exponent range — so its fp16-moments trajectory must track
    fp32 within rounding.  Adam is the documented exception: early-step
    ``nu`` values ((1-beta2) * grad^2 ~ 1e-7) sit BELOW fp16's 6e-5
    min-normal, so fp16 Adam moments degrade by construction (bf16, with
    fp32's exponent range, is the memory-reduced-Adam dtype); the pin
    here is that it still runs finite and stores fp16, not that it
    matches."""
    import jax
    import jax.numpy as jnp
    import optax

    from dlbb_tpu.train.optim import cast_moments

    sgd = dict(optimizer="sgd", momentum=0.9, learning_rate=0.05)
    r32 = run_train(_config(**sgd), verbose=False)
    r16 = run_train(_config(**sgd, moments_dtype="float16"), verbose=False)
    assert r16["moments_dtype"] == "float16"
    np.testing.assert_allclose(r16["losses"], r32["losses"],
                               rtol=2e-2, atol=1e-3)

    r16a = run_train(_config(optimizer="adam", moments_dtype="float16"),
                     verbose=False)
    assert all(np.isfinite(r16a["losses"]))

    opt = cast_moments(optax.adam(1e-3), jnp.float16)
    params = {"w": jnp.ones((4, 4), jnp.float32)}
    state = opt.init(params)
    grads = {"w": jnp.full((4, 4), 0.5, jnp.float32)}
    updates, state2 = opt.update(grads, state, params)
    assert updates["w"].dtype == jnp.float32
    float_dtypes = {
        x.dtype for x in jax.tree.leaves(state2)
        if jnp.issubdtype(x.dtype, jnp.floating)
    }
    assert float_dtypes == {jnp.dtype(jnp.float16)}


def test_cast_moments_skips_quantized_bookkeeping():
    """Integer and byte-wide quantised leaves (int8 counters, fp8 residual
    caches from compressed-gradient state) must pass through cast_moments
    untouched — float-casting a quantised payload corrupts it, and the
    fp32 upcast inside update must not widen its storage."""
    import jax
    import jax.numpy as jnp
    import optax

    from dlbb_tpu.train.optim import cast_moments

    book = {"q": jnp.arange(-4, 4, dtype=jnp.int8),
            "f8": jnp.asarray([0.5, -0.25], jnp.float8_e4m3fn),
            "count": jnp.zeros((), jnp.int32),
            "mu": jnp.zeros((4,), jnp.float32)}

    inner = optax.GradientTransformation(
        init=lambda params: jax.tree.map(jnp.copy, book),
        update=lambda u, s, params=None: (u, s),
    )
    opt = cast_moments(inner, jnp.bfloat16)
    state = opt.init({"w": jnp.ones((4,), jnp.float32)})
    assert state["q"].dtype == jnp.int8
    assert state["f8"].dtype == jnp.float8_e4m3fn
    assert state["count"].dtype == jnp.int32
    assert state["mu"].dtype == jnp.bfloat16  # the real moment IS cast
    _, state2 = opt.update({"w": jnp.zeros(4)}, state)
    assert state2["q"].dtype == jnp.int8
    assert state2["f8"].dtype == jnp.float8_e4m3fn
    np.testing.assert_array_equal(np.asarray(state2["q"]),
                                  np.arange(-4, 4))


def test_moments_dtype_rejected_unknown():
    with pytest.raises(ValueError, match="moments_dtype"):
        build_optimizer({"optimizer": "adam", "moments_dtype": "int8"})


def test_schedule_values():
    sched = build_schedule({"learning_rate": 1.0, "schedule": "warmup_cosine",
                            "warmup_steps": 10, "decay_steps": 100})
    assert float(sched(0)) == 0.0
    np.testing.assert_allclose(float(sched(10)), 1.0, rtol=1e-6)
    assert float(sched(100)) < 0.1
    cos = build_schedule({"learning_rate": 1.0, "schedule": "cosine",
                          "decay_steps": 100})
    np.testing.assert_allclose(float(cos(0)), 1.0, rtol=1e-6)
    const = build_schedule({"learning_rate": 0.5})
    assert float(const(12345)) == 0.5


def test_unknown_names_rejected():
    with pytest.raises(ValueError, match="optimizer"):
        build_optimizer({"optimizer": "lamb"})
    with pytest.raises(ValueError, match="schedule"):
        build_schedule({"schedule": "linear"})
