"""Overlapped collective-matmul: ring-decomposed TP projections.

The GSPMD Megatron layout (``models/sharding.py``) leaves tensor-parallel
collective time *exposed*: each row-parallel matmul ends in an all-reduce
that sits serially between the matmul producing its operand and the next
matmul consuming its result.  The round-5 chip artifacts put the 7B full
forward at 163.3 TFLOP/s vs 176.9 for the comm-free simplified variant —
the gap is that serial collective time.

This module applies the decomposition of Wang et al., ASPLOS 2023
("Overlap Communication with Dependent Computation via Decomposition")
and the collective-matmul schedules of Pope et al. 2022: split each
TP projection into per-shard partial matmuls interleaved with a
``lax.ppermute`` ring, so the transfer of one shard rides under the
matmul of another.  The per-layer all-reduce pair becomes an
all-gather-matmul (column parallel) + matmul-reduce-scatter (row
parallel) pair — same total wire bytes (AG + RS = AR), but every hop is
a neighbour ``collective-permute`` that XLA's async scheduler can start
before, and finish after, an independent partial matmul.  Activations
between blocks live *sequence-sharded over tp* (the Megatron
sequence-parallel layout), which is what gives each ring step an
independent chunk to compute on.

Two schedules:

- ``ring``  — unidirectional: P-1 hops, full chunk per hop, one ICI
  direction.
- ``bidir`` — bidirectional: both ICI directions at once.  The
  all-gather ring halves the *hop count* (two chunks arrive per step);
  the reduce-scatter ring splits the output features in half and
  reduces each half around opposite directions (half-sized messages
  both ways).  Wins when the schedule is latency-bound (small chunks,
  long rings) or when both link directions are otherwise idle.

Both carry a **custom VJP** so the backward pass overlaps the same way:
the cotangent of an all-gather-matmul is a matmul-reduce-scatter (and
vice versa), and the weight gradient is its own ring over the saved
activations — no fused-path all-reduces reappear under ``jax.grad``.
Weight gradients are psum'd over the batch-carrying mesh axes (dp, sp)
inside the ring body, exactly the reduction GSPMD would insert for
replicated parameters.

The ring bodies are Python-unrolled (the tp degree is static and small),
so the lowered HLO shows the literal collective-permute chain — which is
what the comm-lint HLO audit pins (``analysis/expectations.py``:
overlapped targets must show the permute chain and no residual oversized
all-gather; see docs/overlap.md for the audit contract).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dlbb_tpu.compat import shard_map

SCHEDULES = ("ring", "bidir")

# mesh axes that may carry the batch/sequence dims alongside tp; weight
# grads psum over the ones present (the replicated-param reduction GSPMD
# would otherwise insert)
_BATCH_AXES = ("dp", "sp")


def _check_schedule(schedule: str) -> bool:
    if schedule not in SCHEDULES:
        raise ValueError(
            f"unknown tp_overlap schedule {schedule!r}; known: {SCHEDULES}"
        )
    return schedule == "bidir"


def _ring_perms(p: int):
    """(forward, backward) ring permutations: forward sends i -> i+1 (each
    device receives from its left neighbour), backward the reverse."""
    fwd = [(i, (i + 1) % p) for i in range(p)]
    bwd = [(i, (i - 1) % p) for i in range(p)]
    return fwd, bwd


# ---------------------------------------------------------------------------
# local ring kernels (run inside shard_map; x/w/dy are per-device blocks)
# ---------------------------------------------------------------------------


def _ring_visit(travelling, axis: str, p: int, bidir: bool, visit):
    """Circulate ``travelling`` (this device's chunk of some ring-sharded
    array) and call ``visit(chunk, src)`` once per source rank, own chunk
    first.  The shared travel loop of every gather-style ring here: each
    ppermute is independent of the visit consuming the chunk in hand, so
    XLA overlaps the hop with the visit's matmul.

    Unidirectional: p-1 forward hops.  Bidirectional: chunks arrive from
    both neighbours each step — ceil((p-1)/2) hops, both ICI directions.
    """
    r = lax.axis_index(axis)
    fwd, bwd = _ring_perms(p)
    visit(travelling, r)
    # every hop runs under a ``ring_hop*`` named scope: the name lands in
    # the HLO op_name metadata, which is how the schedule auditor
    # (analysis/schedule_audit.py) pins exactly these permutes for the
    # serialized-collective gate — each must have a straddling matmul
    if not bidir:
        cur = travelling
        for j in range(1, p):
            with jax.named_scope(f"ring_hop_fwd{j}"):
                cur = lax.ppermute(cur, axis, fwd)  # holds block (r - j)
            visit(cur, (r - j) % p)
        return
    n_fwd = (p - 1 + 1) // 2
    n_bwd = (p - 1) // 2
    cur_f = cur_b = travelling
    for j in range(1, max(n_fwd, n_bwd) + 1):
        if j <= n_fwd:
            with jax.named_scope(f"ring_hop_fwd{j}"):
                cur_f = lax.ppermute(cur_f, axis, fwd)   # block (r - j)
            visit(cur_f, (r - j) % p)
        if j <= n_bwd:
            with jax.named_scope(f"ring_hop_bwd{j}"):
                cur_b = lax.ppermute(cur_b, axis, bwd)   # block (r + j)
            visit(cur_b, (r + j) % p)


def _ag_matmul_body(x, w, axis: str, p: int, bidir: bool):
    """All-gather-matmul: x [b, s, h] (this device's sequence chunk),
    w [h, f] (this device's column shard) -> [b, p*s, f] (full sequence,
    column shard).  Row block ``src`` of the output is ``x_src @ w``;
    x chunks travel the ring while the chunk in hand is multiplied."""
    b, s, h = x.shape
    out = jnp.zeros((b, p * s, w.shape[1]), dtype=x.dtype)

    def visit(chunk, src):
        nonlocal out
        out = lax.dynamic_update_slice_in_dim(
            out, chunk @ w, src * s, axis=1
        )

    _ring_visit(x, axis, p, bidir, visit)
    return out


def _matmul_rs_body(x, w, axis: str, p: int, bidir: bool):
    """Matmul-reduce-scatter: x [b, s, f] (full sequence, this device's
    feature shard), w [f, h] (row shard) -> [b, s/p, h] (this device's
    sequence chunk of the cross-shard sum).

    The accumulator travels the ring: at each step a device adds its own
    partial product for the chunk the accumulator is destined to, so the
    partial matmul for step j+1 is independent of step j's permute."""
    b, s, f = x.shape
    h = w.shape[1]
    if s % p != 0:
        raise ValueError(
            f"matmul_reducescatter: local sequence {s} not divisible by "
            f"ring size {p}"
        )
    s_out = s // p
    r = lax.axis_index(axis)
    fwd, bwd = _ring_perms(p)

    def partial(c, w_shard):
        xc = lax.dynamic_slice_in_dim(x, c * s_out, s_out, axis=1)
        return xc @ w_shard

    if not bidir:
        # target of the accumulator on this device at add-step j is
        # (r + p - 1 - j) mod p; after the last add it is chunk r, fully
        # reduced.  ring_hop named scopes: see _ring_visit
        acc = partial((r + p - 1) % p, w)
        for j in range(1, p):
            with jax.named_scope(f"ring_hop_fwd{j}"):
                acc = lax.ppermute(acc, axis, fwd)
            acc = acc + partial((r + p - 1 - j) % p, w)
        return acc
    # bidirectional: front half of the output features reduces clockwise,
    # back half counter-clockwise — half-sized messages on both ICI
    # directions every step
    hh = h // 2
    w_f, w_b = w[:, :hh], w[:, hh:]
    acc_f = partial((r + p - 1) % p, w_f)
    acc_b = partial((r + 1) % p, w_b)
    for j in range(1, p):
        with jax.named_scope(f"ring_hop_fwd{j}"):
            acc_f = lax.ppermute(acc_f, axis, fwd)
        acc_f = acc_f + partial((r + p - 1 - j) % p, w_f)
        with jax.named_scope(f"ring_hop_bwd{j}"):
            acc_b = lax.ppermute(acc_b, axis, bwd)
        acc_b = acc_b + partial((r + 1 + j) % p, w_b)
    return jnp.concatenate([acc_f, acc_b], axis=-1)


def _ag_grad_w_body(x, dy, axis: str, p: int, bidir: bool,
                    batch_axes: tuple[str, ...]):
    """Weight gradient of the all-gather-matmul: dw [h, f] = sum over the
    gathered sequence of x_src^T @ dy[src rows].  The saved x chunks
    travel the same ring (a re-gather, overlapped with the contraction);
    the result is psum'd over the batch-carrying axes — the
    replicated-parameter reduction."""
    s = x.shape[1]
    dw = None

    def visit(chunk, src):
        nonlocal dw
        dyc = lax.dynamic_slice_in_dim(dy, src * s, s, axis=1)
        term = jnp.einsum("bsh,bsf->hf", chunk, dyc)
        dw = term if dw is None else dw + term

    _ring_visit(x, axis, p, bidir, visit)
    if batch_axes:
        dw = lax.psum(dw, batch_axes)
    return dw


def _rs_grad_w_body(x, dy, axis: str, p: int, bidir: bool,
                    batch_axes: tuple[str, ...]):
    """Weight gradient of the matmul-reduce-scatter: dw [f, h] = x^T @
    AG(dy) over the sequence — the dy chunks travel the ring while the
    stationary x rows they pair with are contracted."""
    s_out = dy.shape[1]
    dw = None

    def visit(dy_chunk, src):
        nonlocal dw
        xc = lax.dynamic_slice_in_dim(x, src * s_out, s_out, axis=1)
        term = jnp.einsum("bsf,bsh->fh", xc, dy_chunk)
        dw = term if dw is None else dw + term

    _ring_visit(dy, axis, p, bidir, visit)
    if batch_axes:
        dw = lax.psum(dw, batch_axes)
    return dw


# ---------------------------------------------------------------------------
# global wrappers (shard_map + custom VJP)
# ---------------------------------------------------------------------------


def _mesh_layout(mesh: Mesh, tp_axis: str):
    """(batch spec entry, sharded-seq spec entry, gathered-seq spec entry,
    batch-carrying axes present) for this mesh."""
    axes = mesh.axis_names
    if tp_axis not in axes:
        raise ValueError(
            f"mesh {tuple(axes)} has no {tp_axis!r} axis for overlapped "
            "collective matmul"
        )
    b = "dp" if "dp" in axes else None
    sp = "sp" if "sp" in axes and mesh.shape["sp"] > 1 else None
    seq_sharded = (sp, tp_axis) if sp else tp_axis
    # size-1 axes stay in the psum set: the reduction is free there but it
    # is what lets shard_map's replication checker prove the P(None, tp)
    # weight-grad out_spec
    batch_axes = tuple(
        a for a in _BATCH_AXES if a in axes and a != tp_axis
    )
    return b, seq_sharded, sp, batch_axes


def _validate(x, w, mesh, tp_axis, col_parallel: bool):
    _, seq_sharded, sp, _ = _mesh_layout(mesh, tp_axis)
    p = mesh.shape[tp_axis]
    seq_div = p * (mesh.shape["sp"] if sp else 1)
    if x.ndim != 3 or w.ndim != 2:
        raise ValueError(
            f"collective matmul expects x [B, S, features] and w 2D; got "
            f"x {x.shape}, w {w.shape}"
        )
    if x.shape[1] % seq_div != 0:
        raise ValueError(
            f"sequence length {x.shape[1]} not divisible by the "
            f"sequence-shard count {seq_div} "
            f"(tp={p}{f' x sp={mesh.shape[sp]}' if sp else ''}); "
            "tp_overlap needs evenly divisible sequence chunks"
        )
    w_dim = 1 if col_parallel else 0
    if w.shape[w_dim] % p != 0:
        raise ValueError(
            f"weight dim {w.shape[w_dim]} not divisible by tp={p}"
        )


def _apply_ag(x, w, mesh, tp_axis, bidir):
    """shard_map'd all-gather-matmul on global arrays: x sequence-sharded
    over (sp, tp), w column-sharded over tp -> y with the full-tp sequence
    and tp-sharded features."""
    p = mesh.shape[tp_axis]
    b, seq_sharded, sp, _ = _mesh_layout(mesh, tp_axis)
    return shard_map(
        lambda x_, w_: _ag_matmul_body(x_, w_, tp_axis, p, bidir),
        mesh=mesh,
        in_specs=(P(b, seq_sharded, None), P(None, tp_axis)),
        out_specs=P(b, sp, tp_axis),
    )(x, w)


def _apply_rs(x, w, mesh, tp_axis, bidir):
    """shard_map'd matmul-reduce-scatter on global arrays: x with tp-sharded
    features, w row-sharded over tp -> y sequence-sharded over (sp, tp)."""
    p = mesh.shape[tp_axis]
    b, seq_sharded, sp, _ = _mesh_layout(mesh, tp_axis)
    return shard_map(
        lambda x_, w_: _matmul_rs_body(x_, w_, tp_axis, p, bidir),
        mesh=mesh,
        in_specs=(P(b, sp, tp_axis), P(tp_axis, None)),
        out_specs=P(b, seq_sharded, None),
    )(x, w)


def _apply_ag_grad_w(x, dy, mesh, tp_axis, bidir):
    p = mesh.shape[tp_axis]
    b, seq_sharded, sp, batch_axes = _mesh_layout(mesh, tp_axis)
    return shard_map(
        lambda x_, dy_: _ag_grad_w_body(
            x_, dy_, tp_axis, p, bidir, batch_axes
        ),
        mesh=mesh,
        in_specs=(P(b, seq_sharded, None), P(b, sp, tp_axis)),
        out_specs=P(None, tp_axis),
    )(x, dy)


def _apply_rs_grad_w(x, dy, mesh, tp_axis, bidir):
    p = mesh.shape[tp_axis]
    b, seq_sharded, sp, batch_axes = _mesh_layout(mesh, tp_axis)
    return shard_map(
        lambda x_, dy_: _rs_grad_w_body(
            x_, dy_, tp_axis, p, bidir, batch_axes
        ),
        mesh=mesh,
        in_specs=(P(b, sp, tp_axis), P(b, seq_sharded, None)),
        out_specs=P(tp_axis, None),
    )(x, dy)


# one custom-VJP closure per (mesh, tp axis, schedule) — jitted callers
# retrace per closure identity, so repeated lookups must return the same
# object (the same reason comm/mesh.py memoises meshes)
_FN_CACHE: dict[tuple, jax.custom_vjp] = {}


def _make_ag_matmul(mesh: Mesh, tp_axis: str, bidir: bool):
    key = ("ag", mesh, tp_axis, bidir)
    fn = _FN_CACHE.get(key)
    if fn is not None:
        return fn

    @jax.custom_vjp
    def ag_matmul(x, w):
        return _apply_ag(x, w, mesh, tp_axis, bidir)

    def fwd(x, w):
        return _apply_ag(x, w, mesh, tp_axis, bidir), (x, w)

    def bwd(res, dy):
        x, w = res
        # the cotangent of an all-gather-matmul is a matmul-reduce-scatter
        # of dy against w^T — the backward overlaps with the same ring
        dx = _apply_rs(dy, jnp.swapaxes(w, 0, 1), mesh, tp_axis, bidir)
        dw = _apply_ag_grad_w(x, dy, mesh, tp_axis, bidir)
        return dx, dw

    ag_matmul.defvjp(fwd, bwd)
    _FN_CACHE[key] = ag_matmul
    return ag_matmul


def _make_matmul_rs(mesh: Mesh, tp_axis: str, bidir: bool):
    key = ("rs", mesh, tp_axis, bidir)
    fn = _FN_CACHE.get(key)
    if fn is not None:
        return fn

    @jax.custom_vjp
    def matmul_rs(x, w):
        return _apply_rs(x, w, mesh, tp_axis, bidir)

    def fwd(x, w):
        return _apply_rs(x, w, mesh, tp_axis, bidir), (x, w)

    def bwd(res, dy):
        x, w = res
        # mirror image: the cotangent of a matmul-reduce-scatter is an
        # all-gather-matmul of dy against w^T
        dx = _apply_ag(dy, jnp.swapaxes(w, 0, 1), mesh, tp_axis, bidir)
        dw = _apply_rs_grad_w(x, dy, mesh, tp_axis, bidir)
        return dx, dw

    matmul_rs.defvjp(fwd, bwd)
    _FN_CACHE[key] = matmul_rs
    return matmul_rs


def allgather_matmul(
    x: jax.Array,
    w: jax.Array,
    mesh: Mesh,
    tp_axis: str = "tp",
    schedule: str = "ring",
) -> jax.Array:
    """Column-parallel projection with the activation all-gather hidden
    behind per-shard partial matmuls.

    x: global ``[B, S, H]``, sequence-sharded over ``(sp?, tp)``;
    w: global ``[H, F]``, column-sharded over ``tp``.
    Returns ``[B, S, F]`` with the sequence gathered over ``tp`` (still
    sp-sharded if the mesh has sp) and features tp-sharded — the layout
    attention and elementwise ops consume directly.

    Differentiable via a custom VJP whose backward uses the mirrored
    overlapped schedules (see module docstring).
    """
    bidir = _check_schedule(schedule)
    _validate(x, w, mesh, tp_axis, col_parallel=True)
    return _make_ag_matmul(mesh, tp_axis, bidir)(x, w)


def matmul_reducescatter(
    x: jax.Array,
    w: jax.Array,
    mesh: Mesh,
    tp_axis: str = "tp",
    schedule: str = "ring",
) -> jax.Array:
    """Row-parallel projection with the partial-sum reduce-scatter hidden
    behind per-shard partial matmuls.

    x: global ``[B, S, F]``, features tp-sharded; w: global ``[F, H]``,
    row-sharded over ``tp``.  Returns ``[B, S, H]`` sequence-sharded over
    ``(sp?, tp)`` — the residual-stream layout of the overlapped block.
    """
    bidir = _check_schedule(schedule)
    _validate(x, w, mesh, tp_axis, col_parallel=False)
    return _make_matmul_rs(mesh, tp_axis, bidir)(x, w)


def activation_spec(mesh: Mesh, tp_axis: str = "tp") -> P:
    """PartitionSpec of the overlapped residual stream: batch over dp,
    sequence over (sp?, tp) — what ``forward`` constrains the scan carry
    to when ``tp_overlap`` is on."""
    b, seq_sharded, _, _ = _mesh_layout(mesh, tp_axis)
    return P(b, seq_sharded, None)
