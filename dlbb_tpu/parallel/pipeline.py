"""Pipeline parallelism: microbatched pipelines over a ``pp`` mesh axis —
a GPipe forward engine (differentiable, used by forward benchmarks AND as
the default training schedule via autodiff) and a 1F1B training engine
(``pipeline_1f1b_grads``) that interleaves each microbatch's backward into
the steady state so the per-stage activation live-range is bounded by the
stage count, not the microbatch count.

The reference has no pipeline parallelism (SURVEY §2.2: "PP — NO"); this is
a capability extension, designed TPU-first rather than as a port of any
torch pipeline engine:

- the transformer's stacked-layer parameter axis is *sharded* over ``pp`` —
  each stage owns a contiguous block of ``num_layers / pp`` layers
  (``models/sharding.py::param_specs(pp_axis=...)``);
- inside one ``shard_map`` (manual over ``pp`` only — ``dp``/``tp`` stay
  under GSPMD via ``axis_names={pp}``), microbatches flow through the
  stages with a ``lax.ppermute`` ring shift per tick: the classic
  scan-over-ticks pipeline, one traced stage body regardless of depth;
- tick ``t`` injects microbatch ``t`` at stage 0 and collects finished
  microbatch ``t - (pp-1)`` at the last stage; after ``M + pp - 1`` ticks a
  ``lax.psum`` masked to the last stage broadcasts the outputs;
- bubble fraction is the GPipe ``(pp-1)/(M + pp - 1)``; raise
  ``num_microbatches`` to amortise it.

Forward and reverse differentiable (``ppermute``/``scan`` have exact
transpose rules), so the same code path serves the E2E forward benchmark
and the DDP/ZeRO training step.

**1F1B** (``training.pipeline_schedule: "1f1b"``): GPipe autodiff keeps
every microbatch's stage inputs alive from its forward tick until the
backward sweep — O(num_microbatches) activations per stage.  The 1F1B
engine instead interleaves a backward wavefront into the forward
wavefront: scan over ``m + 2(pp-1)`` tick *pairs*; in pair ``u`` stage
``i`` forwards microbatch ``u - i`` and backwards microbatch
``u - 2(pp-1) + i`` (each masked outside ``[0, m)`` — bubble ticks
compute on garbage and are masked out, exactly like the GPipe engine's
bubbles), recomputing the stage forward inside the backward's ``jax.vjp``
from the stored stage INPUT.  Each stage therefore alternates
1-forward/1-backward in steady state and holds at most ``2·pp - 1``
in-flight stage inputs — live-range O(pp), independent of the microbatch
count (GPipe-autodiff holds O(m)).  Numerics equal GPipe-autodiff up to
fp summation order (same per-microbatch math; gradients accumulate in
schedule order).

Design constraint that shapes the engine: under SPMD, every device must
issue an IDENTICAL sequence of collectives — and with ``tp``/``ep`` as
GSPMD auto axes, the stage computation itself contains collectives
(Megatron row-parallel psums).  A per-stage ``lax.switch`` between fwd
and bwd bodies (the classic 1F1B formulation) puts those collectives
inside branches that different stages take differently at the same tick,
which deadlocks the mesh (observed on the CPU in-process runtime; equally
illegal over ICI).  The wavefront formulation keeps every tick-pair's op
sequence identical on every device — fwd body, bwd body, activation hop,
cotangent hop — so collective uniformity holds for any dp x pp x tp x ep
composition.  Total real work equals GPipe (one valid F and one valid B
per microbatch per stage); the bubble overhead is ``2(pp-1)`` pairs vs
GPipe's ``pp-1`` ticks per phase.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dlbb_tpu.compat import pcast, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from dlbb_tpu.models.configs import ModelConfig
from dlbb_tpu.models.sharding import PP_AXIS

def schedule_1f1b(n_stages: int, m: int):
    """Closed-form 1F1B wavefront schedule.

    Returns ``(pairs, fwd_mb, bwd_mb)``: the tick-pair count
    ``m + 2(n_stages-1)`` and two ``[pairs, n_stages]`` int32 tables — at
    pair ``u`` stage ``i`` forwards ``fwd_mb[u, i] = u - i`` and backwards
    ``bwd_mb[u, i] = u - 2(n_stages-1) + i``; entries outside ``[0, m)``
    are bubble slots (executed on garbage, masked out).  Invariants (see
    the module docstring and tests): activations/cotangents hop exactly
    one pair between producer and consumer; per-stage in-flight
    microbatches (forwarded, not yet backwarded) never exceed
    ``2·n_stages - 1``.
    """
    pairs = m + 2 * (n_stages - 1)
    u = np.arange(pairs)[:, None]
    i = np.arange(n_stages)[None, :]
    fwd_mb = (u - i).astype(np.int32)
    bwd_mb = (u - 2 * (n_stages - 1) + i).astype(np.int32)
    return pairs, fwd_mb, bwd_mb


def validate_pipeline(config: ModelConfig, n_stages: int, batch_size: int,
                      num_microbatches: Optional[int]) -> int:
    """Check divisibility and attention-mode constraints; returns the
    resolved microbatch count (default: one per stage)."""
    m = num_microbatches if num_microbatches is not None else n_stages
    if m < 1:
        raise ValueError(f"num_microbatches must be >= 1, got {m}")
    if config.num_layers % n_stages != 0:
        raise ValueError(
            f"num_layers={config.num_layers} not divisible by "
            f"pipeline_parallel={n_stages}"
        )
    if batch_size % m != 0:
        raise ValueError(
            f"batch_size={batch_size} not divisible by "
            f"num_microbatches={m}"
        )
    if config.attention not in ("full", "dense", "simplified"):
        raise ValueError(
            f"attention={config.attention!r} cannot run under pipeline "
            "parallelism (ring/ulysses/flash need their own shard_map; "
            "use attention='full'/'dense'/'simplified' with "
            "pipeline_parallel > 1)"
        )
    return m


def pipeline_1f1b_grads(
    params,
    x: jax.Array,
    targets: jax.Array,
    config: ModelConfig,
    mesh: Mesh,
    pp_axis: str = PP_AXIS,
    num_microbatches: Optional[int] = None,
    moe_aux_weight: float = 0.0,
):
    """One full 1F1B training pass: returns ``(loss, grads)`` with ``grads``
    matching the ``params`` pytree (stage-sharded layer blocks + ln_f).

    Loss is the unpipelined ``mse_loss`` semantics: mean squared error over
    the full batch (mean of equal-sized per-microbatch means) plus
    ``moe_aux_weight`` times the layer x microbatch mean MoE aux.

    Every stage's backward step runs ONE shared ``jax.vjp`` of a stage
    function that computes (stage output, per-microbatch loss through
    ln_f + MSE, local aux): mid stages inject the received cotangent on
    the stage output and 0 on the loss; the last stage injects 1/m on the
    loss and 0 on the output — so ln_f gradients flow only where the loss
    is real, with no per-stage code divergence.  Forward recompute inside
    the vjp bounds stored state to the ``2·pp``-deep stage-input ring
    buffer (the 1F1B memory contract; see the module docstring for the
    wavefront schedule and the collective-uniformity rationale).
    """
    from dlbb_tpu.models.transformer import _block, _layernorm

    n_stages = mesh.shape[pp_axis]
    m = validate_pipeline(config, n_stages, x.shape[0], num_microbatches)
    if config.attention == "full":
        # same einsum-pinning rationale as pipeline_forward
        config = config.with_(attention="dense")
    pairs, fwd_tbl, bwd_tbl = schedule_1f1b(n_stages, m)
    depth = 2 * n_stages  # stage-input ring buffer (in-flight <= 2*pp - 1)
    layer_specs = jax.tree.map(lambda _: P(pp_axis), params["layers"])
    aux_cot = moe_aux_weight / (config.num_layers * m)

    def stage_local(sid, layers_local, lnf, x, tgt):
        # the stage index arrives as a pp-sharded [1] array rather than
        # lax.axis_index: under a partial-auto shard_map the latter lowers
        # to a PartitionId instruction the SPMD partitioner rejects
        pp = sid[0]
        is_last = pp == n_stages - 1
        lnf = jax.tree.map(
            lambda t: pcast(t, (pp_axis,), to="varying"), lnf
        )
        mb = x.reshape(m, x.shape[0] // m, *x.shape[1:])
        tgt_mb = tgt.reshape(m, tgt.shape[0] // m, *tgt.shape[1:])
        fwd_mbs = jnp.asarray(fwd_tbl)[:, pp]   # [pairs] this stage's F mb
        bwd_mbs = jnp.asarray(bwd_tbl)[:, pp]   # [pairs] this stage's B mb

        def stage_fn(p, lnf_p, h):
            def body(carry, layer):
                new_h, aux = _block(carry, layer, config)
                return new_h, aux

            if config.remat:
                body = jax.checkpoint(body, prevent_cse=False)
            y, auxs = lax.scan(body, h, p)
            z = _layernorm(y, lnf_p["scale"], lnf_p["bias"])
            return y, z, auxs.sum()

        def stage_fn_with_tgt(p, l, h, t_b):
            y, z, aux = stage_fn(p, l, h)
            loss = jnp.mean(
                (z.astype(jnp.float32) - t_b.astype(jnp.float32)) ** 2
            )
            return y, loss, aux

        def var(t):  # carry entries must be pp-varying
            return pcast(t, (pp_axis,), to="varying")

        mb_shape = mb[0].shape
        grads0 = jax.tree.map(
            lambda p: var(jnp.zeros(p.shape, jnp.float32)), layers_local
        )
        lnf0 = jax.tree.map(
            lambda p: var(jnp.zeros(p.shape, jnp.float32)), lnf
        )
        carry0 = dict(
            acts=var(jnp.zeros((depth, *mb_shape), x.dtype)),
            recv_f=var(jnp.zeros(mb_shape, x.dtype)),
            recv_b=var(jnp.zeros(mb_shape, jnp.float32)),
            grads=grads0,
            dlnf=lnf0,
            loss=var(jnp.zeros((), jnp.float32)),
            aux=var(jnp.zeros((), jnp.float32)),
        )

        def pair(c, u):
            # --- forward wave: stage pp forwards microbatch u - pp ---
            f = fwd_mbs[u]
            valid_f = jnp.logical_and(f >= 0, f < m)
            inject = lax.dynamic_index_in_dim(
                mb, jnp.clip(f, 0, m - 1), 0, keepdims=False
            )
            h_in = jnp.where(pp == 0, inject, c["recv_f"])
            slot = jnp.clip(f, 0, m - 1) % depth
            acts = lax.dynamic_update_index_in_dim(
                c["acts"], h_in.astype(c["acts"].dtype), slot, 0
            )
            acts = jnp.where(valid_f, acts, c["acts"])
            y, _, _ = stage_fn(layers_local, lnf, h_in)

            # --- backward wave: stage pp backwards u - 2(pp-1) + pp ---
            b = bwd_mbs[u]
            valid_b = jnp.logical_and(b >= 0, b < m)
            h_b = lax.dynamic_index_in_dim(
                acts, jnp.clip(b, 0, m - 1) % depth, 0, keepdims=False
            )
            t_b = lax.dynamic_index_in_dim(
                tgt_mb, jnp.clip(b, 0, m - 1), 0, keepdims=False
            )
            (_, loss_b, aux_val), vjp = jax.vjp(
                lambda p, l, h: stage_fn_with_tgt(p, l, h, t_b),
                layers_local, lnf, h_b,
            )
            dy = c["recv_b"].astype(y.dtype)
            cot_y = jnp.where(is_last, jnp.zeros_like(dy), dy)
            cot_loss = jnp.where(is_last, 1.0 / m, 0.0)
            # derive the aux cotangent from the primal so it carries the
            # same shard_map varying-axes type (MoE aux is pp-varying;
            # the dense FFN's constant-zero aux is not)
            cot_aux = aux_val * 0.0 + jnp.float32(aux_cot)
            dp, dl, dh = vjp((cot_y, cot_loss.astype(jnp.float32),
                              cot_aux))
            vb32 = valid_b.astype(jnp.float32)
            grads = jax.tree.map(
                lambda g, a: g + vb32 * a.astype(jnp.float32),
                c["grads"], dp,
            )
            dlnf = jax.tree.map(
                lambda g, a: g + vb32 * a.astype(jnp.float32),
                c["dlnf"], dl,
            )
            loss = c["loss"] + jnp.where(
                jnp.logical_and(is_last, valid_b), loss_b / m, 0.0
            )
            aux = c["aux"] + jnp.where(
                valid_b, aux_val / (config.num_layers * m), 0.0
            )

            # --- hops: activations forward, cotangents backward.  The two
            # permutes MUST execute in one fixed order on every device:
            # XLA's runtimes require a uniform collective order (and at
            # pp=2 the two rings are the same permutation and even share a
            # channel id).  An optimization_barrier is not enough — loop
            # rotation rewires permutes to read the scan carry directly —
            # so the ordering edge is a real data dependency: 0 * fwd_next
            # is not folded by XLA (NaN-honoring semantics), making the
            # cotangent hop consume the activation hop's result.
            send_f = jnp.where(valid_f, y, jnp.zeros_like(y))
            send_b = jnp.where(valid_b, dh.astype(jnp.float32),
                               jnp.zeros(mb_shape, jnp.float32))
            fwd_next = lax.ppermute(
                send_f, pp_axis,
                [(i, (i + 1) % n_stages) for i in range(n_stages)],
            )
            tie = jnp.zeros_like(send_b) * fwd_next.astype(jnp.float32)
            bwd_next = lax.ppermute(
                send_b + tie, pp_axis,
                [(i, (i - 1) % n_stages) for i in range(n_stages)],
            )
            return dict(
                acts=acts, recv_f=fwd_next, recv_b=bwd_next,
                grads=grads, dlnf=dlnf, loss=loss, aux=aux,
            ), None

        final, _ = lax.scan(pair, carry0, jnp.arange(pairs))
        loss = lax.psum(final["loss"], pp_axis)   # only last stage nonzero
        aux = lax.psum(final["aux"], pp_axis)
        dlnf = lax.psum(final["dlnf"], pp_axis)   # real only where loss was
        return final["grads"], dlnf, loss, aux

    stage_ids = jnp.arange(n_stages, dtype=jnp.int32)
    grads_layers, dlnf, loss, aux = shard_map(
        stage_local,
        mesh=mesh,
        in_specs=(P(pp_axis), layer_specs, P(), P(), P()),
        out_specs=(layer_specs, P(), P(), P()),
        axis_names={pp_axis},
    )(stage_ids, params["layers"], params["ln_f"], x, targets)
    total_loss = loss + moe_aux_weight * aux
    grads = {
        "layers": jax.tree.map(
            lambda g, p: g.astype(p.dtype), grads_layers, params["layers"]
        ),
        "ln_f": jax.tree.map(
            lambda g, p: g.astype(p.dtype), dlnf, params["ln_f"]
        ),
    }
    return total_loss, grads


def pipeline_forward(
    params,
    x: jax.Array,
    config: ModelConfig,
    mesh: Mesh,
    pp_axis: str = PP_AXIS,
    num_microbatches: Optional[int] = None,
    with_aux: bool = False,
):
    """Full-model forward with the layer stack pipelined over ``pp_axis``.

    ``params`` must hold the stacked-layer pytree of
    ``models/transformer.py::init_params`` with the leading layer axis
    sharded over ``pp_axis``; the final layernorm runs outside the
    pipeline (replicated, applied after the shard_map).

    ``with_aux=True`` additionally returns the MoE load-balancing loss,
    averaged over layers AND microbatches: each stage accumulates its
    local layers' aux for the microbatch it validly processes at each tick
    (bubble ticks masked out), and a ``psum`` over ``pp_axis`` totals the
    stages.  Mean-over-microbatches is the same approximation gradient
    accumulation makes (``moe_aux_loss`` is nonlinear in the batch, so it
    is not bit-identical to the unpipelined full-batch aux — the standard
    microbatching semantics).
    """
    from dlbb_tpu.models.transformer import _block, _layernorm

    n_stages = mesh.shape[pp_axis]
    m = validate_pipeline(config, n_stages, x.shape[0], num_microbatches)
    if config.attention == "full":
        # pin the einsum kernel inside the stage body: the TPU flash
        # auto-route would drop an opaque pallas_call under the shard_map's
        # auto dp/tp axes — the exact GSPMD pathology validate_pipeline
        # rejects attention='flash' for.  Same math either way.
        config = config.with_(attention="dense")

    layer_specs = jax.tree.map(lambda _: P(pp_axis), params["layers"])

    def stage_local(sid, layers_local, x):
        # layers_local: this stage's [L/pp, ...] block; x: full [B, S, H];
        # sid: pp-sharded [1] stage index (lax.axis_index would lower to a
        # PartitionId the SPMD partitioner rejects under partial-auto)
        pp = sid[0]
        mb = x.reshape(m, x.shape[0] // m, *x.shape[1:])
        state = pcast(jnp.zeros_like(mb[0]), (pp_axis,), to="varying")
        outputs = pcast(jnp.zeros_like(mb), (pp_axis,), to="varying")
        aux0 = pcast(jnp.zeros((), jnp.float32), (pp_axis,),
                         to="varying")

        def local_fwd(h):
            def body(carry, layer):
                new_h, aux = _block(carry, layer, config)
                return new_h, aux

            if config.remat:
                body = jax.checkpoint(body, prevent_cse=False)
            h, auxs = lax.scan(body, h, layers_local)
            return h, auxs.sum()  # sum over this stage's local layers

        def tick(carry, t):
            state, outputs, aux_sum = carry
            inject = lax.dynamic_index_in_dim(
                mb, jnp.clip(t, 0, m - 1), 0, keepdims=False
            )
            y, aux = local_fwd(jnp.where(pp == 0, inject, state))
            # stage p processes microbatch t - p at tick t; outside
            # [0, m) it is running on bubble garbage — mask its aux out
            mb_idx = t - pp
            valid = jnp.logical_and(mb_idx >= 0, mb_idx < m)
            aux_sum = aux_sum + jnp.where(valid, aux, 0.0)
            out_t = t - (n_stages - 1)
            write = jnp.logical_and(
                pp == n_stages - 1,
                jnp.logical_and(out_t >= 0, out_t < m),
            )
            updated = lax.dynamic_update_index_in_dim(
                outputs, y, jnp.clip(out_t, 0, m - 1), 0
            )
            outputs = jnp.where(write, updated, outputs)
            state = lax.ppermute(
                y, pp_axis,
                [(i, (i + 1) % n_stages) for i in range(n_stages)],
            )
            return (state, outputs, aux_sum), None

        (_, outputs, aux_sum), _ = lax.scan(
            tick, (state, outputs, aux0), jnp.arange(m + n_stages - 1)
        )
        # only the last stage holds real outputs; the masked psum is the
        # SPMD broadcast back to every stage
        outputs = lax.psum(
            jnp.where(pp == n_stages - 1, outputs, jnp.zeros_like(outputs)),
            pp_axis,
        )
        # stages hold disjoint layer blocks: psum totals all layers x mbs
        aux_total = lax.psum(aux_sum, pp_axis)
        return outputs.reshape(x.shape), aux_total

    stage_ids = jnp.arange(n_stages, dtype=jnp.int32)
    y, aux_total = shard_map(
        stage_local,
        mesh=mesh,
        in_specs=(P(pp_axis), layer_specs, P()),
        out_specs=(P(), P()),
        axis_names={pp_axis},
    )(stage_ids, params["layers"], x)
    out = _layernorm(y, params["ln_f"]["scale"], params["ln_f"]["bias"])
    if with_aux:
        return out, aux_total / (config.num_layers * m)
    return out
