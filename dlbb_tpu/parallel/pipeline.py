"""Pipeline parallelism: GPipe-style microbatched pipeline over a ``pp``
mesh axis.

The reference has no pipeline parallelism (SURVEY §2.2: "PP — NO"); this is
a capability extension, designed TPU-first rather than as a port of any
torch pipeline engine:

- the transformer's stacked-layer parameter axis is *sharded* over ``pp`` —
  each stage owns a contiguous block of ``num_layers / pp`` layers
  (``models/sharding.py::param_specs(pp_axis=...)``);
- inside one ``shard_map`` (manual over ``pp`` only — ``dp``/``tp`` stay
  under GSPMD via ``axis_names={pp}``), microbatches flow through the
  stages with a ``lax.ppermute`` ring shift per tick: the classic
  scan-over-ticks pipeline, one traced stage body regardless of depth;
- tick ``t`` injects microbatch ``t`` at stage 0 and collects finished
  microbatch ``t - (pp-1)`` at the last stage; after ``M + pp - 1`` ticks a
  ``lax.psum`` masked to the last stage broadcasts the outputs;
- bubble fraction is the GPipe ``(pp-1)/(M + pp - 1)``; raise
  ``num_microbatches`` to amortise it.

Forward and reverse differentiable (``ppermute``/``scan`` have exact
transpose rules), so the same code path serves the E2E forward benchmark
and the DDP/ZeRO training step.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from dlbb_tpu.models.configs import ModelConfig
from dlbb_tpu.models.sharding import PP_AXIS


def validate_pipeline(config: ModelConfig, n_stages: int, batch_size: int,
                      num_microbatches: Optional[int]) -> int:
    """Check divisibility and attention-mode constraints; returns the
    resolved microbatch count (default: one per stage)."""
    m = num_microbatches if num_microbatches is not None else n_stages
    if m < 1:
        raise ValueError(f"num_microbatches must be >= 1, got {m}")
    if config.num_layers % n_stages != 0:
        raise ValueError(
            f"num_layers={config.num_layers} not divisible by "
            f"pipeline_parallel={n_stages}"
        )
    if batch_size % m != 0:
        raise ValueError(
            f"batch_size={batch_size} not divisible by "
            f"num_microbatches={m}"
        )
    if config.attention not in ("full", "dense", "simplified"):
        raise ValueError(
            f"attention={config.attention!r} cannot run under pipeline "
            "parallelism (ring/ulysses/flash need their own shard_map; "
            "use attention='full'/'dense'/'simplified' with "
            "pipeline_parallel > 1)"
        )
    return m


def pipeline_forward(
    params,
    x: jax.Array,
    config: ModelConfig,
    mesh: Mesh,
    pp_axis: str = PP_AXIS,
    num_microbatches: Optional[int] = None,
    with_aux: bool = False,
):
    """Full-model forward with the layer stack pipelined over ``pp_axis``.

    ``params`` must hold the stacked-layer pytree of
    ``models/transformer.py::init_params`` with the leading layer axis
    sharded over ``pp_axis``; the final layernorm runs outside the
    pipeline (replicated, applied after the shard_map).

    ``with_aux=True`` additionally returns the MoE load-balancing loss,
    averaged over layers AND microbatches: each stage accumulates its
    local layers' aux for the microbatch it validly processes at each tick
    (bubble ticks masked out), and a ``psum`` over ``pp_axis`` totals the
    stages.  Mean-over-microbatches is the same approximation gradient
    accumulation makes (``moe_aux_loss`` is nonlinear in the batch, so it
    is not bit-identical to the unpipelined full-batch aux — the standard
    microbatching semantics).
    """
    from dlbb_tpu.models.transformer import _block, _layernorm

    n_stages = mesh.shape[pp_axis]
    m = validate_pipeline(config, n_stages, x.shape[0], num_microbatches)
    if config.attention == "full":
        # pin the einsum kernel inside the stage body: the TPU flash
        # auto-route would drop an opaque pallas_call under the shard_map's
        # auto dp/tp axes — the exact GSPMD pathology validate_pipeline
        # rejects attention='flash' for.  Same math either way.
        config = config.with_(attention="dense")

    layer_specs = jax.tree.map(lambda _: P(pp_axis), params["layers"])

    def stage_local(layers_local, x):
        # layers_local: this stage's [L/pp, ...] block; x: full [B, S, H]
        pp = lax.axis_index(pp_axis)
        mb = x.reshape(m, x.shape[0] // m, *x.shape[1:])
        state = lax.pcast(jnp.zeros_like(mb[0]), (pp_axis,), to="varying")
        outputs = lax.pcast(jnp.zeros_like(mb), (pp_axis,), to="varying")
        aux0 = lax.pcast(jnp.zeros((), jnp.float32), (pp_axis,),
                         to="varying")

        def local_fwd(h):
            def body(carry, layer):
                new_h, aux = _block(carry, layer, config)
                return new_h, aux

            if config.remat:
                body = jax.checkpoint(body, prevent_cse=False)
            h, auxs = lax.scan(body, h, layers_local)
            return h, auxs.sum()  # sum over this stage's local layers

        def tick(carry, t):
            state, outputs, aux_sum = carry
            inject = lax.dynamic_index_in_dim(
                mb, jnp.clip(t, 0, m - 1), 0, keepdims=False
            )
            y, aux = local_fwd(jnp.where(pp == 0, inject, state))
            # stage p processes microbatch t - p at tick t; outside
            # [0, m) it is running on bubble garbage — mask its aux out
            mb_idx = t - pp
            valid = jnp.logical_and(mb_idx >= 0, mb_idx < m)
            aux_sum = aux_sum + jnp.where(valid, aux, 0.0)
            out_t = t - (n_stages - 1)
            write = jnp.logical_and(
                pp == n_stages - 1,
                jnp.logical_and(out_t >= 0, out_t < m),
            )
            updated = lax.dynamic_update_index_in_dim(
                outputs, y, jnp.clip(out_t, 0, m - 1), 0
            )
            outputs = jnp.where(write, updated, outputs)
            state = lax.ppermute(
                y, pp_axis,
                [(i, (i + 1) % n_stages) for i in range(n_stages)],
            )
            return (state, outputs, aux_sum), None

        (_, outputs, aux_sum), _ = lax.scan(
            tick, (state, outputs, aux0), jnp.arange(m + n_stages - 1)
        )
        # only the last stage holds real outputs; the masked psum is the
        # SPMD broadcast back to every stage
        outputs = lax.psum(
            jnp.where(pp == n_stages - 1, outputs, jnp.zeros_like(outputs)),
            pp_axis,
        )
        # stages hold disjoint layer blocks: psum totals all layers x mbs
        aux_total = lax.psum(aux_sum, pp_axis)
        return outputs.reshape(x.shape), aux_total

    y, aux_total = shard_map(
        stage_local,
        mesh=mesh,
        in_specs=(layer_specs, P()),
        out_specs=(P(), P()),
        axis_names={pp_axis},
    )(params["layers"], x)
    out = _layernorm(y, params["ln_f"]["scale"], params["ln_f"]["bias"])
    if with_aux:
        return out, aux_total / (config.num_layers * m)
    return out
