"""Pipeline parallelism: GPipe-style microbatched pipeline over a ``pp``
mesh axis.

The reference has no pipeline parallelism (SURVEY §2.2: "PP — NO"); this is
a capability extension, designed TPU-first rather than as a port of any
torch pipeline engine:

- the transformer's stacked-layer parameter axis is *sharded* over ``pp`` —
  each stage owns a contiguous block of ``num_layers / pp`` layers
  (``models/sharding.py::param_specs(pp_axis=...)``);
- inside one ``shard_map`` (manual over ``pp`` only — ``dp``/``tp`` stay
  under GSPMD via ``axis_names={pp}``), microbatches flow through the
  stages with a ``lax.ppermute`` ring shift per tick: the classic
  scan-over-ticks pipeline, one traced stage body regardless of depth;
- tick ``t`` injects microbatch ``t`` at stage 0 and collects finished
  microbatch ``t - (pp-1)`` at the last stage; after ``M + pp - 1`` ticks a
  ``lax.psum`` masked to the last stage broadcasts the outputs;
- bubble fraction is the GPipe ``(pp-1)/(M + pp - 1)``; raise
  ``num_microbatches`` to amortise it.

Forward and reverse differentiable (``ppermute``/``scan`` have exact
transpose rules), so the same code path serves the E2E forward benchmark
and the DDP/ZeRO training step.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from dlbb_tpu.models.configs import ModelConfig
from dlbb_tpu.models.sharding import PP_AXIS


def validate_pipeline(config: ModelConfig, n_stages: int, batch_size: int,
                      num_microbatches: Optional[int]) -> int:
    """Check divisibility and attention-mode constraints; returns the
    resolved microbatch count (default: one per stage)."""
    m = num_microbatches if num_microbatches is not None else n_stages
    if m < 1:
        raise ValueError(f"num_microbatches must be >= 1, got {m}")
    if config.num_layers % n_stages != 0:
        raise ValueError(
            f"num_layers={config.num_layers} not divisible by "
            f"pipeline_parallel={n_stages}"
        )
    if batch_size % m != 0:
        raise ValueError(
            f"batch_size={batch_size} not divisible by "
            f"num_microbatches={m}"
        )
    if config.attention not in ("full", "simplified"):
        raise ValueError(
            f"attention={config.attention!r} cannot run under pipeline "
            "parallelism (ring/ulysses/flash need their own shard_map; "
            "use attention='full' or 'simplified' with pipeline_parallel > 1)"
        )
    return m


def pipeline_forward(
    params,
    x: jax.Array,
    config: ModelConfig,
    mesh: Mesh,
    pp_axis: str = PP_AXIS,
    num_microbatches: Optional[int] = None,
) -> jax.Array:
    """Full-model forward with the layer stack pipelined over ``pp_axis``.

    ``params`` must hold the stacked-layer pytree of
    ``models/transformer.py::init_params`` with the leading layer axis
    sharded over ``pp_axis``; the final layernorm runs outside the
    pipeline (replicated, applied after the shard_map).
    """
    from dlbb_tpu.models.transformer import _block, _layernorm

    n_stages = mesh.shape[pp_axis]
    m = validate_pipeline(config, n_stages, x.shape[0], num_microbatches)

    layer_specs = jax.tree.map(lambda _: P(pp_axis), params["layers"])

    def stage_local(layers_local, x):
        # layers_local: this stage's [L/pp, ...] block; x: full [B, S, H]
        pp = lax.axis_index(pp_axis)
        mb = x.reshape(m, x.shape[0] // m, *x.shape[1:])
        state = lax.pcast(jnp.zeros_like(mb[0]), (pp_axis,), to="varying")
        outputs = lax.pcast(jnp.zeros_like(mb), (pp_axis,), to="varying")

        def local_fwd(h):
            def body(carry, layer):
                new_h, _aux = _block(carry, layer, config)
                return new_h, None

            if config.remat:
                body = jax.checkpoint(body, prevent_cse=False)
            h, _ = lax.scan(body, h, layers_local)
            return h

        def tick(carry, t):
            state, outputs = carry
            inject = lax.dynamic_index_in_dim(
                mb, jnp.clip(t, 0, m - 1), 0, keepdims=False
            )
            y = local_fwd(jnp.where(pp == 0, inject, state))
            out_t = t - (n_stages - 1)
            write = jnp.logical_and(
                pp == n_stages - 1,
                jnp.logical_and(out_t >= 0, out_t < m),
            )
            updated = lax.dynamic_update_index_in_dim(
                outputs, y, jnp.clip(out_t, 0, m - 1), 0
            )
            outputs = jnp.where(write, updated, outputs)
            state = lax.ppermute(
                y, pp_axis,
                [(i, (i + 1) % n_stages) for i in range(n_stages)],
            )
            return (state, outputs), None

        (_, outputs), _ = lax.scan(
            tick, (state, outputs), jnp.arange(m + n_stages - 1)
        )
        # only the last stage holds real outputs; the masked psum is the
        # SPMD broadcast back to every stage
        outputs = lax.psum(
            jnp.where(pp == n_stages - 1, outputs, jnp.zeros_like(outputs)),
            pp_axis,
        )
        return outputs.reshape(x.shape)

    y = shard_map(
        stage_local,
        mesh=mesh,
        in_specs=(layer_specs, P()),
        out_specs=P(),
        axis_names={pp_axis},
    )(params["layers"], x)
    return _layernorm(y, params["ln_f"]["scale"], params["ln_f"]["bias"])
