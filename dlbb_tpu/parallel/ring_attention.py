"""Ring attention: exact attention (causal or bidirectional) over a
sequence-sharded mesh axis, with grouped-query K/V.

Each device owns one contiguous block of the sequence (queries stay put; key/
value blocks travel the ring).  At ring step ``j`` a device holds the KV
block originally owned by rank ``(rank - j) mod P``; it accumulates that
block's contribution to its local queries with the numerically-stable online
softmax (running max ``m``, normaliser ``l``, weighted accumulator ``acc`` —
the flash-attention recurrence), then forwards the KV block to the next
neighbour with ``lax.ppermute`` — which XLA lowers to neighbour ICI
transfers, overlapping the DMA with the current block's matmuls.

Causality is enforced through *global* positions (query block index is the
device's axis rank, key block index is the travelling block's origin), so
the result is bit-for-bit the causal attention of the unsharded sequence;
with ``causal=False`` the mask is omitted and every block pair attends —
bidirectional long context with the same ring schedule.

Memory per device is O(S/P · d + (S/P)²) — the (S/P)² logits tile — versus
O(S²) for dense attention, which is what makes million-token contexts
feasible on a pod.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dlbb_tpu.compat import shard_map

_NEG_INF = -1e30  # finite mask value: avoids exp(-inf + inf) = nan in the
# online-softmax rescale when a block is fully masked


def _ring_body(q, k0, v0, axis_name: str, num_blocks: int, causal: bool):
    """Local computation: q is this device's block [B, n, Sl, d]; k0, v0 are
    [B, kv_heads, Sl, d] — kv_heads == n for MHA, a divisor of n for
    grouped-query attention (query-head groups share K/V heads via einsum
    broadcasting; K/V stay at kv_heads width both in memory AND on the
    ring, so GQA shrinks the per-step ppermute payload by n/kv_heads)."""
    b, n, sl, d = q.shape
    kvh = k0.shape[1]
    g = n // kvh
    scale = 1.0 / math.sqrt(d)
    my_block = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % num_blocks) for i in range(num_blocks)]

    # grouped view [B, kvh, g, Sl, d] — g == 1 reduces to plain MHA
    q32 = q.astype(jnp.float32).reshape(b, kvh, g, sl, d)
    pos_q = my_block * sl + jnp.arange(sl)  # global query positions

    def attend(j, k_cur, v_cur, m, l, acc):
        """Accumulate ring-step-j's KV block into the online softmax."""
        src = (my_block - j) % num_blocks  # origin rank of the current KV
        logits = (
            jnp.einsum("bhgqd,bhkd->bhgqk", q32, k_cur.astype(jnp.float32))
            * scale
        )
        if causal:
            pos_k = src * sl + jnp.arange(sl)
            mask = pos_k[None, :] <= pos_q[:, None]  # [Sl_q, Sl_k]
            logits = jnp.where(mask, logits, _NEG_INF)
        m_new = jnp.maximum(m, logits.max(axis=-1))
        p = jnp.exp(logits - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        acc_new = acc * corr[..., None] + jnp.einsum(
            "bhgqk,bhkd->bhgqd", p, v_cur.astype(jnp.float32)
        )
        return m_new, l_new, acc_new

    def step(j, carry):
        k_cur, v_cur, m, l, acc = carry
        m, l, acc = attend(j, k_cur, v_cur, m, l, acc)
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return k_next, v_next, m, l, acc

    # accumulators derive from q so they carry q's shard_map varying-axes
    # type (a constant init would be unvarying-in/varying-out, which the
    # scan carry check rejects)
    m0 = q32[..., 0] * 0.0 + _NEG_INF
    l0 = q32[..., 0] * 0.0
    acc0 = q32 * 0.0
    # first num_blocks-1 steps attend-and-forward; the last block is consumed
    # without a final ppermute (its result would be discarded)
    k_last, v_last, m, l, acc = lax.fori_loop(
        0, num_blocks - 1, step, (k0, v0, m0, l0, acc0)
    )
    m, l, acc = attend(num_blocks - 1, k_last, v_last, m, l, acc)
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.reshape(b, n, sl, d).astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    sp_axis: str = "sp",
    causal: bool = True,
    batch_axes: Sequence[str] = ("dp",),
) -> jax.Array:
    """Exact attention (causal or bidirectional) with the sequence dim
    sharded over ``sp_axis``.

    q: global ``[B, num_heads, S, head_dim]``; k, v: same, or grouped-query
    ``[B, kv_heads, S, head_dim]`` with ``num_heads % kv_heads == 0`` —
    K/V stay at kv_heads width in memory and on the ring.  S must divide
    evenly over the ``sp_axis`` mesh size.  Batch may additionally be
    sharded over ``batch_axes`` (those present in the mesh).

    ``causal=False`` attends every query block to every travelling KV
    block (the per-step mask is simply omitted; the online-softmax
    recurrence and ring schedule are position-agnostic, so no skew or
    rank-dependent scheduling is involved).
    """
    if sp_axis not in mesh.axis_names:
        raise ValueError(
            f"mesh {mesh.axis_names} has no {sp_axis!r} axis for ring attention"
        )
    num_blocks = mesh.shape[sp_axis]
    if q.shape[2] % num_blocks != 0:
        raise ValueError(
            f"sequence length {q.shape[2]} not divisible by sp={num_blocks}"
        )
    if q.shape[1] % k.shape[1] != 0:
        raise ValueError(
            f"num_heads {q.shape[1]} not divisible by kv_heads {k.shape[1]}"
        )
    bspec = tuple(a for a in batch_axes if a in mesh.axis_names) or None
    spec = P(bspec, None, sp_axis, None)
    fn = shard_map(
        lambda q_, k_, v_: _ring_body(q_, k_, v_, sp_axis, num_blocks, causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
