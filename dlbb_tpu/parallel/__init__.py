"""Sequence/context and pipeline parallelism.

The reference scales sequence length only as a *payload dimension* (3D sweeps
up to seq 8192, SURVEY §5.7) — it has no sequence-parallel attention and no
pipeline parallelism (SURVEY §2.2).  A TPU-native long-context framework
needs real context parallelism, so this package provides both standard
schemes plus a pipeline engine:

- **ring attention** (``ring_attention``): KV blocks circulate the ICI ring
  via ``lax.ppermute`` while each device accumulates flash-style online
  softmax for its local query block — O(S/P) memory per device, comm
  overlapped with compute by XLA.
- **Ulysses** (``ulysses_attention``): ``lax.all_to_all`` reshards sequence
  shards into head shards, runs dense local attention per head group, and
  reshards back — 2 all-to-alls per layer, requires num_heads % sp == 0.
- **pipeline** (``pipeline_forward``): GPipe-style microbatched pipeline
  over a ``pp`` mesh axis — layer stack sharded across stages, activations
  shifted with ``ppermute`` per tick, differentiable end to end.

Ring/Ulysses are exact (tested against single-device dense attention) and
causal; the pipeline is exact against the single-device layer scan.
"""

from dlbb_tpu.parallel.pipeline import pipeline_forward
from dlbb_tpu.parallel.ring_attention import ring_attention
from dlbb_tpu.parallel.ulysses import ulysses_attention

__all__ = ["pipeline_forward", "ring_attention", "ulysses_attention"]
