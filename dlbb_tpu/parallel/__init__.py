"""Sequence/context parallelism.

The reference scales sequence length only as a *payload dimension* (3D sweeps
up to seq 8192, SURVEY §5.7) — it has no sequence-parallel attention.  A
TPU-native long-context framework needs real context parallelism, so this
package provides both standard schemes:

- **ring attention** (``ring_attention``): KV blocks circulate the ICI ring
  via ``lax.ppermute`` while each device accumulates flash-style online
  softmax for its local query block — O(S/P) memory per device, comm
  overlapped with compute by XLA.
- **Ulysses** (``ulysses_attention``): ``lax.all_to_all`` reshards sequence
  shards into head shards, runs dense local attention per head group, and
  reshards back — 2 all-to-alls per layer, requires num_heads % sp == 0.

Both are exact (tested against single-device dense attention) and causal.
"""

from dlbb_tpu.parallel.ring_attention import ring_attention
from dlbb_tpu.parallel.ulysses import ulysses_attention

__all__ = ["ring_attention", "ulysses_attention"]
