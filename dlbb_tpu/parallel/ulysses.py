"""Ulysses (all-to-all) sequence parallelism.

DeepSpeed-Ulysses-style context parallelism, TPU-native: the sequence dim is
sharded over ``sp_axis``; two ``lax.all_to_all``s reshard [B, n, S/P, d]
(sequence-sharded) into [B, n/P, S, d] (head-sharded), dense causal attention
runs per local head group over the *full* sequence, and a second all-to-all
reshards back.  Communication volume is 2 x activations per layer, rides the
ICI, and — unlike ring attention — latency does not grow with P, at the cost
of requiring ``num_heads % P == 0`` and O(S²) logits per head group.
"""

from __future__ import annotations

from typing import Sequence

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dlbb_tpu.compat import shard_map
from dlbb_tpu.models.attention import dense_attention as _dense_attention


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    sp_axis: str = "sp",
    causal: bool = True,
    batch_axes: Sequence[str] = ("dp",),
) -> jax.Array:
    """Exact attention (causal or bidirectional) with sequence sharded over
    ``sp_axis`` via head resharding.  q: global
    ``[B, num_heads, S, head_dim]``; k, v: same, or grouped-query
    ``[B, kv_heads, S, head_dim]`` — both head counts must divide by the
    ``sp_axis`` mesh size (each device then holds ``num_heads/P`` query
    heads and ``kv_heads/P`` K/V heads after the all-to-all, and the
    per-group dense kernel shares K/V via einsum broadcasting — the
    all-to-all payload for K/V shrinks by ``num_heads/kv_heads``)."""
    if sp_axis not in mesh.axis_names:
        raise ValueError(
            f"mesh {mesh.axis_names} has no {sp_axis!r} axis for ulysses"
        )
    p = mesh.shape[sp_axis]
    num_heads, kv_heads = q.shape[1], k.shape[1]
    if num_heads % p != 0:
        raise ValueError(
            f"ulysses needs num_heads ({num_heads}) divisible by "
            f"sp={p}; use ring attention instead"
        )
    if kv_heads % p != 0:
        raise ValueError(
            f"ulysses needs kv_heads ({kv_heads}) divisible by sp={p}; "
            "broadcast K/V to num_heads first, or use ring attention "
            "(which keeps grouped K/V for any kv_heads)"
        )
    bspec = tuple(a for a in batch_axes if a in mesh.axis_names) or None
    spec = P(bspec, None, sp_axis, None)

    def body(q_, k_, v_):  # local [B, n, S/P, d]
        # seq-sharded -> head-sharded: split heads, gather sequence
        qh = lax.all_to_all(q_, sp_axis, split_axis=1, concat_axis=2, tiled=True)
        kh = lax.all_to_all(k_, sp_axis, split_axis=1, concat_axis=2, tiled=True)
        vh = lax.all_to_all(v_, sp_axis, split_axis=1, concat_axis=2, tiled=True)
        oh = _dense_attention(qh, kh, vh, causal=causal)  # [B, n/P, S, d]
        # head-sharded -> seq-sharded
        return lax.all_to_all(oh, sp_axis, split_axis=2, concat_axis=1, tiled=True)

    fn = shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )
    return fn(q, k, v)
