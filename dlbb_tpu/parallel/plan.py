"""Shared parallelism-plan resolution for the E2E and train harnesses.

One place that parses the YAML ``parallelism:`` section, runs every
validation (device preflight — parity with reference ``run_mpi.py:73-77`` —
attention/sp, MoE/ep, pipeline divisibility), and builds the mesh; the two
harnesses consume the resulting plan instead of duplicating the logic.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Sequence

import jax
from jax.sharding import Mesh

from dlbb_tpu.comm.mesh import build_parallelism_mesh
from dlbb_tpu.models.configs import (
    ModelConfig,
    validate_attention_parallelism,
    validate_expert_parallelism,
    validate_tp_overlap,
)
from dlbb_tpu.parallel.pipeline import validate_pipeline


@dataclass(frozen=True)
class ParallelismPlan:
    dp: int
    sp: int
    pp: int
    ep: int
    tp: int
    num_microbatches: Optional[int]
    mesh: Mesh
    # the model's TP collective-matmul schedule ("off" | "ring" | "bidir"),
    # copied from the resolved ModelConfig so harnesses can record it next
    # to the mesh in result JSON
    tp_overlap: str = "off"

    @classmethod
    def from_config(
        cls,
        config: dict[str, Any],
        model_cfg: ModelConfig,
        devices: Optional[Sequence] = None,
    ) -> "ParallelismPlan":
        par = config.get("parallelism", {})
        tp = par.get("world_size", 1)
        dp = par.get("data_parallel", 1)
        sp = par.get("sequence_parallel", 1)
        pp = par.get("pipeline_parallel", 1)
        ep = par.get("expert_parallel", 1)
        num_microbatches = par.get("num_microbatches")

        needed = tp * dp * sp * pp * ep
        n_avail = len(devices) if devices is not None else len(jax.devices())
        if needed > n_avail:
            raise ValueError(
                f"config needs {needed} devices (tp={tp} x dp={dp} x "
                f"sp={sp} x pp={pp} x ep={ep}), only {n_avail} available"
            )

        validate_attention_parallelism(model_cfg, sp)
        validate_expert_parallelism(model_cfg, ep)
        validate_tp_overlap(
            model_cfg, tp, pp=pp, sp=sp,
            seq_len=config.get("input", {}).get("sequence_length", 0),
        )
        if pp > 1:
            num_microbatches = validate_pipeline(
                model_cfg, pp, config["input"]["batch_size"],
                num_microbatches,
            )
        elif num_microbatches is not None:
            raise ValueError(
                "parallelism.num_microbatches requires "
                "pipeline_parallel > 1 (microbatching is the pipeline's "
                "schedule; without pp it would silently be ignored)"
            )

        mesh = build_parallelism_mesh(dp, sp, pp, tp, ep, devices=devices)
        return cls(dp, sp, pp, ep, tp, num_microbatches, mesh,
                   tp_overlap=model_cfg.tp_overlap)

    def mesh_dict(self) -> dict[str, int]:
        """The result-JSON ``mesh`` field."""
        return {"dp": self.dp, "sp": self.sp, "pp": self.pp,
                "ep": self.ep, "tp": self.tp}
