"""Pure-JAX tensor-parallel decoder.

Forward semantics match reference ``models.py:107-245`` (pre-LN block:
ln1 → QKV col-parallel → attention → out-proj row-parallel → residual;
ln2 → FFN-up col-parallel → gelu → FFN-down row-parallel → residual; final
LN), re-designed for XLA:

- layers are stacked on a leading axis and executed with ``lax.scan`` —
  one traced layer body regardless of depth (compile time O(1) in layers,
  unlike a Python loop over 40 blocks);
- parallelism comes from partition specs (see ``sharding.py``), not
  hand-written collectives;
- layernorm statistics are computed in fp32 and cast back (bf16-safe);
- ``attention="simplified"`` replicates the reference's take-the-query-third
  shortcut (``models.py:162-167``); ``attention="full"`` is causal MHA with
  fp32 softmax.

No code is shared with the reference; citations are for parity auditing.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding

from dlbb_tpu.models.configs import ModelConfig
from dlbb_tpu.models.sharding import PP_AXIS, specs_for_mesh

Params = dict[str, Any]


def _dtype_of(name: str):
    return {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
            "float16": jnp.float16}[name]


def init_params(config: ModelConfig, key: jax.Array) -> Params:
    """Initialise the stacked-layer parameter pytree.

    Scaled-normal kernels (1/sqrt(fan_in)), zero biases, unit LN scales —
    standard init; the reference's randn-based init is at ``models.py:33-38``.
    """
    h, f, L = config.hidden_size, config.ffn_intermediate, config.num_layers
    dtype = _dtype_of(config.dtype)

    def kernel(key, shape, fan_in):
        # sample directly in the target dtype — avoids a transient fp32 copy
        # of each kernel (full-model memory is addressed by
        # init_params_sharded, which materialises shards in place)
        return jax.random.normal(key, shape, dtype=dtype) / math.sqrt(fan_in)

    ks = jax.random.split(key, 5)
    if config.is_moe:
        E = config.num_experts
        ffn = {
            # router logits in the params dtype; gating math runs in fp32
            "router": {"kernel": kernel(ks[4], (L, h, E), h)},
            "ffn_up": {
                "kernel": kernel(ks[2], (L, E, h, f), h),
                "bias": jnp.zeros((L, E, f), dtype),
            },
            "ffn_down": {
                "kernel": kernel(ks[3], (L, E, f, h), f),
                "bias": jnp.zeros((L, E, h), dtype),
            },
        }
    else:
        ffn = {
            "ffn_up": {
                "kernel": kernel(ks[2], (L, h, f), h),
                "bias": jnp.zeros((L, f), dtype),
            },
            "ffn_down": {
                "kernel": kernel(ks[3], (L, f, h), f),
                "bias": jnp.zeros((L, h), dtype),
            },
        }
    layers = {
        "ln1": {"scale": jnp.ones((L, h), dtype), "bias": jnp.zeros((L, h), dtype)},
        "qkv": {
            # qkv_width = H + 2 * kv_heads * head_dim (GQA shrinks the
            # K/V thirds; == 3H for full MHA)
            "kernel": kernel(ks[0], (L, h, config.qkv_width), h),
            "bias": jnp.zeros((L, config.qkv_width), dtype),
        },
        "out": {
            "kernel": kernel(ks[1], (L, h, h), h),
            "bias": jnp.zeros((L, h), dtype),
        },
        "ln2": {"scale": jnp.ones((L, h), dtype), "bias": jnp.zeros((L, h), dtype)},
        **ffn,
    }
    return {
        "layers": layers,
        "ln_f": {"scale": jnp.ones((h,), dtype), "bias": jnp.zeros((h,), dtype)},
    }


def _layernorm(x, scale, bias):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + 1e-5)
    return (y * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(x.dtype)


def _attention(qkv, config: ModelConfig, mesh=None, sp_axis: str = "sp"):
    """qkv: [B, S, qkv_width] -> [B, S, H]."""
    if config.attention == "simplified":
        # reference's benchmarking shortcut: the query projection IS the
        # attention output (``models.py:162-167``)
        return qkv[:, :, : config.hidden_size]

    b, s, _ = qkv.shape
    n, d, kvh = config.num_heads, config.head_dim, config.kv_heads
    h = config.hidden_size
    q = qkv[:, :, :h]
    k = qkv[:, :, h:h + kvh * d]
    v = qkv[:, :, h + kvh * d:]

    def heads(t, nh):  # [B, S, nh*d] -> [B, nh, S, d]
        return t.reshape(b, s, nh, d).transpose(0, 2, 1, 3)

    q, k, v = heads(q, n), heads(k, kvh), heads(v, kvh)
    # Grouped K/V flow at kv_heads width end-to-end through every kernel
    # (dense einsum broadcasting; grouped flash blocks; grouped ring/
    # Ulysses).  The only broadcasts left are sharding fallbacks where a
    # mesh axis cannot divide kv_heads — marked below.

    if config.attention in ("ring", "ulysses"):
        # sequence/context-parallel attention over the mesh's sp axis
        if mesh is None or sp_axis not in mesh.axis_names:
            raise ValueError(
                f"attention={config.attention!r} needs a mesh with a "
                f"{sp_axis!r} axis passed to forward()"
            )
        from dlbb_tpu.parallel import ring_attention, ulysses_attention

        if config.attention == "ring":
            o = ring_attention(q, k, v, mesh, sp_axis=sp_axis,
                               causal=config.causal)
        else:
            if kvh != n and kvh % mesh.shape[sp_axis] != 0:
                # Ulysses all-to-alls the head dim over sp; kv_heads not
                # divisible by sp cannot stay grouped — broadcast fallback
                # (ring attention keeps grouped K/V for any kv_heads)
                k = jnp.repeat(k, n // kvh, axis=1)
                v = jnp.repeat(v, n // kvh, axis=1)
            o = ulysses_attention(q, k, v, mesh, sp_axis=sp_axis,
                                  causal=config.causal)
    elif config.attention == "flash":
        o = _flash_dispatch(q, k, v, config, mesh, sp_axis)
    else:  # "full" (auto-routed exact) | "dense" (forced dense kernel)
        from dlbb_tpu.models.attention import dense_attention

        sp_sharded = (mesh is not None and sp_axis in mesh.axis_names
                      and mesh.shape[sp_axis] > 1)
        if (config.attention == "full" and not sp_sharded
                and _flash_profitable(q.shape)):
            # exact numerics either way; the blocked kernel avoids the
            # [B, N, S, S] score materialisation that throttles (and at
            # S=8192 OOMs) the dense path
            o = _flash_dispatch(q, k, v, config, mesh, sp_axis)
        else:
            o = dense_attention(q, k, v, causal=config.causal)
    return o.transpose(0, 2, 1, 3).reshape(b, s, n * d)


# Route "full" attention through the pallas kernel on real TPUs at
# sequence lengths where it measurably wins; the simulated/CPU dev mesh
# keeps the dense einsum (interpret-mode pallas would be pure overhead).
# Gate calibration (v5e chip, bf16, committed e2e artifacts
# results/e2e/xla_tpu_{1b,7b}_{dense,flash}_s512_world1.json — "dense"
# pins the un-routed kernel, so these pairs stay a real comparison across
# publisher re-runs): at S=512 in-model flash beats dense 1.10x on 1B
# (63.5k vs 57.5k tok/s) and 1.03x on 7B (12.45k vs 12.11k), and the gap
# widens with S (1.31x at S=1024, dense OOMs by 8192).  Standalone
# (outside the model) dense still wins small shapes (B8/N16/D128 S=512:
# 0.29 ms vs 0.41 ms) — in-model numbers govern the route, standalone
# callers pick their own kernel.
FLASH_ROUTE_MIN_SEQ = 512


def _flash_profitable(q_shape) -> bool:
    import jax as _jax

    # lane-aligned sequence required: _fit_block falls back to the largest
    # divisor, and an unfriendly S (e.g. prime) would degrade the grid to
    # tiny blocks — far slower than the dense einsum being replaced
    return (_jax.default_backend() == "tpu"
            and q_shape[2] >= FLASH_ROUTE_MIN_SEQ
            and q_shape[2] % 128 == 0)


def _flash_dispatch(q, k, v, config: ModelConfig, mesh, sp_axis: str):
    """Run the pallas flash kernel under the sharding the mesh dictates.

    pallas_call is opaque to GSPMD — without an explicit shard_map, jit
    would all-gather the batch-(dp) and head-(tp) sharded qkv and run the
    kernel replicated on every device.  Batch entries and heads are
    independent, so map the kernel over whichever of (dp, tp) is actually
    sharded; each device computes only its own slice.
    """
    from dlbb_tpu.ops import flash_attention

    n, kvh = q.shape[1], k.shape[1]
    if mesh is not None and sp_axis in mesh.axis_names and mesh.shape[sp_axis] > 1:
        raise ValueError(
            "attention='flash' does not partition the sequence; use "
            "attention='ring' or 'ulysses' when sequence_parallel > 1"
        )
    dp = (
        "dp" if mesh is not None and "dp" in mesh.axis_names
        and mesh.shape["dp"] > 1 else None
    )
    tp = (
        "tp" if mesh is not None and "tp" in mesh.axis_names
        and mesh.shape["tp"] > 1 else None
    )
    if dp is not None or tp is not None:
        from jax.sharding import PartitionSpec as P

        from dlbb_tpu.compat import shard_map

        if kvh != n and tp is not None and kvh % mesh.shape[tp] != 0:
            # the head axis is tp-sharded; kv_heads not divisible by
            # tp cannot stay grouped — broadcast fallback
            k = jnp.repeat(k, n // kvh, axis=1)
            v = jnp.repeat(v, n // kvh, axis=1)
        spec = P(dp, tp, None, None)
        return shard_map(
            lambda q, k, v: flash_attention(
                q, k, v, causal=config.causal),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,  # pallas_call declares no vma
        )(q, k, v)
    return flash_attention(q, k, v, causal=config.causal)


def router_probs_gates(logits: jax.Array, k: int) -> tuple[jax.Array, jax.Array]:
    """Full fp32 softmax router distribution and the sparse top-k routing
    weights (k largest probabilities renormalised to sum 1 — Mixtral-style
    gating).  Returns ``(probs, gates)``, both [..., E]; gates have exactly
    k nonzeros."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    top_vals, top_idx = jax.lax.top_k(probs, k)
    mask = jax.nn.one_hot(top_idx, logits.shape[-1],
                          dtype=probs.dtype).sum(axis=-2)
    gated = probs * mask
    return probs, gated / gated.sum(axis=-1, keepdims=True)


def top_k_gates(logits: jax.Array, k: int) -> jax.Array:
    """Sparse top-k routing weights; see ``router_probs_gates``."""
    return router_probs_gates(logits, k)[1]


def moe_aux_loss(probs: jax.Array, gates: jax.Array, k: int) -> jax.Array:
    """Switch-Transformer load-balancing loss, generalised to top-k:
    ``E * sum_e f_e * P_e`` with ``f_e`` the fraction of routing slots sent
    to expert e and ``P_e`` its mean router probability.  Equals 1.0 at
    perfect balance, grows as routing collapses onto few experts."""
    num_experts = probs.shape[-1]
    f = (gates > 0).astype(jnp.float32).mean(axis=(0, 1)) / k
    p = probs.mean(axis=(0, 1))
    return num_experts * jnp.sum(f * p)


def _moe_ffn_dense(y, gates32, layer: Params, config: ModelConfig):
    """Top-k gated mixture-of-experts FFN: [B, S, H] -> [B, S, H].

    Dense-dispatch design: every expert runs on every token and the gate
    weights (zero outside the top-k) select the combination.  Static
    shapes, no token dropping, exact under any sharding; with the expert
    dim sharded over ``ep`` each device computes only its local experts
    and the final gate contraction becomes the psum over ``ep`` (GSPMD).
    """
    gates = gates32.astype(y.dtype)
    up = jnp.einsum("bsh,ehf->bsef", y, layer["ffn_up"]["kernel"])
    up = up + layer["ffn_up"]["bias"][None, None, :, :]
    act = jax.nn.gelu(up)
    per_expert = jnp.einsum("bsef,efh->bseh", act,
                            layer["ffn_down"]["kernel"])
    per_expert = per_expert + layer["ffn_down"]["bias"][None, None, :, :]
    return jnp.einsum("bseh,bse->bsh", per_expert, gates)


def moe_capacity(config: ModelConfig, seq_len: int) -> int:
    """Per-expert capacity slots per sequence (GShard formula:
    capacity_factor * tokens * k / E, floored at 1 and capped at seq_len —
    an expert can never receive more than the group's tokens)."""
    c = math.ceil(
        config.moe_capacity_factor * seq_len * config.moe_top_k
        / config.num_experts
    )
    return max(1, min(c, seq_len))


def _moe_ffn_capacity(y, gates, layer: Params, config: ModelConfig):
    """GShard-style capacity-bounded einsum dispatch: [B, S, H] -> [B, S, H].

    Each sequence is a dispatch group; every expert gets a fixed buffer of
    ``moe_capacity(config, S)`` slots per group, and (token, expert)
    routing slots claim buffer slots in sequence order via a per-expert
    cumulative count.  Over-capacity *routing slots* are dropped
    individually: with top-k > 1 a token can lose one expert's
    contribution while keeping another's (at its un-renormalised gate
    weight); a token dropped by every selected expert flows through the
    block's residual only.  All static shapes; per-device expert FLOPs are
    capacity-bounded rather than all-tokens x all-experts; the combine
    contraction over the expert dim lowers to the ``ep`` psum under GSPMD,
    exactly like dense dispatch.
    """
    b, s, _ = y.shape
    cap = moe_capacity(config, s)
    mask = gates > 0
    # slot index each token would take in each expert's queue (per group)
    pos = jnp.cumsum(mask.astype(jnp.int32), axis=1) - 1     # [B, S, E]
    keep = jnp.logical_and(mask, pos < cap)
    dispatch = (
        jax.nn.one_hot(pos, cap, dtype=y.dtype)
        * keep[..., None].astype(y.dtype)
    )                                                        # [B, S, E, C]
    expert_in = jnp.einsum("bsec,bsh->bech", dispatch, y)    # [B, E, C, H]
    up = jnp.einsum("bech,ehf->becf", expert_in,
                    layer["ffn_up"]["kernel"])
    up = up + layer["ffn_up"]["bias"][None, :, None, :]
    act = jax.nn.gelu(up)
    out = jnp.einsum("becf,efh->bech", act, layer["ffn_down"]["kernel"])
    out = out + layer["ffn_down"]["bias"][None, :, None, :]
    combine = dispatch * gates[..., None].astype(y.dtype)    # [B, S, E, C]
    return jnp.einsum("bsec,bech->bsh", combine, out)


def _moe_ffn(y, layer: Params, config: ModelConfig):
    """Route + dispatch: returns ``(out, aux)`` — the FFN output and the
    layer's load-balancing loss (``moe_aux_loss``).  Routing is shared;
    only the dispatch strategy differs between dense and capacity."""
    logits = y @ layer["router"]["kernel"]                  # [B, S, E]
    probs, gates = router_probs_gates(logits, config.moe_top_k)  # fp32
    if config.moe_dispatch == "capacity":
        out = _moe_ffn_capacity(y, gates, layer, config)
    else:
        out = _moe_ffn_dense(y, gates, layer, config)
    return out, moe_aux_loss(probs, gates, config.moe_top_k)


def _use_tp_overlap(config: ModelConfig, mesh) -> bool:
    """Whether this (config, mesh) pair routes TP projections through the
    ring-decomposed collective matmuls (``parallel/collective_matmul.py``).
    The knob is inert without a >1 tp axis, so single-device runs and
    non-TP meshes keep the GSPMD lowering bit for bit."""
    return (config.tp_overlap != "off" and mesh is not None
            and "tp" in getattr(mesh, "axis_names", ())
            and mesh.shape["tp"] > 1)


def _block(x, layer: Params, config: ModelConfig, mesh=None,
           sp_axis: str = "sp"):
    """One transformer block (reference ``TransformerBlock.forward``
    ``models.py:147-190``); the FFN is the gated-expert mixture when
    ``config.num_experts > 0``.

    With ``tp_overlap`` on, the four TP projections run as ring-decomposed
    collective matmuls: the residual stream x enters sequence-sharded over
    tp, each column-parallel projection gathers it behind partial matmuls
    (``allgather_matmul``) and each row-parallel projection returns it to
    the sequence-sharded layout behind the same ring
    (``matmul_reducescatter``) — no exposed TP all-reduce remains.

    Returns ``(x, aux)`` — aux is the layer's MoE load-balancing loss
    (0.0 for the dense FFN)."""
    if _use_tp_overlap(config, mesh):
        from dlbb_tpu.parallel.collective_matmul import (
            allgather_matmul,
            matmul_reducescatter,
        )

        sched = config.tp_overlap

        def col(y, kernel, bias):
            return allgather_matmul(y, kernel, mesh, schedule=sched) + bias

        def row(y, kernel, bias):
            return matmul_reducescatter(y, kernel, mesh,
                                        schedule=sched) + bias
    else:
        def col(y, kernel, bias):
            return y @ kernel + bias

        def row(y, kernel, bias):
            return y @ kernel + bias

    residual = x
    y = _layernorm(x, layer["ln1"]["scale"], layer["ln1"]["bias"])
    qkv = col(y, layer["qkv"]["kernel"], layer["qkv"]["bias"])
    attn = _attention(qkv, config, mesh, sp_axis)
    x = row(attn, layer["out"]["kernel"], layer["out"]["bias"]) + residual

    residual = x
    y = _layernorm(x, layer["ln2"]["scale"], layer["ln2"]["bias"])
    if config.is_moe:
        ffn_out, aux = _moe_ffn(y, layer, config)
        x = ffn_out + residual
    else:
        y = col(y, layer["ffn_up"]["kernel"], layer["ffn_up"]["bias"])
        y = jax.nn.gelu(y)
        x = row(y, layer["ffn_down"]["kernel"],
                layer["ffn_down"]["bias"]) + residual
        aux = jnp.zeros((), jnp.float32)
    return x, aux


def forward(params: Params, x: jax.Array, config: ModelConfig,
            mesh=None, sp_axis: str = "sp", pp_axis: str = PP_AXIS,
            num_microbatches=None, with_aux: bool = False):
    """Full forward pass: scan over stacked layers + final LN
    (reference ``LLM.forward`` ``models.py:224-237``).

    ``mesh`` is required only for sequence-parallel attention modes
    ("ring"/"ulysses") and pipeline parallelism, whose shard_maps need the
    concrete mesh.  A mesh with a >1-sized ``pp_axis`` dispatches to the
    microbatched pipeline engine (``dlbb_tpu/parallel/pipeline.py``).

    ``with_aux=True`` additionally returns the layer-mean MoE
    load-balancing loss (``moe_aux_loss``); under pipeline parallelism it
    is additionally averaged over microbatches (per-stage masked
    accumulation + psum — see ``pipeline_forward``).
    """
    if (mesh is not None and pp_axis in mesh.axis_names
            and mesh.shape[pp_axis] > 1):
        from dlbb_tpu.parallel.pipeline import pipeline_forward

        return pipeline_forward(
            params, x, config, mesh, pp_axis=pp_axis,
            num_microbatches=num_microbatches, with_aux=with_aux,
        )

    if _use_tp_overlap(config, mesh):
        # pin the residual stream to the sequence-sharded-over-tp layout
        # BEFORE the scan: the carry's sharding must be stable across
        # iterations (every block returns this layout), and constraining
        # the entry point keeps GSPMD from resharding per iteration
        from dlbb_tpu.parallel.collective_matmul import activation_spec

        x = jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, activation_spec(mesh))
        )

    def body(carry, layer):
        return _block(carry, layer, config, mesh, sp_axis)

    if config.remat:
        # prevent_cse=False: safe and faster under lax.scan, whose loop
        # structure already rules out the CSE the default barriers guard.
        # Policy selects WHAT each block saves (configs.ModelConfig
        # remat_policy): "full" saves nothing, "dots" saves matmul outputs
        # and recomputes only elementwise ops.
        policy = (jax.checkpoint_policies.dots_saveable
                  if config.remat_policy == "dots" else None)
        body = jax.checkpoint(body, prevent_cse=False, policy=policy)
    x, auxs = jax.lax.scan(body, x, params["layers"])
    y = _layernorm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])
    if with_aux:
        return y, auxs.mean()
    return y


def num_parameters(config: ModelConfig) -> int:
    """Total parameter count (reference ``get_num_parameters``
    ``models.py:239-241``; MoE counts every expert + router)."""
    h, f, L = config.hidden_size, config.ffn_intermediate, config.num_layers
    if config.is_moe:
        E = config.num_experts
        ffn = h * E + E * (h * f + f) + E * (f * h + h)  # router + experts
    else:
        ffn = (h * f + f) + (f * h + h)
    qkvw = config.qkv_width
    per_layer = (
        2 * h            # ln1
        + h * qkvw + qkvw  # fused qkv (GQA-aware width)
        + h * h + h      # out
        + 2 * h          # ln2
        + ffn
    )
    return L * per_layer + 2 * h  # + final LN


def forward_flops(config: ModelConfig, batch_size: int, seq_len: int) -> int:
    """Analytic forward-pass FLOPs for a [B, S, H] batch (matmul and
    dispatch einsum multiply-adds counted as 2 FLOPs; layernorms, gelu,
    softmax, and gating omitted — sub-percent).  Used for
    achieved-TFLOP/s reporting in the harnesses."""
    h, f, L = config.hidden_size, config.ffn_intermediate, config.num_layers
    tokens = batch_size * seq_len
    qkv = 2 * tokens * h * config.qkv_width
    out = 2 * tokens * h * h
    if config.attention == "simplified":
        attn = 0  # the reference's shortcut has no attention matmuls
    else:
        attn = 4 * batch_size * seq_len * seq_len * h  # QK^T + AV
    if config.is_moe:
        E = config.num_experts
        router = 2 * tokens * h * E
        if config.moe_dispatch == "capacity":
            cap = moe_capacity(config, seq_len)
            slots = batch_size * E * cap
            # the one-hot dispatch and combine einsums
            # ('bsec,bsh->bech' / 'bsec,bech->bsh') are dense over
            # [B, S, E, C] x H and dominate for long sequences
            dispatch = 2 * (2 * tokens * E * cap * h)
        else:
            slots = tokens * E
            dispatch = 2 * tokens * E * h  # gate combine 'bseh,bse->bsh'
        ffn = router + dispatch + 2 * slots * h * f * 2
    else:
        ffn = 2 * tokens * h * f * 2
    return L * (qkv + attn + out + ffn)


def shard_params(params: Params, mesh: Mesh, tp_axis: str = "tp") -> Params:
    """Place a parameter pytree onto the mesh with the Megatron TP layout
    (plus layer-stack pp / expert ep sharding when the mesh has those
    axes; MoE is detected from the pytree structure)."""
    specs = specs_for_mesh(mesh, tp_axis, moe="router" in params["layers"])
    return jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs
    )


def init_params_sharded(
    config: ModelConfig, key: jax.Array, mesh: Mesh, tp_axis: str = "tp"
) -> Params:
    """Initialise parameters *directly sharded* onto the mesh.

    jit with sharded out-shardings makes XLA generate each device's shard in
    place (partitionable threefry), so no device ever holds the full
    replicated pytree — required for 7B/13B on 16 GB-HBM chips, where
    ``init_params`` + ``shard_params`` would materialise the whole model on
    the default device first.
    """
    shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        specs_for_mesh(mesh, tp_axis, moe=config.is_moe),
        is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec),
    )
    return jax.jit(
        lambda k: init_params(config, k), out_shardings=shardings
    )(key)
