"""GSPMD partition specs for the TP transformer.

The reference implements tensor parallelism imperatively: per-rank weight
shards plus a hand-written ``comm.Allreduce`` after each row-parallel matmul
(``models.py:19-47`` column, ``:50-100`` row, allreduce ``:95``).  On TPU the
same Megatron layout is *declared*: shard the QKV / FFN-up kernels on their
output dim and the out-proj / FFN-down kernels on their input dim over the
``tp`` mesh axis, and XLA GSPMD inserts exactly the two per-layer
all-reduces over ICI.

Layer params are stacked on a leading ``num_layers`` axis (scanned in the
forward pass); that axis is ``None`` for pure TP and carries the ``pp``
mesh axis under pipeline parallelism (each pipeline stage holds a
contiguous block of layers — ``dlbb_tpu/parallel/pipeline.py``).
"""

from __future__ import annotations

from typing import Optional

from jax.sharding import PartitionSpec as P

TP_AXIS = "tp"
DP_AXIS = "dp"
PP_AXIS = "pp"
EP_AXIS = "ep"


def param_specs(tp_axis: Optional[str] = TP_AXIS,
                pp_axis: Optional[str] = None,
                moe: bool = False,
                ep_axis: Optional[str] = None) -> dict:
    """PartitionSpec pytree matching ``init_params``' structure.

    ``pp_axis`` shards the leading stacked-layer axis across pipeline
    stages; ``moe`` switches the FFN specs to the expert-stacked MoE
    layout, whose expert dim shards over ``ep_axis`` (``None`` = no such
    parallelism)."""
    t, l, e = tp_axis, pp_axis, ep_axis
    if moe:
        ffn = {
            # router stays replicated over tp/ep: [L, H, E] is tiny and
            # every device needs the full gate distribution
            "router": {"kernel": P(l, None, None)},
            # experts shard over ep on their leading expert dim, and each
            # expert keeps the Megatron col/row TP split on its features
            "ffn_up": {"kernel": P(l, e, None, t), "bias": P(l, e, t)},
            "ffn_down": {"kernel": P(l, e, t, None), "bias": P(l, e, None)},
        }
    else:
        ffn = {
            "ffn_up": {"kernel": P(l, None, t), "bias": P(l, t)},
            "ffn_down": {"kernel": P(l, t, None), "bias": P(l, None)},
        }
    return {
        "layers": {
            "ln1": {"scale": P(l, None), "bias": P(l, None)},
            # column parallel: shard out_features (reference models.py:19-47)
            "qkv": {"kernel": P(l, None, t), "bias": P(l, t)},
            # row parallel: shard in_features; partial sums -> psum
            # (reference models.py:50-100)
            "out": {"kernel": P(l, t, None), "bias": P(l, None)},
            "ln2": {"scale": P(l, None), "bias": P(l, None)},
            **ffn,
        },
        "ln_f": {"scale": P(None), "bias": P(None)},
    }


def specs_for_mesh(mesh, tp_axis: str = TP_AXIS,
                   pp_axis: str = PP_AXIS, moe: bool = False,
                   ep_axis: str = EP_AXIS) -> dict:
    """Param specs matched to a concrete mesh: each model-parallel axis
    (tp on features, pp on the stacked-layer dim, ep on the expert dim)
    participates iff the mesh actually has it with size > 1."""
    axes = getattr(mesh, "axis_names", ()) if mesh is not None else ()
    use_pp = pp_axis in axes and mesh.shape[pp_axis] > 1
    use_tp = tp_axis in axes
    use_ep = moe and ep_axis in axes and mesh.shape[ep_axis] > 1
    return param_specs(tp_axis if use_tp else None,
                       pp_axis if use_pp else None,
                       moe=moe,
                       ep_axis=ep_axis if use_ep else None)


def batch_spec(mesh=None, dp_axis: str = DP_AXIS, sp_axis: str = "sp") -> P:
    """Activations sharded over data parallelism on the batch dim, and —
    when the mesh has a sequence-parallel axis — over ``sp`` on the
    sequence dim."""
    if mesh is not None and sp_axis in getattr(mesh, "axis_names", ()):
        return P(dp_axis, sp_axis, None)
    return P(dp_axis, None, None)
