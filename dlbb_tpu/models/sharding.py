"""GSPMD partition specs for the TP transformer.

The reference implements tensor parallelism imperatively: per-rank weight
shards plus a hand-written ``comm.Allreduce`` after each row-parallel matmul
(``models.py:19-47`` column, ``:50-100`` row, allreduce ``:95``).  On TPU the
same Megatron layout is *declared*: shard the QKV / FFN-up kernels on their
output dim and the out-proj / FFN-down kernels on their input dim over the
``tp`` mesh axis, and XLA GSPMD inserts exactly the two per-layer
all-reduces over ICI.

Layer params are stacked on a leading ``num_layers`` axis (scanned in the
forward pass), so every spec below leads with ``None`` for that axis.
"""

from __future__ import annotations

from jax.sharding import PartitionSpec as P

TP_AXIS = "tp"
DP_AXIS = "dp"


def param_specs(tp_axis: str = TP_AXIS) -> dict:
    """PartitionSpec pytree matching ``init_params``' structure."""
    t = tp_axis
    return {
        "layers": {
            "ln1": {"scale": P(None), "bias": P(None)},
            # column parallel: shard out_features (reference models.py:19-47)
            "qkv": {"kernel": P(None, None, t), "bias": P(None, t)},
            # row parallel: shard in_features; partial sums -> psum
            # (reference models.py:50-100)
            "out": {"kernel": P(None, t, None), "bias": P(None, None)},
            "ln2": {"scale": P(None), "bias": P(None)},
            "ffn_up": {"kernel": P(None, None, t), "bias": P(None, t)},
            "ffn_down": {"kernel": P(None, t, None), "bias": P(None, None)},
        },
        "ln_f": {"scale": P(None), "bias": P(None)},
    }


def batch_spec(mesh=None, dp_axis: str = DP_AXIS, sp_axis: str = "sp") -> P:
    """Activations sharded over data parallelism on the batch dim, and —
    when the mesh has a sequence-parallel axis — over ``sp`` on the
    sequence dim."""
    if mesh is not None and sp_axis in getattr(mesh, "axis_names", ()):
        return P(dp_axis, sp_axis, None)
    return P(dp_axis, None, None)
