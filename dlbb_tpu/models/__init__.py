"""Tensor-parallel transformer models (L2 replacement).

Megatron-style column/row-parallel decoder with the reference's semantics
(``models.py``), expressed TPU-first: parallelism is GSPMD partition specs on
a ``(dp, tp)`` mesh — the two all-reduces per layer that the reference
hand-writes (``models.py:95``) are inserted by XLA from the sharding layout.
"""

from dlbb_tpu.models.configs import MODEL_CONFIGS, ModelConfig
from dlbb_tpu.models.transformer import (
    forward,
    init_params,
    init_params_sharded,
    num_parameters,
    shard_params,
)
from dlbb_tpu.models.sharding import param_specs

__all__ = [
    "MODEL_CONFIGS",
    "ModelConfig",
    "init_params",
    "init_params_sharded",
    "forward",
    "num_parameters",
    "shard_params",
    "param_specs",
]
