"""Dense attention — the single shared kernel.

Used by the model's "full" mode and as the per-head-group kernel inside
Ulysses sequence parallelism.  fp32 softmax and PV accumulation, cast back
to the input dtype at the end.  Causal (decoder) masking is the default;
``causal=False`` gives bidirectional attention.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def dense_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = True
) -> jax.Array:
    """q: ``[B, num_heads, S, head_dim]`` -> same shape.

    k, v: ``[B, num_heads, S, head_dim]``, or grouped-query
    ``[B, kv_heads, S, head_dim]`` with ``num_heads % kv_heads == 0`` —
    query-head groups then share K/V heads via einsum broadcasting, with no
    materialised repeat (K/V stay at kv_heads width in memory).
    """
    b, n, s, d = q.shape
    kvh = k.shape[1]
    q32, k32, v32 = (t.astype(jnp.float32) for t in (q, k, v))
    grouped = kvh != n
    if grouped:
        q32 = q32.reshape(b, kvh, n // kvh, s, d)
        logits = jnp.einsum("bhgqd,bhkd->bhgqk", q32, k32) / math.sqrt(d)
    else:
        logits = jnp.einsum("bnqd,bnkd->bnqk", q32, k32) / math.sqrt(d)
    if causal:
        mask = jnp.tril(jnp.ones((s, s), dtype=bool))
        logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    if grouped:
        out = jnp.einsum("bhgqk,bhkd->bhgqd", probs, v32)
        out = out.reshape(b, n, s, d)
    else:
        out = jnp.einsum("bnqk,bnkd->bnqd", probs, v32)
    return out.astype(q.dtype)


def dense_causal(q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
    """Causal ``dense_attention`` (back-compat name)."""
    return dense_attention(q, k, v, causal=True)
