"""Dense causal attention — the single shared kernel.

Used by the model's "full" mode and as the per-head-group kernel inside
Ulysses sequence parallelism.  fp32 softmax and PV accumulation, cast back
to the input dtype at the end.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def dense_causal(q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
    """q, k, v: ``[B, num_heads, S, head_dim]`` -> same shape."""
    d = q.shape[-1]
    logits = (
        jnp.einsum(
            "bnqd,bnkd->bnqk", q.astype(jnp.float32), k.astype(jnp.float32)
        )
        / math.sqrt(d)
    )
    s = q.shape[2]
    mask = jnp.tril(jnp.ones((s, s), dtype=bool))
    logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bnqk,bnkd->bnqd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)
