"""Model size configurations (reference ``models.py:252-271`` MODEL_CONFIGS).

``attention="simplified"`` replicates the reference's benchmarking shortcut
(take the query third of the QKV projection as the attention output,
``models.py:162-167``); ``attention="full"`` is real causal multi-head
attention — an option the reference lacks but a real framework needs.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any


@dataclass(frozen=True)
class ModelConfig:
    hidden_size: int
    num_layers: int
    num_heads: int
    ffn_intermediate: int
    # "full" — exact causal/bidirectional MHA, auto-routed to the pallas
    #   flash kernel on real TPUs at S >= transformer.FLASH_ROUTE_MIN_SEQ
    #   (same math, faster kernel; dense einsum elsewhere);
    # "dense" — exact MHA, einsum kernel always (opt-out of the routing);
    # "simplified" (reference parity shortcut) | "flash" (force the pallas
    # kernel, dlbb_tpu.ops) | "ring" | "ulysses" (sequence/context-parallel
    # attention — dlbb_tpu.parallel)
    attention: str = "full"
    dtype: str = "bfloat16"
    # Grouped-query attention: number of K/V heads (None = num_heads, i.e.
    # full MHA; 1 = MQA).  Query heads share K/V heads in groups of
    # num_heads // num_kv_heads.  The projection/params shrink in every
    # mode, and K/V activations stay at kv_heads width end-to-end through
    # every kernel (dense einsum broadcasting, grouped flash blocks,
    # grouped ring/Ulysses) — the only broadcasts left are sharding
    # fallbacks when a mesh axis cannot divide kv_heads (see
    # transformer._attention).
    num_kv_heads: int | None = None
    # Causal (decoder) masking; False = bidirectional attention.  The
    # "simplified" reference shortcut has no attention at all and ignores
    # this; every real kernel (full/flash/ring/ulysses) supports both.
    causal: bool = True
    # Mixture-of-experts FFN (0 = dense FFN).  num_experts > 0 replaces each
    # block's FFN with moe_top_k-gated experts; experts shard over an
    # ``ep`` mesh axis (capability extension — the reference has no EP,
    # SURVEY §2.2).
    num_experts: int = 0
    moe_top_k: int = 2
    # "dense": every expert runs on every token, gates select (exact, no
    # drops; per-device FLOPs scale with num_experts/ep).
    # "capacity": GShard-style einsum dispatch into per-expert capacity
    # buffers of moe_capacity_factor * S * k / E slots per sequence;
    # over-capacity (token, expert) routing slots are dropped individually
    # (a fully-dropped token passes through the residual only) and
    # per-device FLOPs are capacity-bounded.
    moe_dispatch: str = "dense"
    moe_capacity_factor: float = 1.25
    # Activation rematerialisation: recompute each block's activations in
    # the backward pass instead of storing them (jax.checkpoint around the
    # scanned block) — trades ~1/3 more FLOPs for O(layers) less activation
    # HBM, the standard TPU memory/compute trade.
    remat: bool = False
    # Overlapped tensor-parallel collective-matmul schedule
    # (dlbb_tpu/parallel/collective_matmul.py):
    # - "off": GSPMD Megatron layout — XLA inserts the per-layer TP
    #   all-reduces (the default; unchanged lowering);
    # - "ring": every TP projection becomes a ring-decomposed
    #   all-gather-matmul / matmul-reduce-scatter — the collective is a
    #   chain of neighbour ppermutes hidden behind per-shard partial
    #   matmuls, and activations between blocks live sequence-sharded
    #   over tp;
    # - "bidir": same decomposition on a bidirectional ring (both ICI
    #   directions per step; half the hops for the all-gather side).
    # Requires tp > 1, pp == 1, a dense (non-MoE) FFN, and sequence
    # length divisible by the sequence-shard count — validated by
    # validate_tp_overlap below.
    tp_overlap: str = "off"
    # Rematerialisation policy (effective only with remat=True):
    # - "full": save nothing per block, recompute the whole block forward
    #   in the backward pass (max memory saving, ~+1 forward of recompute);
    # - "dots": jax.checkpoint_policies.dots_saveable — save matmul/einsum
    #   outputs, recompute only the cheap elementwise ops (layernorm, gelu,
    #   softmax): most of the memory saving at near-zero matmul recompute,
    #   usually the best MFU point on TPU (the score tensors of dense
    #   attention are dot outputs, so "dots" keeps them resident — at long
    #   S prefer "full" or flash attention).
    remat_policy: str = "full"

    def __post_init__(self) -> None:
        if self.hidden_size % self.num_heads != 0:
            raise ValueError(
                f"hidden_size {self.hidden_size} not divisible by "
                f"num_heads {self.num_heads}"
            )
        if self.attention not in ("full", "dense", "simplified", "flash",
                                  "ring", "ulysses"):
            raise ValueError(f"unknown attention mode {self.attention!r}")
        if self.num_experts < 0:
            raise ValueError(f"num_experts must be >= 0, got {self.num_experts}")
        if self.num_experts > 0 and not (
                1 <= self.moe_top_k <= self.num_experts):
            raise ValueError(
                f"moe_top_k={self.moe_top_k} must be in [1, "
                f"num_experts={self.num_experts}]"
            )
        if self.moe_dispatch not in ("dense", "capacity"):
            raise ValueError(
                f"unknown moe_dispatch {self.moe_dispatch!r} "
                "(expected 'dense' or 'capacity')"
            )
        if self.moe_capacity_factor <= 0:
            raise ValueError(
                f"moe_capacity_factor must be > 0, got "
                f"{self.moe_capacity_factor}"
            )
        if self.tp_overlap not in ("off", "ring", "bidir"):
            raise ValueError(
                f"unknown tp_overlap {self.tp_overlap!r} "
                "(expected 'off', 'ring', or 'bidir')"
            )
        if self.remat_policy not in ("full", "dots"):
            raise ValueError(
                f"unknown remat_policy {self.remat_policy!r} "
                "(expected 'full' or 'dots'; remat=False is the no-remat "
                "point of the ladder)"
            )
        if self.num_kv_heads is not None:
            if not 1 <= self.num_kv_heads <= self.num_heads:
                raise ValueError(
                    f"num_kv_heads={self.num_kv_heads} must be in "
                    f"[1, num_heads={self.num_heads}]"
                )
            if self.num_heads % self.num_kv_heads != 0:
                raise ValueError(
                    f"num_heads={self.num_heads} not divisible by "
                    f"num_kv_heads={self.num_kv_heads}"
                )

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def kv_heads(self) -> int:
        """Effective K/V head count (GQA; == num_heads for full MHA)."""
        return self.num_kv_heads or self.num_heads

    @property
    def qkv_width(self) -> int:
        """Fused QKV projection output width:
        H (queries) + 2 * kv_heads * head_dim (keys + values)."""
        return self.hidden_size + 2 * self.kv_heads * self.head_dim

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    def with_(self, **kw) -> "ModelConfig":
        return replace(self, **kw)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ModelConfig":
        """Build from the YAML ``model:`` section
        (``configs/baseline_config.yaml``, schema parity with reference
        ``config/baseline_config.yaml:7-13``).  A ``size:`` key selects a
        named config; explicit fields override it."""
        d = dict(d)
        size = d.pop("size", None)
        base = MODEL_CONFIGS[size] if size else None
        fields = {}
        for k in (
            "hidden_size", "num_layers", "num_heads", "ffn_intermediate",
            "attention", "dtype", "num_kv_heads", "causal",
            "num_experts", "moe_top_k",
            "moe_dispatch", "moe_capacity_factor", "tp_overlap",
            "remat", "remat_policy",
        ):
            if k in d:
                fields[k] = d[k]
            elif base is not None:
                fields[k] = getattr(base, k)
        return cls(**fields)


# Attention modes that actually partition the sequence dimension over an
# sp mesh axis.  Single source of truth for config validation (harnesses)
# and the mesh-level guard in transformer._attention.
SP_CAPABLE_ATTENTION = ("ring", "ulysses")


def validate_attention_parallelism(config: ModelConfig, sp: int) -> None:
    """Reject attention-mode / sequence-parallel combinations that would
    silently compute the wrong thing or replicate work per sp shard."""
    if config.attention in SP_CAPABLE_ATTENTION and sp <= 1:
        raise ValueError(
            f"attention={config.attention!r} requires "
            "parallelism.sequence_parallel > 1"
        )
    if sp > 1 and config.attention not in SP_CAPABLE_ATTENTION:
        raise ValueError(
            f"parallelism.sequence_parallel={sp} requires attention in "
            f"{SP_CAPABLE_ATTENTION} (attention={config.attention!r} does "
            "not partition the sequence; it would run replicated per sp "
            "shard)"
        )


def validate_tp_overlap(config: ModelConfig, tp: int, pp: int = 1,
                        seq_len: int = 0, sp: int = 1) -> None:
    """Reject tp_overlap combinations the decomposed schedule cannot run.

    The ring kernels gather/scatter the *sequence* dim over tp, so the
    knob needs a real tp axis, an even sequence split, a dense FFN (the
    MoE expert dispatch keeps its GSPMD lowering), and no pipeline (the
    pipeline engine owns its own shard_map and activation layout)."""
    if config.tp_overlap == "off":
        return
    if tp <= 1:
        raise ValueError(
            f"model.tp_overlap={config.tp_overlap!r} requires "
            "parallelism.world_size (tp) > 1 — without a tp axis there is "
            "no collective to overlap"
        )
    if pp > 1:
        raise ValueError(
            f"model.tp_overlap={config.tp_overlap!r} is incompatible with "
            "pipeline_parallel > 1 (the pipeline engine owns the "
            "activation layout)"
        )
    if config.is_moe:
        raise ValueError(
            f"model.tp_overlap={config.tp_overlap!r} requires a dense FFN "
            "(the MoE expert dispatch is not ring-decomposed; run MoE "
            "models with tp_overlap='off')"
        )
    if seq_len and seq_len % (tp * max(1, sp)) != 0:
        raise ValueError(
            f"input.sequence_length={seq_len} not divisible by the "
            f"sequence-shard count {tp * max(1, sp)} (tp={tp}"
            f"{f' x sp={sp}' if sp > 1 else ''}) required by "
            f"tp_overlap={config.tp_overlap!r}"
        )


def validate_expert_parallelism(config: ModelConfig, ep: int) -> None:
    """Reject expert-parallel degrees that cannot shard the expert dim."""
    if ep <= 1:
        return
    if not config.is_moe:
        raise ValueError(
            f"parallelism.expert_parallel={ep} requires a MoE model "
            "(model.num_experts > 0)"
        )
    if config.num_experts % ep != 0:
        raise ValueError(
            f"num_experts={config.num_experts} not divisible by "
            f"expert_parallel={ep}"
        )


# Attention modes the serving engine's paged-cache path supports: the
# cache stores K/V at kv_heads width and decode attends over it with the
# exact dense kernel, so only the exact-MHA modes qualify ("simplified"
# has no K/V at all; ring/ulysses partition the sequence the cache owns).
SERVABLE_ATTENTION = ("full", "dense")

_DTYPE_BYTES = {"bfloat16": 2, "float16": 2, "float32": 4}

# serving.kv_quantization values the paged cache supports: "int8"
# stores K/V blocks as int8 with one fp32 scale per (layer, slot,
# block, kv-head) as a side-channel plane (serve/kvcache.QuantKVCache).
KV_QUANTIZATION_MODES = ("none", "int8")


def kv_cache_bytes_raw(num_layers: int, max_batch: int, max_seq: int,
                       kv_heads: int, head_dim: int,
                       dtype: str = "bfloat16",
                       kv_quantization: str = "none",
                       block_size: Optional[int] = None) -> int:
    """The one KV-cache footprint formula, on raw geometry (for callers
    holding a serialized model record instead of a ModelConfig — e.g.
    ``obs/attribution.py`` pricing a run's report): K + V, every layer,
    every slot, ``max_seq`` tokens at GQA ``kv_heads`` width.

    ``kv_quantization="int8"`` prices the quantized layout instead:
    1 byte per K/V element plus the fp32 scale side-channel (one scale
    per block per kv-head, needing ``block_size``)."""
    if kv_quantization not in KV_QUANTIZATION_MODES:
        raise ValueError(
            f"kv_quantization={kv_quantization!r} not in "
            f"{KV_QUANTIZATION_MODES}"
        )
    elems = 2 * num_layers * max_batch * max_seq * kv_heads
    if kv_quantization == "int8":
        if block_size is None or block_size < 1 or max_seq % block_size:
            raise ValueError(
                "kv_quantization='int8' needs a positive block_size "
                f"dividing max_seq={max_seq} to price the per-block "
                f"scale plane (got block_size={block_size})"
            )
        # int8 data + fp32 scales [L, B, num_blocks, kvh] for K and V
        return elems * head_dim + (elems // block_size) * 4
    return elems * head_dim * _DTYPE_BYTES.get(dtype, 2)


def kv_cache_bytes(config: ModelConfig, max_batch: int,
                   max_seq: int, kv_quantization: str = "none",
                   block_size: Optional[int] = None) -> int:
    """Total (unsharded) KV-cache footprint of a serving config: K + V,
    every layer, every slot, ``max_seq`` tokens at GQA ``kv_heads``
    width, in the model dtype (or the int8 + fp32-scale layout when
    quantized)."""
    return kv_cache_bytes_raw(config.num_layers, max_batch, max_seq,
                              config.kv_heads, config.head_dim,
                              config.dtype,
                              kv_quantization=kv_quantization,
                              block_size=block_size)


def kv_cache_bytes_per_device(config: ModelConfig, max_batch: int,
                              max_seq: int, dp: int = 1,
                              tp: int = 1,
                              kv_quantization: str = "none",
                              block_size: Optional[int] = None) -> int:
    """Per-device KV-cache footprint under the serving sharding contract
    (slot dim over dp, kv-head dim over tp) — the ONE number both the
    build-time HBM budget gate (``validate_serving``) and the static
    memory audit's decode-step cross-check
    (``analysis/memory_audit.py``, rule ``serving-cache-drift``) price,
    so the two can never drift apart: the audit pins this formula
    against the donated cache-carry bytes of the compiled decode
    program.  The scale side-channel of the int8 layout shards over the
    same dp × tp axes as the data it scales, so one divisor covers
    both."""
    shards = max(1, dp) * (tp if tp > 1 else 1)
    return kv_cache_bytes(config, max_batch, max_seq,
                          kv_quantization=kv_quantization,
                          block_size=block_size) // shards


def validate_serving(config: ModelConfig, max_batch: int, max_seq: int,
                     block_size: int, dp: int = 1, tp: int = 1,
                     hbm_budget_bytes: Optional[int] = None,
                     draft_config: Optional[ModelConfig] = None,
                     kv_quantization: str = "none") -> None:
    """Reject serving configurations the engine cannot run — at build
    time, with a clear error, never as an OOM (or a wrong answer) in the
    middle of a trace.

    Covers the model envelope (exact-MHA attention, dense FFN, no
    tp_overlap), the cache divisibility contract (blocks tile max_seq;
    dp tiles the slot dim; tp tiles kv_heads), and — when
    ``hbm_budget_bytes`` is set — the per-device KV-cache HBM footprint:
    ``max_batch x max_seq`` K/V at kv_heads width, divided by the dp x tp
    shards that actually partition it.

    ``draft_config`` is the speculative-decoding draft model
    (``serving.speculation="draft-model"``): it is validated against the
    SAME mesh and cache geometry (the draft plane is sharded by the same
    ``ParallelismPlan``, so e.g. its ``kv_heads % tp`` contract is
    identical), and its resident weights + second KV-cache plane are
    priced INTO the HBM budget alongside the target cache — an
    infeasible ``(spec, max_batch, gamma)`` combination fails here at
    build time, not as an OOM mid-trace.

    ``kv_quantization="int8"`` prices the quantized cache layout (int8
    data + fp32 per-block scales) against the budget — the capacity
    lever that admits more resident requests per HBM byte."""
    if kv_quantization not in KV_QUANTIZATION_MODES:
        raise ValueError(
            f"serving.kv_quantization={kv_quantization!r} not in "
            f"{KV_QUANTIZATION_MODES}"
        )
    if config.attention not in SERVABLE_ATTENTION:
        raise ValueError(
            f"serving requires attention in {SERVABLE_ATTENTION} "
            f"(attention={config.attention!r}: the paged KV-cache stores "
            "exact per-position K/V; simplified has none and ring/ulysses "
            "partition the sequence the cache owns)"
        )
    if config.is_moe:
        raise ValueError(
            "serving requires a dense FFN (model.num_experts == 0); the "
            "MoE dispatch path is not wired into the decode step"
        )
    if config.tp_overlap != "off":
        raise ValueError(
            f"serving requires model.tp_overlap='off' (got "
            f"{config.tp_overlap!r}): the ring schedules gather the "
            "sequence dim, which decode steps of length 1 cannot shard"
        )
    if max_batch < 1:
        raise ValueError(f"serving.max_batch must be >= 1, got {max_batch}")
    if block_size < 1 or max_seq % block_size != 0:
        raise ValueError(
            f"serving.max_seq={max_seq} must be a positive multiple of "
            f"serving.block_size={block_size} (the cache is paged in "
            "whole blocks)"
        )
    if dp > 1 and max_batch % dp != 0:
        raise ValueError(
            f"serving.max_batch={max_batch} not divisible by dp={dp} "
            "(decode slots shard over the dp axis)"
        )
    if tp > 1 and config.kv_heads % tp != 0:
        raise ValueError(
            f"kv_heads={config.kv_heads} not divisible by tp={tp}: the "
            "KV-cache shards its head dim over tp, so GQA configs need "
            "kv_heads % tp == 0 (pick a smaller tp or more kv heads)"
        )
    if draft_config is not None:
        try:
            validate_serving(draft_config, max_batch, max_seq, block_size,
                             dp=dp, tp=tp)
        except ValueError as e:
            raise ValueError(
                f"speculative draft model is not servable on the same "
                f"ParallelismPlan (dp={dp}, tp={tp}): {e}"
            ) from e
    if hbm_budget_bytes is not None:
        per_device = kv_cache_bytes_per_device(
            config, max_batch, max_seq, dp=dp, tp=tp,
            kv_quantization=kv_quantization, block_size=block_size)
        draft_bytes = 0
        if draft_config is not None:
            # the draft plane is resident for the whole trace: weights
            # (sharded over tp like the target's) + its own paged
            # KV-cache plane, priced against the SAME budget
            from dlbb_tpu.models.transformer import num_parameters

            draft_bytes = (
                num_parameters(draft_config)
                * _DTYPE_BYTES.get(draft_config.dtype, 2)
                // (tp if tp > 1 else 1)
                + kv_cache_bytes_per_device(
                    draft_config, max_batch, max_seq, dp=dp, tp=tp)
            )
        if per_device + draft_bytes > hbm_budget_bytes:
            draft_note = (
                f" + speculative draft plane {draft_bytes / 2**30:.2f} "
                "GiB (weights + second KV-cache)" if draft_bytes else "")
            raise ValueError(
                f"serving KV-cache footprint {per_device / 2**30:.2f} GiB "
                f"per device (max_batch={max_batch} x max_seq={max_seq} "
                f"x {config.num_layers} layers x kv_heads="
                f"{config.kv_heads} x head_dim={config.head_dim} x 2 "
                "(K+V), "
                + (f"int8 + fp32 scales per {block_size}-token block"
                   if kv_quantization == "int8"
                   else f"{_DTYPE_BYTES[config.dtype]} B [{config.dtype}]")
                + f", sharded over dp={dp} x tp={tp})"
                f"{draft_note} "
                f"exceeds the HBM budget of "
                f"{hbm_budget_bytes / 2**30:.2f} GiB — shrink max_batch/"
                "max_seq or raise serving.hbm_budget_gb if the device "
                "really has the headroom"
            )


# Reference sizes (``models.py:252-271``).
MODEL_CONFIGS: dict[str, ModelConfig] = {
    "1B": ModelConfig(hidden_size=2048, num_layers=24, num_heads=16,
                      ffn_intermediate=8192),
    "7B": ModelConfig(hidden_size=4096, num_layers=32, num_heads=32,
                      ffn_intermediate=16384),
    "13B": ModelConfig(hidden_size=5120, num_layers=40, num_heads=40,
                       ffn_intermediate=20480),
}
