"""``python -m dlbb_tpu`` — same CLI as ``python -m dlbb_tpu.cli``."""

import sys

from dlbb_tpu.cli import main

if __name__ == "__main__":
    sys.exit(main())
