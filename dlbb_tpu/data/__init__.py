"""Synthetic data (L3 replacement, reference ``data_gen.py``)."""

from dlbb_tpu.data.synthetic import (
    SyntheticEmbeddingDataset,
    create_dataset_from_config,
)

__all__ = ["SyntheticEmbeddingDataset", "create_dataset_from_config"]
