"""Synthetic embedding batches.

Parity with reference ``data_gen.py``: one fixed, seeded batch of shape
``[batch, seq_len, hidden]`` (seed 42, ``data_gen.py:37``) returned on every
``get_batch()`` call — the benchmark measures compute/communication, not
input variety.  Optionally placed on the mesh with a batch sharding.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


class SyntheticEmbeddingDataset:
    """Fixed seeded batch (reference ``SyntheticEmbeddingDataset``
    ``data_gen.py:10-53``)."""

    def __init__(
        self,
        batch_size: int,
        seq_length: int,
        hidden_size: int,
        seed: int = 42,
        dtype=jnp.bfloat16,
        mesh: Optional[Mesh] = None,
        spec: Optional[PartitionSpec] = None,
    ) -> None:
        self.batch_size = batch_size
        self.seq_length = seq_length
        self.hidden_size = hidden_size
        self.seed = seed
        rng = np.random.default_rng(seed)
        host = rng.standard_normal(
            (batch_size, seq_length, hidden_size), dtype=np.float32
        )
        batch = jnp.asarray(host, dtype=dtype)
        if mesh is not None:
            batch = jax.device_put(
                batch, NamedSharding(mesh, spec or PartitionSpec())
            )
        self._batch = batch

    def get_batch(self) -> jax.Array:
        return self._batch


def _request_host_embeddings(seed: int, prompt_len: int,
                             hidden_size: int,
                             period: Optional[int] = None,
                             prefix_len: Optional[int] = None,
                             prefix_seed: Optional[int] = None) -> np.ndarray:
    """The host-side float32 prompt array both :func:`request_embeddings`
    and :func:`prompt_token_ids` derive from — ONE rng consumption
    pattern, so the device prompt and its host-side token-id view can
    never drift.  ``period`` tiles a seeded motif of that many positions
    (the repeating-structure traffic variant, ``serve/traffic.py``);
    None keeps the original draw byte-identical.

    ``prefix_len``/``prefix_seed`` compose the shared-prefix traffic
    variant: the first ``prefix_len`` positions are drawn from
    ``prefix_seed`` (the GROUP seed — every request in a prefix group
    gets the bit-identical prefix, which is what makes its token-block
    chain content-addressable in the prefix trie), the remainder from
    the per-request ``seed``.  The per-seed draws are prefix-closed
    (``default_rng`` fills row-major), so requests whose clamped prefix
    lengths differ still share their common head."""
    if prefix_len is not None and prefix_seed is not None and prefix_len > 0:
        if prefix_len >= prompt_len:
            raise ValueError(
                f"prefix_len={prefix_len} must leave at least one "
                f"per-request position (prompt_len={prompt_len})"
            )
        head = _request_host_embeddings(prefix_seed, prefix_len,
                                        hidden_size, period=period)
        tail = _request_host_embeddings(seed, prompt_len - prefix_len,
                                        hidden_size, period=period)
        return np.concatenate([head, tail], axis=1)
    rng = np.random.default_rng(seed)
    if period is not None:
        if period < 1:
            raise ValueError(f"period must be >= 1, got {period}")
        motif = rng.standard_normal((1, period, hidden_size),
                                    dtype=np.float32)
        reps = -(-prompt_len // period)
        return np.tile(motif, (1, reps, 1))[:, :prompt_len]
    return rng.standard_normal((1, prompt_len, hidden_size),
                               dtype=np.float32)


def request_embeddings(
    seed: int,
    prompt_len: int,
    hidden_size: int,
    dtype=jnp.bfloat16,
    pad_to: Optional[int] = None,
    period: Optional[int] = None,
    prefix_len: Optional[int] = None,
    prefix_seed: Optional[int] = None,
) -> jax.Array:
    """Seeded synthetic prompt embeddings for ONE serving request:
    ``[1, prompt_len, hidden]`` (``[1, pad_to, hidden]`` when padded for a
    prefill bucket — pad positions are zeros; causal attention plus the
    engine's length masking keep them out of every real token's output).

    The serving analogue of :class:`SyntheticEmbeddingDataset`: the
    benchmark measures scheduling and communication, not input variety,
    but each request still gets its own deterministic inputs (seed from
    the trace, ``serve/traffic.py``) so a replayed trace replays the
    exact computation.  ``period`` tiles a seeded motif instead of a
    fully random draw (the repeating-structure trace variant the
    speculative-decoding bench uses, so n-gram drafting has structure
    to look up); None is byte-identical to the original draw."""
    if pad_to is not None and pad_to < prompt_len:
        raise ValueError(
            f"pad_to={pad_to} is shorter than prompt_len={prompt_len}"
        )
    host = _request_host_embeddings(seed, prompt_len, hidden_size,
                                    period=period, prefix_len=prefix_len,
                                    prefix_seed=prefix_seed)
    if pad_to is not None and pad_to > prompt_len:
        host = np.concatenate(
            [host, np.zeros((1, pad_to - prompt_len, hidden_size),
                            dtype=np.float32)], axis=1,
        )
    return jnp.asarray(host, dtype=dtype)


def prompt_token_ids(seed: int, prompt_len: int, hidden_size: int,
                     period: Optional[int] = None,
                     prefix_len: Optional[int] = None,
                     prefix_seed: Optional[int] = None) -> list[int]:
    """The prompt's greedy token-id view: per-position argmax of the SAME
    host array :func:`request_embeddings` uploads — the n-gram drafter's
    prompt-lookup context (``serve/engine.py``).  Pure numpy, computed at
    admission: drafting hints never need device transfers, and a wrong
    hint costs only acceptance (the target verify gates every commit)."""
    host = _request_host_embeddings(seed, prompt_len, hidden_size,
                                    period=period, prefix_len=prefix_len,
                                    prefix_seed=prefix_seed)
    return [int(t) for t in np.argmax(host[0], axis=-1)]


# Fixed seed for the greedy token-embedding table: one global vocabulary
# per hidden size, shared by every engine so token-identity comparisons
# across engines/meshes are meaningful.
_TOKEN_TABLE_SEED = 0xD1BB


def token_embedding_table(hidden_size: int, dtype=jnp.bfloat16) -> jax.Array:
    """The greedy-decode token embedding table ``[H, H]``.

    The serving engine's legacy decode feeds each output hidden state
    straight back as the next input (the model is its own next-token
    function) — a CONTINUOUS feedback with no discrete token alphabet,
    which speculative decoding cannot draft against.  Greedy token
    feedback (``serving.speculation != "off"``) quantises the loop
    through this table: the committed token is ``argmax`` over the
    output hidden state (vocab = hidden_size, the argmax alphabet the
    equivalence gate already records), and the next input is that
    token's row here.  ``emb(token)`` being a deterministic function of
    the token id is exactly what makes a verified draft bit-identical
    to the sequential step — the foundation of the token-identity
    contract (docs/serving.md, "Speculative decoding")."""
    rng = np.random.default_rng(_TOKEN_TABLE_SEED)
    host = rng.standard_normal((hidden_size, hidden_size),
                               dtype=np.float32)
    return jnp.asarray(host, dtype=dtype)


def create_dataset_from_config(
    config: dict[str, Any],
    mesh: Optional[Mesh] = None,
    spec: Optional[PartitionSpec] = None,
    dtype=jnp.bfloat16,
    hidden_size: Optional[int] = None,
    seed_offset: int = 0,
) -> SyntheticEmbeddingDataset:
    """Build from the YAML ``input:`` + ``model:`` sections (reference
    ``create_dataset_from_config`` ``data_gen.py:56-73``).

    ``hidden_size`` overrides the raw ``model.hidden_size`` key for configs
    that name a model size (``size: "7B"``) instead of spelling dimensions
    out; ``seed_offset`` derives independent batches (e.g. training
    targets) from the same config."""
    if hidden_size is None:
        hidden_size = config["model"]["hidden_size"]
    return SyntheticEmbeddingDataset(
        batch_size=config["input"]["batch_size"],
        seq_length=config["input"]["sequence_length"],
        hidden_size=hidden_size,
        seed=config["input"].get("seed", 42) + seed_offset,
        dtype=dtype,
        mesh=mesh,
        spec=spec,
    )
