"""Synthetic embedding batches.

Parity with reference ``data_gen.py``: one fixed, seeded batch of shape
``[batch, seq_len, hidden]`` (seed 42, ``data_gen.py:37``) returned on every
``get_batch()`` call — the benchmark measures compute/communication, not
input variety.  Optionally placed on the mesh with a batch sharding.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


class SyntheticEmbeddingDataset:
    """Fixed seeded batch (reference ``SyntheticEmbeddingDataset``
    ``data_gen.py:10-53``)."""

    def __init__(
        self,
        batch_size: int,
        seq_length: int,
        hidden_size: int,
        seed: int = 42,
        dtype=jnp.bfloat16,
        mesh: Optional[Mesh] = None,
        spec: Optional[PartitionSpec] = None,
    ) -> None:
        self.batch_size = batch_size
        self.seq_length = seq_length
        self.hidden_size = hidden_size
        self.seed = seed
        rng = np.random.default_rng(seed)
        host = rng.standard_normal(
            (batch_size, seq_length, hidden_size), dtype=np.float32
        )
        batch = jnp.asarray(host, dtype=dtype)
        if mesh is not None:
            batch = jax.device_put(
                batch, NamedSharding(mesh, spec or PartitionSpec())
            )
        self._batch = batch

    def get_batch(self) -> jax.Array:
        return self._batch


def request_embeddings(
    seed: int,
    prompt_len: int,
    hidden_size: int,
    dtype=jnp.bfloat16,
    pad_to: Optional[int] = None,
) -> jax.Array:
    """Seeded synthetic prompt embeddings for ONE serving request:
    ``[1, prompt_len, hidden]`` (``[1, pad_to, hidden]`` when padded for a
    prefill bucket — pad positions are zeros; causal attention plus the
    engine's length masking keep them out of every real token's output).

    The serving analogue of :class:`SyntheticEmbeddingDataset`: the
    benchmark measures scheduling and communication, not input variety,
    but each request still gets its own deterministic inputs (seed from
    the trace, ``serve/traffic.py``) so a replayed trace replays the
    exact computation."""
    if pad_to is not None and pad_to < prompt_len:
        raise ValueError(
            f"pad_to={pad_to} is shorter than prompt_len={prompt_len}"
        )
    rng = np.random.default_rng(seed)
    host = rng.standard_normal((1, prompt_len, hidden_size),
                               dtype=np.float32)
    if pad_to is not None and pad_to > prompt_len:
        host = np.concatenate(
            [host, np.zeros((1, pad_to - prompt_len, hidden_size),
                            dtype=np.float32)], axis=1,
        )
    return jnp.asarray(host, dtype=dtype)


def create_dataset_from_config(
    config: dict[str, Any],
    mesh: Optional[Mesh] = None,
    spec: Optional[PartitionSpec] = None,
    dtype=jnp.bfloat16,
    hidden_size: Optional[int] = None,
    seed_offset: int = 0,
) -> SyntheticEmbeddingDataset:
    """Build from the YAML ``input:`` + ``model:`` sections (reference
    ``create_dataset_from_config`` ``data_gen.py:56-73``).

    ``hidden_size`` overrides the raw ``model.hidden_size`` key for configs
    that name a model size (``size: "7B"``) instead of spelling dimensions
    out; ``seed_offset`` derives independent batches (e.g. training
    targets) from the same config."""
    if hidden_size is None:
        hidden_size = config["model"]["hidden_size"]
    return SyntheticEmbeddingDataset(
        batch_size=config["input"]["batch_size"],
        seq_length=config["input"]["sequence_length"],
        hidden_size=hidden_size,
        seed=config["input"].get("seed", 42) + seed_offset,
        dtype=dtype,
        mesh=mesh,
        spec=spec,
    )
