"""System / device info for result provenance (reference
``utils.py:132-151`` collect_system_info: platform + psutil + torch versions;
here: platform + JAX + device topology)."""

from __future__ import annotations

import platform
from typing import Any


def collect_system_info() -> dict[str, Any]:
    import jax

    devices = jax.devices()
    info: dict[str, Any] = {
        "platform": platform.platform(),
        "python_version": platform.python_version(),
        "processor": platform.processor(),
        "jax_version": jax.__version__,
        "backend": jax.default_backend(),
        "num_devices": len(devices),
        "num_processes": jax.process_count(),
        "device_kind": devices[0].device_kind if devices else "none",
    }
    try:
        import psutil

        info["cpu_count"] = psutil.cpu_count()
        info["memory_gb"] = round(psutil.virtual_memory().total / 2**30, 2)
    except ImportError:
        pass
    return info
