"""Device-honest benchmark timing.

Two regimes (SURVEY §7 "timing semantics under async dispatch"):

- **per_iter** — platforms where ``jax.block_until_ready`` genuinely waits for
  device completion (CPU, locally-attached TPU): time each call bracketed by
  ``block_until_ready``, the analogue of the reference's
  ``Barrier(); Wtime(); op; Wtime()`` (``collectives/1d/openmpi.py:60-66``).

- **chained** — remotely-attached backends (this image's tunneled TPU,
  backend name ``axon``) where ``block_until_ready`` returns on *enqueue*,
  not completion, and each dispatch pays a multi-ms tunnel roundtrip.
  Honest numbers require (a) forcing a data dependency (fetch a scalar
  derived from the result) and (b) amortising the roundtrip: run M iterations
  of ``chain(op(x))`` inside ONE jitted ``lax.fori_loop`` (single dispatch),
  fetch, subtract the calibrated fetch baseline, divide by M.  The chain
  glue feeds each iteration's output back as the next input so XLA cannot
  hoist the op out of the loop.

``resolve_timing_mode("auto")`` picks per_iter unless the backend is known
remote-async (or ``DLBB_TIMING_MODE`` overrides).

Warmup and measurement loops run under ``jax.profiler`` trace
annotations (``utils/profiling.annotate``), so a captured device trace
(``--trace`` / the obs device captures) distinguishes warmup reps from
measurement reps in the timeline.  The annotations wrap the LOOPS, never
the inside of a per-iteration ``perf_counter`` bracket — this module is
the sanctioned timing API (exempt from the timed-region lint rules) and
must never import the obs or chaos-harness packages: the zero-overhead
pins in ``tests/test_obs.py`` and the chaos suite assert, statically,
that nothing here can add instructions to a timed region.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from dlbb_tpu.utils.profiling import annotate

def _remote_async_backend() -> bool:
    """True when the device runtime is remotely attached and
    ``block_until_ready`` returns on enqueue rather than completion.

    The tunneled-TPU plugin registers its platform under the name "tpu", so
    backend name alone cannot distinguish it from a locally-attached TPU; the
    plugin's environment markers can.
    """
    if jax.default_backend() == "cpu":
        return False  # simulated mesh: block_until_ready is a real sync
    if "axon" in os.environ.get("JAX_PLATFORMS", ""):
        return True
    if os.environ.get("PALLAS_AXON_TPU_GEN"):
        return True
    return False


def resolve_timing_mode(mode: str = "auto") -> str:
    if mode != "auto":
        return mode
    env = os.environ.get("DLBB_TIMING_MODE")
    if env:
        return env
    return "chained" if _remote_async_backend() else "per_iter"


def force_completion(x: Any) -> float:
    """Force completion of ``x`` via a minimal data-dependent fetch: a
    device-side reduction to one scalar, then fetch.  The reduction depends
    on EVERY shard of a sharded result (a single-element slice would only
    force shard 0's producer), while only a scalar crosses the wire (a
    ``ravel()[0]`` fetch would all-gather the whole payload first).  The
    reduction's own device cost appears identically in
    ``calibrate_fetch_overhead`` and is subtracted by the chained-timing
    math; the value itself is irrelevant (NaN/inf are fine)."""
    leaf = jax.tree.leaves(x)[0]
    return float(jnp.sum(leaf))


_force = force_completion


def single_iteration_estimate(
    fn, x, trials: int = 3, op_args: tuple = (), agg: str = "median"
) -> float:
    """True-completion time of one ``fn(*op_args, x)`` call: wall time of a
    data-dependent scalar fetch on the result, minus the calibrated fetch
    overhead.  Works on any backend — the fetch cannot be satisfied by
    enqueue — so it cross-validates both timing modes (at one-dispatch
    granularity; see scripts/timing_crosscheck.py).

    ``agg``: "median" for a central estimate (cross-check artifacts), "min"
    for a stall-robust lower bound (the plausibility check — on a loaded
    host any single trial can absorb a multi-ms scheduler stall, and an
    inflated estimate there would falsely condemn honest per-iter
    timings)."""
    out = fn(*op_args, x)
    _force(out)  # compile + warm
    overhead = calibrate_fetch_overhead(out)
    samples = []
    for _ in range(trials):
        t0 = time.perf_counter()
        _force(fn(*op_args, x))
        samples.append(time.perf_counter() - t0)
    samples.sort()
    pick = samples[0] if agg == "min" else samples[len(samples) // 2]
    return max(pick - overhead, 0.0)


def per_iter_plausible(median_block: float, forced: float,
                       ratio: float = 0.2, floor: float = 0.02) -> bool:
    """Is a ``block_until_ready``-based median believable against the
    forced-completion time of one iteration?  Implausible = the op
    "finishes" in under ``ratio`` of its true completion time while the
    true time is above ``floor`` — the signature of a backend whose
    block_until_ready returns on enqueue (remote-async), where per-iter
    timings would be dispatch latencies, not device times.

    ``floor`` is 20 ms: below that, eager-dispatch overhead on a loaded
    host is the same magnitude as the probe itself (no reliable signal),
    and sub-floor ops are dispatch-dominated on a remote backend anyway —
    the regime where dishonest per-iter numbers distort published results
    is the one this check covers."""
    if forced < floor:
        return True  # too fast to distinguish dispatch from completion
    return median_block >= ratio * forced


def calibrate_fetch_overhead(x: Any, trials: int = 5) -> float:
    """Roundtrip cost of the forcing fetch on an already-ready value (min of
    ``trials``)."""
    _force(x)  # ensure ready
    best = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        _force(x)
        best = min(best, time.perf_counter() - t0)
    return best


def time_fn_per_iter(
    fn, *args, warmup: int, iterations: int,
    max_seconds: Optional[float] = None,
) -> tuple[list[float], int, bool]:
    """Per-iteration block_until_ready timing (sync backends).

    ``max_seconds`` caps the *measurement* wall time: after the compile
    warmup, one probe iteration estimates the per-iteration cost and the
    warmup/iteration counts are scaled down to fit the budget (floor of 3
    measured iterations, never more than requested).  The actual counts are
    returned/recorded so result artifacts never overstate the sample size.
    Returns ``(timings, warmup_run, clamped)``.
    """
    with annotate("warmup"):
        jax.block_until_ready(fn(*args))  # compile + first warmup
        warmup_run = 1
        clamped = False
        if max_seconds is not None:
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args))
            probe = time.perf_counter() - t0
            warmup_run += 1
            # when even the 3-sample floor cannot fit the budget (huge
            # payloads on the single-core simulated host), drop the floor
            # to 1 — one honest recorded sample beats minutes of
            # over-budget re-runs
            floor = 1 if 3 * probe > max_seconds else 3
            affordable = max(floor, int(max_seconds / max(probe, 1e-9)))
            if affordable < warmup + iterations:
                clamped = True
                warmup = min(warmup, max(0, affordable // 10))
                iterations = min(iterations, max(floor, affordable - warmup))
        for _ in range(max(0, warmup - warmup_run)):
            jax.block_until_ready(fn(*args))
            warmup_run += 1
    out = []
    with annotate("measure"):
        for _ in range(iterations):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args))
            out.append(time.perf_counter() - t0)
    return out, warmup_run, clamped


def chained_chunk_size(iterations: int, chunk_size: Optional[int] = None) -> int:
    """The chunk size ``time_fn_chained`` will use for ``iterations``.

    Factored out so AOT compilers of the chained loop (the sweep scheduler,
    ``dlbb_tpu.bench.schedule``) bake in exactly the chunk size the
    measurement will divide by — a mismatch would silently rescale every
    sample."""
    if chunk_size is not None:
        return chunk_size
    return max(1, min(10, iterations // 10 or 1))


def build_chained_loop(
    op: Callable,
    chain: Optional[Callable] = None,
    chunk_size: int = 10,
) -> Callable:
    """The jitted ``chunk_size``-iteration fori_loop around ``op`` that
    chained timing measures — exposed so it can be AOT-lowered/compiled
    ahead of the measurement (compile-ahead sweeps) with identical
    semantics, donation included.
    """

    def body(args, c):
        out = op(*args, c)
        return chain(out) if chain is not None else out

    # the carry (x0) is DONATED: chained timing feeds each chunk's output
    # back as the next chunk's input anyway, and without donation XLA must
    # keep input and output carries simultaneously resident — at train-step
    # scale (TrainState = params + Adam moments) that doubles state HBM and
    # OOMs configs whose training loop itself fits (measured: 1B/b8/s512
    # Adam-bf16m trains, then OOMed in this timing loop before the fix)
    return jax.jit(
        lambda args, x0: jax.lax.fori_loop(
            0, chunk_size, lambda i, c: body(args, c), x0
        ),
        donate_argnums=(1,),
    )


def time_fn_chained(
    op: Callable,
    x: Any,
    chain: Optional[Callable] = None,
    warmup: int = 1,
    iterations: int = 100,
    chunk_size: Optional[int] = None,
    op_args: tuple = (),
    compiler_options: Optional[dict[str, str]] = None,
    max_seconds: Optional[float] = None,
    looped: Optional[Callable] = None,
) -> tuple[list[float], dict[str, Any], Any]:
    """Chunked fori_loop timing (remote-async backends).

    ``op`` is invoked as ``op(*op_args, carry)``.  Anything large the op
    needs (model params!) MUST go through ``op_args``, not a closure: arrays
    closed over by the jitted loop are embedded as compile-time constants,
    which at model scale stalls compilation indefinitely.

    ``looped`` short-circuits loop construction with a pre-built (possibly
    pre-compiled) executable from :func:`build_chained_loop` — it MUST have
    been built with this call's chunk size (:func:`chained_chunk_size`) and
    ``compiler_options`` already applied.

    Returns ``(samples, meta, carry)``: each sample is the estimated
    per-iteration time of one chunk, ``(chunk_wall - fetch_overhead) /
    chunk_size``; ``len(samples) == iterations // chunk_size`` (≥ 1).
    The input ``x`` is DONATED to the loop (see the comment in
    :func:`build_chained_loop`) — callers must use the returned final
    ``carry`` instead of ``x`` afterwards.
    """
    chunk_size = chained_chunk_size(iterations, chunk_size)
    chunks = max(1, iterations // chunk_size)

    if looped is None:
        looped = build_chained_loop(op, chain, chunk_size)
        if compiler_options:
            # variant-tuned compilation (e.g. combiner passes disabled) —
            # the options must go on the outer loop jit, which subsumes
            # the op
            looped = looped.lower(op_args, x).compile(
                compiler_options=dict(compiler_options)
            )

    warm_wall = float("inf")
    with annotate("warmup"):
        for _ in range(max(1, warmup)):
            t0 = time.perf_counter()
            x = looped(op_args, x)  # rebind: donated input is now invalid
            _force(x)
            warm_wall = min(warm_wall, time.perf_counter() - t0)
        overhead = calibrate_fetch_overhead(x)

    clamped = False
    if max_seconds is not None and warm_wall > 0:
        affordable = max(1, int(max_seconds / warm_wall))
        if affordable < chunks:
            chunks, clamped = affordable, True

    samples = []
    with annotate("measure"):
        for _ in range(chunks):
            t0 = time.perf_counter()
            x = looped(op_args, x)
            _force(x)
            wall = time.perf_counter() - t0
            samples.append(max(wall - overhead, 0.0) / chunk_size)
    meta = {
        "timing_mode": "chained",
        "timing_method": (
            "jitted lax.fori_loop chunks + data-dependent fetch, "
            "fetch overhead subtracted (remote-async backend)"
        ),
        "timing_granularity": f"chunked({chunk_size})",
        # each sample is a chunk MEAN: downstream p95/p99 measure the
        # spread of chunk means, not per-iteration tail latencies
        "percentile_caveat": (
            f"percentiles are over {chunk_size}-iteration chunk means, "
            "not per-iteration tails"
        ),
        "chunks": chunks,
        "chunk_size": chunk_size,
        "fetch_overhead_s": overhead,
    }
    if clamped:
        meta.update(
            measurement_iterations=chunks * chunk_size,
            time_budget_s=max_seconds,
            time_budget_clamped=True,
        )
    return samples, meta, x


def time_collective(
    op: Callable,
    x: Any,
    chain: Optional[Callable] = None,
    warmup: int = 10,
    iterations: int = 100,
    mode: str = "auto",
    max_seconds: Optional[float] = None,
    compiler_options: Optional[dict[str, str]] = None,
    executable: Optional[Callable] = None,
    chained_loop: Optional[Callable] = None,
) -> tuple[list[float], dict[str, Any]]:
    """Unified entry: returns (per-iteration timings, metadata).

    In chained mode (remote-async backends, incl. the per-iter
    implausibility fallback) ``x`` is DONATED to the timing loop and must
    not be touched by the caller afterwards — the sweep driver builds a
    fresh payload per config, so nothing here returns the carry.

    ``max_seconds`` bounds the measurement wall time per config (slow hosts /
    huge payloads): iteration counts are scaled down to fit and the *actual*
    counts land in the metadata, overriding the sweep's nominal ones in the
    result JSON.  ``compiler_options`` compiles the op (or the chained loop
    around it) with variant-specific XLA options.

    Compile-ahead callers (``dlbb_tpu.bench.schedule``) pass what they
    already compiled: ``executable`` replaces ``op`` for per-iter timing
    (it must be the same program, ``compiler_options`` included), and
    ``chained_loop`` replaces the loop construction in chained mode (built
    via :func:`build_chained_loop` with :func:`chained_chunk_size` of this
    call's ``iterations``).  The traceable ``op`` is still required: the
    per-iter implausibility fallback below re-traces it inside a fresh
    loop, which a compiled executable cannot survive.  Timing semantics
    are unchanged either way — warmup absorbed compilation before, and
    with a pre-compiled program the same warmup calls simply find nothing
    left to absorb.
    """
    mode = resolve_timing_mode(mode)
    if mode == "per_iter":
        if executable is not None:
            op_exec = executable
        else:
            op_exec = op
            if compiler_options and hasattr(op, "lower"):
                # keep the traceable `op` around: the chained fallback below
                # jit-traces it, which a Compiled cannot survive
                op_exec = op.lower(x).compile(
                    compiler_options=dict(compiler_options)
                )
        timings, warmup_run, clamped = time_fn_per_iter(
            op_exec, x, warmup=warmup, iterations=iterations,
            max_seconds=max_seconds,
        )
        # Plausibility floor (robustness beyond the env-marker detection in
        # resolve_timing_mode): if block_until_ready "finished" in a small
        # fraction of the true data-dependent completion time, this backend
        # is remote-async and per-iter numbers are dispatch latencies —
        # warn and fall back to honest chained timing.  Dispatch latencies
        # are ms-scale even over a tunnel, so a >= 50 ms median cannot be
        # enqueue-only and the probe is skipped (saves iterations on huge
        # budgeted configs; recorded as skipped, not as a fake validation).
        meta = {
            "timing_mode": "per_iter",
            "timing_method": "time.perf_counter() + jax.block_until_ready()",
            "timing_granularity": "per_iteration",
        }
        if not timings:  # iterations=0: nothing to sanity-check
            return timings, meta
        sorted_t = sorted(timings)
        median = sorted_t[len(sorted_t) // 2]
        if median >= 0.05:
            meta["forced_completion_probe_skipped"] = True
        else:
            forced = single_iteration_estimate(op_exec, x, trials=3,
                                               agg="min")
            if not per_iter_plausible(median, forced):
                import warnings

                warnings.warn(
                    f"per-iteration timing implausible (median "
                    f"{median * 1e3:.3f} ms vs forced completion "
                    f"{forced * 1e3:.3f} ms): block_until_ready appears to "
                    "return on enqueue; switching to chained timing",
                    stacklevel=2,
                )
                samples, cmeta, _ = time_fn_chained(
                    op, x, chain=chain, warmup=1, iterations=iterations,
                    compiler_options=compiler_options,
                    max_seconds=max_seconds, looped=chained_loop,
                )
                cmeta.update(
                    per_iter_sanity_failed=True,
                    per_iter_median_s=median,
                    forced_completion_s=forced,
                )
                return samples, cmeta
            meta["forced_completion_s"] = forced
        if clamped:
            meta.update(
                measurement_iterations=len(timings),
                warmup_iterations=warmup_run,
                time_budget_s=max_seconds,
                time_budget_clamped=True,
            )
        return timings, meta
    samples, cmeta, _ = time_fn_chained(
        op, x, chain=chain, warmup=max(1, warmup // 10),
        iterations=iterations, compiler_options=compiler_options,
        max_seconds=max_seconds, looped=chained_loop,
    )
    return samples, cmeta
