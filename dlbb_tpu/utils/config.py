"""Config / JSON IO (reference ``utils.py:90-102`` load_config,
``utils.py:268-279`` save_results) + the repo's one atomic-write helper.

Every artifact writer in the repo goes through :func:`atomic_write_text`
(directly or via :func:`save_json`): tmp file in the destination
directory, ``flush`` + ``fsync``, then ``os.replace`` — so a process
killed at any instant leaves either the complete old artifact or the
complete new one, never a truncated JSON/CSV that a resume-mode sweep or
the stats pipeline would trust.  The ``non-atomic-artifact-write``
comm-lint rule (``dlbb_tpu/analysis/source_lint.py``) keeps new writers
from bypassing it.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Any
from uuid import uuid4

import yaml


def load_config(path: str | Path) -> dict[str, Any]:
    """Load a YAML experiment config (schema: ``configs/baseline_config.yaml``,
    mirroring reference ``config/baseline_config.yaml:1-34``)."""
    path = Path(path)
    if not path.exists():
        raise FileNotFoundError(f"config file not found: {path}")
    with open(path) as f:
        cfg = yaml.safe_load(f)
    if not isinstance(cfg, dict):
        raise ValueError(f"config {path} did not parse to a mapping")
    return cfg


def atomic_write_text(text: str, path: str | Path, newline: str = "") -> Path:
    """Durably replace ``path`` with ``text``: tmp + fsync + ``os.replace``.

    The tmp file lives next to the destination (``os.replace`` must not
    cross filesystems) with a unique name — concurrent writers (multi-host
    sweeps on a shared filesystem) must not truncate each other's
    in-flight tmp file.  ``newline`` passes through to ``open`` for CSV
    writers (``newline=""`` is also the plain-text default: content is
    written byte-for-byte, no translation).
    """
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_name(f"{path.name}.{os.getpid()}.{uuid4().hex[:8]}.tmp")
    try:
        with open(tmp, "w", newline=newline) as f:
            f.write(text)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        tmp.unlink(missing_ok=True)
    return path


def save_json(data: dict[str, Any], path: str | Path) -> Path:
    """Write a result dict as pretty JSON via :func:`atomic_write_text`,
    creating parent dirs — a killed run can never leave a truncated
    artifact behind (resume validates content, but a torn file would
    still cost a warning + re-measure; see
    ``dlbb_tpu/resilience/validate.py``)."""
    from dlbb_tpu.resilience import inject

    path = Path(path)
    text = json.dumps(data, indent=2, default=_jsonify)
    if inject.fire("torn-write"):
        # chaos harness: model the LEGACY non-atomic writer dying
        # mid-dump — a truncated JSON lands at the FINAL path and the
        # "process" crashes (TornWrite) before completing the config
        path.parent.mkdir(parents=True, exist_ok=True)
        frac = inject.param("torn_fraction")
        with open(path, "w") as f:
            f.write(text[: max(1, int(len(text) * frac))])
        raise inject.TornWrite(str(path))
    if inject.fire("kill-mid-write"):
        # chaos harness: SIGKILL between the tmp write and os.replace —
        # with the atomic writer the destination never appears; resume
        # re-runs the config (tmp litter is harmless and uniquely named)
        tmp = path.with_name(f"{path.name}.{os.getpid()}.killed.tmp")
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp.write_text(text)
        import signal

        os.kill(os.getpid(), signal.SIGKILL)
    return atomic_write_text(text, path)


def _jsonify(obj: Any):
    import numpy as np

    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, os.PathLike):
        return str(obj)
    raise TypeError(f"not JSON serialisable: {type(obj)}")
