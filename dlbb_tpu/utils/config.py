"""Config / JSON IO (reference ``utils.py:90-102`` load_config,
``utils.py:268-279`` save_results)."""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Any
from uuid import uuid4

import yaml


def load_config(path: str | Path) -> dict[str, Any]:
    """Load a YAML experiment config (schema: ``configs/baseline_config.yaml``,
    mirroring reference ``config/baseline_config.yaml:1-34``)."""
    path = Path(path)
    if not path.exists():
        raise FileNotFoundError(f"config file not found: {path}")
    with open(path) as f:
        cfg = yaml.safe_load(f)
    if not isinstance(cfg, dict):
        raise ValueError(f"config {path} did not parse to a mapping")
    return cfg


def save_json(data: dict[str, Any], path: str | Path) -> Path:
    """Write a result dict as pretty JSON, creating parent dirs.

    Write-to-tmp + ``os.replace`` so a killed run (time-budgeted publisher
    sweeps) can never leave a truncated artifact behind — resume-mode sweeps
    trust file existence, so a partial JSON would be skipped forever and
    leak into the committed corpus."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    # unique tmp name: concurrent writers (multi-host sweeps on a shared
    # filesystem) must not truncate each other's in-flight tmp file
    tmp = path.with_name(f"{path.name}.{os.getpid()}.{uuid4().hex[:8]}.tmp")
    try:
        with open(tmp, "w") as f:
            json.dump(data, f, indent=2, default=_jsonify)
        os.replace(tmp, path)
    finally:
        tmp.unlink(missing_ok=True)
    return path


def _jsonify(obj: Any):
    import numpy as np

    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, os.PathLike):
        return str(obj)
    raise TypeError(f"not JSON serialisable: {type(obj)}")
