"""Shared utilities (reference ``utils.py`` parity: metrics, timing, config
IO, system info)."""

from dlbb_tpu.utils.config import load_config, save_json
from dlbb_tpu.utils.metrics import Timer, summarize
from dlbb_tpu.utils.sysinfo import collect_system_info

__all__ = [
    "Timer",
    "summarize",
    "load_config",
    "save_json",
    "collect_system_info",
]
