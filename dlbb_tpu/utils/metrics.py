"""Metrics summaries and timing.

Parity with reference ``utils.py:17-87``, collapsed to what the harnesses
actually consume: ``summarize`` (the reference ``MetricsCollector.summary``'s
mean/std/min/max/median/p95/p99 math, applied by every harness to its timing
series) and a ``Timer`` context manager — timing here is
``time.perf_counter`` with an optional ``jax.block_until_ready`` sync,
because under XLA's async dispatch a wall-clock timer without a device sync
measures dispatch latency, not execution (SURVEY §7 "hard parts").

The reference's stateful named-series ``MetricsCollector`` object is
deliberately NOT reproduced: in this design each harness owns its timing
list and calls ``summarize`` once, so a collector would be a write-then-
read-back indirection (the reference itself leaves half its ``utils.py``
helpers unused — ``run_experiment``, ``gather_metrics_from_all_ranks``,
``utils.py:172-244`` — a known quirk SURVEY §7 says not to replicate).
"""

from __future__ import annotations

import time
from typing import Any, Optional

import numpy as np


# the full summary schema, empty series included: every caller can rely
# on these keys existing (serving-path metrics — ROADMAP item 1 — key on
# p99.9 tail latency, hence p999).  ONE source of truth with the native
# bindings — the native path zips values against this order, so a field
# added to only one copy would silently mislabel numbers.
from dlbb_tpu.native import SUMMARY_FIELDS as SUMMARY_KEYS


def summarize(values: list[float]) -> dict[str, float]:
    """Summary statistics over a timing series (seconds), matching the
    reference's metric names (``utils.py:43-66``) plus ``p999`` (the
    p99.9 tail the serving-path metrics need).  Uses the native C++
    stats core when available (``dlbb_tpu/native``), numpy otherwise —
    numerics asserted identical in ``tests/test_native.py``.

    An EMPTY series (every sample quarantined, a preempted run) returns
    explicit NaN-valued keys with ``count == 0`` — never a bare ``{}``
    that would KeyError the stats pipeline downstream; NaN is visibly
    not-a-number in every artifact it reaches."""
    arr = np.asarray(values, dtype=np.float64)
    if arr.size == 0:
        out = {k: float("nan") for k in SUMMARY_KEYS}
        out["count"] = 0
        return out
    from dlbb_tpu.native import summarize_native

    native = summarize_native(arr)
    if native is not None:
        return native
    return {
        "mean": float(arr.mean()),
        "std": float(arr.std()),
        "min": float(arr.min()),
        "max": float(arr.max()),
        "median": float(np.median(arr)),
        "p95": float(np.percentile(arr, 95)),
        "p99": float(np.percentile(arr, 99)),
        "p999": float(np.percentile(arr, 99.9)),
        "count": int(arr.size),
    }


class Timer:
    """Context-manager wall timer (reference ``utils.py:73-87``), with an
    optional result to synchronise on before stopping the clock."""

    def __init__(self, sync: Optional[Any] = None) -> None:
        self._sync = sync
        self.elapsed: float = float("nan")

    def __enter__(self) -> "Timer":
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        if self._sync is not None:
            import jax

            jax.block_until_ready(self._sync)
        self.elapsed = time.perf_counter() - self._start
