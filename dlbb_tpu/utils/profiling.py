"""Tracing / profiling subsystem.

The reference has no in-repo profiler — its observability is wall-clock
timers plus *library* debug tracing switched on via env vars
(``CCL_LOG_LEVEL=debug``, ``I_MPI_DEBUG=10``, ``mpirun --report-bindings``;
reference ``collectives/3d/launch_dsccl.sh:34``,
``collectives/3d/launch_mpiccl.sh:12,17-18``).  The TPU-native equivalent is
the XLA profiler: ``jax.profiler`` emits xplane traces (per-op device
timelines, HLO cost analysis, memory viewer) viewable in TensorBoard or
Perfetto — strictly more information than the reference's text logs.

Surface, mirroring the reference's env-switched design:

- ``maybe_trace(trace_dir)`` — context manager; no-op when ``trace_dir`` is
  None/empty.  ``DLBB_TRACE_DIR`` env is the default, so any benchmark can
  be traced without changing its invocation (the CCL_LOG_LEVEL analogue).
- ``annotate(name)`` — host-side named region (``TraceAnnotation``) so
  warmup/measurement phases are distinguishable in the timeline
  (``utils/timing.py`` wraps its warmup/measure loops in these, and
  ``train/loop.py`` its phases).
- ``step_annotation(name, step)`` — per-step annotation for training loops.

This module is one of the two sanctioned profiler API homes (with
``dlbb_tpu/obs/capture.py``): the ``profiler-in-timed-region`` comm-lint
rule forbids profiler calls inside any timed region elsewhere in the
repo, and the runtime observability layer — host-side span tracing,
gated per-config device capture, the predicted-vs-measured calibration
gate — lives in ``dlbb_tpu/obs/`` (``docs/observability.md``).
"""

from __future__ import annotations

import contextlib
import os
from typing import Iterator, Optional

# jax is imported inside each function: the stats subcommands are
# numpy-only by design (cli.py lazy-imports per branch) and must not pay
# the jax import just because this module is on their import path.

__all__ = ["maybe_trace", "annotate", "step_annotation", "default_trace_dir"]


def default_trace_dir() -> Optional[str]:
    """The env-switched default (``DLBB_TRACE_DIR``), or None."""
    return os.environ.get("DLBB_TRACE_DIR") or None


@contextlib.contextmanager
def maybe_trace(trace_dir: Optional[str] = None) -> Iterator[Optional[str]]:
    """Trace everything inside the block to ``trace_dir`` (xplane format).

    ``trace_dir=None`` falls back to ``DLBB_TRACE_DIR``; if that is unset
    too, the block runs untraced at zero cost.  Yields the resolved trace
    directory (or None) so callers can record it in result metadata.
    """
    trace_dir = trace_dir or default_trace_dir()
    if not trace_dir:
        yield None
        return
    import jax

    os.makedirs(trace_dir, exist_ok=True)
    with jax.profiler.trace(trace_dir):
        yield trace_dir


def annotate(name: str):
    """Named host-side region, visible in the trace timeline."""
    import jax

    return jax.profiler.TraceAnnotation(name)


def step_annotation(name: str, step: int):
    """Per-step region for training/benchmark loops (groups device ops
    under one step in the trace viewer)."""
    import jax

    return jax.profiler.StepTraceAnnotation(name, step_num=step)
