"""CPU-simulated multi-device mesh setup — the dev-path analogue of
``mpirun -np N`` on localhost (SURVEY §4).

Must run before the JAX backend initialises.  Two steps are required on this
image: the ``xla_force_host_platform_device_count`` flag, and forcing the
platform back to CPU via *config* — the TPU plugin's sitecustomize overrides
the ``JAX_PLATFORMS`` env var at import time, so the env alone is ignored.

Shared by the CLI (``--simulate N``) and ``tests/conftest.py``.
"""

from __future__ import annotations

import os
import re


def force_cpu_simulation(num_devices: int) -> None:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+",
            f"--xla_force_host_platform_device_count={num_devices}",
            flags,
        )
    else:
        flags = f"{flags} --xla_force_host_platform_device_count={num_devices}"
    os.environ["XLA_FLAGS"] = flags.strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")
