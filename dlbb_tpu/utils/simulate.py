"""CPU-simulated multi-device mesh setup — the dev-path analogue of
``mpirun -np N`` on localhost (SURVEY §4).

Must run before the JAX backend initialises.  Two steps are required on this
image: the ``xla_force_host_platform_device_count`` flag, and forcing the
platform back to CPU via *config* — the TPU plugin's sitecustomize overrides
the ``JAX_PLATFORMS`` env var at import time, so the env alone is ignored.

Shared by the CLI (``--simulate N``) and ``tests/conftest.py``.

This module is also the bookkeeper for WHY the process is on CPU: rounds
4–5 silently lost the chip (ROADMAP item 5), so a degraded fallback — the
backend probe timing out and ``bench.py`` standing up the simulated mesh
instead — must become a first-class, journaled event, not a stderr line.
:func:`topology_record` is the one place that classifies the backend
(requested simulation vs silent CPU fallback) and every sweep writes it
into ``sweep_manifest.json`` and the sweep journal
(``dlbb_tpu/bench/runner.py``).
"""

from __future__ import annotations

import os
import re
from typing import Any, Optional

# Set by force_cpu_simulation: the CPU backend was explicitly requested
# (CLI --simulate, tests, a bench script) rather than silently fallen
# back to.
_SIMULATION_FORCED = False
# The recorded reason when the simulation IS a degraded fallback (the
# bench.py device probe found the accelerator unreachable).
_DEGRADED_REASON: Optional[str] = None


def force_cpu_simulation(num_devices: int,
                         degraded_reason: Optional[str] = None) -> None:
    """Stand up an ``num_devices``-device CPU-simulated mesh.

    ``degraded_reason`` marks this simulation as a *fallback* (the
    accelerator backend was wanted but unreachable); it flows into every
    subsequent :func:`topology_record` so sweeps journal the degradation
    instead of logging it."""
    global _SIMULATION_FORCED, _DEGRADED_REASON
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+",
            f"--xla_force_host_platform_device_count={num_devices}",
            flags,
        )
    else:
        flags = f"{flags} --xla_force_host_platform_device_count={num_devices}"
    os.environ["XLA_FLAGS"] = flags.strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    _SIMULATION_FORCED = True
    if degraded_reason is not None:
        _DEGRADED_REASON = degraded_reason

    import jax

    jax.config.update("jax_platforms", "cpu")


def simulation_forced() -> bool:
    """Whether this process explicitly requested the CPU-simulated mesh."""
    return _SIMULATION_FORCED


def degraded_reason() -> Optional[str]:
    """The recorded degradation reason, or None when the backend is the
    one the process asked for."""
    return _DEGRADED_REASON


def topology_record(
    fault_domains: Optional[dict[str, Any]] = None,
) -> dict[str, Any]:
    """The topology fingerprint every sweep artifact set carries
    (``sweep_manifest.json`` ``topology`` key + a ``topology`` journal
    event): which platform actually backs the mesh, how many devices and
    processes, and whether that is a DEGRADED state — either an explicit
    probe-fallback (:func:`force_cpu_simulation` with a reason) or a
    silent landing on CPU that nobody requested (the exact failure mode
    of rounds 4–5, where the tunnel died and benches fell back without a
    durable record).

    ``fault_domains`` (serving fleets only — ``serve/fleet.py``) maps
    replica id -> device ids; its presence marks the artifact as a
    FLEET run, and overlay/report tooling keys on it so fleet numbers
    never silently aggregate with single-replica numbers."""
    import jax

    platform = jax.default_backend()
    silent_cpu = (
        platform == "cpu"
        and not _SIMULATION_FORCED
        and os.environ.get("JAX_PLATFORMS", "").strip().lower() != "cpu"
    )
    degraded = _DEGRADED_REASON is not None or silent_cpu
    rec: dict[str, Any] = {
        "platform": platform,
        "num_devices": len(jax.devices()),
        "process_count": jax.process_count(),
        "simulated": platform == "cpu",
        "simulation_forced": _SIMULATION_FORCED,
        "degraded": bool(degraded),
    }
    if _DEGRADED_REASON is not None:
        rec["degraded_reason"] = _DEGRADED_REASON
    elif silent_cpu:
        rec["degraded_reason"] = (
            "process landed on the CPU backend without simulation being "
            "requested (accelerator plugin unavailable?)"
        )
    if fault_domains is not None:
        rec["fault_domains"] = dict(fault_domains)
    return rec
