"""JAX version-compatibility shims.

This image pins JAX 0.4.37, where ``shard_map`` still lives at
``jax.experimental.shard_map.shard_map`` with the older keyword surface
(``check_rep``, ``auto``).  Newer JAX promotes it to ``jax.shard_map`` and
renames ``check_rep`` -> ``check_vma`` and ``auto`` -> its complement
``axis_names`` (the axes that ARE manual).  Every shard_map call site in the
repo imports from here and writes against the *new* surface; this module
translates when only the experimental API exists.
"""

from __future__ import annotations

import jax

# True when shard_map supports genuinely-auto (non-manual) mesh axes of
# size > 1.  On jaxlib 0.4.37 the SPMD partitioner hard-aborts the process
# (`Check failed: sharding.IsManualSubgroup()`) on the collective-permutes
# such programs lower to — verified with a ppermute over a manual axis on a
# (2,2,2) mesh with two auto axes — so pipeline+dp/tp/ep composition is
# unavailable and the shim below raises at trace time instead.  Tests for
# that composition skip on this flag.
PARTIAL_AUTO_SHARD_MAP = hasattr(jax, "shard_map")

if PARTIAL_AUTO_SHARD_MAP:  # JAX >= 0.6: first-class API
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None,
                  axis_names=None, **kwargs):
        """New-style ``jax.shard_map`` surface on the experimental API.

        ``axis_names`` (new: the manual axes) becomes ``auto`` (old: the
        axes left automatic — the complement over the mesh); ``check_vma``
        becomes ``check_rep``.
        """
        if axis_names is not None:
            # axes of size 1 are semantically identical manual or auto (the
            # local shard IS the global array and the body never names
            # them), so fold them into the manual set — that keeps e.g. a
            # (dp=1, pp=2, tp=1) pipeline mesh on the working full-manual
            # path below
            auto = frozenset(
                a for a in mesh.axis_names
                if a not in axis_names and mesh.shape[a] > 1
            )
            if auto:
                # Genuinely partial-auto shard_map on this jaxlib aborts
                # XLA with `Check failed: sharding.IsManualSubgroup()`
                # (fatal, kills the process) — fail at trace time instead.
                raise NotImplementedError(
                    "shard_map with auto (non-manual) mesh axes "
                    f"{sorted(auto)} is not supported on JAX "
                    f"{jax.__version__}: the SPMD partitioner aborts on "
                    "manual-subgroup shardings. Use a mesh whose non-"
                    "manual axes have size 1, or a newer JAX."
                )
        if check_vma is not None:
            kwargs["check_rep"] = check_vma
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kwargs)


if hasattr(jax.lax, "pcast"):
    pcast = jax.lax.pcast
else:
    def pcast(x, axis_name, to="varying"):
        """``jax.lax.pcast`` for JAX < 0.7: under the old ``check_rep``
        replication tracking there is no explicit varying-axes type, so
        replicated -> varying casts are implicit and this is the identity."""
        del axis_name, to
        return x


_COMPILER_OPTION_SUPPORT: dict[str, bool] = {}


def supports_compiler_option(name: str, value: str = "") -> bool:
    """Whether this jaxlib's PJRT compile path accepts a per-computation
    DebugOptions override for ``name``.  jaxlib 0.4.x sets options through
    protobuf reflection's ``SetString``, which raises on repeated fields
    (e.g. ``xla_disable_hlo_passes``) — such options then exist only as
    process-start ``XLA_FLAGS``.  Probes with a trivial jit and caches."""
    if name not in _COMPILER_OPTION_SUPPORT:
        import jax.numpy as jnp

        try:
            jax.jit(lambda x: x + 1).lower(jnp.zeros(())).compile(
                compiler_options={name: value}
            )
            _COMPILER_OPTION_SUPPORT[name] = True
        except Exception:
            _COMPILER_OPTION_SUPPORT[name] = False
    return _COMPILER_OPTION_SUPPORT[name]


if hasattr(jax.lax, "axis_size"):
    axis_size = jax.lax.axis_size
else:
    def axis_size(axis_name):
        """``jax.lax.axis_size`` for JAX < 0.5: ``psum(1, axis)`` is
        special-cased to the static axis size."""
        return jax.lax.psum(1, axis_name)
