"""Command-line interface.

Replaces the reference's launch layer (L7): ``mpirun -np N python
collectives/1d/openmpi.py`` with edit-the-file constants becomes
``python -m dlbb_tpu.cli bench1d --ranks 2 4 8 --variant ring``; the
rank-count sweep loops of ``collectives/launch_{openmpi,intelmpi,dsccl}.sh``
become the ``--ranks`` flag; the CCL_* env tuning matrix becomes
``--variant`` (see ``dlbb_tpu.comm.variants``).

``--simulate N`` stands up the N-device CPU-simulated mesh (the dev path,
analogue of running N ranks on localhost) — it must act before the JAX
backend initialises, which is why it is handled first in ``main``.
"""

from __future__ import annotations

import argparse
import sys


def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument("--impl", default="xla_tpu", help="implementation name recorded in results")
    p.add_argument("--variant", default="default", help="named tuning variant")
    p.add_argument("--ranks", type=int, nargs="+", default=None, help="rank counts to sweep")
    p.add_argument("--dtype", default="bfloat16", choices=["bfloat16", "float16", "float32"])
    p.add_argument("--warmup", type=int, default=10)
    p.add_argument("--iters", type=int, default=100)
    p.add_argument("--output", default=None, help="output directory for result JSONs")
    p.add_argument("--simulate", type=int, default=0, metavar="N",
                   help="use an N-device CPU-simulated mesh (dev path)")
    p.add_argument("--resume", action="store_true",
                   help="skip configs whose result JSON already exists in the "
                        "output dir (pick an interrupted sweep back up)")
    p.add_argument("--no-pipeline", action="store_true",
                   help="disable the compile-ahead thread and compile each "
                        "config inline (serial debug mode; identical result "
                        "schema and timing semantics)")
    p.add_argument("--pipeline", action="store_true",
                   help="force the compile-ahead thread on (default: auto — "
                        "enabled only on hosts with spare cores)")
    p.add_argument("--prefetch", type=int, default=2, metavar="K",
                   help="configs compiled ahead of the one measuring "
                        "(pipelined mode; default 2)")
    p.add_argument("--compile-cache", default="auto", metavar="DIR|off",
                   help="persistent XLA compilation cache directory "
                        "('auto' = results/.xla_cache relative to the CWD, "
                        "like every other default path here; 'off' "
                        "disables; DLBB_XLA_CACHE env overrides)")
    p.add_argument("--fault-plan", default=None, metavar="PLAN",
                   help="deterministic fault-injection plan (chaos "
                        "harness, e.g. 'exec-transient:2,seed=7'; "
                        "DLBB_FAULT_PLAN env is the default; see "
                        "docs/resilience.md)")
    p.add_argument("--deadline", type=float, default=None, metavar="SEC",
                   dest="unit_deadline",
                   help="wall-clock watchdog per work unit (compile + "
                        "measurement); an overrun is abandoned and "
                        "quarantined (DLBB_UNIT_DEADLINE env default)")
    p.add_argument("--max-retries", type=int, default=2, metavar="N",
                   help="bounded retries with exponential backoff for "
                        "transient per-config failures (default 2; "
                        "retried configs recompute from scratch and "
                        "record `retries` in the artifact)")
    p.add_argument("--no-journal", action="store_true",
                   help="disable the append-only sweep_journal.jsonl "
                        "(crash audit trail; on by default)")
    p.add_argument("--device-trace", default=None, metavar="DIR",
                   dest="device_trace",
                   help="capture a jax.profiler device trace per config on "
                        "a DEDICATED profile rep (excluded from the stats "
                        "series) under DIR; DLBB_DEVICE_TRACE env is the "
                        "default (docs/observability.md)")
    _add_trace(p)


def _add_trace(p: argparse.ArgumentParser) -> None:
    p.add_argument("--trace", default=None, metavar="DIR",
                   help="write an XLA profiler trace (xplane) to DIR; "
                        "DLBB_TRACE_DIR env is the default")
    p.add_argument("--span-trace", default=None, metavar="FILE",
                   dest="span_trace",
                   help="write a host-side span trace (Chrome trace-event "
                        "JSON, Perfetto-loadable) of the whole run to FILE; "
                        "DLBB_SPANS env is the default "
                        "(docs/observability.md)")


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="dlbb_tpu", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)

    b1 = sub.add_parser("bench1d", help="1D collective microbenchmark sweep")
    _add_common(b1)
    b1.add_argument("--ops", nargs="+", default=None, help="collectives to benchmark")
    b1.add_argument("--sizes", nargs="+", default=None,
                    help="size labels (1KB 64KB 1MB 16MB 64MB 256MB 1GB) or 'extended'")

    b3 = sub.add_parser("bench3d", help="3D (batch, seq, hidden) tensor collective sweep")
    _add_common(b3)
    b3.add_argument("--ops", nargs="+", default=None)
    b3.add_argument("--batch", type=int, nargs="+", default=None)
    b3.add_argument("--seq", type=int, nargs="+", default=None)
    b3.add_argument("--hidden", type=int, nargs="+", default=None)

    s1 = sub.add_parser("stats1d", help="process 1D result JSONs to stats + CSV")
    s1.add_argument("--input", required=True)
    s1.add_argument("--output", required=True)
    s1.add_argument("--algorithm-bandwidth", action="store_true",
                    help="use per-op bus-bandwidth factors instead of the "
                         "reference's uniform formula")

    s3 = sub.add_parser("stats3d", help="process 3D result JSONs to standard+transposed CSVs")
    s3.add_argument("--input", required=True)
    s3.add_argument("--output", required=True)
    s3.add_argument("--impl", default="xla_tpu")

    cp = sub.add_parser(
        "compare",
        help="reference-vs-dlbb_tpu head-to-head comparison report "
             "(CSV + markdown, per-config match/beat/lose verdicts)",
    )
    cp.add_argument("--reference", default="/root/reference",
                    help="reference repo root (holds collectives/{1d,3d}/results)")
    cp.add_argument("--own-1d", default="results/1d/xla_tpu")
    cp.add_argument("--own-3d", default="results/3d/xla_tpu")
    cp.add_argument("--output", default="stats/compare")

    e2 = sub.add_parser("e2e", help="end-to-end TP transformer forward benchmark")
    e2.add_argument("--config", required=True, help="YAML experiment config")
    e2.add_argument("--simulate", type=int, default=0, metavar="N")
    e2.add_argument("--output", default=None)
    e2.add_argument("--tp-overlap", default=None,
                    choices=("off", "ring", "bidir"), dest="tp_overlap",
                    help="override model.tp_overlap: off = GSPMD fused TP "
                         "collectives, ring/bidir = ring-decomposed "
                         "collective matmuls overlapping comm with compute "
                         "(docs/overlap.md)")
    _add_trace(e2)

    rp = sub.add_parser(
        "reports",
        help="regenerate the derived comparison reports (variant tuning "
             "1D + 3D winners, parallelism families) from committed "
             "results/ + stats/ — pure file processing, no backend",
    )
    rp.add_argument("--stats", default="stats", help="stats tree root")
    rp.add_argument("--results", default="results",
                    help="results tree root (parallelism artifacts)")

    an = sub.add_parser(
        "analyze",
        help="comm-lint: static HLO collective audit, α–β schedule audit, "
             "and source lint (verifies benchmarks match their "
             "parallelism plan, no TPU needed — runs on the --simulate "
             "mesh).  Exit codes are a pinned contract: 0 clean / "
             "1 findings / 2 crash (docs/schedule_audit.md)",
    )
    an.add_argument("which", nargs="?", default="all",
                    choices=("hlo", "lint", "schedule", "memory",
                             "numerics", "all", "snapshot", "diff"),
                    help="pass to run: hlo = collective byte audit, "
                         "schedule = α–β critical-path/overlap audit, "
                         "memory = buffer-liveness peak-HBM audit, "
                         "numerics = dtype-flow precision audit, "
                         "lint = AST source lint, all = every pass "
                         "(default); snapshot = (re)write the "
                         "regression baselines (schedule + memory + "
                         "numerics axes), diff = fail on unexplained "
                         "drift from the committed baselines")
    an.add_argument("--simulate", type=int, default=0, metavar="N",
                    help="use an N-device CPU-simulated mesh for the HLO "
                         "audit (targets needing more devices than "
                         "available are skipped)")
    an.add_argument("--json", default=None, metavar="PATH",
                    help="write the machine-readable findings report here")
    an.add_argument("--root", default=".",
                    help="repo root for the source lint (default: cwd)")
    an.add_argument("--strict-warnings", action="store_true",
                    help="exit nonzero on warnings too")
    an.add_argument("--baselines", default=None, metavar="DIR",
                    help="schedule-baseline directory for snapshot/diff "
                         "(default: stats/analysis/baselines)")
    an.add_argument("--tier", default=None, metavar="TIER",
                    help="cost-model link tier for the schedule audit "
                         "(cpu-sim, tpu-v5lite, tpu-v5lite-dcn; default: "
                         "auto from the backend — see "
                         "analysis/costmodel.py)")
    an.add_argument("--model", default="cm1", choices=("cm1", "cm2"),
                    help="cost model the schedule audit prices with: cm1 "
                         "= analytic seed constants, cm2 = coefficients "
                         "fitted from the sweep corpus "
                         "(stats/analysis/costmodel_fit/; falls back to "
                         "cm1 with a fit-missing warning)")
    an.add_argument("--output", default=None, metavar="DIR",
                    help="observability surface for the memory + "
                         "numerics audits: write memory_audit.json / "
                         "numerics_audit.json under DIR, merge the "
                         "per-target peak_live_bytes and numerics gate "
                         "keys into DIR/sweep_manifest.json, and fold "
                         "analysis_peak_live_bytes{target} / "
                         "analysis_numerics_* / per-pass "
                         "analysis_findings{pass,severity} gauges into "
                         "DIR/metrics.prom (docs/memory_audit.md, "
                         "docs/numerics.md)")

    ob = sub.add_parser(
        "obs",
        help="runtime observability: journal->trace reconstruction "
             "(trace), the predicted-vs-measured cost-model calibration "
             "report (calibrate), and the calibration regression gate "
             "(diff) — exit codes pinned 0 clean / 1 findings / 2 crash "
             "(docs/observability.md)",
    )
    ob.add_argument("which", choices=("trace", "calibrate", "diff",
                                      "fit", "attribute", "devtrace"),
                    help="trace = rebuild a Perfetto timeline from a "
                         "sweep's journal; calibrate = measure every "
                         "committed schedule-baseline target and report "
                         "signed predicted-vs-measured error; diff = fail "
                         "when the model error regressed past the "
                         "committed calibration baseline; fit = regress "
                         "cm2 (α, β, peak, per-dispatch γ) from the "
                         "sweep-artifact corpus into the versioned "
                         "fitted DB; attribute = join a run's span "
                         "trace/journal against the cost model into a "
                         "per-phase 'where did the time go' report "
                         "(MD+CSV under stats/analysis/attribution/); "
                         "devtrace = parse the run's device captures "
                         "into per-op measured timelines, report "
                         "measured overlap beside the static proof, and "
                         "mine the op-level cm2 fit samples (MD+CSV+JSON "
                         "under stats/analysis/devtrace/)")
    ob.add_argument("--journal", default=None, metavar="DIR",
                    help="sweep output directory holding "
                         "sweep_journal.jsonl (obs trace)")
    ob.add_argument("--output", default=None,
                    help="output path (trace JSON) or report directory "
                         "(calibrate/diff; default results/obs)")
    ob.add_argument("--baselines", default=None, metavar="DIR",
                    help="schedule-baseline directory to calibrate "
                         "against (default: stats/analysis/baselines)")
    ob.add_argument("--calibration", default=None, metavar="DIR",
                    help="committed calibration baseline for diff "
                         "(default: stats/analysis/calibration)")
    ob.add_argument("--report", default=None, metavar="JSON",
                    help="diff an existing calibration report instead of "
                         "re-measuring")
    ob.add_argument("--simulate", type=int, default=0, metavar="N")
    ob.add_argument("--tier", default=None, metavar="TIER",
                    help="cost-model tier (default: auto from the "
                         "backend; must match the committed baselines)")
    ob.add_argument("--reps", type=int, default=30,
                    help="timed reps per target (default 30)")
    ob.add_argument("--warmup", type=int, default=5)
    ob.add_argument("--targets", nargs="+", default=None,
                    help="substring filter on baseline target names "
                         "(calibrate/diff subset runs)")
    ob.add_argument("--strict-warnings", action="store_true",
                    help="exit nonzero on warnings too")
    ob.add_argument("--model", default="cm1", choices=("cm1", "cm2"),
                    help="cost model for calibrate/diff/attribute: cm1 "
                         "analytic constants, cm2 the fitted DB "
                         "(docs/observability.md)")
    ob.add_argument("--fit-dir", default=None, metavar="DIR",
                    dest="fit_dir",
                    help="fitted-DB directory (default "
                         "stats/analysis/costmodel_fit; obs fit writes "
                         "here, cm2 pricing reads here)")
    ob.add_argument("--results", nargs="+", default=None, metavar="DIR",
                    help="results tree(s) the fit ingests (obs fit; "
                         "default: results)")
    ob.add_argument("--span-trace-file", default=None, metavar="FILE",
                    dest="span_trace_file",
                    help="explicit span-trace JSON for obs attribute "
                         "(default: auto-detect in --journal DIR)")
    ob.add_argument("--min-samples", type=int, default=None,
                    dest="min_samples",
                    help="minimum corpus samples per tier before the fit "
                         "refuses (obs fit; default 16)")
    ob.add_argument("--host", default=None, dest="host_filter",
                    help="substring filter on the corpus host "
                         "fingerprint (obs fit): fit the tier for the "
                         "host you will predict on")

    ch = sub.add_parser(
        "chaos",
        help="chaos gate: mini-sweep/mini-train under each injected fault "
             "class, asserting the resilience invariants (no corrupt "
             "artifact survives, resume completes the grid, hangs are "
             "quarantined — docs/resilience.md)",
    )
    ch.add_argument("--plan", default="all",
                    help="fault class to exercise (compile, transient, "
                         "nan, torn, hang, ckpt, preempt, kill, serve, "
                         "fleet) or 'all'")
    ch.add_argument("--simulate", type=int, default=8, metavar="N",
                    help="CPU-simulated mesh size (default 8; the gate "
                         "needs no TPU)")
    ch.add_argument("--output", default=None,
                    help="workdir for the gate's artifacts (default: a "
                         "fresh temp dir, kept on failure)")

    sv = sub.add_parser(
        "serve",
        help="continuous-batching serving benchmark: a synthetic traffic "
             "trace served through the paged-KV-cache inference engine; "
             "reports goodput, TTFT / per-token latency p50/p99/p99.9, "
             "queue depth and cache occupancy (docs/serving.md)",
    )
    sv.add_argument("--config", default=None,
                    help="experiment YAML with model/parallelism/serving "
                         "sections (default: a small GQA model on an "
                         "auto-planned (dp, tp) mesh)")
    sv.add_argument("--trace", default="poisson",
                    help="arrival process (poisson, bursty, diurnal) or a "
                         "path to a saved trace JSON (replay)")
    sv.add_argument("--requests", type=int, default=100,
                    help="requests to generate (generated traces only)")
    sv.add_argument("--rate", type=float, default=None,
                    help="mean arrival rate in req/s (default 32)")
    sv.add_argument("--seed", type=int, default=42,
                    help="trace seed (arrivals, lengths, embeddings)")
    sv.add_argument("--max-batch", type=int, default=None,
                    dest="max_batch", help="decode slots (default 8)")
    sv.add_argument("--block-size", type=int, default=None,
                    dest="block_size",
                    help="KV-cache tokens per block (default 16)")
    sv.add_argument("--max-seq", type=int, default=None, dest="max_seq",
                    help="per-slot prompt+output ceiling (default 256)")
    sv.add_argument("--queue-capacity", type=int, default=None,
                    dest="queue_capacity",
                    help="admission-control queue bound (default 64)")
    sv.add_argument("--decode-horizon", type=int, default=None,
                    dest="decode_horizon",
                    help="fused-scan horizon cap K: fuse up to K decode "
                         "steps into one on-device lax.scan dispatch "
                         "(default 1 = per-step; docs/serving.md)")
    sv.add_argument("--inflight-window", type=int, default=None,
                    dest="inflight_window",
                    help="bounded in-flight decode dispatch window "
                         "(default 1 = sync every unit; 2 overlaps "
                         "dispatch N+1 with N's compute)")
    sv.add_argument("--prefill-chunk", type=int, default=None,
                    dest="prefill_chunk",
                    help="chunked prefill: tokens per chunk (a "
                         "block-size multiple), interleaved with decode "
                         "steps so long prompts stop head-of-line "
                         "blocking the batch (default: monolithic)")
    sv.add_argument("--compact-threshold", type=float, default=None,
                    dest="compact_threshold",
                    help="occupancy fraction (0, 0.5] at or below which "
                         "fused scans run on a gather-compacted half "
                         "batch (dp=1 meshes only; default: off)")
    sv.add_argument("--speculation", default=None,
                    choices=["off", "greedy", "ngram", "draft-model"],
                    help="decode feedback / drafting mode: off = legacy "
                         "continuous feedback, greedy = token feedback "
                         "without drafting, ngram = prompt-lookup "
                         "self-speculation, draft-model = shallow draft "
                         "transformer on the same mesh "
                         "(docs/serving.md, 'Speculative decoding')")
    sv.add_argument("--spec-gamma", type=int, default=None,
                    dest="spec_gamma",
                    help="draft tokens proposed per verify step (the γ "
                         "of draft-and-verify; required by ngram / "
                         "draft-model)")
    sv.add_argument("--spec-adaptive", action="store_true", default=None,
                    dest="spec_adaptive",
                    help="per-request adaptive γ: back off to a smaller "
                         "verify width on low acceptance EMA")
    sv.add_argument("--temperature", type=float, default=None,
                    help="sampled decode: softmax temperature of the "
                         "residual-sampling verify path (requires a "
                         "drafting speculation mode and "
                         "decode_horizon=1; default 0 = greedy argmax)")
    sv.add_argument("--sample-seed", type=int, default=None,
                    dest="sample_seed",
                    help="host RNG seed for the sampled (temperature "
                         "> 0) path — makes sampled runs replayable")
    sv.add_argument("--prefix-caching", action="store_true", default=None,
                    dest="prefix_caching",
                    help="shared-prefix KV reuse: content-address full "
                         "blocks in a host-side radix trie, attach new "
                         "admissions to a donor's matched blocks (one "
                         "masked copy replaces the matched chunks' "
                         "prefill — requires --prefill-chunk, dp=1; "
                         "docs/serving.md, 'Prefix cache & quantized "
                         "KV')")
    sv.add_argument("--kv-quantization", default=None,
                    dest="kv_quantization", choices=["none", "int8"],
                    help="KV-cache plane dtype: int8 stores K/V blocks "
                         "quantized with per-(block, kv-head) fp32 "
                         "scales — ~4x smaller cache under the same "
                         "hbm_budget_gb (docs/serving.md)")
    sv.add_argument("--prefix-groups", type=int, default=None,
                    dest="prefix_groups", metavar="G",
                    help="generated traces only: split requests into G "
                         "seeded populations sharing a common prompt "
                         "prefix (the system-prompt traffic shape the "
                         "prefix cache exploits)")
    sv.add_argument("--prefix-len", type=int, default=None,
                    dest="prefix_len", metavar="TOKENS",
                    help="shared-prefix length for --prefix-groups "
                         "(clamped per request to prompt_len - 1; "
                         "default: the prompt-range midpoint)")
    sv.add_argument("--slo", type=float, default=None, metavar="SEC",
                    help="per-request deadline (SLO) stamped on every "
                         "generated request: queued requests whose wait "
                         "already blew it are shed "
                         "(request-rejected[reason=deadline]) and "
                         "completions past it are counted "
                         "(docs/serving.md)")
    sv.add_argument("--dispatch-retries", type=int, default=None,
                    dest="max_dispatch_retries",
                    help="bounded retries for a transiently-failed "
                         "prefill/decode dispatch (default 2; host "
                         "state rolls back to the pre-dispatch "
                         "snapshot before each retry)")
    sv.add_argument("--dispatch-deadline-factor", type=float,
                    default=None, dest="dispatch_deadline_factor",
                    help="arm the in-flight dispatch watchdog: abandon "
                         "a decode unit exceeding FACTOR x K x the "
                         "per-step EMA (requests journaled "
                         "request-failed[reason=hung-dispatch]; "
                         "default: off)")
    sv.add_argument("--replicas", type=int, default=None, metavar="N",
                    help="serve through the replica-level fleet "
                         "supervisor: N failure domains, each its own "
                         "engine, with health-fencing / failover / "
                         "hedging / the overload degradation ladder; "
                         "the parallelism section (or auto-plan) then "
                         "describes ONE replica's mesh (docs/fleet.md)")
    sv.add_argument("--hedge-factor", type=float, default=None,
                    dest="hedge_factor", metavar="F",
                    help="fleet hedging: duplicate a request still "
                         "resident past F x the observed p99 latency "
                         "onto another replica — first completion "
                         "wins, the loser is canceled (needs "
                         "--replicas >= 2; docs/fleet.md)")
    sv.add_argument("--fault-plan", default=None, metavar="PLAN",
                    help="deterministic fault-injection plan for the "
                         "serving chaos harness (e.g. "
                         "'serve-decode-fail:1'; DLBB_FAULT_PLAN env "
                         "is the default; docs/resilience.md)")
    sv.add_argument("--resume", action="store_true",
                    help="finish a preempted serving run from the "
                         "serving_resume.json checkpoint in --output: "
                         "replays the remaining trace and merges both "
                         "sessions into the final artifact set")
    sv.add_argument("--output", default=None,
                    help="output directory (default results/serving)")
    sv.add_argument("--simulate", type=int, default=0, metavar="N")
    # --trace names the TRAFFIC here, so the xplane flag gets a
    # serve-specific name (main() routes it into maybe_trace)
    sv.add_argument("--xplane-trace", default=None, metavar="DIR",
                    dest="xplane_trace",
                    help="write an XLA profiler trace (xplane) to DIR "
                         "(the --trace flag of the other levels; "
                         "DLBB_TRACE_DIR env is the default)")
    sv.add_argument("--span-trace", default=None, metavar="FILE",
                    dest="span_trace",
                    help="write a host-side span trace (Chrome "
                         "trace-event JSON) of the run to FILE; "
                         "DLBB_SPANS env is the default "
                         "(docs/observability.md)")
    sv.add_argument("--device-trace", default=None, metavar="DIR",
                    dest="device_trace",
                    help="capture one prefill + one decode scan through "
                         "the obs/capture gate AFTER the trace is served "
                         "(outside every timed region) under DIR; "
                         "DLBB_DEVICE_TRACE env is the default; parsed "
                         "by `obs devtrace` (docs/observability.md)")

    pl = sub.add_parser(
        "plan",
        help="cm2-driven parallelism-plan autotuner: enumerate the full "
             "plan space, statically prune (validate_*/HBM, every pruned "
             "point journaled with its reason), rank by the fitted cost "
             "model, measure the top-k through the real engines "
             "(--auto); or price a fleet capacity curve over a traffic "
             "trace + SLO (--capacity) (docs/autotune.md)",
    )
    mode = pl.add_mutually_exclusive_group(required=True)
    mode.add_argument("--auto", action="store_true",
                      help="run the predict-prune-measure plan search")
    mode.add_argument("--capacity", action="store_true",
                      help="run the fleet capacity planner (predicted vs "
                           "measured goodput/TTFT per plan + replicas-"
                           "for-N-users curve, published to SERVING.md)")
    pl.add_argument("--target", default="serving",
                    choices=("serving", "train"),
                    help="which engine's plan space to search (--auto)")
    pl.add_argument("--top-k", type=int, default=2, dest="top_k",
                    help="cm2-ranked plans to validate with real "
                         "measured runs (the default heuristic plan is "
                         "always measured too)")
    pl.add_argument("--no-measure", action="store_true",
                    dest="no_measure",
                    help="static search only: enumerate, prune, rank — "
                         "skip the measured validation runs")
    pl.add_argument("--no-mesh-champions", action="store_true",
                    dest="no_mesh_champions",
                    help="measure only the overall top-k (default: also "
                         "measure the predicted-best plan of every "
                         "surviving mesh factorization, so a mesh the "
                         "model mis-ranks still reaches the agreement "
                         "table)")
    pl.add_argument("--trace", default="poisson",
                    help="traffic kind for the measured serving runs "
                         "(poisson, bursty, diurnal) or a saved trace")
    pl.add_argument("--requests", type=int, default=24,
                    help="requests per measured serving run")
    pl.add_argument("--rate", type=float, default=None,
                    help="mean arrival rate in req/s (default 32)")
    pl.add_argument("--seed", type=int, default=42,
                    help="trace seed (shared by every measured run)")
    pl.add_argument("--prompt-range", type=int, nargs=2, default=None,
                    dest="prompt_range", metavar=("MIN", "MAX"),
                    help="generated traces only: prompt-length bounds")
    pl.add_argument("--output-range", type=int, nargs=2, default=None,
                    dest="output_range", metavar=("MIN", "MAX"),
                    help="generated traces only: output-length bounds "
                         "(the committed reference workload saturates "
                         "decode with --rate 1e5 --prompt-range 8 16 "
                         "--output-range 240 240)")
    pl.add_argument("--slo", type=float, default=30.0,
                    help="TTFT SLO in seconds (--capacity; stamps the "
                         "trace's deadline_s)")
    pl.add_argument("--user-rate", type=float, default=0.2,
                    dest="user_rate",
                    help="req/s one user issues (--capacity curve)")
    pl.add_argument("--users", type=int, nargs="+",
                    default=(4, 8, 16, 32, 64),
                    help="N-user points on the capacity curve")
    pl.add_argument("--fit-dir", default=None, dest="fit_dir",
                    help="cm2 fitted-coefficient DB directory (default "
                         "stats/analysis/costmodel_fit; a missing fit "
                         "fails the search closed: every point is "
                         "journaled cm2-fit-missing)")
    pl.add_argument("--tier", default=None,
                    help="cost-model tier (default cpu-sim)")
    pl.add_argument("--output", default=None,
                    help="output directory (default results/autotune or "
                         "results/capacity)")
    pl.add_argument("--bench-out", default=None, dest="bench_out",
                    help="also write the repo-root bench artifact "
                         "(BENCH_autotune.json; --auto only)")
    pl.add_argument("--simulate", type=int, default=0, metavar="N")

    tr = sub.add_parser("train", help="DDP/ZeRO-{1,2,3} training-loop benchmark")
    tr.add_argument("--config", required=True, help="YAML experiment config")
    tr.add_argument("--simulate", type=int, default=0, metavar="N")
    tr.add_argument("--zero1", action="store_true", help="shard optimizer state (ZeRO-1)")
    tr.add_argument("--zero", type=int, default=None, choices=(0, 1, 2, 3),
                    metavar="STAGE", dest="zero_stage",
                    help="ZeRO stage: 0=DDP, 1=opt-state sharding, "
                         "2=+grad reduce-scatter, 3=FSDP param sharding")
    tr.add_argument("--output", default=None)
    tr.add_argument("--tp-overlap", default=None,
                    choices=("off", "ring", "bidir"), dest="tp_overlap",
                    help="override model.tp_overlap (see the e2e flag)")
    tr.add_argument("--grad-compression", default=None,
                    choices=("none", "int8", "fp8"), dest="grad_compression",
                    help="override training.grad_compression: quantise "
                         "the dp gradient reduction to an int8/fp8 wire "
                         "with an error-feedback residual "
                         "(docs/compression.md)")
    _add_trace(tr)

    return ap


def main(argv: list[str] | None = None) -> int:
    import os

    args = build_parser().parse_args(argv)
    if getattr(args, "simulate", 0):
        from dlbb_tpu.utils.simulate import force_cpu_simulation

        force_cpu_simulation(args.simulate)
    elif (
        os.environ.get("DLBB_DISTRIBUTED") == "auto"
        and args.cmd in ("bench1d", "bench3d", "e2e", "train", "serve")
    ):
        # pod launcher path (launch/launch_tpu_pod.sh): stand up
        # jax.distributed across hosts before any backend use; stats
        # subcommands are pure file processing and skip the handshake
        from dlbb_tpu.comm.mesh import initialize_distributed

        ctx = initialize_distributed(auto=True)
        print(
            f"[distributed] process {ctx.process_id}/{ctx.num_processes}, "
            f"{ctx.num_devices} devices"
        )

    if getattr(args, "variant", None) is not None:
        from dlbb_tpu.comm.variants import get_variant

        try:
            get_variant(args.variant)
        except KeyError as e:
            print(f"error: {e.args[0]}")
            return 2

    if args.cmd in ("bench1d", "bench3d", "e2e", "train", "serve"):
        # stats subcommands are pure numpy file processing — no backend,
        # no profiler, and no jax import even when DLBB_TRACE_DIR is set
        from dlbb_tpu.obs import spans
        from dlbb_tpu.utils.profiling import maybe_trace

        span_path = getattr(args, "span_trace", None) \
            or spans.default_span_path()
        # serve's --trace selects the traffic; its xplane dir rides the
        # dedicated --xplane-trace flag
        profile_dir = (getattr(args, "xplane_trace", None)
                       if args.cmd == "serve"
                       else getattr(args, "trace", None))
        with spans.tracing(span_path, meta={"cmd": args.cmd}) as tracer, \
                maybe_trace(profile_dir) as trace_dir:
            rc = _dispatch(args)
        if trace_dir:
            print(f"[trace] xplane trace written to {trace_dir}")
        if tracer is not None:
            print(f"[obs] span trace written to {tracer.path} "
                  "(load in https://ui.perfetto.dev)")
        return rc
    return _dispatch(args)


def _pipeline_arg(args):
    """--no-pipeline > --pipeline > None (host-auto)."""
    if args.no_pipeline:
        return False
    if args.pipeline:
        return True
    return None


def _dispatch(args) -> int:
    if args.cmd == "bench1d":
        from dlbb_tpu.bench import (
            DATA_SIZES_1D,
            EXTENDED_DATA_SIZES_1D,
            OPERATIONS_1D,
            Sweep1D,
            run_sweep,
        )

        if args.sizes == ["extended"]:
            sizes = tuple(EXTENDED_DATA_SIZES_1D.items())
        elif args.sizes:
            table = EXTENDED_DATA_SIZES_1D
            unknown = [s for s in args.sizes if s not in table]
            if unknown:
                print(f"unknown size labels {unknown}; known: {list(table)}")
                return 2
            sizes = tuple((s, table[s]) for s in args.sizes)
        else:
            sizes = tuple(DATA_SIZES_1D.items())
        sweep = Sweep1D(
            implementation=args.impl,
            variant=args.variant,
            operations=tuple(args.ops) if args.ops else OPERATIONS_1D,
            data_sizes=sizes,
            rank_counts=tuple(args.ranks) if args.ranks else (2, 4, 8),
            dtype=args.dtype,
            warmup_iterations=args.warmup,
            measurement_iterations=args.iters,
            output_dir=args.output or "results/1d",
            resume=args.resume,
            pipeline=_pipeline_arg(args),
            prefetch=args.prefetch,
            compile_cache=args.compile_cache,
            fault_plan=args.fault_plan,
            unit_deadline_seconds=args.unit_deadline,
            max_retries=args.max_retries,
            journal=not args.no_journal,
            span_trace=args.span_trace,
            device_trace_dir=args.device_trace,
        )
        files = run_sweep(sweep)
        # resume mode counts pre-existing artifacts too — don't claim writes
        print(f"{len(files)} result artifacts in {sweep.output_dir}")
        return 0

    if args.cmd == "bench3d":
        from dlbb_tpu.bench import GRID_3D, OPERATIONS_3D, Sweep3D, run_sweep

        sweep = Sweep3D(
            implementation=args.impl,
            variant=args.variant,
            operations=tuple(args.ops) if args.ops else OPERATIONS_3D,
            batch_sizes=tuple(args.batch) if args.batch else tuple(GRID_3D["batch_sizes"]),
            seq_lengths=tuple(args.seq) if args.seq else tuple(GRID_3D["seq_lengths"]),
            hidden_dims=tuple(args.hidden) if args.hidden else tuple(GRID_3D["hidden_dims"]),
            rank_counts=tuple(args.ranks) if args.ranks else (4, 8),
            dtype=args.dtype,
            warmup_iterations=args.warmup,
            measurement_iterations=args.iters,
            output_dir=args.output or "results/3d",
            resume=args.resume,
            pipeline=_pipeline_arg(args),
            prefetch=args.prefetch,
            compile_cache=args.compile_cache,
            fault_plan=args.fault_plan,
            unit_deadline_seconds=args.unit_deadline,
            max_retries=args.max_retries,
            journal=not args.no_journal,
            span_trace=args.span_trace,
            device_trace_dir=args.device_trace,
        )
        files = run_sweep(sweep)
        print(f"{len(files)} result artifacts in {sweep.output_dir}")
        return 0

    if args.cmd == "stats1d":
        from dlbb_tpu.stats import process_1d_results

        results = process_1d_results(
            args.input, args.output,
            algorithm_bandwidth=args.algorithm_bandwidth,
        )
        print(f"processed {len(results)} result files")
        return 0

    if args.cmd == "stats3d":
        from dlbb_tpu.stats import process_3d_results

        results = process_3d_results(args.input, args.output, args.impl)
        print(f"processed {len(results)} result files")
        return 0

    if args.cmd == "compare":
        from pathlib import Path

        from dlbb_tpu.stats import write_comparison

        summary = write_comparison(
            Path(args.reference), Path(args.own_1d), Path(args.own_3d),
            Path(args.output), repo_root=Path.cwd(),
        )
        for dim in ("1d", "3d"):
            s = summary[dim]
            print(f"{dim}: {s['configs']} configs — {s['beat']} beat, "
                  f"{s['match']} match, {s['lose']} lose")
        print(f"report written to {args.output}/COMPARISON.md")
        return 0

    if args.cmd == "reports":
        from pathlib import Path

        from dlbb_tpu.stats import write_variants_report
        from dlbb_tpu.stats.parallelism_report import (
            DEFAULT_FAMILIES,
            write_parallelism_report,
        )
        from dlbb_tpu.stats.variants_report import write_variants3d_report

        stats_root, results_root = Path(args.stats), Path(args.results)
        produced = 0
        summary = write_variants_report(stats_root / "variants")
        if summary["winners"]:
            produced += 1
            print(f"variants: {len(summary['winners'])} sizes across rank "
                  f"counts {sorted(summary.get('ranks', {}))} -> "
                  f"{stats_root / 'variants' / 'VARIANTS.md'}")
        else:
            print(f"variants: no stats under {stats_root / 'variants'} — "
                  "skipped")
        rows3d = write_variants3d_report(stats_root / "variants3d")
        if rows3d:
            produced += 1
            print(f"variants3d: {len(rows3d)} joined configs -> "
                  f"{stats_root / 'variants3d' / 'VARIANTS3D.md'}")
        else:
            print(f"variants3d: no stats under "
                  f"{stats_root / 'variants3d'} — skipped")
        # only (re)write the parallelism report when its input artifacts
        # exist: a typo'd --results must not clobber the committed report
        # with an all-null table
        par_dir = results_root / "parallelism"
        if any(par_dir.glob("train_*.json")):
            rows = write_parallelism_report(
                par_dir, stats_root / "parallelism", DEFAULT_FAMILIES,
            )
            measured = [
                r for r in rows if r["step_time_mean_s"] is not None
            ]
            produced += 1
            print(f"parallelism: {len(measured)} measured members -> "
                  f"{stats_root / 'parallelism' / 'PARALLELISM.md'}")
        else:
            print(f"parallelism: no train_*.json under {par_dir} — "
                  "skipped")
        cp_dir = par_dir / "cp_scaling"
        if any(cp_dir.glob("train_ddp_cp_s*.json")):
            from dlbb_tpu.stats.parallelism_report import (
                write_cp_scaling_report,
            )

            cp_rows = write_cp_scaling_report(
                cp_dir, stats_root / "parallelism",
            )
            produced += 1
            print(f"cp_scaling: {len(cp_rows)} (S, sp) cells -> "
                  f"{stats_root / 'parallelism' / 'CP_SCALING.md'}")
        else:
            print(f"cp_scaling: no train_ddp_cp_s*.json under {cp_dir} — "
                  "skipped")
        serve_dir = results_root / "serving"
        if any(p.name != "serving_manifest.json"
               for p in serve_dir.rglob("serving_*.json")) or \
                any(serve_dir.rglob("fleet_*.json")):
            from dlbb_tpu.stats.serving_report import write_serving_report

            srows = write_serving_report(serve_dir, stats_root / "serving")
            if srows:
                produced += 1
                print(f"serving: {len(srows)} run(s) -> "
                      f"{stats_root / 'serving' / 'SERVING.md'}")
        else:
            print(f"serving: no serving_*.json under {serve_dir} — "
                  "skipped")
        bench_fleet = Path("BENCH_fleet.json")
        if bench_fleet.exists():
            from dlbb_tpu.stats.serving_report import write_fleet_report

            flrows = write_fleet_report(bench_fleet,
                                        stats_root / "serving")
            if flrows:
                produced += 1
                print(f"fleet: {len(flrows)} setting(s) -> "
                      f"{stats_root / 'serving' / 'FLEET.md'}")
        else:
            print("fleet: no BENCH_fleet.json at the repo root — "
                  "skipped")
        bench_serve = Path("BENCH_serve.json")
        if bench_serve.exists():
            from dlbb_tpu.stats.serving_report import write_fastpath_report

            frows = write_fastpath_report(bench_serve,
                                          stats_root / "serving")
            if frows:
                produced += 1
                print(f"fastpath: {len(frows)} setting(s) -> "
                      f"{stats_root / 'serving' / 'FASTPATH.md'}")
        else:
            print("fastpath: no BENCH_serve.json at the repo root — "
                  "skipped")
        bench_autotune = Path("BENCH_autotune.json")
        if bench_autotune.exists():
            from dlbb_tpu.stats.parallelism_report import (
                write_autotune_report,
            )

            arows = write_autotune_report(bench_autotune,
                                          stats_root / "parallelism")
            if arows:
                produced += 1
                print(f"autotune: {len(arows)} measured plan(s) -> "
                      f"{stats_root / 'parallelism' / 'AUTOTUNE.md'}")
        else:
            print("autotune: no BENCH_autotune.json at the repo root — "
                  "skipped")
        from dlbb_tpu.stats.northstar import (
            default_stats_1d_csv,
            write_northstar_report,
        )

        ns = write_northstar_report(
            default_stats_1d_csv(stats_root), stats_root / "northstar",
        )
        if ns:
            produced += 1
            print(f"northstar: {sum(ns.values())} size rows across "
                  f"{list(ns)} -> {stats_root / 'northstar' / 'NORTHSTAR.md'}")
        else:
            print(f"northstar: no north-star rows in "
                  f"{default_stats_1d_csv(stats_root)} — skipped")
        if produced == 0:
            print("error: nothing to report — check --stats/--results "
                  "point at the committed trees")
            return 1
        return 0

    if args.cmd == "analyze":
        from dlbb_tpu.analysis import run_analysis

        return run_analysis(
            which=args.which, root=args.root, json_path=args.json,
            strict_warnings=args.strict_warnings,
            baselines=args.baselines, tier=args.tier, model=args.model,
            output=args.output,
        )

    if args.cmd == "obs":
        from dlbb_tpu.obs import run_obs

        return run_obs(
            which=args.which, journal=args.journal, output=args.output,
            baselines=args.baselines, calibration=args.calibration,
            report=args.report, tier=args.tier, reps=args.reps,
            warmup=args.warmup, targets=args.targets,
            strict_warnings=args.strict_warnings, model=args.model,
            fit_dir=args.fit_dir, results=args.results,
            trace=args.span_trace_file, min_samples=args.min_samples,
            host_filter=args.host_filter,
        )

    if args.cmd == "chaos":
        from dlbb_tpu.resilience.chaos import run_chaos

        return run_chaos(plan=args.plan, output=args.output)

    if args.cmd == "e2e":
        try:
            from dlbb_tpu.bench.e2e import run_e2e_from_config
        except ImportError:
            print("error: the e2e benchmark module is not available in this build")
            return 2

        result = run_e2e_from_config(args.config, output_dir=args.output,
                                     tp_overlap=args.tp_overlap)
        print(f"forward mean {result['forward_time']['mean'] * 1e3:.2f} ms")
        return 0

    if args.cmd == "serve":
        from dlbb_tpu.serve.bench import run_serve_from_config

        result = run_serve_from_config(
            args.config,
            trace=args.trace,
            num_requests=args.requests,
            seed=args.seed,
            rate=args.rate,
            output_dir=args.output,
            overrides={
                "max_batch": args.max_batch,
                "block_size": args.block_size,
                "max_seq": args.max_seq,
                "queue_capacity": args.queue_capacity,
                "decode_horizon": args.decode_horizon,
                "inflight_window": args.inflight_window,
                "prefill_chunk": args.prefill_chunk,
                "compact_threshold": args.compact_threshold,
                "speculation": args.speculation,
                "spec_gamma": args.spec_gamma,
                "spec_adaptive": args.spec_adaptive,
                "max_dispatch_retries": args.max_dispatch_retries,
                "dispatch_deadline_factor":
                    args.dispatch_deadline_factor,
                "prefix_caching": args.prefix_caching,
                "kv_quantization": args.kv_quantization,
                "temperature": args.temperature,
                "sample_seed": args.sample_seed,
                "hedge_factor": args.hedge_factor,
            },
            resume=args.resume,
            fault_plan=args.fault_plan,
            slo=args.slo,
            device_trace=args.device_trace,
            prefix_groups=args.prefix_groups,
            prefix_len=args.prefix_len,
            replicas=args.replicas,
        )
        req = result["requests"]
        if "failovers" in result:
            live = sum(1 for r in result["replicas"]
                       if r["status"] == "ok")
            print(
                f"fleet: {live}/{len(result['replicas'])} replica(s) "
                f"healthy, {result['failovers']['total']} failover(s), "
                f"{result['hedges']['issued']} hedge(s) issued, "
                f"degrade level {result['degrade']['level']} "
                f"({result['degrade']['name']})"
            )
        if result.get("prefix", {}).get("enabled"):
            pre = result["prefix"]
            print(
                f"prefix cache: {pre['hits']} hit(s), "
                f"{pre['tokens_reused']} token(s) reused "
                f"(hit rate {pre['hit_rate']:.2f})"
            )
        if result.get("preempted"):
            print(
                f"preempted after {req['completed']} completed "
                f"request(s); {len(result['remaining_rids'])} remain — "
                "finish with `serve --resume`"
            )
            return 0
        print(
            f"goodput {result['goodput_tokens_per_s']:.0f} tok/s over "
            f"{req['completed']} completed / {req['rejected']} rejected "
            f"request(s)"
        )
        return 0

    if args.cmd == "plan":
        from dlbb_tpu.analysis.costmodel import DEFAULT_TIER

        tier_name = args.tier or DEFAULT_TIER
        n_dev = args.simulate
        if not n_dev:
            import jax

            n_dev = len(jax.devices())
        trace_params = {}
        if args.prompt_range:
            trace_params["prompt_range"] = tuple(args.prompt_range)
        if args.output_range:
            trace_params["output_range"] = tuple(args.output_range)
        if args.capacity:
            from dlbb_tpu.plan.autotune import run_capacity_plan

            run_capacity_plan(
                n_devices=n_dev, slo=args.slo, users=tuple(args.users),
                user_rate=args.user_rate, trace=args.trace,
                num_requests=args.requests, seed=args.seed,
                rate=args.rate, trace_params=trace_params or None,
                output_dir=args.output or "results/capacity",
                tier_name=tier_name, fit_dir=args.fit_dir,
            )
            return 0
        from dlbb_tpu.plan.autotune import run_plan_search

        result = run_plan_search(
            target=args.target, n_devices=n_dev, top_k=args.top_k,
            output_dir=args.output or "results/autotune",
            trace=args.trace, num_requests=args.requests,
            seed=args.seed, rate=args.rate,
            trace_params=trace_params or None, tier_name=tier_name,
            fit_dir=args.fit_dir, measure=not args.no_measure,
            mesh_champions=not args.no_mesh_champions,
            bench_out=args.bench_out,
        )
        return 1 if result.get("error") else 0

    if args.cmd == "train":
        try:
            from dlbb_tpu.train.loop import run_train_from_config
        except ImportError:
            print("error: the train module is not available in this build")
            return 2

        result = run_train_from_config(
            args.config, zero1=args.zero1, zero_stage=args.zero_stage,
            output_dir=args.output, tp_overlap=args.tp_overlap,
            grad_compression=args.grad_compression,
        )
        if result.get("preempted") and "step_time" not in result:
            print(f"preempted at step {result['preempted_at_step']}; "
                  "checkpoint saved — resume to continue")
            return 0
        print(f"step mean {result['step_time']['mean'] * 1e3:.2f} ms")
        return 0

    return 2


if __name__ == "__main__":
    sys.exit(main())
