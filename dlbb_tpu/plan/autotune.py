"""cm2-driven parallelism-plan autotuner + fleet capacity planner.

The reference framework answers "which knob combination is fastest" by
brute-force sweep (oneCCL knob grids); we have two things the reference
never had — a *fitted* cost model (cm2, regression-gated by the
calibration baseline) and a static memory-feasibility term
(``hbm_headroom_bytes``) — so the sweep becomes the classic
predict-prune-measure autotuner loop:

1. **Enumerate** the full plan space for a ModelConfig + mesh:
   (dp, tp) factorizations x decode_horizon x inflight_window x
   prefill_chunk x compact_threshold for serving targets;
   (dp, sp, pp, tp) factorizations x tp_overlap x grad_compression x
   zero_stage x attention variant (ring/ulysses when sp > 1) for train
   targets.
2. **Prune** statically: every point that fails the repo's own
   ``validate_*`` contracts or whose analytic peak-bytes envelope has
   ``hbm_headroom_bytes < 0`` is dropped — *journaled with its reason*
   (``validation-reject`` / ``infeasible-hbm`` / ``cm2-fit-missing``),
   never silently.  A missing cm2 fit fails the whole search closed:
   ranking with the unfitted analytic seed would launder cm1 guesses as
   "model-picked".
3. **Rank** survivors by cm2-predicted per-token cost (serving) or step
   time (train), composed from the same fitted primitives the schedule
   auditor prices HLO with (``collective_cost_us`` / ``compute_cost_us``
   / ``dispatch_cost_us``).  Ties break toward the *simpler* plan
   (fewest engaged knobs), then lexically — deterministic by
   construction.
4. **Measure** the top-k (plus the default heuristic plan, always) with
   the real serving/train engines, and emit a model-picked vs
   measured-winner agreement table.

On top sits the fleet capacity planner (``cli plan --capacity``): a
``serve/traffic.py`` trace + SLO (``deadline_s``) is priced per
(plan, replica count) with cm2-predicted goodput/TTFT, validated by at
least one measured serving run per plotted plan, and published as a
"how many replicas of which plan serve N users within SLO" curve in
SERVING.md.

Simulated-mesh caveat (same as every measured corpus in this repo):
absolute times are host-core times, not ICI; the cm2 fit is a cpu-sim
fit, so predicted and measured live on the same tier and relative
ordering is the honest signal.  Chip rows stay ``pending_tunnel``.

Import contract: this module is importable without jax (like
``analysis/costmodel``) — the static half (enumerate / prune / rank /
agreement) runs anywhere; engine-backed measurement imports jax lazily.
"""

from __future__ import annotations

import math
from dataclasses import asdict, dataclass
from pathlib import Path
from typing import Any, Optional

from dlbb_tpu.analysis.costmodel import (
    DEFAULT_FIT_DIR,
    DEFAULT_TIER,
    CostTier,
    FitMissingError,
    collective_cost_us,
    compute_cost_us,
    dispatch_cost_us,
    hbm_headroom_bytes,
    load_fitted_tier,
)
from dlbb_tpu.models.configs import (
    ModelConfig,
    kv_cache_bytes_per_device,
    validate_attention_parallelism,
    validate_expert_parallelism,
    validate_tp_overlap,
)
from dlbb_tpu.obs.export import MetricsRegistry
from dlbb_tpu.resilience.journal import SweepJournal
from dlbb_tpu.utils.config import save_json

# pruning reasons — the journal/manifest vocabulary (satellite contract)
PRUNE_VALIDATION = "validation-reject"
PRUNE_HBM = "infeasible-hbm"
PRUNE_FIT = "cm2-fit-missing"
PRUNE_REASONS = (PRUNE_VALIDATION, PRUNE_HBM, PRUNE_FIT)

AUTOTUNE_SCHEMA = "dlbb_autotune_v1"
BENCH_SCHEMA = "dlbb_bench_autotune_v1"
CAPACITY_SCHEMA = "dlbb_capacity_v1"

_DTYPE_BYTES = {"bfloat16": 2, "float16": 2, "float32": 4}

# search-space axes (full grid — every point is either ranked or
# journaled with a prune reason; there is no silent cap anywhere)
SERVE_HORIZONS = (1, 2, 4, 8, 16)
SERVE_INFLIGHT = (1, 2)
TRAIN_OVERLAPS = ("off", "ring", "bidir")
TRAIN_COMPRESSIONS = ("none", "int8", "fp8")
TRAIN_ZERO_STAGES = (0, 1)
SP_ATTENTION_VARIANTS = ("ring", "ulysses")

# reference workload (mirrors serve/bench.py DEFAULT_SERVE_MODEL /
# the serving envelope defaults; kept literal here so the static half
# needs no jax-importing module)
DEFAULT_PLAN_MODEL: dict[str, Any] = {
    "hidden_size": 128, "num_layers": 4, "num_heads": 8,
    "num_kv_heads": 4, "ffn_intermediate": 256, "dtype": "float32",
    "attention": "full",
}
DEFAULT_PLAN_SERVING: dict[str, Any] = {
    "max_batch": 8, "max_seq": 256, "block_size": 16,
    "queue_capacity": 64,
}
DEFAULT_PLAN_INPUT: dict[str, Any] = {
    "batch_size": 8, "sequence_length": 64, "seed": 42,
}

# committed-calibration agreement grid: each family is a set of
# calibration targets measuring the same work under different plan
# knobs; per-entry divisor normalizes multi-step targets to per-step
# cost (decode_fused[k4] runs 4 decode steps per dispatch).  This is
# the pinned validation grid for the >=70% top-2 regression.
CAL_FAMILIES: dict[str, list[tuple[str, float]]] = {
    "ag_matmul_schedule": [
        ("comm/ops.py::ag_matmul[ring]", 1),
        ("comm/ops.py::ag_matmul[bidir]", 1),
        ("comm/ops.py::ag_matmul[fused]", 1),
    ],
    "matmul_rs_schedule": [
        ("comm/ops.py::matmul_rs[ring]", 1),
        ("comm/ops.py::matmul_rs[bidir]", 1),
        ("comm/ops.py::matmul_rs[fused]", 1),
    ],
    "allreduce_schedule": [
        ("comm/ops.py::allreduce", 1),
        ("comm/ops.py::allreduce_hierarchical", 1),
    ],
    "collective_compression": [
        ("comm/ops.py::allreduce", 1),
        ("comm/ops.py::allreduce_q[int8]", 1),
        ("comm/ops.py::allreduce_q[fp8]", 1),
    ],
    "tp_overlap_forward": [
        ("models/transformer.py::forward[dp,tp]", 1),
        ("models/transformer.py::forward[dp,tp,overlap=ring]", 1),
        ("models/transformer.py::forward[dp,tp,overlap=bidir]", 1),
    ],
    "context_parallel_forward": [
        ("models/transformer.py::forward[sp,ring]", 1),
        ("models/transformer.py::forward[sp,ulysses]", 1),
    ],
    "prefill_path": [
        ("serve/engine.py::prefill[dp,tp]", 1),
        ("serve/engine.py::prefill_chunk[dp,tp]", 1),
    ],
    "decode_path": [
        ("serve/engine.py::decode_step[dp,tp]", 1),
        ("serve/engine.py::decode_fused[k4,dp,tp]", 4),
    ],
    "zero_stage": [
        ("train/loop.py::train_step[zero0,dp]", 1),
        ("train/loop.py::train_step[zero1,dp]", 1),
    ],
    "grad_compression": [
        ("train/loop.py::train_step[zero0,dp]", 1),
        ("train/loop.py::train_step[ddp,compressed=int8]", 1),
    ],
}

DEFAULT_CAL_BASELINE = Path(
    "stats/analysis/calibration/calibration_baseline_cm2.json"
)


# ---------------------------------------------------------------------------
# plan points
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PlanPoint:
    """One point of the plan space — the knobs the search owns.

    ``target`` selects which axes are live: serving points use
    (dp, tp) + the decode fast-path knobs; train points use
    (dp, sp, pp, tp) + overlap/compression/zero + the attention
    variant (the per-op variant axis: ring vs ulysses when sp > 1).
    """

    target: str  # "serving" | "train"
    dp: int = 1
    tp: int = 1
    sp: int = 1
    pp: int = 1
    # train knobs
    tp_overlap: str = "off"
    grad_compression: str = "none"
    zero_stage: int = 0
    attention: Optional[str] = None  # per-op variant; None = model default
    # serving knobs
    decode_horizon: int = 1
    prefill_chunk: Optional[int] = None
    compact_threshold: Optional[float] = None
    inflight_window: int = 1

    def key(self) -> str:
        """Compact stable identifier (journal ``config`` field, report
        rows, tie-break of last resort)."""
        if self.target == "serving":
            parts = [f"dp{self.dp}", f"tp{self.tp}",
                     f"K{self.decode_horizon}", f"W{self.inflight_window}"]
            if self.prefill_chunk is not None:
                parts.append(f"chunk{self.prefill_chunk}")
            if self.compact_threshold is not None:
                parts.append(f"compact{self.compact_threshold:g}")
            return "serve[" + ",".join(parts) + "]"
        parts = [f"dp{self.dp}", f"tp{self.tp}", f"sp{self.sp}",
                 f"pp{self.pp}"]
        if self.tp_overlap != "off":
            parts.append(f"overlap={self.tp_overlap}")
        if self.grad_compression != "none":
            parts.append(f"comp={self.grad_compression}")
        if self.zero_stage:
            parts.append(f"zero{self.zero_stage}")
        if self.attention is not None:
            parts.append(f"attn={self.attention}")
        return "train[" + ",".join(parts) + "]"

    def complexity(self) -> int:
        """Number of engaged non-default knobs — the tie-break: when cm2
        cannot separate two plans, the simpler one wins."""
        n = 0
        if self.target == "serving":
            n += int(self.decode_horizon > 1)
            n += int(self.inflight_window > 1)
            n += int(self.prefill_chunk is not None)
            n += int(self.compact_threshold is not None)
        else:
            n += int(self.tp_overlap != "off")
            n += int(self.grad_compression != "none")
            n += int(self.zero_stage > 0)
            n += int(self.attention is not None)
            n += int(self.sp > 1) + int(self.pp > 1)
        return n

    def to_dict(self) -> dict[str, Any]:
        d = asdict(self)
        d["key"] = self.key()
        return d


def _factor_pairs(n: int) -> list[tuple[int, int]]:
    """All (a, b) with a * b == n."""
    return [(a, n // a) for a in range(1, n + 1) if n % a == 0]


def enumerate_serving_space(
    model_cfg: ModelConfig,
    n_devices: int,
    serving: dict[str, Any],
) -> list[PlanPoint]:
    """Full serving grid: every (dp, tp) factorization of the mesh x
    decode horizon x in-flight window x chunked prefill {off, 2 blocks}
    x slot compaction {off, 0.5}.  Infeasible combinations are NOT
    filtered here — pruning journals them with reasons."""
    block = int(serving.get("block_size", 16))
    pts = []
    for dp, tp in _factor_pairs(n_devices):
        for k in SERVE_HORIZONS:
            for w in SERVE_INFLIGHT:
                for chunk in (None, 2 * block):
                    for compact in (None, 0.5):
                        pts.append(PlanPoint(
                            target="serving", dp=dp, tp=tp,
                            decode_horizon=k, inflight_window=w,
                            prefill_chunk=chunk,
                            compact_threshold=compact,
                        ))
    return pts


def enumerate_train_space(
    model_cfg: ModelConfig,
    n_devices: int,
) -> list[PlanPoint]:
    """Full train grid: every ordered (dp, sp, pp, tp) factorization of
    the mesh x tp-overlap schedule x gradient compression x ZeRO stage,
    with the attention variant axis (ring / ulysses) enumerated whenever
    sp > 1 offers the choice (the per-op variant dimension)."""
    pts = []
    for dp in range(1, n_devices + 1):
        if n_devices % dp:
            continue
        rem = n_devices // dp
        for sp in range(1, rem + 1):
            if rem % sp:
                continue
            rem2 = rem // sp
            for pp, tp in _factor_pairs(rem2):
                attn_variants: tuple[Optional[str], ...] = (
                    SP_ATTENTION_VARIANTS if sp > 1 else (None,)
                )
                for attn in attn_variants:
                    for ov in TRAIN_OVERLAPS:
                        for comp in TRAIN_COMPRESSIONS:
                            for z in TRAIN_ZERO_STAGES:
                                pts.append(PlanPoint(
                                    target="train", dp=dp, sp=sp,
                                    pp=pp, tp=tp, tp_overlap=ov,
                                    grad_compression=comp,
                                    zero_stage=z, attention=attn,
                                ))
    return pts


def _point_model(point: PlanPoint, model_cfg: ModelConfig) -> ModelConfig:
    """The model under this point's per-op variant (attention mode)."""
    from dataclasses import replace

    if point.attention is not None \
            and point.attention != model_cfg.attention:
        return replace(model_cfg, attention=point.attention)
    return model_cfg


# ---------------------------------------------------------------------------
# static pruning
# ---------------------------------------------------------------------------


def _serving_peak_bytes(point: PlanPoint, model_cfg: ModelConfig,
                        serving: dict[str, Any]) -> int:
    """Analytic per-device peak-bytes envelope for a serving plan:
    tp-sharded weights + the engine's own KV accounting + a prefill
    activation envelope (2 live [B/dp, S, H] planes)."""
    from dlbb_tpu.models.transformer import num_parameters

    pbytes = _DTYPE_BYTES.get(model_cfg.dtype, 4)
    mb = int(serving["max_batch"])
    ms = int(serving["max_seq"])
    weights = num_parameters(model_cfg) * pbytes // max(point.tp, 1)
    kv = kv_cache_bytes_per_device(
        model_cfg, mb, ms, dp=point.dp, tp=point.tp,
        block_size=int(serving.get("block_size", 16)),
    )
    acts = 2 * (mb // max(point.dp, 1)) * ms \
        * model_cfg.hidden_size * pbytes
    return weights + kv + acts


def _train_peak_bytes(point: PlanPoint, model_cfg: ModelConfig,
                      input_cfg: dict[str, Any]) -> int:
    """Analytic per-device peak-bytes envelope for a train plan:
    weights + grads (model dtype, sharded over tp*pp), fp32 Adam
    moments (additionally sharded over dp under ZeRO>=1), and a
    2-plane activation envelope sharded over (dp, sp, pp)."""
    from dlbb_tpu.models.transformer import num_parameters

    pbytes = _DTYPE_BYTES.get(model_cfg.dtype, 4)
    params = num_parameters(model_cfg)
    shard = max(point.tp, 1) * max(point.pp, 1)
    w_g = 2 * params * pbytes // shard
    opt_shard = shard * (max(point.dp, 1) if point.zero_stage >= 1 else 1)
    opt = 8 * params // opt_shard
    b = int(input_cfg["batch_size"])
    s = int(input_cfg["sequence_length"])
    acts = (2 * b * s * model_cfg.hidden_size
            * model_cfg.num_layers * pbytes
            // (max(point.dp, 1) * max(point.sp, 1) * max(point.pp, 1)))
    return w_g + opt + acts


def prune_point(
    point: PlanPoint,
    model_cfg: ModelConfig,
    tier: CostTier,
    n_devices: int,
    serving: Optional[dict[str, Any]] = None,
    input_cfg: Optional[dict[str, Any]] = None,
) -> Optional[tuple[str, str]]:
    """Static feasibility check; ``None`` for a survivor, otherwise
    ``(reason, detail)`` with reason in :data:`PRUNE_REASONS`.

    Serving points run the engine's own ``ServingConfig.validate``
    contract (the very checks the real build would raise); train points
    run the shared ``validate_*`` family.  Either way a rejection quotes
    the contract's message — the journal stays actionable."""
    model_pt = _point_model(point, model_cfg)
    needed = point.dp * point.tp * point.sp * point.pp
    if needed > n_devices:
        return (PRUNE_VALIDATION,
                f"plan needs {needed} devices, mesh has {n_devices}")
    try:
        if point.target == "serving":
            serving = serving or DEFAULT_PLAN_SERVING
            from dlbb_tpu.serve.engine import ServingConfig

            cfg = ServingConfig.from_dict({
                **serving,
                "decode_horizon": point.decode_horizon,
                "inflight_window": point.inflight_window,
                "prefill_chunk": point.prefill_chunk,
                "compact_threshold": point.compact_threshold,
            })
            cfg.validate(model_pt, dp=point.dp, tp=point.tp)
        else:
            input_cfg = input_cfg or DEFAULT_PLAN_INPUT
            validate_attention_parallelism(model_pt, point.sp)
            validate_expert_parallelism(model_pt, 1)
            validate_tp_overlap(
                model_pt if point.tp_overlap == "off"
                else _with_overlap(model_pt, point.tp_overlap),
                point.tp, pp=point.pp,
                seq_len=int(input_cfg["sequence_length"]), sp=point.sp,
            )
            if point.pp > 1:
                from dlbb_tpu.parallel.pipeline import validate_pipeline

                validate_pipeline(model_pt, point.pp,
                                  int(input_cfg["batch_size"]), None)
            if int(input_cfg["batch_size"]) % (point.dp * point.sp):
                raise ValueError(
                    f"batch_size={input_cfg['batch_size']} not divisible "
                    f"by dp*sp={point.dp * point.sp}"
                )
            if int(input_cfg["sequence_length"]) % point.sp:
                raise ValueError(
                    f"sequence_length={input_cfg['sequence_length']} not "
                    f"divisible by sp={point.sp}"
                )
    except ValueError as e:
        return (PRUNE_VALIDATION, str(e))

    if point.target == "serving":
        peak = _serving_peak_bytes(point, model_pt,
                                   serving or DEFAULT_PLAN_SERVING)
    else:
        peak = _train_peak_bytes(point, model_pt,
                                 input_cfg or DEFAULT_PLAN_INPUT)
    headroom = hbm_headroom_bytes(peak, tier)
    if headroom is not None and headroom < 0:
        return (PRUNE_HBM,
                f"peak {peak} B exceeds tier hbm {tier.hbm_bytes} B "
                f"(headroom {headroom} B)")
    return None


def _with_overlap(model_cfg: ModelConfig, overlap: str) -> ModelConfig:
    from dataclasses import replace

    return replace(model_cfg, tp_overlap=overlap)


# ---------------------------------------------------------------------------
# cm2 prediction
# ---------------------------------------------------------------------------


def _compute_shard(point: PlanPoint, tier: CostTier) -> float:
    """Effective compute-sharding divisor for this tier.

    On a real chip mesh, per-device FLOPs divide by the mesh extent.  On
    the CPU-simulated tiers (``*sim*``) the "devices" are serialized on
    the host — sharding moves work between fake devices without removing
    any of it from the wall clock, so the honest divisor is 1 (the same
    host-core caveat every measured corpus in this repo carries; the cm2
    peak was fitted against exactly such host-serial programs)."""
    if "sim" in tier.name:
        return 1.0
    return float(point.dp * point.tp * point.sp * point.pp)


def predict_serving_per_token_us(
    point: PlanPoint,
    model_cfg: ModelConfig,
    serving: dict[str, Any],
    tier: CostTier,
) -> dict[str, float]:
    """cm2-predicted steady-state decode cost per generated token.

    Composed from the fitted primitives, mirroring how the schedule
    auditor prices compiled programs: one decode step moves the full
    batch one token — per-device compute (QKV/out/FFN at S=1 plus the
    KV-context attention reads at the half-full envelope), 2 tp
    collectives per layer when tp > 1, and the fitted dispatch overhead
    amortized over the fused horizon K and the in-flight window W (the
    two knobs whose entire purpose is to shrink the gamma term)."""
    from dlbb_tpu.models.transformer import forward_flops

    pbytes = _DTYPE_BYTES.get(model_cfg.dtype, 4)
    b = int(serving["max_batch"])
    ms = int(serving["max_seq"])
    h, nl = model_cfg.hidden_size, model_cfg.num_layers
    flops = forward_flops(model_cfg, b, 1) + 4 * b * (ms // 2) * h * nl
    compute = compute_cost_us(flops / _compute_shard(point, tier), tier)
    comm = 0.0
    if point.tp > 1:
        msg = (b // max(point.dp, 1)) * h * pbytes
        wire = 2 * (point.tp - 1) / point.tp * msg
        comm = 2 * nl * collective_cost_us(wire, tier)
    disp = dispatch_cost_us(1, tier) / (
        point.decode_horizon * point.inflight_window
    )
    step = compute + comm + disp
    return {
        "cost_us": step / b,
        "step_us": step,
        "compute_us": compute,
        "comm_us": comm,
        "dispatch_us": disp,
    }


def predict_ttft_us(
    point: PlanPoint,
    model_cfg: ModelConfig,
    serving: dict[str, Any],
    tier: CostTier,
    prompt_len: int,
) -> float:
    """cm2-predicted prefill latency for one request (queueing excluded:
    this is the unloaded-floor TTFT the capacity planner compares to the
    SLO).  A single request shards over tp only; chunked prefill pays
    one dispatch per chunk."""
    from dlbb_tpu.models.transformer import forward_flops

    pbytes = _DTYPE_BYTES.get(model_cfg.dtype, 4)
    h, nl = model_cfg.hidden_size, model_cfg.num_layers
    flops = forward_flops(model_cfg, 1, prompt_len)
    # one request shards over tp only (dp is a batch axis) — and over
    # nothing at all on the host-serial sim tiers (see _compute_shard)
    tp_div = 1.0 if "sim" in tier.name else float(max(point.tp, 1))
    compute = compute_cost_us(flops / tp_div, tier)
    comm = 0.0
    if point.tp > 1:
        wire = 2 * (point.tp - 1) / point.tp * prompt_len * h * pbytes
        comm = 2 * nl * collective_cost_us(wire, tier)
    chunks = 1
    if point.prefill_chunk:
        chunks = max(1, math.ceil(prompt_len / point.prefill_chunk))
    return compute + comm + dispatch_cost_us(chunks, tier)


def predict_train_step_us(
    point: PlanPoint,
    model_cfg: ModelConfig,
    input_cfg: dict[str, Any],
    tier: CostTier,
) -> dict[str, float]:
    """cm2-predicted training step time: 3x-forward compute sharded over
    the full mesh, tp collectives (4 per layer fwd+bwd), sp attention
    exchange (ring: sp-1 staged sends; ulysses: 2 all-to-alls), the dp
    gradient allreduce (compression shrinks wire bytes to 1 B/elem but
    pays quant/dequant compute + 2 dispatches), the ZeRO-1
    reduce-scatter/allgather split, the pipeline bubble, and the
    decomposed-overlap dispatch penalty (on the host-serial simulated
    mesh the ring/bidir schedules ADD chunk dispatches without hiding
    comm — exactly what the calibration baseline measured)."""
    from dlbb_tpu.models.transformer import forward_flops, num_parameters

    pbytes = _DTYPE_BYTES.get(model_cfg.dtype, 4)
    b = int(input_cfg["batch_size"])
    s = int(input_cfg["sequence_length"])
    h, nl = model_cfg.hidden_size, model_cfg.num_layers
    params = num_parameters(model_cfg)
    shard = _compute_shard(point, tier)
    compute = compute_cost_us(3 * forward_flops(model_cfg, b, s) / shard,
                              tier)
    if point.pp > 1:
        m = point.pp  # validate_pipeline default: one microbatch/stage
        compute *= (m + point.pp - 1) / m
    comm = 0.0
    disp = dispatch_cost_us(1, tier)
    if point.tp > 1:
        msg = b * s * h * pbytes / (point.dp * point.sp)
        wire = 2 * (point.tp - 1) / point.tp * msg
        comm += 4 * nl * collective_cost_us(wire, tier)
        if point.tp_overlap == "ring":
            disp += 2 * nl * dispatch_cost_us(point.tp - 1, tier)
        elif point.tp_overlap == "bidir":
            disp += 2 * nl * dispatch_cost_us(max(point.tp // 2, 1), tier)
    if point.sp > 1:
        msg = b * s * h * pbytes / (point.dp * point.sp)
        if point.attention == "ulysses":
            comm += 2 * nl * collective_cost_us(msg, tier)
        else:  # ring
            comm += nl * (point.sp - 1) * collective_cost_us(
                msg / point.sp, tier)
    if point.dp > 1:
        grad_bytes = params * pbytes / (point.tp * point.pp)
        if point.grad_compression != "none":
            grad_bytes /= pbytes  # 1 byte/elem on the wire
            compute += compute_cost_us(
                4 * params / (point.tp * point.pp), tier)
            disp += dispatch_cost_us(2, tier)
        wire = 2 * (point.dp - 1) / point.dp * grad_bytes
        comm += collective_cost_us(wire, tier)
        if point.zero_stage >= 1:
            disp += dispatch_cost_us(1, tier)
    if point.pp > 1:
        disp += dispatch_cost_us(2 * point.pp * point.pp, tier)
    step = compute + comm + disp
    return {
        "cost_us": step,
        "compute_us": compute,
        "comm_us": comm,
        "dispatch_us": disp,
    }


def predict_point_us(
    point: PlanPoint,
    model_cfg: ModelConfig,
    tier: CostTier,
    serving: Optional[dict[str, Any]] = None,
    input_cfg: Optional[dict[str, Any]] = None,
) -> dict[str, float]:
    """Dispatch to the target's predictor; ``cost_us`` is the ranking
    scalar (per-token for serving, per-step for train)."""
    model_pt = _point_model(point, model_cfg)
    if point.target == "serving":
        return predict_serving_per_token_us(
            point, model_pt, serving or DEFAULT_PLAN_SERVING, tier)
    return predict_train_step_us(
        point, model_pt, input_cfg or DEFAULT_PLAN_INPUT, tier)


def rank_points(
    scored: list[tuple[PlanPoint, dict[str, float]]],
) -> list[tuple[PlanPoint, dict[str, float]]]:
    """Deterministic ranking: predicted cost (rounded to ns so fp noise
    cannot reorder), then plan complexity (simpler wins a tie), then the
    lexical key (total order of last resort)."""
    return sorted(
        scored,
        key=lambda pc: (round(pc[1]["cost_us"], 3),
                        pc[0].complexity(), pc[0].key()),
    )


def heuristic_point(
    target: str,
    n_devices: int,
    model_cfg: ModelConfig,
    serving: Optional[dict[str, Any]] = None,
) -> PlanPoint:
    """The default-heuristic plan the search must beat: what the serving
    CLI picks with no flags (``default_parallelism`` + every fast-path
    knob off), or plain DDP for train."""
    if target == "serving":
        serving = serving or DEFAULT_PLAN_SERVING
        from dlbb_tpu.serve.bench import default_parallelism

        dp, tp = default_parallelism(n_devices, model_cfg.kv_heads,
                                     int(serving["max_batch"]))
        return PlanPoint(target="serving", dp=dp, tp=tp)
    return PlanPoint(target="train", dp=n_devices)


# ---------------------------------------------------------------------------
# measurement
# ---------------------------------------------------------------------------


def _measure_serving(
    point: PlanPoint,
    model_dict: dict[str, Any],
    serving: dict[str, Any],
    trace: Any,
    out_dir: Path,
    devices: Optional[Any] = None,
) -> dict[str, Any]:
    """One real serving run for this plan on the shared seeded trace."""
    from dlbb_tpu.serve.bench import run_serving

    config = {
        "model": dict(model_dict),
        "serving": {
            **serving,
            "decode_horizon": point.decode_horizon,
            "inflight_window": point.inflight_window,
            "prefill_chunk": point.prefill_chunk,
            "compact_threshold": point.compact_threshold,
        },
        "parallelism": {"world_size": point.tp,
                        "data_parallel": point.dp},
    }
    report = run_serving(config, trace, output_dir=str(out_dir),
                         devices=devices, verbose=False)
    return {
        "goodput_tokens_per_s": report["goodput_tokens_per_s"],
        "throughput_tokens_per_s": report["throughput_tokens_per_s"],
        "ttft_p50_s": report["ttft"]["median"],
        "completed": report["requests"]["completed"],
        "total": report["requests"]["arrived"],
    }


def _measure_train(
    point: PlanPoint,
    model_dict: dict[str, Any],
    input_cfg: dict[str, Any],
    out_dir: Path,
    devices: Optional[Any] = None,
    iterations: int = 4,
) -> dict[str, Any]:
    """One real training run for this plan (short measured window)."""
    from dlbb_tpu.train.loop import run_train

    model = dict(model_dict)
    if point.tp_overlap != "off":
        model["tp_overlap"] = point.tp_overlap
    if point.attention is not None:
        model["attention"] = point.attention
    config = {
        "experiment": {"name": f"autotune_{point.key()}"},
        "model": model,
        "parallelism": {
            "world_size": point.tp, "data_parallel": point.dp,
            "sequence_parallel": point.sp,
            "pipeline_parallel": point.pp,
        },
        "input": dict(input_cfg),
        "training": {"grad_compression": point.grad_compression,
                     "zero_stage": point.zero_stage},
        "execution": {"warmup_iterations": 1,
                      "benchmark_iterations": iterations},
    }
    report = run_train(config, devices=devices,
                       output_dir=str(out_dir), verbose=False)
    return {
        "step_time_mean_s": report["step_time"]["mean"],
        "tokens_per_second": report["tokens_per_second"],
    }


# ---------------------------------------------------------------------------
# agreement
# ---------------------------------------------------------------------------


def calibration_agreement(
    baseline_path: "str | Path" = DEFAULT_CAL_BASELINE,
    families: Optional[dict[str, list[tuple[str, float]]]] = None,
) -> dict[str, Any]:
    """Model-picked vs measured-winner agreement over the committed
    calibration grid: for each family, does the cm2 top-2 (by predicted
    cost) contain the measured winner?  Families with members missing
    from the baseline are reported with status ``missing-target`` and
    excluded from the ratio denominator — visibly, never silently."""
    import json

    families = families or CAL_FAMILIES
    path = Path(baseline_path)
    if not path.exists():
        return {"ratio": None, "families": [],
                "error": f"calibration baseline not found: {path}"}
    data = json.loads(path.read_text())
    by_target = {t["target"]: t for t in data.get("targets", [])}
    rows: list[dict[str, Any]] = []
    agree = total = 0
    for fam, members in families.items():
        entries = []
        missing = [name for name, _ in members if name not in by_target]
        if missing:
            rows.append({"family": fam, "status": "missing-target",
                         "missing": missing})
            continue
        for name, div in members:
            t = by_target[name]
            entries.append({
                "member": name,
                "predicted_us": t["predicted_us"] / div,
                "measured_us": t["measured_us"] / div,
            })
        pred_order = sorted(entries, key=lambda e: e["predicted_us"])
        meas_winner = min(entries, key=lambda e: e["measured_us"])
        top2 = [e["member"] for e in pred_order[:2]]
        ok = meas_winner["member"] in top2
        agree += int(ok)
        total += 1
        rows.append({
            "family": fam, "status": "ok",
            "predicted_order": [e["member"] for e in pred_order],
            "measured_winner": meas_winner["member"],
            "top2_contains_winner": ok,
            "members": entries,
        })
    return {
        "ratio": (agree / total) if total else None,
        "agree": agree, "total": total,
        "families": rows,
        "baseline": str(path),
    }


def _live_agreement(
    measured: list[dict[str, Any]],
    metric: str,
    higher_is_better: bool,
) -> dict[str, Any]:
    """Agreement over the points actually measured this run: ranks by
    cm2 prediction vs ranks by measurement, and whether the measured
    winner sits in the predicted top-2."""
    if not measured:
        return {"rows": [], "top1_match": None, "top2_contains": None}
    by_pred = sorted(measured, key=lambda r: r["predicted_us"])
    by_meas = sorted(measured, key=lambda r: r[metric],
                     reverse=higher_is_better)
    pred_rank = {r["plan"]: i + 1 for i, r in enumerate(by_pred)}
    meas_rank = {r["plan"]: i + 1 for i, r in enumerate(by_meas)}
    rows = []
    for r in measured:
        rows.append({**r, "predicted_rank": pred_rank[r["plan"]],
                     "measured_rank": meas_rank[r["plan"]]})
    winner = by_meas[0]["plan"]
    top2 = [r["plan"] for r in by_pred[:2]]
    return {
        "rows": sorted(rows, key=lambda r: r["measured_rank"]),
        "measured_winner": winner,
        "predicted_winner": by_pred[0]["plan"],
        "top1_match": winner == by_pred[0]["plan"],
        "top2_contains": winner in top2,
    }


# ---------------------------------------------------------------------------
# the search driver
# ---------------------------------------------------------------------------


def run_plan_search(
    target: str = "serving",
    n_devices: int = 8,
    model: Optional[dict[str, Any]] = None,
    serving: Optional[dict[str, Any]] = None,
    input_cfg: Optional[dict[str, Any]] = None,
    top_k: int = 2,
    output_dir: "str | Path" = "results/autotune",
    trace: str = "poisson",
    num_requests: int = 24,
    seed: int = 42,
    rate: Optional[float] = None,
    trace_params: Optional[dict[str, Any]] = None,
    tier_name: str = DEFAULT_TIER,
    fit_dir: "Optional[str | Path]" = None,
    fit_version: Optional[int] = None,
    measure: bool = True,
    mesh_champions: bool = True,
    devices: Optional[Any] = None,
    verbose: bool = True,
    bench_out: "Optional[str | Path]" = None,
    cal_baseline: "str | Path" = DEFAULT_CAL_BASELINE,
) -> dict[str, Any]:
    """The predict-prune-measure loop.  Returns the full report dict and
    writes ``autotune_report.json`` + journal + ``sweep_manifest.json``
    + ``metrics.prom`` under ``output_dir`` (and ``BENCH_autotune.json``
    when ``bench_out`` is set)."""
    if target not in ("serving", "train"):
        raise ValueError(f"unknown plan target {target!r} "
                         "(expected 'serving' or 'train')")
    out = Path(output_dir)
    out.mkdir(parents=True, exist_ok=True)
    model_dict = {**DEFAULT_PLAN_MODEL, **(model or {})}
    serving_env = {**DEFAULT_PLAN_SERVING, **(serving or {})}
    input_env = {**DEFAULT_PLAN_INPUT, **(input_cfg or {})}
    model_cfg = ModelConfig.from_dict(model_dict)

    journal = SweepJournal(out, meta={"mode": "plan-auto",
                                      "target": target,
                                      "devices": n_devices})
    registry = MetricsRegistry()
    counts = registry.labeled_counter(
        "plan_search_points", "outcome",
        initial=("searched", "measured")
        + tuple(f"pruned-{r}" for r in PRUNE_REASONS),
        help="autotuner plan-space accounting by outcome",
    )

    if target == "serving":
        points = enumerate_serving_space(model_cfg, n_devices, serving_env)
    else:
        points = enumerate_train_space(model_cfg, n_devices)
    counts["searched"] += len(points)

    def _finish(payload: dict[str, Any]) -> dict[str, Any]:
        cal = payload.get("calibration_agreement") or {}
        if cal.get("ratio") is not None:
            registry.set_gauge(
                "plan_agreement_ratio", cal["ratio"],
                help="cm2 top-2 contains measured winner (fraction)",
                scope="calibration-grid",
            )
        live = payload.get("agreement") or {}
        if live.get("top2_contains") is not None:
            registry.set_gauge(
                "plan_agreement_ratio",
                1.0 if live["top2_contains"] else 0.0,
                help="cm2 top-2 contains measured winner (fraction)",
                scope="measured-topk",
            )
        registry.write_textfile(out / "metrics.prom")
        from dlbb_tpu.bench.schedule import write_sweep_manifest

        write_sweep_manifest(out, {
            "mode": "plan-auto",
            "target": target,
            "devices": n_devices,
            "searched": counts["searched"],
            "pruned": {r: counts[f"pruned-{r}"] for r in PRUNE_REASONS},
            "measured": counts["measured"],
            "winner": payload.get("winner"),
            "speedup_vs_default": payload.get("speedup_vs_default"),
            "agreement": {
                "calibration_ratio": cal.get("ratio"),
                "measured_top2_contains": live.get("top2_contains"),
            },
        })
        journal.event("sweep-complete",
                      searched=counts["searched"],
                      measured=counts["measured"])
        journal.close()
        save_json(payload, out / "autotune_report.json")
        return payload

    # cm2 is the ranking model or there is no ranking: a missing fit
    # journals EVERY point and fails the search closed (ranking with the
    # cm1 analytic seed would launder guesses as "model-picked")
    try:
        tier = load_fitted_tier(tier_name, fit_dir or DEFAULT_FIT_DIR,
                                fit_version)
    except FitMissingError as e:
        for p in points:
            counts[f"pruned-{PRUNE_FIT}"] += 1
            journal.event("plan-pruned", config=p.key(),
                          reason=PRUNE_FIT, detail=str(e))
        if verbose:
            print(f"plan --auto: {len(points)} points pruned "
                  f"({PRUNE_FIT}): {e}")
        return _finish({
            "schema": AUTOTUNE_SCHEMA, "target": target,
            "error": f"{PRUNE_FIT}: {e}",
            "searched": len(points), "ranked": [], "measured": [],
            "calibration_agreement": None,
        })

    survivors: list[tuple[PlanPoint, dict[str, float]]] = []
    pruned_rows: list[dict[str, Any]] = []
    for p in points:
        res = prune_point(p, model_cfg, tier, n_devices,
                          serving=serving_env, input_cfg=input_env)
        if res is not None:
            reason, detail = res
            counts[f"pruned-{reason}"] += 1
            journal.event("plan-pruned", config=p.key(),
                          reason=reason, detail=detail)
            pruned_rows.append({"plan": p.key(), "reason": reason,
                                "detail": detail})
            continue
        survivors.append((p, predict_point_us(
            p, model_cfg, tier, serving=serving_env,
            input_cfg=input_env)))

    ranked = rank_points(survivors)
    for i, (p, pred) in enumerate(ranked):
        journal.event("plan-ranked", config=p.key(), rank=i + 1,
                      predicted_us=round(pred["cost_us"], 3))
    if verbose:
        kept = len(ranked)
        print(f"plan --auto [{target}]: {len(points)} searched, "
              f"{len(points) - kept} pruned, {kept} ranked by cm2 "
              f"(tier {tier.name}, fit v{tier.fit.get('fit_version')})")
        for i, (p, pred) in enumerate(ranked[:5]):
            print(f"  #{i + 1} {p.key()}  predicted "
                  f"{pred['cost_us']:.1f} us")

    default_pt = heuristic_point(target, n_devices, model_cfg,
                                 serving_env)
    to_measure: list[tuple[PlanPoint, dict[str, float], str]] = [
        (p, pred, "top-k") for p, pred in ranked[:top_k]
    ]
    # stratified validation: also measure the predicted-best plan of
    # every surviving mesh factorization — cm2 cannot price the sim
    # host's per-shard scheduling effects, and a mesh the model
    # mis-ranks would otherwise never reach the agreement table (the
    # predicted-vs-measured disagreement is the product, not a failure)
    seen = {p.key() for p, _, _ in to_measure}
    if mesh_champions:
        champs: dict[tuple[int, int, int, int],
                     tuple[PlanPoint, dict]] = {}
        for p, pred in ranked:
            champs.setdefault((p.dp, p.tp, p.sp, p.pp), (p, pred))
        for p, pred in champs.values():
            if p.key() not in seen:
                seen.add(p.key())
                to_measure.append((p, pred, "mesh-champion"))
    if default_pt.key() not in seen:
        default_pred = predict_point_us(
            default_pt, model_cfg, tier, serving=serving_env,
            input_cfg=input_env)
        to_measure.append((default_pt, default_pred, "default-heuristic"))

    measured_rows: list[dict[str, Any]] = []
    if measure and to_measure:
        shared_trace = None
        if target == "serving":
            from dlbb_tpu.serve.bench import resolve_trace

            shared_trace = resolve_trace(
                trace, num_requests=num_requests, seed=seed, rate=rate,
                **(trace_params or {}),
            )
        for p, pred, role in to_measure:
            slug = p.key().replace("[", "_").replace("]", "") \
                .replace(",", "_").replace("=", "")
            mdir = out / "measure" / slug
            if target == "serving":
                m = _measure_serving(p, model_dict, serving_env,
                                     shared_trace, mdir, devices=devices)
            else:
                m = _measure_train(p, model_dict, input_env, mdir,
                                   devices=devices)
            counts["measured"] += 1
            row = {"plan": p.key(), "role": role,
                   "predicted_us": round(pred["cost_us"], 3), **m}
            journal.event("plan-measured", config=p.key(), **m)
            measured_rows.append(row)
            if verbose:
                metric = ("goodput_tokens_per_s" if target == "serving"
                          else "tokens_per_second")
                print(f"  measured {p.key()} ({role}): "
                      f"{row[metric]:.0f} tok/s")

    metric = ("goodput_tokens_per_s" if target == "serving"
              else "tokens_per_second")
    agreement = _live_agreement(measured_rows, metric,
                                higher_is_better=True)
    winner = agreement.get("measured_winner")
    speedup = None
    default_row = next((r for r in measured_rows
                        if r["plan"] == default_pt.key()), None)
    winner_row = next((r for r in measured_rows if r["plan"] == winner),
                      None)
    if default_row and winner_row and default_row[metric] > 0:
        speedup = winner_row[metric] / default_row[metric]

    cal = calibration_agreement(cal_baseline)
    payload = {
        "schema": AUTOTUNE_SCHEMA,
        "target": target,
        "devices": n_devices,
        "model": model_dict,
        "serving": serving_env if target == "serving" else None,
        "input": input_env if target == "train" else None,
        "tier": {"name": tier.name, "version": tier.version,
                 "fit": tier.fit},
        "searched": len(points),
        "pruned": {r: counts[f"pruned-{r}"] for r in PRUNE_REASONS},
        "pruned_points": pruned_rows,
        "ranked": [
            {"rank": i + 1, "plan": p.key(),
             "predicted_us": round(pred["cost_us"], 3),
             "complexity": p.complexity(), **p.to_dict()}
            for i, (p, pred) in enumerate(ranked)
        ],
        "measured": measured_rows,
        "winner": winner,
        "default_plan": default_pt.key(),
        "speedup_vs_default": speedup,
        "agreement": agreement,
        "calibration_agreement": cal,
        "trace": {"kind": trace, "num_requests": num_requests,
                  "seed": seed, "rate": rate,
                  "params": trace_params or {}}
        if target == "serving" else None,
    }
    if verbose and speedup is not None:
        print(f"plan --auto: measured winner {winner} = "
              f"{speedup:.2f}x the default heuristic "
              f"({default_pt.key()})")
    result = _finish(payload)
    if bench_out is not None:
        _write_bench(result, Path(bench_out))
        if verbose:
            print(f"bench artifact -> {bench_out}")
    return result


def _write_bench(report: dict[str, Any], path: Path) -> Path:
    """The committed repo-root bench artifact (``cli reports`` input)."""
    payload = {
        "harness": "dlbb_tpu/plan/autotune.py",
        "schema": BENCH_SCHEMA,
        "backend": "cpu",
        "methodology": (
            "full plan-space enumeration, static validate_*/HBM pruning "
            "(every pruned point journaled with reason), cm2-predicted "
            "ranking, top-k + default-heuristic measured through the "
            "real engines on one shared seeded trace"
        ),
        **{k: report[k] for k in (
            "target", "devices", "model", "serving", "input", "tier",
            "searched", "pruned", "ranked", "measured", "winner",
            "default_plan", "speedup_vs_default", "agreement",
            "calibration_agreement", "trace",
        ) if k in report},
        "chip": {
            "status": "pending_tunnel",
            "note": ("chip rows keyed for the next healthy tunnel "
                     "window: DLBB_TPU_TESTS=1 python -m dlbb_tpu.cli "
                     "plan --auto"),
        },
    }
    return save_json(payload, path)


# ---------------------------------------------------------------------------
# fleet capacity planner
# ---------------------------------------------------------------------------


def run_capacity_plan(
    n_devices: int = 8,
    plans: Optional[list[PlanPoint]] = None,
    slo: float = 30.0,
    users: tuple[int, ...] = (4, 8, 16, 32, 64),
    user_rate: float = 0.2,
    trace: str = "poisson",
    num_requests: int = 24,
    seed: int = 42,
    rate: Optional[float] = None,
    trace_params: Optional[dict[str, Any]] = None,
    model: Optional[dict[str, Any]] = None,
    serving: Optional[dict[str, Any]] = None,
    output_dir: "str | Path" = "results/capacity",
    tier_name: str = DEFAULT_TIER,
    fit_dir: "Optional[str | Path]" = None,
    devices: Optional[Any] = None,
    verbose: bool = True,
) -> dict[str, Any]:
    """Fleet capacity planning over a traffic trace + SLO.

    Per (plan, replica count): cm2-predicted goodput (1e6 /
    per-token-us per replica) and unloaded-floor TTFT, validated by one
    *measured* serving run per plotted plan (the trace carries
    ``deadline_s`` = SLO so shed/late requests are the engine's own
    accounting).  A "user" is a request stream issuing ``user_rate``
    req/s; serving N users within SLO needs
    ``ceil(N * user_rate * mean_output_tokens / per-replica goodput)``
    replicas, provided the plan's measured TTFT p50 fits the SLO.
    Replica scaling is linear extrapolation (replicas are independent
    engines behind a round-robin splitter) — stated, not hidden.  That
    assumption is now the literal runtime architecture: ``cli serve
    --replicas N`` runs the counted replicas as independent failure
    domains under ``serve/fleet.py``'s supervisor (least-loaded
    admission, failover re-prefill — docs/fleet.md), and
    ``BENCH_fleet.json`` prices what a replica death costs the curve."""
    out = Path(output_dir)
    out.mkdir(parents=True, exist_ok=True)
    model_dict = {**DEFAULT_PLAN_MODEL, **(model or {})}
    serving_env = {**DEFAULT_PLAN_SERVING, **(serving or {})}
    model_cfg = ModelConfig.from_dict(model_dict)
    tier = load_fitted_tier(tier_name, fit_dir or DEFAULT_FIT_DIR)

    journal = SweepJournal(out, meta={"mode": "plan-capacity",
                                      "devices": n_devices,
                                      "slo_s": slo})

    if plans is None:
        # default fleet candidates: the no-flags heuristic plan + the
        # cm2-ranked winner of a fresh static search (measure=False —
        # the capacity run itself is the measurement)
        static = run_plan_search(
            target="serving", n_devices=n_devices, model=model,
            serving=serving, measure=False, verbose=False,
            output_dir=out / "static_search", tier_name=tier_name,
            fit_dir=fit_dir,
        )
        plans = [heuristic_point("serving", n_devices, model_cfg,
                                 serving_env)]
        ranked = static.get("ranked", [])
        if ranked:
            best = ranked[0]
            pt = PlanPoint(**{
                k: best[k] for k in (
                    "target", "dp", "tp", "sp", "pp", "tp_overlap",
                    "grad_compression", "zero_stage", "attention",
                    "decode_horizon", "prefill_chunk",
                    "compact_threshold", "inflight_window")
            })
            if pt.key() not in {p.key() for p in plans}:
                plans.append(pt)

    from dlbb_tpu.serve.bench import resolve_trace

    shared_trace = resolve_trace(
        trace, num_requests=num_requests, seed=seed, rate=rate,
        deadline_s=slo, **(trace_params or {}),
    )
    prompt_mean = int(round(
        sum(r.prompt_len for r in shared_trace.requests)
        / max(len(shared_trace.requests), 1)))
    output_mean = (sum(r.output_len for r in shared_trace.requests)
                   / max(len(shared_trace.requests), 1))

    plan_rows: list[dict[str, Any]] = []
    for p in plans:
        pred = predict_serving_per_token_us(
            p, _point_model(p, model_cfg), serving_env, tier)
        goodput_pred = 1e6 / pred["cost_us"]
        ttft_pred_s = predict_ttft_us(
            p, _point_model(p, model_cfg), serving_env, tier,
            prompt_mean) / 1e6
        slug = p.key().replace("[", "_").replace("]", "") \
            .replace(",", "_")
        m = _measure_serving(p, model_dict, serving_env, shared_trace,
                             out / "measure" / slug, devices=devices)
        journal.event("capacity-measured", config=p.key(), **m)
        row = {
            "plan": p.key(),
            "point": p.to_dict(),
            "predicted_goodput_tokens_per_s": round(goodput_pred, 1),
            "predicted_ttft_s": round(ttft_pred_s, 6),
            "measured_goodput_tokens_per_s":
                round(m["goodput_tokens_per_s"], 1),
            "measured_ttft_p50_s": round(m["ttft_p50_s"], 6),
            "completed": m["completed"], "total": m["total"],
            "slo_attainable": m["ttft_p50_s"] <= slo,
            "curve": [],
        }
        for n in users:
            demand = n * user_rate * output_mean  # tokens/s
            def _replicas(goodput: float, ttft: float) -> Optional[int]:
                if goodput <= 0 or ttft > slo:
                    return None  # no replica count rescues a blown TTFT
                return max(1, math.ceil(demand / goodput))
            row["curve"].append({
                "users": n,
                "demand_tokens_per_s": round(demand, 1),
                "replicas_predicted": _replicas(goodput_pred,
                                                ttft_pred_s),
                "replicas_measured": _replicas(
                    m["goodput_tokens_per_s"], m["ttft_p50_s"]),
            })
        plan_rows.append(row)
        if verbose:
            print(f"capacity {p.key()}: predicted "
                  f"{goodput_pred:.0f} tok/s, measured "
                  f"{m['goodput_tokens_per_s']:.0f} tok/s, "
                  f"ttft p50 {m['ttft_p50_s'] * 1e3:.1f} ms "
                  f"(SLO {slo:g} s)")

    report = {
        "schema": CAPACITY_SCHEMA,
        "devices": n_devices,
        "model": model_dict,
        "serving": serving_env,
        "slo_s": slo,
        "user_rate_req_per_s": user_rate,
        "mean_prompt_tokens": prompt_mean,
        "mean_output_tokens": round(output_mean, 1),
        "trace": {"kind": trace, "num_requests": num_requests,
                  "seed": seed, "rate": rate, "deadline_s": slo,
                  "params": trace_params or {}},
        "tier": {"name": tier.name, "version": tier.version,
                 "fit": tier.fit},
        "plans": plan_rows,
        "replica_model": ("linear extrapolation: replicas are "
                          "independent engines behind round-robin "
                          "admission; one measured run per plan "
                          "anchors the per-replica numbers"),
    }
    save_json(report, out / "capacity_report.json")
    journal.event("sweep-complete", plans=len(plan_rows))
    journal.close()

    # publish the curve into the serving report tree (SERVING.md)
    from dlbb_tpu.stats.serving_report import publish_capacity_curve

    md = publish_capacity_curve(report)
    if verbose:
        print(f"capacity report -> {out / 'capacity_report.json'}; "
              f"curve -> {md}")
    return report
