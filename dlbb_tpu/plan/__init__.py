"""Plan-space search: cm2-driven parallelism-plan autotuning.

The paper answers "which launcher/knob combination is fastest for this
tensor shape" by brute-force sweep; this package closes the loop with a
predict-prune-measure search grounded in the fitted cm2 cost model and
the static memory-feasibility term (``hbm_headroom_bytes``), so the
sweep only ever *runs* the handful of plans the model cannot separate.
"""

from dlbb_tpu.plan.autotune import (  # noqa: F401
    CAL_FAMILIES,
    PlanPoint,
    calibration_agreement,
    enumerate_serving_space,
    enumerate_train_space,
    heuristic_point,
    predict_point_us,
    prune_point,
    rank_points,
    run_capacity_plan,
    run_plan_search,
)
