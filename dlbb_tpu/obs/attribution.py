"""Span-level time attribution (``cli obs attribute``).

The span tracer (PR 8) records *when* every harness phase ran; the
fitted cost model (cm2) predicts *how long* the device work should
take.  This module joins the two into a "where did the time go"
breakdown for one run directory — per phase (queue-wait / compile /
prefill / decode / execute / write / idle), per sweep config, and per
serving request — with the cm2 prediction decomposed into its
dispatch-overhead / collective-wire / compute terms next to the
measured number, emitted as MD + CSV under
``stats/analysis/attribution/``.

Inputs, in preference order:

- a **span trace** (Chrome trace-event JSON written via
  ``--span-trace``/``DLBB_SPANS``): the main track's timeline is
  partitioned exactly — every instant of the wall belongs to the
  innermost phase-mapped span covering it, to ``host`` (inside an
  unmapped span, e.g. the per-config glue), or to ``idle`` (no span
  open).  Phase times therefore sum to the track's wall time by
  construction.
- a **journal** (``sweep_journal.jsonl``) when no trace exists — the
  committed serving run's case: the last session's event stream is
  segmented and each inter-event interval is attributed to the phase
  the *ending* event closes (``request-admitted`` closes queue-wait,
  ``request-prefill`` a prefill, ``request-completed`` decode work,
  ...).  Coarser than spans, still a complete partition.

Predictions come from :func:`dlbb_tpu.analysis.costmodel.resolve_tier`
(``--model cm1|cm2``): sweep configs re-use the corpus feature
extractor (:mod:`dlbb_tpu.obs.corpus`) on each artifact — per timed
iteration ``γ + α·collectives + wire/β + FLOPs/peak`` — and serving
runs price their recorded dispatch counts (``decode_units``, admitted
prefills) with per-layer tp-collective counts and an analytic
dense-forward FLOPs estimate from the report's model record.  The
per-request table is measured-only (a decode dispatch serves the whole
batch, so charging it to one request would double-count); the
predicted-vs-measured comparison lives at the phase level where
dispatch counts are exact.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Optional

from dlbb_tpu.analysis.costmodel import (
    COST_MODEL_VERSION,
    CostTier,
    resolve_tier,
)
from dlbb_tpu.obs.devtrace import _fmt_us

ATTRIBUTION_SCHEMA = "dlbb_attribution_v1"
DEFAULT_ATTRIBUTION_DIR = Path("stats/analysis/attribution")

# ordered phase vocabulary of the partition (every measured second of
# the wall lands in exactly one)
PHASES = ("queue-wait", "plan", "compile", "payload", "prefill",
          "decode", "execute", "write", "capture", "host", "idle")

# span name -> phase (innermost mapped span wins; prefix match for the
# dynamic names)
_SPAN_PHASE = {
    "plan": "plan",
    "compile": "compile",
    "compile+warmup": "compile",
    "compile-wait": "compile",
    "payload": "payload",
    "measure": "execute",
    "train_step": "execute",
    "device-capture": "capture",
    "write": "write",
    "serve-admission": "queue-wait",
    "serve-prefill": "prefill",
    "serve-prefill-chunk": "prefill",
    "serve-decode": "decode",
}
_SPAN_PHASE_PREFIX = (("calibrate:", "execute"),)

# journal event -> phase of the interval ENDING at that event
_JOURNAL_PHASE = {
    "request-admitted": "queue-wait",
    "request-rejected": "queue-wait",
    "request-infeasible": "queue-wait",
    "request-prefill": "prefill",
    "request-completed": "decode",
    "request-failed": "decode",
    "request-preempted": "decode",
    "completed": "execute",
    "failed": "execute",
    "retry": "execute",
}

CSV_COLUMNS = (
    "kind", "name", "measured_us", "queue_wait_us", "prefill_us",
    "decode_us", "compile_us", "execute_us", "device_us",
    "predicted_execute_us",
    "predicted_dispatch_overhead_us", "predicted_wire_us",
    "predicted_compute_us", "dispatches", "iterations", "tokens",
    "error_factor", "outcome",
)


def _capture_device_us(meta: dict[str, Any],
                       input_dir: Path) -> Optional[float]:
    """Device-measured busy time of ONE execution from a config's
    gated capture (``obs/devtrace.py``): each device's summed device-op
    event time, median across devices, amortised per profile rep.
    None when the capture is absent, failed, or unparseable — the
    device column stays honest-blank rather than guessed."""
    from dlbb_tpu.obs.devtrace import (
        CaptureError,
        _resolve_capture_path,
        device_comm_samples,
        parse_capture,
    )

    if not isinstance(meta, dict) or "error" in meta:
        return None
    path = _resolve_capture_path(meta, input_dir)
    if path is None:
        return None
    try:
        timeline = parse_capture(path)
    except CaptureError:
        return None
    agg = device_comm_samples(timeline,
                              int(meta.get("profile_reps", 1)),
                              buckets=None)
    return agg["measured_device_us"] if agg else None


def _infer_tier(input_dir: Path) -> str:
    """Cost-model tier from the run's artifacts (they record the backend
    they measured on — ``corpus.tier_of_result``); ``cpu-sim`` when
    nothing under the directory records one."""
    from dlbb_tpu.obs.corpus import tier_of_result

    for path in sorted(Path(input_dir).glob("*.json")):
        try:
            data = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError):
            continue
        if isinstance(data, dict) and isinstance(
                data.get("system_info"), dict):
            return tier_of_result(data)
    return "cpu-sim"


def _fmt_bytes(n: Optional[float]) -> str:
    if n is None:
        return "-"
    n = float(n)
    for unit, div in (("GiB", 2**30), ("MiB", 2**20), ("KiB", 2**10)):
        if n >= div:
            return f"{n / div:.1f} {unit}"
    return f"{int(n)} B"


def _span_phase(name: str) -> Optional[str]:
    phase = _SPAN_PHASE.get(name)
    if phase:
        return phase
    for prefix, p in _SPAN_PHASE_PREFIX:
        if name.startswith(prefix):
            return p
    return None


# ---------------------------------------------------------------------------
# measured partition
# ---------------------------------------------------------------------------


def partition_trace(events: list[dict[str, Any]]
                    ) -> tuple[dict[str, float], float, dict]:
    """Partition the busiest track's timeline into phase micro-seconds.
    Returns ``(phase_us, wall_us, per_name_us)``; phases + idle sum to
    ``wall_us`` exactly."""
    # pick the track (pid, tid) carrying the most B/E span time
    totals: dict[tuple, float] = {}
    opens: dict[tuple, dict[str, list[float]]] = {}
    for ev in events:
        if ev.get("ph") not in ("B", "E"):
            continue
        key = (ev.get("pid"), ev.get("tid"))
        stack = opens.setdefault(key, {})
        if ev["ph"] == "B":
            stack.setdefault(ev["name"], []).append(ev["ts"])
        else:
            starts = stack.get(ev["name"])
            if starts:
                totals[key] = totals.get(key, 0.0) + ev["ts"] - starts.pop()
    if not totals:
        return {}, 0.0, {}
    track = max(totals, key=lambda k: totals[k])

    track_events = sorted(
        (ev for ev in events
         if ev.get("ph") in ("B", "E")
         and (ev.get("pid"), ev.get("tid")) == track),
        key=lambda ev: ev["ts"],
    )
    phase_us: dict[str, float] = {}
    per_name: dict[str, float] = {}
    stack: list[str] = []
    prev_ts = track_events[0]["ts"]
    for ev in track_events:
        ts = ev["ts"]
        if ts > prev_ts:
            phase = "idle"
            for name in reversed(stack):
                mapped = _span_phase(name)
                if mapped:
                    phase = mapped
                    break
            else:
                if stack:
                    phase = "host"
            phase_us[phase] = phase_us.get(phase, 0.0) + ts - prev_ts
            if stack:
                per_name[stack[-1]] = per_name.get(stack[-1], 0.0) \
                    + ts - prev_ts
        prev_ts = ts
        if ev["ph"] == "B":
            stack.append(ev["name"])
        elif stack and stack[-1] == ev["name"]:
            stack.pop()
        elif ev["name"] in stack:  # tolerate mild misnesting
            stack.remove(ev["name"])
    wall = track_events[-1]["ts"] - track_events[0]["ts"]
    return phase_us, wall, per_name


def last_session(records: list[dict[str, Any]]) -> list[dict[str, Any]]:
    """Journals are append-only across runs; attribute the LAST session
    only (request ids repeat across sessions)."""
    start = 0
    for i, rec in enumerate(records):
        if rec.get("event") == "sweep-start":
            start = i
    return records[start:]


def partition_journal(records: list[dict[str, Any]]
                      ) -> tuple[dict[str, float], float]:
    """Segment the journal's event stream: each inter-event interval is
    attributed to the phase its ending event closes (unknown enders →
    idle).  Phases sum to the stream's wall time exactly."""
    recs = [r for r in records if "ts" in r]
    recs.sort(key=lambda r: float(r["ts"]))
    phase_us: dict[str, float] = {}
    prev = None
    for rec in recs:
        ts = float(rec["ts"])
        if prev is not None and ts > prev:
            phase = _JOURNAL_PHASE.get(rec.get("event"), "idle")
            phase_us[phase] = phase_us.get(phase, 0.0) + (ts - prev) * 1e6
        prev = ts
    wall = (float(recs[-1]["ts"]) - float(recs[0]["ts"])) * 1e6 \
        if len(recs) > 1 else 0.0
    return phase_us, wall


# ---------------------------------------------------------------------------
# predictions
# ---------------------------------------------------------------------------


def predict_iteration_us(sample: dict[str, Any], tier: CostTier
                         ) -> dict[str, float]:
    """cm-priced decomposition of ONE timed iteration of a corpus-shaped
    sample: {dispatch, wire, compute, total} in µs."""
    dispatch = sample.get("dispatches", 1.0) * tier.gamma_dispatch_us
    wire = (sample.get("collectives", 1.0) * tier.alpha_us
            + sample["wire_bytes"] / tier.beta_bytes_per_us)
    compute = sample.get("flops", 0) / tier.peak_flops_per_us
    return {"dispatch": dispatch, "wire": wire, "compute": compute,
            "total": dispatch + wire + compute}


def _serving_dispatch_features(report: dict[str, Any]
                               ) -> dict[str, dict[str, float]]:
    """Analytic per-dispatch features of the serving engine's two jit
    families, from the report's model/mesh/serving records: decode = one
    token per active slot through the stack (≈ 24·L·h² FLOPs/token, two
    tp psums per layer when tp > 1), prefill = one bucket of prompt
    tokens.  Approximations — the attribution is about magnitudes, the
    audit targets pin the exact inventories."""
    model = report.get("model", {})
    mesh = report.get("mesh", {})
    serving = report.get("serving", {})
    h = int(model.get("hidden_size", 0) or 0)
    layers = int(model.get("num_layers", 0) or 0)
    tp = int(mesh.get("tp", 1) or 1)
    max_batch = int(serving.get("max_batch", 1) or 1)
    dtype_bytes = 4 if "32" in str(model.get("dtype", "")) else 2
    flops_token = 24 * layers * h * h
    coll = (2 * layers) if tp > 1 else 0
    # per-token activation psum: [1, h] partial per layer
    wire_token = (2 * (tp - 1) / tp * h * dtype_bytes * coll
                  if tp > 1 else 0)
    buckets = serving.get("prefill_buckets") or [serving.get("max_seq", 0)]
    mean_bucket = sum(buckets) / max(len(buckets), 1)
    return {
        "decode": {"collectives": float(coll),
                   "wire_bytes": float(wire_token * max_batch),
                   "flops": float(flops_token * max_batch),
                   "dispatches": 1.0},
        "prefill": {"collectives": float(coll),
                    "wire_bytes": float(wire_token * mean_bucket),
                    "flops": float(flops_token * mean_bucket),
                    "dispatches": 1.0},
    }


def _serving_peak_bytes(report: dict[str, Any]) -> dict[str, int]:
    """Static per-device peak-memory prediction per serving phase, from
    the report's model/serving/mesh records — the memory-audit twin of
    the time prediction: tp-sharded weights (~12·L·H² magnitude
    estimate) + the dp/tp-sharded KV cache (priced by the ONE formula,
    ``models.configs.kv_cache_bytes_raw`` — the same number the HBM
    budget gate and the static cache cross-check use) + phase
    activations.  Empty (the column stays honest-blank) when the run
    records no model/serving geometry — sweep runs, legacy reports."""
    from dlbb_tpu.models.configs import kv_cache_bytes_raw

    model = report.get("model", {})
    mesh = report.get("mesh", {})
    serving = report.get("serving", {})
    h = int(model.get("hidden_size", 0) or 0)
    layers = int(model.get("num_layers", 0) or 0)
    heads = int(model.get("num_heads", 0) or 0)
    max_batch = int(serving.get("max_batch", 0) or 0)
    max_seq = int(serving.get("max_seq", 0) or 0)
    if not (h and layers and heads and max_batch and max_seq):
        return {}
    kvh = int(model.get("kv_heads", heads) or heads)
    tp = max(1, int(mesh.get("tp", 1) or 1))
    dp = max(1, int(mesh.get("dp", 1) or 1))
    dtype = str(model.get("dtype", "bfloat16"))
    dtype_bytes = 4 if "32" in dtype else 2
    params_bytes = 12 * layers * h * h * dtype_bytes
    cache_dev = kv_cache_bytes_raw(
        layers, max_batch, max_seq, kvh, h // heads, dtype) // (dp * tp)
    resident = params_bytes // tp + cache_dev
    buckets = serving.get("prefill_buckets") or [max_seq]
    mean_bucket = int(sum(buckets) / max(len(buckets), 1))
    return {
        "decode": resident + 8 * max_batch * 3 * h * dtype_bytes,
        "prefill": resident + 8 * mean_bucket * 3 * h * dtype_bytes,
    }


# ---------------------------------------------------------------------------
# the attribute run
# ---------------------------------------------------------------------------


def _find_span_trace(directory: Path,
                     trace: "Optional[str | Path]") -> Optional[dict]:
    from dlbb_tpu.obs.spans import SPAN_SCHEMA

    candidates = [Path(trace)] if trace else sorted(directory.glob("*.json"))
    for path in candidates:
        try:
            data = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError) as e:
            if trace:
                # an EXPLICIT --span-trace-file must fail loudly — a
                # silent fallback to the coarser journal partition would
                # hide that the named trace was never read
                raise FileNotFoundError(
                    f"--span-trace-file {path}: unreadable ({e})"
                ) from e
            continue
        # a journal-RECONSTRUCTED trace (``obs trace`` output, often
        # sitting in the same directory) carries the span schema but only
        # M/i/X events — partitioning it would yield an empty wall=0
        # report; only a real span trace (B/E pairs) qualifies
        if (isinstance(data, dict)
                and data.get("otherData", {}).get("schema") == SPAN_SCHEMA
                and any(ev.get("ph") in ("B", "E")
                        for ev in data.get("traceEvents", ())
                        if isinstance(ev, dict))):
            return data
        if trace:
            raise ValueError(
                f"--span-trace-file {path} is not a span trace "
                "(wrong/missing otherData.schema, or no B/E span events "
                "— a journal-reconstructed `obs trace` output does not "
                "qualify)"
            )
    return None


def run_attribution(
    input_dir: "str | Path",
    out_dir: "Optional[str | Path]" = None,
    trace: "Optional[str | Path]" = None,
    model: str = COST_MODEL_VERSION,
    tier: Optional[str] = None,
    fit_dir: "Optional[str | Path]" = None,
    name: Optional[str] = None,
    verbose: bool = True,
) -> dict[str, Any]:
    """Attribute one run directory; writes ``<name>.md`` + ``<name>.csv``
    under ``out_dir`` (default ``stats/analysis/attribution/``) and
    returns the attribution record."""
    from dlbb_tpu.resilience.journal import read_journal

    input_dir = Path(input_dir)
    out_dir = Path(out_dir or DEFAULT_ATTRIBUTION_DIR)
    name = name or input_dir.resolve().name
    if tier is None:
        # file processing must stay backend-free: infer the tier from
        # the artifacts (they record their backend), default cpu-sim
        tier = _infer_tier(input_dir)
    cost_tier = resolve_tier(tier, model=model, fit_dir=fit_dir)

    records, torn = read_journal(input_dir)
    session = last_session(records)
    trace_data = _find_span_trace(input_dir, trace)
    if trace_data is not None:
        phase_us, wall_us, _names = partition_trace(
            trace_data["traceEvents"])
        source = "span-trace"
    elif session:
        phase_us, wall_us = partition_journal(session)
        source = "journal"
    else:
        raise FileNotFoundError(
            f"{input_dir} holds neither a span trace nor a parseable "
            "journal — nothing to attribute (run with --span-trace, or "
            "point --input at a sweep/serving output directory)"
        )

    serving = any(str(r.get("event", "")).startswith("request-")
                  for r in session)
    peak_bytes: dict[str, int] = {}
    if serving:
        report = _serving_report(input_dir) or {}
        entities, predicted, device_us = _serving_entities(
            input_dir, session, cost_tier, report)
        peak_bytes = _serving_peak_bytes(report)
    else:
        entities, predicted, device_us = _sweep_entities(
            input_dir, session, cost_tier)

    record = {
        "schema": ATTRIBUTION_SCHEMA,
        "name": name,
        "input_dir": str(input_dir),
        "source": source,
        "kind": "serving" if serving else "sweep",
        "tier": cost_tier.name,
        "cost_model_version": cost_tier.version,
        "fit_version": (cost_tier.fit or {}).get("fit_version"),
        "wall_us": wall_us,
        "phases_us": {p: phase_us.get(p, 0.0) for p in PHASES
                      if phase_us.get(p)},
        "predicted_us": predicted,
        # device-measured phase totals from the run's gated captures
        # (one captured execution x the recorded execution count);
        # empty when the run was uncaptured
        "device_us": device_us,
        # static per-phase peak-memory prediction (what was RESIDENT
        # while the time went) — serving phases only; phases without a
        # memory model stay honest-blank (docs/memory_audit.md)
        "peak_bytes": peak_bytes,
        "entities": entities,
        "torn_journal_lines": torn,
    }
    md_path, csv_path = write_attribution(record, out_dir)
    record["md_path"], record["csv_path"] = str(md_path), str(csv_path)
    if verbose:
        total = sum(record["phases_us"].values())
        print(f"[obs] attribution ({record['kind']}, {source}, "
              f"{cost_tier.version}): wall {wall_us / 1e6:.2f}s, "
              f"phases cover {total / max(wall_us, 1e-9) * 100:.1f}% "
              f"-> {md_path}")
    return record


def _sweep_entities(input_dir: Path, session: list[dict],
                    tier: CostTier) -> tuple[list[dict], dict]:
    """Per-config rows: journal lifecycle joined with each artifact's
    corpus features, priced per iteration."""
    from dlbb_tpu.obs.corpus import ingest_result

    started: dict[str, float] = {}
    done: dict[str, tuple[float, str]] = {}
    for rec in session:
        cfg, ev = rec.get("config"), rec.get("event")
        if not cfg:
            continue
        if ev == "started":
            started[cfg] = float(rec["ts"])
        elif ev in ("completed", "failed"):
            done[cfg] = (float(rec["ts"]), ev)

    entities: list[dict] = []
    pred_totals = {"dispatch": 0.0, "wire": 0.0, "compute": 0.0,
                   "total": 0.0}
    device_execute = 0.0
    configs = sorted(set(started) | set(done)) or sorted(
        p.name for p in input_dir.glob("*.json")
        if p.name != "sweep_manifest.json"
    )
    for cfg in configs:
        path = input_dir / cfg
        row: dict[str, Any] = {"kind": "config", "name": cfg}
        if cfg in started and cfg in done:
            row["measured_us"] = (done[cfg][0] - started[cfg]) * 1e6
            row["outcome"] = done[cfg][1]
        sample = None
        data = None
        if path.exists():
            try:
                data = json.loads(path.read_text())
                sample, _ = ingest_result(path, data)
                if sample is not None:
                    row["compile_us"] = float(
                        data.get("compile_seconds", 0.0)) * 1e6
            except (OSError, json.JSONDecodeError):
                pass
        if isinstance(data, dict):
            # the device column: one captured execution's device-op
            # busy time (median across devices), measured by the gated
            # capture — side by side with the host-span numbers
            dev = _capture_device_us(data.get("device_trace"), input_dir)
            if dev is not None:
                row["device_us"] = dev
                if sample is not None:
                    device_execute += dev * sample["iterations"]
        if sample is not None:
            iters = sample["iterations"]
            per_iter = predict_iteration_us(sample, tier)
            row.update(
                iterations=iters,
                dispatches=iters * sample.get("dispatches", 1.0),
                execute_us=sample["measured_median_us"] * iters,
                predicted_execute_us=per_iter["total"] * iters,
                predicted_dispatch_overhead_us=per_iter["dispatch"] * iters,
                predicted_wire_us=per_iter["wire"] * iters,
                predicted_compute_us=per_iter["compute"] * iters,
            )
            if row["predicted_execute_us"] > 0 and row["execute_us"] > 0:
                m, p = row["execute_us"], row["predicted_execute_us"]
                row["error_factor"] = max(m, p) / min(m, p)
            for k, kk in (("dispatch", "predicted_dispatch_overhead_us"),
                          ("wire", "predicted_wire_us"),
                          ("compute", "predicted_compute_us"),
                          ("total", "predicted_execute_us")):
                pred_totals[k] += row[kk]
        entities.append(row)
    predicted = {
        "execute": pred_totals["total"],
        "dispatch-overhead": pred_totals["dispatch"],
        "collective-wire": pred_totals["wire"],
        "compute": pred_totals["compute"],
    }
    # device-measured execute: one captured execution's device busy
    # time x the iteration count each config timed (empty when the run
    # carried no captures — the column stays honest-blank)
    device_us = {"execute": device_execute} if device_execute > 0 else {}
    return entities, predicted, device_us


def _serving_report(input_dir: Path) -> Optional[dict[str, Any]]:
    """The run's serving report JSON, or None when the directory holds
    only a journal (the crashed-run case)."""
    for path in sorted(Path(input_dir).glob("serving_*.json")):
        if path.name in ("serving_manifest.json", "serving_resume.json"):
            continue
        try:
            data = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError):
            continue
        if isinstance(data, dict) and data.get("schema", "").startswith(
                "dlbb_serving_report"):
            return data
    return None


def _serving_entities(input_dir: Path, session: list[dict],
                      tier: CostTier,
                      report: Optional[dict[str, Any]] = None
                      ) -> tuple[list[dict], dict, dict]:
    """Per-request measured rows (queue-wait / prefill / decode from the
    journal lifecycle) + phase-level predictions from the run report's
    exact dispatch counts + device-measured phase totals from the run's
    capture metas (one captured dispatch per phase x the dispatch
    count)."""
    if report is None:
        report = _serving_report(input_dir) or {}

    marks: dict[str, dict[str, float]] = {}
    for rec in session:
        rid, ev = rec.get("config"), rec.get("event")
        if not rid or not str(ev).startswith("request-"):
            continue
        m = marks.setdefault(rid, {})
        m[ev[len("request-"):]] = float(rec["ts"])
        if ev == "request-completed" and "output_tokens" in rec:
            m["tokens"] = float(rec["output_tokens"])

    entities: list[dict] = []
    for rid in sorted(marks, key=lambda r: marks[r].get("arrived", 0.0)):
        m = marks[rid]
        row: dict[str, Any] = {"kind": "request", "name": rid}
        arr = m.get("arrived")
        adm = m.get("admitted")
        pre = m.get("prefill")
        end = next((m[k] for k in ("completed", "failed", "preempted",
                                   "rejected", "infeasible") if k in m),
                   None)
        if arr is not None and adm is not None:
            row["queue_wait_us"] = (adm - arr) * 1e6
        elif arr is not None and "rejected" in m:
            row["queue_wait_us"] = (m["rejected"] - arr) * 1e6
        if adm is not None and pre is not None:
            row["prefill_us"] = (pre - adm) * 1e6
        if pre is not None and end is not None:
            row["decode_us"] = (end - pre) * 1e6
        if arr is not None and end is not None:
            row["measured_us"] = (end - arr) * 1e6
        if "tokens" in m:
            row["tokens"] = int(m["tokens"])
        row["outcome"] = next(
            (k for k in ("completed", "failed", "preempted", "rejected",
                         "infeasible") if k in m), "in-flight")
        entities.append(row)

    predicted: dict[str, float] = {}
    device_us: dict[str, float] = {}
    if report:
        feats = _serving_dispatch_features(report)
        decode_units = float(report.get("decode_units",
                                        report.get("decode_steps", 0)))
        prefills = float(report.get("requests", {}).get("admitted", 0))
        chunks = float(
            (report.get("fast_path") or {}).get("prefill_chunks") or 0)
        if chunks:
            prefills = chunks
        dec = predict_iteration_us(feats["decode"], tier)
        pre = predict_iteration_us(feats["prefill"], tier)
        predicted = {
            "decode": dec["total"] * decode_units,
            "prefill": pre["total"] * prefills,
            "dispatch-overhead": (dec["dispatch"] * decode_units
                                  + pre["dispatch"] * prefills),
            "collective-wire": (dec["wire"] * decode_units
                                + pre["wire"] * prefills),
            "compute": (dec["compute"] * decode_units
                        + pre["compute"] * prefills),
            "decode_units": decode_units,
            "prefill_dispatches": prefills,
        }
        # the device column: each phase's captured per-dispatch device
        # busy time x the same dispatch counts the predictions price
        for meta in (report.get("observability") or {}).get(
                "device_captures", ()):
            dev = _capture_device_us(meta, input_dir)
            if dev is None:
                continue
            phase = meta.get("phase")
            if phase == "prefill" and prefills:
                device_us["prefill"] = dev * prefills
            elif phase == "decode" and decode_units:
                # the captured scan ran a fixed k token steps while the
                # run's scans vary k per dispatch — normalise the
                # captured time per STEP and scale by the run's total
                # decode steps, never by dispatch count
                k_cap = max(1, int(meta.get("decode_steps_per_scan", 1)))
                steps = float(report.get("decode_steps", decode_units))
                device_us["decode"] = dev / k_cap * steps
    return entities, predicted, device_us


# ---------------------------------------------------------------------------
# output (MD + CSV via atomic_write_text)
# ---------------------------------------------------------------------------


def write_attribution(record: dict[str, Any],
                      out_dir: "str | Path") -> tuple[Path, Path]:
    import csv
    import io

    from dlbb_tpu.utils.config import atomic_write_text

    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    name = record["name"]
    wall = record["wall_us"]
    phases = record["phases_us"]
    predicted = record["predicted_us"]

    lines = [
        f"# Time attribution — {name}",
        "",
        f"- schema: `{ATTRIBUTION_SCHEMA}`",
        f"- kind: {record['kind']} (measured from {record['source']})",
        f"- cost model: {record['cost_model_version']}"
        + (f" (fit v{record['fit_version']})"
           if record.get("fit_version") else "")
        + f" / tier {record['tier']}",
        f"- wall time: {_fmt_us(wall)}",
        "",
        "## Where the wall time went",
        "",
        "Measured phases partition the "
        + ("main span track" if record["source"] == "span-trace"
           else "journal event stream")
        + " — they sum to the wall time.  Predicted columns decompose "
          "the device-work phases with the "
        + record["cost_model_version"]
        + " model (γ·dispatches + α·collectives + wire/β + FLOPs/peak)."
        + ("  The device column is measured from the run's gated "
           "captures: one captured execution's device-op busy time x "
           "the recorded execution count (obs devtrace parses the "
           "same captures per op)." if record.get("device_us") else "")
        + ("  The peak column is the STATIC per-device memory "
           "prediction for the phase's resident set (sharded weights + "
           "KV cache + activations — docs/memory_audit.md); phases "
           "without a memory model stay blank."
           if record.get("peak_bytes") else ""),
        "",
        "| phase | measured | share | device (captured) | predicted "
        "| peak (static) |",
        "|---|---:|---:|---:|---:|---:|",
    ]
    device_us = record.get("device_us") or {}
    peak_bytes = record.get("peak_bytes") or {}
    for phase in PHASES:
        us = phases.get(phase)
        if not us:
            continue
        share = us / wall * 100 if wall else 0.0
        pred = predicted.get(phase)
        dev = device_us.get(phase)
        peak = peak_bytes.get(phase)
        lines.append(f"| {phase} | {_fmt_us(us)} | {share:.1f}% | "
                     f"{_fmt_us(dev) if dev else '-'} | "
                     f"{_fmt_us(pred) if pred else '-'} | "
                     f"{_fmt_bytes(peak) if peak else '-'} |")
    covered = sum(phases.values())
    lines.append(f"| **total** | {_fmt_us(covered)} | "
                 f"{covered / wall * 100 if wall else 0:.1f}% | | | |")
    lines += [
        "",
        "## Predicted device-work decomposition",
        "",
        "| term | predicted |",
        "|---|---:|",
    ]
    for term in ("dispatch-overhead", "collective-wire", "compute"):
        if term in predicted:
            lines.append(f"| {term} | {_fmt_us(predicted[term])} |")
    ent_label = ("request" if record["kind"] == "serving" else "config")
    measured_ents = [e for e in record["entities"]
                     if e.get("measured_us") is not None]
    top = sorted(measured_ents, key=lambda e: -e["measured_us"])[:20]
    lines += [
        "",
        f"## Top {ent_label}s by measured time "
        f"({len(record['entities'])} total; full table in the CSV)",
        "",
    ]
    if record["kind"] == "serving":
        lines += [
            "| request | total | queue-wait | prefill | decode | tokens "
            "| outcome |",
            "|---|---:|---:|---:|---:|---:|---|",
        ]
        for e in top:
            lines.append(
                f"| {e['name']} | {_fmt_us(e.get('measured_us'))} | "
                f"{_fmt_us(e.get('queue_wait_us'))} | "
                f"{_fmt_us(e.get('prefill_us'))} | "
                f"{_fmt_us(e.get('decode_us'))} | "
                f"{e.get('tokens', '-')} | {e.get('outcome', '-')} |")
    else:
        lines += [
            "| config | wall | execute (measured) | device (1 rep) "
            "| execute (predicted) "
            "| of which dispatch | wire | compute | err |",
            "|---|---:|---:|---:|---:|---:|---:|---:|---:|",
        ]
        for e in top:
            err = e.get("error_factor")
            lines.append(
                f"| {e['name']} | {_fmt_us(e.get('measured_us'))} | "
                f"{_fmt_us(e.get('execute_us'))} | "
                f"{_fmt_us(e.get('device_us'))} | "
                f"{_fmt_us(e.get('predicted_execute_us'))} | "
                f"{_fmt_us(e.get('predicted_dispatch_overhead_us'))} | "
                f"{_fmt_us(e.get('predicted_wire_us'))} | "
                f"{_fmt_us(e.get('predicted_compute_us'))} | "
                f"{f'{err:.2f}x' if err else '-'} |")
    lines.append("")
    md_path = atomic_write_text("\n".join(lines), out_dir / f"{name}.md")

    buf = io.StringIO()
    writer = csv.DictWriter(buf, fieldnames=list(CSV_COLUMNS),
                            extrasaction="ignore")
    writer.writeheader()
    for e in record["entities"]:
        writer.writerow(e)
    csv_path = atomic_write_text(buf.getvalue(), out_dir / f"{name}.csv",
                                 newline="")
    return md_path, csv_path


def validate_attribution(record: dict[str, Any],
                         tolerance: float = 0.05) -> list[str]:
    """Schema/consistency check (the acceptance contract): required
    keys, known phases only, and the measured phase partition summing to
    the wall time within ``tolerance``.  Returns problems (empty =
    valid)."""
    problems: list[str] = []
    for key in ("schema", "name", "kind", "wall_us", "phases_us",
                "entities", "cost_model_version"):
        if key not in record:
            problems.append(f"missing key {key!r}")
    if record.get("schema") != ATTRIBUTION_SCHEMA:
        problems.append(f"schema {record.get('schema')!r} != "
                        f"{ATTRIBUTION_SCHEMA!r}")
    unknown = set(record.get("phases_us", {})) - set(PHASES)
    if unknown:
        problems.append(f"unknown phase(s) {sorted(unknown)}")
    wall = record.get("wall_us") or 0.0
    covered = sum(record.get("phases_us", {}).values())
    if wall <= 0:
        # an empty partition must never validate — it means the input
        # trace carried no measurable span time at all
        problems.append("wall_us is zero — nothing was attributed")
    elif abs(covered - wall) > tolerance * wall:
        problems.append(
            f"phases cover {covered:.0f}us of {wall:.0f}us wall "
            f"({covered / wall * 100:.1f}%, tolerance {tolerance:.0%})"
        )
    return problems
