"""cm2: robust α–β–γ regression over the sweep-artifact corpus.

The static cost model (cm1, ``analysis/costmodel.py``) prices a
collective at ``α + wire/β`` and compute at ``FLOPs/peak`` from
hand-seeded constants — useful for *relative* schedule structure, but
committed ~289x off in absolute terms on the cpu-sim tier because the
per-dispatch host overhead (trace/launch/sync of a jitted program) is
un-modelled.  This module fits the missing term — and re-fits the
constants — from measured data (:mod:`dlbb_tpu.obs.corpus`):

    measured_us ≈ γ·dispatches + α·collectives + wire/β + FLOPs/peak

solved per tier by weighted least squares (weights ``1/measured`` — the
relative-error objective, so a 4 s 1 GB ring and a 300 µs 1 KB ring
count equally), with:

- **non-negativity** via an active-set loop (a negative coefficient is
  clamped to zero and its column removed — a fit can conclude "no
  measurable per-collective latency", never a negative one);
- **identifiability fallback** — a corpus where every sample posts one
  collective per dispatch cannot separate α from γ (collinear columns);
  the fit detects the rank deficiency, pins α to the cm1 analytic seed
  and attributes the remaining intercept to γ (recorded as
  ``alpha_pinned``).  Same for peak FLOPs when no sample carries dense
  compute (``peak_pinned``).  The mirror case: a ``host_filter``-ed
  population whose rows all carry the same dispatch count (the
  calibration rows — one dispatch each) cannot identify γ either, and
  since dispatch overhead is a property of the *host runtime*, not of
  the program, γ is then pinned from a pre-fit over the FULL tier
  corpus (whose chained-timing rows amortise the dispatch and expose
  γ directly; recorded as ``gamma_pinned: "tier-corpus"``);
- **device-timed op samples** — the devtrace source
  (:mod:`dlbb_tpu.obs.devtrace`): per-collective measured device µs
  with ``dispatches: 0`` and ``flops: 0``, exempt from ``host_filter``
  (device wire time is a tier property, not a host-runtime one).
  Program-scale samples alone cannot separate wire time from dispatch
  overhead on the cpu-sim tier — these rows are what identifies β
  there instead of pinning it from cm1;
- **outlier rejection** — MAD-based trimming on relative residuals
  (default 6 MADs, two rounds): one noisy host spike must not drag β;
- **fail-closed degeneracy checks** — too few samples, a single
  distinct message size (β unidentifiable), or an all-rejected corpus
  raise :class:`FitError` with the reason; a silently-garbage DB is
  never written.

The result is appended to the versioned fitted DB
(``stats/analysis/costmodel_fit/cm2_<tier>.json``) — append-only like
cm1's version table, so any committed calibration baseline's
``fit_version`` stays interpretable — with per-coefficient 95 % CI
bounds, sample counts and residual stats.  ``analysis/costmodel.py``
loads the latest version as the ``cm2`` pricing tier.

Host-side numpy only — no jax anywhere in this module.
"""

from __future__ import annotations

import json
import math
import time
from pathlib import Path
from typing import Any, Optional, Sequence

from dlbb_tpu.analysis.costmodel import (
    FIT_SCHEMA,
    fit_db_path,
    get_tier,
)

MIN_SAMPLES = 16
MIN_DISTINCT_WIRE = 2
OUTLIER_MAD = 6.0
OUTLIER_ROUNDS = 2

_COEFFS = ("gamma_dispatch_us", "alpha_us", "beta_inv", "peak_inv")


class FitError(RuntimeError):
    """A corpus that cannot produce a trustworthy fit (degenerate or
    contradictory) — the caller must NOT get a DB out of it."""


def _finite(x: Any) -> bool:
    return isinstance(x, (int, float)) and math.isfinite(x) and x > 0


def fit_tier(
    samples: Sequence[dict[str, Any]],
    tier: str,
    min_samples: int = MIN_SAMPLES,
    host_filter: Optional[str] = None,
    outlier_mad: float = OUTLIER_MAD,
) -> dict[str, Any]:
    """Fit one tier's coefficients from corpus samples.  Returns the fit
    record (the DB version entry, minus the version number); raises
    :class:`FitError` on any degeneracy."""
    import numpy as np

    cm1 = get_tier(tier)  # validates the tier name against cm1's table
    usable = [
        s for s in samples
        if s.get("tier") == tier
        and _finite(s.get("measured_median_us"))
        and s.get("wire_bytes") is not None
    ]
    # device-timed samples (the devtrace op rows, dispatches = 0) are
    # EXEMPT from the host filter: the filter isolates the host-runtime
    # dispatch overhead, which a device-op duration never carries —
    # while the wire behaviour they measure is a property of the
    # backend tier the fit predicts (they are what identifies β)
    device_rows = [s for s in usable if s.get("source") == "devtrace"]
    host_rows = [s for s in usable if s.get("source") != "devtrace"]
    gamma_pin: Optional[float] = None
    if host_filter:
        all_rows = usable
        host_rows = [s for s in host_rows
                     if host_filter in str(s.get("host", ""))]
        if len({float(s.get("dispatches", 1.0))
                for s in host_rows}) == 1:
            # the filtered HOST population cannot identify γ (no
            # dispatch-count variation; the device rows' zeros are no
            # evidence of the per-dispatch cost, only of its absence);
            # pin it from the full tier corpus — the host-runtime
            # constant is population-independent
            try:
                pre = fit_tier(all_rows, tier, min_samples=min_samples,
                               outlier_mad=outlier_mad)
                gamma_pin = pre["coefficients"]["gamma_dispatch_us"][
                    "value"]
            except FitError:
                gamma_pin = None  # full corpus degenerate too: fit free
    rows = host_rows + device_rows
    if not rows:
        raise FitError(
            f"no usable corpus samples for tier {tier!r}"
            + (f" with host filter {host_filter!r}" if host_filter else "")
            + " — every row is missing, non-finite, or filtered out"
        )
    if len(rows) < min_samples:
        raise FitError(
            f"only {len(rows)} corpus sample(s) for tier {tier!r} "
            f"(need >= {min_samples}) — a fit this thin would be noise; "
            "run a wider sweep or lower --min-samples deliberately"
        )
    wires = {s["wire_bytes"] for s in rows}
    if len(wires) < MIN_DISTINCT_WIRE:
        raise FitError(
            f"corpus for tier {tier!r} holds a single message size "
            f"(wire_bytes={next(iter(wires))}) — β is unidentifiable "
            "from one point; sweep at least two payload sizes"
        )

    d = np.array([s.get("dispatches", 1.0) for s in rows], dtype=float)
    a = np.array([s.get("collectives", 1.0) for s in rows], dtype=float)
    w = np.array([s["wire_bytes"] for s in rows], dtype=float)
    f = np.array([s.get("flops", 0) for s in rows], dtype=float)
    y = np.array([s["measured_median_us"] for s in rows], dtype=float)

    # identifiability: α needs samples whose collectives-per-dispatch
    # ratio varies (ring vs fused rows); peak needs dense-compute rows
    ratio = a / np.maximum(d, 1e-12)
    alpha_pinned = bool(np.allclose(ratio, ratio[0], rtol=1e-6))
    peak_pinned = bool(not np.any(f > 0))

    # a corpus with no dispatch-bearing samples at all (device-timed
    # rows only) carries zero evidence about γ — an all-zero column
    # would poison the covariance (singular X'X, every CI lost), so γ
    # pins to the cm1 seed (0) like any other unidentifiable term
    gamma_zero_pin = gamma_pin is None and not bool(np.any(d > 0))

    y_fit = y.copy()
    cols: list[tuple[str, "np.ndarray"]] = []
    if gamma_pin is not None:
        y_fit = y_fit - gamma_pin * d
    elif not gamma_zero_pin:
        cols.append(("gamma_dispatch_us", d))
    if alpha_pinned:
        y_fit = y_fit - cm1.alpha_us * a
    else:
        cols.append(("alpha_us", a))
    cols.append(("beta_inv", w))
    if not peak_pinned:
        cols.append(("peak_inv", f))

    keep = np.ones(len(rows), dtype=bool)
    # Stage 1 — outlier rejection on the PLAIN 1/measured-weighted fit
    # (irls_rounds=1).  The IRLS refinement must only ever see the
    # cleaned set: its reweighting trusts the current prediction, and a
    # wild row drags the prediction toward itself — reweighting on a
    # contaminated fit up-weights exactly the rows that need rejecting.
    for _ in range(OUTLIER_ROUNDS):
        _coef, _se, pred = _nnls_relative(
            [(n, c[keep]) for n, c in cols], y_fit[keep], irls_rounds=1
        )
        # relative residuals over the KEPT set; trim past the MAD gate.
        # The MAD floor keeps a near-exact corpus (residuals at numeric
        # noise) from trimming half of itself every round.
        rel = (pred - y_fit[keep]) / np.maximum(y_fit[keep], 1e-9)
        med = float(np.median(rel))
        mad = max(float(np.median(np.abs(rel - med))), 1e-7)
        ok = np.abs(rel - med) <= outlier_mad * mad
        if ok.all():
            break
        idx = np.flatnonzero(keep)
        keep[idx[~ok]] = False
        if keep.sum() < max(min_samples // 2, len(_COEFFS)):
            raise FitError(
                f"outlier rejection left {int(keep.sum())} of {len(rows)} "
                f"sample(s) for tier {tier!r} — the corpus is internally "
                "contradictory (mixed hosts? torn artifacts?); fit refused"
            )
    # Stage 2 — the full IRLS fit (the geomean-error objective) on the
    # cleaned set
    coef, stderr, _pred = _nnls_relative(
        [(n, c[keep]) for n, c in cols], y_fit[keep]
    )

    if gamma_pin is not None:
        gamma = gamma_pin
    elif gamma_zero_pin:
        gamma = cm1.gamma_dispatch_us
    else:
        gamma = coef.get("gamma_dispatch_us", 0.0)
    alpha = cm1.alpha_us if alpha_pinned else coef.get("alpha_us", 0.0)
    beta_inv = coef.get("beta_inv", 0.0)
    peak_inv = coef.get("peak_inv", 0.0)
    beta = 1.0 / beta_inv if beta_inv > 0 else cm1.beta_bytes_per_us
    peak = (1.0 / peak_inv if peak_inv > 0
            else cm1.peak_flops_per_us)
    for name, v in (("gamma_dispatch_us", gamma), ("alpha_us", alpha),
                    ("beta_bytes_per_us", beta),
                    ("peak_flops_per_us", peak)):
        if not math.isfinite(v) or v < 0:
            raise FitError(
                f"fit for tier {tier!r} produced {name}={v!r} — refusing "
                "to write a non-finite/negative coefficient DB"
            )

    # residual stats of the FULL model on the kept samples
    pred_full = (gamma * d + alpha * a + w / beta
                 + (f / peak if peak > 0 else 0.0))
    kept_pred, kept_meas = pred_full[keep], y[keep]
    factors = np.maximum(kept_pred, 1e-9) / np.maximum(kept_meas, 1e-9)
    factors = np.maximum(factors, 1.0 / factors)
    residuals = {
        "geomean_error_factor": float(np.exp(np.log(factors).mean())),
        "max_error_factor": float(factors.max()),
        "rms_log_error": float(
            np.sqrt((np.log(kept_pred / np.maximum(kept_meas, 1e-9)) ** 2)
                    .mean())
        ),
        "median_signed_rel_error": float(
            np.median((kept_pred - kept_meas)
                      / np.maximum(kept_meas, 1e-9))
        ),
    }

    def _ci(name: str, value: float, invert: bool) -> dict[str, Any]:
        se = stderr.get(name)
        out: dict[str, Any] = {"value": value}
        if se is None or not math.isfinite(se):
            return out
        c = coef.get(name, 0.0)
        lo, hi = c - 1.96 * se, c + 1.96 * se
        if invert:
            # β / peak are fitted as inverses: invert the interval ends;
            # a lower inverse bound at/below zero means the upper end is
            # unbounded — recorded as null (bare Infinity is not JSON)
            hi_v = 1.0 / lo if lo > 0 else None
            lo_v = 1.0 / hi if hi > 0 else 0.0
            out.update(ci95=[lo_v, hi_v], stderr_inv=se)
        else:
            out.update(ci95=[max(lo, 0.0), hi], stderr=se)
        return out

    coefficients = {
        "gamma_dispatch_us": (
            {"value": gamma, "pinned": "tier-corpus"}
            if gamma_pin is not None
            else {"value": gamma, "pinned": "cm1"} if gamma_zero_pin
            else _ci("gamma_dispatch_us", gamma, False)
        ),
        "alpha_us": (
            {"value": alpha, "pinned": "cm1"} if alpha_pinned
            else _ci("alpha_us", alpha, False)
        ),
        # a clamped-out inverse coefficient (wire / compute term not
        # positively identified) seeds from cm1 — recorded as a pin,
        # indistinguishable-from-fitted would break the every-pin-is-
        # recorded contract (docs/observability.md)
        "beta_bytes_per_us": (
            {"value": beta, "pinned": "cm1"} if beta_inv <= 0
            else _ci("beta_inv", beta, True)
        ),
        "peak_flops_per_us": (
            {"value": peak, "pinned": "cm1"}
            if peak_pinned or peak_inv <= 0
            else _ci("peak_inv", peak, True)
        ),
    }
    hosts = sorted({str(s.get("host")) for s in rows})
    return {
        "tier": tier,
        "coefficients": coefficients,
        "residuals": residuals,
        "samples_used": int(keep.sum()),
        "samples_total": len(rows),
        # the op-granularity device-timed rows (devtrace source) — the
        # population that identifies β without a host dispatch term
        "device_samples": len(device_rows),
        "outliers_rejected": int(len(rows) - keep.sum()),
        "alpha_pinned": alpha_pinned,
        "peak_pinned": peak_pinned,
        "gamma_pinned": gamma_pin is not None or gamma_zero_pin,
        "host_filter": host_filter,
        "hosts": hosts,
        "distinct_wire_sizes": len(wires),
        "ops": sorted({s["op"] for s in rows}),
    }


def _nnls_relative(cols, y, irls_rounds: int = 6):
    """Non-negative least squares in (approximate) LOG space, by
    iteratively-reweighted linear least squares: round 0 weights rows by
    ``1/measured`` (relative error), each later round by
    ``1/sqrt(prediction · measured)`` — the symmetrised Gauss-Newton
    linearization of ``Σ log(pred/measured)²``, i.e. the geomean-error-
    factor objective the calibration gate scores.  A plain
    ``1/measured`` weighting is asymmetric (under-prediction error is
    bounded at −1, over-prediction unbounded) and systematically
    under-fits mixed-scale corpora; a plain ``1/prediction`` reweight is
    unstable the other way (a row the current fit under-predicts by k
    gets its weight multiplied by k, so the next round chases it — the
    geometric mean bounds that amplification at √k).
    Negative coefficients are clamped out active-set style (a fit may
    conclude "no measurable per-collective latency", never a negative
    one).  Returns ``(coef, stderr, prediction)`` over the free columns
    (dropped ones report 0 with no stderr)."""
    import numpy as np

    names = [n for n, _ in cols]
    wgt = 1.0 / np.maximum(y, 1e-9)
    sol = np.zeros(0)
    pred = y.copy()
    active: list[int] = list(range(len(cols)))
    for round_ in range(irls_rounds):
        # a column clamped out under one weighting may be positive under
        # the next: every round restarts from the full column set
        active = list(range(len(cols)))
        for _ in range(len(cols) + 1):
            X = np.stack([cols[i][1] for i in active], axis=1)
            Xw = X * wgt[:, None]
            yw = y * wgt
            sol, *_ = np.linalg.lstsq(Xw, yw, rcond=None)
            neg = [i for i, v in enumerate(sol) if v < 0]
            if not neg:
                break
            active = [a for i, a in enumerate(active) if i not in neg]
            if not active:
                return {n: 0.0 for n in names}, {}, np.zeros_like(y)
        X = np.stack([cols[i][1] for i in active], axis=1)
        pred = X @ sol
        new_wgt = 1.0 / np.sqrt(np.maximum(pred, 1e-9)
                                * np.maximum(y, 1e-9))
        if np.allclose(new_wgt, wgt, rtol=1e-4):
            break
        wgt = new_wgt
    Xw = X * wgt[:, None]
    dof = max(len(y) - len(active), 1)
    rss = float(((pred - y) * wgt).dot((pred - y) * wgt))
    try:
        cov = rss / dof * np.linalg.inv(Xw.T @ Xw)
        ses = np.sqrt(np.maximum(np.diag(cov), 0.0))
    except np.linalg.LinAlgError:
        ses = np.full(len(active), float("nan"))
    coef = {n: 0.0 for n in names}
    stderr: dict[str, float] = {}
    for i, col_idx in enumerate(active):
        coef[names[col_idx]] = float(sol[i])
        stderr[names[col_idx]] = float(ses[i])
    return coef, stderr, pred


# ---------------------------------------------------------------------------
# versioned DB (append-only, like cm1's COST_MODELS table)
# ---------------------------------------------------------------------------


def save_fit(fit: dict[str, Any], directory: "Optional[str | Path]" = None,
             corpus_meta: Optional[dict[str, Any]] = None
             ) -> tuple[Path, int]:
    """Append one fit as a new version of the tier's cm2 DB; returns
    ``(path, fit_version)``.  Existing versions are never rewritten — a
    calibration baseline recording ``fit_version: 2`` stays
    interpretable after version 3 lands."""
    from dlbb_tpu.utils.config import atomic_write_text

    path = fit_db_path(fit["tier"], directory)
    db: dict[str, Any] = {
        "schema": FIT_SCHEMA, "model": "cm2", "tier": fit["tier"],
        "versions": [],
    }
    if path.exists():
        db = json.loads(path.read_text())
        if db.get("tier") != fit["tier"]:
            raise FitError(
                f"{path} holds tier {db.get('tier')!r}, refusing to "
                f"append a {fit['tier']!r} fit"
            )
    version = len(db["versions"]) + 1
    entry = dict(fit)
    entry["fit_version"] = version
    entry["fitted_at"] = time.time()
    if corpus_meta:
        entry["corpus"] = corpus_meta
    db["versions"].append(entry)
    path.parent.mkdir(parents=True, exist_ok=True)
    atomic_write_text(json.dumps(db, indent=1, sort_keys=True) + "\n", path)
    return path, version


def run_fit(
    results: "Sequence[str | Path]",
    tiers: Optional[Sequence[str]] = None,
    fit_dir: "Optional[str | Path]" = None,
    min_samples: int = MIN_SAMPLES,
    host_filter: Optional[str] = None,
    verbose: bool = True,
    baselines_dir: "Optional[str | Path]" = None,
) -> dict[str, Any]:
    """CLI driver (``cli obs fit``): corpus → per-tier fit → versioned
    DB.  Fits every tier present in the corpus unless ``tiers`` names a
    subset; a tier that fails its degeneracy checks raises (fail closed)
    when explicitly requested, and is reported-but-skipped when it was
    merely present in a mixed corpus."""
    from dlbb_tpu.obs.corpus import build_corpus

    corpus = build_corpus(results, verbose=verbose,
                          baselines_dir=baselines_dir)
    present = sorted({s["tier"] for s in corpus["samples"]})
    requested = list(tiers) if tiers else present
    if not corpus["samples"]:
        raise FitError(
            f"corpus under {[str(r) for r in results]} produced zero "
            f"samples ({len(corpus['skipped'])} file(s) skipped) — "
            "nothing to fit"
        )
    out: dict[str, Any] = {"fits": {}, "skipped_tiers": {}}
    for tier in requested:
        try:
            fit = fit_tier(corpus["samples"], tier,
                           min_samples=min_samples,
                           host_filter=host_filter)
        except FitError as e:
            if tiers:  # explicitly requested → fail closed
                raise
            out["skipped_tiers"][tier] = str(e)
            if verbose:
                print(f"[fit] {tier}: SKIPPED ({e})")
            continue
        corpus_meta = {
            "roots": corpus["roots"],
            "samples": len(corpus["samples"]),
            "files": len({str(s["file"]).split("::")[0]
                          for s in corpus["samples"]}),
            "manifests": len(corpus["manifests"]),
        }
        path, version = save_fit(fit, fit_dir, corpus_meta=corpus_meta)
        out["fits"][tier] = {"path": str(path), "fit_version": version,
                             **fit}
        if verbose:
            c = fit["coefficients"]
            print(
                f"[fit] {tier}: v{version} over {fit['samples_used']}/"
                f"{fit['samples_total']} sample(s) -> {path}\n"
                f"      gamma {c['gamma_dispatch_us']['value']:.1f}us"
                f"/dispatch, alpha {c['alpha_us']['value']:.2f}us, "
                f"beta {c['beta_bytes_per_us']['value']:.0f}B/us, "
                f"peak {c['peak_flops_per_us']['value']:.0f}FLOP/us "
                f"(fit geomean error "
                f"{fit['residuals']['geomean_error_factor']:.2f}x)"
            )
    if not out["fits"]:
        raise FitError(
            "no tier produced a fit — reasons: "
            + "; ".join(f"{t}: {r}" for t, r in
                        out["skipped_tiers"].items())
        )
    return out
