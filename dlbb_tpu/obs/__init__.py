"""Runtime observability subsystem (the runtime mirror of ``analysis/``).

The static-analysis subsystem (comm-lint, the α–β schedule auditor)
*predicts* behaviour; this package observes what actually ran and closes
the loop:

- :mod:`~dlbb_tpu.obs.spans` — thread-safe host-side span tracer emitting
  Chrome trace-event JSON (Perfetto-loadable).  The sweep engine, the
  train loop and the resilience journal all emit into it, so "where did
  this 40-minute sweep's wall clock go" is one trace load away — and a
  crashed sweep's timeline is reconstructable from either the trace or
  the fsync'd journal (every journal event doubles as a trace instant
  through the journal's pluggable sink).
- :mod:`~dlbb_tpu.obs.capture` — gated per-config ``jax.profiler``
  device-trace capture on DEDICATED profile reps that are excluded from
  the stats series and run outside the measurement gate; the
  ``profiler-in-timed-region`` comm-lint rule keeps any profiler call
  out of timed regions, so tracing can never contaminate published
  numbers.
- :mod:`~dlbb_tpu.obs.calibration` — the predicted-vs-measured gate:
  joins the committed α–β schedule baselines
  (``stats/analysis/baselines/``) against real measurements of the SAME
  lowered programs and reports signed relative error per target
  (``cli obs calibrate``); ``cli obs diff`` fails CI when the model
  error regresses past the committed calibration baseline — the
  falsifiability loop ROADMAP item 2's autotuner needs.
- :mod:`~dlbb_tpu.obs.export` — a small counters/gauges metrics registry
  with labels that backs the sweep-manifest aggregates and a
  Prometheus-textfile export (``metrics.prom`` next to the manifest).
- :mod:`~dlbb_tpu.obs.corpus` + :mod:`~dlbb_tpu.obs.fit` — the cm2
  fitted cost model: the sweep-artifact corpus normalised into a sample
  table and robustly regressed (per-tier α, β, peak, per-dispatch γ —
  the term behind cm1's committed ~289x cpu-sim gap) into the
  append-only versioned DB ``stats/analysis/costmodel_fit/`` that
  ``--model cm2`` prices with (``cli obs fit``).
- :mod:`~dlbb_tpu.obs.attribution` — span-level time attribution
  (``cli obs attribute``): a run's span trace / journal partitioned
  into phases and joined against the fitted model's
  dispatch-overhead / wire / compute decomposition, per config and per
  serving request (MD + CSV under ``stats/analysis/attribution/``).
- :mod:`~dlbb_tpu.obs.devtrace` — device-trace analysis
  (``cli obs devtrace``): the per-config capture's trace-event JSON
  parsed into a per-op measured timeline, bucketed by op kind, joined
  against the static schedule baselines (measured overlap efficiency
  beside the static proof, ``runtime-serialized-collective`` gate) and
  mined for the op-level β fit samples (MD + CSV + JSON under
  ``stats/analysis/devtrace/``).

CLI: ``python -m dlbb_tpu.cli obs
{trace,calibrate,diff,fit,attribute,devtrace}``.
Exit codes follow the pinned ``analysis.findings.EXIT_*`` contract:
0 clean / 1 findings / 2 crash.  See ``docs/observability.md``.
"""

from __future__ import annotations

from typing import Optional

from dlbb_tpu.analysis.findings import (
    EXIT_CLEAN,
    EXIT_CRASH,
    EXIT_FINDINGS,
    AnalysisReport,
)


def run_obs(
    which: str,
    journal: Optional[str] = None,
    output: Optional[str] = None,
    baselines: Optional[str] = None,
    calibration: Optional[str] = None,
    report: Optional[str] = None,
    tier: Optional[str] = None,
    reps: int = 30,
    warmup: int = 5,
    targets: Optional[list[str]] = None,
    strict_warnings: bool = False,
    verbose: bool = True,
    model: str = "cm1",
    fit_dir: Optional[str] = None,
    results: Optional[list[str]] = None,
    trace: Optional[str] = None,
    min_samples: Optional[int] = None,
    host_filter: Optional[str] = None,
) -> int:
    """CLI driver for the ``obs`` subcommands.  Same exit-code contract
    as ``analysis.run_analysis``: any internal exception surfaces as
    :data:`EXIT_CRASH`, never as an arbitrary code."""
    try:
        return _run_obs(
            which=which, journal=journal, output=output,
            baselines=baselines, calibration=calibration, report=report,
            tier=tier, reps=reps, warmup=warmup, targets=targets,
            strict_warnings=strict_warnings, verbose=verbose,
            model=model, fit_dir=fit_dir, results=results, trace=trace,
            min_samples=min_samples, host_filter=host_filter,
        )
    except Exception:  # noqa: BLE001 — the exit-code contract
        import traceback

        traceback.print_exc()
        return EXIT_CRASH


def _run_obs(
    which: str,
    journal: Optional[str],
    output: Optional[str],
    baselines: Optional[str],
    calibration: Optional[str],
    report: Optional[str],
    tier: Optional[str],
    reps: int,
    warmup: int,
    targets: Optional[list[str]],
    strict_warnings: bool,
    verbose: bool,
    model: str = "cm1",
    fit_dir: Optional[str] = None,
    results: Optional[list[str]] = None,
    trace: Optional[str] = None,
    min_samples: Optional[int] = None,
    host_filter: Optional[str] = None,
) -> int:
    from pathlib import Path

    if which == "trace":
        from dlbb_tpu.obs.spans import journal_to_trace

        if not journal:
            print("error: obs trace needs --journal DIR (a sweep output "
                  "directory holding sweep_journal.jsonl)")
            return EXIT_CRASH
        out = Path(output) if output else Path(journal) / "sweep_trace.json"
        path, n_events, torn = journal_to_trace(journal, out)
        if verbose:
            print(f"[obs] {n_events} journal event(s) -> {path}"
                  + (f" ({torn} torn line(s) skipped)" if torn else ""))
        return EXIT_CLEAN

    if which == "fit":
        from dlbb_tpu.obs.fit import MIN_SAMPLES, FitError, run_fit

        try:
            out = run_fit(
                results=results or ["results"],
                tiers=[tier] if tier else None,
                fit_dir=fit_dir or output,
                min_samples=(min_samples if min_samples is not None
                             else MIN_SAMPLES),
                host_filter=host_filter,
                verbose=verbose,
                baselines_dir=baselines,
            )
        except FitError as e:
            # a degenerate corpus is a FINDING (exit 1) under the pinned
            # exit-code contract, not a harness crash (exit 2); run_fit
            # raises whenever no tier fits, so out["fits"] is non-empty
            # past this point
            print(f"[obs] fit refused: {e}")
            return EXIT_FINDINGS
        return EXIT_CLEAN

    if which == "devtrace":
        from dlbb_tpu.obs.devtrace import run_devtrace

        if not journal:
            print("error: obs devtrace needs --journal DIR (a sweep or "
                  "serving output directory whose artifacts record "
                  "device captures)")
            return EXIT_CRASH
        _report, findings = run_devtrace(
            input_dir=journal, out_dir=output, baselines_dir=baselines,
            verbose=verbose,
        )
        result = AnalysisReport(findings=findings)
        if findings and verbose:
            print(result.render_summary())
        return result.exit_code(strict_warnings=strict_warnings)

    if which == "attribute":
        from dlbb_tpu.obs.attribution import (
            run_attribution,
            validate_attribution,
        )

        if not journal:
            print("error: obs attribute needs --journal DIR (a sweep or "
                  "serving output directory)")
            return EXIT_CRASH
        record = run_attribution(
            input_dir=journal, out_dir=output, trace=trace, model=model,
            tier=tier, fit_dir=fit_dir, verbose=verbose,
        )
        problems = validate_attribution(record)
        if problems:
            for p in problems:
                print(f"[obs] attribution problem: {p}")
            return EXIT_FINDINGS
        return EXIT_CLEAN

    from dlbb_tpu.obs import calibration as cal

    if which == "calibrate":
        out_dir = Path(output) if output else cal.DEFAULT_REPORT_DIR
        rep = cal.run_calibration(
            baselines_dir=Path(baselines) if baselines else None,
            out_dir=out_dir, tier=tier, reps=reps, warmup=warmup,
            target_filter=targets, verbose=verbose, model=model,
            fit_dir=fit_dir,
        )
        agg = rep["aggregate"]
        if not rep["targets"]:
            # zero measured targets is a FINDING (bad --targets filter,
            # tier skew, too-small mesh), never a crash: the aggregate
            # fields are None here, so don't try to format them
            print(
                f"[obs] calibration measured 0 targets "
                f"({agg['targets_skipped']} skipped) — check --targets / "
                "--tier / --simulate against the committed baselines"
            )
            return EXIT_FINDINGS
        if verbose:
            print(
                f"[obs] calibration: {agg['targets_measured']} target(s) "
                f"measured ({agg['targets_skipped']} skipped), median "
                f"signed error {agg['median_signed_rel_error']:+.2f}x, "
                f"geomean error factor {agg['geomean_error_factor']:.1f}x "
                f"-> {out_dir / cal.REPORT_NAME}"
            )
        return EXIT_CLEAN

    if which == "diff":
        rep_obj = None
        if report:
            import json

            rep_obj = json.loads(Path(report).read_text())
        else:
            out_dir = Path(output) if output else cal.DEFAULT_REPORT_DIR
            rep_obj = cal.run_calibration(
                baselines_dir=Path(baselines) if baselines else None,
                out_dir=out_dir, tier=tier, reps=reps, warmup=warmup,
                target_filter=targets, verbose=verbose, model=model,
                fit_dir=fit_dir,
            )
        base_dir = (Path(calibration) if calibration
                    else cal.DEFAULT_CALIBRATION_DIR)
        # the requested-model pin only applies when THIS run produced the
        # report (--report hands in a pre-priced one, whose model rules)
        findings = cal.diff_calibration(
            rep_obj, base_dir, requested_model=None if report else model)
        result = AnalysisReport(findings=findings)
        if verbose:
            print(result.render_summary())
        return result.exit_code(strict_warnings=strict_warnings)

    print(f"error: unknown obs mode {which!r}")
    return EXIT_CRASH
