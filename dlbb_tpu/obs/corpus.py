"""Sweep-artifact corpus ingestion (the cm2 fit's sample table).

The committed ``results/`` tree holds a thousand-odd measured sweep
artifacts (1D/3D collective micro-benchmarks, tuning variants), each a
JSON with raw per-iteration timings plus enough configuration to compute
the analytic features the α–β model prices: per-device wire bytes
(``expectations.op_wire_bytes``), dense FLOPs (the collective-matmul
micro-ops), and the number of collective instructions one dispatch
posts.  This module normalises that corpus into one flat sample table —
the regression input of :mod:`dlbb_tpu.obs.fit`:

    sample = {op, variant, kind, ranks, dtype, wire_bytes, flops,
              collectives, dispatches, measured_median_us,
              measured_p90_us, measured_p99_us, iterations, tier,
              host, file, ...}

``dispatches`` is per *timed iteration*: per-iter timing dispatches the
program once per sample (1.0); chained timing amortises one dispatch
over the chunk (1/chunk) — exactly the γ-visibility difference the
dispatch-overhead fit needs.

The tier of every sample comes from the artifact's recorded backend
(``system_info.backend``): ``cpu`` → ``cpu-sim``, anything TPU →
``tpu-v5lite``.  A per-host fingerprint (platform + cpu count + device
count) rides along so a fit can be restricted to the host it will
predict (``fit.fit_tier(host_filter=...)``) — dispatch overhead is a
property of the *host runtime*, not of the collective.

Everything here is pure file processing — importable and runnable
WITHOUT jax (the fit must run on a dev box with no backend), mirroring
``analysis/costmodel.py``'s contract.
"""

from __future__ import annotations

import json
import math
import re
from pathlib import Path
from typing import Any, Optional, Sequence

from dlbb_tpu.analysis.expectations import OP_EXPECTED_KINDS, op_wire_bytes

CORPUS_SCHEMA = "dlbb_fit_corpus_v1"

# artifact files that are never measurement samples (manifests, traces,
# journals, reports) — skipped silently, not counted as unparseable
_NON_SAMPLE_NAMES = re.compile(
    r"^(sweep_manifest|serving_manifest|serving_resume|trace_|comm_lint"
    r"|calibration_|metrics|.*_trace)", re.IGNORECASE
)
# the subset that can be skipped WITHOUT reading the file — everything
# above except the two name families the walk must parse (manifests for
# corpus metadata, calibration_* for the schema probe); a multi-MB
# Perfetto trace must not be json.loads'd just to be discarded by name
_PREFILTER_NAMES = re.compile(
    r"^(serving_manifest|serving_resume|trace_|comm_lint"
    r"|metrics|.*_trace)", re.IGNORECASE
)

ELEM_BYTES = {
    "bfloat16": 2, "float16": 2, "float32": 4, "float64": 8,
    "int8": 1, "uint8": 1, "fp8": 1, "float8_e4m3fn": 1,
    "int32": 4, "int64": 8,
}

# ops whose wire model op_wire_bytes declines (schedule-dependent): the
# collective-matmul micro-ops move one activation gather / scatter per
# dispatch regardless of schedule — fused and ring carry the same total
# wire, only the instruction count differs (docs/overlap.md)
_MATMUL_OPS = ("ag_matmul", "matmul_rs")


def tier_of_result(data: dict[str, Any]) -> str:
    """Cost-model tier an artifact was measured on, from its recorded
    backend: the CPU-simulated mesh is the ``cpu-sim`` tier, a real TPU
    the ``tpu-v5lite`` tier (per-tier DCN splits land with the topology
    registry, ROADMAP item 3)."""
    backend = str(
        data.get("system_info", {}).get("backend", "cpu")
    ).lower()
    return "cpu-sim" if backend == "cpu" else "tpu-v5lite"


def host_fingerprint(data: dict[str, Any]) -> str:
    info = data.get("system_info", {})
    return (f"{info.get('platform', '?')}"
            f"/cpu{info.get('cpu_count', '?')}"
            f"/dev{info.get('num_devices', '?')}")


def collectives_per_dispatch(op: str, variant: str, ranks: int) -> float:
    """Analytic count of α-charged collective instructions one dispatch
    posts — the fit's per-collective-latency regressor.

    Fused lowerings post one instruction; the explicit hierarchical
    reductions one per mesh axis; the ring-decomposed schedules
    (overlap_* collective matmuls, the quantised rings) one permute per
    hop.  Approximate by construction — the fit's outlier rejection
    absorbs lowering-level deviations (e.g. XLA splitting a fused
    collective)."""
    p = max(int(ranks), 1)
    if variant.startswith("overlap_") or op.endswith("_q"):
        hops = max(p - 1, 1)
        if op == "allreduce_q":
            # quantised ring reduce-scatter phase + wire-dtype all-gather
            return 2.0 * hops
        return float(hops)
    if op == "allreduce_hierarchical" or variant.startswith("hier"):
        axes = variant[len("hier"):].count("x") + 1 if variant.startswith(
            "hier") else 2
        return float(max(axes, 2))
    if op == "sendrecv":
        return 1.0
    return 1.0


def op_flops(op: str, data: dict[str, Any]) -> int:
    """Dense FLOPs one dispatch executes — nonzero only for the
    collective-matmul micro-ops, whose payload ``[B, S, H]`` (per-rank
    sequence chunk) multiplies the gathered ``[B, P*S, H]`` activation by
    a ``[H, H/P]`` weight column (ag_matmul) or accumulates per-shard
    partial products of the same magnitude (matmul_rs): ≈ 2·B·S·H² FLOPs
    per device either way."""
    if op not in _MATMUL_OPS:
        return 0
    shape = data.get("tensor_shape")
    if isinstance(shape, dict):
        dims = (shape.get("batch"), shape.get("seq_len"),
                shape.get("hidden_dim"))
        if any(d is None for d in dims):
            return 0
        b, s, h = (int(d) for d in dims)
    elif shape and len(shape) == 3 and all(
            isinstance(x, (int, float)) for x in shape):
        b, s, h = (int(x) for x in shape)
    else:
        return 0
    return 2 * b * s * h * h


def sample_wire_bytes(op: str, data: dict[str, Any]) -> Optional[int]:
    """Analytic per-device wire bytes for one dispatch, or None when the
    op has no wire model."""
    n = int(data.get("num_elements", 0))
    p = int(data.get("num_ranks", 0))
    b = ELEM_BYTES.get(str(data.get("dtype", "")).lower())
    if not n or not p or b is None:
        return None
    variant = str(data.get("variant", "default"))
    compression = None
    if variant.startswith("compress_"):
        compression = "fp8" if "fp8" in variant else "int8"
    wire = op_wire_bytes(op, n, p, b, compression=compression)
    if wire is not None:
        return wire
    if op in _MATMUL_OPS:
        # one activation-sized gather (ag) / scatter (rs) per dispatch
        if p <= 1:
            return 0
        if op == "ag_matmul":
            return int((p - 1) * n * b)       # gathered sequence chunks
        return int((p - 1) / p * n * b)       # scattered partial rows
    return None


def _dispatches_per_iteration(data: dict[str, Any]) -> float:
    """Host dispatches amortised into one timed iteration: per-iter
    timing pays one dispatch per sample; chained timing pays one per
    chunk (``timing_granularity: chunked(N)``)."""
    if data.get("timing_mode") != "chained":
        return 1.0
    gran = str(data.get("timing_granularity", ""))
    m = re.search(r"chunked\((\d+)\)", gran)
    chunk = int(m.group(1)) if m else 10
    return 1.0 / max(chunk, 1)


def _flat_timings_us(data: dict[str, Any]) -> list[float]:
    out: list[float] = []
    for group in data.get("timings", ()):  # list of rep groups
        if isinstance(group, (int, float)):
            out.append(float(group) * 1e6)
            continue
        for v in group:
            if isinstance(v, (int, float)) and math.isfinite(v):
                out.append(float(v) * 1e6)
    return out


def ingest_result(path: Path,
                  data: dict[str, Any]) -> "tuple[Optional[dict], str]":
    """One artifact → one corpus sample (or ``(None, reason)``)."""
    op = data.get("operation")
    if not op or "timings" not in data:
        return None, "not a sweep artifact (no operation/timings)"
    timings = _flat_timings_us(data)
    if not timings:
        return None, "no finite timing samples"
    wire = sample_wire_bytes(op, data)
    if wire is None:
        return None, f"op {op!r} has no analytic wire model"
    ranks = int(data.get("num_ranks", 0))
    variant = str(data.get("variant", "default"))
    timings.sort()
    n = len(timings)
    kind_info = OP_EXPECTED_KINDS.get(op, {})
    kind = kind_info.get("required")
    if kind is None and kind_info.get("required_any"):
        kind = sorted(kind_info["required_any"])[0]
    if kind is None:
        # ops outside OP_EXPECTED_KINDS with a wire model: the
        # collective-matmul micro-ops (fused all-gather / psum_scatter)
        # and the quantised rings (permute chains); record the defining
        # primitive
        kind = {"ag_matmul": "all-gather",
                "matmul_rs": "reduce-scatter"}.get(
                    op, "collective-permute")
    return {
        "file": str(path),
        "op": op,
        "variant": variant,
        "kind": kind,
        "ranks": ranks,
        "dtype": data.get("dtype"),
        "num_elements": int(data.get("num_elements", 0)),
        "wire_bytes": int(wire),
        "flops": op_flops(op, data),
        "collectives": collectives_per_dispatch(op, variant, ranks),
        "dispatches": _dispatches_per_iteration(data),
        "measured_median_us": timings[n // 2],
        "measured_p90_us": timings[min(n - 1, int(n * 0.9))],
        "measured_p99_us": timings[min(n - 1, int(n * 0.99))],
        "iterations": n,
        "tier": tier_of_result(data),
        "host": host_fingerprint(data),
        "timestamp": data.get("timestamp"),
    }, ""


def ingest_calibration(path: Path, data: dict[str, Any],
                       baselines_dir: "Optional[str | Path]" = None
                       ) -> tuple[list[dict[str, Any]], list[dict]]:
    """Calibration reports are corpus rows too — the program-scale half
    of the fit.  Each measured target joins its committed schedule
    baseline (``stats/analysis/baselines/``) for analytic features that
    are **critical-path-consistent**: ``obs calibrate --model cm2``
    predicts ``critical_path(fitted tier) + γ``, so the features a
    calibration row regresses against must describe the critical path,
    not the whole program — collective count and wire bytes scaled by
    the baseline's ``comm_on_critical_path_us / comm_total_us`` ratio
    (the baselines record the cm1-priced split, not a per-instruction
    on-path inventory — all of one program's collectives are near-twins,
    so the µs ratio transfers to counts and bytes), and FLOPs as the
    critical path's compute slack (``critical_path_us −
    comm_on_critical_path_us``) re-expanded through the cm1 peak it was
    priced with.  Micro rows alone cannot separate the per-dispatch γ
    from the per-collective α (every micro dispatch posts >= 1
    collective); a calibration row with ZERO collectives (the serving
    compaction programs) pins γ directly, and the many-instruction train
    steps anchor the effective peak.  ``measured_us`` is
    model-independent, so reports priced with either model ingest
    identically."""
    from dlbb_tpu.analysis.costmodel import get_tier
    from dlbb_tpu.analysis.schedule_audit import (
        DEFAULT_BASELINE_DIR,
        load_baselines,
    )

    baselines_dir = Path(baselines_dir or DEFAULT_BASELINE_DIR)
    skipped: list[dict] = []
    if not baselines_dir.is_dir():
        return [], [{"file": str(path),
                     "reason": (f"no schedule baselines under "
                                f"{baselines_dir} to join features from")}]
    baselines = load_baselines(baselines_dir)
    cm1 = get_tier(data.get("tier") or None)
    samples: list[dict[str, Any]] = []
    # skip records carrying a measured_us are the zero-critical-path
    # programs cm1 could not score but measured anyway — the corpus's
    # pure per-dispatch-γ anchors
    rows = list(data.get("targets", ())) + [
        s for s in data.get("skipped", ()) if "measured_us" in s
    ]
    for row in rows:
        base = baselines.get(row.get("target"))
        m = row.get("measured_us")
        if base is None:
            skipped.append({"file": f"{path}::{row.get('target')}",
                            "reason": "no schedule baseline to join"})
            continue
        if not isinstance(m, (int, float)) or not math.isfinite(m) \
                or m <= 0:
            skipped.append({"file": f"{path}::{row.get('target')}",
                            "reason": "non-finite measured_us"})
            continue
        comm_total_us = float(base.get("comm_total_us", 0.0))
        comm_cp_us = float(
            base.get("comm_on_critical_path_us", comm_total_us))
        cp_us = float(base.get("critical_path_us", 0.0))
        on_cp = comm_cp_us / comm_total_us if comm_total_us > 0 else 0.0
        samples.append({
            "file": f"{path}::{row['target']}",
            "op": row["target"],
            "variant": "calibration",
            "kind": "program",
            "ranks": 8,
            "dtype": None,
            "num_elements": 0,
            "wire_bytes": int(base.get("total_wire_bytes", 0) * on_cp),
            "flops": int(max(cp_us - comm_cp_us, 0.0)
                         * cm1.peak_flops_per_us),
            "collectives": float(base.get("num_collectives", 0) * on_cp),
            "dispatches": 1.0,
            "measured_median_us": float(m),
            # calibration rows record p90, not p99 — no fabricated tail
            "measured_p90_us": float(row.get("measured_p90_us", m)),
            "measured_p99_us": None,
            "iterations": int(row.get("reps", 1)),
            "tier": data.get("tier", "cpu-sim"),
            "host": "calibration",
            "timestamp": data.get("timestamp"),
        })
    return samples, skipped


_DEVTRACE_SAMPLE_KEYS = ("op", "kind", "ranks", "wire_bytes",
                         "measured_median_us")


def ingest_devtrace(path: Path, data: dict[str, Any]
                    ) -> tuple[list[dict[str, Any]], list[dict]]:
    """A devtrace report's ``op_samples`` are corpus rows too — the
    op-granularity, device-timed half of the fit (``source:
    "devtrace"``).  Each row is ONE collective op's measured device
    communication time with ``dispatches: 0`` (a device-op duration
    carries no host dispatch) and ``flops: 0`` (compute events are
    bucketed separately), so the population identifies
    ``α·collectives + wire/β`` directly — the rows that un-pin β on
    the cpu-sim tier (``obs fit``)."""
    samples: list[dict[str, Any]] = []
    skipped: list[dict] = []
    for n, row in enumerate(data.get("op_samples", ())):
        if not isinstance(row, dict) or any(
                k not in row for k in _DEVTRACE_SAMPLE_KEYS):
            skipped.append({"file": f"{path}::op_samples[{n}]",
                            "reason": "malformed devtrace op sample"})
            continue
        m = row.get("measured_median_us")
        if not isinstance(m, (int, float)) or not math.isfinite(m) \
                or m <= 0:
            skipped.append({"file": f"{path}::op_samples[{n}]",
                            "reason": "non-finite measured_median_us"})
            continue
        sample = dict(row)
        sample.setdefault("source", "devtrace")
        sample.setdefault("dispatches", 0.0)
        sample.setdefault("flops", 0)
        sample.setdefault("host", "devtrace")
        samples.append(sample)
    return samples, skipped


def _manifest_summary(path: Path, data: dict[str, Any]) -> dict[str, Any]:
    """Compile/dedup aggregates of one ``sweep_manifest.json`` — corpus
    metadata (per-directory context for the samples), not samples."""
    out: dict[str, Any] = {"file": str(path)}
    for key in ("wall_seconds", "compile_seconds_total",
                "cost_model_version"):
        if key in data:
            out[key] = data[key]
    dedup = data.get("dedup") or data.get("work_units")
    if isinstance(dedup, dict):
        out["dedup"] = dedup
    cal = data.get("calibration")
    if isinstance(cal, dict):
        out["calibration"] = {
            k: cal.get(k) for k in ("tier", "cost_model_version",
                                    "geomean_error_factor")
        }
    return out


def build_corpus(roots: "Sequence[str | Path]",
                 verbose: bool = False,
                 baselines_dir: "Optional[str | Path]" = None
                 ) -> dict[str, Any]:
    """Walk one or more results trees into the normalised sample table.

    Calibration reports/baselines among the roots contribute
    program-scale rows (:func:`ingest_calibration`, features joined from
    ``baselines_dir``).  Returns ``{schema, samples, skipped, manifests,
    roots}``; raises :class:`FileNotFoundError` when no root exists (a
    typo'd path must fail loudly, not fit an empty corpus)."""
    roots = [Path(r) for r in roots]
    live = [r for r in roots if r.exists()]
    if not live:
        raise FileNotFoundError(
            f"no corpus root exists among {[str(r) for r in roots]}"
        )
    samples: list[dict[str, Any]] = []
    skipped: list[dict[str, str]] = []
    manifests: list[dict[str, Any]] = []
    for root in live:
        files = [root] if root.is_file() else sorted(root.rglob("*.json"))
        for path in files:
            if _PREFILTER_NAMES.match(path.name):
                continue
            try:
                data = json.loads(path.read_text())
            except (OSError, json.JSONDecodeError) as e:
                skipped.append({"file": str(path),
                                "reason": f"unreadable: {e}"})
                continue
            if not isinstance(data, dict):
                continue
            if path.name == "sweep_manifest.json":
                manifests.append(_manifest_summary(path, data))
                continue
            if data.get("schema") == "dlbb_calibration_v1":
                cal_samples, cal_skipped = ingest_calibration(
                    path, data, baselines_dir=baselines_dir)
                samples.extend(cal_samples)
                skipped.extend(cal_skipped)
                continue
            if data.get("schema") == "dlbb_devtrace_v1":
                dt_samples, dt_skipped = ingest_devtrace(path, data)
                samples.extend(dt_samples)
                skipped.extend(dt_skipped)
                continue
            if _NON_SAMPLE_NAMES.match(path.name):
                continue
            sample, reason = ingest_result(path, data)
            if sample is None:
                skipped.append({"file": str(path), "reason": reason})
                continue
            samples.append(sample)
    if verbose:
        tiers: dict[str, int] = {}
        for s in samples:
            tiers[s["tier"]] = tiers.get(s["tier"], 0) + 1
        print(f"[corpus] {len(samples)} sample(s) "
              f"({', '.join(f'{t}: {n}' for t, n in sorted(tiers.items()))})"
              f", {len(skipped)} skipped, {len(manifests)} manifest(s)")
    return {
        "schema": CORPUS_SCHEMA,
        "roots": [str(r) for r in roots],
        "samples": samples,
        "skipped": skipped,
        "manifests": manifests,
    }


def save_corpus(corpus: dict[str, Any], path: "str | Path") -> Path:
    from dlbb_tpu.utils.config import atomic_write_text

    return atomic_write_text(
        json.dumps(corpus, indent=1, sort_keys=True), Path(path)
    )
